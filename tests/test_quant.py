"""int8 weight-only quantized decoding (inference/quant.py).

Contracts: (1) quantize/dequant round-trips weights to per-channel absmax
precision (~0.4% relative); (2) on a briefly-TRAINED tiny model (peaked
logits, unlike random init where everything ties) quantized decode stays
faithful: teacher-forced logits close, high next-token top-1 agreement,
and the generators accept the quantized tree everywhere the float tree
goes (single-device, beam, ring-pipelined).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core.partition import StageCtx
from pipe_tpu.inference import GenerationConfig, Generator
from pipe_tpu.inference.quant import (QuantLeaf, dequant_tree,
                                      quantize_params)
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM

MODEL = LMConfig(vocab=64, d_model=32, nhead=4, d_ff=64, n_layers=4,
                 seq_len=32, dropout=0.0)


def _trained_params(n_stages=2, steps=25):
    """Train briefly so logits are peaked (tie-free-ish)."""
    from pipe_tpu.data import lm_text
    from pipe_tpu.train.loop import Trainer, TrainerConfig

    cfg = TrainerConfig(batch_size=8, bptt=MODEL.seq_len, chunks=2,
                        n_stages=n_stages, lr=0.05, schedule="gpipe",
                        checkpoint="never")
    lines = lm_text.synthetic_corpus(9000, 60, seed=4)
    vocab = lm_text.Vocab(map(lm_text.basic_english_tokenize, lines))
    src = lm_text.batchify(lm_text.data_process(lines, vocab),
                           cfg.batch_size)
    tr = Trainer(MODEL, cfg)
    state, _ = tr.train_epoch(src, state=tr.init_state(), max_steps=steps,
                              log_every=0)
    model = PipelinedLM(MODEL, n_stages)
    # state params are stacked [n, ...]; rebuild the per-stage list shape
    sp = [[jax.tree_util.tree_map(lambda a: np.asarray(a[s]), blk)
           for blk in state.params[0]]
          for s in range(n_stages)]
    pre = jax.tree_util.tree_map(np.asarray, state.params[1])
    post = jax.tree_util.tree_map(np.asarray, state.params[2])
    return model, (sp, pre, post)


def test_quant_roundtrip_precision():
    w = jax.random.normal(jax.random.key(0), (64, 48)) * 0.3
    ql = quantize_params(w)
    assert isinstance(ql, QuantLeaf) and ql.q.dtype == jnp.int8
    back = np.asarray(ql.dequant(jnp.float32))
    err = np.abs(back - np.asarray(w)).max(axis=0)
    colmax = np.abs(np.asarray(w)).max(axis=0)
    assert (err <= colmax / 127.0 * 1.01).all()   # per-channel absmax bound


def test_quant_skips_vectors_and_keeps_structure():
    model = PipelinedLM(MODEL, 2)
    sp, _, _ = model.init(jax.random.key(0))
    qsp = quantize_params(sp)
    # biases/LN stay plain; projection weights become QuantLeaf
    blk = qsp[0][0]
    assert isinstance(blk["attn"]["wq"], QuantLeaf)
    assert not isinstance(blk["attn"]["bq"], QuantLeaf)
    for leaf in jax.tree_util.tree_leaves(
            blk["ln1"], is_leaf=lambda x: isinstance(x, QuantLeaf)):
        assert not isinstance(leaf, QuantLeaf)  # 1-D LN params stay float
    # dequant restores plain arrays of the original shapes
    deq = dequant_tree(blk, jnp.float32)
    assert deq["attn"]["wq"].shape == sp[0][0]["attn"]["wq"].shape


def test_quantized_decode_faithful_on_trained_model():
    model, (sp, pre, post) = _trained_params()
    qsp = quantize_params(sp)
    prompt = jax.random.randint(jax.random.key(1), (4, 8), 0, MODEL.vocab,
                                jnp.int32)
    gen = Generator(model, GenerationConfig(max_new_tokens=8,
                                            temperature=0.0))
    f_toks = np.asarray(gen.generate((sp, pre, post), prompt))
    q_toks = np.asarray(gen.generate((qsp, pre, post), prompt))
    # peaked logits: the vast majority of greedy tokens agree
    agree = (f_toks == q_toks).mean()
    assert agree >= 0.75, f"top-1 agreement {agree}"

    # teacher-forced logit fidelity through the cached path
    def forced_logits(stage_params):
        blocks = gen._blocks(stage_params)
        caches = [model.block.attn.make_cache(4, 8) for _ in blocks]
        h = model.embed_at(pre, prompt, 0)
        for l, bp in enumerate(blocks):
            h, caches[l] = model.block.decode(gen._dq(bp), h, caches[l], 0)
        return np.asarray(gen._head(post, h))

    lf, lq = forced_logits(sp), forced_logits(qsp)
    rel = np.abs(lf - lq).max() / (np.abs(lf).max() + 1e-9)
    assert rel < 0.08, f"relative logit error {rel}"


def test_quantized_pipelined_decode_runs():
    from pipe_tpu.inference.pipelined import PipelinedGenerator
    from pipe_tpu.parallel.mesh import make_mesh
    from pipe_tpu.parallel.spmd import stack_stage_params

    model, (sp, pre, post) = _trained_params()
    qsp = quantize_params(sp)
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=0.0)
    prompt = jax.random.randint(jax.random.key(2), (4, 8), 0, MODEL.vocab,
                                jnp.int32)
    ref = np.asarray(Generator(model, gen_cfg).generate((qsp, pre, post),
                                                        prompt))
    pg = PipelinedGenerator(make_mesh(2, 1), model, gen_cfg)
    got = np.asarray(pg.generate(stack_stage_params(qsp), pre, post,
                                 prompt))
    # same quantized weights through both executors: tokens identical
    np.testing.assert_array_equal(got, ref)


def test_quantized_beam_runs():
    model, (sp, pre, post) = _trained_params()
    qsp = quantize_params(sp)
    beam = Generator(model, GenerationConfig(max_new_tokens=5, num_beams=3))
    toks, scores = beam.generate_with_scores((qsp, pre, post),
                                             jnp.zeros((2, 4), jnp.int32))
    assert toks.shape == (2, 5) and np.isfinite(np.asarray(scores)).all()
