"""Streaming (vocab-blocked) cross-entropy: ops/losses.streaming_xent.

Contract: identical values AND gradients (h, W, b) to the dense
decoder-then-per_row_ce path — the streaming form is a memory layout
choice, never a math choice — including non-divisible vocab/block, bf16
activations, and the full pipelined training step via
``LMConfig(loss_block=...)``.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core import microbatch as mb
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
from pipe_tpu.ops.losses import streaming_xent
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.scheduled import ScheduledPipeline
from pipe_tpu.parallel.spmd import stack_stage_params


@pytest.mark.parametrize("block", [32, 101, 128])
def test_streaming_matches_dense_values_and_grads(block):
    key = jax.random.key(0)
    rows, s, d, V = 3, 7, 16, 101   # V=101: exercises block padding
    h = jax.random.normal(jax.random.fold_in(key, 0), (rows, s, d))
    w = jax.random.normal(jax.random.fold_in(key, 1), (d, V)) * 0.3
    b = jax.random.normal(jax.random.fold_in(key, 2), (V,)) * 0.1
    tgt = jax.random.randint(jax.random.fold_in(key, 3), (rows, s), 0, V)

    def dense(h, w, b):
        logits = h.astype(jnp.float32) @ w + b
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, tgt[..., None], -1)[..., 0]
        return jnp.mean(lse - gold)

    def streaming(h, w, b):
        return jnp.mean(streaming_xent(h, w, b, tgt, block))

    vd, gd = jax.value_and_grad(dense, argnums=(0, 1, 2))(h, w, b)
    vs, gs = jax.value_and_grad(streaming, argnums=(0, 1, 2))(h, w, b)
    assert float(vd) == pytest.approx(float(vs), rel=1e-6)
    for a, c in zip(gd, gs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-5, atol=1e-6)


def test_streaming_bf16_activations():
    """bf16 h: the streamed tiles accumulate f32 like the dense upcast."""
    key = jax.random.key(1)
    h = jax.random.normal(jax.random.fold_in(key, 0),
                          (2, 5, 8)).astype(jnp.bfloat16)
    w = jax.random.normal(jax.random.fold_in(key, 1), (8, 37)) * 0.3
    b = jnp.zeros((37,))
    tgt = jax.random.randint(jax.random.fold_in(key, 2), (2, 5), 0, 37)
    logits = h.astype(jnp.float32) @ w + b
    dense = jnp.mean(jax.nn.logsumexp(logits, -1) - jnp.take_along_axis(
        logits, tgt[..., None], -1)[..., 0])
    got = jnp.mean(streaming_xent(h, w, b, tgt, 16))
    assert float(got) == pytest.approx(float(dense), rel=2e-2)


def test_loss_block_through_pipelined_step():
    """LMConfig(loss_block=...) through the table executor: loss and ALL
    grads (stage, pre, post incl. the decoder W/b) equal the dense-loss
    run — on the d=2 dynamic path with except_last."""
    m, d_stages = 4, 2
    base = dataclasses.replace(LMConfig().tiny(), n_layers=2, dropout=0.0)
    mesh = make_mesh(d_stages, 1, devices=jax.devices()[:d_stages])
    tokens = jax.random.randint(jax.random.key(1),
                                (2 * m, base.seq_len), 0, base.vocab,
                                jnp.int32)
    x, _ = mb.stack_scatter(
        {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)}, m)
    w = jnp.ones(x["tokens"].shape[:2], jnp.float32)

    results = []
    for loss_block in (None, 32):
        cfg = dataclasses.replace(base, loss_block=loss_block)
        model = PipelinedLM(cfg, d_stages)
        sp, prep, postp = model.init(jax.random.key(0))
        pipe = ScheduledPipeline(mesh, model.stage_fn,
                                 pre_fn=model.pre_fn,
                                 post_fn=model.loss_post_fn,
                                 checkpoint="except_last",
                                 schedule="1f1b")
        loss, grads = jax.jit(pipe.loss_and_grad)(
            stack_stage_params(sp), prep, postp, x, w,
            key=jax.random.key(9))
        results.append((float(loss), grads))
    (l_dense, g_dense), (l_stream, g_stream) = results
    assert l_dense == pytest.approx(l_stream, rel=1e-5)
    for a, b_ in zip(jax.tree_util.tree_leaves(g_dense),
                     jax.tree_util.tree_leaves(g_stream)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-6)
