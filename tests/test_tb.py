"""TensorBoard scalar emission (SURVEY §5: "stdout + TensorBoard scalars").

The writer is self-contained (hand-encoded Event protos + TFRecord
framing, ``obs/tb_writer.py``); these tests pin format correctness by
reading the files back through tensorboard's own ``EventAccumulator``."""

import dataclasses

import numpy as np
import pytest

from pipe_tpu.obs.tb_writer import ScalarWriter


def _load_scalars(logdir):
    ea_mod = pytest.importorskip(
        "tensorboard.backend.event_processing.event_accumulator")
    acc = ea_mod.EventAccumulator(str(logdir))
    acc.Reload()
    return acc


def test_scalar_writer_roundtrip(tmp_path):
    with ScalarWriter(str(tmp_path)) as w:
        for step, v in enumerate([3.5, 2.25, 1.125]):
            w.add_scalar("train/loss", v, step)
        w.add_scalar("eval/loss", 0.5, 7)
    acc = _load_scalars(tmp_path)
    tags = acc.Tags()["scalars"]
    assert set(tags) == {"train/loss", "eval/loss"}
    events = acc.Scalars("train/loss")
    assert [e.step for e in events] == [0, 1, 2]
    np.testing.assert_allclose([e.value for e in events],
                               [3.5, 2.25, 1.125])
    assert acc.Scalars("eval/loss")[0].step == 7
    assert acc.Scalars("eval/loss")[0].value == 0.5


def test_scalar_writer_closed_raises(tmp_path):
    w = ScalarWriter(str(tmp_path))
    w.close()
    with pytest.raises(ValueError):
        w.add_scalar("x", 1.0, 0)


def test_trainer_emits_event_files(tmp_path, monkeypatch):
    """Trainer(tb_dir=...) writes train + eval scalars next to stdout."""
    from pipe_tpu.data import lm_text
    from pipe_tpu.models.transformer_lm import LMConfig
    from pipe_tpu.train.loop import Trainer, TrainerConfig

    lines = lm_text.synthetic_corpus(12_000, 99, seed=3)
    vocab = lm_text.Vocab(map(lm_text.basic_english_tokenize, lines))
    source = lm_text.batchify(lm_text.data_process(lines, vocab), 8)

    model_cfg = dataclasses.replace(LMConfig().tiny(), n_layers=2)
    cfg = TrainerConfig(batch_size=8, eval_batch_size=8,
                        bptt=model_cfg.seq_len, chunks=2, n_stages=2,
                        n_data=1, lr=1e-2, tb_dir=str(tmp_path))
    trainer = Trainer(model_cfg, cfg)
    state, _ = trainer.train_epoch(source, max_steps=4, log_every=2)
    trainer.evaluate(source, state, max_steps=1)

    files = list(tmp_path.glob("events.out.tfevents.*"))
    assert files, "no event file written"
    acc = _load_scalars(tmp_path)
    tags = set(acc.Tags()["scalars"])
    assert {"train/loss", "train/tok_s", "train/lr", "pipeline/bubble",
            "train/epoch_loss", "eval/loss"} <= tags
    steps = [e.step for e in acc.Scalars("train/loss")]
    assert steps == sorted(steps) and len(steps) == 2  # log_every=2, 4 steps
    # scalar values mirror the metrics dict
    assert np.isfinite(acc.Scalars("eval/loss")[0].value)
