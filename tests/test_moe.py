"""MoE / expert parallelism (ops/moe.py): expert sharding over the model
axis is a layout choice, never a math choice — ep=2 forward, aux loss, and
every gradient leaf match the unsharded run under the executor contract
(in-program vjp, no model-axis grad reductions)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pipe_tpu.core.partition import StageCtx
from pipe_tpu.ops.moe import moe_capacity, moe_ffn_apply, moe_ffn_init, \
    moe_ffn_specs
from pipe_tpu.parallel.mesh import MODEL_AXIS, make_mesh
from pipe_tpu.utils.compat import shard_map

D, FF, E, ROWS, SEQ = 8, 16, 4, 2, 8


@pytest.mark.parametrize("k", [1, 2])
def test_moe_ffn_matches_unsharded(k):
    params = moe_ffn_init(jax.random.key(0), D, FF, E)
    h = jax.random.normal(jax.random.key(1), (ROWS, SEQ, D))
    mesh = make_mesh(1, 1, n_model=2, devices=jax.devices()[:2])

    def loss_of(p, h, ep_axis):
        out, aux = moe_ffn_apply(p, h, StageCtx(), n_experts=E, k=k,
                                 ep_axis=ep_axis)
        return jnp.sum(out ** 2) + 0.01 * aux

    l_ref, g_ref = jax.value_and_grad(
        lambda p: loss_of(p, h, None))(params)

    specs = moe_ffn_specs()

    def device_program(p, h):
        return jax.value_and_grad(
            lambda p: loss_of(p, h, MODEL_AXIS))(p)

    run = shard_map(device_program, mesh=mesh,
                        in_specs=(specs, P()),
                        out_specs=(P(), specs), check_vma=False)
    l_ep, g_ep = jax.jit(run)(params, h)
    np.testing.assert_allclose(float(l_ep), float(l_ref), rtol=1e-5)
    for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(g_ep),
            jax.tree_util.tree_leaves_with_path(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=1e-5, err_msg=str(ka))


def test_pp_dp_ep_loss_and_grad_transparency():
    """The full PP x DP x EP product through
    ScheduledPipeline(stage_param_specs=): loss and all grads match the
    unsharded (ep_axis=None) run of the same params."""
    import dataclasses

    from pipe_tpu.core import microbatch as mb
    from pipe_tpu.models.moe_lm import MoELMConfig, MoEPipelinedLM
    from pipe_tpu.models.transformer_lm import LMConfig
    from pipe_tpu.parallel.scheduled import ScheduledPipeline
    from pipe_tpu.parallel.spmd import stack_stage_params

    tiny = LMConfig().tiny()
    cfg = MoELMConfig(
        **{**dataclasses.asdict(tiny),
           "d_model": D, "nhead": 2, "d_ff": FF, "n_layers": 2,
           "seq_len": SEQ, "dropout": 0.0},
        n_experts=E, top_k=2, capacity_factor=2.0)
    m = 2
    model_ep = MoEPipelinedLM(cfg, 2)
    model_ref = MoEPipelinedLM(cfg, 2, ep_axis=None)
    sp, prep, postp = model_ref.init(jax.random.key(0))
    stacked = stack_stage_params(sp)
    tokens = jax.random.randint(jax.random.key(1), (4 * m, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    x, n_rows = mb.stack_scatter(
        {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)}, m)
    w = mb.valid_row_mask(x, n_rows)

    mesh_ref = make_mesh(2, 1, devices=jax.devices()[:2])
    pipe_ref = ScheduledPipeline(
        mesh_ref, model_ref.stage_fn, pre_fn=model_ref.pre_fn,
        post_fn=model_ref.loss_post_fn, checkpoint="never",
        schedule="1f1b")
    l_ref, (g_ref, gpre_ref, gpost_ref) = jax.jit(pipe_ref.loss_and_grad)(
        stacked, prep, postp, x, w, key=jax.random.key(9))

    mesh = make_mesh(2, 2, n_model=2, devices=jax.devices()[:8])
    pipe = ScheduledPipeline(
        mesh, model_ep.stage_fn, pre_fn=model_ep.pre_fn,
        post_fn=model_ep.loss_post_fn, checkpoint="never",
        schedule="1f1b",
        stage_param_specs=model_ep.stage_param_specs())
    l_ep, (g_ep, gpre_ep, gpost_ep) = jax.jit(pipe.loss_and_grad)(
        stacked, prep, postp, x, w, key=jax.random.key(9))

    np.testing.assert_allclose(float(l_ep), float(l_ref), rtol=1e-5)
    for got, exp in ((g_ep, g_ref), (gpre_ep, gpre_ref),
                     (gpost_ep, gpost_ref)):
        for (ka, a), (kb, b) in zip(
                jax.tree_util.tree_leaves_with_path(got),
                jax.tree_util.tree_leaves_with_path(exp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-4, atol=1e-5,
                                       err_msg=str(ka))


def test_moe_capacity_drops_overflow():
    """With capacity_factor tiny, overflowed tokens contribute zero output
    (they ride the residual stream in a block) — and the layer still
    differentiates."""
    params = moe_ffn_init(jax.random.key(0), D, FF, E)
    h = jax.random.normal(jax.random.key(1), (ROWS, SEQ, D))
    out_full, _ = moe_ffn_apply(params, h, StageCtx(), n_experts=E, k=1,
                                capacity_factor=4.0, ep_axis=None)
    out_tiny, _ = moe_ffn_apply(params, h, StageCtx(), n_experts=E, k=1,
                                capacity_factor=0.1, ep_axis=None)
    # capacity 0.1 * 16 / 4 -> 1 slot per expert: most tokens dropped
    assert moe_capacity(ROWS * SEQ, E, 1, 0.1) == 1
    n_zero_tiny = int(jnp.sum(jnp.all(out_tiny == 0, axis=-1)))
    n_zero_full = int(jnp.sum(jnp.all(out_full == 0, axis=-1)))
    assert n_zero_tiny > n_zero_full
    g = jax.grad(lambda p: jnp.sum(moe_ffn_apply(
        p, h, StageCtx(), n_experts=E, k=1, capacity_factor=0.1,
        ep_axis=None)[0] ** 2))(params)
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree_util.tree_leaves(g))
