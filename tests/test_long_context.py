"""PP x CP composition: pipelined LM with context-sharded ring attention.

Transparency bar: the (stage, context)-sharded model must match the plain
single-device LM (same params, full sequence) forward and gradients — nested
ppermute rings included.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core import microbatch as mb
from pipe_tpu.core.partition import StageCtx
from pipe_tpu.models.long_context_lm import ContextParallelLM
from pipe_tpu.models.transformer_lm import LMConfig
from pipe_tpu.parallel.mesh import CONTEXT_AXIS, make_mesh
from pipe_tpu.parallel.spmd import SpmdPipeline, stack_stage_params


def tiny_cfg(seq_len=32):
    return dataclasses.replace(LMConfig().tiny(), n_layers=2, dropout=0.0,
                               seq_len=seq_len, d_model=16, nhead=2)


def plain_reference_loss(model, params, tokens, targets):
    """Single-device oracle: the INDEPENDENT ops.layers implementation.

    Uses TransformerEncoderLayer (full XLA attention, same param structure)
    rather than the model's own block code, so a divergence in the
    context-parallel math cannot cancel out in the comparison.
    """
    from pipe_tpu.ops.layers import TransformerEncoderLayer

    cfg = model.cfg
    sp, prep, postp = params
    table = prep["embed"]["table"]
    h = jnp.take(table, tokens, axis=0) * jnp.sqrt(jnp.float32(cfg.d_model))
    h = model._posenc(h, 0)
    tel = TransformerEncoderLayer(cfg.d_model, cfg.nhead, cfg.d_ff, 0.0,
                                  causal=cfg.causal)
    for blocks in sp:
        for bp in blocks:
            h = tel.apply(bp, h, ctx=StageCtx())
    w = postp["decoder"]["w"]
    b = postp["decoder"]["b"]
    logits = jnp.einsum("bsd,dv->bsv", h.astype(jnp.float32), w) + b
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold, axis=-1)


def run_pp_cp(n_stages, n_context, chunks=2, seq=32, rows=4):
    cfg = dataclasses.replace(tiny_cfg(seq), n_layers=max(2, n_stages))
    model = ContextParallelLM(cfg, n_stages)
    sp, prep, postp = model.init(jax.random.key(0))
    stacked = stack_stage_params(sp)
    mesh = make_mesh(n_stages, 1, n_context=n_context)
    pipe = SpmdPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                        post_fn=model.loss_post_fn, post_with_batch=True,
                        context_axis=CONTEXT_AXIS)
    tokens = jax.random.randint(jax.random.key(1), (rows * chunks, seq),
                                0, cfg.vocab, jnp.int32)
    targets = jnp.roll(tokens, -1, axis=-1)
    x, _ = mb.stack_scatter({"tokens": tokens, "targets": targets}, chunks)
    per_row = pipe(stacked, prep, postp, x)
    return (model, (sp, prep, postp), tokens, targets,
            per_row.reshape(-1), stacked, pipe, x)


@pytest.mark.parametrize("n_stages,n_context", [(2, 2), (2, 4), (4, 2),
                                                (1, 8)])
def test_pp_cp_forward_transparency(n_stages, n_context):
    model, params, tokens, targets, got, *_ = run_pp_cp(n_stages, n_context)
    exp = plain_reference_loss(model, params, tokens, targets)
    np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                               rtol=2e-4, atol=2e-5)


def test_pp_cp_gradient_flows_and_matches():
    model, params, tokens, targets, _, stacked, pipe, x = run_pp_cp(2, 2)
    sp, prep, postp = params

    def pipe_loss(stacked, prep, postp):
        return jnp.mean(pipe(stacked, prep, postp, x))

    def plain_loss(sp, prep, postp):
        return jnp.mean(plain_reference_loss(
            model, (sp, prep, postp), tokens, targets))

    g_pipe = jax.grad(pipe_loss, argnums=(0, 1, 2))(stacked, prep, postp)
    g_plain = jax.grad(plain_loss, argnums=(0, 1, 2))(sp, prep, postp)
    g_plain = (stack_stage_params(g_plain[0]), g_plain[1], g_plain[2])
    for a, e in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_plain)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=2e-3, atol=2e-4)


def test_pp_cp_trains():
    """A jitted SGD loop over the (stage, context) mesh reduces the loss."""
    model, params, tokens, targets, _, stacked, pipe, x = run_pp_cp(
        2, 2, chunks=2, seq=32, rows=4)
    _, prep, postp = params
    p3 = (stacked, prep, postp)

    @jax.jit
    def step(p3):
        def loss(p3):
            return jnp.mean(pipe(*p3, x))
        l, g = jax.value_and_grad(loss)(p3)
        return jax.tree_util.tree_map(lambda a, ga: a - 0.1 * ga, p3, g), l

    losses = []
    for _ in range(15):
        p3, l = step(p3)
        losses.append(float(l))
    assert losses[-1] < losses[0], losses


def test_debug_context_check_passes_and_poisons():
    """debug_context_check: a pmean'd post passes untouched; a post that
    forgets the context reduction is poisoned with NaN instead of silently
    returning one shard's values (the check_vma=False contract, made loud)."""
    n_stages, n_context, chunks, seq, rows = 2, 2, 2, 32, 4
    cfg = dataclasses.replace(tiny_cfg(seq), n_layers=n_stages)
    model = ContextParallelLM(cfg, n_stages)
    sp, prep, postp = model.init(jax.random.key(0))
    stacked = stack_stage_params(sp)
    mesh = make_mesh(n_stages, 1, n_context=n_context)
    tokens = jax.random.randint(jax.random.key(1), (rows * chunks, seq),
                                0, cfg.vocab, jnp.int32)
    x, _ = mb.stack_scatter({"tokens": tokens,
                             "targets": jnp.roll(tokens, -1, -1)}, chunks)

    good = SpmdPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                        post_fn=model.loss_post_fn, post_with_batch=True,
                        context_axis=CONTEXT_AXIS, debug_context_check=True)
    out = good(stacked, prep, postp, x)
    assert np.isfinite(np.asarray(out)).all()

    def bad_post(p, h, x_mb, ctx):
        # context-VARIANT: each shard returns its own first local token id
        # (different global positions per shard; no pmean reduction)
        return x_mb["tokens"][:, 0].astype(jnp.float32)

    bad = SpmdPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                       post_fn=bad_post, post_with_batch=True,
                       context_axis=CONTEXT_AXIS, debug_context_check=True)
    out = bad(stacked, prep, postp, x)
    assert np.isnan(np.asarray(out)).all(), \
        "context-variant post must be poisoned"


def test_interleaved_memory_plan():
    from pipe_tpu.parallel.interleaved import InterleavedSpmdPipeline

    mesh = make_mesh(2, 1)
    pipe = InterleavedSpmdPipeline(mesh, lambda p, h, ctx: h, v=2)
    plan = pipe.memory_plan(8)
    assert plan == {"cycles": 8 * 2 + 1, "activation_slots": 8,
                    "out_slots": 8, "min_microbatches": 2}
