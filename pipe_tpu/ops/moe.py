"""Mixture-of-Experts FFN with expert parallelism (GShard/Switch lineage).

Beyond the reference (no MoE/EP there — SURVEY §2 strategy table). The
TPU-shaped design:

* **Dense dispatch, static shapes**: routing is expressed as one-hot
  dispatch/combine einsums over a fixed per-expert ``capacity`` (GShard's
  formulation) — no dynamic shapes, no sorting; XLA tiles the whole layer
  onto the MXU. Tokens over capacity fall through on the residual stream
  (standard switch behavior).
* **Expert sharding over the ``model`` mesh axis**: expert-indexed leaves
  (``w1/b1/w2/b2`` ``[E, ...]`` and the router's expert columns) shard on
  their expert dim, so each device holds ``E/ep`` experts and computes
  only their capacity slots — compute and memory scale ``1/ep``.
* **Same grad contract as tensor parallelism** (:mod:`.tp_layers`): the
  region is bracketed by the *f*/*g* custom-vjp operators (``tp_enter`` /
  ``tp_allreduce``), every sharded leaf's gradient is local by
  construction (the router weight is sharded BY EXPERT COLUMN for exactly
  this reason — its full-logit row assembles through one ``tp_allreduce``
  of zero-padded local logits), replicated leaves' gradients are
  model-identical, and executors never reduce gradients over the axis.
  Communication: two psums per MoE layer (logits assembly + output
  combine), riding the innermost (fastest-ICI) axis.

``ep_axis=None`` runs the identical math unsharded — the transparency
yardstick (``tests/test_moe.py``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.partition import StageCtx
from ..parallel.mesh import MODEL_AXIS
from .tp_layers import (tp_allreduce, tp_attention_init,
                        tp_attention_sublayer, tp_enter, _dropout,
                        _layernorm)

__all__ = ["moe_ffn_init", "moe_ffn_apply", "moe_ffn_specs", "moe_capacity",
           "moe_block_init", "moe_block_apply", "moe_block_decode",
           "moe_block_specs"]


def moe_ffn_init(key: jax.Array, d_model: int, d_ff: int, n_experts: int,
                 dtype=jnp.float32) -> Dict[str, Any]:
    ks = jax.random.split(key, 3)
    s_in = 1.0 / jnp.sqrt(d_model)
    s_out = 1.0 / jnp.sqrt(d_ff)
    return {
        "wr": jax.random.normal(ks[0], (d_model, n_experts), dtype) * s_in,
        "br": jnp.zeros((n_experts,), dtype),
        "w1": jax.random.normal(ks[1], (n_experts, d_model, d_ff),
                                dtype) * s_in,
        "b1": jnp.zeros((n_experts, d_ff), dtype),
        "w2": jax.random.normal(ks[2], (n_experts, d_ff, d_model),
                                dtype) * s_out,
        "b2": jnp.zeros((n_experts, d_model), dtype),
    }


def moe_ffn_specs() -> Dict[str, Any]:
    """Per-leaf PartitionSpecs: every expert-indexed dim shards over the
    model axis (incl. the router's expert columns)."""
    m = MODEL_AXIS
    return {
        "wr": P(None, m), "br": P(m),
        "w1": P(m, None, None), "b1": P(m, None),
        "w2": P(m, None, None), "b2": P(m, None),
    }


def moe_capacity(n_tokens: int, n_experts: int, k: int,
                 capacity_factor: float) -> int:
    """GShard capacity: per-expert slot count for a ``[n_tokens]`` batch."""
    return max(1, int(capacity_factor * n_tokens * k / n_experts))


def moe_ffn_apply(p: Dict[str, Any], h: jax.Array, ctx: StageCtx, *,
                  n_experts: int, k: int = 2,
                  capacity_factor: float = 1.25,
                  ep_axis: Optional[str] = MODEL_AXIS):
    """Top-k token-choice MoE FFN on LOCAL expert shards.

    ``h``: ``[rows, seq, d]`` replicated over the expert axis. Returns
    ``(out, aux_loss)`` where ``aux_loss`` is the standard load-balancing
    auxiliary (mean over experts of fraction-routed x mean-gate, scaled by
    E — Switch's formulation), identical on every shard.
    """
    if ep_axis is not None:
        psum = lambda v: tp_allreduce(v, ep_axis)
        h = tp_enter(h, ep_axis)
        ep = jax.lax.psum(1, ep_axis)
        shard = jax.lax.axis_index(ep_axis)
        ep_static = jax.core.concrete_or_error(
            int, ep, "expert-axis size must be static")
        if n_experts % ep_static:
            raise ValueError(
                f"n_experts={n_experts} not divisible by the expert-axis "
                f"size {ep_static}: orphaned experts would receive router "
                f"mass but produce zero output")
    else:
        psum = lambda v: v
        ep = 1
        shard = 0
    rows, seq, d = h.shape
    T = rows * seq
    E = n_experts
    e_local = E // ep
    x = h.reshape(T, d)

    # --- router: local expert columns -> full logits via one psum ------
    local_logits = x @ p["wr"] + p["br"]            # [T, E/ep]
    if ep_axis is not None:
        full = jnp.zeros((T, E), local_logits.dtype)
        full = jax.lax.dynamic_update_slice(
            full, local_logits, (0, shard * e_local))
        logits_raw = psum(full)
        # The GATING path's cotangents are shard-partial (each shard's
        # combine touches only its local experts' terms) and softmax
        # couples every column, so the full-logit cotangent must psum
        # before the router weight's column slice: a second f operator.
        # (softmax's vjp is linear in the cotangent, so psum-below ==
        # psum-above.) The AUX path's cotangents are shard-identical
        # (replicated aux value), so it branches off BEFORE tp_enter —
        # through the f operator it would be overcounted ep times.
        logits = tp_enter(logits_raw, ep_axis)
    else:
        logits_raw = local_logits
        logits = local_logits
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gates_aux = jax.nn.softmax(logits_raw.astype(jnp.float32), axis=-1)

    top_g, top_e = jax.lax.top_k(gates, k)          # [T, k]
    top_g = top_g / jnp.sum(top_g, axis=-1, keepdims=True)  # renormalize

    # --- capacity positions (computed identically on every shard) -----
    C = moe_capacity(T, E, k, capacity_factor)
    # flatten the k slots in priority order (slot 0 of every token first)
    flat_e = top_e.T.reshape(-1)                    # [k*T]
    onehot = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)      # [kT, E]
    pos = jnp.cumsum(onehot, axis=0) - 1            # position within expert
    flat_pos = jnp.sum(pos * onehot, axis=-1)       # [kT]
    keep = flat_pos < C
    flat_g = top_g.T.reshape(-1).astype(h.dtype) * keep

    # --- dispatch/combine one-hots over LOCAL experts ------------------
    le = flat_e - shard * e_local                   # local expert index
    local = (flat_e >= shard * e_local) & (flat_e < (shard + 1) * e_local)
    sel = local & keep
    # [kT, E/ep, C] one-hot (0 rows where not selected)
    disp = (jax.nn.one_hot(le, e_local, dtype=h.dtype)[:, :, None]
            * jax.nn.one_hot(flat_pos, C, dtype=h.dtype)[:, None, :]
            * sel[:, None, None].astype(h.dtype))
    tok = jnp.tile(jnp.arange(T), k)                # [kT] token of each slot
    xk = x[tok]                                     # [kT, d]
    x_e = jnp.einsum("tec,td->ecd", disp, xk)       # [E/ep, C, d]

    inner = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", x_e, p["w1"])
                        + p["b1"][:, None])
    y_e = jnp.einsum("ecf,efd->ecd", inner, p["w2"]) + p["b2"][:, None]

    comb = disp * flat_g[:, None, None]             # gate-weighted combine
    y_flat = jnp.einsum("tec,ecd->td", comb, y_e)   # [kT, d] partial
    y_tok = jnp.sum(y_flat.reshape(k, T, d), axis=0)
    out = psum(y_tok).reshape(rows, seq, d)

    # --- load-balance aux (Switch): E * sum_e f_e * m_e ----------------
    # computed from the pre-tp_enter softmax (see router note above)
    assign1 = jax.nn.one_hot(top_e[:, 0], E, dtype=jnp.float32)
    frac = jnp.mean(assign1, axis=0)                # fraction routed (top-1)
    mean_gate = jnp.mean(gates_aux, axis=0)
    aux = E * jnp.sum(frac * mean_gate)
    return out, aux.astype(jnp.float32)


# ---------------------------------------------------------------------------
# MoE transformer block: TP attention + MoE FFN (the standard hybrid —
# attention heads AND experts shard over the same innermost mesh axis)
# ---------------------------------------------------------------------------

def moe_block_init(key: jax.Array, d_model: int, nhead: int, d_ff: int,
                   n_experts: int, dtype=jnp.float32) -> Dict[str, Any]:
    ka, km = jax.random.split(key)
    p = tp_attention_init(ka, d_model, nhead, dtype)   # attention + both LNs
    p["moe"] = moe_ffn_init(km, d_model, d_ff, n_experts, dtype)
    return p


def moe_block_specs() -> Dict[str, Any]:
    from .tp_layers import tp_block_specs
    t = tp_block_specs()
    return {
        "ln1": t["ln1"], "wqkv": t["wqkv"], "bqkv": t["bqkv"],
        "wo": t["wo"], "bo": t["bo"], "ln2": t["ln2"],
        "moe": moe_ffn_specs(),
    }


def moe_block_decode(p: Dict[str, Any], h: jax.Array, cache, pos, *,
                     n_experts: int, k: int = 2,
                     capacity_factor: float = 1.25,
                     ep_axis: Optional[str] = MODEL_AXIS):
    """Incremental :func:`moe_block_apply` with a KV cache (inference):
    cached TP attention (heads sharded over the same axis as the
    experts), then the MoE FFN on the new positions — routing is
    per-token, so the dense dispatch works unchanged at q=1; the aux loss
    is discarded (inference). NOTE: GShard capacity is computed from the
    CURRENT call's token count, so at tiny decode batches use a generous
    ``capacity_factor`` if parity with a full-sequence forward matters
    (over-capacity tokens fall through on the residual, in both paths).
    Returns ``(h, new_cache)``."""
    from .tp_layers import tp_attention_decode

    h, cache = tp_attention_decode(p, h, cache, pos, tp_axis=ep_axis)
    hn = _layernorm(h, p["ln2"])
    ff, _aux = moe_ffn_apply(p["moe"], hn, StageCtx(), k=k,
                             n_experts=n_experts,
                             capacity_factor=capacity_factor,
                             ep_axis=ep_axis)
    return h + ff, cache


def moe_block_apply(p: Dict[str, Any], h: jax.Array, ctx: StageCtx, *,
                    n_experts: int, k: int = 2,
                    capacity_factor: float = 1.25, dropout: float = 0.0,
                    causal: bool = True,
                    ep_axis: Optional[str] = MODEL_AXIS):
    """Pre-LN block: TP attention sublayer, then the MoE FFN on the
    LayerNorm'd stream with a residual add (dropped tokens pass through on
    the residual). Returns ``(h, aux)``."""
    key1 = key2 = None
    if ctx.key is not None:
        key1, key2 = jax.random.split(ctx.key)
    h = tp_attention_sublayer(p, h, causal=causal, dropout=dropout,
                              key=key1, tp_axis=ep_axis)
    hn = _layernorm(h, p["ln2"])
    # moe_ffn_apply is deterministic (no ctx.key use); key2 is reserved
    # for the residual dropout below
    ff, aux = moe_ffn_apply(p["moe"], hn, StageCtx(), k=k,
                            n_experts=n_experts,
                            capacity_factor=capacity_factor,
                            ep_axis=ep_axis)
    return h + _dropout(ff, dropout, key2), aux
