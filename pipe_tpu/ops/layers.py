"""Minimal functional module system + transformer building blocks.

The reference stages are ``nn.Sequential`` children whose math bottoms out in
cuDNN/cuBLAS (``main.py:148``; SURVEY §2 native table). Here layers are pure
``(params, x) -> y`` functions grouped in lightweight Module objects — the
TPU-native equivalent is XLA:TPU codegen onto the MXU, so the "kernel library"
is jnp/einsum with bfloat16-friendly shapes; attention can later swap in a
Pallas flash kernel without changing this interface.

Init is shape-driven: ``module.init(key, x_spec)`` consumes only
``shape``/``dtype`` (arrays or ``jax.ShapeDtypeStruct`` both work), so whole
models initialize without running data through them.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.partition import StageCtx

__all__ = [
    "Module", "Sequential", "Lambda", "Linear", "Embedding", "LayerNorm",
    "Dropout", "MultiHeadAttention", "TransformerEncoderLayer",
    "PreLNBlock", "PositionalEncoding", "Decoder", "spec",
]


def spec(x) -> jax.ShapeDtypeStruct:
    """Abstract ``ShapeDtypeStruct`` of an array or spec (public helper)."""
    return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))


_spec = spec  # internal alias


class Module:
    """A pure-function layer: ``init`` makes params, ``apply`` runs the math."""

    name: str = "module"

    def init(self, key: jax.Array, *example_inputs) -> Any:
        raise NotImplementedError

    def apply(self, params, *inputs, ctx: StageCtx = StageCtx()):
        raise NotImplementedError

    def __call__(self, params, *inputs, ctx: StageCtx = StageCtx()):
        return self.apply(params, *inputs, ctx=ctx)

    def out_spec(self, params, *input_specs):
        """Abstract output spec, used to chain shape-driven inits.

        ``params`` goes through ``eval_shape`` as an argument (not a
        closure), so abstract param trees — ``ShapeDtypeStruct`` leaves, as
        produced by ``StageParamPack.abstract_tree`` for stage-sharded
        params — chain shapes without any concrete weights existing."""
        def f(p, *xs):
            return self.apply(p, *xs, ctx=StageCtx())
        return jax.eval_shape(f, params, *[_spec(x) for x in input_specs])


class Lambda(Module):
    """Wrap a parameterless function as a Module."""

    def __init__(self, fn: Callable, name: str = "lambda"):
        self.fn = fn
        self.name = name

    def init(self, key, *example_inputs):
        return {}

    def apply(self, params, *inputs, ctx: StageCtx = StageCtx()):
        return self.fn(*inputs)


class Sequential(Module):
    """Ordered composition — the analogue of the ``nn.Sequential`` the reference
    requires as Pipe input (``pipe.py:332`` via ``_verify_module``)."""

    def __init__(self, layers: Sequence[Module], name: str = "sequential"):
        self.layers = list(layers)
        self.name = name

    def __len__(self):
        return len(self.layers)

    def __iter__(self):
        return iter(self.layers)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(self.layers[idx])
        return self.layers[idx]

    def init(self, key, *example_inputs):
        params = []
        specs = [_spec(x) for x in example_inputs]
        for i, layer in enumerate(self.layers):
            lkey = jax.random.fold_in(key, i)
            p = layer.init(lkey, *specs)
            params.append(p)
            out = layer.out_spec(p, *specs)
            specs = list(out) if isinstance(out, (tuple, list)) else [out]
        return params

    def apply(self, params, *inputs, ctx: StageCtx = StageCtx()):
        if len(params) != len(self.layers):
            raise ValueError(
                f"Sequential got {len(params)} param entries for "
                f"{len(self.layers)} layers")
        out = inputs
        for i, (layer, p) in enumerate(zip(self.layers, params)):
            r = layer.apply(p, *out, ctx=ctx.fold(i))
            out = r if isinstance(r, tuple) else (r,)
        return out if len(out) > 1 else out[0]


class Linear(Module):
    def __init__(self, features: int, use_bias: bool = True,
                 dtype=jnp.float32, name: str = "linear"):
        self.features = features
        self.use_bias = use_bias
        self.dtype = dtype
        self.name = name

    def init(self, key, x):
        in_features = jnp.shape(x)[-1]
        bound = 1.0 / math.sqrt(in_features)
        wkey, bkey = jax.random.split(key)
        params = {
            "w": jax.random.uniform(wkey, (in_features, self.features),
                                    self.dtype, -bound, bound),
        }
        if self.use_bias:
            params["b"] = jax.random.uniform(bkey, (self.features,),
                                             self.dtype, -bound, bound)
        return params

    def apply(self, params, x, ctx: StageCtx = StageCtx()):
        y = jnp.einsum("...i,io->...o", x, params["w"])
        if self.use_bias:
            y = y + params["b"]
        return y


class Embedding(Module):
    """Token embedding with the tutorial's sqrt(d_model) scaling
    (reference ``Encoder``, ``main.py:139-157`` vicinity)."""

    def __init__(self, vocab: int, features: int, scale: bool = True,
                 dtype=jnp.float32, name: str = "embedding"):
        self.vocab = vocab
        self.features = features
        self.scale = scale
        self.dtype = dtype
        self.name = name

    def init(self, key, x):
        table = jax.random.normal(key, (self.vocab, self.features), self.dtype)
        return {"table": table}

    def apply(self, params, tokens, ctx: StageCtx = StageCtx()):
        y = jnp.take(params["table"], tokens, axis=0)
        if self.scale:
            y = y * jnp.asarray(math.sqrt(self.features), y.dtype)
        return y


class LayerNorm(Module):
    def __init__(self, eps: float = 1e-5, dtype=jnp.float32, name: str = "ln"):
        self.eps = eps
        self.dtype = dtype
        self.name = name

    def init(self, key, x):
        d = jnp.shape(x)[-1]
        return {"g": jnp.ones((d,), self.dtype), "b": jnp.zeros((d,), self.dtype)}

    def apply(self, params, x, ctx: StageCtx = StageCtx()):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mu) * jax.lax.rsqrt(var + self.eps)
        return y * params["g"] + params["b"]


class Dropout(Module):
    """Inverted dropout driven by the explicit ctx key.

    Under remat the identical key replays, so the recomputed forward is
    bit-identical to the stored one — the property the reference bought with
    CUDA RNG state capture (``README.md:528-537``).
    """

    def __init__(self, rate: float, name: str = "dropout"):
        self.rate = rate
        self.name = name

    def init(self, key, x):
        return {}

    def apply(self, params, x, ctx: StageCtx = StageCtx()):
        if not ctx.train or self.rate <= 0.0 or ctx.key is None:
            return x
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(ctx.key, keep, jnp.shape(x))
        return jnp.where(mask, x / keep, jnp.zeros_like(x))


def dot_product_attention(q, k, v, *, causal: bool = False,
                          dropout_rate: float = 0.0,
                          dropout_key: Optional[jax.Array] = None,
                          train: bool = False):
    """Softmax attention with float32 logits (MXU-friendly einsum form)."""
    d = q.shape[-1]
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k).astype(jnp.float32)
    logits = logits / math.sqrt(d)
    if causal:
        qlen, klen = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), bool))
        logits = jnp.where(mask, logits, jnp.asarray(-1e30, logits.dtype))
    weights = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if train and dropout_rate > 0.0 and dropout_key is not None:
        keep = 1.0 - dropout_rate
        m = jax.random.bernoulli(dropout_key, keep, weights.shape)
        weights = jnp.where(m, weights / keep, jnp.zeros_like(weights))
    return jnp.einsum("...hqk,...khd->...qhd", weights, v)


class MultiHeadAttention(Module):
    """Self-attention block (the math inside ``nn.TransformerEncoderLayer``,
    reference ``main.py:148``), batch-first: x is [batch, seq, d_model]."""

    def __init__(self, d_model: int, nhead: int, dropout: float = 0.0,
                 causal: bool = True, dtype=jnp.float32, name: str = "mha",
                 impl: str = "auto"):
        if d_model % nhead:
            raise ValueError("nhead must divide d_model")
        if impl not in ("auto", "xla", "flash"):
            raise ValueError(f"impl must be auto|xla|flash, got {impl!r}")
        self.d_model = d_model
        self.nhead = nhead
        self.head_dim = d_model // nhead
        self.dropout = dropout
        self.causal = causal
        self.dtype = dtype
        self.name = name
        self.impl = impl

    def init(self, key, x):
        keys = jax.random.split(key, 4)
        bound = 1.0 / math.sqrt(self.d_model)

        def mat(k):
            return jax.random.uniform(k, (self.d_model, self.d_model),
                                      self.dtype, -bound, bound)

        return {
            "wq": mat(keys[0]), "wk": mat(keys[1]), "wv": mat(keys[2]),
            "wo": mat(keys[3]),
            "bq": jnp.zeros((self.d_model,), self.dtype),
            "bk": jnp.zeros((self.d_model,), self.dtype),
            "bv": jnp.zeros((self.d_model,), self.dtype),
            "bo": jnp.zeros((self.d_model,), self.dtype),
        }

    def apply(self, params, x, ctx: StageCtx = StageCtx()):
        b, s, _ = x.shape
        h, hd = self.nhead, self.head_dim

        def proj(w, bias):
            return (jnp.einsum("bsd,de->bse", x, w) + bias).reshape(b, s, h, hd)

        q = proj(params["wq"], params["bq"])
        k = proj(params["wk"], params["bk"])
        v = proj(params["wv"], params["bv"])
        dk = ctx.fold(1).key if ctx.key is not None else None
        # Flash (Pallas) path when no attention-weight dropout is active and
        # the tiling covers the sequence; the XLA path otherwise. The choice
        # is static at trace time.
        # Flash handles attention-weight dropout only when compiled on TPU
        # (the kernel's hardware PRNG regenerates masks in backward);
        # interpret mode and unsupported tilings use the XLA path.
        on_tpu = jax.default_backend() == "tpu"
        dropout_active = self.dropout > 0.0 and ctx.train and dk is not None
        # auto: the measured-crossover heuristic lives in flash_auto_ok;
        # explicit impl="flash" bypasses it (tiling support still required).
        if self.impl == "flash":
            from .pallas_attention import supports
            use_flash = (not dropout_active or on_tpu) and supports(s)
        elif self.impl == "auto":
            use_flash = ((not dropout_active or on_tpu)
                         and flash_auto_ok(s))
        else:
            use_flash = False
        if use_flash:
            from .pallas_attention import flash_attention
        if use_flash:
            o = flash_attention(
                q, k, v, causal=self.causal,
                dropout_rate=self.dropout if dropout_active else 0.0,
                dropout_key=dk if dropout_active else None)
        else:
            o = dot_product_attention(q, k, v, causal=self.causal,
                                      dropout_rate=self.dropout,
                                      dropout_key=dk, train=ctx.train)
        o = o.reshape(b, s, self.d_model)
        return jnp.einsum("bsd,de->bse", o, params["wo"]) + params["bo"]

    def make_cache(self, batch: int, max_len: int, dtype=None):
        """Zeroed KV cache for incremental decoding: ``{"k","v"}`` of
        ``[batch, max_len, nhead, head_dim]``."""
        shape = (batch, max_len, self.nhead, self.head_dim)
        dt = dtype if dtype is not None else self.dtype
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}

    def decode(self, params, x, cache, pos, tree=None):
        """Incremental self-attention with a KV cache (inference only).

        ``x``: the new tokens' hidden states ``[b, q, d]`` occupying
        positions ``[pos, pos+q)`` (``q=1`` per decode step; ``q=prompt``
        at prefill with ``pos=0``); ``cache``: :meth:`make_cache` pytree.
        Writes the new K/V rows at ``pos`` and attends each query over
        cache positions ``<= its own`` — exactly :meth:`apply`'s causal
        mask restricted to the live prefix, so teacher-forced cached logits
        match the full forward. Returns ``(out [b, q, d], new_cache)``.

        ``tree`` (optional ``[q, q]`` bool, static): speculative tree
        verification. The q chunk rows are draft-TREE nodes, not a
        contiguous run — K/V still land at cache rows ``[pos, pos+q)``,
        but query row j attends cache rows strictly before ``pos`` plus
        the within-chunk rows where ``tree[j, r]`` (its ancestors-or-
        self). ``tree=None`` keeps the linear causal mask unchanged.
        """
        if not self.causal:
            raise ValueError("KV-cache decode requires causal attention")
        b, q, _ = x.shape
        h, hd = self.nhead, self.head_dim

        def proj(w, bias):
            return (jnp.einsum("bsd,de->bse", x, w) + bias).reshape(
                b, q, h, hd)

        qh = proj(params["wq"], params["bq"])
        kh = proj(params["wk"], params["bk"])
        vh = proj(params["wv"], params["bv"])
        ck = jax.lax.dynamic_update_slice(
            cache["k"], kh.astype(cache["k"].dtype), (0, pos, 0, 0))
        cv = jax.lax.dynamic_update_slice(
            cache["v"], vh.astype(cache["v"].dtype), (0, pos, 0, 0))
        logits = jnp.einsum("bqhd,bkhd->bhqk", qh, ck).astype(jnp.float32)
        logits = logits / math.sqrt(hd)
        kpos = jnp.arange(ck.shape[1])[None, None, None, :]
        if tree is None:
            qpos = pos + jnp.arange(q)[None, None, :, None]
            allowed = kpos <= qpos
        else:
            rel = jnp.arange(ck.shape[1]) - pos            # [K_cache]
            in_chunk = (rel >= 0) & (rel < q)
            within = jnp.asarray(tree)[
                :, jnp.clip(rel, 0, q - 1)]                # [q, K_cache]
            allowed = ((rel < 0) | (in_chunk & within))[
                None, None, :, :]
        logits = jnp.where(allowed, logits,
                           jnp.asarray(-1e30, logits.dtype))
        weights = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", weights, cv).reshape(
            b, q, self.d_model)
        out = jnp.einsum("bsd,de->bse", o, params["wo"]) + params["bo"]
        return out, {"k": ck, "v": cv}


# "gelu" is the EXACT erf form (torch.nn.TransformerEncoderLayer's
# activation='gelu', BERT, ViT); "gelu_tanh" is the tanh approximation
# (GPT-2's gelu_new — and jax.nn.gelu's default). Models must pick the
# variant their reference implementation uses; the HF parity tests pin
# both choices.
_ACTIVATIONS = {
    "relu": jax.nn.relu,
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_tanh": lambda x: jax.nn.gelu(x, approximate=True),
}

# Minimum sequence length at which impl="auto" selects the Pallas flash
# kernel on TPU (measured crossover; see MultiHeadAttention.apply).
FLASH_AUTO_MIN_SEQ = 256


def flash_auto_ok(s: int) -> bool:
    """The auto-selection heuristic, in ONE place (MultiHeadAttention and
    ulysses_attention both consult it): flash on TPU from the measured
    crossover length up, when the kernel tiling covers ``s``. Measured on
    v5e-lite (520M LM, bf16): a single 128-token block can't amortize the
    kernel (XLA +3.7% at s=128); flash wins from s=256 (+1.9%) and grows
    with s."""
    if jax.default_backend() != "tpu" or s < FLASH_AUTO_MIN_SEQ:
        return False
    from .pallas_attention import supports
    return supports(s)


class _TransformerBlockBase(Module):
    """Shared structure of the two block families (attn + FFN + 2 LN +
    dropout, one param pytree); subclasses supply ``apply`` (LN placement)."""

    def __init__(self, d_model: int, nhead: int, dim_feedforward: int,
                 dropout: float = 0.0, causal: bool = True,
                 dtype=jnp.float32, name: str = "block",
                 attn_impl: str = "auto", activation: str = "relu"):
        if activation not in _ACTIVATIONS:
            raise ValueError(
                f"activation must be one of {sorted(_ACTIVATIONS)}, "
                f"got {activation!r}")
        self.attn = MultiHeadAttention(d_model, nhead, dropout, causal, dtype,
                                       impl=attn_impl)
        self.ff1 = Linear(dim_feedforward, dtype=dtype)
        self.ff2 = Linear(d_model, dtype=dtype)
        self.ln1 = LayerNorm(dtype=dtype)
        self.ln2 = LayerNorm(dtype=dtype)
        self.drop = Dropout(dropout)
        self.act = _ACTIVATIONS[activation]
        self.name = name

    def init(self, key, x):
        ks = jax.random.split(key, 5)
        d_model_spec = _spec(x)
        hidden = jax.ShapeDtypeStruct(
            jnp.shape(x)[:-1] + (self.ff1.features,), jnp.result_type(x))
        return {
            "attn": self.attn.init(ks[0], x),
            "ff1": self.ff1.init(ks[1], x),
            "ff2": self.ff2.init(ks[2], hidden),
            "ln1": self.ln1.init(ks[3], d_model_spec),
            "ln2": self.ln2.init(ks[4], d_model_spec),
        }


class TransformerEncoderLayer(_TransformerBlockBase):
    """Post-LN transformer block — semantics of torch's default
    ``nn.TransformerEncoderLayer`` (reference ``main.py:148``): self-attn →
    add&norm → FFN(ReLU/GELU) → add&norm, dropout on each residual branch."""

    def __init__(self, *args, name: str = "encoder_layer", **kwargs):
        super().__init__(*args, name=name, **kwargs)

    def apply(self, params, x, ctx: StageCtx = StageCtx()):
        a = self.attn.apply(params["attn"], x, ctx=ctx.fold(0))
        a = self.drop.apply({}, a, ctx=ctx.fold(1))
        x = self.ln1.apply(params["ln1"], x + a, ctx=ctx)
        h = self.act(self.ff1.apply(params["ff1"], x, ctx=ctx))
        h = self.drop.apply({}, h, ctx=ctx.fold(2))
        h = self.ff2.apply(params["ff2"], h, ctx=ctx)
        h = self.drop.apply({}, h, ctx=ctx.fold(3))
        return self.ln2.apply(params["ln2"], x + h, ctx=ctx)

    def decode(self, params, x, cache, pos, tree=None):
        """Incremental :meth:`apply` (inference: no dropout) — same math on
        the new positions with attention served from the KV cache."""
        a, cache = self.attn.decode(params["attn"], x, cache, pos,
                                    tree=tree)
        x = self.ln1.apply(params["ln1"], x + a)
        h = self.act(self.ff1.apply(params["ff1"], x))
        h = self.ff2.apply(params["ff2"], h)
        return self.ln2.apply(params["ln2"], x + h), cache


class PreLNBlock(_TransformerBlockBase):
    """Pre-LN transformer block (GPT-2 / ViT lineage): x + attn(ln1(x)),
    then x + ffn(ln2(x)) with GELU — the ring-invariant stage body for the
    model zoo's pipelined GPT-2/ViT factorizations. Same param pytree as
    :class:`TransformerEncoderLayer` (shared base); only LN placement
    differs."""

    def __init__(self, *args, name: str = "preln_block",
                 activation: str = "gelu", **kwargs):
        super().__init__(*args, name=name, activation=activation, **kwargs)

    def apply(self, params, x, ctx: StageCtx = StageCtx()):
        a = self.attn.apply(params["attn"],
                            self.ln1.apply(params["ln1"], x, ctx=ctx),
                            ctx=ctx.fold(0))
        x = x + self.drop.apply({}, a, ctx=ctx.fold(1))
        h = self.act(self.ff1.apply(
            params["ff1"], self.ln2.apply(params["ln2"], x, ctx=ctx),
            ctx=ctx))
        h = self.ff2.apply(params["ff2"], h, ctx=ctx)
        return x + self.drop.apply({}, h, ctx=ctx.fold(2))

    def decode(self, params, x, cache, pos, tree=None):
        """Incremental :meth:`apply` (inference: no dropout) — same math on
        the new positions with attention served from the KV cache."""
        a, cache = self.attn.decode(params["attn"],
                                    self.ln1.apply(params["ln1"], x),
                                    cache, pos, tree=tree)
        x = x + a
        h = self.act(self.ff1.apply(params["ff1"],
                                    self.ln2.apply(params["ln2"], x)))
        return x + self.ff2.apply(params["ff2"], h), cache


class PositionalEncoding(Module):
    """Sinusoidal positions + dropout (tutorial ``PositionalEncoding``,
    reference ``main.py`` model section). Batch-first: [batch, seq, d]."""

    def __init__(self, d_model: int, dropout: float = 0.0,
                 max_len: int = 5000, dtype=jnp.float32, name: str = "posenc"):
        self.d_model = d_model
        self.drop = Dropout(dropout)
        position = np.arange(max_len)[:, None]
        div = np.exp(np.arange(0, d_model, 2) * (-math.log(10000.0) / d_model))
        pe = np.zeros((max_len, d_model), np.float32)
        pe[:, 0::2] = np.sin(position * div)
        pe[:, 1::2] = np.cos(position * div)
        self.pe = jnp.asarray(pe, dtype)
        self.name = name

    def init(self, key, x):
        return {}

    def apply(self, params, x, ctx: StageCtx = StageCtx()):
        s = x.shape[-2]
        x = x + self.pe[:s]
        return self.drop.apply({}, x, ctx=ctx)


class Decoder(Module):
    """Final projection to vocab logits (tutorial ``Decoder``, reference
    ``main.py`` model section)."""

    def __init__(self, vocab: int, dtype=jnp.float32, name: str = "decoder"):
        self.proj = Linear(vocab, dtype=dtype)
        self.name = name

    def init(self, key, x):
        return self.proj.init(key, x)

    def apply(self, params, x, ctx: StageCtx = StageCtx()):
        return self.proj.apply(params, x, ctx=ctx)
