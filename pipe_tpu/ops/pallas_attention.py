"""Flash attention as a Pallas TPU kernel, with a full custom-VJP backward.

The reference's attention math lives in cuDNN via
``nn.TransformerEncoderLayer`` (``main.py:148``; SURVEY §2 native table —
"attention via ... a Pallas flash-attention kernel" is the designated
TPU-native replacement). This kernel keeps the O(s²) score matrix out of HBM:

* forward: grid over (batch·head, q-block); K/V stream through VMEM while a
  streaming-softmax (running max ``m``, normalizer ``l``) accumulates the
  output block on-chip; returns O and the per-row logsumexp ``L``;
* backward: the standard flash decomposition — ``D = rowsum(dO·O)``, then a
  dQ kernel (grid over q-blocks, loop over k-blocks) and a dK/dV kernel
  (grid over k-blocks, loop over q-blocks), each rebuilding ``p = exp(s−L)``
  from the saved ``L`` instead of storing attention weights;
* causal masking compares absolute positions, so any (block_q, block_k)
  tiling gives identical numbers;
* layouts follow the Mosaic block rule (last two block dims sublane/lane
  aligned): compute runs on ``[batch·head, seq, head_dim]`` views and the
  row statistics on ``[batch·head, 1, seq]``;
* off-TPU the same kernels run in interpreter mode (tests stay hermetic).

Attention-weight dropout runs *inside* the kernel on TPU (hardware PRNG
seeded per (batch·head, q-block, k-block), so forward and backward
regenerate identical masks without storing them; the normalizer ``l`` is
computed pre-dropout, matching ``dropout(softmax(s)) @ v`` semantics).
Interpret mode has no PRNG, so dropout-bearing steps off-TPU use the XLA
path (``ops.layers``).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "supports"]

NEG_INF = float("-inf")


def supports(seq_len: int, *, block: int = 128, min_tile: int = 8) -> bool:
    """Whether the kernel handles this shape (else callers use the XLA path).

    Needs sublane-aligned rows (f32 tile: 8) and a block tiling that covers
    the sequence exactly (a block >= seq collapses to one full-seq block).
    """
    if seq_len < min_tile or seq_len % min_tile:
        return False
    return block >= seq_len or seq_len % block == 0


def _drop_mask(seed, bh, iq, ik, shape, rate):
    """Regenerable per-(batch*head, q-block, k-block) keep mask, scaled.

    Returns keep/rate scaling factors (0 where dropped). Seeding is a pure
    function of (seed, bh, iq, ik), so the backward kernels rebuild the
    identical mask without storing it.
    """
    # One mixed scalar (multi-operand seeding miscompiles inside fori_loop
    # on some Mosaic versions); constants are odd primes for bit dispersion.
    mixed = (seed
             + bh * jnp.int32(-1640531535)   # 2654435761 as int32
             + iq * jnp.int32(40503)
             + ik * jnp.int32(961748941))
    pltpu.prng_seed(mixed)
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    threshold = jnp.uint32(min(int(rate * (1 << 32)), (1 << 32) - 1))
    keep = bits >= threshold
    return jnp.where(keep, 1.0 / (1.0 - rate), 0.0).astype(jnp.float32)


def _causal_mask(s, q_start, k_start, bq, bk):
    qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(qpos >= kpos, s, NEG_INF)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k,
                seq_len, causal, scale, dropout_rate):
    bq, d = q_ref.shape[1], q_ref.shape[2]
    bh = pl.program_id(0)
    iq = pl.program_id(1)
    q = q_ref[0, :, :] * scale                           # [bq, d]
    q_start = iq * bq

    o = jnp.zeros((bq, d), jnp.float32)
    m = jnp.full((bq,), NEG_INF, jnp.float32)
    l = jnp.zeros((bq,), jnp.float32)

    nk = seq_len // block_k
    nk_needed = nk if not causal else (q_start + bq - 1) // block_k + 1

    def body(ik, carry):
        o, m, l = carry
        k = k_ref[0, pl.ds(ik * block_k, block_k), :]    # [bk, d]
        v = v_ref[0, pl.ds(ik * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk]
        if causal:
            s = _causal_mask(s, q_start, ik * block_k, bq, block_k)
        block_max = jnp.max(s, axis=-1)
        new_m = jnp.maximum(m, block_max)
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(s - safe_m[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l = l * corr + jnp.sum(p, axis=-1)   # normalizer: pre-dropout
        if dropout_rate > 0.0:
            p = p * _drop_mask(seed_ref[0], bh, iq, ik, p.shape,
                               dropout_rate)
        o = o * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return o, new_m, l

    o, m, l = jax.lax.fori_loop(0, nk_needed, body, (o, m, l))
    o = o / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0, :, :] = o.astype(o_ref.dtype)
    lse_ref[0, 0, :] = (jnp.where(jnp.isfinite(m), m, 0.0) +
                        jnp.log(jnp.maximum(l, 1e-30)))


def _smem_scalar_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _fwd(q3, k3, v3, seed, causal, scale, bq, bk, interpret, dropout_rate):
    bh, s, d = q3.shape
    grid = (bh, s // bq)
    qspec = pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0))
    kvspec = pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0))
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_k=bk, seq_len=s, causal=causal,
                          scale=scale, dropout_rate=dropout_rate),
        grid=grid,
        in_specs=[_smem_scalar_spec(), qspec, kvspec, kvspec],
        out_specs=[qspec,
                   pl.BlockSpec((1, 1, bq), lambda i, j: (i, 0, j))],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        interpret=interpret,
    )(seed, q3, k3, v3)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, *, block_k, seq_len, causal, scale,
                   dropout_rate):
    bq, d = q_ref.shape[1], q_ref.shape[2]
    bh = pl.program_id(0)
    iq = pl.program_id(1)
    q_start = iq * bq
    q = q_ref[0, :, :] * scale
    do = do_ref[0, :, :].astype(jnp.float32)
    lse = lse_ref[0, 0, :]
    delta = delta_ref[0, 0, :]

    nk = seq_len // block_k
    nk_needed = nk if not causal else (q_start + bq - 1) // block_k + 1

    def body(ik, dq):
        k = k_ref[0, pl.ds(ik * block_k, block_k), :]
        v = v_ref[0, pl.ds(ik * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_start, ik * block_k, bq, block_k)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            dp = dp * _drop_mask(seed_ref[0], bh, iq, ik, dp.shape,
                                 dropout_rate)
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nk_needed, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, :, :] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, *, block_q, seq_len, causal,
                    scale, dropout_rate):
    bk, d = k_ref.shape[1], k_ref.shape[2]
    bh = pl.program_id(0)
    ik = pl.program_id(1)
    k_start = ik * bk
    k = k_ref[0, :, :]
    v = v_ref[0, :, :]

    nq = seq_len // block_q
    iq0 = 0 if not causal else k_start // block_q

    def body(iq, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(iq * block_q, block_q), :] * scale
        do = do_ref[0, pl.ds(iq * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(iq * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(iq * block_q, block_q)]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, iq * block_q, k_start, block_q, bk)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        if dropout_rate > 0.0:
            mask = _drop_mask(seed_ref[0], bh, iq, ik, p.shape, dropout_rate)
            p_v = p * mask
        else:
            mask = None
            p_v = p
        dv = dv + jax.lax.dot_general(
            p_v, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if mask is not None:
            dp = dp * mask
        ds = p * (dp - delta[:, None])
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        iq0, nq, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)))
    dk_ref[0, :, :] = dk.astype(dk_ref.dtype)
    dv_ref[0, :, :] = dv.astype(dv_ref.dtype)


def _bwd(causal, scale, bq, bk, interpret, dropout_rate, residuals, g):
    q3, k3, v3, seed, o3, lse = residuals
    do3 = g
    bh, s, d = q3.shape
    delta = jnp.einsum("bsd,bsd->bs", do3.astype(jnp.float32),
                       o3.astype(jnp.float32))[:, None, :]   # [bh, 1, s]

    qspec = pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0))
    full = pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0))
    row_q = pl.BlockSpec((1, 1, bq), lambda i, j: (i, 0, j))
    row_full = pl.BlockSpec((1, 1, s), lambda i, j: (i, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_k=bk, seq_len=s,
                          causal=causal, scale=scale,
                          dropout_rate=dropout_rate),
        grid=(bh, s // bq),
        in_specs=[_smem_scalar_spec(), qspec, full, full, qspec, row_q,
                  row_q],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q3.dtype),
        interpret=interpret,
    )(seed, q3, k3, v3, do3, lse, delta)

    kspec = pl.BlockSpec((1, bk, d), lambda i, j: (i, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=bq, seq_len=s,
                          causal=causal, scale=scale,
                          dropout_rate=dropout_rate),
        grid=(bh, s // bk),
        in_specs=[_smem_scalar_spec(), full, kspec, kspec, full, row_full,
                  row_full],
        out_specs=[kspec, kspec],
        out_shape=[jax.ShapeDtypeStruct((bh, s, d), k3.dtype),
                   jax.ShapeDtypeStruct((bh, s, d), v3.dtype)],
        interpret=interpret,
    )(seed, q3, k3, v3, do3, lse, delta)
    return dq, dk, dv, None


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _make(causal: bool, scale: float, bq: int, bk: int, interpret: bool,
          dropout_rate: float):
    @jax.custom_vjp
    def attend(q3, k3, v3, seed):
        o, _ = _fwd(q3, k3, v3, seed, causal, scale, bq, bk, interpret,
                    dropout_rate)
        return o

    def fwd(q3, k3, v3, seed):
        o, lse = _fwd(q3, k3, v3, seed, causal, scale, bq, bk, interpret,
                      dropout_rate)
        return o, (q3, k3, v3, seed, o, lse)

    attend.defvjp(fwd, functools.partial(_bwd, causal, scale, bq, bk,
                                         interpret, dropout_rate))
    return attend


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    dropout_rate: float = 0.0,
                    dropout_key: Optional[jax.Array] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Flash attention over ``[batch, seq, heads, head_dim]`` inputs.

    ``interpret`` defaults to True off-TPU (tests/dev boxes) and False on
    TPU. Raises for shapes the tiling cannot cover — gate with
    :func:`supports` and fall back to the XLA path.

    ``dropout_rate`` > 0 applies attention-weight dropout *inside* the
    kernel (TPU hardware PRNG; masks are a pure function of
    ``dropout_key`` and block indices, so the backward kernels regenerate
    them bit-identically). Only available compiled on TPU — interpret mode
    has no PRNG — so callers must keep dropout off the interpret path.
    """
    b, s, h, d = q.shape
    if not supports(s, block=min(block_q, block_k)):
        raise ValueError(
            f"flash_attention: seq_len {s} not divisible into blocks; "
            f"use ops.layers.dot_product_attention")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got {dropout_rate}")
    if dropout_rate > 0.0:
        if interpret:
            raise NotImplementedError(
                "flash_attention dropout needs the TPU PRNG; interpret "
                "mode must use ops.layers.dot_product_attention")
        if dropout_key is None:
            raise ValueError("dropout_rate > 0 requires dropout_key")
        kd = jax.random.key_data(dropout_key).astype(jnp.uint32).ravel()
        seed = (kd[0] ^ kd[-1]).astype(jnp.int32).reshape((1,))
    else:
        seed = jnp.zeros((1,), jnp.int32)
    scale = float(scale if scale is not None else 1.0 / math.sqrt(d))
    bq = min(block_q, s)
    bk = min(block_k, s)
    # The kernels iterate s // bq and s // bk grids; a non-dividing block
    # (possible with mismatched non-default block_q/block_k) would silently
    # skip trailing positions instead of erroring (ADVICE r1).
    if s % bq or s % bk:
        raise ValueError(
            f"flash_attention: seq_len {s} must be divisible by block_q={bq} "
            f"and block_k={bk}; use ops.layers.dot_product_attention")

    def to3(x):  # [b, s, h, d] -> [b*h, s, d]
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    o3 = _make(causal, scale, bq, bk, bool(interpret),
               float(dropout_rate))(to3(q), to3(k), to3(v), seed)
    return o3.reshape(b, h, s, d).transpose(0, 2, 1, 3)
