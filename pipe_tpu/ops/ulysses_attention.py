"""Ulysses-style sequence parallelism: all-to-all head<->sequence resharding.

The second long-context strategy next to :mod:`.ring_attention` (SURVEY §5
names both; the reference has neither — seq len is a plain dim,
``main.py:107``). Where the ring rotates K/V blocks and keeps queries
sequence-sharded throughout, Ulysses (DeepSpeed-Ulysses lineage, Jacobs et
al. 2023) RESHARDS around the attention itself:

* inputs arrive ``[rows, seq/c, heads, d]`` (sequence sharded over the
  ``context`` axis, like every other tensor in the stage body);
* one ``jax.lax.all_to_all`` per operand flips the sharding to
  ``[rows, seq, heads/c, d]`` — each device now holds the FULL sequence for
  ``heads/c`` heads;
* attention runs UNSHARDED per device — which means the Pallas flash kernel
  (``ops.pallas_attention``) applies as-is, something the ring's streaming
  accumulation cannot use;
* one reverse all-to-all restores sequence sharding for the rest of the
  block (FFN/LN are per-token and never notice).

Trade-offs vs the ring: communication is 4 all-to-alls of activation-sized
tensors per attention (vs n ppermute hops moving K/V twice each), requires
``heads % context == 0``, and peak memory holds one full-sequence attention
for heads/c heads; the ring keeps strictly block-sized tensors. Both are
exact. AD is free: ``all_to_all``'s transpose is the reverse all-to-all, so
``jax.grad`` through this function yields the mirrored communication
pattern.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..utils.compat import axis_size

__all__ = ["ulysses_attention"]


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, *, causal: bool = True,
                      attn_fn: Optional[Callable] = None) -> jax.Array:
    """Exact attention over a sequence sharded on ``axis_name``.

    Args:
      q, k, v: local shards ``[rows, seq_local, heads, head_dim]`` (the
        global sequence is ``seq_local * axis_size``). ``heads`` must be
        divisible by the axis size.
      axis_name: bound mesh axis to reshard over (run under ``shard_map``).
      causal: standard causal masking over GLOBAL positions (positions are
        global after the reshard, so no offset bookkeeping is needed —
        contrast ``ring_attention``'s block-origin arithmetic).
      attn_fn: ``(q, k, v, causal) -> o`` over full-sequence inputs;
        defaults to the library's auto-selected attention (Pallas flash on
        TPU at supported lengths, XLA otherwise).

    Returns the attention output with the INPUT sharding
    (``[rows, seq_local, heads, head_dim]``).
    """
    c = axis_size(axis_name)
    heads = q.shape[2]
    if heads % c:
        raise ValueError(
            f"ulysses_attention needs heads % axis_size == 0, got "
            f"heads={heads}, axis_size={c}")

    def reshard(x):
        # [rows, s/c, h, d] -> [rows, s, h/c, d]: split heads, gather seq
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def unshard(x):
        # [rows, s, h/c, d] -> [rows, s/c, h, d]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qf, kf, vf = reshard(q), reshard(k), reshard(v)
    if attn_fn is None:
        o = _default_attention(qf, kf, vf, causal)
    else:
        o = attn_fn(qf, kf, vf, causal)
    return unshard(o.astype(q.dtype))


def _default_attention(q, k, v, causal):
    """Full-sequence attention: the SHARED auto heuristic
    (``layers.flash_auto_ok``) picks the Pallas flash kernel or the XLA
    softmax path — one crossover policy for every attention call site."""
    from .layers import dot_product_attention, flash_auto_ok

    if flash_auto_ok(q.shape[1]):
        from .pallas_attention import flash_attention
        return flash_attention(q, k, v, causal=causal)
    return dot_product_attention(q, k, v, causal=causal)
