"""Functional layer library (MXU-friendly jnp/einsum ops)."""
