"""Tensor-parallel transformer block (Megatron-style column/row sharding).

Beyond the reference (which has no tensor parallelism — SURVEY §2 strategy
table: TP absent), built the TPU way: parameters carry per-leaf
``PartitionSpec``s over a ``model`` mesh axis, ``shard_map`` hands each
device its local shard, and the block body is written for local shards with
exactly TWO ``psum``s per block (attention output projection and FFN second
matmul) — the canonical column-then-row split:

* QKV projection: **column-parallel** — heads are split over the model axis
  (weights ``[d, 3, H, hd]`` sharded on the head dim), so each device
  computes attention for its ``H/tp`` heads with no communication;
* attention output projection: **row-parallel** — local heads contract
  against the local slice of ``W_O`` (``[H, hd, d]`` sharded on dim 0),
  partial results ``psum`` over the model axis;
* FFN: ``W1 [d, ff]`` column-sharded on dim 1, ``W2 [ff, d]`` row-sharded
  on dim 0, one ``psum`` after ``W2``.

Biases that live on sharded dims (``b_qkv``, ``b1``) are sharded with their
weights; output-side biases (``b_o``, ``b2``) and LayerNorm params are
replicated and added/applied AFTER the psum (once, not tp times).

Invariance: with dropout applied only to REPLICATED activations (the two
residual dropouts, post-psum), the tp=k forward/backward equals the tp=1
computation exactly (up to fp reduction order) — asserted in
``tests/test_tp.py``. Attention-probability dropout would act on
head-sharded tensors (same key ⇒ same mask per shard ⇒ different math from
tp=1), so this block deliberately uses residual dropout only.

Differentiation contract: these blocks are built for IN-PROGRAM vjp — the
schedule-table executor computes ``jax.vjp`` inside the shard_map body and
never reduces gradients over the model axis (see :func:`tp_enter`). Do NOT
wrap a ``shard_map`` of this block in an outer ``jax.grad`` with replicated
``in_specs``: the boundary transpose inserts its own model-axis psum on
replicated operands' cotangents, double-counting every replicated leaf's
gradient on top of the tp_enter contract.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.partition import StageCtx
from ..parallel.mesh import MODEL_AXIS

__all__ = ["tp_block_init", "tp_block_apply", "tp_attention_decode",
           "tp_block_decode",
           "tp_block_specs", "tp_enter", "tp_allreduce",
           "tp_attention_sublayer", "tp_attention_init"]


def tp_attention_init(key: jax.Array, d_model: int, nhead: int,
                      dtype=jnp.float32) -> Dict[str, Any]:
    """Attention + both LayerNorms (the sublayer shared with the MoE
    block); full (unsharded) shapes — sharding comes from the specs."""
    hd = d_model // nhead
    if hd * nhead != d_model:
        raise ValueError(f"d_model={d_model} not divisible by nhead={nhead}")
    ks = jax.random.split(key, 2)
    s_attn = 1.0 / jnp.sqrt(d_model)
    return {
        "ln1": {"scale": jnp.ones((d_model,), dtype),
                "bias": jnp.zeros((d_model,), dtype)},
        "wqkv": jax.random.normal(ks[0], (d_model, 3, nhead, hd),
                                  dtype) * s_attn,
        "bqkv": jnp.zeros((3, nhead, hd), dtype),
        "wo": jax.random.normal(ks[1], (nhead, hd, d_model), dtype) * s_attn,
        "bo": jnp.zeros((d_model,), dtype),
        "ln2": {"scale": jnp.ones((d_model,), dtype),
                "bias": jnp.zeros((d_model,), dtype)},
    }


def tp_block_init(key: jax.Array, d_model: int, nhead: int, d_ff: int,
                  dtype=jnp.float32) -> Dict[str, Any]:
    """Full (unsharded) parameter tree; sharding comes from the specs."""
    ka, kf = jax.random.split(key)
    p = tp_attention_init(ka, d_model, nhead, dtype)
    ks = jax.random.split(kf, 2)
    s_in = 1.0 / jnp.sqrt(d_model)
    s_out = 1.0 / jnp.sqrt(d_ff)
    p.update({
        "w1": jax.random.normal(ks[0], (d_model, d_ff), dtype) * s_in,
        "b1": jnp.zeros((d_ff,), dtype),
        "w2": jax.random.normal(ks[1], (d_ff, d_model), dtype) * s_out,
        "b2": jnp.zeros((d_model,), dtype),
    })
    return p


def tp_block_specs() -> Dict[str, Any]:
    """Per-leaf PartitionSpecs over the block's OWN dims (no stage dim):
    heads and ff sharded over ``model``, everything else replicated."""
    m = MODEL_AXIS
    return {
        "ln1": {"scale": P(), "bias": P()},
        "wqkv": P(None, None, m, None),
        "bqkv": P(None, m, None),
        "wo": P(m, None, None),
        "bo": P(),
        "ln2": {"scale": P(), "bias": P()},
        "w1": P(None, m),
        "b1": P(m),
        "w2": P(m, None),
        "b2": P(),
    }


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_enter(h, axis):
    """Megatron's *f* operator: identity forward, psum backward.

    Marks the entry into a tensor-parallel region — applied to the
    PARALLEL-REGION inputs (the LayerNorm outputs feeding QKV and W1), NOT
    the block input: the residual stream must stay outside the f…psum pair
    or its (already replicated) cotangent would be overcounted tp times.
    Each shard's backward produces only its own heads'/features'
    contribution to ``d loss/d hn``; the all-reduce here makes every
    cotangent upstream of it (LayerNorm params, the residual stream, the
    previous stage, the embed) **identical across model shards**. That
    invariant is the grad contract: executors never reduce gradients over
    the model axis — sharded leaves' grads are local by construction,
    replicated leaves' grads are model-identical by this operator.
    """
    return h


def _tp_enter_fwd(h, axis):
    return h, None


def _tp_enter_bwd(axis, _, g):
    return (jax.lax.psum(g, axis),)


tp_enter.defvjp(_tp_enter_fwd, _tp_enter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_allreduce(x, axis):
    """Megatron's *g* operator: psum forward, IDENTITY backward.

    The row-parallel output sum must not be differentiated as a raw
    ``lax.psum``: JAX's transpose rule for psum is psum, which is correct
    when the output's cotangents vary per shard (e.g. the BN data-axis
    stats) but here the loss is symmetric across model shards, the
    cotangent is replicated, and the transpose-psum would multiply every
    upstream gradient by tp (measured exactly 2x at tp=2). Each shard's
    true ``d loss/d partial_k`` is the unsummed replicated cotangent —
    identity."""
    return jax.lax.psum(x, axis)


def _tp_allreduce_fwd(x, axis):
    return jax.lax.psum(x, axis), None


def _tp_allreduce_bwd(axis, _, g):
    return (g,)


tp_allreduce.defvjp(_tp_allreduce_fwd, _tp_allreduce_bwd)


def _layernorm(x, p, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"]


def _layernorm_tapped(x, p, eps=1e-5):
    """LayerNorm returning its normalized input (the scale-grad tap)."""
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    xhat = (x - mu) * jax.lax.rsqrt(var + eps)
    return xhat * p["scale"] + p["bias"], xhat


def _dropout(x, rate: float, key: Optional[jax.Array]):
    if not rate or key is None:
        return x
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))


def _ops_for(tp_axis):
    if tp_axis is not None:
        return (lambda v: tp_allreduce(v, tp_axis),
                lambda v: tp_enter(v, tp_axis))
    ident = lambda v: v
    return ident, ident


def tp_attention_sublayer(p: Dict[str, Any], h: jax.Array, *,
                          causal: bool, dropout: float,
                          key: Optional[jax.Array],
                          tp_axis: Optional[str]) -> jax.Array:
    """Pre-LN self-attention with column/row head sharding, incl. the
    residual add (shared by the TP block and the MoE block)."""
    psum, enter = _ops_for(tp_axis)
    rows, seq, d = h.shape
    hn = enter(_layernorm(h, p["ln1"]))
    qkv = jnp.einsum("bsd,dthk->btshk", hn, p["wqkv"]) + p["bqkv"][:, None]
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]       # [rows, seq, Hl, hd]
    hd = q.shape[-1]
    scores = jnp.einsum("bshk,bthk->bhst", q, k) / jnp.sqrt(
        jnp.asarray(hd, h.dtype))
    if causal:
        mask = jnp.tril(jnp.ones((seq, seq), bool))
        scores = jnp.where(mask, scores, jnp.asarray(-1e9, scores.dtype))
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(
        h.dtype)
    attn = jnp.einsum("bhst,bthk->bshk", probs, v)   # [rows, seq, Hl, hd]
    # row-parallel out projection: partial sums psum over the model axis;
    # the replicated bias is added AFTER (once) — its cotangent is the
    # replicated output grad, identical on every model shard, per the
    # tp_enter grad contract (no model-axis grad reduction anywhere).
    out = psum(jnp.einsum("bshk,hkd->bsd", attn, p["wo"])) + p["bo"]
    return h + _dropout(out, dropout, key)


def tp_attention_decode(p: Dict[str, Any], h: jax.Array, cache, pos,
                        *, tp_axis: Optional[str] = MODEL_AXIS):
    """Incremental :func:`tp_attention_sublayer` with a KV cache
    (inference), including the residual add.

    ``h``: the new tokens' hidden states ``[b, q, d]``, replicated over
    the model axis; ``cache``: ``{"k","v"}`` of ``[b, max_len, H_local,
    hd]`` — the cache shards BY HEADS with the attention weights, so KV
    memory also divides by tp. One psum (the row-parallel output
    projection); causal by construction (each query attends cache rows
    ``<= its own position``). Returns ``(h, new_cache)``.
    """
    psum, _ = _ops_for(tp_axis)
    b, q, d = h.shape

    hn = _layernorm(h, p["ln1"])
    qkv = jnp.einsum("bsd,dthk->btshk", hn, p["wqkv"]) + p["bqkv"][:, None]
    qh, kh, vh = qkv[:, 0], qkv[:, 1], qkv[:, 2]     # [b, q, Hl, hd]
    hd = qh.shape[-1]
    ck = jax.lax.dynamic_update_slice(
        cache["k"], kh.astype(cache["k"].dtype), (0, pos, 0, 0))
    cv = jax.lax.dynamic_update_slice(
        cache["v"], vh.astype(cache["v"].dtype), (0, pos, 0, 0))
    logits = jnp.einsum("bqhd,bkhd->bhqk", qh, ck).astype(
        jnp.float32) / jnp.sqrt(jnp.float32(hd))
    kpos = jnp.arange(ck.shape[1])[None, None, None, :]
    qpos = pos + jnp.arange(q)[None, None, :, None]
    logits = jnp.where(kpos <= qpos, logits,
                       jnp.asarray(-1e30, logits.dtype))
    probs = jax.nn.softmax(logits, axis=-1).astype(h.dtype)
    attn = jnp.einsum("bhqk,bkhd->bqhd", probs, cv)  # [b, q, Hl, hd]
    out = psum(jnp.einsum("bshk,hkd->bsd", attn, p["wo"])) + p["bo"]
    return h + out, {"k": ck, "v": cv}


def tp_block_decode(p: Dict[str, Any], h: jax.Array, cache, pos,
                    *, tp_axis: Optional[str] = MODEL_AXIS):
    """Incremental :func:`tp_block_apply` with a KV cache (inference):
    cached TP attention, then the column/row FFN (the block's second
    psum). Returns ``(h, new_cache)``."""
    psum, _ = _ops_for(tp_axis)
    h, cache = tp_attention_decode(p, h, cache, pos, tp_axis=tp_axis)
    hn2 = _layernorm(h, p["ln2"])
    inner = jax.nn.gelu(hn2 @ p["w1"] + p["b1"])
    ff = psum(inner @ p["w2"]) + p["b2"]
    return h + ff, cache


def tp_block_tapped(p: Dict[str, Any], h: jax.Array, ctx: StageCtx, zs,
                    *, dropout: float = 0.0,
                    causal: bool = True):
    """Split-backward form of :func:`tp_block_apply` (tp_axis=None math):
    identical forward values, plus

    * ``zs``: a zero pytree (:func:`tp_block_zs`) added at each
      param-consuming op's OUTPUT — vjp w.r.t. ``zs`` (with the params held
      CONSTANT) yields exactly the per-op output cotangents, so the B pass
      contains zero weight-grad matmuls by construction;
    * returns ``(out, taps)`` where ``taps`` are the per-op INPUTS —
      :func:`tp_block_wgrad` turns ``(taps, g_zs)`` into the parameter
      gradients as pure tap x cotangent contractions (the W pass).

    Numerics match ``tp_block_apply(..., tp_axis=None)`` bit-for-bit (the
    zero injections are exact no-ops forward) — deliberately a separate
    function rather than a flag on the shared sublayers so the plain path
    carries zero split machinery; the bit-exact forward equality is pinned
    by ``test_zb_split.py`` (``assert_array_equal``), which is the tripwire
    if the two copies ever drift.
    """
    rows, seq, d = h.shape
    key1 = key2 = None
    if ctx.key is not None:
        key1, key2 = jax.random.split(ctx.key)

    ln1_out, xhat1 = _layernorm_tapped(h, p["ln1"])
    hn = ln1_out + zs["ln1"]
    qkv = (jnp.einsum("bsd,dthk->btshk", hn, p["wqkv"]) + p["bqkv"][:, None]
           + zs["qkv"])
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
    hd = q.shape[-1]
    scores = jnp.einsum("bshk,bthk->bhst", q, k) / jnp.sqrt(
        jnp.asarray(hd, h.dtype))
    if causal:
        mask = jnp.tril(jnp.ones((seq, seq), bool))
        scores = jnp.where(mask, scores, jnp.asarray(-1e9, scores.dtype))
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(
        h.dtype)
    attn = jnp.einsum("bhst,bthk->bshk", probs, v)
    out = jnp.einsum("bshk,hkd->bsd", attn, p["wo"]) + p["bo"] + zs["out"]
    h = h + _dropout(out, dropout, key1)

    ln2_out, xhat2 = _layernorm_tapped(h, p["ln2"])
    hn2 = ln2_out + zs["ln2"]
    pre_act = hn2 @ p["w1"] + p["b1"] + zs["ff1"]
    act = jax.nn.gelu(pre_act)
    ff = act @ p["w2"] + p["b2"] + zs["ff2"]
    h_out = h + _dropout(ff, dropout, key2)
    taps = {"xhat1": xhat1, "hn": hn, "attn": attn, "xhat2": xhat2,
            "hn2": hn2, "act": act}
    return h_out, taps


def tp_block_zs(h: jax.Array, p: Dict[str, Any]):
    """Zero injection points for :func:`tp_block_tapped` (shapes from the
    activation and the param tree)."""
    rows, seq, d = h.shape
    _, three, H, hd = p["wqkv"].shape
    ff = p["w1"].shape[1]
    z = lambda *s: jnp.zeros(s, h.dtype)
    return {"ln1": z(rows, seq, d), "qkv": z(rows, three, seq, H, hd),
            "out": z(rows, seq, d), "ln2": z(rows, seq, d),
            "ff1": z(rows, seq, ff), "ff2": z(rows, seq, d)}


def tp_block_wgrad(taps: Dict[str, Any], gzs: Dict[str, Any]
                   ) -> Dict[str, Any]:
    """Parameter gradients from (taps, per-op output cotangents) — the W
    pass: nothing here but the weight-grad contractions themselves."""
    sum_b = lambda a: jnp.sum(a, axis=(0, 1))
    return {
        "ln1": {"scale": jnp.sum(taps["xhat1"] * gzs["ln1"], axis=(0, 1)),
                "bias": sum_b(gzs["ln1"])},
        "wqkv": jnp.einsum("bsd,btshk->dthk", taps["hn"], gzs["qkv"]),
        "bqkv": jnp.sum(gzs["qkv"], axis=(0, 2)),
        "wo": jnp.einsum("bshk,bsd->hkd", taps["attn"], gzs["out"]),
        "bo": sum_b(gzs["out"]),
        "ln2": {"scale": jnp.sum(taps["xhat2"] * gzs["ln2"], axis=(0, 1)),
                "bias": sum_b(gzs["ln2"])},
        "w1": jnp.einsum("bsd,bsf->df", taps["hn2"], gzs["ff1"]),
        "b1": sum_b(gzs["ff1"]),
        "w2": jnp.einsum("bsf,bsd->fd", taps["act"], gzs["ff2"]),
        "b2": sum_b(gzs["ff2"]),
    }


def tp_block_apply(p: Dict[str, Any], h: jax.Array, ctx: StageCtx,
                   *, dropout: float = 0.0, causal: bool = True,
                   tp_axis: Optional[str] = MODEL_AXIS) -> jax.Array:
    """Pre-LN transformer block on LOCAL parameter shards.

    ``h`` is replicated over the model axis (``[rows, seq, d]``); inside
    ``shard_map`` the sharded leaves arrive as their local slices, so the
    same code runs at tp=1 with ``tp_axis=None`` (no psum) on full params.
    """
    psum, enter = _ops_for(tp_axis)
    key1 = key2 = None
    if ctx.key is not None:
        key1, key2 = jax.random.split(ctx.key)

    h = tp_attention_sublayer(p, h, causal=causal, dropout=dropout,
                              key=key1, tp_axis=tp_axis)

    # --- FFN (column then row) ---
    hn2 = enter(_layernorm(h, p["ln2"]))
    inner = jax.nn.gelu(hn2 @ p["w1"] + p["b1"])     # [rows, seq, ff_local]
    ff = psum(inner @ p["w2"]) + p["b2"]
    return h + _dropout(ff, dropout, key2)
