"""Ring attention: exact attention over a sequence sharded across devices.

Long-context capability (SURVEY §5 "Long-context / sequence parallelism"):
absent from the reference (seq len is a plain dim, ``main.py:107``), but
first-class here. The TPU-idiomatic construction reuses the pipeline's own
transport primitive — ``jax.lax.ppermute`` over ICI — as a K/V ring:

* the sequence axis is sharded over a ``context`` mesh axis (each device
  holds ``seq/n`` query rows and one K/V block);
* ``n`` ring steps rotate the K/V block one hop per step while each device
  accumulates its queries' attention over the visiting block with the
  numerically-stable streaming-softmax (flash-attention) recurrence;
* XLA overlaps the collective-permute with the block einsums — the same
  latency hiding the pipeline relies on (SURVEY §2 native table);
* causal masking compares *global* positions derived from the block's origin
  device, so semantics match single-device causal attention exactly.

Communication: each step moves one K/V block (2·b·s_local·h·d elements) over
ICI; total traffic equals one all-gather of K/V but peak memory stays at one
block — that is the whole point vs. gathering the full sequence.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..utils.compat import axis_size

__all__ = ["ring_attention", "blockwise_attention_reference"]


def _block_attend(q, k, v, o, m, l, q_start, k_start, causal, scale):
    """One streaming-softmax accumulation step over a visiting K/V block.

    q: [b, sq, h, d]; k/v: [b, sk, h, d]; o: [b, sq, h, d] f32;
    m, l: [b, h, sq] f32 running max / normalizer.
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        qpos = q_start + jnp.arange(sq)[:, None]
        kpos = k_start + jnp.arange(sk)[None, :]
        logits = jnp.where(qpos >= kpos, logits,
                           jnp.asarray(-jnp.inf, logits.dtype))

    block_max = jnp.max(logits, axis=-1)                      # [b,h,q]
    new_m = jnp.maximum(m, block_max)
    # fully-masked blocks: new_m can be -inf; make the shift a no-op then
    safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    p = jnp.exp(logits - safe_m[..., None])
    p = jnp.where(jnp.isfinite(logits), p, 0.0)
    correction = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)

    l = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(jnp.float32)
    o = o * correction.transpose(0, 2, 1)[..., None] + pv
    return o, new_m, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, *, causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Exact multi-head attention with sequence sharded over ``axis_name``.

    Call inside ``shard_map``; ``q``/``k``/``v`` are the local shards
    ``[batch, seq_local, heads, head_dim]``. Returns the local output shard
    in ``q``'s dtype. Differentiable (AD reverses the ring automatically —
    the same property the pipeline's backward relies on, SURVEY §7).
    """
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    o0 = jnp.zeros(q.shape, jnp.float32)
    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    q_start = idx * sq

    if n == 1:
        o, m, l = _block_attend(q, k, v, o0, m0, l0, q_start, 0, causal,
                                scale)
        return (o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
                ).astype(q.dtype)

    shift = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, r):
        o, m, l, kb, vb = carry
        # after r hops along +1 ring, we hold the block born on device idx-r
        src = (idx - r) % n
        o, m, l = _block_attend(q, kb, vb, o, m, l, q_start,
                                src * kb.shape[1], causal, scale)
        kb = jax.lax.ppermute(kb, axis_name, shift)
        vb = jax.lax.ppermute(vb, axis_name, shift)
        return (o, m, l, kb, vb), None

    (o, m, l, _, _), _ = jax.lax.scan(step, (o0, m0, l0, k, v),
                                      jnp.arange(n))
    return (o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
            ).astype(q.dtype)


def blockwise_attention_reference(q, k, v, *, causal=True, scale=None):
    """Single-device oracle with identical semantics (tests compare to this)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
        logits = jnp.where(mask, logits, -jnp.inf)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w.astype(v.dtype), v)
