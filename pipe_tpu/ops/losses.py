"""Streaming (vocab-blocked) softmax cross-entropy — the fused head+loss.

The tutorial loss path materializes ``[tokens, vocab]`` f32 logits per
micro-batch (472 MB at the 520M bench shape) just to reduce them to one
scalar per row. This module computes the SAME cross-entropy without ever
holding more than one ``[tokens, block]`` logit tile: a ``lax.scan`` over
vocab blocks carries the online logsumexp (running max + rescaled sumexp —
the flash-attention recurrence applied to the vocab axis) and picks up the
target logit when its block streams past. Peak memory for the head drops
from O(tokens x vocab) to O(tokens x block), which is what makes large
vocabularies and long sequences trainable without shrinking micro-batches.

The backward recomputes each tile (softmax(tile) - onehot) from the saved
final logsumexp — one extra pass of head FLOPs, the standard remat trade —
so the residuals are O(tokens) scalars, not logits. ``custom_vjp`` keeps
the recurrence out of JAX AD (differentiating the scan would save every
tile, defeating the point).

Numerics: block-padded columns contribute exp(-inf) = 0 to the sumexp and
zero gradient; accumulation is f32 throughout; equality with the dense
``per_row_ce``(decoder(h)) path is pinned to ~1e-5 in ``tests/test_losses
.py`` for values AND all three gradients (h, W, b).

Reference baseline: the tutorial computes CrossEntropyLoss on full logits
on the last GPU (``main.py:214-216``); this is the TPU-idiomatic fusion of
that decode+loss pair.

Measured (v5e, 520M bench config, same session): streaming is ~9% SLOWER
than the dense path (140 vs 128 ms/step at block 4096/8192) — the
backward's recompute pass costs real FLOPs and at s=128 x V=28.8k the
dense logits fit comfortably, so there is nothing to win. It is a
CAPACITY knob, not a throughput knob: reach for ``LMConfig(loss_block=)``
when ``tokens x vocab`` logits do not fit (long sequences, 100k+
vocabularies), not to speed up the tutorial config. Numerics note: tiles
multiply bf16 x bf16 with f32 accumulation when ``h`` is bf16 (the dense
path upcasts to an f32 x f32 matmul), and block size changes the f32
summation order — one-step losses agree to ~1e-5, trajectories drift at
the usual float rate.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["streaming_xent"]


def _pad_blocks(w, b, block):
    d, V = w.shape
    nb = -(-V // block)
    pad = nb * block - V
    if pad:
        w = jnp.pad(w, ((0, 0), (0, pad)))
        b = jnp.pad(b, (0, pad), constant_values=-jnp.inf)
    # [nb, d, block] / [nb, block]
    return (jnp.moveaxis(w.reshape(d, nb, block), 1, 0),
            b.reshape(nb, block), nb, pad)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def streaming_xent(h, w, b, targets, block: int = 8192):
    """Per-token cross-entropy ``[*, s]`` of ``softmax(h @ w + b)`` vs
    ``targets``, streamed over vocab blocks (never materializing the full
    logits). ``h``: ``[*, s, d]`` (any float dtype; matmul accumulates
    f32); ``w``: ``[d, V]``; ``b``: ``[V]``; ``targets``: int ``[*, s]``.
    """
    ce, _ = _forward(h, w, b, targets, block)
    return ce


def _forward(h, w, b, targets, block):
    wb, bb, nb, _ = _pad_blocks(w, b, block)
    # the bf16-vs-f32 tile matmul choice falls out of h's dtype: the weight
    # tile is cast TO it below and f32 accumulation is forced either way
    hf = h
    tgt = targets.astype(jnp.int32)

    def tile_logits(k, w_blk, b_blk):
        # f32-accumulated tile: [*, s, block]
        return (jnp.einsum("...sd,db->...sb", hf, w_blk.astype(hf.dtype),
                           preferred_element_type=jnp.float32)
                + b_blk.astype(jnp.float32))

    def body(carry, xs):
        m, s, gold, k = carry
        w_blk, b_blk = xs
        z = tile_logits(k, w_blk, b_blk)
        m2 = jnp.maximum(m, z.max(axis=-1))
        s = s * jnp.exp(m - m2) + jnp.exp(z - m2[..., None]).sum(axis=-1)
        # target logit, if it lives in this block
        local = tgt - k * block
        in_blk = (local >= 0) & (local < block)
        picked = jnp.take_along_axis(
            z, jnp.clip(local, 0, block - 1)[..., None], axis=-1)[..., 0]
        gold = jnp.where(in_blk, picked, gold)
        return (m2, s, gold, k + 1), None

    m0 = jnp.full(tgt.shape, -jnp.inf, jnp.float32)
    s0 = jnp.zeros(tgt.shape, jnp.float32)
    g0 = jnp.zeros(tgt.shape, jnp.float32)
    (m, s, gold, _), _ = jax.lax.scan(body, (m0, s0, g0, 0), (wb, bb))
    lse = m + jnp.log(s)
    return lse - gold, (lse,)


def _fwd(h, w, b, targets, block):
    ce, (lse,) = _forward(h, w, b, targets, block)
    return ce, (h, w, b, targets.astype(jnp.int32), lse)


def _bwd(block, res, g):
    h, w, b, tgt, lse = res
    wb, bb, nb, pad = _pad_blocks(w, b, block)
    hf = h                       # see _forward: tile dtype follows h
    d, V = w.shape

    def body(carry, xs):
        dh, k = carry
        w_blk, b_blk = xs
        z = (jnp.einsum("...sd,db->...sb", hf, w_blk.astype(hf.dtype),
                        preferred_element_type=jnp.float32)
             + b_blk.astype(jnp.float32))
        p = jnp.exp(z - lse[..., None])          # softmax tile (padded
        #                                          cols: exp(-inf)=0)
        local = tgt - k * block
        in_blk = (local >= 0) & (local < block)
        onehot = (jax.nn.one_hot(jnp.clip(local, 0, block - 1), block,
                                 dtype=jnp.float32)
                  * in_blk[..., None].astype(jnp.float32))
        dz = (p - onehot) * g[..., None]         # [*, s, block]
        dh = dh + jnp.einsum("...sb,db->...sd", dz,
                             w_blk.astype(jnp.float32))
        dw_blk = jnp.einsum("...sd,...sb->db", h.astype(jnp.float32), dz)
        db_blk = dz.reshape(-1, dz.shape[-1]).sum(axis=0)
        return (dh, k + 1), (dw_blk, db_blk)

    dh0 = jnp.zeros(h.shape[:-1] + (d,), jnp.float32)
    (dh, _), (dw_t, db_t) = jax.lax.scan(body, (dh0, 0), (wb, bb))
    # [nb, d, block] -> [d, V] (drop padding)
    dw = jnp.moveaxis(dw_t, 0, 1).reshape(d, nb * block)[:, :V]
    db = db_t.reshape(nb * block)[:V]
    return dh.astype(h.dtype), dw.astype(w.dtype), db.astype(b.dtype), None


streaming_xent.defvjp(_fwd, _bwd)
