"""Deferred BatchNorm: micro-batching-safe batch normalization.

Capability parity with the reference ``batchnorm.py`` (imported at
``pipe.py:18,261-266,341-342``; quoted at ``README.md:549-554``): splitting a
mini-batch into ``chunks`` micro-batches would update BN running statistics
``chunks`` times with momentum each time — different numbers than the
unpipelined model. ``DeferredBatchNorm`` accumulates per-micro-batch partial
sums across the whole mini-batch and commits ONE running-stats update per
mini-batch, restoring the unpipelined semantics.

TPU-native re-design: torch mutates module buffers in place; here layers are
pure, so per-microbatch ``(sum, sum_sq, count)`` ride the tracker's
accumulator channel (crossing remat boundaries as explicit outputs — see
``emulator._compute_one``), and ``Pipe`` returns the committed stats as a new
params tree (``pipe(params, x, train=True)`` → ``(out, new_params)`` when
``deferred_batch_norm=True``).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

from ..core.partition import StageCtx
from ..ops.layers import Module, Sequential
from .skip.namespace import Namespace
from .skip.tracker import accumulate

__all__ = ["BatchNorm", "DeferredBatchNorm", "convert_deferred_batch_norm",
           "commit_batchnorm_stats"]

_STATS = "deferred_stats"


class BatchNorm(Module):
    """Plain batch norm over all axes but the last (feature) axis.

    Train mode normalizes by the micro-batch's own statistics — exactly the
    behavior that makes naive micro-batching unsafe and motivates the
    deferred variant (reference ``pipe.py:261-266``). Running stats live in
    the params tree (``mean``/``var``/``count``); eval mode uses them.
    """

    def __init__(self, momentum: float = 0.1, eps: float = 1e-5,
                 dtype=jnp.float32, name: str = "bn"):
        self.momentum = momentum
        self.eps = eps
        self.dtype = dtype
        self.name = name

    def init(self, key, x):
        d = jnp.shape(x)[-1]
        return {
            "scale": jnp.ones((d,), self.dtype),
            "bias": jnp.zeros((d,), self.dtype),
            "mean": jnp.zeros((d,), self.dtype),
            "var": jnp.ones((d,), self.dtype),
        }

    def _normalize(self, params, x, mean, var):
        y = (x - mean) * jax.lax.rsqrt(var + self.eps)
        return y * params["scale"] + params["bias"]

    def _batch_stats(self, x, ctx: StageCtx):
        """Micro-batch (mean, var) — psum'd over a bound data axis so a
        data-sharded micro-batch normalizes by the same whole-micro-batch
        statistics as the unsharded run (mesh factorization must not change
        the math; torch has no DP composition here to mirror, reference
        ``pipe.py:290-293``)."""
        axes = tuple(range(x.ndim - 1))
        if ctx.data_axis is None:
            return jnp.mean(x, axis=axes), jnp.var(x, axis=axes)
        n = 1
        for a in axes:
            n *= x.shape[a]
        n_tot = n * jax.lax.psum(1, ctx.data_axis)
        mean = jax.lax.psum(jnp.sum(x, axis=axes), ctx.data_axis) / n_tot
        # centered two-pass variance (one extra psum) — same numerical
        # stability as jnp.var, so size-1 data axes are bit-comparable to
        # the unsharded path within float tolerance
        var = jax.lax.psum(jnp.sum(jnp.square(x - mean), axis=axes),
                           ctx.data_axis) / n_tot
        return mean, var

    def apply(self, params, x, ctx: StageCtx = StageCtx()):
        if not ctx.train:
            return self._normalize(params, x, params["mean"], params["var"])
        mean, var = self._batch_stats(x, ctx)
        return self._normalize(params, x, mean, var)


class DeferredBatchNorm(BatchNorm):
    """BatchNorm whose running-stat update is deferred to once per mini-batch.

    Each train-mode application normalizes by its micro-batch statistics
    (same activations as the unpipelined model's train forward on that slice
    of data is *not* the goal — parity is with whole-batch BN running stats)
    and accumulates ``(sum, sum_sq, count)``; :func:`commit_batchnorm_stats`
    folds the accumulated whole-mini-batch statistics into ``mean``/``var``
    with one momentum step, matching torch's unbiased-variance update.
    """

    def __init__(self, momentum: float = 0.1, eps: float = 1e-5,
                 dtype=jnp.float32, name: str = "deferred_bn"):
        super().__init__(momentum, eps, dtype, name)
        self.ns = Namespace()  # instance identity for the accumulator channel

    def apply(self, params, x, ctx: StageCtx = StageCtx()):
        if not ctx.train:
            return self._normalize(params, x, params["mean"], params["var"])
        axes = tuple(range(x.ndim - 1))
        n = 1
        for a in axes:
            n *= x.shape[a]
        # Accumulate SHARD-LOCAL partial sums: the executor's host-side
        # cross-shard reduction owns the data-axis sum for the running
        # stats (a second psum here would double-count by n_data).
        accumulate(self.ns, _STATS, {
            "sum": jnp.sum(x, axis=axes),
            "sum_sq": jnp.sum(jnp.square(x), axis=axes),
            "count": jnp.asarray(n, jnp.float32),
        })
        # Normalize by whole-micro-batch statistics (psum'd if sharded).
        mean, var = self._batch_stats(x, ctx)
        return self._normalize(params, x, mean, var)

    def commit(self, params, stats) -> Any:
        """One momentum update from accumulated whole-mini-batch stats."""
        n = stats["count"]
        mean = stats["sum"] / n
        var = stats["sum_sq"] / n - jnp.square(mean)
        unbiased = var * n / jnp.maximum(n - 1.0, 1.0)
        m = self.momentum
        new = dict(params)
        new["mean"] = (1 - m) * params["mean"] + m * mean.astype(self.dtype)
        new["var"] = (1 - m) * params["var"] + m * unbiased.astype(self.dtype)
        return new


def convert_deferred_batch_norm(module: Sequential, chunks: int) -> Sequential:
    """Replace every BatchNorm with a DeferredBatchNorm (reference
    ``DeferredBatchNorm.convert_deferred_batch_norm``, ``pipe.py:341-342``).

    ``chunks`` exists for signature parity with the reference converter; the
    tracker-based accumulator needs no per-chunk state.
    """
    del chunks
    layers = []
    for layer in module:
        if isinstance(layer, BatchNorm) and not isinstance(layer,
                                                           DeferredBatchNorm):
            d = DeferredBatchNorm(layer.momentum, layer.eps, layer.dtype,
                                  name=layer.name)
            layers.append(d)
        else:
            layers.append(layer)
    return Sequential(layers, name=module.name)


def commit_batchnorm_stats(partitions: Sequence[Sequential],
                           params: Sequence[Any], tracker) -> Any:
    """New per-stage params with every DeferredBatchNorm's stats committed.

    ``tracker.accum`` holds the (ns, "deferred_stats") sums collected while
    the schedule ran; layers without accumulated stats keep their params.
    """
    new_params = [list(p) for p in params]
    for j, part in enumerate(partitions):
        for i, layer in enumerate(part):
            if isinstance(layer, DeferredBatchNorm):
                stats = tracker.accum.get((layer.ns, _STATS))
                if stats is not None:
                    new_params[j][i] = layer.commit(params[j][i], stats)
    return new_params
