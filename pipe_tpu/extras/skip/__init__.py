"""Skip-connection subsystem: tensors that jump over pipeline stages.

Capability parity with the reference ``skip/`` package (imported at
``pipe.py:20-21`` and ``pipeline.py:20-21``; SURVEY §2: ``skippable.py``,
``portal.py``, ``tracker.py``, ``layout.py``, ``namespace.py``): a layer deep
in one stage can ``stash`` a tensor and a layer in a *later* stage can ``pop``
it, outside the stage-to-stage dataflow.

TPU-native re-design: the reference routes stashed tensors through "portals"
(phantom autograd nodes riding dedicated copy streams,
``pipeline.py:136-138``). Here a stash is simply a named value recorded by a
:class:`SkipTracker` while the (unrolled, traced) schedule runs — the value's
journey across devices is whatever XLA compiles for the resulting dataflow,
and its gradient path falls out of AD. The static stash/pop wiring is captured
by :func:`inspect_skip_layout`, and :func:`verify_skippables` gives the same
fail-fast init check as the reference (``pipe.py:336``).
"""

from .namespace import Namespace
from .skippable import Skippable, pop, skippable, stash, verify_skippables
from .layout import SkipLayout, inspect_skip_layout
from .tracker import SkipTracker, current_skip_tracker, use_skip_tracker

__all__ = [
    "Namespace",
    "Skippable",
    "skippable",
    "stash",
    "pop",
    "verify_skippables",
    "SkipLayout",
    "inspect_skip_layout",
    "SkipTracker",
    "current_skip_tracker",
    "use_skip_tracker",
]
