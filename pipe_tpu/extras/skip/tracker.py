"""Skip tracker: the runtime store that carries stashed values to their pops.

Parity with the reference ``skip/tracker.py`` (``SkipTracker``,
``SkipTrackerThroughPotals``, ``use_skip_tracker`` — used by the scheduler at
``pipeline.py:21,113,136-138,201,208``). The reference needs one tracker per
micro-batch plus portal objects so stashed tensors ride copy streams between
non-adjacent devices; here the executors run under trace (emulator) where a
plain keyed store suffices — XLA sees the stash→pop dataflow and compiles the
transfer and its gradient. Values are keyed per micro-batch so the m
concurrent wavefront lanes never mix (the reference allocates m trackers for
the same reason, ``pipeline.py:113``).
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Any, Dict, Optional, Tuple

from .layout import SkipLayout

__all__ = ["SkipTracker", "current_skip_tracker", "use_skip_tracker"]

_current: contextvars.ContextVar[Optional["_Scope"]] = contextvars.ContextVar(
    "pipe_tpu_skip_scope", default=None)


class _Scope:
    __slots__ = ("tracker", "microbatch", "stage")

    def __init__(self, tracker: "SkipTracker", microbatch: int, stage: int):
        self.tracker = tracker
        self.microbatch = microbatch
        self.stage = stage


class SkipTracker:
    """Stores stashed values per (microbatch, namespace, name).

    A pop consumes its value (portal lifetime semantics: the reference's
    portal drops its tensor once the destination copy happened).
    """

    def __init__(self, layout: Optional[SkipLayout] = None,
                 spec_mode: bool = False):
        self.layout = layout
        # spec_mode serves shape inference (init/out_spec chains): stashes
        # store only ShapeDtypeStructs (tracers cannot cross eval_shape
        # boundaries), pops return zeros of the stored spec and do not
        # consume, and repeated stashes overwrite (out_spec may re-trace).
        self.spec_mode = spec_mode
        self._store: Dict[Tuple[int, Any, str], Any] = {}
        # Cross-microbatch stat accumulators (deferred BatchNorm channel):
        # keyed (ns, name) only — values merge additively across tasks.
        self.accum: Dict[Tuple[Any, str], Any] = {}

    # -- used by executors ------------------------------------------------
    @contextlib.contextmanager
    def scope(self, microbatch: int, stage: int):
        """Activate this tracker for one (microbatch, stage) task."""
        token = _current.set(_Scope(self, microbatch, stage))
        try:
            yield self
        finally:
            _current.reset(token)

    # -- used by skippable modules ---------------------------------------
    def save(self, microbatch: int, ns, name: str, value: Any) -> None:
        key = (microbatch, ns, name)
        if self.spec_mode:
            import jax
            import jax.numpy as jnp
            self._store[key] = jax.ShapeDtypeStruct(
                jnp.shape(value), jnp.result_type(value))
            return
        if key in self._store:
            raise RuntimeError(
                f"skip {(ns, name)!r} stashed twice for microbatch {microbatch}")
        self._store[key] = value

    def load(self, microbatch: int, ns, name: str) -> Any:
        key = (microbatch, ns, name)
        if key not in self._store:
            raise RuntimeError(
                f"skip {(ns, name)!r} popped before stash "
                f"(microbatch {microbatch})")
        if self.spec_mode:
            import jax.numpy as jnp
            spec = self._store[key]  # non-consuming: re-traces re-pop
            return jnp.zeros(spec.shape, spec.dtype)
        return self._store.pop(key)

    def accumulate(self, ns, name: str, value: Any) -> None:
        """Add ``value`` (a pytree) into the (ns, name) accumulator.

        Used by stat-bearing layers (DeferredBatchNorm): per-microbatch
        partial sums accumulate across the whole mini-batch and are read once
        after the schedule drains (reference ``batchnorm.py`` capability,
        ``README.md:549-554``). Gradients are not tracked through stats.

        In ``spec_mode`` the accumulator records ShapeDtypeStructs instead
        (overwriting, not adding) — the compiled executor uses this to size
        the per-stage stat lanes before tracing.
        """
        import jax
        key = (ns, name)
        if self.spec_mode:
            import jax.numpy as jnp
            self.accum[key] = jax.tree_util.tree_map(
                lambda v: jax.ShapeDtypeStruct(jnp.shape(v),
                                               jnp.result_type(v)), value)
            return
        value = jax.tree_util.tree_map(jax.lax.stop_gradient, value)
        if key in self.accum:
            self.accum[key] = jax.tree_util.tree_map(
                lambda a, b: a + b, self.accum[key], value)
        else:
            self.accum[key] = value

    def __len__(self) -> int:
        return len(self._store)


def accumulate(ns, name: str, value: Any) -> bool:
    """Accumulate into the active tracker; False (no-op) outside a run.

    Spec-mode trackers record shapes (see :meth:`SkipTracker.accumulate`) so
    executors can size stat lanes; they still return True."""
    scope = _current.get()
    if scope is None:
        return False
    scope.tracker.accumulate(ns, name, value)
    return True


def current_skip_tracker() -> _Scope:
    """The active (tracker, microbatch, stage) scope, or raise."""
    scope = _current.get()
    if scope is None:
        raise RuntimeError(
            "stash/pop used outside a pipeline run (no active skip tracker); "
            "skippable modules only work under Pipe/emulator execution")
    return scope


@contextlib.contextmanager
def use_skip_tracker(tracker: SkipTracker, microbatch: int = 0, stage: int = 0):
    """Public form of :meth:`SkipTracker.scope` (reference ``use_skip_tracker``)."""
    with tracker.scope(microbatch, stage):
        yield tracker
