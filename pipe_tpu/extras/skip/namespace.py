"""Skip namespaces: isolate same-named skips from different module instances.

Parity with the reference ``skip/namespace.py`` (SURVEY §2 skip row): a
``Namespace`` is an opaque unique token; ``(namespace, name)`` pairs key every
stash/pop, so two instances of the same skippable module can coexist in one
pipeline via ``module.isolate(ns)``.
"""

from __future__ import annotations

import itertools

__all__ = ["Namespace"]

_counter = itertools.count()


class Namespace:
    """An opaque, hashable, totally-ordered identity token."""

    __slots__ = ("_id",)

    def __init__(self):
        self._id = next(_counter)

    def __repr__(self) -> str:
        return f"<Namespace {self._id}>"

    def __eq__(self, other) -> bool:
        return isinstance(other, Namespace) and self._id == other._id

    def __lt__(self, other) -> bool:
        if not isinstance(other, Namespace):
            return NotImplemented
        return self._id < other._id

    def __hash__(self) -> int:
        return hash(("pipe_tpu.skip.Namespace", self._id))
