"""Static skip layout: which (source stage → destination stage) carries exist.

Parity with the reference ``skip/layout.py`` (``SkipLayout``,
``inspect_skip_layout`` — called at ``pipe.py:348``, consumed by the scheduler
fence at ``pipeline.py:136-138``). The reference uses the layout to issue
portal copies on the right copy streams; here it is pure metadata — executors
and the (future) compiled skip-carry path use it to know how many extra ring
slots a skip occupies, and tests use it to assert wiring.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Tuple

__all__ = ["SkipLayout", "inspect_skip_layout"]


@dataclasses.dataclass(frozen=True)
class SkipLayout:
    """Stash/pop wiring resolved to stage indices.

    ``by_src_dst`` maps ``(src_stage, dst_stage) -> [(ns, name), ...]``.
    """

    n_stages: int
    by_src_dst: Tuple[Tuple[Tuple[int, int], Tuple[Tuple[object, str], ...]], ...]

    def requires_copy(self, src: int, dst: int) -> bool:
        return any(k == (src, dst) for k, _ in self.by_src_dst)

    def copy_policy(self, dst: int) -> Iterator[Tuple[int, object, str]]:
        """(src_stage, ns, name) for every skip arriving at stage ``dst``
        (reference ``SkipLayout.copy_policy(j)``)."""
        for (src, d), names in self.by_src_dst:
            if d == dst:
                for ns, name in names:
                    yield src, ns, name

    @property
    def num_skips(self) -> int:
        return sum(len(names) for _, names in self.by_src_dst)

    def max_hop(self) -> int:
        """Longest stage distance a skip travels (ring-slot requirement)."""
        return max((d - s for (s, d), _ in self.by_src_dst), default=0)

    def stashes_of(self, stage: int) -> Tuple[Tuple[object, str], ...]:
        """Skips produced at ``stage`` that leave it (cross-stage sources).

        Executors use this to export stash values across remat boundaries —
        same-stage stash→pop pairs stay inside the stage body.
        """
        out: List[Tuple[object, str]] = []
        for (src, dst), names in self.by_src_dst:
            if src == stage and dst != stage:
                out.extend(names)
        return tuple(out)

    def pops_of(self, stage: int) -> Tuple[Tuple[object, str], ...]:
        """Skips consumed at ``stage`` that arrive from an earlier stage."""
        out: List[Tuple[object, str]] = []
        for (src, dst), names in self.by_src_dst:
            if dst == stage and src != stage:
                out.extend(names)
        return tuple(out)


def inspect_skip_layout(partitions) -> SkipLayout:
    """Compute the stash→pop stage wiring from partitioned stages.

    ``partitions`` is a sequence of ``Sequential``s (one per stage) whose
    layers may be :class:`~pipe_tpu.extras.skip.skippable.Skippable`.
    Mirrors reference ``inspect_skip_layout`` (``pipe.py:348``).
    """
    stashed_at: Dict[Tuple[object, str], int] = {}
    pairs: Dict[Tuple[int, int], List[Tuple[object, str]]] = {}

    for j, partition in enumerate(partitions):
        for layer in partition:
            stashes = getattr(layer, "stashes", ())
            pops = getattr(layer, "pops", ())
            for key in stashes:
                stashed_at[key] = j
            for key in pops:
                if key in stashed_at:
                    src = stashed_at[key]
                    pairs.setdefault((src, j), []).append(key)

    frozen = tuple(sorted(
        ((sd, tuple(names)) for sd, names in pairs.items()),
        key=lambda kv: kv[0]))
    return SkipLayout(n_stages=len(list(partitions)), by_src_dst=frozen)
