"""``@skippable`` modules and the ``stash``/``pop`` verbs.

Parity with the reference ``skip/skippable.py`` (``@skippable(stash=[...],
pop=[...])``, ``stash``, ``pop``, ``verify_skippables`` — imported at
``pipe.py:20-21``). The reference's generator protocol (``yield stash(...)``)
exists to thread values through an imperative nn.Module ``forward``; here
modules are pure functions running under an active :class:`SkipTracker`
scope, so ``stash``/``pop`` are direct calls:

    @skippable(stash=["1to3"])
    class Stash13(Module):
        def apply(self, params, x, ctx=StageCtx()):
            stash("1to3", x)
            return x

    @skippable(pop=["1to3"])
    class Pop13(Module):
        def apply(self, params, x, ctx=StageCtx()):
            return x + pop("1to3")

Bare ``stash("name", v)`` / ``pop("name")`` resolve through the *calling
module instance* (the decorator binds it around ``apply``), so namespace
isolation works without threading namespaces by hand: two instances of the
same skippable class are isolated with ``module.isolate(Namespace())``, and
``isolate(ns, only=[...])`` moves only the listed names into ``ns``, leaving
the rest in their current namespace (reference ``Skippable.isolate``
semantics).
"""

from __future__ import annotations

import contextvars
import copy
from typing import Dict, Iterable, Optional, Sequence, Set, Tuple

from ...ops.layers import Module, Sequential
from .namespace import Namespace
from .tracker import current_skip_tracker

__all__ = ["Skippable", "skippable", "stash", "pop", "verify_skippables"]

_GLOBAL_NS = Namespace()  # default namespace for un-isolated skips

# The skippable instance whose apply() is currently executing — lets bare
# stash()/pop() resolve names through that instance's namespace map.
_active: contextvars.ContextVar[Optional["Skippable"]] = \
    contextvars.ContextVar("pipe_tpu_active_skippable", default=None)


class Skippable:
    """Mixin marking a Module as stashing/popping named skips.

    Applied by :func:`skippable`; carries ``stashes``/``pops`` as sets of
    ``(namespace, name)`` resolved through the instance's per-name namespace
    map (``isolate`` rewrites entries of that map).
    """

    _stash_names: Tuple[str, ...] = ()
    _pop_names: Tuple[str, ...] = ()

    @property
    def namespace_map(self) -> Dict[str, Namespace]:
        return getattr(self, "_skip_ns_map", {})

    def ns_of(self, name: str) -> Namespace:
        return self.namespace_map.get(name, _GLOBAL_NS)

    def isolate(self, ns: Namespace, *, only: Optional[Iterable[str]] = None):
        """Copy with the given (or all) skip names moved into ``ns``;
        unselected names keep their current namespace."""
        clone = copy.copy(self)
        mapping = dict(self.namespace_map)
        names = tuple(only) if only is not None else (
            self._stash_names + self._pop_names)
        for n in names:
            mapping[n] = ns
        clone._skip_ns_map = mapping
        return clone

    @property
    def stashes(self) -> Set[Tuple[object, str]]:
        return {(self.ns_of(n), n) for n in self._stash_names}

    @property
    def pops(self) -> Set[Tuple[object, str]]:
        return {(self.ns_of(n), n) for n in self._pop_names}


def skippable(stash: Sequence[str] = (), pop: Sequence[str] = ()):
    """Class decorator declaring which skip names a Module stashes/pops."""
    stash_names = tuple(stash)
    pop_names = tuple(pop)

    def decorate(cls):
        if not issubclass(cls, Module):
            raise TypeError("@skippable expects a Module subclass")

        inner_apply = cls.apply

        def apply(self, params, *inputs, **kwargs):
            token = _active.set(self)
            try:
                return inner_apply(self, params, *inputs, **kwargs)
            finally:
                _active.reset(token)

        return type(cls.__name__, (Skippable, cls), {
            "_stash_names": stash_names,
            "_pop_names": pop_names,
            "apply": apply,
        })

    return decorate


def _resolve_ns(name: str, ns: Optional[Namespace]) -> Namespace:
    if ns is not None:
        return ns
    inst = _active.get()
    if inst is not None:
        return inst.ns_of(name)
    return _GLOBAL_NS


def stash(name: str, value, ns: Optional[Namespace] = None) -> None:
    """Record ``value`` under ``name`` for a later stage's :func:`pop`."""
    scope = current_skip_tracker()
    scope.tracker.save(scope.microbatch, _resolve_ns(name, ns), name, value)


def pop(name: str, ns: Optional[Namespace] = None):
    """Retrieve (and consume) the value stashed under ``name``."""
    scope = current_skip_tracker()
    return scope.tracker.load(scope.microbatch, _resolve_ns(name, ns), name)


def verify_skippables(module: Sequential) -> None:
    """Fail-fast static check of stash/pop pairing (reference ``pipe.py:336``).

    Every pop must have exactly one earlier stash of the same ``(ns, name)``;
    a name must not be stashed twice; every stash must be popped (unpopped
    stashes leak memory in a pipeline, so they are rejected like the
    reference's verify).
    """
    stashed: Set[Tuple[object, str]] = set()
    popped: Set[Tuple[object, str]] = set()
    msgs = []

    for i, layer in enumerate(module):
        for key in sorted(getattr(layer, "stashes", ()),
                          key=lambda k: (id(k[0]), k[1])):
            if key in stashed:
                msgs.append(f"layer {i}: '{key[1]}' is stashed twice")
            stashed.add(key)
        for key in sorted(getattr(layer, "pops", ()),
                          key=lambda k: (id(k[0]), k[1])):
            if key not in stashed:
                msgs.append(
                    f"layer {i}: '{key[1]}' is popped before it is stashed")
            elif key in popped:
                msgs.append(f"layer {i}: '{key[1]}' is popped twice")
            popped.add(key)

    for key in stashed - popped:
        msgs.append(f"'{key[1]}' is stashed but never popped")

    if msgs:
        raise TypeError("skip connections are miswired:\n  " +
                        "\n  ".join(msgs))
