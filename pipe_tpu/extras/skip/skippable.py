"""``@skippable`` modules and the ``stash``/``pop`` verbs.

Parity with the reference ``skip/skippable.py`` (``@skippable(stash=[...],
pop=[...])``, ``stash``, ``pop``, ``verify_skippables`` — imported at
``pipe.py:20-21``). The reference's generator protocol (``yield stash(...)``)
exists to thread values through an imperative nn.Module ``forward``; here
modules are pure functions running under an active :class:`SkipTracker`
scope, so ``stash``/``pop`` are direct calls:

    @skippable(stash=["1to3"])
    class Stash13(Module):
        def apply(self, params, x, ctx=StageCtx()):
            stash("1to3", x)
            return x

    @skippable(pop=["1to3"])
    class Pop13(Module):
        def apply(self, params, x, ctx=StageCtx()):
            return x + pop("1to3")

Two instances of the same skippable class are isolated with
``module.isolate(Namespace())`` (reference ``Skippable.isolate``).
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence, Set, Tuple

from ...ops.layers import Module, Sequential
from .namespace import Namespace
from .tracker import current_skip_tracker

__all__ = ["Skippable", "skippable", "stash", "pop", "verify_skippables"]

_GLOBAL_NS = Namespace()  # default namespace for un-isolated skippables


class Skippable:
    """Mixin marking a Module as stashing/popping named skips.

    Applied by :func:`skippable`; carries ``stashes``/``pops`` as sets of
    ``(namespace, name)`` resolved through the instance's namespace.
    """

    _stash_names: Tuple[str, ...] = ()
    _pop_names: Tuple[str, ...] = ()

    @property
    def namespace(self):
        return getattr(self, "_skip_ns", _GLOBAL_NS)

    def isolate(self, ns: Namespace, *, only: Optional[Iterable[str]] = None):
        """Return a copy whose skips live in ``ns`` (reference ``isolate``)."""
        import copy

        clone = copy.copy(self)
        clone._skip_ns = ns
        if only is not None:
            keep = set(only)
            clone._stash_names = tuple(n for n in self._stash_names if n in keep)
            clone._pop_names = tuple(n for n in self._pop_names if n in keep)
        return clone

    @property
    def stashes(self) -> Set[Tuple[object, str]]:
        return {(self.namespace, n) for n in self._stash_names}

    @property
    def pops(self) -> Set[Tuple[object, str]]:
        return {(self.namespace, n) for n in self._pop_names}


def skippable(stash: Sequence[str] = (), pop: Sequence[str] = ()):
    """Class decorator declaring which skip names a Module stashes/pops."""
    stash_names = tuple(stash)
    pop_names = tuple(pop)

    def decorate(cls):
        if not issubclass(cls, Module):
            raise TypeError("@skippable expects a Module subclass")
        return type(cls.__name__, (Skippable, cls), {
            "_stash_names": stash_names,
            "_pop_names": pop_names,
        })

    return decorate


def stash(name: str, value, ns: Optional[Namespace] = None) -> None:
    """Record ``value`` under ``name`` for a later stage's :func:`pop`."""
    scope = current_skip_tracker()
    scope.tracker.save(scope.microbatch, ns or _GLOBAL_NS, name, value)


def pop(name: str, ns: Optional[Namespace] = None):
    """Retrieve (and consume) the value stashed under ``name``."""
    scope = current_skip_tracker()
    return scope.tracker.load(scope.microbatch, ns or _GLOBAL_NS, name)


def verify_skippables(module: Sequential) -> None:
    """Fail-fast static check of stash/pop pairing (reference ``pipe.py:336``).

    Every pop must have exactly one earlier stash of the same ``(ns, name)``;
    a name must not be stashed twice; every stash must be popped (unpopped
    stashes leak memory in a pipeline, so they are rejected like the
    reference's verify).
    """
    stashed: Set[Tuple[object, str]] = set()
    popped: Set[Tuple[object, str]] = set()
    msgs = []

    for i, layer in enumerate(module):
        for key in sorted(getattr(layer, "stashes", ()),
                          key=lambda k: (id(k[0]), k[1])):
            if key in stashed:
                msgs.append(f"layer {i}: '{key[1]}' is stashed twice")
            stashed.add(key)
        for key in sorted(getattr(layer, "pops", ()),
                          key=lambda k: (id(k[0]), k[1])):
            if key not in stashed:
                msgs.append(
                    f"layer {i}: '{key[1]}' is popped before it is stashed")
            elif key in popped:
                msgs.append(f"layer {i}: '{key[1]}' is popped twice")
            popped.add(key)

    for key in stashed - popped:
        msgs.append(f"'{key[1]}' is stashed but never popped")

    if msgs:
        raise TypeError("skip connections are miswired:\n  " +
                        "\n  ".join(msgs))
