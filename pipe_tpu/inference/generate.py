"""Autoregressive generation with KV caches over the pipelined LM families.

The reference package is training-only — its tutorial never samples from
the model it trains (``/root/reference/main.py`` has no generate loop). A
complete framework needs the inference surface too, so this module supplies
it the TPU way: one jitted program per (prompt_len, max_new_tokens) shape —
prefill fills every layer's KV cache in a single batched pass (MXU-sized
matmuls), then a ``lax.scan`` emits one token per step with O(1) work per
layer (the cache turns attention from O(t^2) re-forward into O(t) reads).
Static shapes throughout: the cache is allocated at ``prompt + max_new``
up front, masking handles the live prefix — no dynamic shapes, so XLA
compiles one fast program instead of recompiling per step.

Sampling: greedy (``temperature=0``), temperature softmax, optional top-k
truncation — all inside the scan, driven by an explicit PRNG key chain
(same key => same sample, the package-wide reproducibility contract).

Layer math lives with the layers (``MultiHeadAttention.decode``,
``TransformerEncoderLayer.decode``, ``PreLNBlock.decode`` in
``ops/layers.py``) so cached decode and training forward can never drift
apart; ``tests/test_generate.py`` pins teacher-forced cached logits against
the full training forward and greedy cached generation against a naive
re-forward loop.
"""

from __future__ import annotations

import dataclasses
import time
import warnings
from typing import Optional

import jax
import jax.numpy as jnp

from ..obs.telemetry import get_registry
from .quant import QuantLeaf, dequant_tree

__all__ = ["GenerationConfig", "Generator", "check_positions",
           "head_logits", "sample_logits", "sequence_lengths"]


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    max_new_tokens: int = 32
    temperature: float = 1.0   # 0 = greedy (argmax)
    top_k: Optional[int] = None  # None = full distribution
    # >1: beam search (deterministic, sum-of-log-probs scoring; the
    # temperature/top_k sampling knobs are ignored). KV caches are
    # physically reordered by parent beam each step.
    num_beams: int = 1
    # Stop token: once a sequence samples it, every later step emits
    # pad_token_id instead (static shapes — the scan still runs the full
    # max_new_tokens; finished rows just decode pad). None = no early
    # stop, every sequence runs to max_new_tokens, the pre-EOS behavior.
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    # Serve-side KV memory knobs (ignored by the one-shot generators,
    # which size a private cache per call). kv_block_size=None keeps the
    # monolithic per-slot slab; a power-of-two value switches the slot
    # backends to the paged pool (serve/kvpool.py). prefix_cache gates
    # shared-prefix block reuse inside the pool — pure host-side
    # allocator policy, so disabling it lowers to byte-identical device
    # programs (the absence-is-zero-cost pin, tests/test_kvpool.py).
    kv_block_size: Optional[int] = None
    prefix_cache: bool = True
    # Serve-side speculative decode lane (resident loop only): propose
    # spec_tokens - 1 draft tokens per round from an n-gram match over
    # the slot's own emitted history and verify the whole proposal in
    # ONE fixed-shape width-K pass — accepted tokens are bitwise the
    # sequential chain's (teacher-forced verify + the same split-sample
    # key walk), rejected tails cost nothing (their KV rows sit past the
    # slot position and are overwritten before any unmasked read). None
    # disables the lane; the one-shot generators ignore it.
    spec_tokens: Optional[int] = None

    def __post_init__(self):
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}")
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if self.num_beams < 1:
            raise ValueError(f"num_beams must be >= 1, got {self.num_beams}")
        if self.eos_token_id is not None and self.eos_token_id < 0:
            raise ValueError(
                f"eos_token_id must be >= 0, got {self.eos_token_id}")
        if self.pad_token_id < 0:
            raise ValueError(
                f"pad_token_id must be >= 0, got {self.pad_token_id}")
        if self.kv_block_size is not None and (
                self.kv_block_size < 1
                or (self.kv_block_size & (self.kv_block_size - 1)) != 0):
            raise ValueError(
                f"kv_block_size must be a positive power of two (block "
                f"indexing is a shift+mask in the decode step), got "
                f"{self.kv_block_size}")
        if self.num_beams > 1 and self.eos_token_id is not None:
            raise ValueError(
                "eos_token_id with beam search is not implemented — "
                "EOS-aware beam pruning needs per-hypothesis length "
                "normalization; use num_beams=1 for early stopping")
        if self.spec_tokens is not None and self.spec_tokens < 2:
            raise ValueError(
                f"spec_tokens must be >= 2 (one draft token plus its "
                f"correction), got {self.spec_tokens}")
        if self.spec_tokens is not None and self.num_beams > 1:
            raise ValueError(
                "spec_tokens is a slot-decode lane; beam search has no "
                "speculative form (num_beams must be 1)")

    def check_kv_headroom(self, bucket_max_len: int,
                          block_size: Optional[int] = None,
                          spec_overshoot: int = 0) -> None:
        """Paged serving with length buckets: reject a block size that
        does not divide the per-slot KV span ``bucket_max_len +
        max_new_tokens (+ speculative headroom)`` cleanly — the last
        block would round up and silently waste its tail rows on EVERY
        slot. With ``spec_tokens=K`` the verify chunk writes past the
        last emitted row, so the slot really holds ``max_new_tokens +
        spec_overshoot`` generated rows (the same headroom
        ``validate()`` charges) — the stranded-row check must use the
        spec-padded span, not the nominal one. Called by the slot
        backends at construction (the span is only known once buckets
        are chosen, so the check cannot live in ``__post_init__``)."""
        bs = block_size if block_size is not None else self.kv_block_size
        if bs is None:
            return
        span = int(bucket_max_len) + self.max_new_tokens + spec_overshoot
        waste = -span % bs
        if waste:
            spec = (f" + speculative headroom {spec_overshoot}"
                    if spec_overshoot else "")
            raise ValueError(
                f"kv_block_size={bs} does not divide the KV headroom "
                f"bucket_max_len + max_new_tokens{spec} = "
                f"{bucket_max_len} + {self.max_new_tokens}"
                f"{' + ' + str(spec_overshoot) if spec_overshoot else ''}"
                f" = {span}: every slot's last "
                f"block would waste {waste} of {bs} rows "
                f"({waste / bs:.0%} of a block) as unwritable padding; "
                f"pick a block size dividing {span} or adjust "
                f"max_new_tokens by {waste}")

    def check_decode_headroom(self, prefix_len: int, max_new_tokens: int,
                              bucket_max_len: int,
                              spec_overshoot: int = 0) -> None:
        """Decode-only serving (fleet/disagg.py): a decode replica
        never prefills from scratch — its slot span was sized for
        ``bucket_max_len + max_new_tokens (+ speculative headroom)``
        at construction, so an imported prefix longer than the bucket
        cap plus the request's ``max_new_tokens`` would run decode past
        the last KV row. Reject it HERE, naming the overflow, instead
        of letting the decode step silently clamp (the same
        named-headroom discipline as :meth:`check_kv_headroom`)."""
        span = int(bucket_max_len) + self.max_new_tokens + spec_overshoot
        need = int(prefix_len) + int(max_new_tokens) + spec_overshoot
        if need > span:
            spec = (f" + speculative headroom {spec_overshoot}"
                    if spec_overshoot else "")
            raise ValueError(
                f"decode-only: imported prefix {prefix_len} + "
                f"max_new_tokens {max_new_tokens}{spec} = {need} rows "
                f"exceeds the decode slot span bucket_max_len + "
                f"max_new_tokens{spec} = {bucket_max_len} + "
                f"{self.max_new_tokens}"
                f"{' + ' + str(spec_overshoot) if spec_overshoot else ''}"
                f" = {span} by {need - span} rows; shorten the prefix, "
                f"lower the request's max_new_tokens, or size the "
                f"decode replica's buckets for the prefill fleet's "
                f"output lengths")


def check_positions(model, prompt_len: int, max_new_tokens: int) -> None:
    """Fail loudly when decode would run past the positional table —
    ``embed_at``'s dynamic slice clamps at the edge, which would silently
    reuse the last rows instead of erroring like the training path.
    Models advertise their capacity via ``max_position()``."""
    mp = getattr(model, "max_position", None)
    limit = mp() if callable(mp) else None
    if limit is not None and prompt_len + max_new_tokens > limit:
        raise ValueError(
            f"prompt_len {prompt_len} + max_new_tokens {max_new_tokens} "
            f"exceeds the positional table ({limit} positions)")


def head_logits(model, post_params, h: jax.Array) -> jax.Array:
    """The model head on hidden states (float32 logits) — ONE definition
    shared by the single-device and ring-pipelined generators. Quantized
    head weights (inference/quant.py) dequantize here, in-step."""
    from .quant import dequant_tree
    return model.head.apply(dequant_tree(post_params[model.post_key],
                                         jnp.float32),
                            h.astype(jnp.float32))


def sample_logits(logits: jax.Array, key: jax.Array,
                  cfg: GenerationConfig) -> jax.Array:
    """Next-token ids ``[b]`` from ``logits [b, vocab]`` (float32 math)."""
    logits = logits.astype(jnp.float32)
    if cfg.temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / cfg.temperature
    if cfg.top_k is not None:
        kth = jax.lax.top_k(logits, cfg.top_k)[0][..., -1:]
        logits = jnp.where(logits >= kth, logits,
                           jnp.asarray(-1e30, logits.dtype))
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def sequence_lengths(tokens: jax.Array,
                     eos_token_id: Optional[int]) -> jax.Array:
    """Per-sequence generated length from ``tokens [..., max_new]``: the
    index of the first EOS plus one (the EOS itself counts as emitted),
    or the full width for rows that never stopped. ``None`` => every row
    ran to ``max_new_tokens``."""
    toks = jnp.asarray(tokens)
    width = toks.shape[-1]
    if eos_token_id is None:
        return jnp.full(toks.shape[:-1], width, jnp.int32)
    hit = toks == jnp.int32(eos_token_id)
    first = jnp.argmax(hit, axis=-1)
    return jnp.where(hit.any(axis=-1), first + 1, width).astype(jnp.int32)


class Generator:
    """KV-cached sampling over a :class:`~.models.common.PipelinedTransformer`
    LM factorization (``PipelinedLM`` and friends: ``embed_at`` + causal
    ``block.decode`` + ``post_fn`` head).

    ``generate`` is jitted per (batch, prompt_len) shape; params are the
    ``(stage_params, pre_params, post_params)`` triple from ``model.init``
    (the training layout — no weight conversion between train and serve).

    ``layer_scan=False`` unrolls the per-layer loop inside the decode
    step and carries the KV caches as two stacked arrays in the OUTER
    scan, updated in place per layer — avoiding the inner ``lax.scan``'s
    xs->ys round-trip of the full cache every token (measured 1.16x at
    the 520M scale, batch 32, where decode is cache-traffic-bound). Same
    math; float reduction order differs, so greedy ties can resolve
    differently on near-flat (e.g. untrained) logits.
    """

    def __init__(self, model, gen_cfg: GenerationConfig = GenerationConfig(),
                 *, layer_scan: bool = True, phase_timing: bool = False,
                 shape_cache_warn: int = 16):
        if not hasattr(model, "embed_at"):
            raise TypeError(
                f"{type(model).__name__} has no embed_at; KV-cache "
                "generation needs position-offset embedding")
        if not layer_scan and gen_cfg.num_beams > 1:
            raise ValueError(
                "layer_scan=False is not implemented for beam search "
                "(the beam path's cache-gather dominates its traffic; "
                "use the default scan path)")
        self.model = model
        self.gen_cfg = gen_cfg
        self.layer_scan = layer_scan
        # phase_timing=True additionally times a prefill-only program per
        # generate() call so the registry sees separate prefill/decode
        # histograms (decode = e2e - prefill). It re-runs prefill, so it
        # costs one extra prompt pass per call — opt-in, for profiling.
        self.phase_timing = phase_timing
        self._jitted = jax.jit(self._generate)
        self._jitted_beam = None  # built on first beam-search call
        self._jitted_prefill = None  # built on first phase_timing call
        # Per-shape jit cache bookkeeping: `generate` compiles one program
        # per (batch, prompt_len). That's invisible from the outside —
        # count it, and warn loudly once the cache grows past the
        # threshold (a serving workload feeding raw prompt lengths here
        # should bucket them: pipe_tpu.serve.BucketSpec).
        self.shape_cache_warn = shape_cache_warn
        self._shapes_seen: set = set()

    # --- internals ---

    def _blocks(self, stage_params):
        """Flatten the per-stage block lists into one [block0..blockL-1]
        list, cast to compute dtype (stage_fn's contract). QuantLeaf
        nodes (int8 weight-only quantization, inference/quant.py) pass
        through untouched — they dequantize at use time via _dq."""
        from .quant import QuantLeaf
        cd = self.model.cfg.compute_dtype
        flat = [bp for stage in stage_params for bp in stage]
        return [jax.tree_util.tree_map(
                    lambda p: p if isinstance(p, QuantLeaf)
                    else p.astype(cd),
                    bp, is_leaf=lambda x: isinstance(x, QuantLeaf))
                for bp in flat]

    def _dq(self, bp):
        """Materialize block weights at use time (int8 -> compute dtype
        inside the compiled step; identity when unquantized)."""
        return dequant_tree(bp, self.model.cfg.compute_dtype)

    def _head(self, post_params, h):
        return head_logits(self.model, post_params, h)

    def _make_caches(self, blocks, batch, max_len):
        """One KV cache per layer (hook: the TP generator overrides this
        to size caches by the LOCAL head shard)."""
        m = self.model
        return [m.block.attn.make_cache(batch, max_len,
                                        dtype=m.cfg.compute_dtype)
                for _ in blocks]

    def _prefill(self, blocks, pre_params, prompt, max_len):
        """One batched causal pass: embeds the prompt, writes rows
        [0, prompt_len) of every layer's cache. Returns (h, caches)."""
        m = self.model
        caches = self._make_caches(blocks, prompt.shape[0], max_len)
        h = m.embed_at(pre_params, prompt, 0)
        for l, bp in enumerate(blocks):
            h, caches[l] = m.block.decode(self._dq(bp), h, caches[l], 0)
        return h, caches

    def _layer_step(self, h_carry, inp):
        """Scan body over the stacked layers: one cached decode step."""
        bp, cache = inp
        h_new, cache = self.model.block.decode(self._dq(bp), h_carry[0],
                                               cache, h_carry[1])
        return (h_new, h_carry[1]), cache

    def _generate(self, params, prompt, key):
        m, gen = self.model, self.gen_cfg
        stage_params, pre_params, post_params = params
        blocks = self._blocks(stage_params)
        b, p = prompt.shape
        h, caches = self._prefill(blocks, pre_params, prompt,
                                  p + gen.max_new_tokens)
        key, sub = jax.random.split(key)
        tok = sample_logits(self._head(post_params, h[:, -1:, :])[:, 0, :],
                            sub, gen)

        # decode: one token per scan step, O(1) new work per layer
        cache_stack = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *caches)
        if self.layer_scan:
            block_stack = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *blocks)

            def run_layers(h, pos, caches):
                (h, _), caches = jax.lax.scan(
                    self._layer_step, (h, pos), (block_stack, caches))
                return h, caches
        else:
            # unrolled: per-layer in-place row writes on the OUTER carry —
            # no inner-scan xs->ys round-trip of the full cache per token
            def run_layers(h, pos, caches):
                for l, bp in enumerate(blocks):
                    c_l = jax.tree_util.tree_map(lambda a: a[l], caches)
                    h, c_l = m.block.decode(self._dq(bp), h, c_l, pos)
                    caches = jax.tree_util.tree_map(
                        lambda a, n: a.at[l].set(n), caches, c_l)
                return h, caches

        # EOS handling is a Python-level gate so eos_token_id=None traces
        # the exact pre-EOS program (no dead done-mask ops in the scan).
        eos = gen.eos_token_id

        def step(carry, _):
            if eos is None:
                caches, tok, pos, key = carry
            else:
                caches, tok, pos, key, done = carry
            h = m.embed_at(pre_params, tok[:, None], pos)
            h, caches = run_layers(h, pos, caches)
            key, sub = jax.random.split(key)
            nxt = sample_logits(self._head(post_params, h)[:, 0, :],
                                sub, gen)
            if eos is None:
                return (caches, nxt, pos + 1, key), tok
            # finished rows emit pad from the step AFTER their EOS; the
            # EOS token itself is emitted (it counts toward the length)
            nxt = jnp.where(done, jnp.int32(gen.pad_token_id), nxt)
            done = done | (nxt == jnp.int32(eos))
            return (caches, nxt, pos + 1, key, done), tok

        init = (cache_stack, tok, jnp.int32(p), key)
        if eos is not None:
            init = init + (tok == jnp.int32(eos),)
        carry_out, toks = jax.lax.scan(
            step, init, None, length=gen.max_new_tokens - 1)
        last = carry_out[1]
        # toks holds the tokens *entering* each step; append the final one
        out = jnp.moveaxis(toks, 0, 1)  # [b, max_new-1]
        return jnp.concatenate([out, last[:, None]], axis=1)

    def _prefill_only(self, params, prompt):
        """Prefill pass alone (same math as the head of ``_generate``),
        jitted separately so ``phase_timing`` can attribute wall time to
        prefill vs decode without instrumenting inside the scan."""
        stage_params, pre_params, post_params = params
        blocks = self._blocks(stage_params)
        h, _ = self._prefill(blocks, pre_params, prompt,
                             prompt.shape[1] + self.gen_cfg.max_new_tokens)
        return self._head(post_params, h[:, -1:, :])

    def _observe_phases(self, reg, params, prompt, e2e_sec: float) -> None:
        """Time the prefill-only program and fold the split into the
        registry. First call includes its compile (as the e2e number's
        first call does); decode is the e2e remainder."""
        if self._jitted_prefill is None:
            self._jitted_prefill = jax.jit(self._prefill_only)
        t0 = time.perf_counter()
        jax.block_until_ready(self._jitted_prefill(params, prompt))
        pf = time.perf_counter() - t0
        reg.histogram("serve.prefill_sec").observe(pf)
        reg.histogram("serve.decode_sec").observe(max(e2e_sec - pf, 0.0))

    def _generate_beam(self, params, prompt):
        """Beam search: deterministic, sum-of-log-probs scoring.

        Caches are tiled to ``b*k`` rows after prefill and physically
        re-gathered by parent beam each step (the standard KV-cache beam
        reorder — one cache-sized gather per step). Returns
        ``(tokens [b, max_new], scores [b])`` for the best beam.
        """
        m, gen = self.model, self.gen_cfg
        k = gen.num_beams
        stage_params, pre_params, post_params = params
        blocks = self._blocks(stage_params)
        b, p = prompt.shape
        # prefill on the UNtiled batch, then branch into k beams
        h, caches = self._prefill(blocks, pre_params, prompt,
                                  p + gen.max_new_tokens)
        logp = jax.nn.log_softmax(
            self._head(post_params, h[:, -1:, :])[:, 0, :], axis=-1)
        scores, tok = jax.lax.top_k(logp, k)          # [b, k] each
        tok = tok.astype(jnp.int32)

        cache_stack = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *caches)
        cache_stack = jax.tree_util.tree_map(
            lambda c: jnp.repeat(c, k, axis=1), cache_stack)  # [L, b*k, ...]
        block_stack = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *blocks)

        out0 = jnp.zeros((b, k, gen.max_new_tokens), jnp.int32)
        out0 = out0.at[:, :, 0].set(tok)

        def step(carry, t):
            caches, scores, tok, out = carry
            pos = p + t
            h = m.embed_at(pre_params, tok.reshape(b * k, 1), pos)
            (h, _), caches = jax.lax.scan(
                self._layer_step, (h, pos), (block_stack, caches))
            logp = jax.nn.log_softmax(
                self._head(post_params, h)[:, 0, :], axis=-1)  # [b*k, V]
            V = logp.shape[-1]
            total = scores[:, :, None] + logp.reshape(b, k, V)
            scores, idx = jax.lax.top_k(total.reshape(b, k * V), k)
            parent = idx // V                              # [b, k]
            tok = (idx % V).astype(jnp.int32)
            flat_parent = (jnp.arange(b)[:, None] * k + parent).reshape(-1)
            caches = jax.tree_util.tree_map(
                lambda c: jnp.take(c, flat_parent, axis=1), caches)
            out = jnp.take_along_axis(out, parent[:, :, None], axis=1)
            out = jax.lax.dynamic_update_slice(
                out, tok[:, :, None], (0, 0, t + 1))
            return (caches, scores, tok, out), None

        (_, scores, _, out), _ = jax.lax.scan(
            step, (cache_stack, scores, tok, out0),
            jnp.arange(gen.max_new_tokens - 1))
        best = jnp.argmax(scores, axis=1)
        toks = jnp.take_along_axis(
            out, best[:, None, None], axis=1)[:, 0, :]
        return toks, jnp.take_along_axis(scores, best[:, None], axis=1)[:, 0]

    def _note_shape(self, shape_key) -> None:
        """Track the per-shape jit cache: one program per (batch,
        prompt_len) [plus a tag for the beam variant]. Counters make the
        cache visible to serving telemetry; the warning fires when an
        unbucketed workload is compiling per raw prompt length."""
        reg = get_registry()
        if shape_key in self._shapes_seen:
            reg.counter("serve.program_cache_hits").inc()
            return
        self._shapes_seen.add(shape_key)
        reg.counter("serve.program_cache_misses").inc()
        reg.gauge("serve.program_cache_entries").set(len(self._shapes_seen))
        if len(self._shapes_seen) == self.shape_cache_warn + 1:
            warnings.warn(
                f"Generator has compiled {len(self._shapes_seen)} distinct "
                f"(batch, prompt_len) programs — every new prompt shape "
                f"recompiles the full prefill+decode step. Bucket prompt "
                f"lengths (pipe_tpu.serve.BucketSpec / ServeEngine) or pad "
                f"to a fixed shape to cap the cache.",
                RuntimeWarning, stacklevel=3)

    # --- public ---

    def generate(self, params, prompt: jax.Array,
                 key: Optional[jax.Array] = None) -> jax.Array:
        """Sample ``[b, max_new_tokens]`` continuations of ``prompt
        [b, prompt_len]`` int32 ids. ``num_beams > 1`` runs beam search
        (deterministic; ``key`` unused)."""
        check_positions(self.model, prompt.shape[1],
                        self.gen_cfg.max_new_tokens)
        if self.gen_cfg.num_beams > 1:
            return self.generate_with_scores(params, prompt)[0]
        if key is None:
            key = jax.random.key(0)
        prompt = jnp.asarray(prompt, jnp.int32)
        self._note_shape(prompt.shape)
        reg = get_registry()
        t0 = time.perf_counter()
        out = self._jitted(params, prompt, key)
        if reg.enabled:
            # Block for an honest latency number; callers read the tokens
            # to host right after anyway.
            out = jax.block_until_ready(out)
            dt = time.perf_counter() - t0
            reg.histogram("serve.generate_sec").observe(dt)
            tokens = prompt.shape[0] * self.gen_cfg.max_new_tokens
            reg.counter("serve.tokens").inc(tokens)
            if dt > 0:
                reg.gauge("serve.tokens_per_sec").set(tokens / dt)
            if self.phase_timing:
                self._observe_phases(reg, params, prompt, dt)
        return out

    def generate_with_scores(self, params, prompt: jax.Array):
        """Beam search returning ``(tokens [b, max_new], scores [b])`` —
        the best beam's tokens and its total log-probability."""
        if self.gen_cfg.num_beams < 2:
            raise ValueError("generate_with_scores requires num_beams >= 2")
        check_positions(self.model, prompt.shape[1],
                        self.gen_cfg.max_new_tokens)
        if self._jitted_beam is None:
            self._jitted_beam = jax.jit(self._generate_beam)
        prompt = jnp.asarray(prompt, jnp.int32)
        self._note_shape(("beam",) + prompt.shape)
        reg = get_registry()
        t0 = time.perf_counter()
        out = self._jitted_beam(params, prompt)
        if reg.enabled:
            out = jax.block_until_ready(out)
            dt = time.perf_counter() - t0
            reg.histogram("serve.beam_sec").observe(dt)
            tokens = prompt.shape[0] * self.gen_cfg.max_new_tokens
            reg.counter("serve.tokens").inc(tokens)
            if dt > 0:
                reg.gauge("serve.tokens_per_sec").set(tokens / dt)
        return out

    def generate_with_lengths(self, params, prompt: jax.Array,
                              key: Optional[jax.Array] = None):
        """``(tokens [b, max_new], lengths [b])`` — per-sequence generated
        length: up to and including the first EOS, or ``max_new_tokens``
        when the row never stopped (always ``max_new_tokens`` with
        ``eos_token_id=None``). Rows past their length hold pad."""
        out = self.generate(params, prompt, key)
        return out, sequence_lengths(out, self.gen_cfg.eos_token_id)
