"""Draft sources for the speculative decode lane.

PR 11 built the hard half of speculation — the fixed-shape teacher-forced
verify chunk with exact rollback and the key chain advanced by accepted
count — and fed it the cheapest possible drafter (n-gram history lookup,
acceptance ~0.01 on the bench model). This module makes the draft side
real, behind one interface:

* :class:`NgramDraft` — the PR 11 lookup, kept as the zero-cost baseline.
* :class:`TruncatedDraft` — runs the FIRST ``draft_stages`` stages of the
  already-partitioned model (the same stacked block params the verify
  uses, QuantLeaf-aware) plus a tied-embedding head, greedy, K-1 steps.
  The "early layers carry most next-token signal" argument of LayerPipe /
  2BP applied to inference: the draft is a strict prefix of the model
  itself, so its KV rows land in the real cache and the verify pass
  overwrites every row the draft touched (the rollback-overwrite law
  needs no extra storage).
* :class:`TreeDraft` — ``branches`` top-B continuations from one shared
  truncated-model root step, each rolled out greedily to depth K-1 on a
  private copy of the draft-layer caches. All branches verify in the
  SAME fixed-shape chunk under a causal tree mask
  (:func:`tree_layout`); the engine accepts the longest matching
  root-to-leaf path.

Every drafter's ``propose`` is pure jax — it runs INSIDE the resident
``while_loop`` body, keeping the zero-host-sync steady state.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .quant import dequant_tree

__all__ = ["DraftSource", "NgramDraft", "TruncatedDraft", "TreeDraft",
           "tree_layout", "resolve_draft"]


def tree_layout(K: int, branches: int) -> Tuple[np.ndarray, np.ndarray]:
    """Static layout of the flattened draft tree for ``branches`` chains
    of depth ``K-1`` sharing one root.

    Returns ``(depths [Q], anc [Q, Q])`` with ``Q = 1 + branches*(K-1)``:
    row 0 is the root (the slot's current token, depth 0); branch ``b``
    level ``i`` sits at row ``1 + b*(K-1) + i`` with depth ``i+1``.
    ``anc[j, r]`` is True when chunk row ``r`` is an ancestor-or-self of
    chunk row ``j`` — the within-chunk attention mask."""
    Q = 1 + branches * (K - 1)
    depths = np.zeros((Q,), np.int32)
    anc = np.zeros((Q, Q), bool)
    anc[0, 0] = True
    for b in range(branches):
        base = 1 + b * (K - 1)
        for i in range(K - 1):
            r = base + i
            depths[r] = i + 1
            anc[r, 0] = True
            anc[r, base:base + i + 1] = True
    return depths, anc


class DraftSource:
    """One speculative draft proposal per resident round.

    ``propose`` returns ``(drafts [S, branches, K-1] int32, caches)``:
    for each slot, ``branches`` candidate continuations of the current
    token. The caches come back because prefix drafters write real KV
    rows at positions >= ``pos`` — all of them re-written by the verify
    chunk before any unmasked read (the rollback-overwrite law)."""

    name = "?"
    branches = 1

    def propose(self, m, gen, pre, block_stack, caches, tok, pos, hist,
                K: int, paged: bool):
        raise NotImplementedError

    def draft_cost_frac(self, K: int, n_layers: int) -> float:
        """Predicted draft device-time over total round device-time,
        counting (rows x layers) work units — the breakeven input the
        planner and the bench gate consume."""
        raise NotImplementedError


# ---------------------------------------------------------------------------
# n-gram lookup (the PR 11 drafter, zero draft cost)
# ---------------------------------------------------------------------------

class NgramDraft(DraftSource):
    """Tokens following the most recent earlier occurrence of the
    current token in the slot's device-side history buffer."""

    name = "ngram"

    def propose(self, m, gen, pre, block_stack, caches, tok, pos, hist,
                K, paged):
        H = hist.shape[1]
        idx = jnp.arange(H, dtype=jnp.int32)

        def draft_one(hrow, t, p):
            mask = (hrow == t) & (idx < p)
            j = jnp.max(jnp.where(mask, idx, jnp.int32(-1)))
            start = jnp.maximum(j + 1, 0)
            return jax.lax.dynamic_slice(hrow, (start,), (K - 1,))

        drafts = jax.vmap(draft_one)(hist, tok, pos)       # [S, K-1]
        return drafts[:, None, :], caches

    def draft_cost_frac(self, K, n_layers):
        return 0.0


# ---------------------------------------------------------------------------
# truncated-pipeline rollout (shared machinery for linear and tree)
# ---------------------------------------------------------------------------

def _tied_logits(m, pre, h):
    """Tied-embedding head: score hidden states against the embedding
    table. The ``sqrt(d)`` embed scaling is uniform over vocab, so the
    argmax the greedy rollout takes is scale-invariant."""
    table = pre["embed"]["table"].astype(jnp.float32)
    return h.astype(jnp.float32) @ table.T


def _draft_step(m, dstack, dcaches, pre, tok, pos, paged):
    """One q=1 greedy step through the draft-layer prefix: embeds
    ``tok`` at ``pos``, writes KV row ``pos`` in every draft layer,
    returns the tied-head hidden state ``[S, d]`` and updated caches.
    Mirrors the verify chunk's per-layer vmap exactly (same
    ``block.decode``), so draft rows are bitwise what the verify would
    write for the same (token, position)."""
    cd = m.cfg.compute_dtype
    h = jax.vmap(
        lambda t, p: m.embed_at(pre, t[None, None], p)[0])(tok, pos)

    def layer(h, inp):
        bp, cache = inp
        bpd = dequant_tree(bp, cd)

        if paged:
            def one(hh, cache_l, pp):
                cache = {name: cache_l[name][None]
                         for name in ("k", "v")}
                out, c2 = m.block.decode(bpd, hh[None], cache, pp)
                return out[0], {name: c2[name][0]
                                for name in ("k", "v")}
        else:
            def one(hh, cc, pp):
                out, cc2 = m.block.decode(
                    bpd, hh[None],
                    jax.tree_util.tree_map(lambda a: a[None], cc), pp)
                return out[0], jax.tree_util.tree_map(
                    lambda a: a[0], cc2)

        return jax.vmap(one)(h, cache, pos)

    h, dcaches = jax.lax.scan(layer, h, (dstack, dcaches))
    return h[:, 0], dcaches


def _slice_draft(tree, Ld):
    return jax.tree_util.tree_map(lambda a: a[:Ld], tree)


def _merge_draft(dcaches, caches, Ld):
    return jax.tree_util.tree_map(
        lambda d, full: jnp.concatenate([d, full[Ld:]], axis=0),
        dcaches, caches)


class TruncatedDraft(DraftSource):
    """Greedy K-1 step rollout through the first ``draft_layers``
    layers of the model plus a tied-embedding head."""

    name = "truncated"

    def __init__(self, draft_layers: int):
        if draft_layers < 1:
            raise ValueError(
                f"truncated draft needs >= 1 draft layer, got "
                f"{draft_layers}")
        self.draft_layers = draft_layers

    def propose(self, m, gen, pre, block_stack, caches, tok, pos, hist,
                K, paged):
        Ld = self.draft_layers
        dstack = _slice_draft(block_stack, Ld)
        dcaches = _slice_draft(caches, Ld)
        cur, p = tok, pos
        outs = []
        for _ in range(K - 1):
            h, dcaches = _draft_step(m, dstack, dcaches, pre, cur, p,
                                     paged)
            cur = jnp.argmax(_tied_logits(m, pre, h),
                             axis=-1).astype(jnp.int32)
            outs.append(cur)
            p = p + 1
        drafts = jnp.stack(outs, axis=1)                   # [S, K-1]
        return drafts[:, None, :], _merge_draft(dcaches, caches, Ld)

    def draft_cost_frac(self, K, n_layers):
        d = (K - 1) * self.draft_layers
        return d / (d + K * n_layers)


class TreeDraft(DraftSource):
    """Top-``branches`` first tokens from one shared truncated root
    step, each continued greedily on a private draft-cache copy. The
    branch copies are discarded — only the shared root row (re-written
    by the verify chunk) persists in the real caches."""

    name = "tree"

    def __init__(self, branches: int, draft_layers: int):
        if branches < 2:
            raise ValueError(
                f"tree draft needs >= 2 branches (1 branch IS the "
                f"truncated drafter), got {branches}")
        if draft_layers < 1:
            raise ValueError(
                f"tree draft needs >= 1 draft layer, got {draft_layers}")
        self.branches = branches
        self.draft_layers = draft_layers

    def propose(self, m, gen, pre, block_stack, caches, tok, pos, hist,
                K, paged):
        Ld, B = self.draft_layers, self.branches
        S = tok.shape[0]
        dstack = _slice_draft(block_stack, Ld)
        dcaches = _slice_draft(caches, Ld)
        # shared root step: writes row `pos` in the real draft caches
        h, dcaches = _draft_step(m, dstack, dcaches, pre, tok, pos,
                                 paged)
        first = jax.lax.top_k(_tied_logits(m, pre, h), B)[1] \
            .astype(jnp.int32)                              # [S, B]
        if K > 2:
            # per-branch private rollouts: tile the draft caches along
            # the slot axis (S*B pseudo-slots) and reuse the same step
            bcaches = jax.tree_util.tree_map(
                lambda a: jnp.repeat(a, B, axis=1), dcaches)
            cur = first.reshape(-1)
            p = jnp.repeat(pos + 1, B)
            outs = [cur]
            for _ in range(K - 2):
                h, bcaches = _draft_step(m, dstack, bcaches, pre, cur,
                                         p, paged)
                cur = jnp.argmax(_tied_logits(m, pre, h),
                                 axis=-1).astype(jnp.int32)
                outs.append(cur)
                p = p + 1
            drafts = jnp.stack(outs, axis=1).reshape(S, B, K - 1)
        else:
            drafts = first[:, :, None]                      # [S, B, 1]
        return drafts, _merge_draft(dcaches, caches, Ld)

    def draft_cost_frac(self, K, n_layers):
        steps = 1 + self.branches * max(K - 2, 0)
        d = steps * self.draft_layers
        Q = 1 + self.branches * (K - 1)
        return d / (d + Q * n_layers)


def resolve_draft(name: str, *, n_stages: int, layers_per_stage: int,
                  draft_stages: int = 1,
                  spec_branches: Optional[int] = None) -> DraftSource:
    """Build a drafter from flag-level options, rejecting impossible
    combinations loudly (never a silent fallback)."""
    if name == "ngram":
        return NgramDraft()
    if draft_stages < 1 or draft_stages >= n_stages:
        raise ValueError(
            f"draft_stages={draft_stages} must be in [1, "
            f"{n_stages - 1}] — the draft is a STRICT prefix of the "
            f"{n_stages}-stage model (a full-depth draft is just the "
            f"model)")
    Ld = draft_stages * layers_per_stage
    if name == "truncated":
        return TruncatedDraft(Ld)
    if name == "tree":
        if spec_branches is None or spec_branches < 2:
            raise ValueError(
                f"tree draft needs spec_branches >= 2, got "
                f"{spec_branches}")
        return TreeDraft(spec_branches, Ld)
    raise ValueError(
        f"unknown draft source {name!r}: pick ngram | truncated | tree")
