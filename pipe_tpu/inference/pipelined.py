"""Pipelined autoregressive decoding over stage-sharded parameters.

Serving a model whose weights are pipeline-sharded (each device holds ONLY
its stages' blocks — the whole point of `Pipe.shard_params`) cannot use the
single-device :class:`~.generate.Generator`: every token must traverse all
stages. Naively that serializes — one token in flight, n-1 stages idle.
This module pipelines the *requests* instead: the batch is split into
``n_stages`` groups that chase each other around the stage ring, one
ppermute per cycle (the same ICI transport as the training executors), so
in steady state every stage decodes a different group's token each cycle —
aggregate throughput of one token-group per cycle, the inference analogue
of GPipe's fill-drain (which never needs a backward, so the schedule is
just the ring).

Structure per cycle (device = stage ``s``, cycle ``c``, group
``(c - s) mod n``): stage 0 embeds the group's current token (first
revolution: the prefill's sampled token, afterwards the token arriving on
the wrap edge), every stage runs its blocks through the KV caches it owns
for that group, stage n-1 samples and sends the token around the wrap to
stage 0 — which needs it exactly at cycle ``c+1``, when that group's next
revolution begins. A prefill phase first walks each group's prompt through
the ring once (q=prompt_len), filling cache rows ``[0, p)``.

Static-shape discipline: invalid fill/drain cycles write their garbage
K/V rows into a sacrificial cache region past ``p + max_new`` and their
garbage tokens into a sentinel output column (the executors' masked-slot
trick, ``parallel/buffers.py``) — no per-cycle ``lax.cond``, no dynamic
shapes. Known cost: the active group's cache slab is sliced out and
written back each cycle (same order of HBM traffic as the attention read
itself); acceptable at decode arithmetic intensity.

``tests/test_pipelined_gen.py`` pins greedy pipelined output against the
single-device Generator token-for-token.
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..obs.telemetry import get_registry
from ..parallel.mesh import STAGE_AXIS
from .generate import (GenerationConfig, check_positions, head_logits,
                       sample_logits, sequence_lengths)
from .quant import QuantLeaf, dequant_tree
from ..utils.compat import shard_map

__all__ = ["PipelinedGenerator"]


class PipelinedGenerator:
    """Ring-pipelined KV-cache sampling over a ``stage`` mesh axis.

    ``model`` is a ``PipelinedTransformer`` LM with ``embed_at`` (see
    :class:`~.generate.Generator`); params are the training layout with
    ``stage_params`` stacked ``[n_stages, ...]`` (``stack_stage_params``)
    and sharded over ``stage`` — serve the weights exactly as trained.
    The batch must divide into ``n_stages`` groups.
    """

    def __init__(self, mesh: Mesh, model,
                 gen_cfg: GenerationConfig = GenerationConfig()):
        if STAGE_AXIS not in mesh.axis_names:
            raise ValueError(f"mesh must have a {STAGE_AXIS!r} axis")
        if not hasattr(model, "embed_at"):
            raise TypeError(
                f"{type(model).__name__} has no embed_at; KV-cache "
                "generation needs position-offset embedding")
        self.mesh = mesh
        self.model = model
        self.gen_cfg = gen_cfg
        self.n_stages = mesh.shape[STAGE_AXIS]
        # jitted device programs keyed by (prompt_len, rows_per_group,
        # param treedef): jit caches by callable identity, and shard_map +
        # partial build fresh callables — without this cache every
        # generate() call would retrace AND recompile
        self._programs = {}

    # --- internals ---

    def _ring(self, x):
        n = self.n_stages
        return jax.lax.ppermute(x, STAGE_AXIS,
                                [(i, (i + 1) % n) for i in range(n)])

    def _head(self, post_params, h):
        return head_logits(self.model, post_params, h)

    def _run_blocks(self, block_stack, h, caches, grp, pos):
        """Run this stage's blocks on ``h`` against group ``grp``'s cache
        slab; returns (h, updated caches). ``caches``: pytree of
        ``[lps, n_groups, rpg, cache_len, nh, hd]``."""
        m = self.model
        cd = m.cfg.compute_dtype
        lps = jax.tree_util.tree_leaves(caches)[0].shape[0]

        def slab_slice(a):
            s = jax.lax.dynamic_slice(
                a, (0, grp) + (0,) * (a.ndim - 2),
                (lps, 1) + a.shape[2:])
            return jnp.squeeze(s, axis=1)

        def slab_write(a, new):
            return jax.lax.dynamic_update_slice(
                a, new[:, None], (0, grp) + (0,) * (a.ndim - 2))

        slab = jax.tree_util.tree_map(slab_slice, caches)

        def layer_step(h_c, inp):
            bp, cache = inp
            h_new, cache = m.block.decode(dequant_tree(bp, cd), h_c,
                                          cache, pos)
            return h_new, cache

        h, new_slab = jax.lax.scan(layer_step, h, (block_stack, slab))
        caches = jax.tree_util.tree_map(slab_write, caches, new_slab)
        return h, caches

    def _device_program(self, stage_params, pre_params, post_params,
                        prompt_g, key, *, p, rpg):
        m, gen, n = self.model, self.gen_cfg, self.n_stages
        max_new = gen.max_new_tokens
        s = jax.lax.axis_index(STAGE_AXIS)
        cd = m.cfg.compute_dtype
        nh, hd = m.block.attn.nhead, m.block.attn.head_dim
        # sacrificial region: p rows past the live prefix absorbs garbage
        # writes from fill/drain cycles (prefill writes q=p rows at once)
        cache_len = p + max_new + p
        sac = p + max_new

        def local_slice(a):
            # this device's stage slice (leading dim n/n_devices == 1);
            # QuantLeaf nodes slice through their children, stay quantized
            if isinstance(a, QuantLeaf):
                return QuantLeaf(q=a.q[0], scale=a.scale[0])
            return a[0].astype(cd)

        blocks = [jax.tree_util.tree_map(
                      local_slice, bp,
                      is_leaf=lambda x: isinstance(x, QuantLeaf))
                  for bp in stage_params]
        block_stack = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *blocks)
        lps = len(blocks)
        caches = {"k": jnp.zeros((lps, n, rpg, cache_len, nh, hd), cd),
                  "v": jnp.zeros((lps, n, rpg, cache_len, nh, hd), cd)}

        def pre_key(grp):
            return jax.random.fold_in(jax.random.fold_in(key, grp), 0)

        def dec_key(grp, t):
            return jax.random.fold_in(jax.random.fold_in(key, grp), t + 1)

        # ---- prefill: each group's prompt rides the ring once (q = p)
        def pre_cycle(carry, c):
            h_carry, caches, init_toks = carry
            raw = c - s
            valid = (raw >= 0) & (raw < n)
            grp = jnp.clip(raw, 0, n - 1)
            pos = jnp.where(valid, 0, sac)
            h_embed = m.embed_at(pre_params,
                                 jnp.take(prompt_g, grp, axis=0), 0)
            h_in = jnp.where(s == 0, h_embed, h_carry)
            h_out, caches = self._run_blocks(block_stack, h_in, caches,
                                             grp, pos)
            logits = self._head(post_params, h_out[:, -1:, :])[:, 0, :]
            tok = sample_logits(logits, pre_key(grp), gen)
            emit = (s == n - 1) & valid
            old = jnp.take(init_toks, grp, axis=0)
            init_toks = jax.lax.dynamic_update_slice(
                init_toks, jnp.where(emit, tok, old)[None], (grp, 0))
            return (self._ring(h_out), caches, init_toks), None

        h0 = jnp.zeros((rpg, p, m.cfg.d_model), cd)
        init_toks = jnp.zeros((n, rpg), jnp.int32)
        (_, caches, init_toks), _ = jax.lax.scan(
            pre_cycle, (h0, caches, init_toks), jnp.arange(2 * n - 1))
        # only stage n-1 sampled real tokens; replicate its table
        init_toks = jax.lax.psum(
            jnp.where(s == n - 1, init_toks, 0), STAGE_AXIS)

        # EOS: Python-level gate so eos_token_id=None traces the exact
        # pre-EOS program. Every stage carries its own done table, but
        # only stage n-1's chain is consulted (its tokens ride the wrap
        # edge and fill `out`); the other stages' updates track garbage
        # samples harmlessly.
        eos = gen.eos_token_id

        # ---- decode: one token-group per cycle in steady state (q = 1)
        def dec_cycle(carry, c):
            if eos is None:
                h_carry, tok_ring, caches, out = carry
            else:
                h_carry, tok_ring, caches, out, done = carry
            raw = c - s
            valid = (raw >= 0) & (raw < n * max_new)
            grp = jnp.mod(raw, n)
            t = jnp.where(valid, raw // n, 0)
            pos = jnp.where(valid, p + t, sac)
            tok_use = jnp.where(c < n, jnp.take(init_toks, grp, axis=0),
                                tok_ring)
            h_embed = m.embed_at(pre_params, tok_use[:, None], pos)
            h_in = jnp.where(s == 0, h_embed, h_carry)
            h_out, caches = self._run_blocks(block_stack, h_in, caches,
                                             grp, pos)
            logits = self._head(post_params, h_out)[:, 0, :]
            tok_out = sample_logits(logits, dec_key(grp, t), gen)
            if eos is not None:
                done_g = jnp.take(done, grp, axis=0)
                tok_out = jnp.where(done_g, jnp.int32(gen.pad_token_id),
                                    tok_out)
                done = jax.lax.dynamic_update_slice(
                    done, (done_g | (tok_out == jnp.int32(eos)))[None],
                    (grp, 0))
            emit = (s == n - 1) & valid
            # slot t holds the token SAMPLED while processing decode index
            # t — i.e. generated token t+1 (the assembly below prepends
            # init_toks as generated token 0 and drops the last sample,
            # which is never re-embedded, mirroring Generator's scan)
            t_write = jnp.where(emit, t, max_new)
            out = jax.lax.dynamic_update_slice(
                out, tok_out[None, :, None], (grp, 0, t_write))
            ring_out = (self._ring(h_out), self._ring(tok_out), caches,
                        out)
            if eos is not None:
                ring_out = ring_out + (done,)
            return ring_out, None

        h0 = jnp.zeros((rpg, 1, m.cfg.d_model), cd)
        out = jnp.zeros((n, rpg, max_new + 1), jnp.int32)
        cycles = n * max_new + n - 1
        carry0 = (h0, jnp.zeros((rpg,), jnp.int32), caches, out)
        if eos is not None:
            carry0 = carry0 + (init_toks == jnp.int32(eos),)
        carry_out, _ = jax.lax.scan(dec_cycle, carry0, jnp.arange(cycles))
        out = carry_out[3]
        # tokens ENTERING each step are init_toks (t=0 slot) shifted by the
        # sampled stream: out[g, :, t] holds the token sampled AT decode
        # index t, i.e. generated token t+1; generated token 0 is
        # init_toks[g]. Assemble [n_groups, rpg, max_new].
        gen_toks = jnp.concatenate(
            [init_toks[:, :, None], out[:, :, :max_new - 1]], axis=2)
        return jax.lax.psum(jnp.where(s == n - 1, gen_toks, 0), STAGE_AXIS)

    # --- beam search over the ring -----------------------------------

    def _device_program_beam(self, stage_params, pre_params, post_params,
                             prompt_g, *, p, rpg):
        """Ring-pipelined beam search (deterministic, sum-of-log-probs —
        the single-device ``Generator._generate_beam`` contract over
        stage-sharded weights).

        The pipelined twist is the cache reorder: after stage ``n-1``'s
        top-k for group ``g`` at decode index ``t``, the surviving-beam
        parent indices must reach EVERY stage's cache slab before that
        group's step ``t+1`` — so the parent vector rides the ring with
        the activation carrier (one extra [rpg*k] int32 per hop), and
        each stage gathers its own slab rows by the arriving parents
        right before decoding. The wrap edge carries (token, parent)
        from stage n-1 to stage 0, which needs them exactly one cycle
        later — the same timing argument as the greedy path's token.

        Beams flatten row-major (``flat = row*k + beam``, matching the
        single-device cache tiling); prefill runs untiled (rpg rows) and
        the slabs tile ``rpg -> rpg*k`` once, after the prefill scan.
        """
        m, gen, n = self.model, self.gen_cfg, self.n_stages
        k = gen.num_beams
        max_new = gen.max_new_tokens
        s = jax.lax.axis_index(STAGE_AXIS)
        cd = m.cfg.compute_dtype
        nh, hd = m.block.attn.nhead, m.block.attn.head_dim
        cache_len = p + max_new + p
        sac = p + max_new

        def local_slice(a):
            if isinstance(a, QuantLeaf):
                return QuantLeaf(q=a.q[0], scale=a.scale[0])
            return a[0].astype(cd)

        blocks = [jax.tree_util.tree_map(
                      local_slice, bp,
                      is_leaf=lambda x: isinstance(x, QuantLeaf))
                  for bp in stage_params]
        block_stack = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *blocks)
        lps = len(blocks)
        caches = {"k": jnp.zeros((lps, n, rpg, cache_len, nh, hd), cd),
                  "v": jnp.zeros((lps, n, rpg, cache_len, nh, hd), cd)}

        # ---- prefill: untiled (rpg rows), identical to the greedy path
        # except stage n-1 seeds the beam state instead of sampling
        def pre_cycle(carry, c):
            h_carry, caches, tok0, sc0 = carry
            raw = c - s
            valid = (raw >= 0) & (raw < n)
            grp = jnp.clip(raw, 0, n - 1)
            pos = jnp.where(valid, 0, sac)
            h_embed = m.embed_at(pre_params,
                                 jnp.take(prompt_g, grp, axis=0), 0)
            h_in = jnp.where(s == 0, h_embed, h_carry)
            h_out, caches = self._run_blocks(block_stack, h_in, caches,
                                             grp, pos)
            logits = self._head(post_params, h_out[:, -1:, :])[:, 0, :]
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            sc_g, tok_g = jax.lax.top_k(logp, k)          # [rpg, k]
            emit = (s == n - 1) & valid
            tok0 = jax.lax.dynamic_update_slice(
                tok0, jnp.where(emit, tok_g.astype(jnp.int32),
                                jnp.take(tok0, grp, axis=0))[None],
                (grp, 0, 0))
            sc0 = jax.lax.dynamic_update_slice(
                sc0, jnp.where(emit, sc_g,
                               jnp.take(sc0, grp, axis=0))[None],
                (grp, 0, 0))
            return (self._ring(h_out), caches, tok0, sc0), None

        h0 = jnp.zeros((rpg, p, m.cfg.d_model), cd)
        tok0 = jnp.zeros((n, rpg, k), jnp.int32)
        sc0 = jnp.zeros((n, rpg, k), jnp.float32)
        (_, caches, tok0, sc0), _ = jax.lax.scan(
            pre_cycle, (h0, caches, tok0, sc0), jnp.arange(2 * n - 1))
        tok0 = jax.lax.psum(jnp.where(s == n - 1, tok0, 0), STAGE_AXIS)
        sc0 = jax.lax.psum(jnp.where(s == n - 1, sc0, 0.0), STAGE_AXIS)

        # tile slabs rpg -> rpg*k (flat = row*k + beam)
        tile = jnp.arange(rpg * k) // k
        caches = jax.tree_util.tree_map(
            lambda a: jnp.take(a, tile, axis=2), caches)

        # ---- decode: beams ride the rows; parents ride the ring
        ident = jnp.arange(rpg * k, dtype=jnp.int32) % k   # [rpg*k] beams
        out0 = jnp.zeros((n, rpg, k, max_new), jnp.int32)
        out0 = out0.at[:, :, :, 0].set(tok0)
        scores0 = sc0                                       # [n, rpg, k]

        def dec_cycle(carry, c):
            (h_carry, par_h, tok_ring, par_ring, caches, scores,
             out) = carry
            raw = c - s
            valid = (raw >= 0) & (raw < n * (max_new - 1))
            grp = jnp.mod(raw, n)
            t = jnp.where(valid, raw // n, 0)
            pos = jnp.where(valid, p + t, sac)
            first = (c < n)      # step 0: beams seeded from the prefill
            tok_use = jnp.where(
                first, jnp.take(tok0, grp, axis=0).reshape(rpg * k),
                tok_ring)
            # parent of the beams being decoded this step (identity at
            # step 0 and on invalid cycles — never shuffle a slab whose
            # turn it is not)
            par_in = jnp.where(s == 0, par_ring, par_h)
            parent = jnp.where(first | ~valid, ident, par_in)
            flat_parent = (jnp.arange(rpg * k, dtype=jnp.int32) // k) * k \
                + parent
            # persistent beam reorder of this group's slab
            def slab_gather(a):
                grp_slab = jax.lax.dynamic_slice(
                    a, (0, grp) + (0,) * (a.ndim - 2),
                    (lps, 1) + a.shape[2:])
                reordered = jnp.take(grp_slab, flat_parent, axis=2)
                return jax.lax.dynamic_update_slice(
                    a, reordered, (0, grp) + (0,) * (a.ndim - 2))
            caches = jax.tree_util.tree_map(slab_gather, caches)

            h_embed = m.embed_at(pre_params, tok_use[:, None], pos)
            h_in = jnp.where(s == 0, h_embed, h_carry)
            h_out, caches = self._run_blocks(block_stack, h_in, caches,
                                             grp, pos)
            logits = self._head(post_params, h_out)[:, 0, :]
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            V = logp.shape[-1]
            sc_g = jax.lax.dynamic_slice(scores, (grp, 0, 0),
                                         (1, rpg, k))[0]
            total = sc_g[:, :, None] + logp.reshape(rpg, k, V)
            sc_new, idx = jax.lax.top_k(total.reshape(rpg, k * V), k)
            par_new = (idx // V).astype(jnp.int32)          # [rpg, k]
            tok_new = (idx % V).astype(jnp.int32)
            emit = (s == n - 1) & valid
            scores = jax.lax.dynamic_update_slice(
                scores, jnp.where(emit, sc_new, sc_g)[None], (grp, 0, 0))
            out_g = jax.lax.dynamic_slice(
                out, (grp, 0, 0, 0), (1, rpg, k, max_new))[0]
            out_re = jnp.take_along_axis(out_g, par_new[:, :, None],
                                         axis=1)
            t_write = jnp.where(emit, t + 1, max_new)
            # out-of-range start clamps, so route the garbage write to a
            # full-copy no-op instead: keep out_g when not emitting
            out_wr = jax.lax.dynamic_update_slice(
                out_re, tok_new[:, :, None], (0, 0, t_write))
            out = jax.lax.dynamic_update_slice(
                out, jnp.where(emit, out_wr, out_g)[None], (grp, 0, 0, 0))
            return (self._ring(h_out), self._ring(parent),
                    self._ring(tok_new.reshape(rpg * k)),
                    self._ring(par_new.reshape(rpg * k)),
                    caches, scores, out), None

        h0 = jnp.zeros((rpg * k, 1, m.cfg.d_model), cd)
        cycles = n * (max_new - 1) + n - 1
        carry0 = (h0, ident, jnp.zeros((rpg * k,), jnp.int32), ident,
                  caches, scores0, out0)
        if max_new > 1:
            (_, _, _, _, _, scores, out), _ = jax.lax.scan(
                dec_cycle, carry0, jnp.arange(cycles))
        else:
            scores, out = scores0, out0
        best = jnp.argmax(scores, axis=2)                   # [n, rpg]
        toks = jnp.take_along_axis(
            out, best[:, :, None, None], axis=2)[:, :, 0, :]
        best_sc = jnp.take_along_axis(scores, best[:, :, None],
                                      axis=2)[:, :, 0]
        toks = jax.lax.psum(jnp.where(s == n - 1, toks, 0), STAGE_AXIS)
        best_sc = jax.lax.psum(jnp.where(s == n - 1, best_sc, 0.0),
                               STAGE_AXIS)
        return toks, best_sc

    # --- public ---

    def generate(self, stage_params, pre_params, post_params,
                 prompt: jax.Array,
                 key: Optional[jax.Array] = None) -> jax.Array:
        """Sample ``[b, max_new_tokens]`` continuations of ``prompt
        [b, prompt_len]``; rows ``[g*rpg:(g+1)*rpg]`` form ring group
        ``g``. ``num_beams > 1`` runs ring-pipelined beam search
        (deterministic; ``key`` unused)."""
        if self.gen_cfg.num_beams > 1:
            return self.generate_with_scores(stage_params, pre_params,
                                             post_params, prompt)[0]
        b, p = prompt.shape
        n = self.n_stages
        if b % n:
            raise ValueError(f"batch {b} must divide into {n} ring groups")
        check_positions(self.model, p, self.gen_cfg.max_new_tokens)
        rpg = b // n
        prompt_g = jnp.asarray(prompt, jnp.int32).reshape(n, rpg, p)
        if key is None:
            key = jax.random.key(0)

        cache_key = (p, rpg,
                     jax.tree_util.tree_structure((stage_params, pre_params,
                                                   post_params)))
        reg = get_registry()
        run = self._programs.get(cache_key)
        if run is not None:
            reg.counter("serve.pipelined.program_cache_hits").inc()
        else:
            reg.counter("serve.pipelined.program_cache_misses").inc()
            in_specs = (
                jax.tree_util.tree_map(lambda _: P(STAGE_AXIS),
                                       stage_params),
                jax.tree_util.tree_map(lambda _: P(), pre_params),
                jax.tree_util.tree_map(lambda _: P(), post_params),
                P(), P(),
            )
            run = jax.jit(shard_map(
                functools.partial(self._device_program, p=p, rpg=rpg),
                mesh=self.mesh, in_specs=in_specs, out_specs=P(),
                check_vma=False))
            self._programs[cache_key] = run
        t0 = time.perf_counter()
        out = run(stage_params, pre_params, post_params, prompt_g, key)
        if reg.enabled:
            # Block for an honest wall-clock number; serving callers read
            # the tokens to host right after anyway.
            out = jax.block_until_ready(out)
            dt = time.perf_counter() - t0
            reg.histogram("serve.pipelined.generate_sec").observe(dt)
            tokens = b * self.gen_cfg.max_new_tokens
            reg.counter("serve.pipelined.tokens").inc(tokens)
            if dt > 0:
                reg.gauge("serve.pipelined.tokens_per_sec").set(tokens / dt)
        return out.reshape(b, self.gen_cfg.max_new_tokens)

    def generate_with_lengths(self, stage_params, pre_params, post_params,
                              prompt: jax.Array,
                              key: Optional[jax.Array] = None):
        """``(tokens [b, max_new], lengths [b])`` — the pipelined analogue
        of ``Generator.generate_with_lengths``: lengths run up to and
        including the first EOS (or ``max_new_tokens`` without one)."""
        out = self.generate(stage_params, pre_params, post_params,
                            prompt, key)
        return out, sequence_lengths(out, self.gen_cfg.eos_token_id)

    def generate_with_scores(self, stage_params, pre_params, post_params,
                             prompt: jax.Array):
        """Ring-pipelined beam search returning ``(tokens [b, max_new],
        scores [b])`` — the best beam per row, matching the single-device
        ``Generator.generate_with_scores`` contract."""
        if self.gen_cfg.num_beams < 2:
            raise ValueError("generate_with_scores requires num_beams >= 2")
        b, p = prompt.shape
        n = self.n_stages
        if b % n:
            raise ValueError(f"batch {b} must divide into {n} ring groups")
        check_positions(self.model, p, self.gen_cfg.max_new_tokens)
        rpg = b // n
        prompt_g = jnp.asarray(prompt, jnp.int32).reshape(n, rpg, p)

        cache_key = ("beam", p, rpg,
                     jax.tree_util.tree_structure((stage_params, pre_params,
                                                   post_params)))
        reg = get_registry()
        run = self._programs.get(cache_key)
        if run is not None:
            reg.counter("serve.pipelined.program_cache_hits").inc()
        else:
            reg.counter("serve.pipelined.program_cache_misses").inc()
            in_specs = (
                jax.tree_util.tree_map(lambda _: P(STAGE_AXIS),
                                       stage_params),
                jax.tree_util.tree_map(lambda _: P(), pre_params),
                jax.tree_util.tree_map(lambda _: P(), post_params),
                P(),
            )
            run = jax.jit(shard_map(
                functools.partial(self._device_program_beam, p=p, rpg=rpg),
                mesh=self.mesh, in_specs=in_specs, out_specs=(P(), P()),
                check_vma=False))
            self._programs[cache_key] = run
        t0 = time.perf_counter()
        toks, scores = run(stage_params, pre_params, post_params, prompt_g)
        if reg.enabled:
            toks, scores = jax.block_until_ready((toks, scores))
            dt = time.perf_counter() - t0
            reg.histogram("serve.pipelined.beam_sec").observe(dt)
            tokens = b * self.gen_cfg.max_new_tokens
            reg.counter("serve.pipelined.tokens").inc(tokens)
            if dt > 0:
                reg.gauge("serve.pipelined.tokens_per_sec").set(tokens / dt)
        return (toks.reshape(b, self.gen_cfg.max_new_tokens),
                scores.reshape(b))
