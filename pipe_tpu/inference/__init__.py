"""Inference: KV-cached autoregressive generation over the pipelined LMs."""

from .generate import GenerationConfig, Generator, sample_logits
from .pipelined import PipelinedGenerator

__all__ = ["GenerationConfig", "Generator", "PipelinedGenerator",
           "sample_logits"]
