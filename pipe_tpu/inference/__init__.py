"""Inference: KV-cached autoregressive generation over the pipelined LMs."""

from .generate import GenerationConfig, Generator, sample_logits
from .long_context import ContextShardedGenerator
from .pipelined import PipelinedGenerator
from .quant import QuantLeaf, dequant_tree, quantize_params
from .tp import TPShardedGenerator

__all__ = ["GenerationConfig", "Generator", "PipelinedGenerator",
           "ContextShardedGenerator", "TPShardedGenerator", "QuantLeaf",
           "quantize_params", "dequant_tree", "sample_logits"]
