"""Tensor-parallel decoding: serve Megatron-sharded weights as trained.

A model whose blocks shard over the ``model`` mesh axis (heads + FFN
columns, ``ops/tp_layers.py``) decodes with the SAME split: each device
projects q/k/v for its local heads, keeps a head-sharded KV cache (cache
memory divides by tp like the weights), attends locally, and the block's
two psums (attention output projection, FFN second matmul) are the only
per-layer communication — identical structure to the training forward,
so serving needs no weight conversion and no resharding.

Implementation: :class:`TPShardedGenerator` subclasses the single-device
:class:`~.generate.Generator` — the inherited prefill/decode program runs
unchanged as the shard_map device program (``tp_block_decode`` binds the
model axis inside); only cache creation (local head count) and the jit
wrapping (per-leaf PartitionSpecs from ``tp_block_specs``) differ.

``tests/test_tp_gen.py`` pins greedy tp=2/tp=4 output token-for-token
against the unsharded (``tp_axis=None``) model on the same weights;
``tests/test_moe_gen.py`` does the same for the MoE family (experts +
heads sharded — ``moe_block_decode`` routes per-token, so the dense
dispatch works unchanged at q=1).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..obs.telemetry import get_registry
from ..parallel.mesh import MODEL_AXIS
from .generate import GenerationConfig, Generator, check_positions
from ..utils.compat import shard_map

__all__ = ["TPShardedGenerator"]


class TPShardedGenerator(Generator):
    """KV-cached decoding over model-axis-sharded weights.

    Works for any LM whose block exposes ``tp_axis=MODEL_AXIS``, a
    cache-aware ``decode``, and whose model provides ``stage_param_specs``
    (per-leaf PartitionSpecs) — :class:`TPPipelinedLM` (Megatron split)
    and :class:`~..models.moe_lm.MoEPipelinedLM` (experts + heads
    sharded). Params are ``model.init``'s full trees — the per-leaf specs
    shard them on entry.

    Beam search works over the sharded weights too: the beam machinery is
    layout-agnostic — log-probs come off the (replicated) vocab head
    after each block's psum, so ``top_k``/parent selection compute
    identically on every model shard, and the per-step KV-cache reorder
    gathers on the BATCH axis, which the head-sharded caches keep whole.
    """

    def __init__(self, mesh: Mesh, model,
                 gen_cfg: GenerationConfig = GenerationConfig()):
        if MODEL_AXIS not in mesh.axis_names:
            raise ValueError(f"mesh must have a {MODEL_AXIS!r} axis")
        if getattr(model.block, "tp_axis", None) != MODEL_AXIS:
            raise ValueError(
                "TPShardedGenerator needs a model built with "
                f"tp_axis={MODEL_AXIS!r} (got "
                f"{getattr(model.block, 'tp_axis', None)!r})")
        super().__init__(model, gen_cfg)
        self.mesh = mesh
        self.tp = mesh.shape[MODEL_AXIS]
        if model.cfg.nhead % self.tp:
            raise ValueError(f"nhead={model.cfg.nhead} must divide over "
                             f"tp={self.tp}")
        self._programs = {}

    def _make_caches(self, blocks, batch, max_len):
        """Caches sized by the LOCAL head shard (blocks arrive inside
        shard_map with their model-axis slices)."""
        cd = self.model.cfg.compute_dtype
        caches = []
        for bp in blocks:
            h_local, hd = bp["wqkv"].shape[2], bp["wqkv"].shape[3]
            shape = (batch, max_len, h_local, hd)
            caches.append({"k": jnp.zeros(shape, cd),
                           "v": jnp.zeros(shape, cd)})
        return caches

    def _sharded_program(self, params, prompt, *, beam: bool):
        """Build (or fetch) the jitted shard_map program: greedy/sampling
        (``_generate``, keyed) or beam (``_generate_beam``, deterministic,
        two replicated outputs)."""
        stage_params, pre_params, post_params = params
        cache_key = (beam, prompt.shape,
                     jax.tree_util.tree_structure(params))
        run = self._programs.get(cache_key)
        if run is not None:
            get_registry().counter("serve.tp.program_cache_hits").inc()
            return run
        get_registry().counter("serve.tp.program_cache_misses").inc()
        stage_specs = [self.model.stage_param_specs()
                       for _ in stage_params]
        in_specs = (
            stage_specs,
            jax.tree_util.tree_map(lambda _: P(), pre_params),
            jax.tree_util.tree_map(lambda _: P(), post_params),
            P(),
        )
        if beam:
            run = jax.jit(shard_map(
                lambda sp, pre, post, pr: self._generate_beam(
                    (sp, pre, post), pr),
                mesh=self.mesh, in_specs=in_specs, out_specs=(P(), P()),
                check_vma=False))
        else:
            run = jax.jit(shard_map(
                lambda sp, pre, post, pr, k: self._generate(
                    (sp, pre, post), pr, k),
                mesh=self.mesh, in_specs=in_specs + (P(),),
                out_specs=P(), check_vma=False))
        self._programs[cache_key] = run
        return run

    def generate(self, params, prompt: jax.Array,
                 key: Optional[jax.Array] = None) -> jax.Array:
        """Sample ``[b, max_new_tokens]`` continuations with the weights
        sharded over the model axis. ``num_beams > 1`` runs beam search
        (deterministic; ``key`` unused)."""
        check_positions(self.model, prompt.shape[1],
                        self.gen_cfg.max_new_tokens)
        if self.gen_cfg.num_beams > 1:
            return self.generate_with_scores(params, prompt)[0]
        if key is None:
            key = jax.random.key(0)
        run = self._sharded_program(params, prompt, beam=False)
        return run(params[0], params[1], params[2],
                   jnp.asarray(prompt, jnp.int32), key)

    def generate_with_scores(self, params, prompt):
        """Beam search over the sharded weights: ``(tokens, scores)``,
        token-for-token equal to the single-device Generator's."""
        if self.gen_cfg.num_beams < 2:
            raise ValueError("generate_with_scores requires num_beams >= 2")
        check_positions(self.model, prompt.shape[1],
                        self.gen_cfg.max_new_tokens)
        run = self._sharded_program(params, prompt, beam=True)
        return run(params[0], params[1], params[2],
                   jnp.asarray(prompt, jnp.int32))
