"""Weight-only int8 quantization for KV-cached decoding.

Single-sequence decode is weight-bandwidth-bound: every generated token
re-reads every parameter once (the 520M tutorial model measures ~2.6 ms/
token at batch 1 — the HBM roofline on ~1 GB of bf16 weights,
``GEN_BENCH_r03.jsonl``). Halving the bytes halves that floor: block
weights quantize to int8 with one float32 scale per output channel
(absmax symmetric), and the dequantize (`q * scale`) happens INSIDE the
compiled decode step, where XLA fuses it into the matmul's operand read —
HBM traffic is int8-sized, the MXU still sees bf16/f32 operands.

Scope and honesty: weight-only (activations and KV caches stay in the
compute dtype), inference-only, symmetric per-channel — the standard
first rung of the quantization ladder. Per-channel absmax keeps the
worst-case relative weight error ~0.4%; the accuracy contract (trained
tiny model: teacher-forced logits within tolerance, top-1 next-token
agreement) is pinned in ``tests/test_quant.py``, and the throughput claim
is measured on the real chip (``tools/gen_bench.py --int8``).

Mechanics: :func:`quantize_params` maps every quantizable 2-D weight leaf
to a :class:`QuantLeaf` pytree node (int8 codes + f32 scales) in the SAME
tree structure; the generators call :func:`dequant_tree` on each block's
params at use time (identity on unquantized leaves), so the layer code
never knows quantization exists.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

__all__ = ["QuantLeaf", "quantize_params", "dequant_tree",
           "quantize_kv_rows"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QuantLeaf:
    """int8 codes + grouped float32 scales for one weight (see
    :func:`_quantize_leaf` for the exact grouping per rank)."""

    q: jax.Array        # int8, original shape
    scale: jax.Array    # f32, shape [..., 1] broadcastable over axis -2

    def dequant(self, dtype=jnp.bfloat16):
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)


def _quantize_leaf(w: jax.Array) -> QuantLeaf:
    """Symmetric absmax int8, one scale per axis(-2) group.

    For the 2-D ``[d_in, d_out]`` weights of the standard model families
    axis -2 IS the contraction axis, so this is exact per-output-channel
    absmax and the ~0.4% relative-error argument in the module docstring
    applies. For higher-rank leaves (e.g. TP's ``wqkv [d, 3, heads, hd]``,
    where axis -2 is the *head* axis) the grouping is whatever axis -2
    happens to be — dequantization is exact regardless (the scale is
    stored and multiplied back), but the per-channel accuracy bound does
    NOT transfer to those layouts; measure before serving a quantized
    >2-D-weight model."""
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QuantLeaf(q=q, scale=scale)


def quantize_params(stage_params) -> Any:
    """Quantize every >=2-D weight leaf of the (per-stage) block trees.

    Input is the ``stage_params`` list from ``model.init`` (or any block
    pytree); 1-D leaves (biases, LayerNorm params) stay float — and
    embeddings sit in pre/post params, untouched, since they are gathered
    rather than matmul'd. The returned tree has the same
    structure with weights replaced by :class:`QuantLeaf` nodes — feed it
    to the generators in place of the original stage params.
    """
    def one(leaf):
        if isinstance(leaf, (jax.Array, jnp.ndarray)) and leaf.ndim >= 2:
            return _quantize_leaf(leaf)
        return leaf

    return jax.tree_util.tree_map(one, stage_params)


def quantize_kv_rows(rows: jax.Array):
    """Symmetric absmax int8 over the last axis — one f32 scale per
    ``[..., head_dim]`` vector. The KV-block analog of
    :func:`_quantize_leaf`, used by the paged pool (``serve/kvpool.py``)
    to quantize rows on scatter; the matching dequant happens inside the
    gathered attention read. Per-row per-head scales keep the relative
    error bound of the weight path; the accuracy contract is tolerance
    (``tests/test_kvpool.py``), NOT the engine's bitwise pin."""
    r32 = rows.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(r32), axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(r32 / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequant_tree(params, dtype=jnp.bfloat16):
    """Materialize bf16 weights from QuantLeaf nodes (identity on plain
    arrays). Called inside the compiled step so XLA fuses the dequant
    into the consuming matmul's operand read."""
    return jax.tree_util.tree_map(
        lambda x: x.dequant(dtype) if isinstance(x, QuantLeaf) else x,
        params, is_leaf=lambda x: isinstance(x, QuantLeaf))
