"""Long-context decoding: the prompt's KV cache stays context-sharded.

The long-context training story (ring attention over a ``context`` mesh
axis, ``ops/ring_attention.py``) has an inference counterpart: a prompt
too long for one chip's HBM must be PREFILLED sharded — and then its KV
cache IS the sharded object, so decode must attend across shards. This
module implements exactly that:

* **prefill**: each context device embeds its sequence shard (global
  position offsets), runs the blocks with ``ring_attention`` for the
  attention output (exact, block-sized peak memory), and keeps its LOCAL
  K/V rows as the prompt cache — no gather, each device permanently owns
  ``1/n_context`` of the prompt cache;
* **decode**: the new token's query is tiny, so it replicates; every
  device computes a streaming-softmax PARTIAL (numerator, normalizer,
  running max) over its prompt-cache shard, device 0 adds the partial
  over the (short, replicated) decode-time cache, and one
  ``pmax``/``psum`` pair merges the partials — the distributed
  flash-attention combine. Everything else (FFN, LN, head, sampling) is
  replicated compute on a [b, 1, d] activation: negligible next to the
  sharded cache read, and it keeps the program free of host round-trips.

Memory: per device, prompt cache = ``prompt/n_context`` rows + decode
cache = ``max_new`` rows. The decode-time traffic is one tiny
collective per layer per token over ICI.

``tests/test_long_context_gen.py`` pins greedy output token-for-token
against the single-device :class:`~.generate.Generator` on the SAME
weights (the two programs share parameter trees via ``PipelinedLM.init``).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.long_context_lm import ContextParallelLM
from ..parallel.mesh import CONTEXT_AXIS
from .generate import GenerationConfig, check_positions, sample_logits
from .quant import dequant_tree
from ..utils.compat import shard_map

__all__ = ["ContextShardedGenerator"]


def _partial_attend(q, k, v, mask, scale):
    """Streaming-softmax partial of ``q`` over masked keys.

    q: [b, 1, h, hd]; k/v: [b, S, h, hd]; mask: [S] bool (which rows are
    live). Returns (o [b,1,h,hd] f32, m [b,h,1] f32, l [b,h,1] f32) — an
    UNnormalized numerator with its own max and normalizer, mergeable with
    other partials by the usual flash combine.
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    logits = jnp.where(mask[None, None, None, :], logits,
                       jnp.asarray(-jnp.inf, logits.dtype))
    m = jnp.max(logits, axis=-1)                     # [b, h, 1]
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(logits - safe_m[..., None])
    p = jnp.where(jnp.isfinite(logits), p, 0.0)
    l = jnp.sum(p, axis=-1)                          # [b, h, 1]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(
        jnp.float32)
    return o, m, l


def _merge_partials(parts):
    """Merge [(o, m, l), ...] partials locally (flash combine)."""
    o, m, l = parts[0]
    for o2, m2, l2 in parts[1:]:
        new_m = jnp.maximum(m, m2)
        safe = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        a1 = jnp.where(jnp.isfinite(m), jnp.exp(m - safe), 0.0)
        a2 = jnp.where(jnp.isfinite(m2), jnp.exp(m2 - safe), 0.0)
        o = o * a1.transpose(0, 2, 1)[..., None] \
            + o2 * a2.transpose(0, 2, 1)[..., None]
        l = l * a1 + l2 * a2
        m = new_m
    return o, m, l


def _global_combine(o, m, l, axis):
    """psum/pmax the partials over the context axis and normalize."""
    M = jax.lax.pmax(m, axis)
    safe = jnp.where(jnp.isfinite(M), M, 0.0)
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - safe), 0.0)
    num = jax.lax.psum(o * alpha.transpose(0, 2, 1)[..., None], axis)
    den = jax.lax.psum(l * alpha, axis)
    return num / jnp.maximum(den, 1e-30).transpose(0, 2, 1)[..., None]


class ContextShardedGenerator:
    """KV-cached decoding with the prompt cache sharded over ``context``.

    ``model`` is a :class:`ContextParallelLM`; params come from
    ``model.init`` (identical trees to the single-device LM — serve what
    you trained). The prompt length must divide by the context-axis size.
    """

    def __init__(self, mesh: Mesh, model: ContextParallelLM,
                 gen_cfg: GenerationConfig = GenerationConfig()):
        if CONTEXT_AXIS not in mesh.axis_names:
            raise ValueError(f"mesh must have a {CONTEXT_AXIS!r} axis")
        self.mesh = mesh
        self.model = model
        self.gen_cfg = gen_cfg
        self.n_ctx = mesh.shape[CONTEXT_AXIS]
        self._programs = {}

    # --- per-layer math (mirrors ContextParallelLM._block exactly) ---

    def _proj(self, bp, h):
        cfg = self.model.cfg
        rows, s, d = h.shape
        hd = d // cfg.nhead

        def one(w, b):
            return (jnp.einsum("bsd,de->bse", h, w) + b).reshape(
                rows, s, cfg.nhead, hd)

        a = bp["attn"]
        return (one(a["wq"], a["bq"]), one(a["wk"], a["bk"]),
                one(a["wv"], a["bv"]))

    def _post_attn(self, bp, h, a):
        L = self.model._layers
        rows, s, d = h.shape
        a = a.reshape(rows, s, d)
        a = jnp.einsum("bsd,de->bse", a, bp["attn"]["wo"]) + bp["attn"]["bo"]
        x = L["ln"].apply(bp["ln1"], h + a)
        f = jax.nn.relu(L["ff1"].apply(bp["ff1"], x))
        f = L["ff2"].apply(bp["ff2"], f)
        return L["ln"].apply(bp["ln2"], x + f)

    # --- device program ---

    def _device_program(self, stage_params, pre_params, post_params,
                        prompt, key, *, s_local):
        m, gen = self.model, self.gen_cfg
        cfg = m.cfg
        n = self.n_ctx
        cd = cfg.compute_dtype
        max_new = gen.max_new_tokens
        idx = jax.lax.axis_index(CONTEXT_AXIS)
        nh, hd = cfg.nhead, cfg.d_model // cfg.nhead
        scale = 1.0 / math.sqrt(hd)
        b = prompt.shape[0]
        s_global = s_local * n

        from .quant import QuantLeaf
        blocks = [jax.tree_util.tree_map(
                      lambda p: p if isinstance(p, QuantLeaf)
                      else p.astype(cd),
                      bp, is_leaf=lambda x: isinstance(x, QuantLeaf))
                  for stage in stage_params for bp in stage]
        L = len(blocks)

        # ---- prefill: ring attention for outputs, local K/V kept as the
        # permanently-sharded prompt cache
        from ..ops.ring_attention import ring_attention
        h = m.pre_fn(pre_params, prompt, None)
        pk = jnp.zeros((L, b, s_local, nh, hd), cd)
        pv = jnp.zeros((L, b, s_local, nh, hd), cd)
        for l, bp in enumerate(blocks):
            bp = dequant_tree(bp, cd)
            q, k, v = self._proj(bp, h)
            a = ring_attention(q, k, v, CONTEXT_AXIS, causal=cfg.causal)
            pk = pk.at[l].set(k.astype(cd))
            pv = pv.at[l].set(v.astype(cd))
            h = self._post_attn(bp, h, a)
        # first token: logits of the LAST global position (device n-1)
        logits = self._head(post_params, h[:, -1:, :])[:, 0, :]
        key, sub = jax.random.split(key)
        tok = sample_logits(logits, sub, gen)
        tok = jax.lax.psum(jnp.where(idx == n - 1, tok, 0), CONTEXT_AXIS)

        # ---- decode: replicated q, sharded prompt cache, replicated
        # decode cache (device 0 owns its attention contribution)
        dk0 = jnp.zeros((L, b, max_new, nh, hd), cd)
        dv0 = jnp.zeros((L, b, max_new, nh, hd), cd)
        block_stack = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *blocks)
        prompt_mask = jnp.ones((s_local,), bool)

        def step(carry, t):
            dk, dv, tok, key = carry
            pos = s_global + t
            h = m._posenc(
                m._layers["embed"].apply(pre_params["embed"], tok[:, None]),
                pos).astype(cd)

            def layer(h_c, inp):
                bp, pkl, pvl, dkl, dvl = inp
                bp = dequant_tree(bp, cd)
                q, k, v = self._proj(bp, h_c)
                dkl = jax.lax.dynamic_update_slice(
                    dkl, k.astype(cd), (0, t, 0, 0))
                dvl = jax.lax.dynamic_update_slice(
                    dvl, v.astype(cd), (0, t, 0, 0))
                p_prompt = _partial_attend(q, pkl, pvl, prompt_mask, scale)
                dec_mask = (jnp.arange(max_new) <= t) & (idx == 0)
                p_dec = _partial_attend(q, dkl, dvl, dec_mask, scale)
                o, mm, ll = _merge_partials([p_prompt, p_dec])
                a = _global_combine(o, mm, ll, CONTEXT_AXIS).astype(cd)
                return self._post_attn(bp, h_c, a), (dkl, dvl)

            h, (dk, dv) = jax.lax.scan(layer, h,
                                       (block_stack, pk, pv, dk, dv))
            logits = self._head(post_params, h)[:, 0, :]
            key, sub = jax.random.split(key)
            nxt = sample_logits(logits, sub, gen)
            return (dk, dv, nxt, key), tok

        (_, _, last, _), toks = jax.lax.scan(
            step, (dk0, dv0, tok, key), jnp.arange(max_new - 1))
        out = jnp.moveaxis(toks, 0, 1)
        return jnp.concatenate([out, last[:, None]], axis=1)

    def _head(self, post_params, h):
        w = post_params["decoder"]["w"]
        bb = post_params["decoder"]["b"]
        return (jnp.einsum("bsd,dv->bsv", h.astype(jnp.float32),
                           w.astype(jnp.float32)) + bb)

    # --- beam search over the sharded prompt cache -------------------

    def _device_program_beam(self, stage_params, pre_params, post_params,
                             prompt, *, s_local):
        """Context-sharded beam search (deterministic, sum-of-log-probs
        — the single-device ``Generator._generate_beam`` contract).

        The TPU-native trick: beams of one row share the prompt, so the
        (large, context-sharded) prompt cache needs NO per-beam tiling
        and NO per-step reorder — the ``k`` beam queries ride
        ``_partial_attend``'s query axis against the SAME shard (each
        query attends all masked keys independently; there is no
        intra-query coupling to break). Only the (short, replicated)
        decode-time cache tiles to ``b*k`` rows and gathers by parent
        each step, exactly like the single-device beam. Beams flatten
        row-major (``flat = row*k + beam``).
        """
        m, gen = self.model, self.gen_cfg
        cfg = m.cfg
        k = gen.num_beams
        n = self.n_ctx
        cd = cfg.compute_dtype
        max_new = gen.max_new_tokens
        idx = jax.lax.axis_index(CONTEXT_AXIS)
        nh, hd = cfg.nhead, cfg.d_model // cfg.nhead
        scale = 1.0 / math.sqrt(hd)
        b = prompt.shape[0]
        s_global = s_local * n

        from .quant import QuantLeaf
        blocks = [jax.tree_util.tree_map(
                      lambda p: p if isinstance(p, QuantLeaf)
                      else p.astype(cd),
                      bp, is_leaf=lambda x: isinstance(x, QuantLeaf))
                  for stage in stage_params for bp in stage]
        L = len(blocks)

        # ---- prefill: identical to the greedy path (untiled rows)
        from ..ops.ring_attention import ring_attention
        h = m.pre_fn(pre_params, prompt, None)
        pk = jnp.zeros((L, b, s_local, nh, hd), cd)
        pv = jnp.zeros((L, b, s_local, nh, hd), cd)
        for l, bp in enumerate(blocks):
            bp = dequant_tree(bp, cd)
            q, kk, vv = self._proj(bp, h)
            a = ring_attention(q, kk, vv, CONTEXT_AXIS, causal=cfg.causal)
            pk = pk.at[l].set(kk.astype(cd))
            pv = pv.at[l].set(vv.astype(cd))
            h = self._post_attn(bp, h, a)
        # beam seed: logits of the LAST global position (device n-1)
        logits = self._head(post_params, h[:, -1:, :])[:, 0, :]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        sc0, tok0 = jax.lax.top_k(logp, k)                # [b, k]
        tok0 = tok0.astype(jnp.int32)
        sc0 = jax.lax.psum(jnp.where(idx == n - 1, sc0, 0.0), CONTEXT_AXIS)
        tok0 = jax.lax.psum(jnp.where(idx == n - 1, tok0, 0), CONTEXT_AXIS)

        # ---- decode: beams on the rows; prompt cache untiled
        dk0 = jnp.zeros((L, b * k, max_new, nh, hd), cd)
        dv0 = jnp.zeros((L, b * k, max_new, nh, hd), cd)
        block_stack = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *blocks)
        prompt_mask = jnp.ones((s_local,), bool)
        out0 = jnp.zeros((b, k, max_new), jnp.int32)
        out0 = out0.at[:, :, 0].set(tok0)

        def step(carry, t):
            dk, dv, scores, tok, out = carry
            pos = s_global + t
            h = m._posenc(
                m._layers["embed"].apply(pre_params["embed"],
                                         tok.reshape(b * k)[:, None]),
                pos).astype(cd)

            def layer(h_c, inp):
                bp, pkl, pvl, dkl, dvl = inp
                bp = dequant_tree(bp, cd)
                q, kk, vv = self._proj(bp, h_c)       # q: [b*k, 1, nh, hd]
                dkl = jax.lax.dynamic_update_slice(
                    dkl, kk.astype(cd), (0, t, 0, 0))
                dvl = jax.lax.dynamic_update_slice(
                    dvl, vv.astype(cd), (0, t, 0, 0))
                # prompt partial: beams ride the query axis of the shared
                # (untiled) shard — o [b, k, nh, hd], m/l [b, nh, k]
                qp = q.reshape(b, k, nh, hd)
                o_p, m_p, l_p = _partial_attend(qp, pkl, pvl, prompt_mask,
                                                scale)
                p_prompt = (o_p.reshape(b * k, 1, nh, hd),
                            m_p.transpose(0, 2, 1).reshape(b * k, nh, 1),
                            l_p.transpose(0, 2, 1).reshape(b * k, nh, 1))
                dec_mask = (jnp.arange(max_new) <= t) & (idx == 0)
                p_dec = _partial_attend(q, dkl, dvl, dec_mask, scale)
                o, mm, ll = _merge_partials([p_prompt, p_dec])
                a = _global_combine(o, mm, ll, CONTEXT_AXIS).astype(cd)
                return self._post_attn(bp, h_c, a), (dkl, dvl)

            h, (dk, dv) = jax.lax.scan(layer, h,
                                       (block_stack, pk, pv, dk, dv))
            logits = self._head(post_params, h)[:, 0, :]   # [b*k, V]
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            V = logp.shape[-1]
            total = scores[:, :, None] + logp.reshape(b, k, V)
            scores, top = jax.lax.top_k(total.reshape(b, k * V), k)
            parent = (top // V).astype(jnp.int32)          # [b, k]
            tok = (top % V).astype(jnp.int32)
            flat_parent = (jnp.arange(b)[:, None] * k + parent).reshape(-1)
            dk = jnp.take(dk, flat_parent, axis=1)
            dv = jnp.take(dv, flat_parent, axis=1)
            out = jnp.take_along_axis(out, parent[:, :, None], axis=1)
            out = jax.lax.dynamic_update_slice(
                out, tok[:, :, None], (0, 0, t + 1))
            return (dk, dv, scores, tok, out), None

        if max_new > 1:
            (_, _, scores, _, out), _ = jax.lax.scan(
                step, (dk0, dv0, sc0, tok0, out0),
                jnp.arange(max_new - 1))
        else:
            scores, out = sc0, out0
        best = jnp.argmax(scores, axis=1)
        toks = jnp.take_along_axis(
            out, best[:, None, None], axis=1)[:, 0, :]
        best_sc = jnp.take_along_axis(scores, best[:, None], axis=1)[:, 0]
        return toks, best_sc

    # --- public ---

    def generate(self, params, prompt: jax.Array,
                 key: Optional[jax.Array] = None) -> jax.Array:
        """Sample ``[b, max_new_tokens]`` continuations; ``prompt
        [b, s_global]`` is context-sharded on entry (s_global divisible by
        the context-axis size). ``num_beams > 1`` runs context-sharded
        beam search (deterministic; ``key`` unused)."""
        if self.gen_cfg.num_beams > 1:
            return self.generate_with_scores(params, prompt)[0]
        stage_params, pre_params, post_params = params
        b, s_global = prompt.shape
        n = self.n_ctx
        if s_global % n:
            raise ValueError(
                f"prompt length {s_global} must divide over {n} context "
                f"shards")
        check_positions(self.model, s_global, self.gen_cfg.max_new_tokens)
        if key is None:
            key = jax.random.key(0)
        s_local = s_global // n

        cache_key = (b, s_local,
                     jax.tree_util.tree_structure(params))
        run = self._programs.get(cache_key)
        if run is None:
            in_specs = (
                jax.tree_util.tree_map(lambda _: P(), stage_params),
                jax.tree_util.tree_map(lambda _: P(), pre_params),
                jax.tree_util.tree_map(lambda _: P(), post_params),
                P(None, CONTEXT_AXIS),   # prompt: sequence-sharded
                P(),
            )
            run = jax.jit(shard_map(
                functools.partial(self._device_program, s_local=s_local),
                mesh=self.mesh, in_specs=in_specs, out_specs=P(),
                check_vma=False))
            self._programs[cache_key] = run
        out = run(stage_params, pre_params, post_params,
                  jnp.asarray(prompt, jnp.int32), key)
        return out

    def generate_with_scores(self, params, prompt: jax.Array):
        """Context-sharded beam search returning ``(tokens [b, max_new],
        scores [b])`` — the best beam per row, matching the single-device
        ``Generator.generate_with_scores`` contract."""
        if self.gen_cfg.num_beams < 2:
            raise ValueError("generate_with_scores requires num_beams >= 2")
        stage_params, pre_params, post_params = params
        b, s_global = prompt.shape
        n = self.n_ctx
        if s_global % n:
            raise ValueError(
                f"prompt length {s_global} must divide over {n} context "
                f"shards")
        check_positions(self.model, s_global, self.gen_cfg.max_new_tokens)
        s_local = s_global // n

        cache_key = ("beam", b, s_local,
                     jax.tree_util.tree_structure(params))
        run = self._programs.get(cache_key)
        if run is None:
            in_specs = (
                jax.tree_util.tree_map(lambda _: P(), stage_params),
                jax.tree_util.tree_map(lambda _: P(), pre_params),
                jax.tree_util.tree_map(lambda _: P(), post_params),
                P(None, CONTEXT_AXIS),   # prompt: sequence-sharded
            )
            run = jax.jit(shard_map(
                functools.partial(self._device_program_beam,
                                  s_local=s_local),
                mesh=self.mesh, in_specs=in_specs, out_specs=(P(), P()),
                check_vma=False))
            self._programs[cache_key] = run
        return run(stage_params, pre_params, post_params,
                   jnp.asarray(prompt, jnp.int32))
