"""Executors: serial emulator and SPMD shard_map pipeline."""
