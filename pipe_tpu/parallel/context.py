"""Context (sequence) parallelism: shard the sequence axis over the mesh.

The long-context execution layer (SURVEY §5): a ``context`` mesh axis carries
ring attention (``ops.ring_attention``) so sequences longer than one chip's
HBM run exactly, with K/V blocks riding the same ``ppermute``/ICI transport
as the pipeline. Composes with the ``(stage, data)`` mesh — context is just
another named axis.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.ring_attention import ring_attention
from ..utils.compat import shard_map

__all__ = ["CONTEXT_AXIS", "make_context_mesh", "context_parallel_attention"]

CONTEXT_AXIS = "context"


def make_context_mesh(n_context: int,
                      devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-D mesh over the context axis."""
    devices = list(devices if devices is not None else jax.devices())
    if n_context <= 0 or n_context > len(devices):
        raise ValueError(f"need {n_context} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:n_context]), (CONTEXT_AXIS,))


def context_parallel_attention(mesh: Mesh, q: jax.Array, k: jax.Array,
                               v: jax.Array, *, causal: bool = True,
                               axis: str = CONTEXT_AXIS,
                               impl: str = "ring") -> jax.Array:
    """Exact attention over globally ``[batch, seq, heads, head_dim]`` inputs
    with ``seq`` sharded over ``axis``; returns the same-sharded output.

    ``impl='ring'`` rotates K/V blocks over the axis (block-sized peak
    memory, any head count); ``impl='ulysses'`` all-to-all-reshards to full
    sequence x heads/c per device (lets the flash kernel run unsharded;
    needs ``heads % axis_size == 0``). Both are exact — see
    ``ops.ulysses_attention`` for the trade-offs.
    """
    if impl == "ring":
        body = partial(ring_attention, axis_name=axis, causal=causal)
    elif impl == "ulysses":
        from ..ops.ulysses_attention import ulysses_attention
        body = partial(ulysses_attention, axis_name=axis, causal=causal)
    else:
        raise ValueError(f"impl must be ring|ulysses, got {impl!r}")
    spec = P(None, axis, None, None)
    fn = shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
