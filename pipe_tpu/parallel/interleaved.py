"""Interleaved virtual-stage pipeline: bubble shrinks by the interleave depth.

The schedule capability behind BASELINE config #4 (interleaved 1F1B-style
placement). Each of ``d`` devices hosts ``v`` non-contiguous *virtual* stages
(Megatron assignment: virtual stage ``s`` lives on device ``s % d``), so the
fill/drain bubble is ``(d-1)/(m·v + d-1)`` — ``~v×`` smaller than GPipe's
``(d-1)/(m + d-1)`` at equal per-device work.

SPMD realization (one compiled program, same transport as ``spmd.py``):

* device ``p`` at cycle ``c`` runs task ``k = c - p`` of its private work
  queue — group ``g = k // m``, micro-batch ``i = k % m``, virtual stage
  ``s = g·d + p``; every device is busy every cycle between its fill and
  drain, ``m·v + d - 1`` cycles total;
* stage outputs shift one hop (+1 ring, ``lax.ppermute``) every cycle; the
  wraparound edge ``d-1 → 0`` *is* the jump to the next group, and arriving
  activations wait in a per-micro-batch slot buffer (an activation for
  micro-batch ``i`` is always consumed before its next-group replacement
  arrives, which requires ``m ≥ d`` — the standard interleaved-schedule
  constraint);
* backward and remat follow ``spmd.py``: AD reverses the ring, remat is a
  static per-mode ``jax.checkpoint`` of the stage body.

Parameter layout: :func:`stack_interleaved_params` permutes the ``S = v·d``
per-virtual-stage pytrees device-major, so the plain ``P(stage)`` sharding of
the leading axis gives device ``p`` exactly its groups ``g·d + p``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.partition import StageCtx
from ..core.remat import checkpoint_stop, validate_mode
from .mesh import DATA_AXIS, STAGE_AXIS
from ..utils.rng import make_key
from ..utils.compat import shard_map

__all__ = ["InterleavedSpmdPipeline", "stack_interleaved_params",
           "unstack_interleaved_params"]


def stack_interleaved_params(params_per_virtual_stage, n_devices: int):
    """Stack S=v·d same-structure pytrees device-major on a leading axis.

    Global row ``p·v + g`` holds virtual stage ``g·d + p``, so sharding the
    leading axis over ``stage`` hands device ``p`` rows ``[p·v, (p+1)·v)`` =
    its interleave groups in order.
    """
    S = len(params_per_virtual_stage)
    if S % n_devices:
        raise ValueError(f"{S} virtual stages not divisible by "
                         f"{n_devices} devices")
    v = S // n_devices
    order = [g * n_devices + p for p in range(n_devices) for g in range(v)]
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack([leaves[s] for s in order], axis=0),
        *params_per_virtual_stage)


def unstack_interleaved_params(stacked, n_devices: int):
    """Inverse of :func:`stack_interleaved_params`: a per-virtual-stage
    list in TRUE virtual-stage order (virtual stage ``g·d + p`` lives at
    stacked row ``p·v + g``). Keeps the permutation convention in this
    module — serving consumers must not re-derive it."""
    S = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    if S % n_devices:
        raise ValueError(f"{S} stacked rows not divisible by "
                         f"{n_devices} devices")
    v = S // n_devices
    return [jax.tree_util.tree_map(
                lambda a: a[(vs % n_devices) * v + vs // n_devices], stacked)
            for vs in range(S)]


@dataclasses.dataclass
class InterleavedSpmdPipeline:
    """Compiled interleaved pipeline over a ``(stage[, data])`` mesh.

    Same contract as :class:`~pipe_tpu.parallel.spmd.SpmdPipeline` (pre_fn on
    virtual stage 0, post_fn on virtual stage S-1, homogeneous ring-invariant
    stage body), plus ``v`` = interleave depth.
    """

    mesh: Any
    stage_fn: Callable
    v: int = 2
    pre_fn: Optional[Callable] = None
    post_fn: Optional[Callable] = None
    post_with_batch: bool = False
    checkpoint: str = "never"
    remat_policy: Any = None

    def __post_init__(self):
        validate_mode(self.checkpoint)
        if STAGE_AXIS not in self.mesh.axis_names:
            raise ValueError(f"mesh must have a {STAGE_AXIS!r} axis")
        if self.v < 1:
            raise ValueError("interleave depth v must be >= 1")
        self.n_devices = self.mesh.shape[STAGE_AXIS]
        self.has_data_axis = DATA_AXIS in self.mesh.axis_names
        # see spmd.SpmdPipeline.bn_axis
        self.bn_axis = (DATA_AXIS if self.has_data_axis
                        and self.mesh.shape[DATA_AXIS] > 1 else None)
        self._pre = self.pre_fn or (lambda p, x, ctx: x)
        if self.post_fn is None:
            self._post = lambda p, h, x_mb, ctx: h
        elif self.post_with_batch:
            self._post = self.post_fn
        else:
            self._post = lambda p, h, x_mb, ctx: self.post_fn(p, h, ctx)

    # -----------------------------------------------------------------
    def memory_plan(self, m: int) -> dict:
        """Static per-device buffer counts — the memory story, inspectable.

        The bubble/v win is bought with O(m) per-device buffers: every
        micro-batch needs an activation slot because each device revisits it
        once per interleave group (plus AD residuals across the
        ``m*v + d - 1``-cycle scan), and the schedule needs ``m >= d`` so a
        slot frees before its next-group replacement arrives. GPipe's AD
        executor carries no slot buffer at all (its O(m) liveness is in AD
        residuals); the memory-capped alternative is
        :class:`~pipe_tpu.parallel.scheduled.ScheduledPipeline` (1F1B,
        ``min(m, n)`` stashed inputs).
        """
        d, v = self.n_devices, self.v
        return {"cycles": m * v + d - 1, "activation_slots": m,
                "out_slots": m, "min_microbatches": d}

    # -----------------------------------------------------------------
    def __call__(self, stage_params, pre_params, post_params, x,
                 *, key: Optional[jax.Array] = None, train: bool = False):
        """Run on micro-batched ``x`` ([m, mb, ...] pytree); returns stacked
        post outputs [m, mb_out, ...] like ``SpmdPipeline``."""
        x_leaves = jax.tree_util.tree_leaves(x)
        if not x_leaves:
            raise TypeError("x must contain at least one array leaf")
        m = x_leaves[0].shape[0]
        d = self.n_devices
        if m < d:
            raise ValueError(
                f"interleaved schedule needs micro-batches >= devices "
                f"(m={m} < d={d}): an activation's buffer slot must free "
                f"before its next-group replacement arrives")
        stop = checkpoint_stop(self.checkpoint, m, train)
        key = key if key is not None else make_key(0)
        data = DATA_AXIS if self.has_data_axis else None
        ctx0 = StageCtx(key=None, train=train)

        x_mb_spec = jax.eval_shape(
            lambda a: jax.tree_util.tree_map(lambda l: l[0], a), x)
        h_spec = jax.eval_shape(
            lambda p, a: self._pre(p, a, ctx0), pre_params, x_mb_spec)
        out_spec = jax.eval_shape(
            lambda p, h, a: self._post(p, h, a, ctx0),
            post_params, h_spec, x_mb_spec)

        in_specs = (
            jax.tree_util.tree_map(lambda _: P(STAGE_AXIS), stage_params),
            jax.tree_util.tree_map(lambda _: P(), pre_params),
            jax.tree_util.tree_map(lambda _: P(), post_params),
            jax.tree_util.tree_map(
                lambda l: P(*([None, data] + [None] * (l.ndim - 2))), x),
            P(),
        )
        out_specs = jax.tree_util.tree_map(
            lambda s: P(*([STAGE_AXIS, None, data]
                          + [None] * (len(s.shape) - 1))),
            out_spec)

        run = shard_map(
            functools.partial(self._device_program, m=m, stop=stop,
                              train=train),
            mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False)
        stacked = run(stage_params, pre_params, post_params, x, key)
        return jax.tree_util.tree_map(lambda a: a[-1], stacked)

    # -----------------------------------------------------------------
    def _device_program(self, stage_params, pre_params, post_params, x, key,
                        *, m, stop, train):
        d, v = self.n_devices, self.v
        S = d * v
        p = jax.lax.axis_index(STAGE_AXIS)
        ctx0 = StageCtx(key=None, train=train)

        x_mb_spec = jax.eval_shape(
            lambda a: jax.tree_util.tree_map(lambda l: l[0], a), x)
        h_spec = jax.eval_shape(
            lambda pp, a: self._pre(pp, a, ctx0), pre_params, x_mb_spec)
        out_spec = jax.eval_shape(
            lambda pp, h, a: self._post(pp, h, a, ctx0),
            post_params, h_spec, x_mb_spec)

        from .buffers import drop_sentinel, masked_slot_write, slot_buffer

        zeros = lambda s: jnp.zeros(s.shape, s.dtype)
        # Slot m is the sentinel: masked writes go there unconditionally
        # instead of a per-cycle lax.cond around each buffer update.
        buf = slot_buffer(h_spec, m)
        outbuf = slot_buffer(out_spec, m)

        def idx_tree(tree, i):
            return jax.tree_util.tree_map(
                lambda l: jax.lax.dynamic_index_in_dim(l, i, 0,
                                                       keepdims=False), tree)

        def set_tree(tree, i, val, pred):
            return masked_slot_write(tree, val, i, pred, m)

        def body(params_g, k, h):
            return self.stage_fn(params_g, h,
                                 StageCtx(key=k, train=train,
                                          data_axis=self.bn_axis))

        if stop > 0:
            body = jax.checkpoint(body, policy=self.remat_policy) \
                if self.remat_policy is not None else jax.checkpoint(body)

        def cycle(carry, c):
            buf, outbuf = carry
            k = c - p
            active = (k >= 0) & (k < m * v)
            kc = jnp.clip(k, 0, m * v - 1)
            g = kc // m
            i = kc % m
            s = g * d + p
            ckey = jax.random.fold_in(jax.random.fold_in(key, i), s)

            x_i = idx_tree(x, i)
            h_in = jax.lax.cond(
                (s == 0) & active,
                lambda: self._pre(pre_params, x_i,
                                  StageCtx(key=jax.random.fold_in(ckey, 0),
                                           train=train,
                                           data_axis=self.bn_axis)),
                lambda: idx_tree(buf, i))

            params_g = idx_tree(stage_params, g)
            out = body(params_g, jax.random.fold_in(ckey, 1), h_in)

            emit = active & (s == S - 1)
            post_val = jax.lax.cond(
                emit,
                lambda: self._post(post_params, out, x_i,
                                   StageCtx(key=jax.random.fold_in(ckey, 2),
                                            train=train,
                                            data_axis=self.bn_axis)),
                lambda: jax.tree_util.tree_map(zeros, out_spec))
            outbuf = set_tree(outbuf, i, post_val, emit)

            # +1 ring shift (wraparound d-1 -> 0 advances to the next group)
            perm = [(q, (q + 1) % d) for q in range(d)]
            sent = jax.tree_util.tree_map(
                lambda a: jax.lax.ppermute(a, STAGE_AXIS, perm), out)

            # store the arriving activation into its micro-batch slot
            ps = (p - 1) % d
            ks = c - ps
            valid_s = (ks >= 0) & (ks < m * v)
            kcs = jnp.clip(ks, 0, m * v - 1)
            gs = kcs // m
            i_s = kcs % m
            s_s = gs * d + ps
            store = valid_s & (s_s != S - 1)
            buf = set_tree(buf, i_s, sent, store)
            return (buf, outbuf), None

        (buf, outbuf), _ = jax.lax.scan(
            cycle, (buf, outbuf), jnp.arange(m * v + d - 1))
        # drop the sentinel slot before stacking under the stage axis
        return jax.tree_util.tree_map(
            lambda b: b[None], drop_sentinel(outbuf, m))
