"""Memory-capped schedules (1F1B / zb-h1 / interleaved-1f1b) for Pipe(mesh=).

The reference's entire fork/join machinery exists so backward can run each
micro-batch as soon as its gradient arrives, releasing activations early
(reference ``pipeline.py:128-132``); its user-facing constructor is
``Pipe(module, chunks, checkpoint)`` (``pipe.py:308-314``). Round 2 had true
1F1B only behind the expert :class:`~pipe_tpu.parallel.scheduled
.ScheduledPipeline` API (homogeneous ``stage_fn`` + manually stacked
params). This module closes that gap: it lowers a ``Pipe``'s arbitrary
heterogeneous partitions onto the table executor, so
``Pipe(module, chunks, checkpoint, mesh=mesh, schedule='1f1b')`` — the
literal capability statement of the target — trains with the ``min(m, n)``
activation cap and the exact per-micro-batch checkpoint policy.

How heterogeneity rides the homogeneous table executor — every boundary is
made ring-uniform by the same per-dtype packed carrier the GPipe-wavefront
executor uses (:class:`~pipe_tpu.core.packing.PackPlan`):

* ``pre_fn`` packs the micro-batch inputs into the carrier (boundary 0);
* ``stage_fn`` is a ``lax.switch`` over virtual stages — branch ``s``
  unpacks boundary ``s``, applies partition ``s`` (params unpacked from the
  device's stage-sharded row), packs boundary ``s+1``. ``ctx.stage``
  (threaded by the executor) selects the branch;
* ``post_fn`` unpacks the final boundary and applies the user's
  ``loss_fn`` to get the per-row loss the executor's masked mean expects.

Because EVERY partition packs to the same fixed-capacity carrier, all
partitions are ring-compatible by construction — uneven balance and
multi-value boundaries need no special casing. Params use the stage-sharded
packed layout (``Pipe.shard_params``), so this is also the path where 1F1B's
activation cap meets partition-per-device weight placement.

Interleaved schedules (``v > 1``): the module must split into ``v*d``
partitions; virtual stage ``s`` lives on device ``s % d``, so the packed
param rows are laid out device-major (row ``p*v + g`` holds virtual stage
``g*d + p`` — ``stack_interleaved_params`` ordering).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import microbatch as mb
from ..core.packing import PackPlan, StageParamPack
from ..core.partition import StageCtx
from ..core.schedule import Schedule, get_schedule
from ..obs.telemetry import get_registry
from .mesh import DATA_AXIS, STAGE_AXIS
from .scheduled import ScheduledPipeline

__all__ = ["HeteroScheduledPipeline"]


class HeteroScheduledPipeline:
    """Training executor lowering Pipe partitions onto schedule tables."""

    def __init__(self, mesh, partitions, skip_layout, chunks: int,
                 checkpoint: str, schedule, remat_policy=None,
                 overlap_transport=None, phase_compile=None):
        self.mesh = mesh
        self.d = mesh.shape[STAGE_AXIS]
        self.remat_policy = remat_policy
        # Overlapped packed boundary transport, forwarded verbatim to the
        # inner ScheduledPipeline (which resolves the tri-state per
        # backend) — the front door inherits the same one-collective-
        # per-direction engine. The eval forward() path is unaffected
        # (its FWD-masked tables always run serialized).
        self.overlap_transport = overlap_transport
        # Phase-compiled table lowering (unrolled ramps + switch-free
        # steady-state scan), forwarded verbatim to the inner
        # ScheduledPipeline — tri-state, same contract as its
        # ``phase_compile`` field.
        self.phase_compile = phase_compile
        self.schedule: Schedule = (get_schedule(schedule)
                                   if isinstance(schedule, str) else schedule)
        self.v = self.schedule.v
        self.S = self.v * self.d
        if len(partitions) != self.S:
            raise ValueError(
                f"{len(partitions)} partitions for schedule "
                f"{self.schedule.name!r} on a {self.d}-device stage axis "
                f"(needs v*d = {self.S})")
        self.layout = skip_layout
        # stable lane order for cross-stage skips (matches hetero.py)
        self.lane_keys: List[Tuple[Any, str, int, int]] = []
        if skip_layout is not None:
            for (src, dst), names in skip_layout.by_src_dst:
                if src != dst:
                    for ns, name in names:
                        self.lane_keys.append((ns, name, src, dst))
        self.lane_pairs = tuple((src, dst)
                                for _, _, src, dst in self.lane_keys)
        self.partitions = list(partitions)
        self.chunks = chunks
        self.checkpoint = checkpoint
        self.has_data = DATA_AXIS in mesh.axis_names
        self.n_data = mesh.shape[DATA_AXIS] if self.has_data else 1
        # Collective axis the runtime StageCtx carries (scheduled.py sets
        # the same on its contexts): batch-stat psums reduce over the data
        # axis only when it is real (> 1 replica).
        self.bn_axis = DATA_AXIS if self.has_data and self.n_data > 1 \
            else None
        self.param_pack: Optional[StageParamPack] = None
        # uniform-fastpath verdict cache: (param treedefs, boundary shapes,
        # train) → bool, so the O(S) per-partition re-trace + const
        # comparisons (with their host syncs) run once per configuration.
        self._uniform_cache: Dict[Any, bool] = {}
        # Deferred-BN stat lanes through the op tables (reference
        # batchnorm.py capability, pipe.py:341-342) — mirrors hetero.py
        from ..extras.norm import BatchNorm, DeferredBatchNorm
        self.has_bn = any(isinstance(l, DeferredBatchNorm)
                          for part in self.partitions for l in part)
        self.has_batch_stats = any(isinstance(l, BatchNorm)
                                   for part in self.partitions for l in part)

    # -- param layout ------------------------------------------------------
    def row_of(self, s: int) -> int:
        """Packed-param row holding virtual stage ``s`` (device-major for
        interleaved: row ``p*v + g`` = virtual stage ``g*d + p``)."""
        if self.v == 1:
            return s
        return (s % self.d) * self.v + (s // self.d)

    def shard_params(self, params_per_stage: Sequence[Any]):
        """Per-partition trees → ``{dtype: [S, cap]}`` rows sharded over the
        stage axis in the executor's device-major row order."""
        if len(params_per_stage) != self.S:
            raise ValueError(
                f"{len(params_per_stage)} per-stage trees for {self.S} "
                f"virtual stages")
        rows = [params_per_stage[self._stage_of_row(r)]
                for r in range(self.S)]
        pack = StageParamPack(rows)
        packed = pack.shard(self.mesh, rows, stage_axis=STAGE_AXIS)
        self.param_pack = pack
        return packed

    def _stage_of_row(self, r: int) -> int:
        if self.v == 1:
            return r
        return (r % self.v) * self.d + (r // self.v)

    def unshard_params(self, packed):
        if self.param_pack is None:
            raise ValueError("no StageParamPack: call shard_params() first")
        rows = self.param_pack.unshard(packed)
        return [rows[self.row_of(s)] for s in range(self.S)]

    # -- uniform fast-path param views (traced) ----------------------------
    def _unpacked_param_tree(self, packed):
        """Packed ``{dtype: [S, cap]}`` rows → the natural stage-stacked
        tree (leaf ``[S, ...]``) the raw homogeneous executor takes. Static
        per-row slices + reshapes, so the stage-axis sharding propagates
        untouched. Only valid under the uniform fast path (every row shares
        one layout/treedef); removes the per-cycle ``unpack_stage``
        slice/reshape chain from the hot loop."""
        pack = self.param_pack
        plan = pack.plans[0]
        offsets = {dt: 0 for dt in pack.capacities}
        leaves = []
        for spec, size, dt in zip(plan.specs, plan.sizes, plan.dtypes):
            off = offsets[dt]
            flat = jax.lax.slice_in_dim(packed[dt], off, off + size, axis=1)
            offsets[dt] = off + size
            leaves.append(jnp.reshape(flat, (self.S,) + tuple(spec.shape)))
        return jax.tree_util.tree_unflatten(pack.treedefs[0], leaves)

    def _repack_param_tree(self, tree):
        """Inverse of :meth:`_unpacked_param_tree`, applied to the GRADS so
        the fast path still returns cotangents in the packed layout the
        caller's optimizer state is keyed on. Pure reshape/concat/pad —
        value-preserving, zero cotangent in the padding."""
        pack = self.param_pack
        plan = pack.plans[0]
        leaves = jax.tree_util.tree_leaves(tree)
        chunks: Dict[str, list] = {dt: [] for dt in pack.capacities}
        for leaf, size, dt in zip(leaves, plan.sizes, plan.dtypes):
            chunks[dt].append(jnp.reshape(leaf, (self.S, size)))
        out = {}
        for dt, cap in pack.capacities.items():
            if chunks[dt]:
                flat = (jnp.concatenate(chunks[dt], axis=1)
                        if len(chunks[dt]) > 1 else chunks[dt][0])
                pad = cap - flat.shape[1]
                out[dt] = (jnp.pad(flat, ((0, 0), (0, pad)))
                           if pad else flat)
            else:
                out[dt] = jnp.zeros((self.S, cap), dtype=np.dtype(dt))
        return out

    def memory_plan(self, m: Optional[int] = None) -> dict:
        from .scheduled import SkipLanes
        # lane specs are per-call (they depend on input shapes), but the
        # plan only reads the PAIRS — pass them so the skip park counts
        # the executor will actually allocate appear in the plan
        sp = ScheduledPipeline(self.mesh, stage_fn=None, pre_fn=None,
                               post_fn=None, checkpoint=self.checkpoint,
                               schedule=self.schedule,
                               remat_policy=self._train_remat_policy(),
                               skip_lanes=(SkipLanes(self.lane_pairs, ())
                                           if self.lane_pairs else None),
                               overlap_transport=self.overlap_transport,
                               phase_compile=self.phase_compile)
        return sp.memory_plan(m if m is not None else self.chunks)

    def _train_remat_policy(self):
        """The policy as the TRAINING executor sees it: at 'never' every
        micro-batch stores full residuals, so the policy is inert there —
        don't forward it (Pipe.remat_policy legitimately serves the
        forward path under 'never'; forwarding would fire the executor's
        inert-policy warning at a user who configured it for forward)."""
        return self.remat_policy if self.checkpoint != "never" else None

    def _branches_uniform(self, low, *, train: bool) -> bool:
        """True when every per-stage switch branch computes the SAME
        function — the uniform-partition fast path.

        The per-cycle ``lax.switch`` over stage branches is the price of
        ARBITRARY partitions (XLA's conditional copy-insertion around the
        scan carry was measured at ~2x step time on the cpu8 probe, and
        123 ms/step on-chip for the d=1 analogue). But the reference's only
        entry point is ``Pipe`` itself, and the most common model is a
        uniform stack of identical blocks — for those every branch is the
        same computation over a different (identically-laid-out) param row,
        so ONE shared branch replaces the switch and the emitted program
        matches the raw homogeneous :class:`ScheduledPipeline` exactly.

        Uniformity = (a) no skip lanes / deferred-BN bookkeeping (their
        branches differ per stage), (b) no statics closed into boundary 0,
        (c) all boundary specs identical (incl. input and output — ring
        invariance), (d) all packed param rows identical in layout, and
        (e) every partition's ``apply`` traces to an identical jaxpr with
        equal closure constants. Checked at trace time; any failure falls
        back to the switch, so arbitrary partitions are never wrong — just
        not specialized.

        The probe's StageCtx mirrors the runtime one the executor builds
        (same ``train`` flag, same ``data_axis`` collective name), except
        ``stage``, pinned to 0. That pin is the probe's one ASSUMPTION:
        ``apply`` must not Python-branch on ``ctx.stage`` (e.g.
        ``if ctx.stage == 3: extra_op()``) — such a module would trace
        identically at stage 0 yet compute per-stage-different functions,
        and the fast path would wrongly collapse them into one branch. No
        Partition in the repo reads ``ctx.stage`` (the executor threads it
        for the switch itself); a ``data_axis`` collective inside ``apply``
        fails the unbound-axis trace here and falls back to the switch —
        conservative, never wrong.

        The verdict is cached per (param treedefs, boundary shapes, train):
        the O(S) re-trace plus per-const host syncs run once per
        configuration, not once per jit retrace (cache hits/misses are
        counted in the metrics registry).
        """
        if self.S == 1 or self.lane_keys or self.has_bn:
            return False
        if low["closed"]:
            return False
        bspecs = [[(tuple(jnp.shape(sp)), str(jnp.result_type(sp)))
                   for sp in b] for b in low["boundaries"]]
        if any(b != bspecs[0] for b in bspecs[1:]):
            return False
        pack = low["pack"]
        if any(td != pack.treedefs[0] for td in pack.treedefs[1:]):
            return False
        row0 = [(tuple(s.shape), str(s.dtype)) for s in pack.plans[0].specs]
        for plan in pack.plans[1:]:
            if [(tuple(s.shape), str(s.dtype)) for s in plan.specs] != row0:
                return False
        cache_key = (tuple(pack.treedefs),
                     tuple(tuple(b) for b in bspecs), train)
        cached = self._uniform_cache.get(cache_key)
        if cached is not None:
            get_registry().counter("pipe.uniform_probe.cache_hits").inc()
            return cached
        get_registry().counter("pipe.uniform_probe.cache_misses").inc()
        verdict = self._probe_branches_uniform(low, train=train)
        self._uniform_cache[cache_key] = verdict
        return verdict

    def _probe_branches_uniform(self, low, *, train: bool) -> bool:
        """The uncached jaxpr-equality probe behind
        :meth:`_branches_uniform` (which see)."""
        pack = low["pack"]
        key_spec = jax.eval_shape(lambda: jax.random.key(0))
        in_specs = [jax.ShapeDtypeStruct(jnp.shape(sp),
                                         jnp.result_type(sp))
                    for sp in low["boundaries"][0]]
        ref_jaxpr = ref_consts = None
        try:
            for s_idx, part in enumerate(self.partitions):
                def fn(p, key, *vals, _part=part):
                    ctx = StageCtx(key=key, train=train, stage=0,
                                   data_axis=self.bn_axis)
                    return _part.apply(p, *vals, ctx=ctx)
                closed = jax.make_jaxpr(fn)(
                    pack.abstract_tree(self.row_of(s_idx)), key_spec,
                    *in_specs)
                if ref_jaxpr is None:
                    ref_jaxpr, ref_consts = str(closed.jaxpr), closed.consts
                    continue
                if str(closed.jaxpr) != ref_jaxpr:
                    return False
                if len(closed.consts) != len(ref_consts):
                    return False
                for a, b in zip(closed.consts, ref_consts):
                    if (jnp.shape(a) != jnp.shape(b)
                            or jnp.result_type(a) != jnp.result_type(b)
                            or not bool(jnp.all(jnp.equal(a, b)))):
                        return False
        except Exception as e:
            # Tracing hiccup: keep the general switch — correct, but ~2x
            # slower, so say WHY out loud instead of degrading silently
            # (VERDICT r5 #3: any probe failure used to disable the fast
            # path forever with no signal).
            import warnings
            warnings.warn(
                "uniform-partition fast-path probe failed while tracing "
                f"stage {s_idx} ({type(e).__name__}: {e}); falling back "
                "to the per-cycle lax.switch executor", stacklevel=3)
            return False
        return True

    def _record_fastpath(self, surface: str) -> None:
        """Publish the dispatch decision: the ``pipe.uniform_fastpath``
        gauge (1 = shared branch, 0 = lax.switch) plus per-path lowering
        counters, so the silent fallback to the ~2x-slower switch path is
        visible in any metrics snapshot."""
        reg = get_registry()
        reg.gauge("pipe.uniform_fastpath").set(int(self.uniform_fastpath))
        reg.counter(f"pipe.lowerings.{surface}").inc()
        reg.counter("pipe.lowerings.fastpath" if self.uniform_fastpath
                    else "pipe.lowerings.switch").inc()

    def _discover_stats(self, pack, boundaries, spec_tracker):
        """Train-mode spec pass per partition discovering each virtual
        stage's deferred-BN accumulator keys/shapes (shared by
        :meth:`loss_and_grad` and :meth:`forward`). Returns
        ``(stat_keys, stat_specs_st, stat_spec)`` — all empty/None when the
        module has no DeferredBatchNorm."""
        stat_keys: List[list] = [[] for _ in range(self.S)]
        stat_specs_st: List[list] = [[] for _ in range(self.S)]
        if not self.has_bn:
            return stat_keys, stat_specs_st, None
        import functools as _ft
        from ..extras.skip import use_skip_tracker

        def _apply_train(part_, p_, *xs_):
            return part_.apply(p_, *xs_, ctx=StageCtx(train=True))

        with use_skip_tracker(spec_tracker):
            for s_idx, part in enumerate(self.partitions):
                seen = set(spec_tracker.accum)
                jax.eval_shape(
                    _ft.partial(_apply_train, part),
                    pack.abstract_tree(self.row_of(s_idx)),
                    *boundaries[s_idx])
                for k_ in spec_tracker.accum:
                    if k_ not in seen:
                        stat_keys[s_idx].append(k_)
                        stat_specs_st[s_idx].append(spec_tracker.accum[k_])
        stat_spec = tuple(tuple(sp_) for sp_ in stat_specs_st)
        return stat_keys, stat_specs_st, stat_spec

    # -- shared lowering (forward + loss_and_grad) -------------------------
    def _lower_boundaries(self, params, inputs, *, what: str,
                          check_batch_stats: bool = True):
        """Classify inputs, scatter/pad, and walk the boundary-spec chain
        — the machinery both :meth:`forward` and :meth:`loss_and_grad`
        lower through. Returns a dict of the pieces; ``what`` names the
        calling surface for error messages."""
        if not isinstance(params, dict):
            raise TypeError(
                f"{what} runs on stage-sharded packed params; call "
                "Pipe.shard_params/init_sharded first")
        if self.param_pack is None:
            raise ValueError(
                "no StageParamPack on this executor; call shard_params() "
                "(or Pipe.shard_params) first")
        self.param_pack.check_packed(params)
        pack = self.param_pack
        m = self.chunks
        mb.check(*inputs)

        kinds: List[str] = []
        for x in inputs:
            if isinstance(x, mb.NoChunk):
                kinds.append("nochunk")
            elif mb.is_array(x):
                kinds.append("array")
            else:
                kinds.append("static")
        closed = {p: (x.value if k == "nochunk" else x)
                  for p, (x, k) in enumerate(zip(inputs, kinds))
                  if k != "array"}
        dyn = {str(p): x for p, (x, k) in enumerate(zip(inputs, kinds))
               if k == "array"}
        if not dyn:
            raise TypeError(f"{what} needs at least one array input")
        stacked, true_rows = mb.stack_scatter(dyn, m)
        if (check_batch_stats and self.has_batch_stats
                and true_rows % (m * self.n_data)):
            raise ValueError(
                f"BatchNorm needs the batch ({true_rows} rows) to divide "
                f"evenly into chunks*data ({m}*{self.n_data}): padded "
                "rows would contaminate the batch statistics")

        rows = next(iter(stacked.values())).shape[1]
        mb_rows = -(-rows // self.n_data) * self.n_data
        padded = mb_rows != rows
        if padded:
            def pad_rows(v):
                pad = ([(0, 0), (0, mb_rows - rows)]
                       + [(0, 0)] * (v.ndim - 2))
                return jnp.pad(v, pad)
            stacked = {p: pad_rows(v) for p, v in stacked.items()}
        local_rows = mb_rows // self.n_data

        def local_spec(v):
            return jax.ShapeDtypeStruct((local_rows,) + v.shape[2:],
                                        v.dtype)

        in_specs: List[Any] = []
        for p in range(len(inputs)):
            if p in closed:
                in_specs.append(closed[p])
            else:
                in_specs.append(local_spec(stacked[str(p)]))
        plans: List[PackPlan] = []
        x_plan_specs = [s for p, s in enumerate(in_specs)
                        if p not in closed]
        plans.append(PackPlan([jax.ShapeDtypeStruct(s.shape, s.dtype)
                               for s in x_plan_specs]))
        # Spec-mode tracker: skip-carrying partitions stash/pop during the
        # boundary walk (shapes only); its store afterwards holds each
        # lane's local value spec.
        from ..extras.skip import SkipTracker, use_skip_tracker
        spec_tracker = SkipTracker(self.layout, spec_mode=True)
        specs = in_specs
        boundaries = [in_specs]
        with use_skip_tracker(spec_tracker):
            for s_idx, part in enumerate(self.partitions):
                out = part.out_spec(pack.abstract_tree(self.row_of(s_idx)),
                                    *specs)
                specs = (list(out) if isinstance(out, (tuple, list))
                         else [out])
                boundaries.append(specs)
                plans.append(PackPlan(
                    [jax.ShapeDtypeStruct(jnp.shape(sp_),
                                          jnp.result_type(sp_))
                     for sp_ in specs]))
        capacities: Dict[str, int] = {}
        for plan in plans:
            for dt, sz in plan.per_dtype.items():
                capacities[dt] = max(capacities.get(dt, 0), sz)
        dyn_pos = [p for p in range(len(inputs)) if p not in closed]
        return dict(pack=pack, m=m, kinds=kinds, closed=closed,
                    stacked=stacked, true_rows=true_rows, rows=rows,
                    mb_rows=mb_rows, padded=padded, local_rows=local_rows,
                    plans=plans, boundaries=boundaries,
                    capacities=capacities, dyn_pos=dyn_pos,
                    spec_tracker=spec_tracker)

    # -- forward/eval through the FWD-masked tables ------------------------
    def forward(self, params, *inputs,
                key: Optional[jax.Array] = None, train: bool = False):
        """Forward outputs through the op tables with BWD rows masked to
        IDLE — the eval path for interleaved (v > 1) placements, which
        have no wavefront executor (reference eval-mode pipeline,
        ``pipeline.py:153-155``). Returns gathered final-partition outputs
        (a value, or a tuple for multi-value boundaries); for deferred-BN
        models with ``train=True`` the return is ``(outputs, stats)`` and
        the caller commits the running-stats update (mirroring the
        wavefront executor's contract).

        ``@skippable`` stashes ride the executor's forward lanes (each a
        single direct permute into a FIFO park at the destination) — the
        eval analogue of the training path's portal lanes.
        """
        low = self._lower_boundaries(params, inputs, what="forward",
                                     check_batch_stats=train)
        pack, plans = low["pack"], low["plans"]
        boundaries, capacities = low["boundaries"], low["capacities"]
        closed, dyn_pos = low["closed"], low["dyn_pos"]
        spec_tracker = low["spec_tracker"]
        # eval-mode BN reads running stats from params (pure) — only a
        # train-mode forward needs the stat lanes and the commit
        collect_stats = self.has_bn and train
        stat_keys, stat_specs_st, stat_spec = (
            self._discover_stats(pack, boundaries, spec_tracker)
            if collect_stats else ([], [], None))
        has_lanes = bool(self.lane_keys)
        lane_specs = tuple(spec_tracker._store[(0, ns, name)]
                           for ns, name, _, _ in self.lane_keys)
        lane_pairs = tuple((src, dst)
                           for _, _, src, dst in self.lane_keys)
        branch_pops = [
            [(l, ns, name) for l, (ns, name, src, dst)
             in enumerate(self.lane_keys) if dst == s_idx]
            for s_idx in range(self.S)]
        branch_stashes = [
            [(l, ns, name) for l, (ns, name, src, dst)
             in enumerate(self.lane_keys) if src == s_idx]
            for s_idx in range(self.S)]

        def pre_fn(prep, x_mb, ctx):
            del prep
            vals = [x_mb[str(p)] for p in dyn_pos]
            return plans[0].pack(vals, capacities)

        def make_branch(s_idx):
            part = self.partitions[s_idx]

            def branch(params_g, carrier, ctx, pops=None):
                packed_vals = plans[s_idx].unpack(carrier)
                vals: List[Any] = []
                it = iter(packed_vals)
                for p in range(len(boundaries[s_idx])):
                    if s_idx == 0 and p in closed:
                        vals.append(closed[p])
                    else:
                        vals.append(next(it))
                p_tree = pack.unpack_stage(params_g, self.row_of(s_idx))
                if not collect_stats and not has_lanes:
                    out = part.apply(p_tree, *vals, ctx=ctx)
                    out_vals = (list(out) if isinstance(out, (tuple, list))
                                else [out])
                    return plans[s_idx + 1].pack(out_vals, capacities)
                # seed the popped lane values, run under a local tracker
                # (which also captures BN stat accumulations), then export
                # this stage's stashes/stats — zeros for lanes/slots it
                # does not own, so every switch branch is structure-uniform
                from ..extras.skip import SkipTracker
                local = SkipTracker(self.layout)
                for l, ns, name in branch_pops[s_idx]:
                    local.save(0, ns, name, pops[l])
                with local.scope(0, s_idx):
                    out = part.apply(p_tree, *vals, ctx=ctx)
                out_vals = (list(out) if isinstance(out, (tuple, list))
                            else [out])

                def zeros_of(spec):
                    return jax.tree_util.tree_map(
                        lambda sp_: jnp.zeros(sp_.shape, sp_.dtype), spec)

                ret: List[Any] = [plans[s_idx + 1].pack(out_vals,
                                                        capacities)]
                if has_lanes:
                    stashes = [jnp.zeros(sp_.shape, sp_.dtype)
                               for sp_ in lane_specs]
                    for l, ns, name in branch_stashes[s_idx]:
                        stashes[l] = local.load(0, ns, name)
                    ret.append(tuple(stashes))
                if collect_stats:
                    ret.append(tuple(
                        tuple((local.accum[k_]
                               if s2 == s_idx and k_ in local.accum
                               else zeros_of(spec))
                              for k_, spec in zip(stat_keys[s2],
                                                  stat_specs_st[s2]))
                        for s2 in range(self.S)))
                return ret[0] if len(ret) == 1 else tuple(ret)

            return branch

        branches = [make_branch(s_idx) for s_idx in range(self.S)]

        self.uniform_fastpath = self._branches_uniform(low, train=train)
        self._record_fastpath("forward")
        if self.uniform_fastpath:
            # Identity lowering (see loss_and_grad): native boundary-value
            # carrier + natural stage-stacked params — the interleaved
            # (v > 1) eval front door emits the raw executor's program too.
            part0 = self.partitions[0]

            def pre_fn(prep, x_mb, ctx):  # noqa: F811 — fast-path override
                del prep
                return tuple(x_mb[str(p)] for p in dyn_pos)

            def stage_fn(params_g, h, ctx):
                out = part0.apply(params_g, *h, ctx=ctx)
                return (tuple(out) if isinstance(out, (tuple, list))
                        else (out,))
        else:
            def stage_fn(params_g, h, ctx, pops=None):
                s = ctx.stage
                if isinstance(s, int):
                    return branches[s](params_g, h, ctx, pops)
                return jax.lax.switch(
                    s, [lambda pg=params_g, hh=h, c=ctx, pp=pops, b=b:
                        b(pg, hh, c, pp)
                        for b in branches])

        from .scheduled import SkipLanes
        sp = ScheduledPipeline(self.mesh, stage_fn, pre_fn=pre_fn,
                               post_fn=None, checkpoint=self.checkpoint,
                               schedule=self.schedule,
                               skip_lanes=(SkipLanes(lane_pairs, lane_specs)
                                           if has_lanes else None),
                               stat_spec=stat_spec)
        # out_fn unpacks the final-boundary carrier into row-major values
        # INSIDE the device program, so the data axis lands on the rows
        # dim of the collected outputs (the fast path's carrier IS the
        # value tuple — nothing to unpack)
        if self.uniform_fastpath:
            res = sp.forward(self._unpacked_param_tree(params), (),
                             low["stacked"], key=key, train=train,
                             out_fn=lambda h: h)
        else:
            res = sp.forward(params, (), low["stacked"], key=key,
                             train=train,
                             out_fn=lambda h: tuple(
                                 plans[self.S].unpack(h)))
        outs, stats_t = res if collect_stats else (res, None)
        n_out = len(boundaries[self.S])
        gathered = []
        for pos in range(n_out):
            o = outs[pos]                 # [m, mb_rows, ...]
            if low["padded"]:
                o = o[:, :low["rows"]]
            gathered.append(mb.stack_gather(o, low["true_rows"]))
        out = tuple(gathered) if n_out > 1 else gathered[0]
        if collect_stats and train:
            stats = {}
            for s_idx in range(self.S):
                for k_, stv in zip(stat_keys[s_idx], stats_t[s_idx]):
                    stats[k_] = stv
            return out, stats
        return out

    # -- the training step -------------------------------------------------
    def loss_and_grad(self, params, *inputs,
                      targets: Any = None,
                      loss_fn: Callable,
                      key: Optional[jax.Array] = None):
        """One pipelined training step: ``(loss, packed_grads)``.

        ``loss_fn(*outputs, targets_mb) -> [rows]`` maps one micro-batch's
        final-boundary outputs (the values ``Pipe.__call__`` would return)
        plus the matching micro-batch of ``targets`` to per-row losses; the
        executor reduces them as a padding-masked mean. With
        ``targets=None``, ``loss_fn(*outputs) -> [rows]``.

        Wrap the whole train step in ``jax.jit`` (see tests): the lowering
        is rebuilt per call (boundary plans depend on the input shapes), so
        un-jitted use re-traces the pipeline every step.
        """
        low = self._lower_boundaries(params, inputs, what="loss_and_grad")
        pack, m = low["pack"], low["m"]
        closed, stacked = low["closed"], low["stacked"]
        true_rows, rows, mb_rows = (low["true_rows"], low["rows"],
                                    low["mb_rows"])
        plans, boundaries = low["plans"], low["boundaries"]
        capacities, dyn_pos = low["capacities"], low["dyn_pos"]
        spec_tracker = low["spec_tracker"]
        from ..extras.skip import SkipTracker, use_skip_tracker

        # build the loss mask against the PRE-pad rows ( _lower already
        # zero-padded `stacked` to divide the data axis), then pad it
        w = mb.valid_row_mask(
            {p: v[:, :rows] for p, v in stacked.items()}, true_rows)
        tgt_stacked = None
        if targets is not None:
            tgt_stacked, t_rows = mb.stack_scatter(targets, m)
            if t_rows != true_rows:
                raise ValueError(
                    f"targets batch {t_rows} != inputs batch {true_rows}")
        if low["padded"]:
            def pad_rows(v):
                pad = ([(0, 0), (0, mb_rows - rows)]
                       + [(0, 0)] * (v.ndim - 2))
                return jnp.pad(v, pad)
            if tgt_stacked is not None:
                tgt_stacked = jax.tree_util.tree_map(pad_rows, tgt_stacked)
            w = jnp.pad(w, [(0, 0), (0, mb_rows - rows)])

        lane_specs = tuple(spec_tracker._store[(0, ns, name)]
                           for ns, name, _, _ in self.lane_keys)
        lane_pairs = tuple((src, dst)
                           for _, _, src, dst in self.lane_keys)

        # Deferred-BN stat lanes: a train-mode spec pass per partition
        # discovers each stage's accumulator keys/shapes (mirrors
        # hetero.py); same tracker so skip stash specs resolve.
        collect_stats = self.has_bn
        stat_keys, stat_specs_st, stat_spec = self._discover_stats(
            pack, boundaries, spec_tracker)

        # -- executor bodies ----------------------------------------------
        def pre_fn(prep, x_mb, ctx):
            del prep
            vals = [x_mb["in"][str(p)] for p in dyn_pos]
            return plans[0].pack(vals, capacities)

        has_lanes = bool(self.lane_keys)
        # per-branch lane bookkeeping: which lanes this stage pops/stashes
        branch_pops = [
            [(l, ns, name) for l, (ns, name, src, dst)
             in enumerate(self.lane_keys) if dst == s_idx]
            for s_idx in range(self.S)]
        branch_stashes = [
            [(l, ns, name) for l, (ns, name, src, dst)
             in enumerate(self.lane_keys) if src == s_idx]
            for s_idx in range(self.S)]

        def make_branch(s_idx):
            part = self.partitions[s_idx]

            def branch(params_g, carrier, ctx, pops=None):
                packed_vals = plans[s_idx].unpack(carrier)
                vals: List[Any] = []
                it = iter(packed_vals)
                for p in range(len(boundaries[s_idx])):
                    if s_idx == 0 and p in closed:
                        vals.append(closed[p])
                    else:
                        vals.append(next(it))
                p_tree = pack.unpack_stage(params_g, self.row_of(s_idx))
                if not has_lanes and not collect_stats:
                    out = part.apply(p_tree, *vals, ctx=ctx)
                    out_vals = (list(out) if isinstance(out, (tuple, list))
                                else [out])
                    return plans[s_idx + 1].pack(out_vals, capacities)
                # seed the popped lane values, run under a local tracker
                # (which also captures BN stat accumulations), then export
                # this stage's stashes/stats — zeros for lanes/slots it
                # does not own, so every switch branch is structure-uniform
                local = SkipTracker(self.layout)
                for l, ns, name in branch_pops[s_idx]:
                    local.save(0, ns, name, pops[l])
                with local.scope(0, s_idx):
                    out = part.apply(p_tree, *vals, ctx=ctx)
                out_vals = (list(out) if isinstance(out, (tuple, list))
                            else [out])
                ret: List[Any] = [plans[s_idx + 1].pack(out_vals,
                                                        capacities)]
                if has_lanes:
                    stashes = [jnp.zeros(sp_.shape, sp_.dtype)
                               for sp_ in lane_specs]
                    for l, ns, name in branch_stashes[s_idx]:
                        stashes[l] = local.load(0, ns, name)
                    ret.append(tuple(stashes))
                if collect_stats:
                    def zeros_of(spec):
                        return jax.tree_util.tree_map(
                            lambda sp_: jnp.zeros(sp_.shape, sp_.dtype),
                            spec)
                    ret.append(tuple(
                        tuple((local.accum[k_]
                               if s2 == s_idx and k_ in local.accum
                               else zeros_of(spec))
                              for k_, spec in zip(stat_keys[s2],
                                                  stat_specs_st[s2]))
                        for s2 in range(self.S)))
                return tuple(ret)

            return branch

        branches = [make_branch(s_idx) for s_idx in range(self.S)]

        self.uniform_fastpath = self._branches_uniform(low, train=True)
        self._record_fastpath("loss_and_grad")
        if self.uniform_fastpath:
            # Uniform partitions: identity lowering. The switch is gone AND
            # the adapter machinery goes with it — the carrier is the raw
            # boundary value tuple (every boundary spec is identical, so the
            # ring is uniform without PackPlan's flatten/pad/slice per
            # cycle), and params flow as the natural stage-stacked tree
            # (one slice/reshape per step via _unpacked_param_tree, not one
            # unpack_stage chain per cycle). This is the program the raw
            # homogeneous ScheduledPipeline emits — the front-door tax is
            # the jaxpr-equality probe, paid once per configuration.
            part0 = self.partitions[0]

            def pre_fn(prep, x_mb, ctx):
                del prep
                return tuple(x_mb["in"][str(p)] for p in dyn_pos)

            def stage_fn(params_g, h, ctx):
                out = part0.apply(params_g, *h, ctx=ctx)
                return (tuple(out) if isinstance(out, (tuple, list))
                        else (out,))

            def post_fn(postp, h, x_mb, ctx):
                del postp
                args = list(h)
                if targets is not None:
                    args.append(x_mb["tgt"])
                per_row = loss_fn(*args)
                if jnp.ndim(per_row) != 1:
                    raise ValueError(
                        f"loss_fn must return per-row losses [rows]; got "
                        f"shape {jnp.shape(per_row)}")
                return per_row
        else:
            def stage_fn(params_g, h, ctx, pops=None):
                s = ctx.stage
                if isinstance(s, int):      # d == 1 static specialization
                    return branches[s](params_g, h, ctx, pops)
                return jax.lax.switch(
                    s, [lambda pg=params_g, hh=h, c=ctx, pp=pops, b=b:
                        b(pg, hh, c, pp)
                        for b in branches])

            def post_fn(postp, h, x_mb, ctx):
                del postp
                outs = plans[self.S].unpack(h)
                args = list(outs)
                if targets is not None:
                    args.append(x_mb["tgt"])
                per_row = loss_fn(*args)
                if jnp.ndim(per_row) != 1:
                    raise ValueError(
                        f"loss_fn must return per-row losses [rows]; got "
                        f"shape {jnp.shape(per_row)}")
                return per_row

        x = {"in": stacked}
        if tgt_stacked is not None:
            x["tgt"] = tgt_stacked

        from .scheduled import SkipLanes
        sp = ScheduledPipeline(self.mesh, stage_fn, pre_fn=pre_fn,
                               post_fn=post_fn, checkpoint=self.checkpoint,
                               schedule=self.schedule,
                               remat_policy=self._train_remat_policy(),
                               skip_lanes=(SkipLanes(lane_pairs, lane_specs)
                                           if has_lanes else None),
                               stat_spec=stat_spec,
                               overlap_transport=self.overlap_transport,
                               phase_compile=self.phase_compile)
        # stage-sharded packed rows ARE the stacked stage params; () for
        # pre/post (packing has no weights; the loss is pure)
        if collect_stats:
            loss, (g_packed, _, _), stats_t = sp.loss_and_grad(
                params, (), (), x, w, key=key)
            stats = {}
            for s_idx in range(self.S):
                for k_, stv in zip(stat_keys[s_idx], stats_t[s_idx]):
                    stats[k_] = stv
            return loss, g_packed, stats
        if self.uniform_fastpath:
            # grads come back against the natural stacked tree; repack so
            # the caller's optimizer state stays keyed on the packed layout
            loss, (g_tree, _, _) = sp.loss_and_grad(
                self._unpacked_param_tree(params), (), (), x, w, key=key)
            return loss, self._repack_param_tree(g_tree)
        loss, (g_packed, _, _) = sp.loss_and_grad(params, (), (), x, w,
                                                  key=key)
        return loss, g_packed
