"""Serial clock-cycle executor: the pipeline semantics without the mesh.

This is the TPU build's rebirth of the reference's CPU-sentinel-stream trick
(``AbstractStream`` admitting a CPU fallback, reference ``pipe.py:22``,
``pipeline.py:22``): the full scheduler — wavefront order, per-microbatch remat,
skip carries, ctx/RNG threading — runs on one device with no collectives, so
transparency tests (pipelined loss == unpipelined loss) and heterogeneous-stage
models need no mesh at all. The whole executor is pure and jit-able; the Python
loops unroll into one XLA program.

Where the reference needed ``fence`` (Copy/Wait stream ops + fork/join phony
edges, ``pipeline.py:119-142``) between ``compute`` dispatches, here the data
dependence between cycle k and k+1 is simply function composition — XLA sees
the true dependency graph, and backward order falls out of ``jax.grad``.
"""

from __future__ import annotations

import contextlib
from typing import Any, List, Optional, Sequence

import jax

from ..core import microbatch as mb
from ..core.partition import Stage, StageCtx
from ..core.remat import apply_remat, checkpoint_stop, validate_mode
from ..core.schedule import GPipeSchedule, Schedule

__all__ = ["run"]


def _compute_one(stage: Stage, params: Any, batch: mb.Batch, ctx: StageCtx,
                 remat: bool, remat_policy) -> mb.Batch:
    """Run one (microbatch, stage) task, optionally under jax.checkpoint.

    The PRNG key rides as an explicit argument of the remat'd function so the
    recomputed forward sees the identical key — the reference's
    ``save/restore_rng_states`` (``README.md:528-537``) with no runtime state.
    """
    key = ctx.key

    def task(p, k, *inputs):
        inner = StageCtx(key=k, train=ctx.train,
                         microbatch=ctx.microbatch, stage=ctx.stage)
        return stage(p, *inputs, ctx=inner)

    task = apply_remat(task, enabled=remat, policy=remat_policy)
    with jax.named_scope(f"chunk{ctx.microbatch}-stage{ctx.stage}"):
        return batch.call(lambda *inputs: task(params, key, *inputs))


def run(stages: Sequence[Stage],
        params_per_stage: Sequence[Any],
        batches: List[mb.Batch],
        *,
        schedule: Optional[Schedule] = None,
        checkpoint: str = "never",
        train: bool = False,
        key: Optional[jax.Array] = None,
        remat_policy=None,
        skip_tracker=None) -> List[mb.Batch]:
    """Execute the clock-cycle schedule serially; returns transformed batches.

    Mirrors ``Pipeline.run`` (reference ``pipeline.py:100-117``): iterate the
    wavefront; for each (i, j) run stage j on micro-batch i, rematerializing
    when ``i < checkpoint_stop`` (``pipeline.py:195-214``). The first stage
    failure propagates immediately (eager Python → strictly earlier than the
    reference's hold-and-drain, ``pipeline.py:239-247``, which existed only
    because of worker threads).
    """
    validate_mode(checkpoint)
    schedule = schedule or GPipeSchedule()
    m, n = len(batches), len(stages)
    stop = checkpoint_stop(checkpoint, m, train)
    batches = list(batches)

    for cycle in schedule.cycles(m, n):
        for (i, j) in cycle:
            if not (0 <= i < m and 0 <= j < n):
                raise IndexError(
                    f"schedule {schedule.name!r} emitted task (microbatch={i}, "
                    f"stage={j}) outside the {m}x{n} grid")
            ctx = StageCtx(key=key, train=train, microbatch=i, stage=j)
            ctx = ctx.fold(i, j) if key is not None else ctx
            cm = (skip_tracker.scope(microbatch=i, stage=j)
                  if skip_tracker is not None else contextlib.nullcontext())
            with cm:
                batches[i] = _compute_one(
                    stages[j], params_per_stage[j], batches[i], ctx,
                    remat=i < stop, remat_policy=remat_policy)
    return batches
