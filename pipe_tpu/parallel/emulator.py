"""Serial clock-cycle executor: the pipeline semantics without the mesh.

This is the TPU build's rebirth of the reference's CPU-sentinel-stream trick
(``AbstractStream`` admitting a CPU fallback, reference ``pipe.py:22``,
``pipeline.py:22``): the full scheduler — wavefront order, per-microbatch remat,
skip carries, ctx/RNG threading — runs on one device with no collectives, so
transparency tests (pipelined loss == unpipelined loss) and heterogeneous-stage
models need no mesh at all. The whole executor is pure and jit-able; the Python
loops unroll into one XLA program.

Where the reference needed ``fence`` (Copy/Wait stream ops + fork/join phony
edges, ``pipeline.py:119-142``) between ``compute`` dispatches, here the data
dependence between cycle k and k+1 is simply function composition — XLA sees
the true dependency graph, and backward order falls out of ``jax.grad``.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax

from ..core import microbatch as mb
from ..core.partition import Stage, StageCtx
from ..core.remat import apply_remat, checkpoint_stop, validate_mode
from ..core.schedule import GPipeSchedule, Schedule

__all__ = ["run"]


def _compute_one(stage: Stage, params: Any, batch: mb.Batch, ctx: StageCtx,
                 remat: bool, remat_policy, skip_tracker=None) -> mb.Batch:
    """Run one (microbatch, stage) task, optionally under jax.checkpoint.

    The PRNG key rides as an explicit argument of the remat'd function so the
    recomputed forward sees the identical key — the reference's
    ``save/restore_rng_states`` (``README.md:528-537``) with no runtime state.

    Skip values cross the task (and hence the ``jax.checkpoint``) boundary as
    explicit inputs/outputs: incoming pops are loaded from the persistent
    tracker and fed in, outgoing stashes are returned and saved back. Tracers
    must not leak out of a remat trace via Python state, so a fresh per-task
    tracker serves the in-stage stash/pop calls — the TPU-native stand-in for
    the reference's portal machinery threading skips through the
    ``Checkpointing`` graph (``pipeline.py:136-138,201,208``).
    """
    key = ctx.key
    layout = (getattr(skip_tracker, "layout", None)
              if skip_tracker is not None else None)
    pop_keys = layout.pops_of(ctx.stage) if layout else ()
    stash_keys = layout.stashes_of(ctx.stage) if layout else ()

    def call_payload(p, k, *inputs):
        inner = StageCtx(key=k, train=ctx.train,
                         microbatch=ctx.microbatch, stage=ctx.stage)
        return stage(p, *inputs, ctx=inner)

    if skip_tracker is None:
        def task(p, k, *inputs):
            return call_payload(p, k, *inputs)

        task = apply_remat(task, enabled=remat, policy=remat_policy)
        with jax.named_scope(f"chunk{ctx.microbatch}-stage{ctx.stage}"):
            return batch.call(lambda *inputs: task(params, key, *inputs))

    from ..extras.skip import SkipTracker

    pop_vals = [skip_tracker.load(ctx.microbatch, ns, name)
                for ns, name in pop_keys]

    def task(p, k, pop_vals, *inputs):
        local = SkipTracker(layout)
        for (ns, name), v in zip(pop_keys, pop_vals):
            local.save(ctx.microbatch, ns, name, v)
        with local.scope(ctx.microbatch, ctx.stage):
            out = call_payload(p, k, *inputs)
        stash_vals = [local.load(ctx.microbatch, ns, name)
                      for ns, name in stash_keys]
        # Stat accumulators (deferred BN) also cross the remat boundary as
        # explicit outputs; dict keys are static by the end of the trace.
        return out, stash_vals, dict(local.accum)

    task = apply_remat(task, enabled=remat, policy=remat_policy)
    with jax.named_scope(f"chunk{ctx.microbatch}-stage{ctx.stage}"):
        result, stash_vals, accums = task(params, key, pop_vals,
                                          *batch.values)
    for (ns, name), v in zip(stash_keys, stash_vals):
        skip_tracker.save(ctx.microbatch, ns, name, v)
    for (ns, name), v in accums.items():
        skip_tracker.accumulate(ns, name, v)
    if isinstance(result, (tuple, list)):
        return mb.Batch(tuple(result), atomic=False)
    return mb.Batch(result, atomic=True)


def _corrupt_hop(batch: mb.Batch, mode: str) -> mb.Batch:
    """Chaos-plan transport fault on a stage-boundary hop: 'drop' zeroes
    the payload (a lost transfer), 'corrupt' scales it by NaN (a torn
    one). Structural at trace time — with no plan the program is
    untouched."""
    import jax.numpy as jnp

    def one(v):
        if not mb.is_array(v):
            return v                      # NoChunk riders pass through
        if mode == "drop":
            return jnp.zeros_like(v)
        if jnp.issubdtype(v.dtype, jnp.inexact):
            return v * jnp.asarray(jnp.nan, v.dtype)
        return jnp.full_like(v, -1)       # int payload: garbage fill

    def hit(*vals):
        out = tuple(one(v) for v in vals)
        return out[0] if len(out) == 1 else out

    return batch.call(hit)


def run(stages: Sequence[Stage],
        params_per_stage: Sequence[Any],
        batches: List[mb.Batch],
        *,
        schedule: Optional[Schedule] = None,
        checkpoint: str = "never",
        train: bool = False,
        key: Optional[jax.Array] = None,
        remat_policy=None,
        skip_tracker=None,
        chaos=None,
        hop_health=None) -> List[mb.Batch]:
    """Execute the clock-cycle schedule serially; returns transformed batches.

    Mirrors ``Pipeline.run`` (reference ``pipeline.py:100-117``): iterate the
    wavefront; for each (i, j) run stage j on micro-batch i, rematerializing
    when ``i < checkpoint_stop`` (``pipeline.py:195-214``). The first stage
    failure propagates immediately (eager Python → strictly earlier than the
    reference's hold-and-drain, ``pipeline.py:239-247``, which existed only
    because of worker threads).

    ``chaos`` (a :class:`~pipe_tpu.resilience.ChaosPlan`) injects
    transport faults: after stage ``j`` produces micro-batch ``i``, a
    planned ``transport_drop``/``transport_corrupt`` at ``(i, j)``
    zeroes/NaN-poisons the hop before stage ``j+1`` consumes it —
    deterministic, and absent from the program when no plan is given.
    A ``persistent_hop_drop`` fault matches every micro-batch crossing
    its hop. ``hop_health`` (a
    :class:`~pipe_tpu.resilience.HopHealth`) records every crossing —
    faulted or clean — so persistent hop failure accumulates a streak
    the elastic controller can escalate on, while one-shot faults reset.
    """
    validate_mode(checkpoint)
    schedule = schedule or GPipeSchedule()
    m, n = len(batches), len(stages)
    stop = checkpoint_stop(checkpoint, m, train)
    batches = list(batches)

    for cycle in schedule.cycles(m, n):
        for (i, j) in cycle:
            if not (0 <= i < m and 0 <= j < n):
                raise IndexError(
                    f"schedule {schedule.name!r} emitted task (microbatch={i}, "
                    f"stage={j}) outside the {m}x{n} grid")
            ctx = StageCtx(key=key, train=train, microbatch=i, stage=j)
            ctx = ctx.fold(i, j) if key is not None else ctx
            batches[i] = _compute_one(
                stages[j], params_per_stage[j], batches[i], ctx,
                remat=i < stop, remat_policy=remat_policy,
                skip_tracker=skip_tracker)
            if j < n - 1:
                mode = (chaos.transport_fault(i, j)
                        if chaos is not None else None)
                if mode is not None:
                    batches[i] = _corrupt_hop(batches[i], mode)
                if hop_health is not None:
                    hop_health.record(j, mode is not None)
    return batches
