"""Compiled SPMD executor for HETEROGENEOUS stage pipelines — Pipe's mesh path.

The reference's flagship API drives arbitrary ``nn.Sequential`` partitions on
the multi-device pipeline (``Pipe.__init__`` builds the multi-device
``Pipeline``, ``pipe.py:344-356``; ``forward`` runs it, ``pipe.py:431-494``) —
stages differ in parameter structure and in activation signature. The
homogeneous executor (:mod:`.spmd`) cannot express that: its ring invariant
needs one activation shape and one stacked parameter structure.

This executor keeps the single-program SPMD design and handles heterogeneity
with three devices-visible mechanisms, all static at trace time:

* **``lax.switch`` stage bodies**: device ``j`` selects branch ``j`` by
  ``axis_index``; each branch closes over its partition's layer composition
  statically. All branches are uniformly remat-wrapped (mixed remat/plain
  branches trip the jax 0.9.0 cond+remat+PRNG bug — uniform branches
  differentiate fine, verified in tests).
* **Packed ring carrier**: between stages, the (possibly multi-value,
  shape-varying) boundary pytree is flattened per dtype into fixed-capacity
  1-D buffers sized to the largest boundary — one static ``ppermute`` shape
  for the whole pipeline. Branch ``s`` unpacks boundary ``s`` and packs
  boundary ``s+1`` with statically-known layouts.
* **Skip lanes**: every cross-stage ``@skippable`` stash rides the same ring
  as an extra lane, written by its source branch and consumed by its
  destination branch ``dst - src`` hops later — the arrival cycle is exactly
  the destination's compute cycle for that micro-batch, so a single array per
  skip suffices (no slot buffers). This is the compiled lowering of the
  reference's portal machinery (``skip/portal.py`` via ``pipeline.py:136-138``)
  that round 1 left emulator-only.

Parameters come in two layouts:

* **Stage-sharded (the memory-scaling layout)**: :meth:`shard_params` packs
  each stage's param tree into per-dtype rows of a ``[n, cap]`` array
  sharded ``P('stage')`` (:class:`~pipe_tpu.core.packing.StageParamPack`) —
  each device holds ONLY its partition's weights plus per-dtype padding to
  the largest stage, matching the reference's partition-per-device placement
  (``_split_module``, reference ``pipe.py:191-218,344-356``). Branch ``j``
  unpacks its own row (static slice+reshape, aliased by XLA); grads come
  back in the same sharded layout with no stage-axis communication.
* **Replicated per-stage pytrees** (legacy/simple): every stage's tree on
  every device (``P()``); only branch ``j`` touches stage ``j``'s params,
  and the psum inserted by AD-of-``shard_map`` recovers exact gradients.
  Convenient at toy scale; OOMs at exactly the model scale where pipeline
  parallelism is the point — use :meth:`shard_params`.

Remat on this path is static per mode (``except_last``
remats all micro-batches like :mod:`.spmd`; the exact policy lives in
:mod:`.scheduled`).
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..core import microbatch as mb
from ..core.packing import PackPlan as _PackPlan, StageParamPack
from ..core.partition import StageCtx
from ..core.remat import apply_remat, checkpoint_stop, validate_mode
from .mesh import DATA_AXIS, STAGE_AXIS
from ..utils.rng import make_key
from ..utils.compat import shard_map

__all__ = ["HeteroSpmdPipeline"]


def _zeros_of(spec_tree):
    """Zero arrays from a tree of ShapeDtypeStructs."""
    return jax.tree_util.tree_map(
        lambda sp_: jnp.zeros(sp_.shape, sp_.dtype), spec_tree)


def _apply_train(part, p, *xs):
    """Train-mode apply for the stat-lane spec pass (key None ⇒ dropout
    no-op; only BN's accumulate channel distinguishes it from out_spec)."""
    return part.apply(p, *xs, ctx=StageCtx(train=True))


class HeteroSpmdPipeline:
    """Executor over a ``(stage[, data])`` mesh for Pipe's partitions."""

    def __init__(self, mesh: Mesh, partitions, skip_layout, chunks: int,
                 checkpoint: str = "except_last"):
        validate_mode(checkpoint)
        if STAGE_AXIS not in mesh.axis_names:
            raise ValueError(f"mesh must have a {STAGE_AXIS!r} axis")
        self.mesh = mesh
        self.n_stages = mesh.shape[STAGE_AXIS]
        if len(partitions) != self.n_stages:
            raise ValueError(
                f"{len(partitions)} partitions for a {self.n_stages}-stage "
                f"mesh axis")
        self.partitions = list(partitions)
        self.layout = skip_layout
        self.chunks = chunks
        self.checkpoint = checkpoint
        self.has_data = DATA_AXIS in mesh.axis_names
        self.n_data = mesh.shape[DATA_AXIS] if self.has_data else 1
        # stable lane order for cross-stage skips
        self.lane_keys: List[Tuple[Any, str, int, int]] = []
        for (src, dst), names in skip_layout.by_src_dst:
            if src != dst:
                for ns, name in names:
                    self.lane_keys.append((ns, name, src, dst))
        # Established by shard_params(); None until then (replicated layout).
        self.param_pack: Optional[StageParamPack] = None
        # Deferred-BN: stat-bearing layers accumulate (sum, sum_sq, count)
        # per micro-batch; the executor threads those accumulators through
        # the scan as explicit lanes (reference batchnorm.py capability,
        # README.md:549-554).
        from ..extras.norm import BatchNorm, DeferredBatchNorm
        self.has_bn = any(isinstance(l, DeferredBatchNorm)
                          for part in self.partitions for l in part)
        # Any batch-statistics layer (plain OR deferred BN) makes padded
        # rows unacceptable in train mode: fake zero rows would enter the
        # normalization statistics.
        self.has_batch_stats = any(isinstance(l, BatchNorm)
                                   for part in self.partitions for l in part)

    # -----------------------------------------------------------------
    def shard_params(self, params_per_stage: Sequence[Any]):
        """Convert per-stage trees to the stage-sharded packed layout
        (``{dtype: [n, cap]}``, row j on stage j's devices) and remember the
        pack plans so subsequent calls accept the packed form."""
        if len(params_per_stage) != self.n_stages:
            raise ValueError(
                f"{len(params_per_stage)} per-stage trees for a "
                f"{self.n_stages}-stage pipeline")
        pack = StageParamPack(params_per_stage)
        packed = pack.shard(self.mesh, params_per_stage,
                            stage_axis=STAGE_AXIS)
        self.param_pack = pack  # only after shard() succeeded
        return packed

    def unshard_params(self, packed):
        """Packed params (or grads in the same layout) → per-stage trees."""
        if self.param_pack is None:
            raise ValueError("no StageParamPack: call shard_params() first")
        return self.param_pack.unshard(packed)

    # -----------------------------------------------------------------
    def __call__(self, params: Sequence[Any], *inputs,
                 key: Optional[jax.Array] = None,
                 train: bool = False, remat_policy=None):
        n = self.n_stages
        m = self.chunks
        # Packed stage-sharded params ({dtype: [n, cap]}) vs per-stage trees.
        packed = isinstance(params, dict)
        if packed:
            if self.param_pack is None:
                raise ValueError(
                    "packed params given but no StageParamPack on this "
                    "executor; call shard_params() (or Pipe.shard_params) "
                    "first")
            self.param_pack.check_packed(params)
        mb.check(*inputs)
        kinds = []
        for x in inputs:
            if isinstance(x, mb.NoChunk):
                kinds.append("nochunk")
            elif mb.is_array(x):
                kinds.append("array")
            else:
                kinds.append("static")
        static_vals = {p: x for p, (x, k) in
                       enumerate(zip(inputs, kinds)) if k == "static"}
        dyn = {str(p): x for p, (x, k) in enumerate(zip(inputs, kinds))
               if k != "static"}
        stacked, bs = mb.stack_scatter(dyn, m)
        true_rows = next(v.shape[1] for p, v in stacked.items()
                         if kinds[int(p)] == "array")
        # Rows must divide the data axis; zero-pad the shortfall (tiny
        # batches / batch < chunks) and slice it back off after gather.
        # Padded rows DO flow through the stages zeroed (as stack_scatter's
        # chunk padding already does): row-wise math is unaffected after the
        # slice, but cross-row batch statistics would see them — the same
        # class of hazard micro-batching itself poses to BatchNorm, which is
        # why Pipe routes stat-bearing models to deferred-BN (emulator-only).
        mb_rows = -(-true_rows // self.n_data) * self.n_data
        if mb_rows != true_rows:
            def pad_rows(p, v):
                if kinds[int(p)] != "array":
                    return v
                pad = [(0, 0), (0, mb_rows - true_rows)] + \
                    [(0, 0)] * (v.ndim - 2)
                return jnp.pad(v, pad)
            stacked = {p: pad_rows(p, v) for p, v in stacked.items()}
        local_rows = mb_rows // self.n_data

        # --- local per-micro-batch boundary chain (+ skip lane specs) ----
        def local_spec(p, v):
            if kinds[int(p)] == "array":
                return jax.ShapeDtypeStruct((local_rows,) + v.shape[2:],
                                            v.dtype)
            return jax.ShapeDtypeStruct(v.shape[1:], v.dtype)

        from ..extras.skip import SkipTracker, use_skip_tracker
        spec_tracker = SkipTracker(self.layout, spec_mode=True)
        vals0: List[Any] = []
        for p in range(len(inputs)):
            if p in static_vals:
                vals0.append(static_vals[p])
            else:
                vals0.append(local_spec(p, stacked[str(p)]))
        boundaries = [vals0]
        specs = vals0
        with use_skip_tracker(spec_tracker):
            for jdx, part in enumerate(self.partitions):
                p_j = (self.param_pack.abstract_tree(jdx) if packed
                       else params[jdx])
                out = part.out_spec(p_j, *specs)
                specs = list(out) if isinstance(out, (tuple, list)) else [out]
                boundaries.append(specs)
        lane_specs = [spec_tracker._store[(0, ns, name)]
                      for ns, name, _, _ in self.lane_keys]

        # Deferred-BN stat lanes: a train-mode spec pass per partition
        # discovers each stage's accumulator keys and shapes. Reuses the
        # same spec tracker so skip stash specs resolve; dropout is a no-op
        # (ctx.key is None), so only the stat channel differs from the
        # boundary walk above.
        stat_keys: List[list] = [[] for _ in range(n)]
        stat_specs: List[list] = [[] for _ in range(n)]
        collect_stats = self.has_bn and train
        if self.has_batch_stats and train and bs % (m * self.n_data):
            raise ValueError(
                f"BatchNorm needs the batch ({bs} rows) to divide evenly "
                f"into chunks*data ({m}*{self.n_data}): padded rows would "
                "contaminate the batch statistics")
        if collect_stats:
            with use_skip_tracker(spec_tracker):
                for jdx, part in enumerate(self.partitions):
                    seen = set(spec_tracker.accum)
                    p_j = (self.param_pack.abstract_tree(jdx) if packed
                           else params[jdx])
                    jax.eval_shape(
                        functools.partial(_apply_train, part),
                        p_j, *boundaries[jdx])
                    for k_ in spec_tracker.accum:
                        if k_ not in seen:
                            stat_keys[jdx].append(k_)
                            stat_specs[jdx].append(spec_tracker.accum[k_])

        # pack plans for boundaries 1..n-1 (stage inputs beyond stage 0)
        plans = [None] + [_PackPlan(boundaries[b]) for b in range(1, n)]
        capacities: dict = {}
        for plan in plans[1:]:
            for dt, sz in plan.per_dtype.items():
                capacities[dt] = max(capacities.get(dt, 0), sz)
        if not capacities:  # single stage: carrier still needs a leaf
            capacities = {"float32": 1}
        out_specs_local = boundaries[n]

        keyed = key is not None
        key = key if keyed else make_key(0)
        stop = checkpoint_stop(self.checkpoint, m, train)

        # --- shard_map specs --------------------------------------------
        data = DATA_AXIS if self.has_data else None

        def in_spec(p, v):
            if kinds[int(p)] == "array":
                return P(*([None, data] + [None] * (v.ndim - 2)))
            return P()

        x_specs = {p: in_spec(p, v) for p, v in stacked.items()}
        out_sp = tuple(
            P(*([STAGE_AXIS, None, data] + [None] * (len(s.shape) - 1)))
        for s in out_specs_local)

        if packed:
            # one row per device: only its own partition's weights live here
            p_arg = dict(params)
            p_spec = {dt: P(STAGE_AXIS, None) for dt in p_arg}
        else:
            p_arg = tuple(params)
            p_spec = jax.tree_util.tree_map(lambda _: P(), p_arg)
        stat_sp = tuple(
            tuple(jax.tree_util.tree_map(
                lambda _: (P(STAGE_AXIS, DATA_AXIS) if self.has_data
                           else P(STAGE_AXIS)), sp_)
                for sp_ in stage_specs)
            for stage_specs in stat_specs)
        run = shard_map(
            functools.partial(
                self._device_program, m=m, plans=plans,
                capacities=capacities, lane_specs=lane_specs,
                out_specs_local=out_specs_local, train=train, keyed=keyed,
                remat_on=stop > 0, remat_policy=remat_policy,
                static_vals=static_vals, kinds=kinds, packed=packed,
                stat_keys=stat_keys, stat_specs=stat_specs),
            mesh=self.mesh,
            in_specs=(p_spec, x_specs, P()),
            out_specs=(out_sp, stat_sp),
            check_vma=False)
        stacked_out, stats_out = run(p_arg, stacked, key)
        # device n-1's slice holds the real outputs: [n, m, rows...] -> [m, ...]
        outs = tuple(o[-1] for o in stacked_out)
        if mb_rows != true_rows:  # drop data-axis padding before gather
            outs = tuple(o[:, :true_rows] for o in outs)
        gathered = tuple(mb.stack_gather(o, bs) for o in outs)
        result = gathered if len(gathered) > 1 else gathered[0]
        if not collect_stats:
            return result
        # Stage s's stats live in row s (zeros elsewhere); data shards sum
        # HOST-SIDE — no in-program subgroup collective (see scheduled.py's
        # wsum note for why that matters on the virtual CPU platform).
        stats: dict = {}
        for jdx in range(n):
            for k_, st in zip(stat_keys[jdx], stats_out[jdx]):
                stats[k_] = jax.tree_util.tree_map(
                    lambda a: (a[jdx].sum(axis=0) if self.has_data
                               else a[jdx]), st)
        return result, stats

    # -----------------------------------------------------------------
    def _make_branch(self, s, all_params, train, keyed, remat_on,
                     remat_policy, plans, capacities, out_specs_local,
                     static_vals, kinds, packed, stat_keys, stat_specs):
        from ..extras.skip import SkipTracker

        n = self.n_stages
        part = self.partitions[s]
        pops = self.layout.pops_of(s) if self.layout else ()
        stashes = self.layout.stashes_of(s) if self.layout else ()
        lane_index = {(ns, name): idx
                      for idx, (ns, name, _, _) in enumerate(self.lane_keys)}
        pop_idx = [lane_index[k] for k in pops]
        stash_idx = [lane_index[k] for k in stashes]

        def branch(x_t, carrier, lanes, kij):
            if s == 0:
                vals = []
                for p in range(len(kinds)):
                    if p in static_vals:
                        vals.append(static_vals[p])
                    else:
                        vals.append(x_t[str(p)])
            else:
                vals = plans[s].unpack(carrier)
            pop_vals = [lanes[i] for i in pop_idx]

            def task(p, k, pop_vals, *vals):
                local = SkipTracker(self.layout)
                for (ns, name), v in zip(pops, pop_vals):
                    local.save(0, ns, name, v)
                ctx = StageCtx(key=k if keyed else None, train=train,
                               data_axis=DATA_AXIS
                               if self.has_data and self.n_data > 1
                               else None)
                with local.scope(0, s), jax.named_scope(f"stage{s}"):
                    out = part.apply(p, *vals, ctx=ctx)
                stash_vals = [local.load(0, ns, name) for ns, name in stashes]
                # This stage's deferred-BN stat contributions (explicit remat
                # outputs, like the stashes — stop_gradient'd at source)
                stat_vals = tuple(
                    (local.accum[k_] if k_ in local.accum
                     else _zeros_of(spec))
                    for k_, spec in zip(stat_keys[s], stat_specs[s]))
                return out, stash_vals, stat_vals

            wrapped = apply_remat(task, enabled=remat_on, policy=remat_policy)
            if packed:
                # local row [1, cap] per dtype → this stage's tree; only the
                # selected switch branch executes its unpack, and its
                # transpose scatters grads straight back into the local row.
                p_s = self.param_pack.unpack_stage(
                    {dt: a[0] for dt, a in all_params.items()}, s)
            else:
                p_s = all_params[s]
            out, stash_vals, stat_vals = wrapped(p_s, kij, pop_vals, *vals)
            out_vals = list(out) if isinstance(out, (tuple, list)) else [out]
            lanes2 = list(lanes)
            for idx, v in zip(stash_idx, stash_vals):
                lanes2[idx] = v
            if s == n - 1:
                out_t = tuple(out_vals)
                carrier2 = carrier
            else:
                out_t = tuple(jnp.zeros(sp.shape, sp.dtype)
                              for sp in out_specs_local)
                carrier2 = plans[s + 1].pack(out_vals, capacities)
            # uniform switch-branch structure: this stage's stats in slot s,
            # zeros for every other stage's slots (tiny trees)
            stat_t = tuple(
                stat_vals if s2 == s
                else tuple(_zeros_of(spec) for spec in stat_specs[s2])
                for s2 in range(n))
            return carrier2, tuple(lanes2), out_t, stat_t

        return branch

    # -----------------------------------------------------------------
    def _device_program(self, all_params, x, key, *, m, plans, capacities,
                        lane_specs, out_specs_local, train, keyed, remat_on,
                        remat_policy, static_vals, kinds, packed, stat_keys,
                        stat_specs):
        n = self.n_stages
        j = jax.lax.axis_index(STAGE_AXIS)

        branches = [
            self._make_branch(s, all_params, train, keyed, remat_on,
                              remat_policy, plans, capacities,
                              out_specs_local, static_vals, kinds, packed,
                              stat_keys, stat_specs)
            for s in range(n)]

        carrier0 = {dt: jnp.zeros((cap,), dtype=np.dtype(dt))
                    for dt, cap in capacities.items()}
        lanes0 = tuple(jnp.zeros(sp.shape, sp.dtype) for sp in lane_specs)
        outbuf0 = tuple(jnp.zeros((m + 1,) + tuple(sp.shape), sp.dtype)
                        for sp in out_specs_local)
        bn_acc0 = tuple(
            tuple(_zeros_of(spec) for spec in stage_specs)
            for stage_specs in stat_specs)
        fwd_perm = [(k, k + 1) for k in range(n - 1)]

        def index_x(t):
            return jax.tree_util.tree_map(
                lambda l: jax.lax.dynamic_index_in_dim(
                    l, t, 0, keepdims=False), x)

        def cycle(carry, t):
            carrier, lanes, outbuf, bn_acc = carry
            i = t - j
            x_t = index_x(jnp.clip(t, 0, m - 1))
            kij = jax.random.fold_in(jax.random.fold_in(key, i), j)
            carrier2, lanes2, out_t, stat_t = jax.lax.switch(
                j, branches, x_t, carrier, lanes, kij)
            valid = (j == n - 1) & (i >= 0) & (i < m)
            widx = jnp.where(valid, jnp.clip(i, 0, m - 1), m)
            outbuf = tuple(
                jax.lax.dynamic_update_index_in_dim(buf, o, widx, 0)
                for buf, o in zip(outbuf, out_t))
            # BN stats only from cycles where this device computes a REAL
            # micro-batch — fill/drain cycles run the branch on garbage
            # (zero carriers), whose statistics must not leak in.
            valid_c = (i >= 0) & (i < m)
            bn_acc = jax.tree_util.tree_map(
                lambda a, c: a + jnp.where(valid_c, c, 0), bn_acc, stat_t)
            if n > 1:
                carrier2 = jax.tree_util.tree_map(
                    lambda a: jax.lax.ppermute(a, STAGE_AXIS, fwd_perm),
                    carrier2)
                lanes2 = jax.tree_util.tree_map(
                    lambda a: jax.lax.ppermute(a, STAGE_AXIS, fwd_perm),
                    lanes2)
            return (carrier2, lanes2, outbuf, bn_acc), None

        (carrier, lanes, outbuf, bn_acc), _ = jax.lax.scan(
            cycle, (carrier0, lanes0, outbuf0, bn_acc0),
            jnp.arange(m + n - 1))
        # drop the garbage slot; stack under a stage axis for out_specs;
        # stats gain leading (stage[, data]) axes for host-side reduction
        lead = ((lambda l: l[None, None]) if self.has_data
                else (lambda l: l[None]))
        stats_out = jax.tree_util.tree_map(lead, bn_acc)
        return tuple(b[None, :m] for b in outbuf), stats_out
