"""SPMD pipeline executor: one compiled program, ppermute transport over ICI.

This replaces the reference's entire runtime machine — per-device worker
threads and queues (``pipeline.py:98,237,240``; ``README.md:39-47,291-314``),
per-(stage,chunk) copy streams with ``Copy``/``Wait`` autograd ops
(``pipe.py:417-429``; ``README.md:185-237,324-369``), and fork/join phony
ordering edges (``pipeline.py:128-132``) — with a single ``shard_map``'d
``lax.scan`` over clock cycles:

* transport: ``jax.lax.ppermute`` (XLA ``collective-permute``) shifts the
  activation ring one stage forward per cycle — the D2D copy *and* its
  ordering, compiled;
* schedule: the scan index IS the clock cycle (``pipeline.py:63-79``); stage
  ``j`` works on micro-batch ``i = t - j``, idling (masked) during fill/drain;
* backward: ``jax.grad`` differentiates the scan — reverse ppermutes and
  reverse schedule fall out of AD (the moral equivalent of ``Copy.backward``/
  ``Wait.backward``, ``README.md:219-237,359-369``), and backward micro-batch
  ordering is compiled instead of discovered by a C++ graph walk;
* remat: ``jax.checkpoint`` on the stage body (modes ``always``/
  ``except_last``/``never``, reference ``pipe.py:354``), eval-mode off
  (``pipeline.py:153-155``). NOTE: on this compiled path the remat decision is
  *static* — ``except_last`` remats every micro-batch (numerically identical;
  memory ≤ the reference's except_last; ~1/m extra recompute). The exact
  per-microbatch policy needs ``lax.cond(i < stop, remat(body), body)``, which
  jax 0.9.0 cannot differentiate when the body consumes PRNG (cond branch
  residual join emits mismatched branch return types). The serial emulator
  path implements the exact per-microbatch policy;
* overlap: XLA's latency-hiding scheduler overlaps the collective-permute with
  stage compute — the role of the reference's dedicated copy streams.

Stage heterogeneity (SURVEY §7 hard part #2) is handled Encoder/Decoder-style:
the pipelined body is a *homogeneous* stage stack (params stacked on a leading
``[n_stages, ...]`` axis, sharded over the ``stage`` mesh axis), while an
optional ``pre_fn`` (e.g. embed+posenc) runs only on stage 0 and ``post_fn``
(e.g. decode or per-microbatch loss) only on stage n-1, their params
replicated. This matches the tutorial topology (Encoder + N×block + Decoder,
``main.py:139-157``) while keeping every ppermute a static same-shape ring
shift.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core.partition import StageCtx
from ..core.remat import checkpoint_stop, validate_mode
from .mesh import DATA_AXIS, STAGE_AXIS
from ..utils.rng import make_key
from ..utils.compat import shard_map

__all__ = ["SpmdPipeline", "stack_stage_params"]


def stack_stage_params(params_per_stage):
    """Stack per-stage (identically-structured) pytrees on a leading stage axis."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *params_per_stage)


def unstack_stage_params(stacked, n_stages: int):
    """Inverse of :func:`stack_stage_params`: back to a per-stage list."""
    return [jax.tree_util.tree_map(lambda a: a[i], stacked)
            for i in range(n_stages)]


def _identity(params, x, ctx):
    return x


@dataclasses.dataclass
class SpmdPipeline:
    """GPipe pipeline compiled over a ``(stage[, data])`` mesh.

    Args:
      mesh: mesh containing ``stage`` (and optionally ``data``) axes.
      stage_fn: ``(params_j, h, ctx) -> h`` homogeneous stage body; input and
        output activation must have identical shape/dtype (ring invariant).
      pre_fn: ``(pre_params, x_mb, ctx) -> h`` run on stage 0 only (embed).
        ``x_mb`` is one micro-batch slice of the input pytree.
      post_fn: ``(post_params, h, ctx) -> out`` run on stage n-1 only (decode
        or per-example loss); with ``post_with_batch=True`` it is
        ``(post_params, h, x_mb, ctx)`` where ``x_mb`` is the micro-batch the
        output belongs to — e.g. targets for computing loss in-pipeline
        without materializing logits. ``out``'s leading dim must be the
        micro-batch rows (it is sharded over ``data``).
      checkpoint: ``always | except_last | never`` (reference ``pipe.py:354``).
    """

    mesh: Mesh
    stage_fn: Callable
    pre_fn: Optional[Callable] = None
    post_fn: Optional[Callable] = None
    post_with_batch: bool = False
    checkpoint: str = "never"
    remat_policy: Any = None
    # Remat the post (decode/loss) body during training: trades the
    # [rows, seq, vocab]-scale loss residuals (118 MB/micro-batch at tutorial
    # scale, saved for ALL m micro-batches by grad-of-scan) for a decoder
    # recompute at backward time. Numerically identical (same key replays).
    # Default OFF: measured on v5e at tutorial scale it is ~3% SLOWER
    # (160.4 vs 155.7 ms/step) — XLA's schedule absorbs the residual traffic
    # better than the recompute; turn on only when those residuals are what
    # OOMs the step.
    remat_post: bool = False
    # Context (sequence) parallelism: name of a mesh axis over which dim
    # ``context_dim`` of every input leaf with enough rank is sharded. Stage
    # bodies then see local sequence shards and use ring collectives
    # (ops.ring_attention) over that axis — PP x CP composition.
    # CONTRACT: with context_axis set, ``post_fn``'s output MUST be
    # context-invariant (reduce over the axis, e.g. ``lax.pmean`` like
    # ContextParallelLM.loss_post_fn) — out_specs assemble assuming context
    # replication and vma checking is off, so a still-sharded output (e.g.
    # raw per-token logits) would silently return one shard's values.
    context_axis: Optional[str] = None
    context_dim: int = 2
    # Debug mode for the context-invariance contract above: verify at run
    # time that post_fn's output really is identical across context shards
    # (vma checking is off, so a forgotten pmean would otherwise silently
    # return one shard's values). On violation every inexact output leaf is
    # poisoned with NaN and a debug line is printed — loud by construction.
    debug_context_check: bool = False

    def __post_init__(self):
        validate_mode(self.checkpoint)
        if STAGE_AXIS not in self.mesh.axis_names:
            raise ValueError(f"mesh must have a {STAGE_AXIS!r} axis")
        if self.context_axis and self.context_axis not in self.mesh.axis_names:
            raise ValueError(
                f"mesh has no {self.context_axis!r} axis for context_axis")
        if self.context_axis and self.post_fn is None:
            raise ValueError(
                "context_axis requires a post_fn whose output is context-"
                "invariant (e.g. a pmean'd loss); the identity post would "
                "silently return one context shard's activations")
        self.n_stages = self.mesh.shape[STAGE_AXIS]
        self.has_data_axis = DATA_AXIS in self.mesh.axis_names
        # Bound data axis for batch-statistics layers (BatchNorm psums its
        # normalization stats over it — mesh factorization must not change
        # the math); None when absent or size 1.
        self.bn_axis = (DATA_AXIS if self.has_data_axis
                        and self.mesh.shape[DATA_AXIS] > 1 else None)
        self._pre = self.pre_fn or _identity
        if self.post_fn is None:
            self._post = lambda p, h, x_mb, ctx: h
        elif self.post_with_batch:
            self._post = self.post_fn
        else:
            self._post = lambda p, h, x_mb, ctx: self.post_fn(p, h, ctx)
        # _post_spec: the unchecked form, for eval_shape outside shard_map
        # (the checker's pmean needs the mesh axis bound).
        self._post_spec = self._post
        if self.context_axis and self.debug_context_check:
            self._post = self._context_checked(self._post)

    def _context_checked(self, post):
        """Wrap post so a context-variant output turns into NaN + a print.

        A correct post ends in a collective over the context axis (pmean /
        psum), which by definition leaves every shard with the same value —
        so any cross-shard deviation is a contract violation, not noise.
        """
        axis = self.context_axis

        def checked(p, h, x_mb, ctx):
            out = post(p, h, x_mb, ctx)
            leaves = [o for o in jax.tree_util.tree_leaves(out)
                      if jnp.issubdtype(o.dtype, jnp.inexact)]
            if not leaves:
                return out
            delta = jnp.max(jnp.stack([
                jnp.max(jnp.abs((o - jax.lax.pmean(o, axis))
                                .astype(jnp.float32))) for o in leaves]))
            bad = delta > 1e-5
            jax.lax.cond(
                bad,
                lambda: jax.debug.print(
                    "pipe_tpu context-invariance VIOLATION: post_fn output "
                    "differs across context shards by {d:.3e}; it must end "
                    "in a pmean/psum over the context axis. Outputs are "
                    "poisoned with NaN.", d=delta),
                lambda: None)
            poison = jnp.where(bad, jnp.float32(jnp.nan), jnp.float32(0))
            return jax.tree_util.tree_map(
                lambda o: o + poison.astype(o.dtype)
                if jnp.issubdtype(o.dtype, jnp.inexact) else o, out)

        return checked

    # -----------------------------------------------------------------
    def __call__(self, stage_params, pre_params, post_params, x,
                 *, key: Optional[jax.Array] = None, train: bool = False):
        """Run the pipeline on micro-batched input ``x``: a [m, mb, ...] array
        or a pytree of such (e.g. ``{"tokens": ..., "targets": ...}``).

        Returns ``[m, mb_out, ...]`` stacked ``post_fn`` outputs (a global
        array whose data lives on the last stage's devices).
        """
        x_leaves = jax.tree_util.tree_leaves(x)
        if not x_leaves:
            raise TypeError("x must contain at least one array leaf")
        m = x_leaves[0].shape[0]
        n = self.n_stages
        stop = checkpoint_stop(self.checkpoint, m, train)
        # Key is threaded as data so remat replays identical dropout.
        key = key if key is not None else make_key(0)

        data = DATA_AXIS if self.has_data_axis else None
        ctx0 = StageCtx(key=None, train=train)

        # Global post-output spec (for the caller-visible shape only; local
        # buffer shapes are derived inside the device program on local shards).
        x_mb_spec = jax.eval_shape(
            lambda a: jax.tree_util.tree_map(lambda l: l[0], a), x)
        h_spec = jax.eval_shape(
            lambda p, a: self._pre(p, a, ctx0), pre_params, x_mb_spec)
        out_spec = jax.eval_shape(
            lambda p, h, a: self._post_spec(p, h, a, ctx0),
            post_params, h_spec, x_mb_spec)

        def x_spec(l):
            # [m, mb_rows, (seq,) ...]: rows sharded over data; with context
            # parallelism, dim ``context_dim`` also sharded over context.
            spec = [None, data] + [None] * (l.ndim - 2)
            if self.context_axis and l.ndim > self.context_dim:
                spec[self.context_dim] = self.context_axis
            return P(*spec)

        in_specs = (
            jax.tree_util.tree_map(lambda _: P(STAGE_AXIS), stage_params),
            jax.tree_util.tree_map(lambda _: P(), pre_params),
            jax.tree_util.tree_map(lambda _: P(), post_params),
            jax.tree_util.tree_map(x_spec, x),
            P(),                          # key
        )
        # result leaves: [stage, m, mb_rows_out, ...]
        out_specs = jax.tree_util.tree_map(
            lambda s: P(*([STAGE_AXIS, None, data]
                          + [None] * (len(s.shape) - 1))),
            out_spec)

        run = shard_map(
            functools.partial(self._device_program, m=m, stop=stop,
                              train=train),
            mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False)

        stacked = run(stage_params, pre_params, post_params, x, key)
        # Only the last stage's slice holds real data: [n, m, ...] -> [m, ...]
        return jax.tree_util.tree_map(lambda a: a[-1], stacked)

    # -----------------------------------------------------------------
    def _device_program(self, stage_params, pre_params, post_params, x, key,
                        *, m, stop, train):
        """The per-device SPMD program (runs under shard_map)."""
        n = self.n_stages
        j = jax.lax.axis_index(STAGE_AXIS)
        # This device's stage slice: leading dim n/n_devices == 1 for GPipe.
        params_j = jax.tree_util.tree_map(lambda p: p[0], stage_params)

        # Local (per-shard) activation and output specs.
        ctx0 = StageCtx(key=None, train=train)
        x_mb_spec = jax.eval_shape(
            lambda a: jax.tree_util.tree_map(lambda l: l[0], a), x)
        h_spec = jax.eval_shape(
            lambda p, a: self._pre(p, a, ctx0), pre_params, x_mb_spec)
        out_spec = jax.eval_shape(
            lambda p, h, a: self._post_spec(p, h, a, ctx0),
            post_params, h_spec, x_mb_spec)

        from .buffers import drop_sentinel, masked_slot_write, slot_buffer

        h0 = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), h_spec)
        # Sentinel slot: invalid cycles write unconditionally into slot m
        # (masked index instead of a per-cycle lax.cond around the update).
        outbuf = slot_buffer(out_spec, m)

        # Stage 0's ingest slices ride the scan's xs; the same buffer (its
        # first m slices) serves the last stage's x_i gathers — one copy,
        # padded with repeats of the final micro-batch for the drain cycles.
        x_fill = jax.tree_util.tree_map(
            lambda l: jnp.concatenate([l] + [l[-1:]] * (n - 1), axis=0)
            if n > 1 else l, x)

        def index_x(idx):
            return jax.tree_util.tree_map(
                lambda l: jax.lax.dynamic_index_in_dim(
                    l, idx, 0, keepdims=False), x_fill)

        def body(p, k, h):
            # ctx.stage carries this device's (traced) stage index so
            # stage-aware wrappers (resilience.chaos.wrap_stage_fn) can
            # target one stage; the model itself never reads it.
            return self.stage_fn(p, h, StageCtx(key=k, train=train,
                                                stage=j,
                                                data_axis=self.bn_axis))

        if stop > 0:
            # remat'd when the mode asks for any remat at all (static
            # selection; see module docstring for why not per-i)
            body = jax.checkpoint(body, policy=self.remat_policy) \
                if self.remat_policy is not None else jax.checkpoint(body)

        def post_body(p, h, x_mb, k):
            return self._post(p, h, x_mb,
                              StageCtx(key=k, train=train,
                                       data_axis=self.bn_axis))

        # see remat_post field docstring: drop the [rows, seq, vocab]-scale
        # loss residuals, recompute the decode at backward time
        post_fn = (jax.checkpoint(post_body)
                   if train and self.remat_post else post_body)

        def single_stage_cycle(_, xs_t):
            # n == 1: no ring, no fill/drain, every cycle valid — degrade to
            # straight-line micro-batch accumulation with zero schedule
            # machinery (this is what the vs_baseline contract measures).
            # x rides the scan's xs and out its stacked ys: no carry, no
            # per-cycle gathers or buffer updates.
            x_t, t = xs_t
            ctx_key = jax.random.fold_in(jax.random.fold_in(key, t), 0)
            h = self._pre(pre_params, x_t,
                          StageCtx(key=jax.random.fold_in(ctx_key, 0),
                                   train=train, data_axis=self.bn_axis))
            h = body(params_j, jax.random.fold_in(ctx_key, 1), h)
            out_t = post_fn(post_params, h, x_t,
                            jax.random.fold_in(ctx_key, 2))
            return None, out_t

        def cycle(carry, xs_t):
            h, outbuf = carry
            # --- stage 0 ingests micro-batch t (clamped during drain);
            # its slice rides the scan's xs, not a per-cycle gather ---
            x_t, t = xs_t
            i = t - j  # micro-batch index in flight on this device
            ctx_key = jax.random.fold_in(jax.random.fold_in(key, i), j)

            h = jax.lax.cond(
                j == 0,
                lambda: self._pre(pre_params,
                                  x_t,
                                  StageCtx(key=jax.random.fold_in(ctx_key, 0),
                                           train=train,
                                           data_axis=self.bn_axis)),
                lambda: h)

            h = body(params_j, jax.random.fold_in(ctx_key, 1), h)

            # --- last stage emits output for valid micro-batches (the x_i
            # gather lives inside the branch: only the last stage pays) ---
            valid = (j == n - 1) & (i >= 0) & (i < m)
            out_t = jax.lax.cond(
                valid,
                lambda: post_fn(post_params, h,
                                index_x(jnp.clip(i, 0, m - 1)),
                                jax.random.fold_in(ctx_key, 2)),
                lambda: jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, s.dtype), out_spec))
            outbuf = masked_slot_write(outbuf, out_t,
                                       jnp.clip(i, 0, m - 1), valid, m)

            # --- ring shift: stage j -> j+1 (XLA collective-permute) ---
            perm = [(k, k + 1) for k in range(n - 1)]
            h = jax.tree_util.tree_map(
                lambda a: jax.lax.ppermute(a, STAGE_AXIS, perm), h)
            return (h, outbuf), None

        if n == 1:
            _, outs = jax.lax.scan(single_stage_cycle, None,
                                   (x, jnp.arange(m)))
            return jax.tree_util.tree_map(lambda b: b[None], outs)
        (h, outbuf), _ = jax.lax.scan(
            cycle, (h0, outbuf), (x_fill, jnp.arange(m + n - 1)))
        # Drop the sentinel slot; stack on a leading stage axis so
        # out_specs=P(stage,...) is exact (device j contributes its outbuf as
        # slice j; only j=n-1 is real).
        return jax.tree_util.tree_map(
            lambda b: b[None], drop_sentinel(outbuf, m))
