"""Sentinel-slot buffer idiom shared by the compiled executors.

A per-cycle conditional buffer update (``lax.cond`` around a
``dynamic_update_index_in_dim``) costs a real branch in the scan hot loop.
The executors instead allocate one extra *sentinel* slot and always write,
masking only the index::

    buf   = slot_buffer(spec_tree, m)          # m real slots + 1 sentinel
    buf   = masked_slot_write(buf, val, i, pred, m)
    real  = drop_sentinel(buf, m)              # [:m]

Invalid cycles land in slot ``m`` (never read, dropped at the end), valid
ones in their real slot — uniform per-cycle code, no branch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "slot_buffer", "masked_slot_write", "drop_sentinel",
    "packed_words", "pack_words", "unpack_words",
]


def slot_buffer(spec_tree, slots: int):
    """Zeros of ``[slots + 1, *leaf.shape]`` per leaf (last slot = sentinel)."""
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros((slots + 1,) + tuple(s.shape), s.dtype),
        spec_tree)


def masked_slot_write(buf_tree, val_tree, index, pred, sentinel: int):
    """Write ``val`` at ``index`` where ``pred``, else into the sentinel."""
    widx = jnp.where(pred, index, sentinel)
    return jax.tree_util.tree_map(
        lambda buf, v: jax.lax.dynamic_update_index_in_dim(
            buf, v.astype(buf.dtype), widx, 0),
        buf_tree, val_tree)


def drop_sentinel(buf_tree, slots: int):
    """The real slots: ``leaf[:slots]`` per leaf."""
    return jax.tree_util.tree_map(lambda b: b[:slots], buf_tree)


# ---------------------------------------------------------------------------
# Packed word carrier: one flat uint32 buffer per transport direction
# ---------------------------------------------------------------------------
#
# The overlapped executors move each direction's whole boundary pytree
# (activations + forward skip lanes; gradients + reverse lanes) as ONE
# contiguous ``uint32[N]`` vector, so each scan cycle issues exactly one
# ``ppermute`` per direction regardless of how many leaves, dtypes or lanes
# ride along. Packing is a pure bitcast/reshape — bitwise exact for every
# dtype (bf16 riding next to f32 loses nothing), no casts, no copies beyond
# the concatenation XLA fuses into the collective's source buffer.
#
# Layout: leaves in ``tree_leaves`` order; each leaf is raveled, padded to a
# whole number of 32-bit words, and bitcast to uint32. The layout is static
# (shapes/dtypes known at trace time) so unpacking slices at fixed offsets.

_WORD = 4  # bytes per packed word


def _leaf_words(shape, dtype) -> int:
    size = int(np.prod(shape, dtype=np.int64)) if shape else 1
    nbytes = size * np.dtype(dtype).itemsize
    return -(-nbytes // _WORD)


def packed_words(spec_tree) -> int:
    """Total uint32 words ``pack_words`` produces for this spec (leaves need
    only ``.shape``/``.dtype``)."""
    return sum(_leaf_words(leaf.shape, leaf.dtype)
               for leaf in jax.tree_util.tree_leaves(spec_tree))


def _pack_leaf(x):
    if x.dtype == jnp.bool_:
        raise TypeError("pack_words: bool leaves have no defined bit "
                        "layout; cast to uint8 first")
    itemsize = np.dtype(x.dtype).itemsize
    flat = x.reshape(-1)
    if itemsize >= _WORD:
        w = jax.lax.bitcast_convert_type(flat, jnp.uint32)
        return w.reshape(-1)
    r = _WORD // itemsize
    pad = (-flat.size) % r
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return jax.lax.bitcast_convert_type(flat.reshape(-1, r), jnp.uint32)


def pack_words(tree):
    """Pack a pytree of arrays into one flat ``uint32`` vector (bitwise)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((0,), jnp.uint32)
    return jnp.concatenate([_pack_leaf(x) for x in leaves])


def _unpack_leaf(words, shape, dtype):
    itemsize = np.dtype(dtype).itemsize
    size = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if itemsize >= _WORD:
        k = itemsize // _WORD
        x = jax.lax.bitcast_convert_type(
            words.reshape(-1, k) if k > 1 else words, dtype)
    else:
        r = _WORD // itemsize
        x = jax.lax.bitcast_convert_type(words, _uint_of(itemsize))
        x = x.reshape(-1)[:size]
        if x.dtype != np.dtype(dtype):
            x = jax.lax.bitcast_convert_type(x, dtype)
    return x.reshape(shape)


def _uint_of(itemsize: int):
    return {1: jnp.uint8, 2: jnp.uint16}[itemsize]


def unpack_words(vec, spec_tree):
    """Inverse of :func:`pack_words` given the (static) spec of the packed
    tree; slices at fixed offsets, bitwise exact."""
    leaves, treedef = jax.tree_util.tree_flatten(spec_tree)
    out, off = [], 0
    for leaf in leaves:
        nw = _leaf_words(leaf.shape, np.dtype(leaf.dtype))
        out.append(_unpack_leaf(
            jax.lax.dynamic_slice_in_dim(vec, off, nw), tuple(leaf.shape),
            np.dtype(leaf.dtype)))
        off += nw
    return jax.tree_util.tree_unflatten(treedef, out)
