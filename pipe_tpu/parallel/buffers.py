"""Sentinel-slot buffer idiom shared by the compiled executors.

A per-cycle conditional buffer update (``lax.cond`` around a
``dynamic_update_index_in_dim``) costs a real branch in the scan hot loop.
The executors instead allocate one extra *sentinel* slot and always write,
masking only the index::

    buf   = slot_buffer(spec_tree, m)          # m real slots + 1 sentinel
    buf   = masked_slot_write(buf, val, i, pred, m)
    real  = drop_sentinel(buf, m)              # [:m]

Invalid cycles land in slot ``m`` (never read, dropped at the end), valid
ones in their real slot — uniform per-cycle code, no branch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["slot_buffer", "masked_slot_write", "drop_sentinel"]


def slot_buffer(spec_tree, slots: int):
    """Zeros of ``[slots + 1, *leaf.shape]`` per leaf (last slot = sentinel)."""
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros((slots + 1,) + tuple(s.shape), s.dtype),
        spec_tree)


def masked_slot_write(buf_tree, val_tree, index, pred, sentinel: int):
    """Write ``val`` at ``index`` where ``pred``, else into the sentinel."""
    widx = jnp.where(pred, index, sentinel)
    return jax.tree_util.tree_map(
        lambda buf, v: jax.lax.dynamic_update_index_in_dim(
            buf, v.astype(buf.dtype), widx, 0),
        buf_tree, val_tree)


def drop_sentinel(buf_tree, slots: int):
    """The real slots: ``leaf[:slots]`` per leaf."""
    return jax.tree_util.tree_map(lambda b: b[:slots], buf_tree)
