"""Schedule-table executor: hand-scheduled forward+backward in ONE scan.

The reference has no backward scheduler at all — backward order is discovered
at runtime by the C++ autograd engine walking fork/join/Copy/Wait nodes
(``pipeline.py:128-132``; ``README.md:106-183,219-237``), which is precisely
why its 1F1B-style memory release works: each micro-batch's backward runs as
soon as its gradient arrives, freeing activations early. The AD executor
(:mod:`.spmd`) gets correctness from ``jax.grad``-of-``scan`` but inherits
GPipe's O(m) activation liveness: every micro-batch's residuals survive until
the scan's backward.

This module instead compiles the *whole* training step — forward, backward,
loss, gradient accumulation — as one ``lax.scan`` over ``2(m+n-1)`` uniform
clock slots, driven by static (cycle, stage) → (op, micro-batch) tables
emitted by :meth:`core.schedule.Schedule.op_tables`. Per cycle each device
either

* **FWD**: runs its stage on one micro-batch (stashing the stage *input* in a
  ring buffer), or
* **BWD**: re-runs the stage from the stashed input under ``jax.vjp`` and
  applies the cotangent arriving from the next stage (manual remat — the
  compiled analogue of ``Recompute.backward`` re-running forward just before
  ``Checkpoint.backward`` consumes it, ``README.md:450-537``), or
* **IDLE**: passes through (a fill/drain bubble slot).

Transport is two ``ppermute`` rings — activations j→j+1, cotangents j+1→j —
shifted every cycle; the tables guarantee a value is consumed exactly when it
arrives (gradients) or is parked in the stash until its cycle (activations).

What this buys over the AD executor:

* **True 1F1B**: with ``schedule='1f1b'`` the stashed-input buffer holds at
  most ``min(m, n)`` micro-batches (vs GPipe's ``m``) — the activation-memory
  cap that is the entire point of the reference's fork/join machinery.
* **Exact ``except_last``**: per-micro-batch remat policy with *uniform*
  per-cycle code: micro-batch m-1's vjp residuals are saved at forward time
  (a flattened-``vjp_fn`` pytree carried in the scan), every other micro-batch
  recomputes — sidestepping the jax 0.9.0 ``cond``+remat+PRNG bug that forces
  the AD executor's static remat (see ``spmd.py`` module docstring). Matches
  the reference mode map ``pipe.py:354`` exactly on the compiled path.
* **Schedules as data**: any table satisfying
  :func:`core.schedule.verify_op_tables` runs unmodified.

Checkpoint-mode → storage map (per stage):

=============  =====================  ==========================
mode           stashed inputs         stored vjp residuals
=============  =====================  ==========================
always         S slots                none (recompute all)
except_last    S slots                1 slot (micro-batch m-1)
never          S slots                S slots (recompute none)
=============  =====================  ==========================

with S = ``schedule.stash_slots(m, n)`` = m for GPipe, min(m, n) for 1F1B.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..core.partition import StageCtx
from ..core.remat import validate_mode
from ..core.schedule import (BWD, FWD, GPipeSchedule, OneFOneBSchedule,
                             Schedule, get_schedule)
from .mesh import DATA_AXIS, STAGE_AXIS

__all__ = ["ScheduledPipeline"]


def _index(tree, i):
    return jax.tree_util.tree_map(
        lambda l: jax.lax.dynamic_index_in_dim(l, i, 0, keepdims=False), tree)


@dataclasses.dataclass
class ScheduledPipeline:
    """Training executor: ``loss_and_grad`` on a ``(stage[, data])`` mesh.

    Args:
      mesh: mesh with a ``stage`` axis (and optionally ``data``/others).
      stage_fn: ``(params_j, h, ctx) -> h`` homogeneous stage body (ring
        invariant: input/output activation shapes identical).
      pre_fn: ``(pre_params, x_mb, ctx) -> h``, run on stage 0 (embed).
      post_fn: ``(post_params, h, x_mb, ctx) -> per-row loss [rows]``, run on
        stage n-1. Training executors always compute loss in-pipeline (the
        reference moves targets to the last GPU for the same reason,
        ``main.py:216``).
      checkpoint: ``always | except_last | never`` — exact per-micro-batch
        policy (reference ``pipe.py:354``).
      schedule: ``'gpipe' | '1f1b'`` or a :class:`Schedule` with op tables.
    """

    mesh: Mesh
    stage_fn: Callable
    pre_fn: Callable
    post_fn: Callable
    checkpoint: str = "except_last"
    schedule: Any = "1f1b"
    context_axis: Optional[str] = None
    context_dim: int = 2

    def __post_init__(self):
        validate_mode(self.checkpoint)
        if STAGE_AXIS not in self.mesh.axis_names:
            raise ValueError(f"mesh must have a {STAGE_AXIS!r} axis")
        if isinstance(self.schedule, str):
            self.schedule = get_schedule(self.schedule)
        if not isinstance(self.schedule, (GPipeSchedule, OneFOneBSchedule)):
            # anything emitting valid op tables works; these two are shipped
            if not hasattr(self.schedule, "op_tables"):
                raise ValueError(
                    f"schedule {self.schedule!r} has no op_tables")
        self.n_stages = self.mesh.shape[STAGE_AXIS]
        self.has_data_axis = DATA_AXIS in self.mesh.axis_names
        if self.context_axis and self.context_axis not in self.mesh.axis_names:
            raise ValueError(
                f"mesh has no {self.context_axis!r} axis for context_axis")

    # -----------------------------------------------------------------
    def memory_plan(self, m: int) -> dict:
        """Static per-stage buffer counts — the memory story, inspectable."""
        n = self.n_stages
        S = self.schedule.stash_slots(m, n)
        R = {"always": 0, "except_last": 1, "never": S}[self.checkpoint]
        return {"cycles": 2 * (m + n - 1), "stash_slots": S,
                "residual_slots": R}

    # -----------------------------------------------------------------
    def loss_and_grad(self, stage_params, pre_params, post_params, x, w,
                      *, key: Optional[jax.Array] = None):
        """One pipelined step: returns ``(loss, (g_stage, g_pre, g_post))``.

        ``x``: pytree of ``[m, rows, ...]`` micro-batched arrays;
        ``w``: ``[m, rows]`` per-row loss weights (0 for padding rows — the
        loss is ``sum(w * per_row) / sum(w)``).
        """
        x_leaves = jax.tree_util.tree_leaves(x)
        if not x_leaves:
            raise TypeError("x must contain at least one array leaf")
        m = x_leaves[0].shape[0]
        key = key if key is not None else jax.random.key(0)
        data = DATA_AXIS if self.has_data_axis else None

        def x_spec(l):
            spec = [None, data] + [None] * (l.ndim - 2)
            if self.context_axis and l.ndim > self.context_dim:
                spec[self.context_dim] = self.context_axis
            return P(*spec)

        in_specs = (
            jax.tree_util.tree_map(lambda _: P(STAGE_AXIS), stage_params),
            jax.tree_util.tree_map(lambda _: P(), pre_params),
            jax.tree_util.tree_map(lambda _: P(), post_params),
            jax.tree_util.tree_map(x_spec, x),
            P(None, data),                # w
            P(),                          # key
        )
        out_specs = (
            P(),                          # loss
            (jax.tree_util.tree_map(lambda _: P(STAGE_AXIS), stage_params),
             jax.tree_util.tree_map(lambda _: P(), pre_params),
             jax.tree_util.tree_map(lambda _: P(), post_params)),
        )
        run = jax.shard_map(
            functools.partial(self._device_program, m=m),
            mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False)
        return run(stage_params, pre_params, post_params, x, w, key)

    # -----------------------------------------------------------------
    def _f_full(self, params_j, prep, postp, h_in, x_mb, w_mb, kij, j):
        """The per-(cycle, stage) forward: pre (stage 0 only) → body → loss
        contribution (stage n-1 only). Everything the backward needs to
        differentiate is an explicit argument — no closure over device state
        (in particular no collective-derived values like the global weight
        sum, which would change the vjp residual structure under shard_map) —
        so the residual structure is derivable abstractly. The contribution is
        UNNORMALIZED (``sum(w * per_row)``); the executor divides the loss and
        scales the backward seed by ``1/sum(w)``."""
        n = self.n_stages
        train = True
        h0 = jax.lax.cond(
            j == 0,
            lambda: self.pre_fn(prep, x_mb,
                                StageCtx(key=jax.random.fold_in(kij, 0),
                                         train=train)),
            lambda: h_in)
        h1 = self.stage_fn(params_j, h0,
                           StageCtx(key=jax.random.fold_in(kij, 1),
                                    train=train))
        contrib = jax.lax.cond(
            j == n - 1,
            lambda: jnp.sum(
                w_mb * self.post_fn(postp, h1, x_mb,
                                    StageCtx(key=jax.random.fold_in(kij, 2),
                                             train=train))
            ).astype(jnp.float32),
            lambda: jnp.zeros((), jnp.float32))
        return h1, contrib

    def _vjp_wrt(self, params_j, prep, postp, h_in, x_mb, w_mb, kij, j):
        """vjp of :meth:`_f_full` w.r.t. (stage params, pre, post, h_in)."""
        return jax.vjp(
            lambda a, b, c, d: self._f_full(a, b, c, d, x_mb, w_mb, kij, j),
            params_j, prep, postp, h_in)

    # -----------------------------------------------------------------
    def _device_program(self, stage_params, pre_params, post_params, x, w,
                        key, *, m):
        n = self.n_stages
        j = jax.lax.axis_index(STAGE_AXIS)
        params_j = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        plan = self.memory_plan(m)
        S, R = plan["stash_slots"], plan["residual_slots"]
        mode = self.checkpoint

        # Total loss weight, global over the data axis (w is replicated over
        # stage/context) — contributions are pre-divided so loss and grads
        # come out as the masked mean.
        wsum = jnp.sum(w).astype(jnp.float32)
        if self.has_data_axis:
            wsum = jax.lax.psum(wsum, DATA_AXIS)

        # --- local shape specs -------------------------------------------
        ctx0 = StageCtx(key=None, train=True)
        x_mb_spec = jax.eval_shape(lambda a: _index_spec(a), x)
        w_mb_spec = jax.eval_shape(lambda a: _index_spec(a), w)
        h_spec = jax.eval_shape(
            lambda p, a: self.pre_fn(p, a, ctx0), pre_params, x_mb_spec)

        # Canonical vjp structure (abstract — no tracers leak in):
        i32 = jax.ShapeDtypeStruct((), jnp.int32)
        key_spec = jax.eval_shape(lambda: jax.random.key(0))
        (_, _), vjp_fn_spec = jax.eval_shape(
            self._vjp_wrt, params_j, pre_params, post_params, h_spec,
            x_mb_spec, w_mb_spec, key_spec, i32)
        res_specs, res_treedef = jax.tree_util.tree_flatten(vjp_fn_spec)
        inv_wsum = 1.0 / wsum

        # --- schedule tables (static data → scan xs) ---------------------
        op_np, mb_np = self.schedule.op_tables(m, n)
        T = op_np.shape[0]
        # rx[t, j]: the ring value arriving at stage j at cycle t is stage
        # j-1's cycle-(t-1) output — a real activation iff that was a FWD.
        rxop_np = np.full((T, n), 0, np.int32)
        rxmb_np = np.zeros((T, n), np.int32)
        rxop_np[1:, 1:] = (op_np[:-1, :-1] == FWD).astype(np.int32)
        rxmb_np[1:, 1:] = mb_np[:-1, :-1]
        xs = (jnp.asarray(op_np), jnp.asarray(mb_np),
              jnp.asarray(rxop_np), jnp.asarray(rxmb_np))

        # --- carry -------------------------------------------------------
        def zeros_of(spec):
            return jnp.zeros(spec.shape, spec.dtype)

        def slots_of(spec, k):
            # one extra garbage slot so masked writes need no read-back
            return jnp.zeros((k + 1,) + tuple(spec.shape), spec.dtype)

        h_ring = jax.tree_util.tree_map(zeros_of, h_spec)
        g_ring = jax.tree_util.tree_map(zeros_of, h_spec)
        stash = jax.tree_util.tree_map(lambda s: slots_of(s, S), h_spec)
        res_store = ([slots_of(s, R if mode == "never" else 1)
                      for s in res_specs] if mode != "always" else [])
        g_sp = jax.tree_util.tree_map(jnp.zeros_like, params_j)
        g_pre = jax.tree_util.tree_map(jnp.zeros_like, pre_params)
        g_post = jax.tree_util.tree_map(jnp.zeros_like, post_params)
        loss0 = jnp.zeros((), jnp.float32)

        fwd_perm = [(k, k + 1) for k in range(n - 1)]
        bwd_perm = [(k + 1, k) for k in range(n - 1)]

        def res_slot_for(i):
            """Where micro-batch i's residuals live (garbage slot if unsaved)."""
            if mode == "never":
                return i % S
            # except_last: slot 0 holds micro-batch m-1, slot 1 is garbage
            return jnp.where(i == m - 1, 0, 1)

        def cycle(carry, row):
            h_ring, g_ring, stash, res_store, g_sp, g_pre, g_post, loss = carry
            op_r, mb_r, rxop_r, rxmb_r = row
            opj = jax.lax.dynamic_index_in_dim(op_r, j, 0, keepdims=False)
            i = jax.lax.dynamic_index_in_dim(mb_r, j, 0, keepdims=False)
            rxv = jax.lax.dynamic_index_in_dim(rxop_r, j, 0, keepdims=False)
            rxi = jax.lax.dynamic_index_in_dim(rxmb_r, j, 0, keepdims=False)

            # 1) park the arriving activation (garbage slot when not real)
            rslot = jnp.where(rxv == 1, rxi % S, S)
            stash = jax.tree_util.tree_map(
                lambda st, hr: jax.lax.dynamic_update_index_in_dim(
                    st, hr, rslot, 0), stash, h_ring)

            kij = jax.random.fold_in(jax.random.fold_in(key, i), j)
            x_mb = _index(x, i)
            w_mb = _index(w, i)
            h_in = jax.tree_util.tree_map(
                lambda st: jax.lax.dynamic_index_in_dim(
                    st, i % S, 0, keepdims=False), stash)

            def fwd_branch():
                if mode == "always":
                    h1, contrib = self._f_full(
                        params_j, pre_params, post_params, h_in, x_mb, w_mb,
                        kij, j)
                    new_res = res_store
                else:
                    (h1, contrib), vjp_fn = self._vjp_wrt(
                        params_j, pre_params, post_params, h_in, x_mb, w_mb,
                        kij, j)
                    leaves = jax.tree_util.tree_leaves(vjp_fn)
                    assert [(l.shape, l.dtype) for l in leaves] == \
                        [(s.shape, s.dtype) for s in res_specs], \
                        "vjp residual structure drifted from abstract spec"
                    slot = res_slot_for(i) if mode == "except_last" else i % S
                    new_res = [
                        jax.lax.dynamic_update_index_in_dim(st, l, slot, 0)
                        for st, l in zip(res_store, leaves)]
                return (new_res, g_sp, g_pre, g_post, loss + contrib,
                        h1, g_ring)

            def bwd_branch():
                seed_h = jax.tree_util.tree_map(
                    lambda g: jnp.where(j == n - 1, jnp.zeros_like(g), g),
                    g_ring)
                # contribution cotangent: d(masked mean)/d(contrib) = 1/sum(w)
                seed = (seed_h, inv_wsum)

                def apply_stored():
                    slot = res_slot_for(i) if mode == "except_last" else i % S
                    leaves = [
                        jax.lax.dynamic_index_in_dim(st, slot, 0,
                                                     keepdims=False)
                        for st in res_store]
                    vjp_fn = jax.tree_util.tree_unflatten(res_treedef, leaves)
                    return vjp_fn(seed)

                def apply_recomputed():
                    _, vjp_fn = self._vjp_wrt(
                        params_j, pre_params, post_params, h_in, x_mb, w_mb,
                        kij, j)
                    return vjp_fn(seed)

                if mode == "never":
                    gp, gpre, gpost, gh = apply_stored()
                elif mode == "always":
                    gp, gpre, gpost, gh = apply_recomputed()
                else:  # except_last: stored for m-1, recomputed otherwise
                    gp, gpre, gpost, gh = jax.lax.cond(
                        i == m - 1, apply_stored, apply_recomputed)
                add = functools.partial(jax.tree_util.tree_map, jnp.add)
                return (res_store, add(g_sp, gp), add(g_pre, gpre),
                        add(g_post, gpost), loss, h_ring, gh)

            def idle_branch():
                return (res_store, g_sp, g_pre, g_post, loss, h_ring, g_ring)

            res_store2, g_sp2, g_pre2, g_post2, loss2, tx_h, tx_g = \
                jax.lax.switch(opj, [idle_branch, fwd_branch, bwd_branch])

            if n > 1:
                tx_h = jax.tree_util.tree_map(
                    lambda a: jax.lax.ppermute(a, STAGE_AXIS, fwd_perm), tx_h)
                tx_g = jax.tree_util.tree_map(
                    lambda a: jax.lax.ppermute(a, STAGE_AXIS, bwd_perm), tx_g)
            return (tx_h, tx_g, stash, res_store2, g_sp2, g_pre2, g_post2,
                    loss2), None

        carry0 = (h_ring, g_ring, stash, res_store, g_sp, g_pre, g_post,
                  loss0)
        (_, _, _, _, g_sp, g_pre, g_post, loss), _ = jax.lax.scan(
            cycle, carry0, xs)

        # --- cross-device reductions ------------------------------------
        # stage grads: per-stage shards stay put; replicas over other axes sum
        other_axes = tuple(a for a in self.mesh.axis_names if a != STAGE_AXIS)
        if other_axes:
            g_sp = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, other_axes), g_sp)
        # pre/post grads + loss: only edge stages contributed; psum collects
        reduce_axes = (STAGE_AXIS,) + other_axes
        g_pre = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, reduce_axes), g_pre)
        g_post = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, reduce_axes), g_post)
        loss_axes = ((STAGE_AXIS, DATA_AXIS) if self.has_data_axis
                     else (STAGE_AXIS,))
        loss = jax.lax.psum(loss, loss_axes) * inv_wsum

        g_sp = jax.tree_util.tree_map(lambda g: g[None], g_sp)
        return loss, (g_sp, g_pre, g_post)


def _index_spec(tree):
    return jax.tree_util.tree_map(lambda l: l[0], tree)
