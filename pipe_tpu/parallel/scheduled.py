"""Schedule-table executor: hand-scheduled forward+backward in ONE scan.

The reference has no backward scheduler at all — backward order is discovered
at runtime by the C++ autograd engine walking fork/join/Copy/Wait nodes
(``pipeline.py:128-132``; ``README.md:106-183,219-237``), which is precisely
why its 1F1B-style memory release works: each micro-batch's backward runs as
soon as its gradient arrives, freeing activations early. The AD executor
(:mod:`.spmd`) gets correctness from ``jax.grad``-of-``scan`` but inherits
GPipe's O(m) activation liveness: every micro-batch's residuals survive until
the scan's backward.

This module instead compiles the *whole* training step — forward, backward,
loss, gradient accumulation — as one ``lax.scan`` over uniform clock slots,
driven by static (cycle, device) → (op, micro-batch, group) tables emitted
by :meth:`core.schedule.Schedule.op_tables`. Per cycle each device either

* **FWD**: runs one of its stage bodies on one micro-batch (stashing the
  stage *input* in a ring buffer), or
* **BWD**: re-runs the stage from the stashed input under ``jax.vjp`` and
  applies the cotangent arriving from the next stage (manual remat — the
  compiled analogue of ``Recompute.backward`` re-running forward just before
  ``Checkpoint.backward`` consumes it, ``README.md:450-537``), or
* **IDLE**: passes through (a fill/drain bubble slot).

Transport is two ``ppermute`` rings — activations one hop forward,
cotangents one hop backward — shifted every cycle; the tables guarantee a
value is consumed exactly when it arrives (gradients) or is parked in the
stash until its cycle (activations).

What this buys over the AD executors:

* **True 1F1B**: with ``schedule='1f1b'`` the stashed-input buffer holds at
  most ``min(m, n)`` micro-batches (vs GPipe's ``m``) — the activation-memory
  cap that is the entire point of the reference's fork/join machinery.
* **Interleaved 1F1B** (``schedule='interleaved-1f1b'``): each device hosts
  ``v`` non-adjacent virtual stages (virtual stage ``s`` on device
  ``s % d``), every boundary is one hop on the WRAPAROUND ring, and both
  passes come from the same static table — the fill bubble shrinks vs plain
  1F1B of the same depth while keeping the 1F1B memory story
  (:class:`~pipe_tpu.core.schedule.InterleavedOneFOneBSchedule`).
* **Exact ``except_last``**: per-micro-batch remat policy with *uniform*
  per-cycle code: micro-batch m-1's vjp residuals are saved at forward time
  (a flattened-``vjp_fn`` pytree carried in the scan), every other micro-batch
  recomputes — sidestepping the jax 0.9.0 ``cond``+remat+PRNG bug that forces
  the AD executor's static remat (see ``spmd.py`` module docstring). Matches
  the reference mode map ``pipe.py:354`` exactly on the compiled path.
* **Schedules as data**: any table satisfying the
  :mod:`core.schedule` verifiers runs unmodified.

Checkpoint-mode → storage map (per device; ``Sg`` = per-virtual-stage stash
slots = ``schedule.stash_slots(m, d)``, ``v`` = interleave depth):

=============  =====================  ==========================
mode           stashed inputs         stored vjp residuals
=============  =====================  ==========================
always         v·Sg slots             none (recompute all)
except_last    v·Sg slots             v slots (micro-batch m-1)
never          v·Sg slots             v·Sg slots (recompute none)
=============  =====================  ==========================

plus ``Sg`` activation-sized slots parking the last virtual stage's outputs.
The post (decode/loss) is NEVER part of the stored residuals — its vjp is
rebuilt fresh at backward time from the parked output, because post residuals
are vocab-scale (a [rows, seq, vocab] logits tensor plus a weight-cast copy,
hundreds of MB at tutorial scale) and slot structure replicates across every
slot; folding the post in OOMed a 16G v5e on the 520M tutorial config.

Parameter layout: the stage axis stacks all ``v·d`` virtual stages
device-major (``stack_interleaved_params`` ordering: global row ``p·v + g``
= virtual stage ``g·d + p``), so each device's shard is its ``v`` groups in
order; ``v = 1`` reduces to plain per-stage stacking and reproduces the
non-interleaved executor exactly (same tables, same key folds).
"""

from __future__ import annotations

import dataclasses
import functools
import warnings
from typing import Any, Callable, Optional
from ..utils.compat import shard_map

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..core.partition import StageCtx
from ..core.remat import validate_mode
from ..core.schedule import (BWD, FWD, IDLE, WGRAD, GPipeSchedule,
                             InterleavedOneFOneBSchedule, OneFOneBSchedule,
                             Schedule, compile_phases, get_schedule,
                             shift_comm_tables, verify_shifted_op_tables,
                             overlap_joint_capacity, _times_by_code)
from .buffers import pack_words, packed_words, unpack_words
from .mesh import DATA_AXIS, MODEL_AXIS, STAGE_AXIS
from ..obs.telemetry import get_registry
from ..utils.rng import make_key

__all__ = ["ScheduledPipeline", "SplitBackwardStage", "SkipLanes"]


@dataclasses.dataclass(frozen=True)
class SkipLanes:
    """Cross-stage ``@skippable`` carries through the table executor.

    The wavefront executor's skip lanes (``hetero.py``) need no parking:
    device ``j`` computes micro-batch ``i`` at cycle ``i+j``, so a value
    emitted at the source is consumed the cycle it arrives. Table
    schedules (1F1B) interleave B ops, so arrival and consumption
    decouple — the compiled analogue of the reference's portals riding
    copy streams inside the training fence (``pipeline.py:136-138``).
    Mechanism, all static at trace time:

    * forward: the stash value boards a per-lane register and takes ONE
      direct ``ppermute`` hop ``src % d -> dst % d`` (the lane has its
      own permute, so it never relays through intermediate devices —
      less ICI traffic than a hop-per-cycle ring, and on wrapped
      interleaved placements a transiting value cannot collide with a
      fresh stash at its source device, which is what previously kept
      skips off v > 1). It is captured into a FIFO park at the
      destination at its host-computed arrival cycle (``FWD(i, src) + 1``)
      and read at FWD(i, dst) — and re-read at BWD(i, dst) under
      recompute modes, exactly like the activation stash. Lanes whose
      endpoints share a device (possible when v > 1) skip the permute:
      the register IS the transport;
    * backward: BWD(i, dst)'s vjp yields the pop cotangent, which takes
      the reverse direct hop to the source and seeds the stash output of
      BWD(i, src)'s vjp — the compiled ``PortalOrange``/``PortalBlue``
      pair;
    * park sizes are the smallest FIFO depths with no live-window
      collision, computed from the op tables per lane.

    With lanes configured the stage contract becomes
    ``stage_fn(params_g, h, ctx, pops) -> (h, stashes)`` where ``pops``/
    ``stashes`` are tuples over lanes — a stage reads only the lanes it
    pops and must return zeros (of the lane spec) for lanes it does not
    stash. Requires a non-split-backward schedule.

    ``pairs[l] = (src, dst)`` virtual stage indices (``src < dst``);
    ``specs[l]`` is the lane's value pytree of ShapeDtypeStructs.
    """

    pairs: tuple
    specs: tuple


@dataclasses.dataclass(frozen=True)
class SplitBackwardStage:
    """Structural B/W split of a stage body (zero-bubble's real contract).

    The round-3 audit (docs/architecture.md) measured that applying a
    stored vjp at both B and W executes the FULL transpose twice — XLA
    does not prune the unused outputs inside switch branches. This
    protocol makes the split structural instead of hoped-for:

    * ``tapped_fn(params_g, h, ctx, zs) -> (h_out, taps)`` — the stage
      forward with a zero pytree ``zs`` injected at every param-consuming
      op's OUTPUT and the per-op INPUTS returned as ``taps``;
    * the executor takes ``jax.vjp`` w.r.t. ``(pre, h, zs)`` with the
      stage params CLOSED OVER AS CONSTANTS — the stored transpose
      therefore contains zero weight-grad contractions by construction
      (verified by HLO dot census in tests), and applying it at B yields
      the input-grad chain plus ``g_zs``, the per-op output cotangents;
    * ``wgrad_fn(taps, gzs) -> params_g-structured grads`` — the W op:
      nothing but the weight-grad contractions themselves.

    Pair with ``checkpoint='never'`` and a ``splits_backward`` schedule
    (zb-h1); the executor rejects other combinations. Memory: ``taps``
    ride ``Sg`` FIFO slots (FWD -> W window) and ``g_zs`` ride the
    ``Wg`` cotangent-park window — both activation-scale.

    ``zs_fn(params_g, h) -> zeros pytree`` sizes the injection points.
    """

    tapped_fn: Any
    wgrad_fn: Any
    zs_fn: Any

# Auto cutoff for the d == 1 trace-time unroll (ScheduledPipeline
# .static_unroll=None): tables longer than this use the dynamic scan — HLO
# size and temp memory grow with the unroll (observed: 16 unrolled cycles
# OOM a 16G v5e at the 520M tutorial config where 8 fit comfortably).
_STATIC_UNROLL_MAX_CYCLES = 12


def _index(tree, i):
    return jax.tree_util.tree_map(
        lambda l: jax.lax.dynamic_index_in_dim(l, i, 0, keepdims=False), tree)


def _vjp_leaves(vjp_fn, specs):
    """Flatten ``vjp_fn`` into its residual leaves. One flattener for BOTH
    residual stores (full and policy-shaped) so slot layout and the
    structure-drift assert cannot diverge between them. The actual store
    write happens once, post-switch, in the cycle body (sentinel-masked) —
    branches only hand back the leaves, never an updated store, so XLA can
    alias the store across scan iterations."""
    leaves = jax.tree_util.tree_leaves(vjp_fn)
    assert [(l.shape, l.dtype) for l in leaves] == \
        [(sp_.shape, sp_.dtype) for sp_ in specs], \
        "vjp residual structure drifted from abstract spec"
    return leaves


def _load_vjp(store, treedef, slot):
    """Gather ``slot``'s leaves from ``store`` and rebuild the vjp callable
    — the read twin of :func:`_vjp_leaves`."""
    leaves = [jax.lax.dynamic_index_in_dim(st, slot, 0, keepdims=False)
              for st in store]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _is_diff(spec) -> bool:
    return jnp.issubdtype(jnp.asarray(spec).dtype
                          if not hasattr(spec, "dtype") else spec.dtype,
                          jnp.inexact)


def _ring_to_seed(ring_tree, primal_spec):
    """Ring cotangent -> vjp seed: integer (non-differentiable) primal
    lanes — e.g. token ids riding the packed boundary carrier — expect
    ``float0`` cotangents from ``jax.vjp``; the ring parks placeholder
    zeros of the primal dtype for them (see :func:`_vjp_to_ring`)."""
    return jax.tree_util.tree_map(
        lambda rv, sp_: (rv if _is_diff(sp_)
                         else np.zeros(sp_.shape, jax.dtypes.float0)),
        ring_tree, primal_spec)


def _vjp_to_ring(ct_tree, primal_spec):
    """vjp cotangent -> ring value: ``float0`` leaves (int primal lanes)
    become concrete zeros of the PRIMAL dtype so the carry/ppermute pytree
    stays uniform. The zeros are inert — every consumer converts back via
    :func:`_ring_to_seed` before seeding a vjp."""
    return jax.tree_util.tree_map(
        lambda ct, sp_: (jnp.zeros(sp_.shape, sp_.dtype)
                         if ct.dtype == jax.dtypes.float0 else ct),
        ct_tree, primal_spec)


@dataclasses.dataclass
class ScheduledPipeline:
    """Training executor: ``loss_and_grad`` on a ``(stage[, data])`` mesh.

    Args:
      mesh: mesh with a ``stage`` axis (and optionally ``data``/others).
        The stage axis size is the DEVICE count d; with an interleaved
        schedule the model must factor into ``v*d`` virtual stage bodies.
      stage_fn: ``(params_g, h, ctx) -> h`` homogeneous stage body (ring
        invariant: input/output activation shapes identical).
      pre_fn: ``(pre_params, x_mb, ctx) -> h``, run on virtual stage 0.
      post_fn: ``(post_params, h, x_mb, ctx) -> per-row loss [rows]``, run on
        the last virtual stage. Training executors always compute loss
        in-pipeline (the reference moves targets to the last GPU for the
        same reason, ``main.py:216``).
      checkpoint: ``always | except_last | never`` — exact per-micro-batch
        policy (reference ``pipe.py:354``).
      schedule: ``'gpipe' | '1f1b' | 'interleaved-1f1b'`` or a
        :class:`Schedule` with op tables.
    """

    mesh: Mesh
    stage_fn: Callable
    pre_fn: Callable
    post_fn: Callable
    checkpoint: str = "except_last"
    schedule: Any = "1f1b"
    context_axis: Optional[str] = None
    context_dim: int = 2
    # Trace-time static specialization of the tables when the stage axis has
    # ONE device (see _device_program_static): None = auto (on when the
    # table has <= _STATIC_UNROLL_MAX_CYCLES cycles), True = force, False =
    # always use the dynamic scan. The static program is branch-free (2.3x
    # faster at tutorial scale: no conditional-copy traffic) but its HLO
    # size and temp footprint grow with the unroll — at m=8 on the 520M
    # config it exceeds a 16G chip where the dynamic path fits; set False
    # (or rely on the cycle cap) in that regime.
    static_unroll: Optional[bool] = None
    # Per-leaf PartitionSpecs for ONE stage's param tree over the leaf's
    # OWN dims (tensor parallelism): e.g. a Megatron block's
    # ``{"wqkv": P(None, None, 'model', None), ...}`` — the executor
    # prepends the stage axis for the stacked layout, hands each device
    # its local shard inside shard_map, and NEVER reduces gradients over
    # the model axis (the TP grad contract: sharded leaves' grads are
    # local by construction, replicated leaves' grads are model-identical
    # via the block's tp_enter operator — see ops/tp_layers.py). None =
    # every leaf replicated over non-stage axes (the homogeneous default).
    stage_param_specs: Optional[Any] = None
    # Structural B/W split of the stage body for zero-bubble schedules —
    # see :class:`SplitBackwardStage`. Requires checkpoint='never' and a
    # splits_backward schedule; replaces stage_fn for fwd/bwd purposes.
    # The string "auto" derives the split from stage_fn by jaxpr surgery
    # (core.remat.split_backward_stage) — works for any stage body whose
    # params enter linearly (matmuls/scales/casts; see SplitUnsupported).
    split_stage: Optional[Any] = None
    # Selective rematerialization for the RECOMPUTE micro-batches (a
    # ``jax.checkpoint_policies`` member, e.g. ``dots_saveable``): instead
    # of stashing the stage input and re-running the whole forward at
    # backward time, the forward stores the policy-saved residual subset
    # (matmul outputs) and the backward recomputes only the cheap
    # elementwise remainder — the FLOPs-vs-HBM dial the reference's
    # all-or-nothing Checkpoint lacks. The per-micro-batch mode semantics
    # are unchanged: SAVED micro-batches (never: all; except_last: m-1)
    # still store full residuals. Works on the d=1 static program AND the
    # d>1 dynamic scan: the policy-saved residual pytree differs from the
    # full set, so the dynamic path carries TWO slot stores — the full
    # store (saved micro-batches) and a policy-shaped store (recompute
    # micro-batches) — each internally uniform, with cond-gated
    # writes/reads selecting between them per micro-batch. Inert (a
    # warning) under checkpoint='never', where every micro-batch stores
    # full residuals anyway.
    remat_policy: Optional[Any] = None
    # Cross-stage @skippable carries — see :class:`SkipLanes`. Changes the
    # stage_fn contract to (params_g, h, ctx, pops) -> (h, stashes).
    skip_lanes: Optional[SkipLanes] = None
    # Per-step stat lanes (deferred BatchNorm, reference batchnorm.py via
    # pipe.py:341-342): a pytree spec of per-step accumulators, uniform
    # across stages (each stage fills only its own slots, zeros elsewhere;
    # values must be stop_gradient'd at source). The stage contract
    # appends a stats output — (h[, stashes], stats) — and loss_and_grad
    # returns ``(loss, grads, stats)``; stats accumulate over FWD ops ONLY
    # (a BWD recompute re-computes and discards them, so recompute modes
    # cannot double-count) and are summed over the stage/data axes.
    stat_spec: Optional[Any] = None
    # Overlapped (software-pipelined) boundary transport: each direction's
    # boundary pytree (activations + riding skip lanes forward, cotangents
    # + reverse lanes backward) packs into ONE flat uint32 buffer
    # (buffers.pack_words), the scan carry double-buffers it, and the
    # single per-direction ppermute launches at the START of each cycle —
    # moving cycle t-1's sends while cycle t computes. Requires the comm-
    # shifted op tables (core.schedule.shift_comm_tables): every consumer
    # is retimed >= 2 cycles behind its producer and the shifted tables are
    # re-verified at trace time (verify_shifted_op_tables). None = auto: ON
    # for d > 1 on accelerator backends (async collectives overlap
    # compute), OFF on CPU meshes (XLA:CPU's ppermute is a blocking
    # rendezvous, so the longer shifted tables only add cycles) and always
    # OFF at d == 1 (no transport). True/False force it for d > 1. Results
    # are bitwise-identical to the serialized path: the retimer preserves
    # per-device op order, and packing is a pure bitcast.
    overlap_transport: Optional[bool] = None
    # Phase-compiled execution (core.schedule.compile_phases): the op table
    # is re-timed into cycle-uniform phases and each phase lowers
    # separately — warmup/cooldown ramps unroll to straight-line code
    # (each cycle's single op code is a trace-time constant; partially
    # idle cycles mask their stores/accumulators by data selects), and the
    # dense periodic steady state lowers to a fixed-body ``lax.scan``
    # whose body is the period's concrete (fwd, bwd[, wgrad]) sequence —
    # NO ``lax.switch`` dispatch and NO sentinel-masked no-op branches:
    # every device runs real work every steady cycle. Rides the packed
    # double-buffered overlap transport (the aligner emits hop-2 tables)
    # and the (values, slot) store discipline, so XLA buffer aliasing
    # survives. None = auto: ON for d > 1 on accelerator backends when the
    # compiler accepts the table, OFF on CPU meshes (explicit True forces
    # it anywhere, which is how the cpu8 probes run it). Tables the
    # compiler rejects fall back loudly to the interpreted executor
    # (warnings.warn + the scheduled.phase.rejected counter). Bitwise
    # parity with the interpreted executor: the aligner preserves each
    # (stage, op-code) stream's order — F ops feed loss/stats and B/W ops
    # feed the grad accumulators, disjoint state — so every accumulation
    # order is preserved even though F/B interleaving changes.
    phase_compile: Optional[bool] = None

    def __post_init__(self):
        validate_mode(self.checkpoint)
        if STAGE_AXIS not in self.mesh.axis_names:
            raise ValueError(f"mesh must have a {STAGE_AXIS!r} axis")
        if isinstance(self.schedule, str):
            self.schedule = get_schedule(self.schedule)
        if not isinstance(self.schedule, (GPipeSchedule, OneFOneBSchedule,
                                          InterleavedOneFOneBSchedule)):
            # anything emitting valid op tables works; these are shipped
            if not hasattr(self.schedule, "op_tables"):
                raise ValueError(
                    f"schedule {self.schedule!r} has no op_tables")
        self.n_stages = self.mesh.shape[STAGE_AXIS]      # devices d
        if isinstance(self.split_stage, str):
            if self.split_stage != "auto":
                raise ValueError(
                    f"split_stage must be a SplitBackwardStage or 'auto', "
                    f"got {self.split_stage!r}")
            # derive the tapped/wgrad/zs triple from the stage fn itself
            # (core.remat.split_backward_stage) — any model, no hand-rolled
            # tapped forward
            from ..core.remat import split_backward_stage
            self.split_stage = split_backward_stage(self.stage_fn)
        if self.split_stage is not None:
            if not getattr(self.schedule, "splits_backward", False):
                raise ValueError(
                    "split_stage requires a splits_backward schedule "
                    "(zb-h1): B/W table ops are where the split executes")
            if self.checkpoint != "never":
                raise ValueError(
                    "split_stage requires checkpoint='never': the stored "
                    "params-constant vjp IS the activation store")
            if self.stage_param_specs is not None:
                raise ValueError(
                    "split_stage does not compose with stage_param_specs "
                    "(tensor-parallel sharded stage params): the tapped/"
                    "wgrad fns are written for unsharded math and would "
                    "silently drop the cross-shard psums")
            if self.remat_policy is not None:
                raise ValueError(
                    "split_stage already defines its storage (full "
                    "residuals + taps); remat_policy would be silently "
                    "inert — drop one of the two")
        if self.skip_lanes is not None and not self.skip_lanes.pairs:
            self.skip_lanes = None          # empty lanes = no skips
        if self.skip_lanes is not None:
            if getattr(self.schedule, "splits_backward", False):
                raise NotImplementedError(
                    "skip lanes do not compose with split-backward "
                    "schedules (zb-h1): the W op's params-only grads "
                    "cannot seed the reverse skip ring")
            if self.split_stage is not None:
                raise ValueError(
                    "split_stage's tapped/wgrad fns have no pop/stash "
                    "arguments; skip models use plain stage bodies")
            if self.n_stages < 2:
                raise ValueError(
                    "cross-stage skip lanes need a >=2-device stage axis")
            S = self.schedule.v * self.n_stages
            for (src, dst) in self.skip_lanes.pairs:
                if not (0 <= src < dst < S):
                    raise ValueError(
                        f"skip lane ({src}, {dst}) out of range for "
                        f"{S} stages (need 0 <= src < dst < {S})")
            for sp_ in jax.tree_util.tree_leaves(self.skip_lanes.specs):
                if hasattr(sp_, "dtype") and not jnp.issubdtype(
                        sp_.dtype, jnp.inexact):
                    raise NotImplementedError(
                        f"skip lane values must be float (got "
                        f"{sp_.dtype}): integer lanes would need the "
                        "float0 cotangent plumbing the h carrier has "
                        "(_ring_to_seed/_vjp_to_ring) on the reverse "
                        "skip ring too")
        if self.stat_spec is not None:
            if self.split_stage is not None:
                raise ValueError(
                    "split_stage's tapped/wgrad fns have no stats output; "
                    "stat lanes need plain stage bodies")
            if getattr(self.schedule, "splits_backward", False):
                raise NotImplementedError(
                    "stat lanes do not compose with split-backward "
                    "schedules (zb-h1): the W op's seed has no stats slot")
        if self.remat_policy is not None and self.checkpoint == "never":
            warnings.warn(
                "remat_policy is inert under checkpoint='never': every "
                "micro-batch stores its full residual set and nothing is "
                "recomputed. Use 'always' or 'except_last' to engage the "
                "policy.", stacklevel=2)
        if (getattr(self.schedule, "splits_backward", False)
                and self.checkpoint != "never"):
            warnings.warn(
                f"schedule {self.schedule.name!r} splits backward into B/W "
                f"ops to fill bubble slots with weight-grad compute, but "
                f"checkpoint={self.checkpoint!r} recomputes the forward at "
                f"B and the full backward runs there — the W slots carry no "
                f"compute and the zero-bubble advantage is lost. Pair "
                f"zero-bubble schedules with checkpoint='never'.",
                stacklevel=2)
        self.v = self.schedule.v
        self.n_virtual = self.v * self.n_stages
        self.has_data_axis = DATA_AXIS in self.mesh.axis_names
        # see spmd.SpmdPipeline.bn_axis
        self.bn_axis = (DATA_AXIS if self.has_data_axis
                        and self.mesh.shape[DATA_AXIS] > 1 else None)
        if self.context_axis and self.context_axis not in self.mesh.axis_names:
            raise ValueError(
                f"mesh has no {self.context_axis!r} axis for context_axis")
        # per-m phase-compiler verdicts (host-side analysis, ms-scale, but
        # the reject warning must fire once per (pipeline, m), not per
        # retrace)
        self._phase_cache = {}

    # -----------------------------------------------------------------
    def memory_plan(self, m: int) -> dict:
        """Static per-device buffer counts — the memory story, inspectable.
        Reflects the ACTIVE transport: under overlapped transport the slot
        counts come from the comm-shifted tables (stash windows widen by
        the extra in-flight cycle; a small grad park appears). The
        checkpoint-mode → slot-count arithmetic is the SHARED formula in
        ``core/memplan.py`` — the same one the auto-planner prices
        candidate configs with (``estimate_memory``), so the two cannot
        drift."""
        from ..core.memplan import MemoryPlanInputs, activation_slot_plan
        d, v = self.n_stages, self.v
        phased = self._phase_program(m)
        overlap = phased is not None or self._overlap_enabled()
        Gg = 0
        if phased is not None:
            (op_np, mb_np, grp_np, _, _), _, Sg, Gg, Wg, _, _ = \
                self._host_tables_phased(m)
        elif overlap:
            (op_np, mb_np, grp_np, _, _), _, Sg, Gg, Wg, _, _ = \
                self._host_tables_overlap(m)
        else:
            Sg = self.schedule.stash_slots(m, d)
            Wg = self.schedule.wstash_slots(m, d)
        plan = {"cycles": self._cycles(m),
                **activation_slot_plan(MemoryPlanInputs(
                    v=v, stash_slots=Sg, wstash_slots=Wg,
                    checkpoint=self.checkpoint,
                    has_remat_policy=self.remat_policy is not None,
                    split_stage=self.split_stage is not None,
                    overlap=overlap, grad_park_slots=Gg)),
                "transport": ("phase-compiled" if phased is not None
                              else "overlapped" if overlap
                              else "serialized")}
        if phased is not None:
            plan["phase_segments"] = tuple(
                (s_.kind, s_.t0, s_.t1, s_.period)
                for s_ in phased.segments)
            plan["phase_unrolled_cycles"] = phased.unrolled_cycles
            plan["phase_scan_cycles"] = phased.scan_cycles
        if self.skip_lanes is not None:
            if not overlap:
                tables = self.schedule.op_tables(m, d)
                op_np, mb_np = tables[0], tables[1]
                grp_np = (tables[2] if len(tables) > 2
                          else np.zeros_like(op_np))
            _, _, Kf, Kg = self._skip_tables(m, op_np, mb_np, grp_np,
                                             overlap=overlap)
            plan["skip_lanes"] = len(self.skip_lanes.pairs)
            plan["skip_fwd_park_slots"] = sum(Kf)
            plan["skip_bwd_park_slots"] = sum(Kg)
        return plan

    def _cycles(self, m: int) -> int:
        if self._phase_program(m) is not None:
            return self._phase_program(m).cycles
        if self._overlap_enabled():
            return self._host_tables_overlap(m)[1]
        tables = self.schedule.op_tables(m, self.n_stages)
        return tables[0].shape[0]

    def _overlap_enabled(self) -> bool:
        """Resolve the ``overlap_transport`` tri-state (see field comment).
        Always False at d == 1 — there is no boundary transport to shift."""
        if self.n_stages <= 1:
            return False
        if self.overlap_transport is not None:
            return bool(self.overlap_transport)
        return self.mesh.devices.flat[0].platform != "cpu"

    def _phase_verdict(self, m):
        """Phase-compile this pipeline's table at m (cached per m). On
        rejection: bump the fallback counter and — when the user explicitly
        asked for phase compilation — warn once, naming the reason."""
        if m not in self._phase_cache:
            tables = self.schedule.op_tables(m, self.n_stages)
            op0, mb0 = tables[0], tables[1]
            grp0 = tables[2] if len(tables) > 2 else None
            verdict = compile_phases(op0, mb0, grp0, m=m, d=self.n_stages,
                                     v=self.v)
            if verdict.accepted:
                get_registry().counter("scheduled.phase.compiled").inc()
            else:
                get_registry().counter("scheduled.phase.rejected").inc()
                if self.phase_compile:
                    warnings.warn(
                        f"phase_compile=True but the phase compiler "
                        f"rejected the {self.schedule.name!r} op table at "
                        f"m={m} ({verdict.reason}); falling back to the "
                        f"interpreted table executor", stacklevel=3)
            self._phase_cache[m] = verdict
        return self._phase_cache[m]

    def _phase_program(self, m):
        """Resolve the ``phase_compile`` tri-state to an accepted
        :class:`~pipe_tpu.core.schedule.PhaseProgram`, or None for the
        interpreted executor (disabled, d == 1, auto-off on CPU, or the
        compiler rejected the table — the loud path in _phase_verdict)."""
        if self.n_stages <= 1 or self.phase_compile is False:
            return None
        if (self.phase_compile is None
                and self.mesh.devices.flat[0].platform == "cpu"):
            return None
        verdict = self._phase_verdict(m)
        return verdict.program if verdict.accepted else None

    # -----------------------------------------------------------------
    def loss_and_grad(self, stage_params, pre_params, post_params, x, w,
                      *, key: Optional[jax.Array] = None):
        """One pipelined step: returns ``(loss, (g_stage, g_pre, g_post))``.

        ``x``: pytree of ``[m, rows, ...]`` micro-batched arrays;
        ``w``: ``[m, rows]`` per-row loss weights (0 for padding rows — the
        loss is ``sum(w * per_row) / sum(w)``).
        ``stage_params``: all ``v*d`` virtual stages stacked device-major on
        the leading axis (``stack_stage_params`` for v=1,
        ``stack_interleaved_params`` otherwise).
        """
        x_leaves = jax.tree_util.tree_leaves(x)
        if not x_leaves:
            raise TypeError("x must contain at least one array leaf")
        m = x_leaves[0].shape[0]
        key = key if key is not None else make_key(0)
        data = DATA_AXIS if self.has_data_axis else None
        # Lowering counters: these fire at TRACE time (this method runs
        # inside the caller's jit trace), so they count compiles/retraces,
        # not executions — a growing count on a steady workload is the
        # compile-cache-miss signal.
        get_registry().counter("scheduled.loss_and_grad.lowerings").inc()
        get_registry().gauge("scheduled.cycles").set(self._cycles(m))
        phased = self._phase_program(m)
        overlap = phased is not None or self._overlap_enabled()
        get_registry().gauge("scheduled.transport.overlap").set(int(overlap))
        get_registry().gauge("scheduled.phase.active").set(
            int(phased is not None))
        if phased is not None:
            get_registry().gauge("scheduled.phase.scan_cycles").set(
                phased.scan_cycles)
            get_registry().gauge("scheduled.phase.unrolled_cycles").set(
                phased.unrolled_cycles)
        if self.n_stages > 1:
            # per-cycle collective count: the overlapped path packs every
            # boundary leaf and lane into one buffer per direction;
            # serialized adds each skip-lane perm group's own permutes
            ncoll = 2
            if not overlap and self.skip_lanes is not None:
                fps, bps = self._lane_perms()
                ncoll += len({tuple(pf) for pf in fps if pf is not None})
                ncoll += len({tuple(pb) for pb in bps if pb is not None})
        else:
            ncoll = 0
        get_registry().gauge(
            "scheduled.transport.collectives_per_cycle").set(ncoll)
        # Total loss weight, computed OUTSIDE the device program (w is the
        # full global array here) and passed in replicated. Keeping this as
        # an in-program psum over the data axis made it the one SUBGROUP
        # collective racing the stage-ring ppermutes — a combination that
        # intermittently starves XLA:CPU's blocking rendezvous into deadlock
        # on the single-core virtual-device test platform. Hoisting it is
        # also simply cheaper: one host-side reduction per step.
        wsum = jnp.sum(w).astype(jnp.float32)

        def x_spec(l):
            spec = [None, data] + [None] * (l.ndim - 2)
            if self.context_axis and l.ndim > self.context_dim:
                spec[self.context_dim] = self.context_axis
            return P(*spec)

        sp_specs = self._stage_param_in_specs(stage_params)
        in_specs = (
            sp_specs,
            jax.tree_util.tree_map(lambda _: P(), pre_params),
            jax.tree_util.tree_map(lambda _: P(), post_params),
            jax.tree_util.tree_map(x_spec, x),
            P(None, data),                # w
            P(),                          # wsum (precomputed, replicated)
            P(),                          # key
        )
        out_specs = (
            P(),                          # loss
            (sp_specs,
             jax.tree_util.tree_map(lambda _: P(), pre_params),
             jax.tree_util.tree_map(lambda _: P(), post_params)),
        )
        if self.stat_spec is not None:    # stats: psum'd in-program
            out_specs = out_specs + (
                jax.tree_util.tree_map(lambda _: P(), self.stat_spec),)
        run = shard_map(
            functools.partial(self._device_program, m=m),
            mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False)
        return run(stage_params, pre_params, post_params, x, w, wsum, key)

    # -----------------------------------------------------------------
    def forward(self, stage_params, pre_params, x, *,
                key: Optional[jax.Array] = None, train: bool = False,
                out_fn: Optional[Callable] = None):
        """FWD-only execution of the op tables: BWD/WGRAD rows masked to
        IDLE — the compiled analogue of the reference running eval through
        the same pipeline with checkpointing off (``pipeline.py:153-155``).
        This is the forward/eval path for interleaved placements (v > 1),
        which the wavefront executor cannot host. Returns the last virtual
        stage's outputs ``[m, rows, ...]`` (no post/loss applied).

        ``out_fn(h) -> pytree of [rows, ...]`` post-processes the final
        stage's activation before collection (e.g. unpacking a packed ring
        carrier into row-major values) — collected outputs must have ROWS
        as their leading dim so the data axis lands on it. Identity by
        default.

        With ``stat_spec`` the stage contract appends a stats output
        (``(h, stats)``) and the return becomes ``(outputs, stats)``: stats
        accumulate over the FWD ops (each micro-batch runs exactly once per
        stage here — no recompute, no double-count) and are psum'd over the
        stage/data axes, giving deferred BatchNorm a train-mode forward on
        interleaved (v > 1) placements. With ``skip_lanes`` the stage
        contract gains pops/stashes (see :class:`SkipLanes`); stashes take
        their direct lane hop into the FIFO park and are popped at
        FWD(i, dst) — no reverse lanes (no backward here).
        """
        if self.split_stage is not None:
            raise NotImplementedError(
                "forward() does not use the split-backward protocol")
        x_leaves = jax.tree_util.tree_leaves(x)
        if not x_leaves:
            raise TypeError("x must contain at least one array leaf")
        m = x_leaves[0].shape[0]
        key = key if key is not None else make_key(0)
        data = DATA_AXIS if self.has_data_axis else None
        get_registry().counter("scheduled.forward.lowerings").inc()
        out_fn = out_fn if out_fn is not None else (lambda h: h)

        def x_spec(l):
            spec = [None, data] + [None] * (l.ndim - 2)
            if self.context_axis and l.ndim > self.context_dim:
                spec[self.context_dim] = self.context_axis
            return P(*spec)

        sp_specs = self._stage_param_in_specs(stage_params)
        ctx0 = StageCtx(key=None, train=train)
        # per-micro-batch LOCAL specs (this runs at host level, before
        # shard_map splits the rows/context dims)
        n_data = self.mesh.shape[DATA_AXIS] if self.has_data_axis else 1

        def x_mb_sds(l):
            shape = list(l.shape[1:])     # drop the m dim
            shape[0] //= n_data
            if self.context_axis and l.ndim > self.context_dim:
                shape[self.context_dim - 1] //= \
                    self.mesh.shape[self.context_axis]
            return jax.ShapeDtypeStruct(tuple(shape), l.dtype)

        x_mb_spec = jax.tree_util.tree_map(x_mb_sds, x)
        h_spec = jax.eval_shape(
            lambda p, a: self.pre_fn(p, a, ctx0), pre_params, x_mb_spec)
        out_sds = jax.eval_shape(out_fn, h_spec)
        in_specs = (
            sp_specs,
            jax.tree_util.tree_map(lambda _: P(), pre_params),
            jax.tree_util.tree_map(x_spec, x),
            P(),                          # key
        )
        out_specs = jax.tree_util.tree_map(
            lambda sp_: P(*([STAGE_AXIS, None, data]
                            + [None] * (len(sp_.shape) - 1))), out_sds)
        if self.stat_spec is not None:   # stats: psum'd in-program
            out_specs = (out_specs, jax.tree_util.tree_map(
                lambda _: P(), self.stat_spec))
        run = shard_map(
            functools.partial(self._device_forward, m=m, train=train,
                              out_fn=out_fn),
            mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False)
        res = run(stage_params, pre_params, x, key)
        out, stats = res if self.stat_spec is not None else (res, None)
        # the last virtual stage lives on device d-1 (v=1: linear chain;
        # v>1: stage S-1 = (v-1)*d + (d-1) is on device d-1 either way)
        out = jax.tree_util.tree_map(lambda o: o[-1], out)
        return out if self.stat_spec is None else (out, stats)

    def _device_forward(self, stage_params, pre_params, x, key, *, m,
                        train, out_fn):
        d, v = self.n_stages, self.v
        S = self.n_virtual
        j = jax.lax.axis_index(STAGE_AXIS)
        params_dev = stage_params

        ctx0 = StageCtx(key=None, train=train)
        x_mb_spec = jax.eval_shape(lambda a: _index_spec(a), x)
        h_spec = jax.eval_shape(
            lambda p, a: self.pre_fn(p, a, ctx0), pre_params, x_mb_spec)

        (op_np, mb_np, grp_np, rxslot_np), T, Sg, sentinel = \
            self._host_tables(m)
        # eval: checkpointing (hence backward) does not exist — mask every
        # non-FWD op to IDLE; the FWD entries' relative timing already
        # satisfies the ring transport constraints the full table verified
        op_np = np.where(op_np == FWD, FWD, IDLE)
        lanes = self.skip_lanes
        if lanes is not None:
            capf_np, _, Kf, _ = self._skip_tables(m, op_np, mb_np, grp_np,
                                                  fwd_only=True)
            lane_fwd_perms, _ = self._lane_perms()
            xs = (jnp.asarray(op_np), jnp.asarray(mb_np),
                  jnp.asarray(grp_np), jnp.asarray(rxslot_np),
                  jnp.asarray(capf_np))
        else:
            Kf = ()
            xs = (jnp.asarray(op_np), jnp.asarray(mb_np),
                  jnp.asarray(grp_np), jnp.asarray(rxslot_np))

        def zeros_of(spec):
            return jnp.zeros(spec.shape, spec.dtype)

        def slots_of(spec, k):
            return jnp.zeros((k + 1,) + tuple(spec.shape), spec.dtype)

        out_sds = jax.eval_shape(out_fn, h_spec)
        h_ring = jax.tree_util.tree_map(zeros_of, h_spec)
        stash = jax.tree_util.tree_map(
            lambda s_: slots_of(s_, v * Sg), h_spec)
        # one output slot per micro-batch + a sentinel for non-last stages
        outbuf = jax.tree_util.tree_map(
            lambda s_: slots_of(s_, m), out_sds)
        if lanes is not None:
            sk_ring0 = tuple(jax.tree_util.tree_map(zeros_of, sp_)
                             for sp_ in lanes.specs)
            sk_park0 = tuple(
                jax.tree_util.tree_map(
                    lambda s_, k=k: slots_of(s_, k), sp_)
                for sp_, k in zip(lanes.specs, Kf))
        else:
            sk_ring0 = sk_park0 = ()

        if v == 1:
            fwd_perm = [(k, k + 1) for k in range(d - 1)]
        else:
            fwd_perm = [(q, (q + 1) % d) for q in range(d)]

        def cycle(carry, row):
            h_ring, stash, outbuf, stats_acc, sk_ring, sk_park = carry
            if lanes is not None:
                op_r, mb_r, grp_r, rx_r, capf_r = row
            else:
                op_r, mb_r, grp_r, rx_r = row
            opj = jax.lax.dynamic_index_in_dim(op_r, j, 0, keepdims=False)
            i = jax.lax.dynamic_index_in_dim(mb_r, j, 0, keepdims=False)
            g = jax.lax.dynamic_index_in_dim(grp_r, j, 0, keepdims=False)
            rslot = jax.lax.dynamic_index_in_dim(rx_r, j, 0, keepdims=False)
            s = g * d + j

            stash = jax.tree_util.tree_map(
                lambda st, hr: jax.lax.dynamic_update_index_in_dim(
                    st, hr, rslot, 0), stash, h_ring)
            if lanes is not None:
                # capture arriving lane values into their FIFO parks at
                # the host-planned slots (sentinel writes are no-ops into
                # the spare slot)
                fslots = [jax.lax.dynamic_index_in_dim(
                    capf_r[l], j, 0, keepdims=False)
                    for l in range(len(lanes.pairs))]
                sk_park = tuple(
                    jax.tree_util.tree_map(
                        lambda st, reg, sl=sl:
                        jax.lax.dynamic_update_index_in_dim(st, reg, sl, 0),
                        pk, rg)
                    for pk, rg, sl in zip(sk_park, sk_ring, fslots))
            kis = jax.random.fold_in(jax.random.fold_in(key, i), s)
            x_mb = _index(x, i)
            params_g = (_index(params_dev, 0) if v == 1
                        else _index(params_dev, g))
            h_in = jax.tree_util.tree_map(
                lambda st: jax.lax.dynamic_index_in_dim(
                    st, g * Sg + i % Sg, 0, keepdims=False), stash)
            pops = (tuple(
                jax.tree_util.tree_map(
                    lambda st, k=k: jax.lax.dynamic_index_in_dim(
                        st, i % k, 0, keepdims=False), pk)
                for pk, k in zip(sk_park, Kf))
                if lanes is not None else None)

            def fwd_branch():
                h0 = jax.lax.cond(
                    s == 0,
                    lambda: self.pre_fn(
                        pre_params, x_mb,
                        StageCtx(key=jax.random.fold_in(kis, 0),
                                 train=train, data_axis=self.bn_axis)),
                    lambda: h_in)
                ctx = StageCtx(key=jax.random.fold_in(kis, 1), train=train,
                               stage=s, data_axis=self.bn_axis)
                out = (self.stage_fn(params_g, h0, ctx, pops)
                       if lanes is not None
                       else self.stage_fn(params_g, h0, ctx))
                h1, stashes, st = self._split_out(out)
                stats2 = (jax.tree_util.tree_map(jnp.add, stats_acc, st)
                          if self.stat_spec is not None else stats_acc)
                if lanes is not None:
                    # fresh stashes board their lanes at the source stage
                    tx_sk = tuple(
                        jax.tree_util.tree_map(
                            lambda sv, reg, src=src: jnp.where(
                                jnp.asarray(s == src), sv, reg), svv, rg)
                        for (src, _), svv, rg in zip(lanes.pairs, stashes,
                                                     sk_ring))
                else:
                    tx_sk = sk_ring
                widx = jnp.where(s == S - 1, i, m)   # sentinel elsewhere
                new_out = jax.tree_util.tree_map(
                    lambda buf, l: jax.lax.dynamic_update_index_in_dim(
                        buf, l, widx, 0), outbuf, out_fn(h1))
                return new_out, h1, stats2, tx_sk

            def idle_branch():
                return outbuf, h_ring, stats_acc, sk_ring

            outbuf2, tx_h, stats2, tx_sk = jax.lax.switch(
                jnp.clip(opj, 0, 1), [idle_branch, fwd_branch])
            if d > 1:
                tx_h = jax.tree_util.tree_map(
                    lambda a: jax.lax.ppermute(a, STAGE_AXIS, fwd_perm),
                    tx_h)
                if lanes is not None:
                    tx_sk = tuple(
                        (jax.tree_util.tree_map(
                            lambda a, pf=pf: jax.lax.ppermute(
                                a, STAGE_AXIS, pf), lv)
                         if pf is not None else lv)
                        for lv, pf in zip(tx_sk, lane_fwd_perms))
            return (tx_h, stash, outbuf2, stats2, tx_sk, sk_park), None

        stats0 = (self._zero_seed_like(self.stat_spec)
                  if self.stat_spec is not None else ())
        (_, _, outbuf, stats_out, _, _), _ = jax.lax.scan(
            cycle, (h_ring, stash, outbuf, stats0, sk_ring0, sk_park0), xs)
        outs = jax.tree_util.tree_map(lambda b: b[None, :m], outbuf)
        if self.stat_spec is None:
            return outs
        # each virtual stage fills only its own slots (zeros elsewhere);
        # data shards hold per-shard partial sums — psum collects both
        stat_axes = ((STAGE_AXIS, DATA_AXIS) if self.has_data_axis
                     else (STAGE_AXIS,))
        stats_out = jax.tree_util.tree_map(
            lambda a: jax.lax.psum(a, stat_axes), stats_out)
        return outs, stats_out

    # -----------------------------------------------------------------
    def _stage_param_in_specs(self, stage_params):
        """Stacked-layout PartitionSpecs: P(stage) per leaf, or
        P(stage, *leaf_spec) when ``stage_param_specs`` names per-leaf
        shardings (tensor parallelism)."""
        if self.stage_param_specs is None:
            return jax.tree_util.tree_map(lambda _: P(STAGE_AXIS),
                                          stage_params)
        is_p = lambda v: isinstance(v, P)
        specs = jax.tree_util.tree_map(
            lambda s_: P(STAGE_AXIS, *s_), self.stage_param_specs,
            is_leaf=is_p)
        got = jax.tree_util.tree_structure(specs)
        want = jax.tree_util.tree_structure(stage_params)
        if got != want:
            raise ValueError(
                f"stage_param_specs structure {got} does not match the "
                f"stacked stage params {want}")
        return specs

    def _grad_reduce_axes(self):
        """Mesh axes grads sum over: every non-stage axis EXCEPT the model
        axis (TP grad contract — see ``stage_param_specs``)."""
        return tuple(a for a in self.mesh.axis_names
                     if a not in (STAGE_AXIS, MODEL_AXIS))

    # -----------------------------------------------------------------
    def _f_body(self, params_g, prep, h_in, x_mb, kis, s, pops=None):
        """The per-(cycle, device) forward for virtual stage ``s``: pre
        (stage 0 only) → stage body. Everything the backward needs to
        differentiate is an explicit argument — no closure over device state
        (in particular no collective-derived values like the global weight
        sum, which would change the vjp residual structure under shard_map) —
        so the residual structure is derivable abstractly.

        With :class:`SkipLanes`, ``pops`` is the per-lane tuple of popped
        values and the return is ``(h_out, stashes)``.

        The post (decode/loss) is deliberately NOT part of this function:
        its vjp residuals are vocab-scale ([rows, seq, vocab] logits plus a
        weight-cast copy — hundreds of MB at tutorial scale) and the residual
        store replicates slot structure across every (virtual stage, slot),
        so folding the post into the stored vjp OOMs a 16G chip. Instead the
        executor stashes the last stage's ~activation-sized output and
        rebuilds the post vjp fresh at backward time (:meth:`_post_contrib`)
        — the compiled analogue of the reference keeping the loss OUTSIDE
        ``Pipe`` and feeding its gradient into the recorded graph
        (``main.py:216-218``)."""
        train = True
        h0 = jax.lax.cond(
            s == 0,
            lambda: self.pre_fn(prep, x_mb,
                                StageCtx(key=jax.random.fold_in(kis, 0),
                                         train=train,
                                         data_axis=self.bn_axis)),
            lambda: h_in)
        # ctx.stage carries the VIRTUAL stage index (traced on the d>1 path,
        # a Python int on the d=1 static path) so heterogeneous adapters can
        # switch their per-stage bodies on it (parallel.hetero_scheduled).
        ctx = StageCtx(key=jax.random.fold_in(kis, 1),
                       train=train, stage=s, data_axis=self.bn_axis)
        if self.skip_lanes is not None:
            return self.stage_fn(params_g, h0, ctx, pops)
        return self.stage_fn(params_g, h0, ctx)

    def _split_out(self, out):
        """Destructure a stage output into ``(h, stashes, stats)`` per the
        configured extras (None for the absent ones) — the single decoder
        for every (skip_lanes x stat_spec) combination."""
        if self.skip_lanes is not None and self.stat_spec is not None:
            h, sk, st = out
            return h, sk, st
        if self.skip_lanes is not None:
            h, sk = out
            return h, sk, None
        if self.stat_spec is not None:
            h, st = out
            return h, None, st
        return out, None, None

    def _zero_seed_like(self, spec_tree):
        return jax.tree_util.tree_map(
            lambda sp_: jnp.zeros(sp_.shape, sp_.dtype), spec_tree)

    def _make_seed(self, seed_h, seed_sk):
        """Assemble the vjp seed matching the stage output structure:
        stats always get zero cotangents (stop_gradient'd at source)."""
        if self.skip_lanes is not None and self.stat_spec is not None:
            return (seed_h, seed_sk, self._zero_seed_like(self.stat_spec))
        if self.skip_lanes is not None:
            return (seed_h, seed_sk)
        if self.stat_spec is not None:
            return (seed_h, self._zero_seed_like(self.stat_spec))
        return seed_h

    def _post_contrib(self, postp, h1, x_mb, w_mb, kis):
        """UNNORMALIZED loss contribution ``sum(w * per_row)`` of one
        micro-batch; the executor divides by the global ``sum(w)`` and seeds
        its backward with ``1/sum(w)``."""
        return jnp.sum(
            w_mb * self.post_fn(postp, h1, x_mb,
                                StageCtx(key=jax.random.fold_in(kis, 2),
                                         train=True,
                                         data_axis=self.bn_axis))
        ).astype(jnp.float32)

    def _vjp_wrt(self, params_g, prep, h_in, x_mb, kis, s, pops=None):
        """vjp of :meth:`_f_body` w.r.t. (group params, pre, h_in[, pops]).

        With skip lanes the primal out is ``(h, stashes)``, the seed is
        ``(g_h, g_stashes)`` and the cotangents gain ``g_pops``."""
        if self.skip_lanes is not None:
            return jax.vjp(
                lambda a, b, dd, pp: self._f_body(a, b, dd, x_mb, kis, s,
                                                  pops=pp),
                params_g, prep, h_in, pops)
        return jax.vjp(
            lambda a, b, dd: self._f_body(a, b, dd, x_mb, kis, s),
            params_g, prep, h_in)

    def _f_body_split(self, params_g, prep, h_in, x_mb, kis, s, zs):
        """Split-backward twin of :meth:`_f_body`: pre (stage 0 only) then
        the TAPPED stage body. Returns ``(h_out, taps)``."""
        train = True
        h0 = jax.lax.cond(
            s == 0,
            lambda: self.pre_fn(prep, x_mb,
                                StageCtx(key=jax.random.fold_in(kis, 0),
                                         train=train,
                                         data_axis=self.bn_axis)),
            lambda: h_in)
        return self.split_stage.tapped_fn(
            params_g, h0,
            StageCtx(key=jax.random.fold_in(kis, 1), train=train, stage=s,
                     data_axis=self.bn_axis), zs)

    def _vjp_wrt_split(self, params_g, prep, h_in, x_mb, kis, s):
        """Params-constant vjp of the tapped body w.r.t. (pre, h, zs):
        ``(h1, vjp_fn, taps)``; ``vjp_fn(seed) -> (gpre, gh, gzs)``."""
        zs = self.split_stage.zs_fn(params_g, h_in)
        return jax.vjp(
            lambda b, dd, zz: self._f_body_split(
                params_g, b, dd, x_mb, kis, s, zz),
            prep, h_in, zs, has_aux=True)

    def _vjp_wrt_policy(self, params_g, prep, h_in, x_mb, kis, s,
                        pops=None):
        """Policy-selective vjp: residuals are only what ``remat_policy``
        saves (the backward recomputes the rest in place)."""
        if self.skip_lanes is not None:
            wrapped = jax.checkpoint(
                lambda a, b, dd, pp: self._f_body(a, b, dd, x_mb, kis, s,
                                                  pops=pp),
                policy=self.remat_policy)
            return jax.vjp(wrapped, params_g, prep, h_in, pops)
        wrapped = jax.checkpoint(
            lambda a, b, dd: self._f_body(a, b, dd, x_mb, kis, s),
            policy=self.remat_policy)
        return jax.vjp(wrapped, params_g, prep, h_in)

    # -----------------------------------------------------------------
    def _host_tables(self, m):
        """Static (cycle, device) tables + receive-slot plan, host-side."""
        d, v = self.n_stages, self.v
        S = v * d
        Sg = self.schedule.stash_slots(m, d)
        tables = self.schedule.op_tables(m, d)
        if len(tables) == 2:            # non-interleaved: group is always 0
            op_np, mb_np = tables
            grp_np = np.zeros_like(op_np)
        else:
            op_np, mb_np, grp_np = tables
        T = op_np.shape[0]
        sentinel = v * Sg
        # rxslot[t, p]: stash slot for the value arriving at device p at
        # cycle t (the upstream device's cycle-(t-1) output), sentinel when
        # it is not a real activation (IDLE/BWD upstream, or the last
        # virtual stage's output, which has no consumer).
        rxslot_np = np.full((T, d), sentinel, np.int32)
        for t in range(1, T):
            for p in range(d):
                q = (p - 1) % d
                if self.v == 1 and p == 0:
                    continue            # linear ring: nothing enters stage 0
                if op_np[t - 1, q] != FWD:
                    continue
                s_up = grp_np[t - 1, q] * d + q
                if s_up >= S - 1:
                    continue
                g2 = (s_up + 1) // d
                rxslot_np[t, p] = g2 * Sg + (mb_np[t - 1, q] % Sg)
        return (op_np, mb_np, grp_np, rxslot_np), T, Sg, sentinel

    def _host_tables_overlap(self, m):
        """Comm-shifted tables + receive/grad-park plans for overlapped
        transport (host-side, all static).

        The serialized tables are retimed by :func:`shift_comm_tables` so a
        value produced at cycle t is permuted at the START of body t+1 and
        parked there AFTER that body's compute — first legal read t+2 (hop
        latency 2). Slot capacities are then re-derived from the shifted
        timings under the park-after-compute window rule
        (:func:`overlap_joint_capacity`): one joint ``Sg`` covers the
        arriving-input stash, the in-branch residual/taps stores and the
        last stage's ``h_last`` park (they share the ``g*Sg + i % Sg`` /
        ``i % Sg`` slot arithmetic); ``Gg`` sizes the NEW grad park — under
        serialized transport the reverse ring is rigid (a cotangent is
        consumed the cycle it arrives), under overlap it is elastic and
        arriving cotangents park in a small FIFO until their BWD.
        ``verify_shifted_op_tables`` re-proves the whole contract before
        the tables reach the executor.

        ``rxslot`` keeps the serialized arithmetic unchanged: in both
        modes the value parked at body t was produced by the upstream
        compute at body t-1 (serialized: end-of-body permute; overlapped:
        start-of-next-body permute). ``gxslot`` is its reverse-direction
        twin for the grad park."""
        tables = self.schedule.op_tables(m, self.n_stages)
        if len(tables) == 2:
            op0, mb0 = tables
            grp0 = None
        else:
            op0, mb0, grp0 = tables
        op_np, mb_np, grp_np = shift_comm_tables(
            op0, mb0, grp0, m=m, d=self.n_stages, v=self.v)
        return self._overlap_plans(op_np, mb_np, grp_np, m,
                                   has_grp=grp0 is not None)

    def _host_tables_phased(self, m):
        """Plans for the phase-compiled executor: identical structure to
        :meth:`_host_tables_overlap` (the aligner emits hop-2 tables that
        honor the same park-after-compute transport contract), but the
        tables come from :func:`~pipe_tpu.core.schedule.compile_phases` —
        cycle-uniform, segmented into ramps and dense periodic windows.
        Callers must only reach here with an accepted verdict."""
        prog = self._phase_program(m)
        if prog is None:
            raise AssertionError(
                "_host_tables_phased called without an accepted phase "
                "program — the caller must fall back to the interpreter")
        return self._overlap_plans(prog.op, prog.mbi, prog.grp, m,
                                   has_grp=self.v > 1)

    def _overlap_plans(self, op_np, mb_np, grp_np, m, *, has_grp):
        """Capacity + park plans for hop-2 (overlapped-transport) tables —
        shared by the comm-shifted and phase-aligned paths."""
        d, v = self.n_stages, self.v
        S = v * d
        T = op_np.shape[0]
        t_f, t_b, t_w = _times_by_code(op_np, mb_np, grp_np, m, d, v)
        read_last = np.maximum(t_f, np.maximum(t_b, t_w))
        wins = [(t_f[:, s - 1] + 1, read_last[:, s]) for s in range(1, S)]
        wins += [(t_f[:, s], read_last[:, s]) for s in range(S)]
        wins += [(t_f[:, S - 1], t_b[:, S - 1])]        # h_last park
        Sg = overlap_joint_capacity(wins, m)
        gw = [(t_b[:, s + 1] + 1, t_b[:, s]) for s in range(S - 1)]
        Gg = overlap_joint_capacity(gw, m) if gw else 1
        has_w = bool((op_np == WGRAD).any())
        split_dce = has_w and self.checkpoint == "never"
        Wg = (overlap_joint_capacity(
            [(t_b[:, s], t_w[:, s]) for s in range(S)], m)
            if split_dce else 0)
        verify_shifted_op_tables(
            op_np, mb_np, grp_np if has_grp else None,
            m=m, d=d, v=v, splits_backward=has_w, stash_slots=Sg,
            grad_slots=Gg, wstash_slots=Wg if split_dce else None)
        sentinel = v * Sg
        gsentinel = v * Gg
        rxslot_np = np.full((T, d), sentinel, np.int32)
        gxslot_np = np.full((T, d), gsentinel, np.int32)
        for t in range(1, T):
            for p in range(d):
                q = (p - 1) % d
                if not (v == 1 and p == 0) and op_np[t - 1, q] == FWD:
                    s_up = grp_np[t - 1, q] * d + q
                    if s_up < S - 1:
                        g2 = (s_up + 1) // d
                        rxslot_np[t, p] = g2 * Sg + (mb_np[t - 1, q] % Sg)
                q = (p + 1) % d
                if not (v == 1 and p == d - 1) and op_np[t - 1, q] == BWD:
                    s_up = grp_np[t - 1, q] * d + q
                    if s_up > 0:
                        g2 = (s_up - 1) // d
                        gxslot_np[t, p] = g2 * Gg + (mb_np[t - 1, q] % Gg)
        return ((op_np, mb_np, grp_np, rxslot_np, gxslot_np), T, Sg, Gg,
                Wg, sentinel, gsentinel)

    def _lane_hops(self):
        """Physical hop count per skip lane on the ring: ``(dst%d - src%d)
        % d``. Under overlapped transport a lane with >= 1 hops rides the
        packed carriers as an H-slot shift register (one relay hop per
        cycle); 0-hop lanes (same device, v > 1) keep their register — a
        permute would move them off-device."""
        d = self.n_stages
        return tuple(((dst % d) - (src % d)) % d
                     for (src, dst) in self.skip_lanes.pairs)

    def _skip_tables(self, m, op_np, mb_np, grp_np, *, fwd_only=False,
                     overlap=False):
        """Host-side skip-lane plan from the op tables.

        Per lane ``l = (src, dst)`` (VIRTUAL stage indices; the physical
        endpoints are ``src % d`` / ``dst % d``):

        * ``capf[t, l, p]``: FIFO slot at device ``p`` parking the value
          arriving on the forward lane hop at cycle ``t`` (sentinel
          ``Kf[l]`` when nothing real arrives). Arrival is deterministic:
          the stash emitted at FWD(i, src) takes the lane's single direct
          permute, reaching ``dst % d`` at cycle ``fwd(i, src) + 1``.
        * ``capg[t, l, p]``: same for the pop cotangent taking the reverse
          hop from BWD(i, dst) to ``src % d``.
        * ``Kf[l]`` / ``Kg[l]``: smallest FIFO depths such that slot
          ``i % K`` never collides across overlapping live windows. The
          forward live window extends to BWD(i, dst) under recompute
          modes (the re-run needs the pops again), mirroring the
          activation stash.

        ``fwd_only=True`` plans for the FWD-masked eval tables: windows
        end at FWD(i, dst) (no reread — eval has no backward) and the
        reverse plan is skipped (``capg=None, Kg=()``).

        ``overlap=True`` plans for the comm-shifted tables: lanes ride the
        packed carriers as per-cycle relays, so arrival is ``max(H, 1)``
        cycles after boarding (H = physical hops; 0-hop register lanes
        still capture one cycle later), and because arrivals park AFTER
        the cycle's compute the consumer must be STRICTLY later than the
        arrival.
        """
        d = self.n_stages
        hops = self._lane_hops() if overlap else None
        S = self.n_virtual
        T = op_np.shape[0]
        pairs = self.skip_lanes.pairs
        fwd_c = np.full((m, S), -1, np.int64)
        bwd_c = np.full((m, S), -1, np.int64)
        for t in range(T):
            for p in range(d):
                s = grp_np[t, p] * d + p
                if op_np[t, p] == FWD:
                    fwd_c[mb_np[t, p], s] = t
                elif op_np[t, p] == BWD:
                    bwd_c[mb_np[t, p], s] = t

        def fifo_depth(windows):
            for K in range(1, m + 1):
                ok = all(
                    windows[i][1] < windows[i2][0]
                    for i in range(m) for i2 in range(i + K, m, K))
                if ok:
                    return K
            return m

        Kf, Kg = [], []
        f_events, g_events = [], []   # (t, lane, device, slot)
        for lidx, (src, dst) in enumerate(pairs):
            lag = max(hops[lidx], 1) if overlap else 1
            slack = 1 if overlap else 0   # park-after-compute: strict <
            wf, wg = [], []
            for i in range(m):
                arr_f = fwd_c[i, src] + lag
                use_f = fwd_c[i, dst]
                # host-side plan invariants raise (not assert: python -O
                # must not turn a timing violation into silent corruption)
                if not (0 <= fwd_c[i, src] and arr_f + slack <= use_f):
                    raise ValueError(
                        f"skip lane ({src},{dst}): stash for micro-batch "
                        f"{i} arrives at cycle {arr_f} after its FWD "
                        f"{use_f} — the schedule violates the "
                        f"{'relay' if overlap else 'direct-hop'} "
                        f"timing assumption")
                reread = (not fwd_only
                          and self.remat_policy is None
                          and (self.checkpoint == "always"
                               or (self.checkpoint == "except_last"
                                   and i != m - 1)))
                wf.append((arr_f, bwd_c[i, dst] if reread else use_f))
                if fwd_only:
                    continue
                arr_g = bwd_c[i, dst] + lag
                use_g = bwd_c[i, src]
                if not (0 <= bwd_c[i, dst] and arr_g + slack <= use_g):
                    raise ValueError(
                        f"skip lane ({src},{dst}): cotangent for "
                        f"micro-batch {i} arrives at cycle {arr_g} after "
                        f"its BWD {use_g} — the schedule violates the "
                        f"{'relay' if overlap else 'direct-hop'} "
                        f"timing assumption")
                wg.append((arr_g, use_g))
            kf = fifo_depth(wf)
            Kf.append(kf)
            for i in range(m):
                f_events.append((wf[i][0], lidx, dst % d, i % kf))
            if not fwd_only:
                kg = fifo_depth(wg)
                Kg.append(kg)
                for i in range(m):
                    g_events.append((wg[i][0], lidx, src % d, i % kg))
        capf = np.zeros((T, len(pairs), d), np.int32)
        for lidx in range(len(pairs)):
            capf[:, lidx, :] = Kf[lidx]      # sentinel
        for (t, lidx, p, slot) in f_events:
            capf[t, lidx, p] = slot
        if fwd_only:
            return capf, None, Kf, ()
        capg = np.zeros((T, len(pairs), d), np.int32)
        for lidx in range(len(pairs)):
            capg[:, lidx, :] = Kg[lidx]
        for (t, lidx, p, slot) in g_events:
            capg[t, lidx, p] = slot
        return capf, capg, Kf, Kg

    def _lane_perms(self):
        """Per-lane direct permute endpoints, MERGED across disjoint lanes.

        Base form: lane ``(src, dst)`` takes one hop ``src % d -> dst % d``
        (``None`` when both virtual stages share a device — the lane
        register itself is the transport, no collective needed).

        Merge: lanes whose endpoint pairs are pairwise disjoint (no shared
        source, no shared destination) are grouped, and every lane in a
        group gets the group's UNION perm list. Identical perm lists let
        XLA's collective-permute combiner fuse the group's per-lane
        permutes into one collective per cycle instead of L. Soundness: a
        lane's register riding another pair's route only changes which
        garbage lands at non-capture devices — un-listed destinations
        already receive zeros from ``ppermute``, and the host capture
        tables (``_skip_tables``) park anything not scheduled into the
        sentinel slot either way.
        """
        d = self.n_stages

        def merged(pairs_mod):
            # greedy grouping: first group whose used srcs/dsts are
            # disjoint from this lane's pair
            groups: List[dict] = []
            assign = [None] * len(pairs_mod)
            for l, pm in enumerate(pairs_mod):
                if pm is None:
                    continue
                ps, pd = pm
                for gi, grp in enumerate(groups):
                    if ps not in grp["src"] and pd not in grp["dst"]:
                        grp["src"].add(ps)
                        grp["dst"].add(pd)
                        grp["perm"].append((ps, pd))
                        assign[l] = gi
                        break
                else:
                    groups.append({"src": {ps}, "dst": {pd},
                                   "perm": [(ps, pd)]})
                    assign[l] = len(groups) - 1
            return [None if a is None else groups[a]["perm"]
                    for a in assign]

        fwd_pairs = [None if (src % d) == (dst % d)
                     else (src % d, dst % d)
                     for (src, dst) in self.skip_lanes.pairs]
        bwd_pairs = [None if pm is None else (pm[1], pm[0])
                     for pm in fwd_pairs]
        return merged(fwd_pairs), merged(bwd_pairs)

    def _use_static(self, m: int) -> bool:
        if self.static_unroll is not None:
            return self.static_unroll
        return self._cycles(m) <= _STATIC_UNROLL_MAX_CYCLES

    # -----------------------------------------------------------------
    def _device_program_static(self, stage_params, pre_params, post_params,
                               x, w, wsum, key, *, m):
        """Single-stage-device specialization: the tables unrolled at trace
        time into straight-line code.

        With ``d == 1`` every table entry ``op[t, 0]`` is a static Python
        int, so the per-cycle ``lax.switch``/slot machinery of the dynamic
        path is unnecessary — and measurably hostile: XLA's copy-insertion
        around conditionals inside the scan copies the pass-through grad
        accumulators (the full per-device param tree) almost every cycle,
        measured at 123 ms/step of pure ``copy`` on the 520M tutorial config
        (2.0x the AD executor). Here ops specialize at trace time: stash,
        residual store and the cotangent hand-off become Python dicts of
        traced values, grads accumulate with straight adds, and the emitted
        program matches hand-written gradient accumulation with the exact
        per-micro-batch checkpoint policy interleaved in table order. The
        dynamic scan path remains the d > 1 program.
        """
        v = self.v
        S = self.n_virtual
        mode = self.checkpoint
        inv_wsum = 1.0 / wsum

        tables = self.schedule.op_tables(m, 1)
        op_np, mb_np = tables[0], tables[1]
        grp_np = tables[2] if len(tables) == 3 else np.zeros_like(op_np)

        split_w = bool((op_np == WGRAD).any())
        stash = {}     # (i, s) -> stage input (released at B, or W if split)
        res = {}       # (i, g) -> vjp_fn (policy-gated)
        h_last = {}    # i -> last virtual stage's output (pops at BWD)
        gbuf = {}      # (i, s) -> cotangent from stage s+1 (pops at BWD)
        wpend = {}     # (i, g) -> deferred (gp, gpre) or (structural
        #                split) the per-op output cotangents g_zs
        tapsd = {}     # (i, g) -> taps (structural split only)
        g_per_group = {}
        g_pre = jax.tree_util.tree_map(jnp.zeros_like, pre_params)
        g_post = jax.tree_util.tree_map(jnp.zeros_like, post_params)
        loss = jnp.zeros((), jnp.float32)
        stats_acc = None   # lazily set from the first FWD's stats output
        add = functools.partial(jax.tree_util.tree_map, jnp.add)

        for t in range(op_np.shape[0]):
            opj = int(op_np[t, 0])
            if opj == 0:          # IDLE
                continue
            i = int(mb_np[t, 0])
            g = int(grp_np[t, 0])
            s = g                 # d == 1: virtual stage == group
            kis = jax.random.fold_in(jax.random.fold_in(key, i), s)
            x_mb = _index(x, i)
            w_mb = _index(w, i)
            params_g = _index(stage_params, g)
            # Read (not pop) at FWD: recompute modes re-read the same input
            # at this stage's BWD, which is where the entry is released.
            h_in = stash.get((i, s))
            if h_in is None:      # stage 0 consumes x via pre inside _f_body
                h_in = jax.tree_util.tree_map(
                    lambda l: jnp.zeros(l.shape, l.dtype),
                    jax.eval_shape(lambda p, a: self.pre_fn(
                        p, a, StageCtx(key=None, train=True)),
                        pre_params, x_mb))
            if opj == FWD:
                save = (mode == "never"
                        or (mode == "except_last" and i == m - 1))
                if self.split_stage is not None:   # never mode guaranteed
                    h1, vjp_fn, taps = self._vjp_wrt_split(
                        params_g, pre_params, h_in, x_mb, kis, s)
                    res[(i, g)] = vjp_fn
                    tapsd[(i, g)] = taps
                elif save:
                    out, vjp_fn = self._vjp_wrt(
                        params_g, pre_params, h_in, x_mb, kis, s)
                    h1, _, stats_t = self._split_out(out)
                    res[(i, g)] = vjp_fn
                elif self.remat_policy is not None:
                    # selective remat: store the policy-saved residual
                    # subset now; backward recomputes only the remainder
                    out, vjp_fn = self._vjp_wrt_policy(
                        params_g, pre_params, h_in, x_mb, kis, s)
                    h1, _, stats_t = self._split_out(out)
                    res[(i, g)] = vjp_fn
                else:
                    out = self._f_body(params_g, pre_params, h_in, x_mb,
                                       kis, s)
                    h1, _, stats_t = self._split_out(out)
                if self.stat_spec is not None:
                    stats_acc = (add(stats_acc, stats_t)
                                 if stats_acc is not None else stats_t)
                if s == S - 1:
                    loss = loss + self._post_contrib(post_params, h1, x_mb,
                                                     w_mb, kis)
                    h_last[i] = h1
                else:
                    stash[(i, s + 1)] = h1
            elif opj == BWD:
                if s == S - 1:
                    _, post_vjp = jax.vjp(
                        lambda pp, hh: self._post_contrib(
                            pp, hh, x_mb, w_mb, kis),
                        post_params, h_last.pop(i))
                    gpost, seed_h = post_vjp(inv_wsum)
                    g_post = add(g_post, gpost)
                else:
                    seed_h = gbuf.pop((i, s))
                if self.split_stage is not None:
                    # structural split: stored params-constant vjp — the
                    # input-grad chain only; per-op cotangents park for W
                    gpre, gh, gzs = res.pop((i, g))(seed_h)
                    g_pre = add(g_pre, gpre)
                    wpend[(i, g)] = gzs
                    if s > 0:
                        gbuf[(i, s - 1)] = gh
                    continue
                vjp_fn = res.pop((i, g), None)
                if vjp_fn is None:
                    _, vjp_fn = self._vjp_wrt(
                        params_g, pre_params, h_in, x_mb, kis, s)
                gp, gpre, gh = vjp_fn(self._make_seed(seed_h, None))
                if split_w:
                    # B/W split table (zb-h1): the weight/pre grads computed
                    # here are traced values — defer only their ACCUMULATION
                    # to the W slot (straight-line code, so no recompute;
                    # ordering is immaterial at d == 1 where there is no
                    # bubble to fill, but the table contract is honored).
                    wpend[(i, g)] = (gp, gpre)
                else:
                    g_per_group[g] = (add(g_per_group[g], gp)
                                      if g in g_per_group else gp)
                    g_pre = add(g_pre, gpre)
                if s > 0:
                    gbuf[(i, s - 1)] = gh
                if not split_w:
                    stash.pop((i, s), None)
            else:                 # WGRAD
                if self.split_stage is not None:
                    # structural split: pure weight-grad contractions
                    gp = self.split_stage.wgrad_fn(tapsd.pop((i, g)),
                                                   wpend.pop((i, g)))
                else:
                    gp, gpre = wpend.pop((i, g))
                    g_pre = add(g_pre, gpre)
                g_per_group[g] = (add(g_per_group[g], gp)
                                  if g in g_per_group else gp)
                stash.pop((i, s), None)
        assert not stash and not res and not h_last and not gbuf \
            and not wpend and not tapsd, \
            "static schedule left unconsumed state"

        g_sp = jax.tree_util.tree_map(
            lambda *rows: jnp.stack(rows, axis=0),
            *[g_per_group[g] for g in range(v)])

        other_axes = self._grad_reduce_axes()
        if other_axes:
            g_sp = jax.tree_util.tree_map(
                lambda gg: jax.lax.psum(gg, other_axes), g_sp)
            g_pre = jax.tree_util.tree_map(
                lambda gg: jax.lax.psum(gg, other_axes), g_pre)
            g_post = jax.tree_util.tree_map(
                lambda gg: jax.lax.psum(gg, other_axes), g_post)
        loss_axes = (DATA_AXIS,) if self.has_data_axis else ()
        if loss_axes:
            loss = jax.lax.psum(loss, loss_axes)
        if self.stat_spec is not None:
            if stats_acc is None:
                stats_acc = self._zero_seed_like(self.stat_spec)
            if loss_axes:
                stats_acc = jax.tree_util.tree_map(
                    lambda a: jax.lax.psum(a, loss_axes), stats_acc)
            return loss * inv_wsum, (g_sp, g_pre, g_post), stats_acc
        return loss * inv_wsum, (g_sp, g_pre, g_post)

    # -----------------------------------------------------------------
    def _device_program(self, stage_params, pre_params, post_params, x, w,
                        wsum, key, *, m):
        d, v = self.n_stages, self.v
        S = self.n_virtual
        if d == 1 and self._use_static(m):
            get_registry().counter("scheduled.program.static_unroll").inc()
            return self._device_program_static(
                stage_params, pre_params, post_params, x, w, wsum, key, m=m)
        phased_prog = self._phase_program(m)
        if phased_prog is not None:
            get_registry().counter("scheduled.program.phase_compiled").inc()
        else:
            get_registry().counter("scheduled.program.dynamic_scan").inc()
        # The phased path IS an overlap-transport program: the aligner
        # emits hop-2 tables and the body reuses the packed double-buffered
        # carriers, parks and capacities unchanged.
        overlap = phased_prog is not None or self._overlap_enabled()
        j = jax.lax.axis_index(STAGE_AXIS)
        # This device's shard: [v, ...] — its interleave groups in order.
        params_dev = stage_params
        mode = self.checkpoint

        # --- local shape specs -------------------------------------------
        ctx0 = StageCtx(key=None, train=True)
        x_mb_spec = jax.eval_shape(lambda a: _index_spec(a), x)
        h_spec = jax.eval_shape(
            lambda p, a: self.pre_fn(p, a, ctx0), pre_params, x_mb_spec)
        params_g_spec = jax.eval_shape(lambda p: _index_spec(p), params_dev)

        # Canonical vjp structure (abstract — no tracers leak in):
        i32 = jax.ShapeDtypeStruct((), jnp.int32)
        # mirror the CALLER's key impl (rbg on TPU via utils/rng.make_key,
        # threefry elsewhere): the key rides the stored vjp residuals, and
        # a hardcoded jax.random.key(0) spec (always threefry) would make
        # the abstract residual structure drift from the traced one on any
        # platform whose tuned impl differs
        key_spec = jax.eval_shape(lambda k: k, key)
        lanes = self.skip_lanes
        pops_spec = lanes.specs if lanes is not None else None
        if self.split_stage is not None:
            zs_spec = jax.eval_shape(self.split_stage.zs_fn,
                                     params_g_spec, h_spec)
            _, vjp_fn_spec, taps_spec = jax.eval_shape(
                self._vjp_wrt_split, params_g_spec, pre_params, h_spec,
                x_mb_spec, key_spec, i32)
        else:
            zs_spec = taps_spec = None
            _, vjp_fn_spec = jax.eval_shape(
                self._vjp_wrt, params_g_spec, pre_params, h_spec,
                x_mb_spec, key_spec, i32, pops_spec)
        res_specs, res_treedef = jax.tree_util.tree_flatten(vjp_fn_spec)
        # Structural split: the stored B-vjp's residual leaves include pure
        # PASSTHROUGHS of values the B cycle can already see — the stage
        # weights (vjp consts: dx = gy @ W^T needs W), the pre params, the
        # stashed h_in, x_mb. Streaming those through the slot store costs
        # full leaf-size writes EVERY cycle (the sentinel-write discipline)
        # for values that never change between F and B; on the serialized
        # cpu8 probe the weight copies alone are ~30% of the split's res
        # traffic. Detect them structurally (jaxpr outvar == invar) and
        # rebuild at B from the branch environment instead of storing.
        split_res_pt = None
        if self.split_stage is not None:
            def _res_leaves_of(pg, pre, hh, xx, kk, ss):
                _, vjp_fn, _ = self._vjp_wrt_split(pg, pre, hh, xx, kk, ss)
                return tuple(jax.tree_util.tree_leaves(vjp_fn))

            jpr = jax.make_jaxpr(_res_leaves_of)(
                params_g_spec, pre_params, h_spec, x_mb_spec, key_spec, i32)
            srcs = [("pg", params_g_spec), ("pre", pre_params),
                    ("h", h_spec), ("x", x_mb_spec)]
            src_of, pos = {}, 0
            for kind, tree in srcs:
                leaves_k = jax.tree_util.tree_leaves(tree)
                for k, iv in enumerate(
                        jpr.jaxpr.invars[pos:pos + len(leaves_k)]):
                    src_of[iv] = (kind, k)
                pos += len(leaves_k)
            split_res_pt = {}
            for idx, ov in enumerate(jpr.jaxpr.outvars):
                hit = (None if isinstance(ov, jax.core.Literal)
                       else src_of.get(ov))
                if hit is not None:
                    sp_ = res_specs[idx]
                    lv = jax.tree_util.tree_leaves(dict(srcs)[hit[0]])[
                        hit[1]]
                    assert (tuple(sp_.shape), sp_.dtype) == \
                        (tuple(lv.shape), lv.dtype), \
                        "passthrough residual aval drifted from its source"
                    split_res_pt[idx] = hit
            n_res_leaves_full = len(res_specs)
            res_specs = [sp_ for idx, sp_ in enumerate(res_specs)
                         if idx not in split_res_pt]
        # Policy-selective remat: the policy vjp's residual pytree (what
        # jax.checkpoint's policy saves) differs from the full set, so the
        # recompute micro-batches get their OWN uniform slot store. At
        # 'never' every micro-batch is saved-full and the policy is inert
        # (warned at init); guard on mode so no dead store rides the carry.
        use_policy = (self.remat_policy is not None
                      and self.checkpoint != "never")
        if use_policy:
            _, pvjp_fn_spec = jax.eval_shape(
                self._vjp_wrt_policy, params_g_spec, pre_params, h_spec,
                x_mb_spec, key_spec, i32, pops_spec)
            pres_specs, pres_treedef = jax.tree_util.tree_flatten(
                pvjp_fn_spec)
        else:
            pres_specs, pres_treedef = [], None
        inv_wsum = 1.0 / wsum

        # --- schedule tables (static data → scan xs) ---------------------
        if overlap:
            ((op_np, mb_np, grp_np, rxslot_np, gxslot_np), T, Sg, Gg,
             Wg_ov, sentinel, gsentinel) = (
                 self._host_tables_phased(m) if phased_prog is not None
                 else self._host_tables_overlap(m))
            base_xs = [jnp.asarray(op_np), jnp.asarray(mb_np),
                       jnp.asarray(grp_np), jnp.asarray(rxslot_np),
                       jnp.asarray(gxslot_np)]
        else:
            (op_np, mb_np, grp_np, rxslot_np), T, Sg, sentinel = \
                self._host_tables(m)
            base_xs = [jnp.asarray(op_np), jnp.asarray(mb_np),
                       jnp.asarray(grp_np), jnp.asarray(rxslot_np)]
        if lanes is not None:
            capf_np, capg_np, Kf, Kg = self._skip_tables(
                m, op_np, mb_np, grp_np, overlap=overlap)
            lane_fwd_perms, lane_bwd_perms = self._lane_perms()
            lane_hops = self._lane_hops()
            xs = tuple(base_xs + [jnp.asarray(capf_np),
                                  jnp.asarray(capg_np)])
        else:
            Kf = Kg = ()
            lane_hops = ()
            xs = tuple(base_xs)
        if phased_prog is not None:
            # host-side row columns for the per-phase lowering: unrolled
            # cycles slice single rows, scan segments reshape to
            # (iterations, period, ...) stacks
            cols_np = [op_np, mb_np, grp_np, rxslot_np, gxslot_np]
            if lanes is not None:
                cols_np += [capf_np, capg_np]
        # Split-backward (zero-bubble) tables carry WGRAD ops: B computes
        # the input grad only (and parks its cotangent); W consumes the
        # parked cotangent for the weight grads. Static: shapes the carry
        # and the branch list.
        has_w = bool((op_np == WGRAD).any())
        # Stored-residual mode: the one stored vjp serves both halves (XLA
        # DCE prunes weight-grad matmuls from B and input-grad matmuls from
        # W), so B parks its cotangent for W. Recompute modes: the vjp only
        # exists once the forward re-runs at B, so the FULL backward
        # accumulates there and W is a no-op — recompute-once, no park.
        split_dce = has_w and mode == "never"
        Wg = ((Wg_ov if overlap else self.schedule.wstash_slots(m, d))
              if split_dce else 0)

        # --- carry -------------------------------------------------------
        def zeros_of(spec):
            return jnp.zeros(spec.shape, spec.dtype)

        def slots_of(spec, k):
            # One extra sentinel slot so masked writes need no read-back.
            # EVERY slot store uses this form: the cycle body writes each
            # store exactly once, unconditionally, after the op switch
            # (non-writing ops target the sentinel). Cond-gated writes or
            # stores returned through lax.switch defeat XLA's while-loop
            # buffer aliasing and re-copy the whole store every cycle —
            # one sentinel slot of extra memory buys O(stores) MB/cycle
            # of removed copies.
            return jnp.zeros((k + 1,) + tuple(spec.shape), spec.dtype)

        h_ring = jax.tree_util.tree_map(zeros_of, h_spec)
        g_ring = jax.tree_util.tree_map(zeros_of, h_spec)
        stash = jax.tree_util.tree_map(
            lambda s_: slots_of(s_, v * Sg), h_spec)
        # Last virtual stage's outputs, parked until their backward rebuilds
        # the post vjp (activation-sized — the whole point of keeping the
        # post out of res_store; see _f_body docstring). Sg slots suffice:
        # h1 of micro-batch i goes live at FWD(i, S-1), no earlier than the
        # stash arrival the Sg FIFO proof bounds, and frees at the same
        # BWD(i, S-1).
        h_last = jax.tree_util.tree_map(
            lambda s_: slots_of(s_, Sg), h_spec)
        # Deferred-W park (B -> W window), activation-scale slots: the
        # downstream cotangent seed (legacy stored-vjp split) or the
        # per-op output cotangents g_zs (structural split).
        wpark_spec = zs_spec if self.split_stage is not None else h_spec
        wstash = (jax.tree_util.tree_map(
            lambda s_: slots_of(s_, v * Wg), wpark_spec)
            if split_dce else ())
        # Structural split: per-op input taps, FWD -> W FIFO window.
        taps_store = (jax.tree_util.tree_map(
            lambda s_: slots_of(s_, v * Sg), taps_spec)
            if self.split_stage is not None else ())
        n_res = self.memory_plan(m)["residual_slots"]
        res_store = ([slots_of(s_, n_res) for s_ in res_specs]
                     if mode != "always" else [])
        # Recompute micro-batches' policy-saved residuals: FWD -> BWD FIFO,
        # same window as the stash (slot g*Sg + i % Sg).
        pres_store = ([slots_of(s_, v * Sg) for s_ in pres_specs]
                      if use_policy else [])
        # Skip lanes: one forward + one reverse ring register per lane and
        # a sentinel-slotted FIFO park at each end (capture writes use the
        # host-computed slot tables, so the sentinel form applies).
        if lanes is not None:
            sk_ring = tuple(jax.tree_util.tree_map(zeros_of, sp_)
                            for sp_ in lanes.specs)
            gk_ring = tuple(jax.tree_util.tree_map(zeros_of, sp_)
                            for sp_ in lanes.specs)
            sk_park = tuple(
                jax.tree_util.tree_map(
                    lambda s_, k=k: slots_of(s_, k), sp_)
                for sp_, k in zip(lanes.specs, Kf))
            gk_park = tuple(
                jax.tree_util.tree_map(
                    lambda s_, k=k: slots_of(s_, k), sp_)
                for sp_, k in zip(lanes.specs, Kg))
        else:
            sk_ring = gk_ring = sk_park = gk_park = ()
        if overlap:
            # Packed double-buffered boundary carriers: ONE uint32 vector
            # per direction holds the in-flight boundary pytree — the h
            # ring value plus, per riding skip lane (>= 1 physical hops),
            # an H-slot shift register relaying the lane value one hop per
            # cycle (slot 0 = freshly boarded, slot H-1 = arriving). 0-hop
            # lanes (same device, v > 1) keep their flat register carry —
            # a permute would move them off-device.
            ride = tuple(h >= 1 for h in lane_hops)
            reg_idx = tuple(l for l in range(len(lane_hops))
                            if not ride[l])

            def lane_stack_spec(l):
                if not ride[l]:
                    return ()
                return jax.tree_util.tree_map(
                    lambda sp_: jax.ShapeDtypeStruct(
                        (lane_hops[l],) + tuple(sp_.shape), sp_.dtype),
                    lanes.specs[l])

            lane_stacks_spec = tuple(lane_stack_spec(l)
                                     for l in range(len(lane_hops)))
            pend_spec = (h_spec, lane_stacks_spec)
            pend_words = packed_words(pend_spec)
            pend_f0 = jnp.zeros((pend_words,), jnp.uint32)
            pend_g0 = jnp.zeros((pend_words,), jnp.uint32)
            # Elastic reverse ring: arriving cotangents park here until
            # their BWD (serialized transport consumes them on arrival —
            # its reverse ring is rigid and needs no park).
            gpark = jax.tree_util.tree_map(
                lambda s_: slots_of(s_, v * Gg), h_spec)
            sk_reg = tuple(jax.tree_util.tree_map(zeros_of, lanes.specs[l])
                           for l in reg_idx)
            gk_reg = tuple(jax.tree_util.tree_map(zeros_of, lanes.specs[l])
                           for l in reg_idx)
            reg_pos = {l: k for k, l in enumerate(reg_idx)}
            get_registry().gauge(
                "scheduled.transport.packed_words_per_direction").set(
                pend_words)
        g_sp = jax.tree_util.tree_map(jnp.zeros_like, params_dev)
        g_pre = jax.tree_util.tree_map(jnp.zeros_like, pre_params)
        g_post = jax.tree_util.tree_map(jnp.zeros_like, post_params)
        loss0 = jnp.zeros((), jnp.float32)

        if v == 1:
            fwd_perm = [(k, k + 1) for k in range(d - 1)]
            bwd_perm = [(k + 1, k) for k in range(d - 1)]
        else:
            fwd_perm = [(q, (q + 1) % d) for q in range(d)]
            bwd_perm = [(q, (q - 1) % d) for q in range(d)]

        def res_slot_for(i, g):
            """Where (micro-batch i, group g)'s residuals live. Non-saving
            forwards route their (zero) values to the sentinel slot, so
            this is only consulted for saved micro-batches."""
            if mode == "never":
                return g * Sg + i % Sg
            return g  # except_last: slot g holds micro-batch m-1

        # Zero write-values for ops that do not store into a given slot
        # store this cycle. The post-switch writer is unconditional — one
        # masked write per store per cycle, sentinel slot when inactive —
        # so every branch hands back a full (values, slot) set. Streaming
        # one zero value-set into a sentinel slot is the price of XLA
        # aliasing every store in place across the scan; cond-gated writes
        # and stores returned through lax.switch measurably re-copy the
        # whole store every cycle instead.
        res_zero = ([zeros_of(s_) for s_ in res_specs]
                    if mode != "always" else [])
        pres_zero = [zeros_of(s_) for s_ in pres_specs]
        taps_zero = (jax.tree_util.tree_map(zeros_of, taps_spec)
                     if self.split_stage is not None else ())
        w_zero = (jax.tree_util.tree_map(zeros_of, wpark_spec)
                  if split_dce else ())

        def cycle(carry, row, concrete=None, masked=False):
            """One table cycle. ``concrete=None``: interpreted — the op
            code is read from the row and dispatched via ``lax.switch``.
            ``concrete=<op code>`` (phase-compiled lowering): the branch is
            picked at TRACE time — no dispatch in the lowered body. Dense
            cycles (``masked=False``) run it as-is; ramp cycles with idle
            devices (``masked=True``) run the branch on garbage for the
            idle devices and mask the damage by data selects — store slots
            route to the sentinel, accumulators keep their prior value,
            lane registers keep their pass-through semantics. Transmitted
            garbage needs no mask: every park is driven by the host slot
            tables, which sentinel all unscheduled arrivals, and the
            double-buffered carriers never hold a value past its park."""
            if overlap:
                (pend_f, pend_g, stash, gpark, h_last, wstash, taps_store,
                 res_store, pres_store, sk_reg, gk_reg, sk_park, gk_park,
                 stats_acc, g_sp, g_pre, g_post, loss) = carry
            else:
                (h_ring, g_ring, stash, h_last, wstash, taps_store,
                 res_store, pres_store, sk_ring, gk_ring, sk_park, gk_park,
                 stats_acc, g_sp, g_pre, g_post, loss) = carry
            cols = list(row)
            op_r, mb_r, grp_r, rx_r = cols[:4]
            if overlap:
                gx_r = cols[4]
            if lanes is not None:
                capf_r, capg_r = cols[-2], cols[-1]
            opj = jax.lax.dynamic_index_in_dim(op_r, j, 0, keepdims=False)
            i = jax.lax.dynamic_index_in_dim(mb_r, j, 0, keepdims=False)
            g = jax.lax.dynamic_index_in_dim(grp_r, j, 0, keepdims=False)
            rslot = jax.lax.dynamic_index_in_dim(rx_r, j, 0, keepdims=False)
            s = g * d + j                 # this cycle's virtual stage
            if lanes is not None:
                fslots = [jax.lax.dynamic_index_in_dim(
                    capf_r[l], j, 0, keepdims=False)
                    for l in range(len(lanes.pairs))]
                gslots = [jax.lax.dynamic_index_in_dim(
                    capg_r[l], j, 0, keepdims=False)
                    for l in range(len(lanes.pairs))]

            if overlap:
                # Software pipeline: launch the collectives moving the
                # PREVIOUS cycle's packed sends NOW — nothing below this
                # cycle's switch reads them (the shifted tables prove every
                # consumer is >= 1 body behind the park), so the permutes
                # run alongside the compute instead of gating it.
                gslot = jax.lax.dynamic_index_in_dim(gx_r, j, 0,
                                                     keepdims=False)
                rx_f = jax.lax.ppermute(pend_f, STAGE_AXIS, fwd_perm)
                rx_g = jax.lax.ppermute(pend_g, STAGE_AXIS, bwd_perm)
                rx_h, rx_sks = unpack_words(rx_f, pend_spec)
                rx_gh, rx_gks = unpack_words(rx_g, pend_spec)
                # the names the shared branch code consumes: h_ring is
                # only a garbage filler for non-FWD tx_h; g_ring is the
                # parked cotangent seed for this (i, s)'s BWD; lane rings
                # are the arriving slot (riding lanes) or the register
                h_ring = rx_h
                g_ring = jax.tree_util.tree_map(
                    lambda st: jax.lax.dynamic_index_in_dim(
                        st, g * Gg + i % Gg, 0, keepdims=False), gpark)
                sk_ring = tuple(
                    (sk_reg[reg_pos[l]] if not ride[l]
                     else jax.tree_util.tree_map(lambda a: a[-1],
                                                 rx_sks[l]))
                    for l in range(len(lane_hops)))
                gk_ring = tuple(
                    (gk_reg[reg_pos[l]] if not ride[l]
                     else jax.tree_util.tree_map(lambda a: a[-1],
                                                 rx_gks[l]))
                    for l in range(len(lane_hops)))
            else:
                # 1) park the arriving activation (sentinel slot when not
                # real)
                stash = jax.tree_util.tree_map(
                    lambda st, hr: jax.lax.dynamic_update_index_in_dim(
                        st, hr, rslot, 0), stash, h_ring)
                # 1b) park arriving skip values / pop cotangents (host
                # tables mark the exact arrival cycles; sentinel slot
                # otherwise)
                if lanes is not None:
                    sk_park = tuple(
                        jax.tree_util.tree_map(
                            lambda st, reg, sl=sl:
                            jax.lax.dynamic_update_index_in_dim(
                                st, reg, sl, 0),
                            pk, rg)
                        for pk, rg, sl in zip(sk_park, sk_ring, fslots))
                    gk_park = tuple(
                        jax.tree_util.tree_map(
                            lambda st, reg, sl=sl:
                            jax.lax.dynamic_update_index_in_dim(
                                st, reg, sl, 0),
                            pk, rg)
                        for pk, rg, sl in zip(gk_park, gk_ring, gslots))

            kis = jax.random.fold_in(jax.random.fold_in(key, i), s)
            x_mb = _index(x, i)
            w_mb = _index(w, i)
            # v=1: the single group is hoisted statically (no per-cycle
            # gather); v>1: one gather per cycle selects the active group.
            params_g = (_index(params_dev, 0) if v == 1
                        else _index(params_dev, g))
            h_in = jax.tree_util.tree_map(
                lambda st: jax.lax.dynamic_index_in_dim(
                    st, g * Sg + i % Sg, 0, keepdims=False), stash)
            # Popped skip values for this (i, s): FIFO slot i % Kf per lane.
            # Every stage reads them (uniform code); only the lane's dst
            # stage body uses them. Recompute modes re-read at BWD, exactly
            # like h_in.
            pops = (tuple(
                jax.tree_util.tree_map(
                    lambda st, k=k: jax.lax.dynamic_index_in_dim(
                        st, i % k, 0, keepdims=False), pk)
                for pk, k in zip(sk_park, Kf))
                if lanes is not None else None)

            # Sentinel-routed (values, slot) pairs for branches that skip
            # a given store this cycle (full_like keeps the slot dtype
            # uniform across branches so lax.switch avals agree).
            no_res = (res_zero, jnp.full_like(i, n_res))
            no_pres = (pres_zero, jnp.full_like(i, v * Sg))
            no_taps = (taps_zero, jnp.full_like(i, v * Sg))
            no_w = (w_zero, jnp.full_like(i, v * Wg))
            hl_none = jnp.full_like(i, Sg)

            def apply_vjp(seed):
                """Cotangents from the stored or recomputed vjp per the
                checkpoint policy — shared by the B and W branches so slot
                layout and policy gating cannot drift between them. ``seed``
                is ``g_h`` (or ``(g_h, g_stashes)`` with skip lanes); the
                result gains ``g_pops`` with lanes."""
                def apply_stored():
                    return _load_vjp(res_store, res_treedef,
                                     res_slot_for(i, g))(seed)

                def apply_recomputed():
                    _, vjp_fn = self._vjp_wrt(
                        params_g, pre_params, h_in, x_mb, kis, s, pops)
                    return vjp_fn(seed)

                def apply_policy_stored():
                    return _load_vjp(pres_store, pres_treedef,
                                     g * Sg + i % Sg)(seed)

                if mode == "never":
                    return apply_stored()
                recompute = (apply_policy_stored if use_policy
                             else apply_recomputed)
                if mode == "always":
                    return recompute()
                # except_last: stored for m-1, recomputed otherwise
                return jax.lax.cond(i == m - 1, apply_stored, recompute)

            def scatter_gp(G, gp):
                """Accumulate group g's param grads into its row of G."""
                if v == 1:
                    return jax.tree_util.tree_map(
                        lambda G_, gg: G_ + gg[None], G, gp)
                return jax.tree_util.tree_map(
                    lambda G_, gg: jax.lax.dynamic_update_index_in_dim(
                        G_, jax.lax.dynamic_index_in_dim(
                            G_, g, 0, keepdims=False) + gg, g, 0),
                    G, gp)

            def fwd_branch():
                def vjp_and_store():
                    out, vjp_fn = self._vjp_wrt(
                        params_g, pre_params, h_in, x_mb, kis, s, pops)
                    return (out, (_vjp_leaves(vjp_fn, res_specs),
                                  res_slot_for(i, g)), no_pres, no_taps)

                def split_vjp_and_store():
                    # structural split: params-constant vjp + taps values;
                    # passthrough residual leaves (weights, pre params,
                    # h_in, x_mb) are dropped here and rebuilt at B from
                    # the branch environment — see split_res_pt above
                    out, vjp_fn, taps = self._vjp_wrt_split(
                        params_g, pre_params, h_in, x_mb, kis, s)
                    leaves = jax.tree_util.tree_leaves(vjp_fn)
                    stored = [l for idx, l in enumerate(leaves)
                              if idx not in split_res_pt]
                    assert [(l.shape, l.dtype) for l in stored] == \
                        [(sp_.shape, sp_.dtype) for sp_ in res_specs], \
                        "split vjp residual structure drifted from spec"
                    return (out, (stored, res_slot_for(i, g)), no_pres,
                            (taps, g * Sg + i % Sg))

                def policy_vjp_and_store():
                    # selective remat: forward hands back the policy-saved
                    # residual subset (its own uniform slot structure);
                    # backward recomputes only the cheap remainder
                    out, vjp_fn = self._vjp_wrt_policy(
                        params_g, pre_params, h_in, x_mb, kis, s, pops)
                    return (out, no_res,
                            (_vjp_leaves(vjp_fn, pres_specs),
                             g * Sg + i % Sg), no_taps)

                def body_only():
                    return (self._f_body(params_g, pre_params, h_in, x_mb,
                                         kis, s, pops), no_res, no_pres,
                            no_taps)

                recompute_fwd = (policy_vjp_and_store if use_policy
                                 else body_only)
                if self.split_stage is not None:   # never mode guaranteed
                    out, res_w, pres_w, taps_w = split_vjp_and_store()
                elif mode == "always":
                    out, res_w, pres_w, taps_w = recompute_fwd()
                elif mode == "never":
                    out, res_w, pres_w, taps_w = vjp_and_store()
                else:
                    # except_last: ONLY micro-batch m-1 pays the residual
                    # capture; the rest run the plain body (they recompute
                    # at BWD) or, under remat_policy, hand back just the
                    # policy-saved subset — their full-residual values are
                    # zeros bound for the sentinel slot.
                    out, res_w, pres_w, taps_w = jax.lax.cond(
                        i == m - 1, vjp_and_store, recompute_fwd)
                h1, stashes, stats_t = self._split_out(out)
                if lanes is not None:
                    # inject this stage's fresh stashes into their lanes;
                    # pass the arriving value onward everywhere else
                    tx_sk = tuple(
                        jax.tree_util.tree_map(
                            lambda sv, reg, src=src: jnp.where(
                                jnp.asarray(s == src), sv, reg), svv, rg)
                        for (src, _), svv, rg in zip(lanes.pairs, stashes,
                                                     sk_ring))
                else:
                    tx_sk = sk_ring
                # FWD ops run only on real (i, s) — no fill/drain garbage
                # to mask, and BWD recomputes discard their stats, so this
                # is the one accumulation point
                new_stats = (jax.tree_util.tree_map(jnp.add, stats_acc,
                                                    stats_t)
                             if self.stat_spec is not None else stats_acc)
                is_last = s == S - 1
                # loss contribution: forward value only (its vjp is rebuilt
                # at BWD time from the parked h1 — never stored)
                contrib = jax.lax.cond(
                    is_last,
                    lambda: self._post_contrib(post_params, h1, x_mb, w_mb,
                                               kis),
                    lambda: jnp.zeros((), jnp.float32))
                # h1 doubles as the h_last write value (tx_h); non-last
                # stages stream it into the sentinel slot
                hl_slot = jnp.where(is_last, i % Sg, Sg)
                return (hl_slot, no_w, taps_w, res_w, pres_w,
                        new_stats, g_sp, g_pre, g_post, loss + contrib, h1,
                        g_ring, tx_sk, gk_ring)

            def bwd_branch():
                is_last = s == S - 1

                # Last stage: rebuild the post vjp FRESH from the parked h1
                # (no vocab-scale residuals live in the carry; the compiled
                # analogue of the reference's loss living outside Pipe and
                # its gradient seeding the recorded graph, main.py:216-218).
                # Cotangent of the contribution: d(masked mean) = 1/sum(w).
                def post_seed():
                    h1 = jax.tree_util.tree_map(
                        lambda st: jax.lax.dynamic_index_in_dim(
                            st, i % Sg, 0, keepdims=False), h_last)
                    _, post_vjp = jax.vjp(
                        lambda pp, hh: self._post_contrib(pp, hh, x_mb, w_mb,
                                                          kis),
                        post_params, h1)
                    gpost_, gh1 = post_vjp(inv_wsum)
                    # int (non-differentiable) carrier lanes — e.g. token
                    # ids in the packed boundary — yield float0 cotangents;
                    # the ring carries concrete placeholder zeros for them
                    return gpost_, _vjp_to_ring(gh1, h_spec)

                def ring_seed():
                    return (jax.tree_util.tree_map(jnp.zeros_like,
                                                   post_params), g_ring)

                gpost, seed_h = jax.lax.cond(is_last, post_seed, ring_seed)
                add = functools.partial(jax.tree_util.tree_map, jnp.add)

                if lanes is not None:
                    # stash-output seeds: the pop cotangent that rode the
                    # reverse ring from BWD(i, dst), parked at this source
                    # device; zeros for lanes this stage does not stash
                    # (their stash outputs are constants anyway)
                    seed_sk = tuple(
                        jax.tree_util.tree_map(
                            lambda st, k=k, src=src: jnp.where(
                                jnp.asarray(s == src),
                                jax.lax.dynamic_index_in_dim(
                                    st, i % k, 0, keepdims=False),
                                jnp.zeros(st.shape[1:], st.dtype)),
                            pk)
                        for pk, k, (src, _) in zip(gk_park, Kg,
                                                   lanes.pairs))
                else:
                    seed_sk = None
                seed_f0 = _ring_to_seed(seed_h, h_spec)
                seed = self._make_seed(seed_f0, seed_sk)

                if self.split_stage is not None:
                    # structural split: the stored params-constant vjp IS
                    # the input-grad chain (zero weight-grad contractions
                    # in it by construction); per-op output cotangents
                    # park for W, pre grads accumulate here (edge-stage
                    # embed path only).
                    slot = res_slot_for(i, g)
                    stored = iter(
                        jax.lax.dynamic_index_in_dim(st, slot, 0,
                                                     keepdims=False)
                        for st in res_store)
                    env = {"pg": jax.tree_util.tree_leaves(params_g),
                           "pre": jax.tree_util.tree_leaves(pre_params),
                           "h": jax.tree_util.tree_leaves(h_in),
                           "x": jax.tree_util.tree_leaves(x_mb)}
                    leaves = [
                        (next(stored) if idx not in split_res_pt
                         else env[split_res_pt[idx][0]]
                         [split_res_pt[idx][1]])
                        for idx in range(n_res_leaves_full)]
                    vjp_fn = jax.tree_util.tree_unflatten(res_treedef,
                                                          leaves)
                    gpre, gh, gzs = vjp_fn(seed_f0)
                    gh = _vjp_to_ring(gh, h_spec)
                    return (hl_none, (gzs, g * Wg + i % Wg), no_taps,
                            no_res, no_pres, stats_acc, g_sp,
                            add(g_pre, gpre), add(g_post, gpost), loss,
                            h_ring, gh, sk_ring, gk_ring)

                if lanes is not None:
                    gp, gpre, gh, g_pops = apply_vjp(seed)
                    # pop cotangents board the reverse ring at their dst
                    # stage; everyone else forwards the arriving value
                    tx_gk = tuple(
                        jax.tree_util.tree_map(
                            lambda gv, reg, dst=dst: jnp.where(
                                jnp.asarray(s == dst), gv, reg), gvv, rg)
                        for (_, dst), gvv, rg in zip(lanes.pairs, g_pops,
                                                     gk_ring))
                else:
                    gp, gpre, gh = apply_vjp(seed)
                    tx_gk = gk_ring
                gh = _vjp_to_ring(gh, h_spec)
                if split_dce:
                    # split backward, stored residuals: B emits only the
                    # input grad (XLA DCE prunes the unused weight-grad
                    # matmuls from the stored-residual call); the cotangent
                    # parks for the W op.
                    return (hl_none, (seed_h, g * Wg + i % Wg), no_taps,
                            no_res, no_pres, stats_acc, g_sp, g_pre,
                            add(g_post, gpost), loss, h_ring, gh,
                            sk_ring, tx_gk)
                # combined backward (non-split tables), or a split table
                # under a recompute mode — the vjp was just built from the
                # single forward recompute, so weight grads accumulate here
                # and the table's W slot (if any) is a no-op.
                return (hl_none, no_w, no_taps, no_res, no_pres,
                        stats_acc, scatter_gp(g_sp, gp), add(g_pre, gpre),
                        add(g_post, gpost), loss, h_ring, gh,
                        sk_ring, tx_gk)

            def wgrad_branch():
                add = functools.partial(jax.tree_util.tree_map, jnp.add)
                if self.split_stage is not None:
                    # structural split: NOTHING here but the weight-grad
                    # contractions from (taps, per-op cotangents).
                    taps = jax.tree_util.tree_map(
                        lambda st: jax.lax.dynamic_index_in_dim(
                            st, g * Sg + i % Sg, 0, keepdims=False),
                        taps_store)
                    gzs = jax.tree_util.tree_map(
                        lambda st: jax.lax.dynamic_index_in_dim(
                            st, g * Wg + i % Wg, 0, keepdims=False), wstash)
                    gp = self.split_stage.wgrad_fn(taps, gzs)
                    return (hl_none, no_w, no_taps, no_res, no_pres,
                            stats_acc, scatter_gp(g_sp, gp),
                            g_pre, g_post, loss, h_ring, g_ring,
                            sk_ring, gk_ring)
                if not split_dce:
                    # recompute modes: full backward already ran at B.
                    return idle_branch()
                seed_h = jax.tree_util.tree_map(
                    lambda st: jax.lax.dynamic_index_in_dim(
                        st, g * Wg + i % Wg, 0, keepdims=False), wstash)
                gp, gpre, _ = apply_vjp(_ring_to_seed(seed_h, h_spec))
                return (hl_none, no_w, no_taps, no_res, no_pres,
                        stats_acc, scatter_gp(g_sp, gp), add(g_pre, gpre),
                        g_post, loss, h_ring, g_ring, sk_ring, gk_ring)

            def idle_branch():
                return (hl_none, no_w, no_taps, no_res, no_pres,
                        stats_acc, g_sp, g_pre, g_post, loss, h_ring,
                        g_ring, sk_ring, gk_ring)

            branches = [idle_branch, fwd_branch, bwd_branch]
            if has_w:
                branches.append(wgrad_branch)
            if concrete is None:
                branch_out = jax.lax.switch(opj, branches)
            else:
                branch_out = branches[concrete]()
            (hl_slot, (w_v, w_s), (taps_v, taps_s), (res_v, res_s),
             (pres_v, pres_s), stats2, g_sp2, g_pre2, g_post2, loss2,
             tx_h, tx_g, tx_sk, tx_gk) = branch_out
            if concrete is not None and masked and concrete != IDLE:
                # Partially idle ramp cycle: idle devices just ran the
                # cycle's branch on garbage inputs. Garbage VALUES are
                # inert (sentinel-driven parks, see cycle docstring);
                # garbage SLOTS and accumulator updates are not — route
                # the former to the sentinels and keep the latter.
                active = opj == concrete

                def keep(new, old):
                    return jax.tree_util.tree_map(
                        lambda a_, b_: jnp.where(active, a_, b_), new, old)

                hl_slot = jnp.where(active, hl_slot, Sg)
                w_s = jnp.where(active, w_s, v * Wg)
                taps_s = jnp.where(active, taps_s, v * Sg)
                res_s = jnp.where(active, res_s, n_res)
                pres_s = jnp.where(active, pres_s, v * Sg)
                stats2 = keep(stats2, stats_acc)
                g_sp2 = keep(g_sp2, g_sp)
                g_pre2 = keep(g_pre2, g_pre)
                g_post2 = keep(g_post2, g_post)
                loss2 = jnp.where(active, loss2, loss)
                # idle semantics for lane registers is pass-through: a
                # garbage overwrite here would clobber a live 0-hop
                # register between its stash and pop stages
                tx_sk = tuple(keep(t_, r_)
                              for t_, r_ in zip(tx_sk, sk_ring))
                tx_gk = tuple(keep(t_, r_)
                              for t_, r_ in zip(tx_gk, gk_ring))

            # THE slot-store writers: branches return (values, slot), and
            # each store takes exactly one unconditional masked write per
            # cycle here — never a whole updated store through the switch
            # — so XLA aliases every store in place across the scan
            # instead of re-copying it each cycle. tx_h doubles as the
            # h_last write value (h1 on FWD cycles; sentinel otherwise).
            h_last2 = jax.tree_util.tree_map(
                lambda st, l: jax.lax.dynamic_update_index_in_dim(
                    st, l, hl_slot, 0), h_last, tx_h)
            wstash2 = (jax.tree_util.tree_map(
                lambda st, l: jax.lax.dynamic_update_index_in_dim(
                    st, l, w_s, 0), wstash, w_v) if split_dce else ())
            taps2 = (jax.tree_util.tree_map(
                lambda st, l: jax.lax.dynamic_update_index_in_dim(
                    st, l, taps_s, 0), taps_store, taps_v)
                if self.split_stage is not None else ())
            res_store2 = [
                jax.lax.dynamic_update_index_in_dim(st, l, res_s, 0)
                for st, l in zip(res_store, res_v)]
            pres_store2 = [
                jax.lax.dynamic_update_index_in_dim(st, l, pres_s, 0)
                for st, l in zip(pres_store, pres_v)]

            if overlap:
                # Park this cycle's ARRIVALS only now — the compute above
                # read the pre-park carry, so the unpacked receives never
                # gate the switch (first legal read is the next body).
                stash2 = jax.tree_util.tree_map(
                    lambda st, hr: jax.lax.dynamic_update_index_in_dim(
                        st, hr, rslot, 0), stash, rx_h)
                gpark2 = jax.tree_util.tree_map(
                    lambda st, gr: jax.lax.dynamic_update_index_in_dim(
                        st, gr, gslot, 0), gpark, rx_gh)
                if lanes is not None:
                    # lane captures: riding lanes park their expiring
                    # shift-register slot, register lanes the register —
                    # both are what sk_ring/gk_ring already name
                    sk_park2 = tuple(
                        jax.tree_util.tree_map(
                            lambda st, reg, sl=sl:
                            jax.lax.dynamic_update_index_in_dim(
                                st, reg, sl, 0),
                            pk, rg)
                        for pk, rg, sl in zip(sk_park, sk_ring, fslots))
                    gk_park2 = tuple(
                        jax.tree_util.tree_map(
                            lambda st, reg, sl=sl:
                            jax.lax.dynamic_update_index_in_dim(
                                st, reg, sl, 0),
                            pk, rg)
                        for pk, rg, sl in zip(gk_park, gk_ring, gslots))
                    # relay: the freshly boarded value enters slot 0,
                    # everything in flight advances one hop
                    tx_stacks = tuple(
                        (() if not ride[l] else jax.tree_util.tree_map(
                            lambda bv, stk: jnp.concatenate(
                                [bv[None], stk[:-1]], axis=0),
                            tx_sk[l], rx_sks[l]))
                        for l in range(len(lane_hops)))
                    tg_stacks = tuple(
                        (() if not ride[l] else jax.tree_util.tree_map(
                            lambda bv, stk: jnp.concatenate(
                                [bv[None], stk[:-1]], axis=0),
                            tx_gk[l], rx_gks[l]))
                        for l in range(len(lane_hops)))
                    sk_reg2 = tuple(tx_sk[l] for l in reg_idx)
                    gk_reg2 = tuple(tx_gk[l] for l in reg_idx)
                else:
                    sk_park2, gk_park2 = sk_park, gk_park
                    tx_stacks = tg_stacks = ()
                    sk_reg2 = gk_reg2 = ()
                pend_f2 = pack_words((tx_h, tx_stacks))
                pend_g2 = pack_words((tx_g, tg_stacks))
                return (pend_f2, pend_g2, stash2, gpark2, h_last2, wstash2,
                        taps2, res_store2, pres_store2, sk_reg2, gk_reg2,
                        sk_park2, gk_park2, stats2, g_sp2, g_pre2, g_post2,
                        loss2), None

            if d > 1:
                tx_h = jax.tree_util.tree_map(
                    lambda a: jax.lax.ppermute(a, STAGE_AXIS, fwd_perm), tx_h)
                tx_g = jax.tree_util.tree_map(
                    lambda a: jax.lax.ppermute(a, STAGE_AXIS, bwd_perm), tx_g)
                if lanes is not None:
                    # each lane takes its OWN direct hop (src%d -> dst%d);
                    # same-device lanes keep the register as transport
                    tx_sk = tuple(
                        (jax.tree_util.tree_map(
                            lambda a, pf=pf: jax.lax.ppermute(
                                a, STAGE_AXIS, pf), lv)
                         if pf is not None else lv)
                        for lv, pf in zip(tx_sk, lane_fwd_perms))
                    tx_gk = tuple(
                        (jax.tree_util.tree_map(
                            lambda a, pb=pb: jax.lax.ppermute(
                                a, STAGE_AXIS, pb), lv)
                         if pb is not None else lv)
                        for lv, pb in zip(tx_gk, lane_bwd_perms))
            return (tx_h, tx_g, stash, h_last2, wstash2, taps2, res_store2,
                    pres_store2, tx_sk, tx_gk, sk_park, gk_park, stats2,
                    g_sp2, g_pre2, g_post2, loss2), None

        stats0 = (self._zero_seed_like(self.stat_spec)
                  if self.stat_spec is not None else ())
        if overlap:
            carry0 = (pend_f0, pend_g0, stash, gpark, h_last, wstash,
                      taps_store, res_store, pres_store, sk_reg, gk_reg,
                      sk_park, gk_park, stats0, g_sp, g_pre, g_post, loss0)
        else:
            carry0 = (h_ring, g_ring, stash, h_last, wstash, taps_store,
                      res_store, pres_store, sk_ring, gk_ring, sk_park,
                      gk_park, stats0, g_sp, g_pre, g_post, loss0)
        if phased_prog is not None:
            # Per-phase lowering: ramps unroll to straight-line cycles
            # (concrete op code each, idle devices masked), the dense
            # periodic steady state becomes a fixed-body scan — the body
            # is the period's concrete branch sequence, one sub-cycle per
            # period offset, fed by (iterations, period, ...) row stacks.
            # No lax.switch anywhere; no masked no-ops inside the scan.
            codes = phased_prog.cycle_codes
            dense = phased_prog.dense
            carry = carry0
            for seg in phased_prog.segments:
                if seg.kind == "unroll":
                    for t in range(seg.t0, seg.t1):
                        row = tuple(jnp.asarray(c[t]) for c in cols_np)
                        carry, _ = cycle(carry, row, concrete=codes[t],
                                         masked=not dense[t])
                    continue
                seg_xs = tuple(
                    jnp.asarray(c[seg.t0:seg.t1].reshape(
                        (seg.iters, seg.period) + c.shape[1:]))
                    for c in cols_np)

                def seg_body(carry, rows, _codes=seg.codes):
                    for k, code_k in enumerate(_codes):
                        sub = tuple(r_[k] for r_ in rows)
                        carry, _ = cycle(carry, sub, concrete=code_k)
                    return carry, None

                carry, _ = jax.lax.scan(seg_body, carry, seg_xs)
            final_carry = carry
        else:
            final_carry, _ = jax.lax.scan(cycle, carry0, xs)
        stats_out, g_sp, g_pre, g_post, loss = final_carry[-5:]

        # --- cross-device reductions ------------------------------------
        # stage grads: per-device shards stay put; replicas over other axes
        # sum (never the model axis — TP grad contract)
        other_axes = self._grad_reduce_axes()
        if other_axes:
            g_sp = jax.tree_util.tree_map(
                lambda gg: jax.lax.psum(gg, other_axes), g_sp)
        # pre/post grads + loss: only edge stages contributed; psum collects
        reduce_axes = (STAGE_AXIS,) + other_axes
        g_pre = jax.tree_util.tree_map(
            lambda gg: jax.lax.psum(gg, reduce_axes), g_pre)
        g_post = jax.tree_util.tree_map(
            lambda gg: jax.lax.psum(gg, reduce_axes), g_post)
        loss_axes = ((STAGE_AXIS, DATA_AXIS) if self.has_data_axis
                     else (STAGE_AXIS,))
        loss = jax.lax.psum(loss, loss_axes) * inv_wsum

        if self.stat_spec is not None:
            # each stage fills only its own slots (zeros elsewhere) and
            # data shards hold per-shard partial sums — NOT the model
            # axis, over which activations (hence stats) are replicated
            stats_out = jax.tree_util.tree_map(
                lambda a: jax.lax.psum(a, loss_axes), stats_out)
            return loss, (g_sp, g_pre, g_post), stats_out
        return loss, (g_sp, g_pre, g_post)


def _index_spec(tree):
    return jax.tree_util.tree_map(lambda l: l[0], tree)
