"""Mesh construction helpers for the (stage, data) device grid.

The reference's "device layer" is a list of per-partition CUDA devices plus
``chunks × stages`` copy streams (``pipe.py:350-351,417-429``). The TPU-native
equivalent is a named ``jax.sharding.Mesh``: the ``stage`` axis carries the
pipeline (transport = ``ppermute`` over ICI), and an optional ``data`` axis
gives first-class data parallelism — composable with every checkpoint mode,
fixing the reference's DDP-only-with-checkpoint='never' limitation
(``pipe.py:290-293``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["make_mesh", "STAGE_AXIS", "DATA_AXIS", "CONTEXT_AXIS",
           "MODEL_AXIS"]

STAGE_AXIS = "stage"
DATA_AXIS = "data"
CONTEXT_AXIS = "context"
MODEL_AXIS = "model"


def make_mesh(n_stages: int,
              n_data: Optional[int] = None,
              *,
              n_context: Optional[int] = None,
              n_model: Optional[int] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ``(stage[, data][, context][, model])`` mesh.

    With ``n_data=None`` the data axis is sized to use all remaining devices
    (``len(devices) // (n_stages * n_context * n_model)``); pass ``n_data=1``
    for a pure pipeline mesh. Stage is the *outer* axis so consecutive stages
    land on ICI-adjacent devices in the common case; the context axis
    (sequence parallelism) and the model axis (tensor parallelism) are
    innermost so their per-layer collectives (K/V ring; the two psums per
    block) stay ICI-local — TP has the highest collective frequency, so it
    gets the fastest links (the scaling-book layout).
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_stages <= 0:
        raise ValueError("n_stages must be positive")
    if n_context is not None and n_context <= 0:
        raise ValueError("n_context must be positive (or None for no axis)")
    if n_model is not None and n_model <= 0:
        raise ValueError("n_model must be positive (or None for no axis)")
    ctx = n_context or 1
    tp = n_model or 1
    if len(devices) % (n_stages * ctx * tp):
        raise ValueError(
            f"{len(devices)} devices not divisible by "
            f"n_stages*n_context*n_model={n_stages * ctx * tp}")
    if n_data is None:
        n_data = len(devices) // (n_stages * ctx * tp)
    used = n_stages * n_data * ctx * tp
    if used > len(devices):
        raise ValueError(
            f"mesh {n_stages}x{n_data}x{ctx}x{tp} needs {used} devices, "
            f"have {len(devices)}")
    shape = [n_stages, n_data]
    names = [STAGE_AXIS, DATA_AXIS]
    if n_context is not None:
        shape.append(ctx)
        names.append(CONTEXT_AXIS)
    if n_model is not None:
        shape.append(tp)
        names.append(MODEL_AXIS)
    grid = np.asarray(devices[:used]).reshape(shape)
    return Mesh(grid, tuple(names))
