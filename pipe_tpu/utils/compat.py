"""Version tolerance for the narrow jax surface this framework binds.

The framework targets current jax (``jax.shard_map``,
``jax_num_cpu_devices``, ``jax.profiler.ProfileData``); CI containers and
user sites often carry one stable release behind, where the same
capabilities live under older names (``jax.experimental.shard_map`` with
``check_rep``, ``--xla_force_host_platform_device_count``) or do not exist
at all (xplane parsing). Every cross-version binding goes through here so
call sites stay on the modern spelling and the fallback policy is written
once.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

__all__ = ["shard_map", "profile_data", "set_num_cpu_devices", "axis_size"]


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` on current jax; on releases that predate the
    public export (<= 0.4.x) the size comes from the bound axis frame —
    same static int, no collective."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    from jax.core import axis_frame  # type: ignore[attr-defined]
    frame = axis_frame(axis_name)
    return frame if isinstance(frame, int) else frame.size


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` on current jax; the ``jax.experimental`` spelling
    (with ``check_vma`` renamed to its predecessor ``check_rep``) on
    releases that predate the public export."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def profile_data() -> Optional[Any]:
    """``jax.profiler.ProfileData`` (xplane proto parsing) or None when
    this jax cannot read traces back — callers degrade to their
    timing-based fallbacks."""
    try:
        from jax.profiler import ProfileData  # type: ignore[attr-defined]
        return ProfileData
    except ImportError:
        return None


def set_num_cpu_devices(num_devices: int) -> None:
    """Request ``num_devices`` virtual CPU devices, before backend init.

    Current jax exposes this as the ``jax_num_cpu_devices`` config; older
    releases only honor the XLA flag ``--xla_force_host_platform_device_
    count``, which must be in ``XLA_FLAGS`` before the CPU client starts.
    """
    try:
        jax.config.update("jax_num_cpu_devices", num_devices)
    except AttributeError:
        import os
        flag = f"--xla_force_host_platform_device_count={num_devices}"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = f"{flags} {flag}".strip()
