"""Platform helpers: force the virtual multi-device CPU platform for tests/CI.

The TPU-build analogue of the reference's CPU-sentinel-stream trick
(``AbstractStream`` admits a CPU fallback so every layer unit-tests without
GPUs — reference pipe.py:22, pipeline.py:22): here the whole framework —
scheduler, SPMD shard_map pipeline, ppermute rings, remat — runs on N virtual
CPU devices, so multi-"chip" tests need no TPU pod.

This machine additionally boots every interpreter through an ``.axon_site``
sitecustomize registering a real-TPU PJRT plugin and pinning
``JAX_PLATFORMS=axon``; with that plugin registered, CPU selection via env
vars hangs at backend init. :func:`force_cpu_platform` therefore neutralizes
the plugin in-process (pop the factory, flip ``jax_platforms`` through
``jax.config``) — which works whether or not jax was already imported.
"""

from __future__ import annotations

import os

__all__ = ["force_cpu_platform", "on_real_tpu"]


def force_cpu_platform(num_devices: int = 8) -> None:
    """Make jax see ``num_devices`` CPU devices, even on axon-hooked machines.

    Must run before the first jax *computation* (backend init), but is safe
    after ``import jax``.
    """
    os.environ.setdefault("PIPE_TPU_FORCED_CPU", "1")
    import jax
    from jax._src import xla_bridge as xb

    xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", num_devices)


def on_real_tpu() -> bool:
    import jax

    try:
        return jax.devices()[0].platform.lower() in ("tpu", "axon")
    except Exception:
        return False
