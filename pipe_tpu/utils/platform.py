"""Platform helpers: force the virtual multi-device CPU platform for tests/CI.

The TPU-build analogue of the reference's CPU-sentinel-stream trick
(``AbstractStream`` admits a CPU fallback so every layer unit-tests without
GPUs — reference pipe.py:22, pipeline.py:22): here the whole framework —
scheduler, SPMD shard_map pipeline, ppermute rings, remat — runs on N virtual
CPU devices, so multi-"chip" tests need no TPU pod.

This machine additionally boots every interpreter through an ``.axon_site``
sitecustomize registering a real-TPU PJRT plugin and pinning
``JAX_PLATFORMS=axon``; with that plugin registered, CPU selection via env
vars hangs at backend init. :func:`force_cpu_platform` therefore neutralizes
the plugin in-process (pop the factory, flip ``jax_platforms`` through
``jax.config``) — which works whether or not jax was already imported.
"""

from __future__ import annotations

import os

__all__ = ["force_cpu_platform", "on_real_tpu"]


def force_cpu_platform(num_devices: int = 8) -> None:
    """Make jax see ``num_devices`` CPU devices, even on axon-hooked machines.

    Must run before the first jax *computation* (backend init), but is safe
    after ``import jax``.
    """
    os.environ.setdefault("PIPE_TPU_FORCED_CPU", "1")
    import jax
    from jax._src import xla_bridge as xb

    # N virtual devices time-share the host cores (often ONE core in CI).
    # XLA:CPU's collective rendezvous hard-terminates the process when a
    # participant is >45s late — which a device legitimately is whenever its
    # pre-collective compute runs serialized behind 7 siblings. Give the
    # rendezvous real headroom; these flags must be set before backend init.
    # Older XLA builds (no ``jax_num_cpu_devices`` config either) predate
    # the flags AND abort on unknown XLA_FLAGS, so gate on the vintage.
    if hasattr(jax.config, "jax_num_cpu_devices"):
        flags = os.environ.get("XLA_FLAGS", "")
        for flag in ("xla_cpu_collective_timeout_seconds",
                     "xla_cpu_collective_call_terminate_timeout_seconds"):
            if flag not in flags:   # never override an operator's setting
                flags = f"{flags} --{flag}=600".strip()
        os.environ["XLA_FLAGS"] = flags

    xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")
    from .compat import set_num_cpu_devices
    set_num_cpu_devices(num_devices)


def sync_if_forced_cpu(x):
    """Block on ``x`` when running on the forced-CPU virtual platform.

    On N virtual devices time-sharing few host cores, jax's async dispatch
    lets successive compiled runs interleave; blocked collective-rendezvous
    waiters from run k+1 can then starve the worker threads run k still
    needs — a livelock (observed: 7 devices parked in run k+1's first
    ppermute while run k never finishes on the one remaining thread).
    Serializing steps with a host sync removes the hazard. On real TPU this
    is a no-op: async dispatch is exactly what overlaps host and device
    there, and the rendezvous mechanism does not exist.
    """
    if os.environ.get("PIPE_TPU_FORCED_CPU"):
        import jax

        jax.block_until_ready(x)
    return x


def on_real_tpu() -> bool:
    import jax

    try:
        return jax.devices()[0].platform.lower() in ("tpu", "axon")
    except Exception:
        return False
