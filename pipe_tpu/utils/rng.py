"""Backend-tuned PRNG key construction.

Dropout randomness is driven by explicit JAX PRNG keys — the TPU-native
replacement for the reference's CUDA RNG state capture/restore in recompute
(``README.md:528-537``): the same key replayed through the remat'd forward
reproduces every mask bit-for-bit, whatever the key's implementation.

The *implementation* rides with the key, and it matters for throughput: the
portable default (``threefry2x32``) computes random bits on the VPU and at
tutorial-LM mask volume costs real time — measured on v5e, 56 ms of a 216 ms
train step (26%) was threefry bit generation (three residual-branch masks of
[rows, seq, d_model] plus an attention-weight mask of [rows, heads, seq, seq]
per layer, x16 layers x 4 micro-batches, regenerated again in the remat
re-forward). The TPU-native ``rbg`` impl maps to the hardware
``RngBitGenerator`` and removes ~80% of that cost (measured 215.7 ->
159.7 ms/step).

Properties preserved by ``rbg`` that this framework relies on:

* same key -> same bits: remat replay stays bit-identical (``core/remat``);
* ``fold_in``/``split`` derive decorrelated per-(micro-batch, stage, layer)
  streams (the executors fold indices into the step key).

What ``rbg`` gives up is cross-backend bit-stability of the streams — which
nothing here relies on: transparency tests compare pipelined vs plain *within*
one platform using one key, and the CPU suite keeps the default impl (this
helper only selects ``rbg`` when the backend really is TPU).
"""

from __future__ import annotations

from typing import Optional

import jax

__all__ = ["default_prng_impl", "make_key"]


def default_prng_impl() -> Optional[str]:
    """The throughput-right key impl for the current backend.

    ``"rbg"`` on TPU (hardware RngBitGenerator); ``None`` (jax's configured
    default, normally threefry2x32) everywhere else.
    """
    return "rbg" if jax.default_backend() == "tpu" else None


def make_key(seed: int, impl: Optional[str] = None) -> jax.Array:
    """``jax.random.key`` with the backend-tuned impl (override with ``impl``)."""
    chosen = impl if impl is not None else default_prng_impl()
    if chosen is None:
        return jax.random.key(seed)
    return jax.random.key(seed, impl=chosen)
