"""GPT-2 model family, pipelined (BASELINE.json config #3: 4-stage
GPT-2-small 124M, chunks=16, skip-connection via ``@skippable``).

Architecture: learned token + position embeddings, pre-LN blocks with gelu_new (tanh-approximate GELU)
(:class:`~pipe_tpu.ops.layers.PreLNBlock`), final LayerNorm, vocab head.
The head is untied from the embedding table: tied weights would be one
parameter owned by two pipeline stages, which the reference rejects outright
(``_verify_splitting``, reference ``pipe.py:70-87``) and which an SPMD
stage-sharded layout cannot express without replication; documented
divergence from the original GPT-2.

Two factorizations, mirroring :mod:`.transformer_lm`:

* :func:`build_sequential` — layer list for ``Pipe`` (any balance, emulator
  or ``mesh=`` executor). With ``embed_skip=True`` the embedding output is
  ``@skippable``-stashed at stage 0 and popped into the final pre-head
  LayerNorm input — a cross-stage residual demonstrating the skip subsystem
  on a real model (the BASELINE config names exactly this composition).
* :class:`PipelinedGPT2` — homogeneous stage stack for the compiled
  training executors (SpmdPipeline / ScheduledPipeline / interleaved).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List

import jax
import jax.numpy as jnp

from ..core.partition import StageCtx
from ..extras.skip import pop, skippable, stash
from ..ops.layers import (Dropout, Linear, LayerNorm, Module, PreLNBlock,
                          Sequential, spec)
from .common import PipelinedTransformer, per_row_ce

__all__ = ["GPT2Config", "build_sequential", "PipelinedGPT2"]


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    """GPT-2 small by default (124M: 12 layers, d=768, 12 heads)."""

    vocab: int = 50257
    d_model: int = 768
    nhead: int = 12
    d_ff: int = 3072               # 4 * d_model
    n_layers: int = 12
    dropout: float = 0.1
    seq_len: int = 1024
    compute_dtype: Any = jnp.float32

    def tiny(self) -> "GPT2Config":
        return dataclasses.replace(
            self, vocab=101, d_model=16, nhead=2, d_ff=64, n_layers=4,
            seq_len=16, dropout=0.0)


class GPT2Embed(Module):
    """Learned token + position embeddings with embedding dropout."""

    def __init__(self, cfg: GPT2Config):
        self.cfg = cfg
        self.drop = Dropout(cfg.dropout)
        self.name = "gpt2_embed"

    def init(self, key, tokens):
        cfg = self.cfg
        kw, kp = jax.random.split(key)
        return {
            "wte": 0.02 * jax.random.normal(
                kw, (cfg.vocab, cfg.d_model), jnp.float32),
            "wpe": 0.01 * jax.random.normal(
                kp, (cfg.seq_len, cfg.d_model), jnp.float32),
        }

    def apply(self, params, tokens, ctx: StageCtx = StageCtx()):
        s = tokens.shape[-1]
        h = jnp.take(params["wte"], tokens, axis=0) + params["wpe"][:s]
        return self.drop.apply({}, h, ctx=ctx).astype(self.cfg.compute_dtype)


class GPT2Head(Module):
    """Final LayerNorm + (untied) vocab projection."""

    def __init__(self, cfg: GPT2Config):
        self.cfg = cfg
        self.ln = LayerNorm()
        self.proj = Linear(cfg.vocab, use_bias=False)
        self.name = "gpt2_head"

    def init(self, key, h):
        kl, kp = jax.random.split(key)
        h = spec(h)
        return {"ln_f": self.ln.init(kl, h), "proj": self.proj.init(kp, h)}

    def apply(self, params, h, ctx: StageCtx = StageCtx()):
        h = self.ln.apply(params["ln_f"], h.astype(jnp.float32), ctx=ctx)
        return self.proj.apply(params["proj"], h, ctx=ctx)


@skippable(stash=["gpt2_embed"])
class _StashEmbed(Module):
    def init(self, key, h):
        return {}

    def apply(self, params, h, ctx: StageCtx = StageCtx()):
        stash("gpt2_embed", h)
        return h


@skippable(pop=["gpt2_embed"])
class _JoinEmbed(Module):
    """Embedding shortcut: re-inject the stage-0 embedding right before the
    head (a cross-stage residual riding the skip subsystem's ring lanes on
    the compiled path)."""

    def init(self, key, h):
        return {}

    def apply(self, params, h, ctx: StageCtx = StageCtx()):
        return h + pop("gpt2_embed").astype(h.dtype)


def build_sequential(cfg: GPT2Config, embed_skip: bool = False) -> Sequential:
    layers: List[Module] = [GPT2Embed(cfg)]
    if embed_skip:
        layers.append(_StashEmbed())
    for _ in range(cfg.n_layers):
        layers.append(PreLNBlock(cfg.d_model, cfg.nhead, cfg.d_ff,
                                 cfg.dropout, causal=True,
                                 activation="gelu_tanh"))
    if embed_skip:
        layers.append(_JoinEmbed())
    layers.append(GPT2Head(cfg))
    return Sequential(layers, name="gpt2")


class PipelinedGPT2(PipelinedTransformer):
    """Homogeneous factorization: embed | k pre-LN blocks per stage | head."""

    def __init__(self, cfg: GPT2Config, n_stages: int):
        self.embed = GPT2Embed(cfg)
        self.block = PreLNBlock(cfg.d_model, cfg.nhead, cfg.d_ff,
                                cfg.dropout, causal=True,
                                activation="gelu_tanh")
        self.head = GPT2Head(cfg)
        super().__init__(cfg, n_stages)

    def loss_post_fn(self, post_params, h, x_mb, ctx: StageCtx):
        """Per-row mean token CE [mb_rows] — in-pipeline loss contract."""
        logits = self.head.apply(post_params["head"], h, ctx=ctx)
        return per_row_ce(logits, x_mb["targets"])

    def embed_at(self, pre_params, tokens, pos):
        """Embed tokens occupying positions ``[pos, pos+q)`` — for
        incremental decoding (inference: no dropout)."""
        p = pre_params["embed"]
        h = jnp.take(p["wte"], tokens, axis=0)
        pe = jax.lax.dynamic_slice_in_dim(p["wpe"], pos,
                                          tokens.shape[-1], axis=0)
        return (h + pe).astype(self.cfg.compute_dtype)

    def max_position(self) -> int:
        """Positional capacity (wpe rows) — inference guard contract."""
        return self.cfg.seq_len
