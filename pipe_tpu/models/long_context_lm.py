"""Long-context Transformer LM: pipeline stages × ring-attention context shards.

The composition the task calls first-class and the reference lacks entirely
(SURVEY §5 "Long-context / sequence parallelism": absent, seq len is a plain
dim): the sequence axis is sharded over a ``context`` mesh axis *inside*
every pipeline stage, so one model trains with

* **PP** over ``stage`` (the ppermute activation ring between stages), and
* **CP** over ``context`` (the ppermute K/V ring *within* each stage's
  attention, ``ops.ring_attention``) —

two nested ICI rings in one compiled program. Peak per-chip sequence memory
drops by the context factor while the math stays exactly softmax attention
(ring parity tests), so sequences far beyond one chip's HBM train without
approximation.

Usage mirrors :class:`~pipe_tpu.models.transformer_lm.PipelinedLM`, with a
``(stage, data, context)`` mesh (``make_mesh(n_stages, n_data,
n_context=...)``) and ``SpmdPipeline(context_axis="context")`` so input
token/target leaves arrive sequence-sharded.
"""

from __future__ import annotations

from typing import Any, List

import jax
import jax.numpy as jnp

from ..core.partition import StageCtx
from ..ops.ring_attention import ring_attention
from ..parallel.mesh import CONTEXT_AXIS
from .transformer_lm import LMConfig

__all__ = ["ContextParallelLM"]


def _axis_index_or_zero(axis: str):
    """axis_index, or 0 when no mesh axis is bound.

    SpmdPipeline computes output *specs* by eval_shape outside shard_map;
    only shapes matter there, so the shard offset can be anything.
    """
    try:
        return jax.lax.axis_index(axis)
    except NameError:
        return jnp.int32(0)


def _pmean_or_identity(x, axis: str):
    try:
        return jax.lax.pmean(x, axis)
    except NameError:
        return x


class ContextParallelLM:
    """Embed | k context-parallel blocks per stage | loss, all context-sharded.

    Functions run under ``shard_map`` with ``stage``/``data``/``context``
    axes bound. Activations are ``[rows, seq_local, d_model]``; attention is
    exact over the *global* sequence via ``sp_impl``: the K/V ppermute ring
    (``'ring'``, block-sized peak memory) or Ulysses all-to-all resharding
    (``'ulysses'``, unsharded per-device attention — flash-kernel
    compatible; needs ``nhead % n_context == 0``). The loss pmean's over
    context so every shard returns the identical per-row value.
    """

    def __init__(self, cfg: LMConfig, n_stages: int, sp_impl: str = "ring"):
        if cfg.n_layers % n_stages:
            raise ValueError(f"n_layers={cfg.n_layers} must divide into "
                             f"n_stages={n_stages}")
        if sp_impl not in ("ring", "ulysses"):
            raise ValueError(f"sp_impl must be ring|ulysses, got {sp_impl!r}")
        self.cfg = cfg
        self.n_stages = n_stages
        self.sp_impl = sp_impl
        self.layers_per_stage = cfg.n_layers // n_stages
        # Build sublayers (and especially PositionalEncoding's constant
        # table) EAGERLY: creating them lazily inside a traced function
        # would turn the table into a jit tracer that cannot cross into
        # shard_map bodies.
        from ..ops import layers as L
        self._layers_cache = dict(
            embed=L.Embedding(cfg.vocab, cfg.d_model, scale=True),
            posenc=L.PositionalEncoding(cfg.d_model, 0.0,
                                        max_len=max(5000, cfg.seq_len)),
            ff1=L.Linear(cfg.d_ff), ff2=L.Linear(cfg.d_model),
            ln=L.LayerNorm(),
        )

    # --- params (reuse the standard LM's structure) ---

    def init(self, key: jax.Array):
        from .transformer_lm import PipelinedLM
        return PipelinedLM(self.cfg, self.n_stages).init(key)

    # --- pieces (layer math reused from ops.layers; only the attention and
    # the position offset are context-parallel-specific) ---

    @property
    def _layers(self):
        """Shared sublayer instances (built eagerly in __init__)."""
        return self._layers_cache

    def max_position(self) -> int:
        """Positional capacity (sinusoid table rows) — inference guard.

        Without this, ``check_positions`` is inert and prompts/decodes past
        the table silently clamp inside ``_posenc``'s dynamic_slice — the
        exact silent-reuse failure the guard exists to prevent.
        """
        return int(self._layers["posenc"].pe.shape[0])

    def _posenc(self, h, seq_offset):
        """PositionalEncoding's precomputed table, sliced at the shard offset."""
        pe = self._layers["posenc"].pe  # [max_len, d]
        s_local = h.shape[-2]
        sl = jax.lax.dynamic_slice_in_dim(
            pe, jnp.asarray(seq_offset, jnp.int32), s_local, axis=0)
        return h + sl.astype(h.dtype)

    def pre_fn(self, pre_params, x_mb, ctx: StageCtx):
        tokens = x_mb["tokens"] if isinstance(x_mb, dict) else x_mb
        h = self._layers["embed"].apply(pre_params["embed"], tokens, ctx=ctx)
        # global positions: offset by this context shard's start
        offset = _axis_index_or_zero(CONTEXT_AXIS) * tokens.shape[-1]
        h = self._posenc(h, offset)
        return h.astype(self.cfg.compute_dtype)

    def _block(self, bp, h, ctx: StageCtx):
        """ops.layers.TransformerEncoderLayer math with the attention swapped
        for the context ring (dropout omitted on this path — rate-0 configs —
        to keep the ring exact)."""
        cfg = self.cfg
        L = self._layers
        rows, s_local, d = h.shape
        hd = d // cfg.nhead

        def proj(w, b):
            return (jnp.einsum("bsd,de->bse", h, w) + b).reshape(
                rows, s_local, cfg.nhead, hd)

        q = proj(bp["attn"]["wq"], bp["attn"]["bq"])
        k = proj(bp["attn"]["wk"], bp["attn"]["bk"])
        v = proj(bp["attn"]["wv"], bp["attn"]["bv"])
        if self.sp_impl == "ulysses":
            from ..ops.ulysses_attention import ulysses_attention
            a = ulysses_attention(q, k, v, CONTEXT_AXIS, causal=cfg.causal)
        else:
            a = ring_attention(q, k, v, CONTEXT_AXIS, causal=cfg.causal)
        a = a.reshape(rows, s_local, d)
        a = jnp.einsum("bsd,de->bse", a, bp["attn"]["wo"]) + bp["attn"]["bo"]

        x = L["ln"].apply(bp["ln1"], h + a)
        f = jax.nn.relu(L["ff1"].apply(bp["ff1"], x))
        f = L["ff2"].apply(bp["ff2"], f)
        return L["ln"].apply(bp["ln2"], x + f)

    def stage_fn(self, blocks, h, ctx: StageCtx):
        cd = self.cfg.compute_dtype
        for l, bp in enumerate(blocks):
            bp = jax.tree_util.tree_map(lambda p: p.astype(cd), bp)
            h = self._block(bp, h, ctx.fold(l))
        return h

    def loss_post_fn(self, post_params, h, x_mb, ctx: StageCtx):
        """Per-row mean token CE over the GLOBAL sequence (pmean'd)."""
        w = post_params["decoder"]["w"]
        b = post_params["decoder"]["b"]
        logits = (jnp.einsum("bsd,dv->bsv", h.astype(jnp.float32), w)
                  + b).astype(jnp.float32)
        targets = x_mb["targets"]
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        local_mean = jnp.mean(logz - gold, axis=-1)          # [rows]
        return _pmean_or_identity(local_mean, CONTEXT_AXIS)  # global mean

    def num_params(self, params_tuple) -> int:
        return sum(int(p.size) for p in jax.tree_util.tree_leaves(params_tuple))
