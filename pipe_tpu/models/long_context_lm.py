"""Long-context Transformer LM: pipeline stages × ring-attention context shards.

The composition the task calls first-class and the reference lacks entirely
(SURVEY §5 "Long-context / sequence parallelism": absent, seq len is a plain
dim): the sequence axis is sharded over a ``context`` mesh axis *inside*
every pipeline stage, so one model trains with

* **PP** over ``stage`` (the ppermute activation ring between stages), and
* **CP** over ``context`` (the ppermute K/V ring *within* each stage's
  attention, ``ops.ring_attention``) —

two nested ICI rings in one compiled program. Peak per-chip sequence memory
drops by the context factor while the math stays exactly softmax attention
(ring parity tests), so sequences far beyond one chip's HBM train without
approximation.

Usage mirrors :class:`~pipe_tpu.models.transformer_lm.PipelinedLM`, with a
``(stage, data, context)`` mesh (``make_mesh(n_stages, n_data,
n_context=...)``) and ``SpmdPipeline(context_axis="context")`` so input
token/target leaves arrive sequence-sharded.
"""

from __future__ import annotations

from typing import Any, List

import jax
import jax.numpy as jnp

from ..core.partition import StageCtx
from ..ops.ring_attention import ring_attention
from ..parallel.mesh import CONTEXT_AXIS
from .transformer_lm import LMConfig

__all__ = ["ContextParallelLM"]


def _axis_index_or_zero(axis: str):
    """axis_index, or 0 when no mesh axis is bound.

    SpmdPipeline computes output *specs* by eval_shape outside shard_map;
    only shapes matter there, so the shard offset can be anything.
    """
    try:
        return jax.lax.axis_index(axis)
    except NameError:
        return jnp.int32(0)


def _pmean_or_identity(x, axis: str):
    try:
        return jax.lax.pmean(x, axis)
    except NameError:
        return x


class ContextParallelLM:
    """Embed | k ring-attention blocks per stage | loss, all context-sharded.

    Functions run under ``shard_map`` with ``stage``/``data``/``context``
    axes bound. Activations are ``[rows, seq_local, d_model]``; attention is
    exact over the *global* sequence via the context ring; the loss pmean's
    over context so every shard returns the identical per-row value.
    """

    def __init__(self, cfg: LMConfig, n_stages: int):
        if cfg.n_layers % n_stages:
            raise ValueError(f"n_layers={cfg.n_layers} must divide into "
                             f"n_stages={n_stages}")
        self.cfg = cfg
        self.n_stages = n_stages
        self.layers_per_stage = cfg.n_layers // n_stages

    # --- params (reuse the standard LM's structure) ---

    def init(self, key: jax.Array):
        from .transformer_lm import PipelinedLM
        return PipelinedLM(self.cfg, self.n_stages).init(key)

    # --- pieces ---

    def _posenc(self, h, seq_offset):
        d = self.cfg.d_model
        pos = (seq_offset
               + jnp.arange(h.shape[-2], dtype=jnp.float32))[:, None]
        div = jnp.exp(jnp.arange(0, d, 2, dtype=jnp.float32)
                      * (-jnp.log(10000.0) / d))
        angles = pos * div[None, :]
        pe = jnp.zeros((h.shape[-2], d), jnp.float32)
        pe = pe.at[:, 0::2].set(jnp.sin(angles))
        pe = pe.at[:, 1::2].set(jnp.cos(angles))
        return h + pe.astype(h.dtype)

    def pre_fn(self, pre_params, x_mb, ctx: StageCtx):
        tokens = x_mb["tokens"] if isinstance(x_mb, dict) else x_mb
        table = pre_params["embed"]["table"]
        h = jnp.take(table, tokens, axis=0)
        h = h * jnp.asarray(jnp.sqrt(jnp.float32(self.cfg.d_model)), h.dtype)
        # global positions: offset by this context shard's start
        seq_local = tokens.shape[-1]
        offset = _axis_index_or_zero(CONTEXT_AXIS) * seq_local
        h = self._posenc(h, offset.astype(jnp.float32))
        return h.astype(self.cfg.compute_dtype)

    def _block(self, bp, h, ctx: StageCtx):
        """One transformer block with ring attention over the context axis.

        Same math as ``ops.layers.TransformerEncoderLayer`` (post-LN, ReLU
        FFN) with the attention swapped for the context ring; dropout is
        omitted on this long-context path (rate 0 configs) to keep the ring
        exact.
        """
        cfg = self.cfg
        rows, s_local, d = h.shape
        hd = d // cfg.nhead

        def proj(w, b):
            return (jnp.einsum("bsd,de->bse", h, w) + b).reshape(
                rows, s_local, cfg.nhead, hd)

        a = ring_attention(
            proj(bp["attn"]["wq"], bp["attn"]["bq"]),
            proj(bp["attn"]["wk"], bp["attn"]["bk"]),
            proj(bp["attn"]["wv"], bp["attn"]["bv"]),
            CONTEXT_AXIS, causal=cfg.causal)
        a = a.reshape(rows, s_local, d)
        a = jnp.einsum("bsd,de->bse", a, bp["attn"]["wo"]) + bp["attn"]["bo"]

        def ln(p, x):
            mu = jnp.mean(x, axis=-1, keepdims=True)
            var = jnp.var(x, axis=-1, keepdims=True)
            return (x - mu) * jax.lax.rsqrt(var + 1e-5) * p["g"] + p["b"]

        x = ln(bp["ln1"], h + a)
        f = jax.nn.relu(jnp.einsum("bsd,do->bso", x, bp["ff1"]["w"])
                        + bp["ff1"]["b"])
        f = jnp.einsum("bso,od->bsd", f, bp["ff2"]["w"]) + bp["ff2"]["b"]
        return ln(bp["ln2"], x + f)

    def stage_fn(self, blocks, h, ctx: StageCtx):
        cd = self.cfg.compute_dtype
        for l, bp in enumerate(blocks):
            bp = jax.tree_util.tree_map(lambda p: p.astype(cd), bp)
            h = self._block(bp, h, ctx.fold(l))
        return h

    def loss_post_fn(self, post_params, h, x_mb, ctx: StageCtx):
        """Per-row mean token CE over the GLOBAL sequence (pmean'd)."""
        w = post_params["decoder"]["w"]
        b = post_params["decoder"]["b"]
        logits = (jnp.einsum("bsd,dv->bsv", h.astype(jnp.float32), w)
                  + b).astype(jnp.float32)
        targets = x_mb["targets"]
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        local_mean = jnp.mean(logz - gold, axis=-1)          # [rows]
        return _pmean_or_identity(local_mean, CONTEXT_AXIS)  # global mean

    def num_params(self, params_tuple) -> int:
        return sum(int(p.size) for p in jax.tree_util.tree_leaves(params_tuple))
