"""Tensor-parallel Transformer LM: the PP x TP x DP factorization.

Beyond the reference (no TP there — SURVEY §2 strategy table): the same
embed | k blocks per stage | decode factorization as
:class:`~pipe_tpu.models.transformer_lm.PipelinedLM`, but the block is the
Megatron-split :mod:`~pipe_tpu.ops.tp_layers` block whose head and FFN dims
shard over a ``model`` mesh axis. ``stage_param_specs()`` hands the
executors the per-leaf ``PartitionSpec``s (stage axis prepended by the
executor), so each device holds ``1/(n_stages * tp)`` of the block weights
— pipeline memory scaling times tensor memory scaling.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.partition import StageCtx
from ..ops.tp_layers import (tp_block_apply, tp_block_init, tp_block_specs,
                             tp_block_tapped, tp_block_wgrad, tp_block_zs)
from ..parallel.mesh import MODEL_AXIS
from .transformer_lm import LMConfig, PipelinedLM

__all__ = ["TPPipelinedLM", "tp_split_backward_stage"]


def tp_split_backward_stage(cfg: LMConfig):
    """A :class:`~pipe_tpu.parallel.scheduled.SplitBackwardStage` for a
    stage of TP-block layers (``tp_axis=None`` math — the structural-split
    executor owns the parallelism axes): per-layer tapped forwards chain,
    zs/taps are per-layer lists, and the W op is the per-layer weight-grad
    contractions cast back to the parameter dtype. Key folding matches
    ``PipelinedTransformer.stage_fn`` (``ctx.fold(l)`` per layer), so
    dropout is bit-identical to the plain executor path."""
    from ..parallel.scheduled import SplitBackwardStage

    cd = cfg.compute_dtype

    def cast(bp):
        return jax.tree_util.tree_map(lambda p: p.astype(cd), bp)

    def tapped_fn(params_g, h, ctx, zs):
        taps = []
        for l, (bp, z) in enumerate(zip(params_g, zs)):
            h, t = tp_block_tapped(cast(bp), h, ctx.fold(l), z,
                                   dropout=cfg.dropout, causal=cfg.causal)
            taps.append(t)
        return h, taps

    def zs_fn(params_g, h):
        # activation shape is ring-invariant, so one zs set per layer
        return [tp_block_zs(h, bp) for bp in params_g]

    def wgrad_fn(taps, gzs):
        return [jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float32), tp_block_wgrad(t, gz))
            for t, gz in zip(taps, gzs)]

    return SplitBackwardStage(tapped_fn=tapped_fn, wgrad_fn=wgrad_fn,
                              zs_fn=zs_fn)


class _TPCacheShim:
    """make_cache provider (the ``block.attn`` surface the generators
    expect); ``nhead`` here is the FULL head count — the TP generator
    overrides cache creation with the local shard count."""

    def __init__(self, cfg: LMConfig):
        self.nhead = cfg.nhead
        self.head_dim = cfg.d_model // cfg.nhead

    def make_cache(self, batch: int, max_len: int, dtype=jnp.float32):
        shape = (batch, max_len, self.nhead, self.head_dim)
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


class _TPBlock:
    """Module shim over the functional TP block (init/apply contract)."""

    def __init__(self, cfg: LMConfig, tp_axis):
        self.cfg = cfg
        self.tp_axis = tp_axis
        self.attn = _TPCacheShim(cfg)

    def init(self, key, h_spec):
        del h_spec
        cfg = self.cfg
        return tp_block_init(key, cfg.d_model, cfg.nhead, cfg.d_ff)

    def apply(self, p, h, ctx: StageCtx = StageCtx()):
        return tp_block_apply(p, h, ctx, dropout=self.cfg.dropout,
                              causal=self.cfg.causal, tp_axis=self.tp_axis)

    def decode(self, p, h, cache, pos):
        """Incremental apply with a KV cache (inference; heads local)."""
        from ..ops.tp_layers import tp_block_decode
        if not self.cfg.causal:
            raise ValueError("KV-cache decode requires causal attention")
        return tp_block_decode(p, h, cache, pos, tp_axis=self.tp_axis)


class TPPipelinedLM(PipelinedLM):
    """embed | k TP blocks per stage | decode, over (stage, data, model).

    Identical factorization, embed/posenc/loss path, and key schedule to
    :class:`PipelinedLM` — only the block differs (the Megatron-split
    :mod:`~pipe_tpu.ops.tp_layers` block). ``tp_axis=None`` runs the same
    math unsharded (the transparency yardstick); embed/decoder stay
    replicated over the model axis (their vocab-scale matmuls amortize
    over the whole pipeline once, and the reference keeps them on edge
    stages anyway).
    """

    def __init__(self, cfg: LMConfig, n_stages: int, tp_axis=MODEL_AXIS):
        super().__init__(cfg, n_stages)
        self.block = _TPBlock(cfg, tp_axis)

    def stage_param_specs(self):
        """Specs for ONE stage's params (list of per-layer block trees);
        executors prepend the stage axis for the stacked layout."""
        return [tp_block_specs() for _ in range(self.layers_per_stage)]
