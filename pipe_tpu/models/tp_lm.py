"""Tensor-parallel Transformer LM: the PP x TP x DP factorization.

Beyond the reference (no TP there — SURVEY §2 strategy table): the same
embed | k blocks per stage | decode factorization as
:class:`~pipe_tpu.models.transformer_lm.PipelinedLM`, but the block is the
Megatron-split :mod:`~pipe_tpu.ops.tp_layers` block whose head and FFN dims
shard over a ``model`` mesh axis. ``stage_param_specs()`` hands the
executors the per-leaf ``PartitionSpec``s (stage axis prepended by the
executor), so each device holds ``1/(n_stages * tp)`` of the block weights
— pipeline memory scaling times tensor memory scaling.
"""

from __future__ import annotations

from ..core.partition import StageCtx
from ..ops.tp_layers import tp_block_apply, tp_block_init, tp_block_specs
from ..parallel.mesh import MODEL_AXIS
from .transformer_lm import LMConfig, PipelinedLM

__all__ = ["TPPipelinedLM"]


class _TPBlock:
    """Module shim over the functional TP block (init/apply contract)."""

    def __init__(self, cfg: LMConfig, tp_axis):
        self.cfg = cfg
        self.tp_axis = tp_axis

    def init(self, key, h_spec):
        del h_spec
        cfg = self.cfg
        return tp_block_init(key, cfg.d_model, cfg.nhead, cfg.d_ff)

    def apply(self, p, h, ctx: StageCtx = StageCtx()):
        return tp_block_apply(p, h, ctx, dropout=self.cfg.dropout,
                              causal=self.cfg.causal, tp_axis=self.tp_axis)


class TPPipelinedLM(PipelinedLM):
    """embed | k TP blocks per stage | decode, over (stage, data, model).

    Identical factorization, embed/posenc/loss path, and key schedule to
    :class:`PipelinedLM` — only the block differs (the Megatron-split
    :mod:`~pipe_tpu.ops.tp_layers` block). ``tp_axis=None`` runs the same
    math unsharded (the transparency yardstick); embed/decoder stay
    replicated over the model axis (their vocab-scale matmuls amortize
    over the whole pipeline once, and the reference keeps them on edge
    stages anyway).
    """

    def __init__(self, cfg: LMConfig, n_stages: int, tp_axis=MODEL_AXIS):
        super().__init__(cfg, n_stages)
        self.block = _TPBlock(cfg, tp_axis)

    def stage_param_specs(self):
        """Specs for ONE stage's params (list of per-layer block trees);
        executors prepend the stage axis for the stacked layout."""
        return [tp_block_specs() for _ in range(self.layers_per_stage)]
