"""BERT model family, pipelined (BASELINE.json config #4: 8-stage
BERT-large pretraining, chunks=32, interleaved schedule).

Architecture: word + learned position embeddings -> LayerNorm -> dropout,
post-LN bidirectional encoder blocks with GELU (the BERT lineage is post-LN,
so the tutorial's :class:`~pipe_tpu.ops.layers.TransformerEncoderLayer` is
the stage body with ``causal=False``), and an MLM head (dense + GELU + LN +
vocab projection). Pretraining here is masked-LM only; the NSP head and
segment-pair plumbing are out of scope (modern BERT-lineage pretraining
drops NSP anyway), documented divergence.

The in-pipeline loss contract: ``x_mb = {"tokens": masked input ids,
"targets": original ids, "mlm_weights": [rows, seq] 1.0 at masked
positions}`` — per-row masked mean CE so only the ~15% masked positions
contribute. :func:`mask_tokens` implements the 80/10/10 corruption.

``PipelinedBERT(cfg, n_virtual)`` factors the 24 layers into any divisor —
8 devices x interleave 3 covers the BASELINE 8-stage interleaved config via
``InterleavedSpmdPipeline(v=3)``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Tuple

import jax
import jax.numpy as jnp

from ..core.partition import StageCtx
from ..ops.layers import (Dropout, LayerNorm, Linear, Module,
                          Sequential, TransformerEncoderLayer, spec)
from .common import PipelinedTransformer, per_row_ce

__all__ = ["BertConfig", "mask_tokens", "build_sequential", "PipelinedBERT"]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    """BERT-large by default (340M: 24 layers, d=1024, 16 heads)."""

    vocab: int = 30522
    d_model: int = 1024
    nhead: int = 16
    d_ff: int = 4096
    n_layers: int = 24
    dropout: float = 0.1
    seq_len: int = 512
    mask_token_id: int = 103       # [MASK] in the WordPiece vocab
    compute_dtype: Any = jnp.float32

    def tiny(self) -> "BertConfig":
        return dataclasses.replace(
            self, vocab=101, d_model=16, nhead=2, d_ff=64, n_layers=4,
            seq_len=16, dropout=0.0, mask_token_id=1)


def mask_tokens(key: jax.Array, tokens: jax.Array, cfg: BertConfig,
                mask_rate: float = 0.15) -> Tuple[jax.Array, jax.Array]:
    """BERT 80/10/10 corruption: returns ``(masked_tokens, mlm_weights)``.

    Of the ``mask_rate`` selected positions, 80% become ``[MASK]``, 10% a
    random id, 10% stay unchanged; ``mlm_weights`` is 1.0 exactly at the
    selected positions (the loss targets).
    """
    ks, km, kr = jax.random.split(key, 3)
    selected = jax.random.bernoulli(ks, mask_rate, tokens.shape)
    roll = jax.random.uniform(km, tokens.shape)
    random_ids = jax.random.randint(kr, tokens.shape, 0, cfg.vocab,
                                    tokens.dtype)
    corrupted = jnp.where(
        roll < 0.8, jnp.asarray(cfg.mask_token_id, tokens.dtype),
        jnp.where(roll < 0.9, random_ids, tokens))
    masked = jnp.where(selected, corrupted, tokens)
    return masked, selected.astype(jnp.float32)


class BertEmbed(Module):
    """Word + learned position embeddings, LayerNorm, dropout."""

    def __init__(self, cfg: BertConfig):
        self.cfg = cfg
        self.ln = LayerNorm()
        self.drop = Dropout(cfg.dropout)
        self.name = "bert_embed"

    def init(self, key, tokens):
        cfg = self.cfg
        kw, kp, kl = jax.random.split(key, 3)
        h = jax.ShapeDtypeStruct(jnp.shape(tokens) + (cfg.d_model,),
                                 jnp.float32)
        return {
            "word": 0.02 * jax.random.normal(
                kw, (cfg.vocab, cfg.d_model), jnp.float32),
            "pos": 0.02 * jax.random.normal(
                kp, (cfg.seq_len, cfg.d_model), jnp.float32),
            "ln": self.ln.init(kl, h),
        }

    def apply(self, params, tokens, ctx: StageCtx = StageCtx()):
        s = tokens.shape[-1]
        h = jnp.take(params["word"], tokens, axis=0) + params["pos"][:s]
        h = self.ln.apply(params["ln"], h, ctx=ctx)
        return self.drop.apply({}, h, ctx=ctx).astype(self.cfg.compute_dtype)


class MLMHead(Module):
    """Transform (dense + GELU + LN) then vocab projection."""

    def __init__(self, cfg: BertConfig):
        self.cfg = cfg
        self.dense = Linear(cfg.d_model)
        self.ln = LayerNorm()
        self.proj = Linear(cfg.vocab)
        self.name = "mlm_head"

    def init(self, key, h):
        kd, kl, kp = jax.random.split(key, 3)
        h = spec(h)
        return {"dense": self.dense.init(kd, h), "ln": self.ln.init(kl, h),
                "proj": self.proj.init(kp, h)}

    def apply(self, params, h, ctx: StageCtx = StageCtx()):
        # exact-erf gelu, consistent with the encoder blocks and HF BERT's
        # BertPredictionHeadTransform
        h = jax.nn.gelu(self.dense.apply(params["dense"],
                                         h.astype(jnp.float32), ctx=ctx),
                        approximate=False)
        h = self.ln.apply(params["ln"], h, ctx=ctx)
        return self.proj.apply(params["proj"], h, ctx=ctx)


def build_sequential(cfg: BertConfig) -> Sequential:
    layers: List[Module] = [BertEmbed(cfg)]
    for _ in range(cfg.n_layers):
        layers.append(TransformerEncoderLayer(
            cfg.d_model, cfg.nhead, cfg.d_ff, cfg.dropout, causal=False,
            activation="gelu"))
    layers.append(MLMHead(cfg))
    return Sequential(layers, name="bert")


class PipelinedBERT(PipelinedTransformer):
    """Homogeneous factorization over ``n_virtual`` stage bodies.

    Pass ``n_virtual = n_devices * v`` and stack with
    ``stack_interleaved_params(sp, n_devices)`` for the interleaved
    executor, or ``n_virtual = n_stages`` + ``stack_stage_params`` for the
    plain ones.
    """

    def __init__(self, cfg: BertConfig, n_virtual: int):
        self.embed = BertEmbed(cfg)
        self.block = TransformerEncoderLayer(
            cfg.d_model, cfg.nhead, cfg.d_ff, cfg.dropout, causal=False,
            activation="gelu")
        self.head = MLMHead(cfg)
        super().__init__(cfg, n_virtual)
        self.n_virtual = n_virtual

    def loss_post_fn(self, post_params, h, x_mb, ctx: StageCtx):
        """Per-row masked-mean MLM CE [mb_rows]."""
        logits = self.head.apply(post_params["head"], h, ctx=ctx)
        return per_row_ce(logits, x_mb["targets"],
                          weights=x_mb["mlm_weights"])
