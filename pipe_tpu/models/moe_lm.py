"""Mixture-of-Experts Transformer LM: the PP x DP x EP factorization.

Same embed | k blocks per stage | decode scaffolding as
:class:`~pipe_tpu.models.transformer_lm.PipelinedLM`; the block is the
hybrid :func:`~pipe_tpu.ops.moe.moe_block_apply` (TP attention + MoE FFN,
experts and heads sharded over the ``model`` mesh axis).

Aux-loss note: the load-balance auxiliary is available at the layer level
(``moe_ffn_apply`` returns it); the homogeneous stage contract is
``h -> h``, so the pipelined model trains the router through the GATING
path only (top-k gate values multiply expert outputs, so router gradients
flow regardless) and drops the aux regularizer — threading a scalar
accumulator channel through the table executor is the documented follow-up
(the hetero path's deferred-BN lanes show the mechanism).
"""

from __future__ import annotations

import dataclasses

from ..core.partition import StageCtx
from ..ops.moe import moe_block_apply, moe_block_init, moe_block_specs
from ..parallel.mesh import MODEL_AXIS
from .transformer_lm import LMConfig, PipelinedLM

__all__ = ["MoELMConfig", "MoEPipelinedLM"]


@dataclasses.dataclass(frozen=True)
class MoELMConfig(LMConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25


class _MoEBlock:
    def __init__(self, cfg: MoELMConfig, ep_axis):
        self.cfg = cfg
        self.ep_axis = ep_axis
        # the generators' sharding/cache contract names (heads shard over
        # the same axis as the experts; full-head cache shim for the
        # unsharded path)
        self.tp_axis = ep_axis
        from .tp_lm import _TPCacheShim
        self.attn = _TPCacheShim(cfg)

    def init(self, key, h_spec):
        del h_spec
        cfg = self.cfg
        return moe_block_init(key, cfg.d_model, cfg.nhead, cfg.d_ff,
                              cfg.n_experts)

    def apply(self, p, h, ctx: StageCtx = StageCtx()):
        cfg = self.cfg
        out, _aux = moe_block_apply(
            p, h, ctx, n_experts=cfg.n_experts, k=cfg.top_k,
            capacity_factor=cfg.capacity_factor, dropout=cfg.dropout,
            causal=cfg.causal, ep_axis=self.ep_axis)
        return out

    def decode(self, p, h, cache, pos):
        """Incremental apply with a KV cache (inference; aux discarded)."""
        from ..ops.moe import moe_block_decode
        cfg = self.cfg
        if not cfg.causal:
            raise ValueError("KV-cache decode requires causal attention")
        return moe_block_decode(
            p, h, cache, pos, n_experts=cfg.n_experts, k=cfg.top_k,
            capacity_factor=cfg.capacity_factor, ep_axis=self.ep_axis)


class MoEPipelinedLM(PipelinedLM):
    """embed | k MoE blocks per stage | decode over (stage, data, model)."""

    def __init__(self, cfg: MoELMConfig, n_stages: int,
                 ep_axis=MODEL_AXIS):
        super().__init__(cfg, n_stages)
        self.block = _MoEBlock(cfg, ep_axis)

    def stage_param_specs(self):
        return [moe_block_specs() for _ in range(self.layers_per_stage)]
