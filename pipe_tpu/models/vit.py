"""Vision Transformer family, pipelined (BASELINE.json config #5: 8-stage
ViT-L/16 ImageNet, chunks=8, non-LM tensor shapes, uneven stage balance).

Architecture: patchify (``[b, H, W, C] -> [b, (H/p)(W/p), p*p*C]`` reshape +
linear projection — the convolution-free, MXU-friendly form of the patch
embedding), class token + learned positions, pre-LN GELU blocks
(:class:`~pipe_tpu.ops.layers.PreLNBlock`, ``causal=False``), final LN and a
classification head over the class token.

Non-LM properties this family exercises end-to-end:

* 4-D image inputs micro-batched through scatter/stack_scatter;
* an odd token count (197 = 196 patches + cls for /16 at 224) that the
  flash-attention tiling cannot cover — the XLA attention path is selected
  statically (``supports()`` gate);
* integer class labels with a scalar-per-row loss (no seq dimension);
* uneven balance through ``Pipe(mesh=...)`` (embed and head stages cost
  nothing like the block stages).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List

import jax
import jax.numpy as jnp

from ..core.partition import StageCtx
from ..ops.layers import (Dropout, LayerNorm, Linear, Module, PreLNBlock,
                          Sequential, spec)
from .common import PipelinedTransformer, per_row_ce

__all__ = ["ViTConfig", "build_sequential", "PipelinedViT"]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    """ViT-L/16 by default (304M: 24 layers, d=1024, 16 heads, patch 16)."""

    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    n_classes: int = 1000
    d_model: int = 1024
    nhead: int = 16
    d_ff: int = 4096
    n_layers: int = 24
    dropout: float = 0.1
    compute_dtype: Any = jnp.float32

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def n_tokens(self) -> int:
        return self.n_patches + 1  # + class token

    def tiny(self) -> "ViTConfig":
        return dataclasses.replace(
            self, image_size=16, patch_size=4, n_classes=11, d_model=16,
            nhead=2, d_ff=64, n_layers=4, dropout=0.0)


class PatchEmbed(Module):
    """Patchify + project + class token + learned positions + dropout."""

    def __init__(self, cfg: ViTConfig):
        if cfg.image_size % cfg.patch_size:
            raise ValueError(
                f"image {cfg.image_size} not divisible by patch "
                f"{cfg.patch_size}")
        self.cfg = cfg
        self.proj = Linear(cfg.d_model)
        self.drop = Dropout(cfg.dropout)
        self.name = "patch_embed"

    def init(self, key, images):
        cfg = self.cfg
        kp, kc, ke = jax.random.split(key, 3)
        patch_dim = cfg.patch_size * cfg.patch_size * cfg.channels
        flat = jax.ShapeDtypeStruct((1, cfg.n_patches, patch_dim),
                                    jnp.float32)
        return {
            "proj": self.proj.init(kp, flat),
            "cls": 0.02 * jax.random.normal(kc, (1, 1, cfg.d_model),
                                            jnp.float32),
            "pos": 0.02 * jax.random.normal(
                ke, (cfg.n_tokens, cfg.d_model), jnp.float32),
        }

    def apply(self, params, images, ctx: StageCtx = StageCtx()):
        cfg = self.cfg
        b = images.shape[0]
        p, g = cfg.patch_size, cfg.image_size // cfg.patch_size
        # [b, H, W, C] -> [b, g*g, p*p*C]
        x = images.reshape(b, g, p, g, p, cfg.channels)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, g * g, p * p * cfg.channels)
        h = self.proj.apply(params["proj"], x.astype(jnp.float32), ctx=ctx)
        cls = jnp.broadcast_to(params["cls"], (b, 1, cfg.d_model))
        h = jnp.concatenate([cls, h], axis=1) + params["pos"]
        return self.drop.apply({}, h, ctx=ctx).astype(cfg.compute_dtype)


class ViTHead(Module):
    """Final LN + linear classifier over the class token."""

    def __init__(self, cfg: ViTConfig):
        self.cfg = cfg
        self.ln = LayerNorm()
        self.proj = Linear(cfg.n_classes)
        self.name = "vit_head"

    def init(self, key, h):
        kl, kp = jax.random.split(key)
        h = spec(h)
        cls = jax.ShapeDtypeStruct(tuple(h.shape[:-2]) + (h.shape[-1],),
                                   jnp.float32)
        return {"ln": self.ln.init(kl, h), "proj": self.proj.init(kp, cls)}

    def apply(self, params, h, ctx: StageCtx = StageCtx()):
        h = self.ln.apply(params["ln"], h.astype(jnp.float32), ctx=ctx)
        return self.proj.apply(params["proj"], h[..., 0, :], ctx=ctx)


def build_sequential(cfg: ViTConfig) -> Sequential:
    layers: List[Module] = [PatchEmbed(cfg)]
    for _ in range(cfg.n_layers):
        layers.append(PreLNBlock(cfg.d_model, cfg.nhead, cfg.d_ff,
                                 cfg.dropout, causal=False))
    layers.append(ViTHead(cfg))
    return Sequential(layers, name="vit")


class PipelinedViT(PipelinedTransformer):
    """Homogeneous factorization: patch-embed | k blocks per stage | head."""

    input_key = "images"

    def __init__(self, cfg: ViTConfig, n_stages: int):
        self.embed = PatchEmbed(cfg)
        self.block = PreLNBlock(cfg.d_model, cfg.nhead, cfg.d_ff,
                                cfg.dropout, causal=False)
        self.head = ViTHead(cfg)
        super().__init__(cfg, n_stages)

    def x_spec(self):
        cfg = self.cfg
        return jax.ShapeDtypeStruct(
            (1, cfg.image_size, cfg.image_size, cfg.channels), jnp.float32)

    def h_spec(self):
        cfg = self.cfg
        return jax.ShapeDtypeStruct((1, cfg.n_tokens, cfg.d_model),
                                    jnp.float32)

    def loss_post_fn(self, post_params, h, x_mb, ctx: StageCtx):
        """Per-row softmax CE against integer labels [mb_rows]."""
        logits = self.head.apply(post_params["head"], h, ctx=ctx)
        return per_row_ce(logits, x_mb["labels"])
