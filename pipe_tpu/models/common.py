"""Shared scaffolding for the pipelined model families.

Every zoo model factors the same way for the compiled executors — an embed
module on stage 0, a homogeneous ring-invariant block repeated
``layers_per_stage`` times per stage, a head on the last stage — and shares
one parameter-init key schedule (``fold_in(key, 0)`` = embed, ``1`` = head,
``2 + s*lps + l`` = block ``l`` of stage ``s``). :class:`PipelinedTransformer`
holds that scaffolding once; subclasses supply the modules, the input spec,
and the loss.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from ..core.partition import StageCtx

__all__ = ["per_row_ce", "PipelinedTransformer"]


def per_row_ce(logits, targets, weights=None):
    """Per-row cross-entropy from logits (f32 accumulation).

    ``logits``: ``[rows, ..., vocab]``; ``targets``: integer ``[rows, ...]``.
    Without ``weights`` returns the mean CE over every non-row axis (or the
    bare CE when targets are scalar per row); with ``weights`` (same shape
    as targets) returns the weighted mean ``sum(w*ce)/max(sum(w), 1)`` —
    BERT's masked-LM form. Always ``[rows]`` float32.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    ce = logz - gold
    reduce_axes = tuple(range(1, ce.ndim))
    if weights is not None:
        w = weights.astype(jnp.float32)
        return jnp.sum(ce * w, axis=reduce_axes) / jnp.maximum(
            jnp.sum(w, axis=reduce_axes), 1.0)
    if reduce_axes:
        return jnp.mean(ce, axis=reduce_axes)
    return ce


class PipelinedTransformer:
    """Base factorization: embed | k blocks per stage | head.

    Subclass contract: set ``cfg`` (with ``n_layers`` and
    ``compute_dtype``), ``embed``, ``block``, ``head`` modules and
    ``input_key`` (the x_mb dict key feeding the embed) before calling
    ``super().__init__(cfg, n_stages)``; override :meth:`x_spec` /
    :meth:`h_spec` when the input is not ``[1, seq_len]`` int tokens; define
    ``loss_post_fn``. ``init`` returns
    ``(stage_params, pre_params, post_params)`` ready for
    ``stack_stage_params`` (or ``stack_interleaved_params``).
    """

    input_key = "tokens"
    post_key = "head"

    def __init__(self, cfg, n_stages: int):
        if cfg.n_layers % n_stages:
            raise ValueError(
                f"n_layers={cfg.n_layers} must divide into "
                f"n_stages={n_stages} (use Pipe for uneven splits)")
        self.cfg = cfg
        self.n_stages = n_stages
        self.layers_per_stage = cfg.n_layers // n_stages

    # --- specs (override for non-token inputs) ---

    def x_spec(self) -> jax.ShapeDtypeStruct:
        return jax.ShapeDtypeStruct((1, self.cfg.seq_len), jnp.int32)

    def h_spec(self) -> jax.ShapeDtypeStruct:
        cfg = self.cfg
        return jax.ShapeDtypeStruct((1, cfg.seq_len, cfg.d_model),
                                    jnp.float32)

    # --- params ---

    def init(self, key: jax.Array):
        h = self.h_spec()
        pre_params = {"embed": self.embed.init(jax.random.fold_in(key, 0),
                                               self.x_spec())}
        post_params = {self.post_key: self.head.init(
            jax.random.fold_in(key, 1), h)}
        stage_params: List[Any] = []
        for s in range(self.n_stages):
            blocks = []
            for l in range(self.layers_per_stage):
                lkey = jax.random.fold_in(
                    key, 2 + s * self.layers_per_stage + l)
                blocks.append(self.block.init(lkey, h))
            stage_params.append(blocks)
        return stage_params, pre_params, post_params

    # --- SPMD stage functions ---

    def pre_fn(self, pre_params, x_mb, ctx: StageCtx):
        leaf = x_mb[self.input_key] if isinstance(x_mb, dict) else x_mb
        return self.embed.apply(pre_params["embed"], leaf, ctx=ctx)

    def stage_fn(self, blocks, h, ctx: StageCtx):
        cd = self.cfg.compute_dtype
        for l, bp in enumerate(blocks):
            bp = jax.tree_util.tree_map(lambda p: p.astype(cd), bp)
            h = self.block.apply(bp, h, ctx=ctx.fold(l))
        return h

    def num_params(self, params_tuple) -> int:
        return sum(int(p.size)
                   for p in jax.tree_util.tree_leaves(params_tuple))
