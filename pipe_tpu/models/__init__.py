"""Model zoo: the tutorial LM plus the BASELINE.json config families.

* :mod:`.transformer_lm` — WikiText-2 tutorial parity (reference main.py).
* :mod:`.long_context_lm` — ring-attention context-parallel LM (PP x CP).
* :mod:`.gpt2` — GPT-2 small/medium causal LM, optional @skippable
  embedding shortcut (BASELINE config #3).
* :mod:`.bert` — BERT-large MLM pretraining, interleave-ready (config #4).
* :mod:`.vit` — ViT-L/16 image classification, non-LM shapes (config #5).
"""

from .bert import BertConfig, PipelinedBERT, mask_tokens
from .common import PipelinedTransformer, per_row_ce
from .gpt2 import GPT2Config, PipelinedGPT2
from .long_context_lm import ContextParallelLM
from .transformer_lm import LMConfig, PipelinedLM
from .vit import PipelinedViT, ViTConfig

__all__ = [
    "BertConfig", "PipelinedBERT", "mask_tokens",
    "ContextParallelLM",
    "GPT2Config", "PipelinedGPT2",
    "LMConfig", "PipelinedLM",
    "PipelinedTransformer", "per_row_ce",
    "PipelinedViT", "ViTConfig",
]
