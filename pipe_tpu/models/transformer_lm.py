"""Tutorial-parity Transformer language model, pipelined both ways.

Workload parity with the reference driver (``main.py:101-120,139-171``):
WikiText-2 LM with Encoder (embedding + positional encoding), N ×
``TransformerEncoderLayer``, Decoder (projection to vocab); defaults emsize
2048, nhid 2048, nlayers 16, nhead 32, dropout 0.2, batch-first inputs
(``main.py:108-113``).

Two execution paths:

* :func:`build_sequential` — a heterogeneous ``Sequential`` for the ``Pipe``
  API / serial emulator (any stage split, like the reference's
  Encoder+blocks+Decoder partitions);
* :class:`PipelinedLM` — the SPMD path: homogeneous stacked transformer-block
  stages over the ``stage`` mesh axis, embed as ``pre_fn`` on stage 0 and
  decode (or per-token loss) as ``post_fn`` on stage n-1.

Mixed precision is TPU-idiomatic: params live in float32, stage compute can
run in bfloat16 (MXU native), logits/loss in float32.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.partition import StageCtx
from ..ops.layers import (Decoder, Embedding, PositionalEncoding, Sequential,
                          TransformerEncoderLayer)
from .common import PipelinedTransformer, per_row_ce

__all__ = ["LMConfig", "build_sequential", "PipelinedLM", "cross_entropy"]


@dataclasses.dataclass(frozen=True)
class LMConfig:
    """Tutorial hyperparameters (reference ``main.py:101-120``)."""

    vocab: int = 28782          # WikiText-2 vocab size ballpark
    d_model: int = 2048         # emsize
    nhead: int = 32
    d_ff: int = 2048            # nhid
    n_layers: int = 16
    dropout: float = 0.2
    seq_len: int = 128          # bptt
    causal: bool = True
    compute_dtype: Any = jnp.float32   # set jnp.bfloat16 on TPU
    attn_impl: str = "auto"            # auto | xla | flash (ops.layers.MHA)
    # Vocab block size for the streaming (fused head+loss) cross-entropy
    # (``ops/losses.streaming_xent``): the [tokens, vocab] logits never
    # materialize — peak head memory drops to O(tokens x block) at the
    # cost of one recompute pass of head FLOPs in the backward. None =
    # the dense decoder + per_row_ce path (parity default).
    loss_block: Any = None

    def tiny(self) -> "LMConfig":
        return dataclasses.replace(
            self, vocab=101, d_model=16, nhead=2, d_ff=32, n_layers=4,
            seq_len=16, dropout=0.0)


def cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean token cross-entropy, float32 accumulation.

    The reference computes ``CrossEntropyLoss(output.view(-1, V), targets)``
    on the last stage's device (``main.py:216``).
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold)


# ---------------------------------------------------------------------------
# Heterogeneous Sequential path (Pipe / emulator)
# ---------------------------------------------------------------------------

def build_sequential(cfg: LMConfig) -> Sequential:
    """Encoder + N blocks + Decoder as one Sequential (reference
    ``main.py:139-157`` builds exactly this module list for ``Pipe``)."""
    layers = [
        Embedding(cfg.vocab, cfg.d_model, scale=True),
        PositionalEncoding(cfg.d_model, cfg.dropout, max_len=max(5000, cfg.seq_len)),
    ]
    for _ in range(cfg.n_layers):
        layers.append(TransformerEncoderLayer(
            cfg.d_model, cfg.nhead, cfg.d_ff, cfg.dropout, causal=cfg.causal,
            attn_impl=cfg.attn_impl))
    layers.append(Decoder(cfg.vocab))
    return Sequential(layers, name="transformer_lm")


# ---------------------------------------------------------------------------
# SPMD path: homogeneous stacked stages
# ---------------------------------------------------------------------------

class PipelinedLM(PipelinedTransformer):
    """The SPMD-ready factorization: embed | k blocks per stage | decode.

    ``init`` returns ``(stage_params, pre_params, post_params)`` where
    ``stage_params`` is a list (length n_stages) of identically-structured
    pytrees — feed through ``stack_stage_params`` and ``SpmdPipeline``.
    """

    post_key = "decoder"

    def __init__(self, cfg: LMConfig, n_stages: int):
        self.embed = Embedding(cfg.vocab, cfg.d_model, scale=True)
        self.posenc = PositionalEncoding(
            cfg.d_model, cfg.dropout, max_len=max(5000, cfg.seq_len))
        self.block = TransformerEncoderLayer(
            cfg.d_model, cfg.nhead, cfg.d_ff, cfg.dropout, causal=cfg.causal,
            attn_impl=cfg.attn_impl)
        self.decoder = Decoder(cfg.vocab)
        self.head = self.decoder  # base-class alias (init/post param slot)
        super().__init__(cfg, n_stages)

    # --- SPMD stage functions (pre adds the tutorial's posenc) ---

    def pre_fn(self, pre_params, x_mb, ctx: StageCtx):
        tokens = x_mb["tokens"] if isinstance(x_mb, dict) else x_mb
        h = self.embed.apply(pre_params["embed"], tokens, ctx=ctx)
        h = self.posenc.apply({}, h, ctx=ctx.fold(1))
        return h.astype(self.cfg.compute_dtype)

    def embed_at(self, pre_params, tokens, pos):
        """Embed tokens occupying positions ``[pos, pos+q)`` — pre_fn with
        a position offset, for incremental decoding (inference: no
        dropout)."""
        h = self.embed.apply(pre_params["embed"], tokens)
        pe = jax.lax.dynamic_slice_in_dim(
            self.posenc.pe, pos, tokens.shape[-1], axis=0)
        return (h + pe).astype(self.cfg.compute_dtype)

    def embed_tree(self, pre_params, tokens, pos, depths):
        """Embed draft-TREE chunk rows: row r of ``tokens [b, Q]`` is a
        tree node at logical position ``pos + depths[r]`` (the root sits
        at ``pos``; same-depth nodes on different branches share a
        position). :meth:`embed_at` with a per-row position gather
        instead of a contiguous slice."""
        h = self.embed.apply(pre_params["embed"], tokens)
        pe = jnp.take(self.posenc.pe, pos + depths, axis=0)
        return (h + pe).astype(self.cfg.compute_dtype)

    def max_position(self) -> int:
        """Positional capacity (sinusoid table rows) — inference guard."""
        return int(self.posenc.pe.shape[0])

    def post_fn(self, post_params, h, ctx: StageCtx):
        return self.decoder.apply(post_params["decoder"],
                                  h.astype(jnp.float32), ctx=ctx)

    def loss_post_fn(self, post_params, h, x_mb, ctx: StageCtx):
        """In-pipeline loss: per-row mean token cross-entropy [mb_rows].

        Use with ``SpmdPipeline(post_with_batch=True)`` and
        ``x = {"tokens": [m,mb,seq], "targets": [m,mb,seq]}`` — the loss is
        computed on the last stage against the matching micro-batch, so the
        [m, mb, seq, vocab] logits never materialize in HBM (the reference
        moves targets to the last GPU for the same reason, ``main.py:216``).

        With ``cfg.loss_block`` set, even the per-micro-batch
        ``[mb, seq, vocab]`` logits never materialize: the head+loss fuse
        into the vocab-streamed cross-entropy (``ops/losses``)."""
        if self.cfg.loss_block:
            from ..ops.losses import streaming_xent
            p = post_params["decoder"]
            ce = streaming_xent(h, p["w"], p["b"], x_mb["targets"],
                                int(self.cfg.loss_block))   # [mb, seq]
            return jnp.mean(ce, axis=-1)                    # [mb_rows]
        logits = self.decoder.apply(post_params["decoder"],
                                    h.astype(jnp.float32), ctx=ctx)
        return per_row_ce(logits, x_mb["targets"])  # [mb_rows]
