"""Fault injection, anomaly detection, and recovery for train + serve.

The north-star system serves heavy traffic; at that scale faults are
weather, not news — a NaN step, a wedged serve tick, a torn checkpoint
must cost *one step / one request*, not the job. Three layers, each
usable alone:

* :mod:`.chaos` — deterministic, seeded, step/tick-indexed fault
  injection (:class:`ChaosPlan`): NaN/inf into grads or activations,
  loss spikes, data-iterator raises, transport-hop drop/corrupt on the
  emulator, serve-tick stalls, queue floods, backend raises. Drives
  the recovery proofs in ``tools/chaos_bench.py`` (``CHAOS_r09.json``)
  and the ``chaos``-marked tests.
* :mod:`.detect` — cheap in-program detection: a fused
  finiteness+loss-spike check on the train step (one extra global-norm
  reduction, no host sync of its own — see :func:`detect.step_guard`)
  and :class:`detect.TickWatchdog` for the serve tick (wall-clock
  budget, stuck-slot ceiling, deadline-miss EWMA for overload
  shedding).
* :mod:`.recover` — policies: skip-step with optimizer-state rollback
  happens *inside* the jitted step (a ``where``-select, zero
  recompiles); :class:`recover.ResilienceController` adds host-side
  bounded rewind-to-snapshot with exponential backoff;
  :class:`recover.RetryingIterator` retries the data iterator.

The whole subsystem is strictly opt-in and the opt-out is bitwise: with
``TrainerConfig.resilience=None`` (the default) and no
:class:`ChaosPlan`, every lowered program is byte-identical to the
unwired build — pinned by ``tests/test_resilience.py``'s HLO equality
tests. See ``docs/resilience.md`` for the fault model and the recovery
state machine.
"""

from .chaos import ChaosError, ChaosPlan, Fault
from .detect import TickWatchdog, step_guard
from .recover import (DataIteratorFailed, ResilienceConfig,
                      ResilienceController, RetryingIterator,
                      TrainingAborted)

__all__ = [
    "ChaosError", "ChaosPlan", "Fault",
    "TickWatchdog", "step_guard",
    "DataIteratorFailed", "ResilienceConfig", "ResilienceController",
    "RetryingIterator", "TrainingAborted",
]
