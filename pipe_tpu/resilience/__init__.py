"""Fault injection, anomaly detection, and recovery for train + serve.

The north-star system serves heavy traffic; at that scale faults are
weather, not news — a NaN step, a wedged serve tick, a torn checkpoint
must cost *one step / one request*, not the job. Three layers, each
usable alone:

* :mod:`.chaos` — deterministic, seeded, step/tick-indexed fault
  injection (:class:`ChaosPlan`): NaN/inf into grads or activations,
  loss spikes, data-iterator raises, transport-hop drop/corrupt on the
  emulator, serve-tick stalls, queue floods, backend raises. Drives
  the recovery proofs in ``tools/chaos_bench.py`` (``CHAOS_r09.json``)
  and the ``chaos``-marked tests.
* :mod:`.detect` — cheap in-program detection: a fused
  finiteness+loss-spike check on the train step (one extra global-norm
  reduction, no host sync of its own — see :func:`detect.step_guard`)
  and :class:`detect.TickWatchdog` for the serve tick (wall-clock
  budget, stuck-slot ceiling, deadline-miss EWMA for overload
  shedding).
* :mod:`.recover` — policies: skip-step with optimizer-state rollback
  happens *inside* the jitted step (a ``where``-select, zero
  recompiles); :class:`recover.ResilienceController` adds host-side
  bounded rewind-to-snapshot with exponential backoff;
  :class:`recover.RetryingIterator` retries the data iterator.
* :mod:`.elastic` — the top rung: survive the *loss of a pipeline
  stage*. :class:`elastic.BuddyStore` replicates every stage's
  params/optimizer shard to its ring buddy on a cadence (one ppermute
  hop, sha256-pinned bitwise against the source);
  :class:`elastic.ElasticController` reads a per-stage gradient
  heartbeat from the step's aux carry and raises
  :class:`elastic.StageLost` when a stage goes persistently silent;
  :func:`elastic.replan_after_loss` re-cuts the balance over the
  ``n-1`` survivors, re-verifies the op table, restores from the
  buddy, and resumes — :func:`elastic.train_elastic` drives the whole
  ladder, aborting (:class:`recover.TrainingAborted`) past
  ``max_replans``.

The whole subsystem is strictly opt-in and the opt-out is bitwise: with
``TrainerConfig.resilience=None`` and ``TrainerConfig.elastic=None``
(the defaults) and no :class:`ChaosPlan`, every lowered program is
byte-identical to the unwired build — pinned by
``tests/test_resilience.py`` and ``tests/test_elastic.py``'s HLO
equality tests. See ``docs/resilience.md`` for the fault model and the
recovery state machine.
"""

from .chaos import (KILL_NONE, ChaosError, ChaosPlan, Fault, current_kill,
                    kill_scope, wrap_stage_fn)
from .detect import HopHealth, TickWatchdog, stage_heartbeat, step_guard
from .elastic import (BuddyStore, ElasticConfig, ElasticController,
                      StageLost, replan_after_loss, restack_state,
                      train_elastic)
from .recover import (DataIteratorFailed, ResilienceConfig,
                      ResilienceController, RetryingIterator,
                      TrainingAborted)

__all__ = [
    "ChaosError", "ChaosPlan", "Fault", "KILL_NONE", "current_kill",
    "kill_scope", "wrap_stage_fn",
    "HopHealth", "TickWatchdog", "stage_heartbeat", "step_guard",
    "BuddyStore", "ElasticConfig", "ElasticController", "StageLost",
    "replan_after_loss", "restack_state", "train_elastic",
    "DataIteratorFailed", "ResilienceConfig", "ResilienceController",
    "RetryingIterator", "TrainingAborted",
]
