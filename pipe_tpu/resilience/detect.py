"""Anomaly detection: in-program train-step guard + serve-tick watchdog.

Train side — :func:`step_guard` is traced INTO the guarded train step:
one ``global_norm`` reduction (NaN/inf in any gradient leaf propagates
into it) fused with a loss-spike test against an EWMA carried in the
device-side aux state. No host sync of its own: the verdict rides the
step outputs the loop already holds, and the host reads it on its own
cadence (``ResilienceConfig.check_every``). Skip-step then happens
inside the same program (``where``-select in ``train/loop.py``), so an
isolated NaN step costs one wasted micro-batch of work, never a
poisoned optimizer state.

Serve side — :class:`TickWatchdog` is pure host bookkeeping for the
engine tick: a wall-clock budget per tick (a stalled backend shows up
as ``resilience.watchdog_slow_ticks`` instead of silent lag), a
stuck-slot ceiling (a slot alive far past the ticks its token budget
can need is retired ``status="error"`` rather than squatting forever),
and the deadline-miss EWMA that arms the degraded mode (shed
lowest-priority queued work — see ``serve/engine.py``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

__all__ = ["step_guard", "stage_heartbeat", "HopHealth", "TickWatchdog"]


def step_guard(loss, grads, ewma, step, *, spike_factor: float,
               warmup_steps: int, ewma_alpha: float):
    """Fused finiteness + loss-spike check, traced into the train step.

    Returns ``(ok, new_ewma)``: ``ok`` is False when the loss or any
    gradient is non-finite, or (past warmup) the loss exceeds
    ``spike_factor`` x the EWMA of accepted losses. The EWMA folds only
    accepted steps — a rejected spike must not drag the baseline toward
    itself and mask a follow-up.
    """
    import jax.numpy as jnp
    import optax

    loss32 = loss.astype(jnp.float32)
    gnorm = optax.global_norm(grads)
    finite = jnp.isfinite(loss32) & jnp.isfinite(gnorm)
    warmed = step >= warmup_steps
    # non-finite loss fails `finite` already; guard the comparison so a
    # NaN loss cannot sneak past via compare-False semantics
    spike = warmed & finite & (loss32 > ewma * spike_factor)
    ok = finite & ~spike
    seeded = ewma > 0.0
    new_ewma = jnp.where(
        ok,
        jnp.where(seeded, ewma_alpha * loss32 + (1.0 - ewma_alpha) * ewma,
                  loss32),
        ewma)
    return ok, new_ewma


def stage_heartbeat(stage_grads, n_stages: int):
    """Per-stage gradient power — the elastic controller's liveness
    signal, traced into the elastic train step.

    ``stage_grads`` is the stage-stacked gradient pytree (every leaf
    carries the ``n_stages`` leading axis). Returns a ``[n_stages]``
    float32 vector of summed squared gradient magnitude per stage. A
    killed stage ``j`` (output zeroed) contributes exactly 0.0 for every
    stage ``<= j`` — the zero scale annihilates the backward signal into
    and through the dead stage — while survivors downstream keep
    nonzero grads (their params still shape the loss). The controller
    localizes the kill as the LARGEST persistently-silent index. Like
    :func:`step_guard`, this adds one reduction per leaf and no host
    sync of its own: the vector rides the step aux carry.
    """
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(stage_grads)
    total = jnp.zeros((n_stages,), jnp.float32)
    for g in leaves:
        g32 = g.astype(jnp.float32)
        axes = tuple(range(1, g32.ndim))
        total = total + jnp.sum(g32 * g32, axis=axes)
    return total


@dataclasses.dataclass
class HopHealth:
    """Per-hop failure-streak ledger for the emulator executor.

    The emulator records every stage-boundary crossing
    (:meth:`record`): a chaos-faulted hop bumps that hop's consecutive
    streak, a clean crossing resets it. A transient ``transport_drop``
    (one micro-batch) therefore never accumulates, while a
    ``persistent_hop_drop`` marches the streak up by the full
    micro-batch count every run — once it reaches ``dead_after`` the
    hop lands in :attr:`dead_hops` and the caller escalates to the
    elastic rung instead of retrying forever.
    """

    dead_after: int = 2
    _streaks: dict = dataclasses.field(default_factory=dict, init=False,
                                       repr=False, compare=False)
    _faults: dict = dataclasses.field(default_factory=dict, init=False,
                                      repr=False, compare=False)

    def __post_init__(self):
        if self.dead_after < 1:
            raise ValueError(
                f"dead_after must be >= 1, got {self.dead_after}")

    def record(self, stage: int, faulted: bool) -> None:
        """Fold one crossing of the hop leaving ``stage``."""
        if faulted:
            self._streaks[stage] = self._streaks.get(stage, 0) + 1
            self._faults[stage] = self._faults.get(stage, 0) + 1
        else:
            self._streaks[stage] = 0

    def streak(self, stage: int) -> int:
        """Current consecutive-fault streak for the hop leaving
        ``stage`` (0 = healthy or never crossed)."""
        return self._streaks.get(stage, 0)

    def faults(self, stage: int) -> int:
        """Total faulted crossings of the hop since construction."""
        return self._faults.get(stage, 0)

    @property
    def dead_hops(self) -> list:
        """Hops whose streak has reached ``dead_after``, ascending."""
        return sorted(j for j, s in self._streaks.items()
                      if s >= self.dead_after)


@dataclasses.dataclass
class TickWatchdog:
    """Serve-tick health policy (host-side; no device program change).

    ``tick_budget_s`` — a tick slower than this is counted and evented
    (``resilience.watchdog_slow_ticks``); None disables.
    ``stuck_slack_ticks`` — a live slot is declared stuck (and retired
    ``status="error"``) once its age exceeds the ticks its token budget
    can possibly need (``ceil(max_new / decode_chunk)``) plus this
    slack; None disables.
    ``shed_ewma_threshold`` — deadline-miss EWMA (per retirement,
    ``shed_ewma_alpha`` horizon) above which the engine enters degraded
    mode and sheds lowest-priority queued requests; None disables.

    Beyond the thresholds, the watchdog is the engine's health ledger:
    the engine feeds every verdict back through ``record_tick`` /
    ``record_outcome`` / ``record_stuck``, and the read-only properties
    (``miss_ewma``, ``slow_streak``, ``slow_ticks``, ``stuck_slots``,
    ``last_tick_s``) are the public health surface the fleet router's
    state machine consumes — no reaching into engine privates, and the
    signals are all host-side bookkeeping the engine already computed
    (never an extra device sync). One watchdog instance per engine.
    """

    tick_budget_s: Optional[float] = None
    stuck_slack_ticks: Optional[int] = 8
    shed_ewma_threshold: Optional[float] = None
    shed_ewma_alpha: float = 0.1
    _miss_ewma: float = dataclasses.field(default=0.0, init=False,
                                          repr=False, compare=False)
    _slow_streak: int = dataclasses.field(default=0, init=False,
                                          repr=False, compare=False)
    _slow_ticks: int = dataclasses.field(default=0, init=False,
                                         repr=False, compare=False)
    _stuck_slots: int = dataclasses.field(default=0, init=False,
                                          repr=False, compare=False)
    _last_tick_s: float = dataclasses.field(default=0.0, init=False,
                                            repr=False, compare=False)

    def __post_init__(self):
        if self.tick_budget_s is not None and self.tick_budget_s <= 0:
            raise ValueError(
                f"tick_budget_s must be > 0, got {self.tick_budget_s}")
        if self.stuck_slack_ticks is not None and self.stuck_slack_ticks < 1:
            raise ValueError(
                f"stuck_slack_ticks must be >= 1, got "
                f"{self.stuck_slack_ticks}")
        if self.shed_ewma_threshold is not None and \
                not 0.0 < self.shed_ewma_threshold <= 1.0:
            raise ValueError(
                f"shed_ewma_threshold must be in (0, 1], got "
                f"{self.shed_ewma_threshold}")

    def stuck_after(self, max_new_tokens: int, decode_chunk: int) -> \
            Optional[int]:
        """Tick-age ceiling for a slot with this token budget (None when
        stuck detection is disabled)."""
        if self.stuck_slack_ticks is None:
            return None
        need = math.ceil(max_new_tokens / max(decode_chunk, 1))
        return need + self.stuck_slack_ticks

    # -- recording (engine-side feed) ---------------------------------------

    def record_tick(self, duration_s: float) -> bool:
        """Fold one tick's wall clock. Returns True when the tick blew
        ``tick_budget_s`` (always False with the budget disabled);
        consecutive overruns accumulate in ``slow_streak``, a healthy
        tick resets it."""
        self._last_tick_s = float(duration_s)
        over = self.tick_budget_s is not None \
            and duration_s > self.tick_budget_s
        if over:
            self._slow_ticks += 1
            self._slow_streak += 1
        else:
            self._slow_streak = 0
        return over

    def record_outcome(self, missed_deadline: bool) -> float:
        """Fold one served retirement into the deadline-miss EWMA
        (``shed_ewma_alpha`` horizon) and return the new value. Only
        *served* outcomes (ok/timeout) belong here — shed work is the
        response to misses and must not latch degraded mode."""
        miss = 1.0 if missed_deadline else 0.0
        a = self.shed_ewma_alpha
        self._miss_ewma = a * miss + (1.0 - a) * self._miss_ewma
        return self._miss_ewma

    def record_stuck(self) -> None:
        """Count one stuck-slot retirement."""
        self._stuck_slots += 1

    # -- read-only health surface (what the router consumes) ----------------

    @property
    def miss_ewma(self) -> float:
        """Deadline-miss EWMA over served retirements."""
        return self._miss_ewma

    @property
    def slow_streak(self) -> int:
        """Consecutive ticks over ``tick_budget_s`` (0 = on budget)."""
        return self._slow_streak

    @property
    def slow_ticks(self) -> int:
        """Total ticks over budget since construction."""
        return self._slow_ticks

    @property
    def stuck_slots(self) -> int:
        """Total stuck-slot retirements since construction."""
        return self._stuck_slots

    @property
    def last_tick_s(self) -> float:
        """Wall-clock duration of the most recent tick."""
        return self._last_tick_s
