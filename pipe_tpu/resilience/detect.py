"""Anomaly detection: in-program train-step guard + serve-tick watchdog.

Train side — :func:`step_guard` is traced INTO the guarded train step:
one ``global_norm`` reduction (NaN/inf in any gradient leaf propagates
into it) fused with a loss-spike test against an EWMA carried in the
device-side aux state. No host sync of its own: the verdict rides the
step outputs the loop already holds, and the host reads it on its own
cadence (``ResilienceConfig.check_every``). Skip-step then happens
inside the same program (``where``-select in ``train/loop.py``), so an
isolated NaN step costs one wasted micro-batch of work, never a
poisoned optimizer state.

Serve side — :class:`TickWatchdog` is pure host bookkeeping for the
engine tick: a wall-clock budget per tick (a stalled backend shows up
as ``resilience.watchdog_slow_ticks`` instead of silent lag), a
stuck-slot ceiling (a slot alive far past the ticks its token budget
can need is retired ``status="error"`` rather than squatting forever),
and the deadline-miss EWMA that arms the degraded mode (shed
lowest-priority queued work — see ``serve/engine.py``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

__all__ = ["step_guard", "TickWatchdog"]


def step_guard(loss, grads, ewma, step, *, spike_factor: float,
               warmup_steps: int, ewma_alpha: float):
    """Fused finiteness + loss-spike check, traced into the train step.

    Returns ``(ok, new_ewma)``: ``ok`` is False when the loss or any
    gradient is non-finite, or (past warmup) the loss exceeds
    ``spike_factor`` x the EWMA of accepted losses. The EWMA folds only
    accepted steps — a rejected spike must not drag the baseline toward
    itself and mask a follow-up.
    """
    import jax.numpy as jnp
    import optax

    loss32 = loss.astype(jnp.float32)
    gnorm = optax.global_norm(grads)
    finite = jnp.isfinite(loss32) & jnp.isfinite(gnorm)
    warmed = step >= warmup_steps
    # non-finite loss fails `finite` already; guard the comparison so a
    # NaN loss cannot sneak past via compare-False semantics
    spike = warmed & finite & (loss32 > ewma * spike_factor)
    ok = finite & ~spike
    seeded = ewma > 0.0
    new_ewma = jnp.where(
        ok,
        jnp.where(seeded, ewma_alpha * loss32 + (1.0 - ewma_alpha) * ewma,
                  loss32),
        ewma)
    return ok, new_ewma


@dataclasses.dataclass
class TickWatchdog:
    """Serve-tick health policy (host-side; no device program change).

    ``tick_budget_s`` — a tick slower than this is counted and evented
    (``resilience.watchdog_slow_ticks``); None disables.
    ``stuck_slack_ticks`` — a live slot is declared stuck (and retired
    ``status="error"``) once its age exceeds the ticks its token budget
    can possibly need (``ceil(max_new / decode_chunk)``) plus this
    slack; None disables.
    ``shed_ewma_threshold`` — deadline-miss EWMA (per retirement,
    ``shed_ewma_alpha`` horizon) above which the engine enters degraded
    mode and sheds lowest-priority queued requests; None disables.
    """

    tick_budget_s: Optional[float] = None
    stuck_slack_ticks: Optional[int] = 8
    shed_ewma_threshold: Optional[float] = None
    shed_ewma_alpha: float = 0.1

    def __post_init__(self):
        if self.tick_budget_s is not None and self.tick_budget_s <= 0:
            raise ValueError(
                f"tick_budget_s must be > 0, got {self.tick_budget_s}")
        if self.stuck_slack_ticks is not None and self.stuck_slack_ticks < 1:
            raise ValueError(
                f"stuck_slack_ticks must be >= 1, got "
                f"{self.stuck_slack_ticks}")
        if self.shed_ewma_threshold is not None and \
                not 0.0 < self.shed_ewma_threshold <= 1.0:
            raise ValueError(
                f"shed_ewma_threshold must be in (0, 1], got "
                f"{self.shed_ewma_threshold}")

    def stuck_after(self, max_new_tokens: int, decode_chunk: int) -> \
            Optional[int]:
        """Tick-age ceiling for a slot with this token budget (None when
        stuck detection is disabled)."""
        if self.stuck_slack_ticks is None:
            return None
        need = math.ceil(max_new_tokens / max(decode_chunk, 1))
        return need + self.stuck_slack_ticks
