"""Deterministic fault injection: seeded, step/tick-indexed ChaosPlans.

A :class:`ChaosPlan` is a static list of :class:`Fault`\\ s, each firing
at a known step (train) or tick (serve) index for a known duration —
the same discipline as the schedule tables: everything decided up
front, nothing random at run time (the ``seed`` only derives payload
*content*, e.g. flood prompts, never *whether* a fault fires). That
determinism is what makes the recovery proofs in
``tools/chaos_bench.py`` reproducible artifacts instead of flaky
demos.

Injection sites, by fault kind:

==================  =======================================================
``nan_grads``       gradients scaled by NaN inside the jitted train step
``inf_grads``       gradients scaled by +inf inside the jitted train step
``nan_loss``        the step loss replaced by NaN
``loss_spike``      the step loss scaled by ``magnitude`` (default 1e3)
``nan_activations`` the pre-stage activations scaled by NaN (rides the
                    wrapped ``pre_fn``; corrupts loss AND grads the way
                    a real numeric blowup does)
``data_raise``      :class:`ChaosError` raised from the data iterator
``transport_drop``  a stage-boundary hop zeroed in the EMULATOR executor
                    (an in-array activation fault, despite the name —
                    it never touches a real wire)
``transport_corrupt`` the same emulator hop scaled by NaN instead
``wire_partition``  fleet proc wire: the parent drops the covered
                    outgoing frame, severs the connection and refuses
                    the child's re-dial for ``magnitude`` seconds
                    (capped 30s) — heals by reconnect + replay
``wire_delay``      fleet proc wire: the covered outgoing frame is
                    held ``magnitude`` seconds (capped 5s) before send
``wire_corrupt``    fleet proc wire: the covered outgoing frame's last
                    byte is flipped AFTER checksumming, so the
                    receiver's CRC32 rejects it and forces a resync
``wire_dup``        fleet proc wire: the covered outgoing frame is
                    sent twice (sequence dedup must collapse them)
``stall_tick``      the serve engine sleeps ``magnitude`` seconds in-tick
``queue_flood``     the serve queue force-filled to capacity with junk
``backend_raise``   :class:`ChaosError` raised at the next backend
                    prefill (exercises the slot-error containment path)
``wedge_replica``   router fleet: replica ``stage``'s decode raises
                    :class:`ChaosError` while the fault covers the tick
                    (transient wedge — clears when the window ends)
``kill_replica``    router fleet: replica ``stage``'s decode raises
                    permanently from ``step`` onward (the replica never
                    comes back; the router must fail work over)
``slow_replica``    router fleet: replica ``stage``'s decode sleeps
                    ``magnitude`` seconds per tick while covered (the
                    watchdog sees the overrun; drives SUSPECT)
``kill_stage``      pipeline stage ``stage``'s output zeroed permanently
                    from ``step`` onward (rides the wrapped ``stage_fn``
                    via a traced kill code; the stage never comes back —
                    the elastic controller must re-plan around it)
``persistent_hop_drop`` the stage-boundary hop leaving ``stage`` zeroed
                    for EVERY micro-batch from the moment the fault
                    arms (emulator executor; feeds the per-hop
                    failure-streak counter)
==================  =======================================================

Train-step faults ride a *traced* ``inject`` code (one int32 scalar
argument of the guarded step): the program is compiled once and the
host flips the code at the fault step — zero recompiles across
fault/no-fault steps, and a plan with no faults simply keeps the code
at 0. The activation hook threads the traced code to the model's
``pre_fn`` through a trace-time context (:func:`inject_scope` /
:func:`current_inject`), set only while the guarded step is tracing.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional, Sequence, Tuple

__all__ = ["ChaosError", "Fault", "ChaosPlan",
           "INJECT_NONE", "INJECT_NAN_GRADS", "INJECT_INF_GRADS",
           "INJECT_NAN_LOSS", "INJECT_LOSS_SPIKE", "INJECT_NAN_ACT",
           "KILL_NONE", "inject_scope", "current_inject",
           "kill_scope", "current_kill", "apply_train_faults",
           "wrap_pre_fn", "wrap_stage_fn"]


class ChaosError(RuntimeError):
    """An injected fault (never raised by real code paths)."""


TRAIN_KINDS = ("nan_grads", "inf_grads", "nan_loss", "loss_spike",
               "nan_activations")
DATA_KINDS = ("data_raise",)
# "transport" faults reach the EMULATOR's stage-boundary hops only —
# they corrupt activations in-array and never touch a real wire. The
# fleet's actual socket wire is faulted by WIRE_KINDS below, routed
# through pipe_tpu.fleet.proc.apply_wire_chaos at the framing layer.
TRANSPORT_KINDS = ("transport_drop", "transport_corrupt",
                   "persistent_hop_drop")
WIRE_KINDS = ("wire_partition", "wire_delay", "wire_corrupt", "wire_dup")
SERVE_KINDS = ("stall_tick", "queue_flood", "backend_raise")
REPLICA_KINDS = ("wedge_replica", "kill_replica", "slow_replica")
STAGE_KINDS = ("kill_stage",)
KINDS = TRAIN_KINDS + DATA_KINDS + TRANSPORT_KINDS + WIRE_KINDS \
    + SERVE_KINDS + REPLICA_KINDS + STAGE_KINDS

# Traced inject codes (the int32 scalar argument of the guarded step).
INJECT_NONE = 0
INJECT_NAN_GRADS = 1
INJECT_INF_GRADS = 2
INJECT_NAN_LOSS = 3
INJECT_LOSS_SPIKE = 4
INJECT_NAN_ACT = 5
_TRAIN_CODE = {"nan_grads": INJECT_NAN_GRADS,
               "inf_grads": INJECT_INF_GRADS,
               "nan_loss": INJECT_NAN_LOSS,
               "loss_spike": INJECT_LOSS_SPIKE,
               "nan_activations": INJECT_NAN_ACT}

# Traced kill code (the int32 scalar argument of the elastic step):
# the stage index to silence, or KILL_NONE for a healthy step.
KILL_NONE = -1


@dataclasses.dataclass(frozen=True)
class Fault:
    """One planned fault: ``kind`` fires at ``step`` (train step or
    serve tick index, 0-based) for ``count`` consecutive indices.
    ``stage``/``microbatch`` address transport faults (the hop leaving
    ``stage`` for micro-batch ``microbatch``); ``magnitude`` scales
    ``loss_spike`` (factor) and ``stall_tick`` (seconds)."""

    kind: str
    step: int
    count: int = 1
    stage: int = 0
    microbatch: int = 0
    magnitude: float = 1e3

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"one of {KINDS}")
        if self.step < 0 or self.count < 1:
            raise ValueError(
                f"fault needs step >= 0 and count >= 1, got "
                f"step={self.step} count={self.count}")

    def covers(self, index: int) -> bool:
        return self.step <= index < self.step + self.count


class ChaosPlan:
    """A static, seeded fault schedule. Immutable after construction;
    safe to share between a Trainer and a ServeEngine (train faults key
    on step index, serve faults on tick index — disjoint kinds)."""

    def __init__(self, faults: Sequence[Fault] = (), *, seed: int = 0):
        self.faults: Tuple[Fault, ...] = tuple(faults)
        self.seed = int(seed)

    def __bool__(self) -> bool:
        return bool(self.faults)

    def __repr__(self) -> str:
        return f"ChaosPlan({list(self.faults)!r}, seed={self.seed})"

    def active(self, kind: str, index: int) -> Optional[Fault]:
        """The first ``kind`` fault covering ``index`` (or None)."""
        for f in self.faults:
            if f.kind == kind and f.covers(index):
                return f
        return None

    def without(self, kind: str) -> "ChaosPlan":
        """A new plan minus every ``kind`` fault (same seed). The
        elastic recovery driver uses this to rebuild the survivor
        topology's plan: the killed stage no longer exists, so its
        ``kill_stage`` fault must not re-fire against the new indices."""
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; one of {KINDS}")
        return ChaosPlan([f for f in self.faults if f.kind != kind],
                         seed=self.seed)

    # -- train step ---------------------------------------------------------

    def train_inject(self, step: int) -> Tuple[int, float]:
        """(inject code, magnitude) for the guarded train step at
        ``step`` — (0, 1.0) when no train fault covers it."""
        for f in self.faults:
            if f.kind in _TRAIN_CODE and f.covers(step):
                return _TRAIN_CODE[f.kind], float(f.magnitude)
        return INJECT_NONE, 1.0

    def train_kill(self, step: int) -> int:
        """The stage index a ``kill_stage`` fault silences at ``step``,
        or :data:`KILL_NONE`. Like ``kill_replica``, a stage kill is
        permanent: it matches every step from ``step`` onward regardless
        of ``count`` — a dead stage never comes back on its own."""
        for f in self.faults:
            if f.kind == "kill_stage" and step >= f.step:
                return int(f.stage)
        return KILL_NONE

    def last_train_fault_step(self) -> int:
        """Last step index any train-visible fault covers (-1 if none) —
        chaos_bench uses it to define steps-to-recover."""
        last = -1
        for f in self.faults:
            if f.kind in _TRAIN_CODE:
                last = max(last, f.step + f.count - 1)
        return last

    # -- data iterator ------------------------------------------------------

    def maybe_raise_data(self, index: int) -> None:
        f = self.active("data_raise", index)
        if f is not None:
            raise ChaosError(
                f"injected data-iterator fault at batch {index} "
                f"(plan seed {self.seed})")

    # -- emulator transport -------------------------------------------------

    def transport_fault(self, microbatch: int, stage: int) -> Optional[str]:
        """'drop' | 'corrupt' | None for the hop leaving ``stage`` with
        micro-batch ``microbatch`` (emulator executor only). A
        ``persistent_hop_drop`` on the hop matches EVERY micro-batch —
        the failure-streak counter sees it never clear."""
        for f in self.faults:
            if f.kind not in TRANSPORT_KINDS or f.stage != stage:
                continue
            if f.kind == "persistent_hop_drop":
                return "drop"
            if f.microbatch == microbatch:
                return "drop" if f.kind == "transport_drop" else "corrupt"
        return None

    # -- fleet proc wire ----------------------------------------------------

    def wire_fault(self, kind: str, index: int,
                   replica: int = 0) -> Optional[Fault]:
        """The first ``kind`` wire fault hitting ``replica``'s proc
        wire (addressed via ``Fault.stage``, like replica faults) at
        outgoing frame ``index``. Consulted by
        :func:`pipe_tpu.fleet.proc.apply_wire_chaos` per parent->child
        frame — frame index, not tick, is the coverage key, so a drill
        can corrupt exactly the Nth frame regardless of timing."""
        if kind not in WIRE_KINDS:
            raise ValueError(f"{kind!r} is not a wire fault kind; "
                             f"one of {WIRE_KINDS}")
        for f in self.faults:
            if f.kind == kind and f.stage == replica and f.covers(index):
                return f
        return None

    # -- serve tick ---------------------------------------------------------

    def serve_fault(self, kind: str, tick: int) -> Optional[Fault]:
        if kind not in SERVE_KINDS:
            raise ValueError(f"{kind!r} is not a serve fault kind")
        return self.active(kind, tick)

    # -- router fleet -------------------------------------------------------

    def replica_fault(self, kind: str, tick: int,
                      replica: int) -> Optional[Fault]:
        """The first ``kind`` fault hitting ``replica`` (addressed via
        ``Fault.stage``) at router tick ``tick``. ``kill_replica`` is
        permanent — it matches every tick from ``step`` onward, however
        small ``count`` is; a killed replica never recovers."""
        if kind not in REPLICA_KINDS:
            raise ValueError(f"{kind!r} is not a replica fault kind; "
                             f"one of {REPLICA_KINDS}")
        for f in self.faults:
            if f.kind != kind or f.stage != replica:
                continue
            if kind == "kill_replica":
                if tick >= f.step:
                    return f
            elif f.covers(tick):
                return f
        return None

    def flood_prompt(self, i: int) -> list:
        """Deterministic junk prompt ``i`` for queue_flood (content from
        the plan seed, so floods are reproducible)."""
        import numpy as np
        rng = np.random.RandomState(self.seed * 1_000_003 + i)
        return [int(t) for t in rng.randint(1, 32, size=4)]


# ---------------------------------------------------------------------------
# Traced-injection plumbing (train step)
# ---------------------------------------------------------------------------

_trace_local = threading.local()


class inject_scope:
    """Context manager installing the traced inject code for the
    duration of one guarded-step trace, so wrapped model fns
    (:func:`wrap_pre_fn`) can read it. ``code=None`` installs nothing
    (the wrapped fns then compile to the identity)."""

    def __init__(self, code):
        self.code = code

    def __enter__(self):
        self._prev = getattr(_trace_local, "code", None)
        _trace_local.code = self.code
        return self

    def __exit__(self, *exc):
        _trace_local.code = self._prev


def current_inject():
    """The traced inject code installed by :class:`inject_scope`, or
    None outside any scope (including every non-resilient trace)."""
    return getattr(_trace_local, "code", None)


class kill_scope:
    """Context manager installing the traced kill code (a stage index,
    or :data:`KILL_NONE`) for the duration of one elastic-step trace,
    so wrapped stage fns (:func:`wrap_stage_fn`) can read it. Same
    discipline as :class:`inject_scope`: ``code=None`` installs nothing
    and the wrapped fns compile to the identity."""

    def __init__(self, code):
        self.code = code

    def __enter__(self):
        self._prev = getattr(_trace_local, "kill", None)
        _trace_local.kill = self.code
        return self

    def __exit__(self, *exc):
        _trace_local.kill = self._prev


def current_kill():
    """The traced kill code installed by :class:`kill_scope`, or None
    outside any scope (including every non-elastic trace)."""
    return getattr(_trace_local, "kill", None)


def apply_train_faults(inject, magnitude, loss, grads):
    """Apply the grad/loss fault selected by the traced ``inject`` code.
    One scalar select + one broadcast multiply per tree — the program
    is identical whichever code the host passes at run time."""
    import jax
    import jax.numpy as jnp

    gscale = jnp.where(
        inject == INJECT_NAN_GRADS, jnp.float32(jnp.nan),
        jnp.where(inject == INJECT_INF_GRADS, jnp.float32(jnp.inf),
                  jnp.float32(1.0)))
    grads = jax.tree_util.tree_map(
        lambda g: g * gscale.astype(g.dtype), grads)
    lscale = jnp.where(
        inject == INJECT_NAN_LOSS, jnp.float32(jnp.nan),
        jnp.where(inject == INJECT_LOSS_SPIKE,
                  jnp.float32(magnitude), jnp.float32(1.0)))
    loss = loss * lscale.astype(loss.dtype)
    return loss, grads


def wrap_pre_fn(pre_fn):
    """Wrap a model ``pre_fn`` so INJECT_NAN_ACT poisons the activations
    it emits. Outside an :class:`inject_scope` (every non-chaos trace)
    the wrapper is a transparent pass-through — no program change."""
    import jax.numpy as jnp

    def chaos_pre_fn(prep, x, ctx):
        h = pre_fn(prep, x, ctx)
        code = current_inject()
        if code is None:
            return h
        scale = jnp.where(code == INJECT_NAN_ACT, jnp.float32(jnp.nan),
                          jnp.float32(1.0))
        return h * scale.astype(h.dtype)

    return chaos_pre_fn


def wrap_stage_fn(stage_fn):
    """Wrap a model ``stage_fn`` so a traced ``kill_stage`` code zeroes
    the killed stage's entire output (activations, stashes, stats — a
    dead chip emits nothing). ``ctx.stage`` is a Python int in the
    emulator and a traced ``axis_index`` in the compiled executors; the
    ``==`` compare works in both. Outside a :class:`kill_scope` (every
    non-elastic trace) the wrapper is a transparent pass-through — no
    program change. Multiplying by the 1.0 branch is bitwise-exact, so
    pre-kill steps match an unarmed run exactly."""
    import jax
    import jax.numpy as jnp

    def chaos_stage_fn(params, h, ctx, *rest):
        out = stage_fn(params, h, ctx, *rest)
        code = current_kill()
        if code is None:
            return out
        scale = jnp.where(ctx.stage == code, jnp.float32(0.0),
                          jnp.float32(1.0))
        return jax.tree_util.tree_map(
            lambda o: o * scale.astype(o.dtype), out)

    return chaos_stage_fn
