"""Elastic degraded-mode training: survive stage loss, re-plan, resume.

The top rung of the recovery ladder (docs/resilience.md): skip-step
(PR 5) drops one poisoned update, rewind restores a known-good
snapshot, and THIS module survives the fault class neither can — a
pipeline stage that dies and stays dead. Three cooperating pieces:

* :class:`BuddyStore` — buddy replication. On a healthy-step cadence,
  every stage's shard of the stacked params + optimizer moments rides
  one extra ppermute hop to its ring neighbor ``(j+1) % n`` and is
  host-fetched there, with per-stage sha256 manifests
  (:func:`~pipe_tpu.train.state.stage_shard_manifest`) pinning the
  copy bitwise against the source shard. Any single stage loss is then
  recoverable from the survivors: stage ``j``'s state lives on buddy
  ``j+1``, and all shards carry the same step, so the reassembled
  state is consistent by construction.

* :class:`ElasticController` — detection. The elastic train step
  (``Trainer._train_step_elastic``) carries a per-stage gradient
  heartbeat in the device aux state; the controller reads it on the
  host cadence and raises :class:`StageLost` once a stage stays silent
  ``dead_after`` accepted steps. No host sync on the healthy path —
  the heartbeat rides the same aux fetch the numeric ladder already
  reads.

* :func:`replan_after_loss` — recovery. Re-cut the layer balance over
  the ``n-1`` survivors (:func:`~pipe_tpu.core.balance
  .rebalance_stage_loss`), re-emit and re-verify the op table for the
  new width (:func:`~pipe_tpu.core.schedule.replan_stage_loss` —
  schedules as data mean recovery is a fresh emission plus the same
  proofs every table must pass), rebuild the Trainer on the survivor
  devices, restore from the buddy snapshot, regroup the stage stacking
  (:func:`restack_state` — init keys are GLOBAL-layer-indexed, so the
  regrouped params are bitwise the params a born-``n-1``-stage run
  would hold), and resume mid-epoch at the snapshot step.

:func:`train_elastic` drives the whole ladder: train → StageLost →
re-plan → resume, bounded by ``max_replans``, aborting loudly
(:class:`~.recover.TrainingAborted`) when no survivor topology exists.
``TrainerConfig.elastic=None`` (the default) constructs none of this
and the train step lowers byte-identical to the non-elastic build
(pinned in tests/test_elastic.py).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, List, Optional

import jax
import numpy as np

from ..obs.events import RECOVERY

__all__ = ["ElasticConfig", "StageLost", "BuddyStore", "ElasticController",
           "restack_state", "replan_after_loss", "train_elastic"]


class StageLost(RuntimeError):
    """A pipeline stage is persistently silent — escalate to re-plan."""

    def __init__(self, stage: int, detected_step: int,
                 snapshot_step: Optional[int]):
        super().__init__(
            f"pipeline stage {stage} persistently silent at step "
            f"{detected_step} (last buddy snapshot: "
            f"{'step ' + str(snapshot_step) if snapshot_step is not None else 'none'})")
        self.stage = stage
        self.detected_step = detected_step
        self.snapshot_step = snapshot_step


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Elastic-training knobs (``TrainerConfig.elastic``; None — the
    default — keeps the train step bitwise identical to the guarded
    build and constructs no buddy machinery)."""

    # buddy-replication cadence (accepted steps between captures; a
    # capture is skipped while any anomaly or silent streak is live —
    # only an all-healthy state is worth replicating)
    snapshot_every: int = 10
    # consecutive guard-accepted steps a stage's gradient heartbeat
    # must stay at exactly zero before the controller declares it dead
    dead_after: int = 2
    # host cadence for reading the heartbeat vector (shares the sync
    # the numeric ladder already pays at its own check_every)
    check_every: int = 1
    # verify every buddy capture bitwise against the source shards
    # (per-stage sha256; cheap at snapshot cadence, and the pin that
    # makes restore-from-buddy trustworthy)
    verify_replication: bool = True
    # how many stage losses one run may survive before aborting
    max_replans: int = 1
    # optional directory receiving a fsync'd buddy manifest JSON per
    # capture (train.state.write_buddy_manifest) for post-crash audit
    snapshot_dir: Optional[str] = None

    def __post_init__(self):
        if self.snapshot_every < 1 or self.dead_after < 1 \
                or self.check_every < 1:
            raise ValueError(
                "snapshot_every, dead_after and check_every must all "
                "be >= 1")
        if self.max_replans < 0:
            raise ValueError(
                f"max_replans must be >= 0, got {self.max_replans}")


def _is_staged(leaf, n_stages: int) -> bool:
    """True when ``leaf`` is mesh-placed with the stage axis leading —
    the shards the buddy ring must replicate. Replicated leaves (prep,
    postp, Adam's count, the step counter) every survivor already
    holds."""
    from jax.sharding import NamedSharding

    from ..parallel.mesh import STAGE_AXIS

    if not isinstance(leaf, jax.Array):
        return False
    sharding = getattr(leaf, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return False
    spec = sharding.spec
    return len(spec) > 0 and spec[0] == STAGE_AXIS


class BuddyStore:
    """Distributed in-memory checkpoint: each stage's shard, captured
    via one ppermute hop to its ring buddy and pinned by per-stage
    sha256 manifests. One store per Trainer (``Trainer.elastic_store``)
    so the snapshot survives the :class:`StageLost` raise."""

    def __init__(self, mesh, n_stages: int, *, verify: bool = True,
                 registry=None, events=None,
                 snapshot_dir: Optional[str] = None):
        self.mesh = mesh
        self.n = int(n_stages)
        self.verify = verify
        self.registry = registry
        self.events = events
        self.snapshot_dir = snapshot_dir
        self.snapshots = 0
        self._ring = None
        self._step: Optional[int] = None
        self._treedef = None
        self._staged_idx: Optional[List[int]] = None
        self._buddy: Optional[List[np.ndarray]] = None
        self._repl: Optional[List[Any]] = None
        self._manifest: Optional[dict] = None

    # -- the buddy ring ------------------------------------------------------

    def _ring_fn(self):
        """One jitted ppermute shifting every stage's shard to ring
        neighbor ``(j+1) % n`` along the stage axis — the same
        collective the boundary transport rides, as a separate
        low-frequency ring."""
        if self._ring is None:
            from jax.sharding import PartitionSpec as P

            from ..parallel.mesh import STAGE_AXIS
            from ..utils.compat import shard_map

            perm = [(i, (i + 1) % self.n) for i in range(self.n)]

            def send(xs):
                return [jax.lax.ppermute(x, STAGE_AXIS, perm) for x in xs]

            self._ring = jax.jit(shard_map(
                send, mesh=self.mesh, in_specs=P(STAGE_AXIS),
                out_specs=P(STAGE_AXIS)))
        return self._ring

    # -- capture / restore ---------------------------------------------------

    @property
    def step(self) -> Optional[int]:
        """Global batch index of the captured snapshot (None = none)."""
        return self._step

    @property
    def has_snapshot(self) -> bool:
        return self._step is not None

    def capture(self, state, step: int) -> None:
        """Replicate every stage-sharded leaf of ``state`` to its buddy
        and host-fetch the copies, with the replicated remainder (and a
        per-stage manifest) alongside. With ``verify`` the buddy copies
        are re-hashed against the source shards — a diverged hop fails
        loudly at capture time, never at restore."""
        from ..train.state import stage_shard_manifest, write_buddy_manifest

        flat, treedef = jax.tree_util.tree_flatten(state)
        staged_idx = [k for k, leaf in enumerate(flat)
                      if _is_staged(leaf, self.n)]
        staged_set = set(staged_idx)
        staged = [flat[k] for k in staged_idx]
        if not staged:
            raise RuntimeError(
                "BuddyStore.capture: no stage-sharded leaves in the "
                "state — is this trainer's mesh stage-partitioned?")
        rolled = self._ring_fn()(staged)
        buddy = [np.asarray(x) for x in rolled]
        repl = [np.asarray(flat[k]) if isinstance(flat[k], jax.Array)
                else flat[k]
                for k in range(len(flat)) if k not in staged_set]
        unroll = [(j + 1) % self.n for j in range(self.n)]
        recovered = [np.take(a, unroll, axis=0) for a in buddy]
        manifest = stage_shard_manifest(recovered, self.n)
        if self.verify:
            src = stage_shard_manifest([np.asarray(x) for x in staged],
                                       self.n)
            for j in range(self.n):
                if manifest[str(j)] != src[str(j)]:
                    raise RuntimeError(
                        f"buddy copy of stage {j}'s shard diverged from "
                        f"the source at capture (step {step}) — the "
                        f"replication ring is corrupting data")
        self._treedef = treedef
        self._staged_idx = staged_idx
        self._buddy = buddy
        self._repl = repl
        self._manifest = manifest
        self._step = int(step)
        self.snapshots += 1
        if self.registry is not None:
            self.registry.counter("resilience.elastic.snapshots").inc()
        if self.events is not None:
            self.events.event(RECOVERY, action="buddy_capture", step=step,
                              stages=self.n, verified=self.verify)
        if self.snapshot_dir is not None:
            write_buddy_manifest(self.snapshot_dir, int(step), manifest,
                                 self.n)

    def restore_state(self):
        """Reassemble the FULL state at the snapshot step from the
        buddy copies (stage ``j``'s shard read back from ring position
        ``(j+1) % n``), re-verified against the capture manifest.
        Returns a host (numpy-leaved) pytree in the captured tree
        structure — feed it to :func:`restack_state` + device_put."""
        from ..train.state import stage_shard_manifest

        if not self.has_snapshot:
            raise RuntimeError("no buddy snapshot captured yet")
        unroll = [(j + 1) % self.n for j in range(self.n)]
        recovered = [np.take(a, unroll, axis=0) for a in self._buddy]
        got = stage_shard_manifest(recovered, self.n)
        for j in range(self.n):
            if got[str(j)] != self._manifest[str(j)]:
                raise RuntimeError(
                    f"buddy shard for stage {j} failed its manifest pin "
                    f"at restore (snapshot step {self._step}) — refusing "
                    f"to resume on corrupt state")
        flat: List[Any] = []
        staged_it = iter(recovered)
        repl_it = iter(self._repl)
        staged_set = set(self._staged_idx)
        for k in range(self._treedef.num_leaves):
            flat.append(next(staged_it) if k in staged_set
                        else next(repl_it))
        if self.registry is not None:
            self.registry.counter("resilience.elastic.restores").inc()
        if self.events is not None:
            self.events.event(RECOVERY, action="buddy_restore",
                              step=self._step, stages=self.n)
        return jax.tree_util.tree_unflatten(self._treedef, flat)


class ElasticController:
    """Host half of the elastic rung: buddy-capture cadence and the
    dead-stage verdict. ``after_step`` mirrors
    ``ResilienceController.after_step`` and runs right after it."""

    def __init__(self, cfg: ElasticConfig, store: BuddyStore, *,
                 registry=None, events=None,
                 log_fn: Callable[[str], None] = print):
        self.cfg = cfg
        self.store = store
        self.registry = registry
        self.events = events
        self.log_fn = log_fn

    @property
    def snapshots(self) -> int:
        return self.store.snapshots

    def after_step(self, b: int, state, aux):
        """Read the heartbeat on the check cadence. Captures a buddy
        snapshot when the state is all-healthy on the snapshot cadence;
        raises :class:`StageLost` when any stage's silent streak
        reaches ``dead_after``. Returns ``(state, aux)`` unchanged
        otherwise."""
        cfg = self.cfg
        if (b + 1) % cfg.check_every:
            return state, aux
        hb = np.asarray(aux[3])     # the host sync point (check cadence)
        consec = int(aux[1])
        dead = np.nonzero(hb >= cfg.dead_after)[0]
        if dead.size:
            # A kill at stage j silences every stage <= j (zero output
            # kills the backward signal upstream of the cut): the
            # LARGEST silent index localizes the dead stage.
            stage = int(dead.max())
            snap = self.store.step
            if self.registry is not None:
                self.registry.counter("resilience.elastic.stage_lost").inc()
            if self.events is not None:
                self.events.event(RECOVERY, action="stage_lost",
                                  stage=stage, step=b, snapshot_step=snap,
                                  silent_steps=int(hb[stage]))
            self.log_fn(
                f"| elastic: stage {stage} silent {int(hb[stage])} "
                f"accepted steps at step {b} -> StageLost "
                f"(buddy snapshot @ {snap})")
            raise StageLost(stage, b, snap)
        if consec == 0 and not hb.any():
            if not self.store.has_snapshot \
                    or (b + 1) % cfg.snapshot_every == 0:
                self.store.capture(state, b)
        return state, aux


# ---------------------------------------------------------------------------
# Restacking: regroup an n-stage stacked state over n-1 stages
# ---------------------------------------------------------------------------

def _restack_blocks(stacked: List[Any], n_old: int, n_new: int) -> List[Any]:
    """Regroup a stage-stacked block list (``len = layers_per_stage``
    entries, every leaf leading with ``n_old``) over ``n_new`` stages.
    Pure host-side reshuffling: global layer ``g = s * lps + l`` keeps
    its exact bytes, only the (stage, slot) coordinates move."""
    lps_old = len(stacked)
    total = n_old * lps_old
    if total % n_new:
        raise ValueError(
            f"{total} layers do not regroup over {n_new} stages "
            f"(uniform stage bodies need n_layers % n_stages == 0)")
    lps_new = total // n_new
    layers = []
    for s in range(n_old):
        for l in range(lps_old):
            layers.append(jax.tree_util.tree_map(
                lambda a, _s=s: np.asarray(a)[_s], stacked[l]))
    out = []
    for l in range(lps_new):
        blocks = [layers[s * lps_new + l] for s in range(n_new)]
        out.append(jax.tree_util.tree_map(
            lambda *xs: np.stack(xs, 0), *blocks))
    return out


def _restack_params_like(tpl, n_old: int, n_new: int):
    sp, pre, post = tpl
    return (_restack_blocks(list(sp), n_old, n_new), pre, post)


def restack_state(state, n_old: int, n_new: int):
    """Regroup a host-side n_old-stage TrainState over ``n_new`` stages:
    the stacked params AND the Adam moments mirroring them (found
    structurally — any optax chain entry carrying ``mu``/``nu``);
    replicated leaves (prep/postp, count, step) pass through untouched.

    Because ``PipelinedLM.init`` keys every block by its GLOBAL layer
    index, the restacked params are bitwise the params a freshly-built
    ``n_new``-stage model would initialize to had it trained the same
    tape — the property the elastic acceptance pin rides.
    """
    from ..train.state import TrainState

    params = _restack_params_like(state.params, n_old, n_new)
    new_opt = []
    for entry in state.opt_state:
        if hasattr(entry, "mu") and hasattr(entry, "nu"):
            entry = entry._replace(
                mu=_restack_params_like(entry.mu, n_old, n_new),
                nu=_restack_params_like(entry.nu, n_old, n_new))
        new_opt.append(entry)
    return TrainState(params=params, opt_state=tuple(new_opt),
                      step=state.step)


# ---------------------------------------------------------------------------
# Recovery driver
# ---------------------------------------------------------------------------

def replan_after_loss(trainer, lost: StageLost, *,
                      log_fn: Callable[[str], None] = print):
    """Rebuild the run over the ``n-1`` survivors after a stage loss.

    Verifies the degraded topology (op table emission + proofs via
    :func:`~pipe_tpu.core.schedule.replan_stage_loss`, balance re-cut),
    constructs a new Trainer on the survivor devices (the dead stage's
    mesh row is dropped), restores + restacks the buddy snapshot, and
    returns ``(new_trainer, restored_state, start_step)`` ready for
    ``train_epoch(..., start_step=start_step)``. Raises
    :class:`~.recover.TrainingAborted` when no survivor topology
    exists — the final rung of the ladder.
    """
    from ..core.schedule import replan_stage_loss
    from .recover import TrainingAborted

    t0 = time.perf_counter()
    cfg = trainer.cfg
    n = cfg.n_stages
    n_new = n - 1
    store = trainer.elastic_store()
    if n_new < 2:
        raise TrainingAborted(
            f"stage {lost.stage} lost with only {n} stages — no pipeline "
            f"survives the re-plan")
    n_layers = trainer.model_cfg.n_layers
    if n_layers % n_new:
        raise TrainingAborted(
            f"stage {lost.stage} lost but {n_layers} layers do not "
            f"regroup over {n_new} survivors (uniform stage bodies)")
    if not store.has_snapshot:
        raise TrainingAborted(
            f"stage {lost.stage} lost at step {lost.detected_step} before "
            f"the first buddy snapshot — nothing to restore from")
    plan = replan_stage_loss(
        cfg.chunks, n, lost.stage, schedule=cfg.schedule,
        balance=[n_layers // n] * n)
    # Survivor devices: drop the dead stage's row from the mesh so the
    # new (n-1)-stage mesh reuses exactly the chips that still answer.
    surv = np.delete(np.asarray(trainer.mesh.devices), lost.stage,
                     axis=0).reshape(-1).tolist()
    new_cfg = dataclasses.replace(cfg, n_stages=n_new)
    new_chaos = (trainer.chaos.without("kill_stage")
                 if trainer.chaos is not None else None)
    new_tr = type(trainer)(trainer.model_cfg, new_cfg, devices=surv,
                           chaos=new_chaos)
    template = new_tr.init_state()
    host = store.restore_state()
    host_new = restack_state(host, n, n_new)
    restored = jax.tree_util.tree_map(
        lambda h, t: (jax.device_put(np.asarray(h), t.sharding)
                      if isinstance(t, jax.Array) else h),
        host_new, template)
    start_step = store.step + 1
    lost_steps = lost.detected_step - store.step
    dt = time.perf_counter() - t0
    registry = trainer.registry
    registry.counter("resilience.elastic.replans").inc()
    registry.counter("resilience.elastic.lost_steps").inc(max(lost_steps, 0))
    registry.gauge("resilience.elastic.recovery_s").set(dt)
    trainer.events.event(
        RECOVERY, action="replan", stage=lost.stage, n_stages=n_new,
        balance=list(plan.balance or ()), schedule=cfg.schedule,
        phase_ok=plan.phase.accepted, snapshot_step=store.step,
        resume_step=start_step, lost_steps=lost_steps, recovery_s=dt)
    log_fn(f"| elastic: re-planned {n}->{n_new} stages after losing "
           f"stage {lost.stage} (balance {list(plan.balance or ())}, "
           f"table verified, phase "
           f"{'ok' if plan.phase.accepted else 'rejected'}); resuming "
           f"from buddy snapshot @ step {store.step} "
           f"({lost_steps} steps lost, {dt:.2f}s recovery)")
    return new_tr, restored, start_step


def train_elastic(trainer, source, *, epoch: int = 0, state=None,
                  max_steps: Optional[int] = None, log_every: int = 0,
                  log_fn: Callable[[str], None] = print):
    """Run an epoch under the full ladder: train, and on
    :class:`StageLost` re-plan over the survivors and resume, up to
    ``ElasticConfig.max_replans`` times (then
    :class:`~.recover.TrainingAborted`). Returns ``(trainer, state,
    info)`` — the trainer may be a NEW, narrower instance after a
    recovery; ``info['recoveries']`` records each one."""
    from .recover import TrainingAborted

    start = 0
    history: List[dict] = []
    while True:
        try:
            state, info = trainer.train_epoch(
                source, epoch, state, max_steps=max_steps,
                log_every=log_every, log_fn=log_fn, start_step=start)
            info["replans"] = len(history)
            info["recoveries"] = history
            return trainer, state, info
        except StageLost as lost:
            max_replans = getattr(trainer.cfg.elastic, "max_replans", 1)
            if len(history) >= max_replans:
                raise TrainingAborted(
                    f"stage {lost.stage} lost at step "
                    f"{lost.detected_step} after {len(history)} re-plans "
                    f"(max_replans={max_replans})") from lost
            t0 = time.perf_counter()
            trainer, state, start = replan_after_loss(trainer, lost,
                                                      log_fn=log_fn)
            history.append({
                "stage": lost.stage,
                "detected_step": lost.detected_step,
                "snapshot_step": lost.snapshot_step,
                "resume_step": start,
                "lost_steps": lost.detected_step - (lost.snapshot_step or 0),
                "n_stages": trainer.cfg.n_stages,
                "recovery_s": time.perf_counter() - t0,
            })
