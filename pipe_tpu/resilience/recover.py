"""Recovery policies: skip-step aux state, bounded rewind, data retry.

The recovery state machine (docs/resilience.md):

1. **Skip-step** — an anomalous step's params/optimizer update is
   dropped *inside* the jitted step (``where``-select against the
   pre-step state, ``train/loop.py``); the device-side aux carry
   ``(loss EWMA, consecutive anomalies, total anomalies)`` tracks it
   with no host involvement.
2. **Rewind** — when anomalies persist (``consec >= rewind_after``) the
   host restores the last known-good in-memory snapshot; each
   successive rewind sleeps an exponentially longer backoff, and after
   ``max_rewinds`` the controller raises :class:`TrainingAborted` —
   loud failure beats silently looping on poisoned state.
3. **Data retry** — :class:`RetryingIterator` rebuilds a failed batch
   iterator at its last position with exponential backoff; exhausting
   the budget raises :class:`DataIteratorFailed`.

Everything host-side here is clock- and sleep-injectable so the chaos
tests run deterministically without real waiting.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Iterator, Optional, Tuple

__all__ = ["ResilienceConfig", "ResilienceController", "RetryingIterator",
           "TrainingAborted", "DataIteratorFailed"]


class TrainingAborted(RuntimeError):
    """Anomalies persisted through the rewind budget — the run cannot
    make progress and refuses to pretend otherwise."""


class DataIteratorFailed(RuntimeError):
    """The data iterator kept failing past the retry budget."""


@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    """Detection + recovery knobs (``TrainerConfig.resilience``; None —
    the default — keeps the train step bitwise identical to the
    unguarded build)."""

    # detection (traced into the step; detect.step_guard)
    spike_factor: float = 4.0     # loss > factor * EWMA => anomaly
    warmup_steps: int = 10        # spike check disarmed before this step
    ewma_alpha: float = 0.1       # loss-EWMA horizon (~10 accepted steps)
    # host cadence: read the device verdict every N steps (1 = every
    # step; >1 trades detection latency for fewer host syncs on
    # async-dispatch backends)
    check_every: int = 1
    # rewind policy
    rewind_after: int = 3         # consecutive anomalies => rewind
    max_rewinds: int = 3          # then TrainingAborted
    rewind_backoff_s: float = 0.0  # sleep 2**k * this before rewind k
    snapshot_every: int = 10      # known-good snapshot cadence (steps)
    # data-iterator retry
    data_retries: int = 3
    data_backoff_s: float = 0.05

    def __post_init__(self):
        if self.spike_factor <= 1.0:
            raise ValueError(
                f"spike_factor must be > 1, got {self.spike_factor}")
        if self.check_every < 1 or self.rewind_after < 1 \
                or self.snapshot_every < 1:
            raise ValueError(
                "check_every, rewind_after and snapshot_every must all "
                "be >= 1")
        if self.max_rewinds < 0 or self.data_retries < 0:
            raise ValueError(
                "max_rewinds and data_retries must be >= 0")


def _copy_tree(tree):
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda a: jnp.array(a, copy=True) if isinstance(a, jax.Array)
        else a, tree)


class ResilienceController:
    """Host half of the recovery loop: owns the known-good snapshot,
    the rewind budget/backoff, and the resilience.* counters/events.

    ``after_step`` is called by ``Trainer.train_epoch`` after each
    guarded step with the fresh ``(state, aux)``; it reads the device
    verdict every ``check_every`` steps and returns the (possibly
    rewound) pair. ``aux`` is the device carry
    ``(loss_ewma f32, consec i32, anomalies i32)``.
    """

    def __init__(self, cfg: ResilienceConfig, registry, events,
                 log_fn: Callable[[str], None] = print,
                 sleep: Callable[[float], None] = time.sleep):
        self.cfg = cfg
        self.registry = registry
        self.events = events
        self.log_fn = log_fn
        self.sleep = sleep
        self.rewinds = 0
        self._snapshot: Optional[Tuple[Any, Any]] = None
        self._snapshot_step: Optional[int] = None
        self._seen_anomalies = 0

    @property
    def anomalies(self) -> int:
        """Total anomalous (skipped) steps observed so far."""
        return self._seen_anomalies

    # -- snapshots ----------------------------------------------------------

    def snapshot(self, state, aux, step: int) -> None:
        """Record (a copy of) a known-good state; never called with an
        anomalous one (the loop snapshots only at consec == 0)."""
        self._snapshot = (_copy_tree(state), _copy_tree(aux))
        self._snapshot_step = step

    # -- the per-step hook --------------------------------------------------

    def after_step(self, b: int, state, aux):
        """Inspect the verdict (on the check cadence), apply the rewind
        policy, refresh the snapshot. Returns ``(state, aux)`` —
        rewound copies when the policy fired, the inputs otherwise."""
        cfg = self.cfg
        if (b + 1) % cfg.check_every:
            return state, aux
        _, consec_a, total_a = aux
        consec = int(consec_a)      # the host sync point (check cadence)
        total = int(total_a)
        if total > self._seen_anomalies:
            fresh = total - self._seen_anomalies
            self._seen_anomalies = total
            self.registry.counter("resilience.anomalies").inc(fresh)
            self.registry.counter("resilience.skipped_steps").inc(fresh)
            self.events.event("resilience", action="skip_step", step=b,
                              consecutive=consec, total=total)
        if consec == 0:
            if self._snapshot is None or (b + 1) % cfg.snapshot_every == 0:
                self.snapshot(state, aux, b)
            return state, aux
        if consec < cfg.rewind_after:
            return state, aux
        # persistent anomalies: rewind (bounded, exponential backoff)
        if self.rewinds >= cfg.max_rewinds:
            raise TrainingAborted(
                f"{consec} consecutive anomalous steps at step {b} after "
                f"{self.rewinds} rewinds (max_rewinds="
                f"{cfg.max_rewinds}) — refusing to continue on "
                f"persistently poisoned state")
        if self._snapshot is None:
            raise TrainingAborted(
                f"{consec} consecutive anomalous steps at step {b} with "
                f"no known-good snapshot to rewind to")
        backoff = cfg.rewind_backoff_s * (2 ** self.rewinds)
        if backoff > 0:
            self.sleep(backoff)
        self.rewinds += 1
        self.registry.counter("resilience.rewinds").inc()
        self.events.event("resilience", action="rewind", step=b,
                          to_step=self._snapshot_step,
                          rewind=self.rewinds, backoff_s=backoff)
        self.log_fn(f"| resilience: rewind #{self.rewinds} at step {b} "
                    f"-> snapshot of step {self._snapshot_step} "
                    f"({consec} consecutive anomalies)")
        snap_state, snap_aux = self._snapshot
        # hand out copies: the step donates its state input, and the
        # snapshot must survive further rewinds
        ewma, _, _ = snap_aux
        import jax.numpy as jnp
        fresh_aux = (_copy_tree(ewma), jnp.int32(0),
                     jnp.int32(self._seen_anomalies))
        return _copy_tree(snap_state), fresh_aux


class RetryingIterator:
    """Iterator wrapper that rebuilds a failed source at its position.

    ``factory(pos)`` must return an iterator yielding items from index
    ``pos`` on (``Trainer._batches(..., start=pos)`` has exactly this
    shape). ``StopIteration`` passes through; any other exception —
    including injected :class:`~.chaos.ChaosError`\\ s via ``chaos`` —
    burns one retry, sleeps an exponential backoff, and rebuilds.
    """

    def __init__(self, factory: Callable[[int], Iterator], *,
                 retries: int = 3, backoff_s: float = 0.05,
                 chaos=None, registry=None, events=None,
                 sleep: Callable[[float], None] = time.sleep,
                 start: int = 0):
        self._factory = factory
        self._retries = retries
        self._backoff_s = backoff_s
        self._chaos = chaos
        self._registry = registry
        self._events = events
        self._sleep = sleep
        self._it: Optional[Iterator] = None
        # ``start`` seeds the position for mid-epoch resumption (the
        # elastic path): chaos/data indices stay GLOBAL batch indices.
        self._pos = int(start)

    def __iter__(self) -> "RetryingIterator":
        return self

    def __next__(self):
        last: Optional[Exception] = None
        for attempt in range(self._retries + 1):
            try:
                if self._it is None:
                    self._it = self._factory(self._pos)
                if self._chaos is not None and attempt == 0:
                    self._chaos.maybe_raise_data(self._pos)
                item = next(self._it)
                self._pos += 1
                return item
            except StopIteration:
                raise
            except Exception as e:           # noqa: BLE001 — retry scope
                last = e
                self._it = None              # rebuild from _pos
                if self._registry is not None:
                    self._registry.counter("resilience.data_retries").inc()
                if self._events is not None:
                    self._events.event("resilience", action="data_retry",
                                       batch=self._pos, attempt=attempt,
                                       error=type(e).__name__)
                if attempt < self._retries:
                    self._sleep(self._backoff_s * (2 ** attempt))
        raise DataIteratorFailed(
            f"data iterator failed {self._retries + 1} times at batch "
            f"{self._pos} (last: {type(last).__name__}: {last})")
