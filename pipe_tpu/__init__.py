"""pipe_tpu — TPU-native synchronous pipeline parallelism.

A brand-new framework with the capabilities of
``torch.distributed.pipeline.sync.Pipe`` (torchgpipe lineage), re-designed for
TPU: one compiled JAX/XLA program under a ``(stage, data)`` mesh, where
``lax.ppermute`` over ICI replaces CUDA-stream P2P copies, the compiled
clock-cycle schedule replaces worker threads and autograd-embedded
Wait/Copy/Fork/Join nodes, and ``jax.checkpoint`` replaces the
Checkpoint/Recompute machinery. See SURVEY.md for the structural analysis of
the reference and the capability map.
"""

from .core import microbatch
from .core.microbatch import Batch, NoChunk, gather, scatter
from .core.partition import BalanceError, Stage, StageCtx
from .core.schedule import (GPipeSchedule, InterleavedSchedule,
                            OneFOneBSchedule, ZeroBubbleSchedule,
                            clock_cycles, get_schedule)
from .ops.layers import (Decoder, Dropout, Embedding, Lambda, LayerNorm,
                         Linear, Module, MultiHeadAttention,
                         PositionalEncoding, Sequential,
                         TransformerEncoderLayer)
from .core.planner import CostProfile, Plan, auto_plan
from .inference import GenerationConfig, Generator, PipelinedGenerator
from .pipe import Pipe

__version__ = "0.1.0"

__all__ = [
    "Pipe", "NoChunk", "Batch", "BalanceError", "Stage", "StageCtx",
    "scatter", "gather", "microbatch",
    "GPipeSchedule", "OneFOneBSchedule", "InterleavedSchedule",
    "ZeroBubbleSchedule", "clock_cycles", "get_schedule",
    "Module", "Sequential", "Lambda", "Linear", "Embedding", "LayerNorm",
    "Dropout", "MultiHeadAttention", "TransformerEncoderLayer",
    "PositionalEncoding", "Decoder",
    "GenerationConfig", "Generator", "PipelinedGenerator",
    "CostProfile", "Plan", "auto_plan",
]
