"""Prompt-length bucketing: a small, closed set of prefill shapes.

XLA compiles one program per input shape. A serving workload feeds
arbitrary prompt lengths, so prefilling at the raw length would compile
an unbounded family of programs — the per-shape jit cache blindspot the
serve telemetry now counts (``serve.program_cache_entries``). The fix is
the standard one: round every prompt length up to the nearest member of
a fixed bucket set and right-pad. The engine then compiles at most
``len(lengths)`` prefill programs, ever.

Right-padding is safe by the causal mask: ``MultiHeadAttention.decode``
masks ``kpos > qpos`` at -1e30, so pad rows past the true length never
influence real positions, and decode overwrites each padded cache row
before the first step that could attend to it. ``tests/test_serve.py``
pins bucketed-prefill output token-for-token against the unpadded
one-shot ``Generator``.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

__all__ = ["BucketSpec"]


@dataclasses.dataclass(frozen=True)
class BucketSpec:
    """Sorted, fixed set of prompt lengths the engine compiles for.

    ``bucket_for(p)`` returns the smallest bucket >= p and raises when
    the prompt exceeds the largest bucket — admission control rejects
    what it cannot serve instead of silently recompiling.
    """

    lengths: Tuple[int, ...]

    def __post_init__(self):
        if not self.lengths:
            raise ValueError("BucketSpec needs at least one length")
        lens = tuple(sorted(set(int(x) for x in self.lengths)))
        if lens[0] < 1:
            raise ValueError(f"bucket lengths must be >= 1, got {lens}")
        object.__setattr__(self, "lengths", lens)

    @classmethod
    def of(cls, *lengths: int) -> "BucketSpec":
        return cls(tuple(lengths))

    @classmethod
    def pow2(cls, min_len: int = 8, max_len: int = 512) -> "BucketSpec":
        """Powers of two in [min_len, max_len] — at most 2x padding waste
        per prompt, log2(max/min)+1 compiled prefill programs."""
        if min_len < 1 or max_len < min_len:
            raise ValueError(
                f"need 1 <= min_len <= max_len, got {min_len}, {max_len}")
        out, b = [], 1
        while b < min_len:
            b *= 2
        while b <= max_len:
            out.append(b)
            b *= 2
        if not out or out[-1] < max_len:
            out.append(max_len)
        return cls(tuple(out))

    @property
    def max_len(self) -> int:
        return self.lengths[-1]

    def bucket_for(self, prompt_len: int) -> int:
        if prompt_len < 1:
            raise ValueError(f"prompt_len must be >= 1, got {prompt_len}")
        for b in self.lengths:
            if b >= prompt_len:
                return b
        raise ValueError(
            f"prompt_len {prompt_len} exceeds the largest bucket "
            f"{self.lengths[-1]}; admit shorter prompts or widen the spec")

    def pad(self, prompt: Sequence[int],
            pad_token_id: int = 0) -> Tuple[list, int]:
        """``(padded ids of bucket length, true length)``."""
        p = len(prompt)
        b = self.bucket_for(p)
        return list(prompt) + [int(pad_token_id)] * (b - p), p
