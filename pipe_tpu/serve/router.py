"""Fleet front door: N serve-engine replicas behind one request queue.

The placement/health/exactly-once state machine PR 7 built here now
lives in :mod:`~..fleet.control` (the transport-agnostic
:class:`~..fleet.control.FleetController`), where it coordinates both
in-process engines and real OS-process replicas
(:mod:`~..fleet.proc`). This module keeps the original engine-facing
constructor — :class:`Router` is the controller over
:class:`~..fleet.control.InProcessTransport` wrappers, one per engine —
so every existing caller and the pinned ``tests/test_router.py`` suite
run unchanged, byte-for-byte:

* **One front queue, N replica queues.** Callers submit to the router's
  bounded :class:`~.queue.RequestQueue` (ids are fleet-unique — replica
  queues never mint ids); each tick the router places waiting requests
  onto HEALTHY replicas, least-loaded or session-affine. Deadlines,
  priorities and cancellation ride the *same* :class:`~.queue.Request`
  object end-to-end: ``submitted_at``/``deadline`` are set once at
  submit and survive every re-queue, so a failed-over request never
  regains deadline credit, and ``cancel`` is one flag flip wherever the
  request currently sits (front, parked for retry, replica queue, or a
  live slot).

* **A health state machine per replica**, driven entirely by signals
  the engines already export — the :class:`~..resilience.TickWatchdog`
  read-only surface (``slow_streak``, ``miss_ewma``) plus
  ``ServeEngine.consecutive_decode_errors`` and retryable-failure
  responses. States::

      HEALTHY --(slow streak / decode error / retryable failure)--> SUSPECT
      SUSPECT --(recover_healthy_ticks clean ticks)--> HEALTHY
      HEALTHY|SUSPECT --(wedge thresholds)--> WEDGED
      WEDGED --(queued work evicted, drain() issued)--> DRAINING
      DRAINING --(engine.drained)--> RETIRED

  SUSPECT only stops *placement* (hysteresis: transient stalls must not
  flap work across the fleet); WEDGED is one-way — the replica's queued
  requests are reclaimed intact (``evict_queued``) and its live slots
  run out under ``drain()``.

* **Retry budgets, not retry storms.** A request bounced by a wedged or
  erroring replica (``finish_reason`` ``backend_error``/``stuck``) is
  parked with exponential backoff (``backoff_base_s * 2^(attempts-1)``,
  capped) and re-placed on a healthy replica while
  ``attempts < retry_budget`` (attempts counts placements). Budget
  exhausted → one terminal ``status="error"`` /
  ``finish_reason="retries_exhausted"`` response. Every submitted id
  yields **exactly one** terminal :class:`~.queue.Response` through the
  router — a duplicate delivery raises, and ``tests/test_router.py``
  pins the exactly-once ledger under ``kill_replica`` chaos.

* **Lifecycle**: ``spawn_fn`` adds a replica after the front queue sits
  at ``spawn_depth`` for ``spawn_sustain_ticks`` consecutive ticks;
  ``retire_idle_ticks`` drains replicas the traffic no longer needs
  (never below ``min_replicas``). Both are host decisions between
  ticks; compiled programs are untouched.

The router is strictly additive: not constructing one changes nothing
anywhere (``apps/serve.py`` keeps the direct single-engine path, and
the engines' decode HLO is byte-identical — same opt-out-is-absent
discipline as the resilience layer). The default serial mode is
single-threaded like the engine tick loop; ``async_tick=True`` gives
each replica its own tick thread (:class:`~..fleet.control
.InProcessTransport` async mode), so one slow replica no longer stalls
its siblings — the fleet ``tick()`` then only sweeps/places/delivers.
Replica chaos (``wedge_replica``/``kill_replica``/``slow_replica``)
wraps the replica backends only when a
:class:`~..resilience.ChaosPlan` is passed.
"""

from __future__ import annotations

import functools
import time
from typing import Callable, Optional, Sequence

from ..fleet.control import (DRAINING, HEALTHY, RETIRED, RETRYABLE_REASONS,
                             STATES, SUSPECT, WEDGED, _STATE_CODE,
                             FleetController, InProcessTransport, Replica,
                             RouterPolicy)
from .engine import ServeEngine
from .queue import RequestQueue

__all__ = ["Router", "RouterPolicy", "Replica",
           "HEALTHY", "SUSPECT", "WEDGED", "DRAINING", "RETIRED"]

# re-exported for callers that imported them from here
_ = (STATES, _STATE_CODE, RETRYABLE_REASONS)


class Router(FleetController):
    """Shard one front :class:`~.queue.RequestQueue` across N
    :class:`~.engine.ServeEngine` replicas with health-gated failover.

    ``engines`` must be homogeneous (same model/buckets/caps — admission
    validation uses replica 0's backend) and each must own its own
    queue on the *same clock* as the front queue. ``spawn_fn`` (if
    given) builds one more engine on demand for the spawn hook.
    ``chaos`` arms replica-level fault injection
    (:data:`~..resilience.chaos.REPLICA_KINDS`, addressed by
    ``Fault.stage`` = replica index); None leaves the backends
    untouched. ``async_tick=True`` runs each replica under its own tick
    thread instead of the serial per-``tick()`` round-robin.

    The surface mirrors :class:`~.engine.ServeEngine` — ``submit`` /
    ``tick`` / ``cancel`` / ``response`` / ``drain`` / ``idle`` /
    ``run_until_idle`` — so drivers (``apps/serve.py``) swap one for
    the other without restructuring their loop.
    """

    def __init__(self, engines: Sequence[ServeEngine],
                 queue: Optional[RequestQueue] = None, *,
                 policy: RouterPolicy = RouterPolicy(),
                 spawn_fn: Optional[Callable[[], ServeEngine]] = None,
                 chaos=None, event_log=None,
                 clock: Optional[Callable[[], float]] = None,
                 async_tick: bool = False):
        engines = list(engines)
        if not engines:
            raise ValueError("Router needs at least one engine replica")
        if queue is None:
            queue = RequestQueue(clock=clock or time.monotonic)
        elif clock is not None and clock is not queue.clock:
            raise ValueError(
                "pass the clock on the queue (router adopts queue.clock)")
        seen = set()
        for eng in engines:
            if eng.queue is queue:
                raise ValueError(
                    "a replica engine may not share the router's front "
                    "queue (the router owns placement)")
            if id(eng.queue) in seen:
                raise ValueError(
                    "replica engines may not share a queue with each "
                    "other (each replica owns its backlog)")
            seen.add(id(eng.queue))
            if eng.clock is not queue.clock:
                raise ValueError(
                    "every replica engine must run on the front queue's "
                    "clock (deadlines are absolute in one clock domain)")
        self.chaos = chaos
        self.async_tick = bool(async_tick)
        wrapped_spawn = None
        if spawn_fn is not None:
            def wrapped_spawn():
                return InProcessTransport(spawn_fn(),
                                          async_tick=self.async_tick)
        super().__init__(
            [InProcessTransport(e, async_tick=self.async_tick)
             for e in engines],
            queue, policy=policy, spawn_fn=wrapped_spawn,
            event_log=event_log)

    # -- construction helpers ----------------------------------------------

    def _add_replica(self, transport: InProcessTransport) -> Replica:
        rep = super()._add_replica(transport)
        # trace completeness for the in-process fleet: replica engines
        # built without their own event log inherit the router's, so
        # per-request prefill/terminal records land in the SAME stream
        # the controller's queued/placed/delivered records use and
        # FleetObserver.stitch() sees one complete timeline (the
        # process-fleet equivalent ships child events over the wire)
        from ..obs.events import NULL_EVENT_LOG
        eng = getattr(transport, "engine", None)
        if eng is not None and eng.events is NULL_EVENT_LOG \
                and self.events is not NULL_EVENT_LOG:
            eng.events = self.events
        if self.chaos is not None:
            self._install_chaos(rep)
        return rep

    def _install_chaos(self, rep: Replica) -> None:
        """Wrap this replica's backend so planned replica faults fire at
        the router tick they cover. Kill/wedge raise from BOTH prefill
        and decode (a dead box fails everything); slow sleeps inside
        decode so the replica's own watchdog sees the overrun — chaos
        manifests only through the signals real faults would produce."""
        from ..resilience.chaos import ChaosError
        plan, router, idx = self.chaos, self, rep.index
        backend = rep.engine.backend
        orig_decode, orig_prefill = backend.decode, backend.prefill

        def _dead() -> Optional[str]:
            t = router._tick_index
            if plan.replica_fault("kill_replica", t, idx) is not None:
                return "kill_replica"
            if plan.replica_fault("wedge_replica", t, idx) is not None:
                return "wedge_replica"
            return None

        def chaotic_decode(live):
            kind = _dead()
            if kind is not None:
                raise ChaosError(
                    f"injected {kind} on replica {idx} at router tick "
                    f"{router._tick_index}")
            f = plan.replica_fault("slow_replica", router._tick_index, idx)
            if f is not None:
                time.sleep(f.magnitude)
            return orig_decode(live)

        # wraps() keeps the wrapped prefill's signature visible so the
        # engine's demand-kwarg probe (_prefill_kwargs) sees the real
        # backend: paged pools still get max_new_tokens, 3-arg
        # stub/legacy backends still get the legacy call.
        @functools.wraps(orig_prefill)
        def chaotic_prefill(slot, prompt, seed, **kw):
            kind = _dead()
            if kind is not None:
                raise ChaosError(
                    f"injected {kind} on replica {idx} at router tick "
                    f"{router._tick_index}")
            return orig_prefill(slot, prompt, seed, **kw)

        backend.decode = chaotic_decode
        backend.prefill = chaotic_prefill
