"""Fleet front door: N serve-engine replicas behind one request queue.

One :class:`~.engine.ServeEngine` is S slots on one device (or one
ring); the north star serves heavy traffic, which means N replicas and
the question PR 5 left open: what happens when one of them wedges? The
:class:`Router` answers it the same way the rest of the stack answers
everything — host-side table maintenance over signals the hot path
already produces:

* **One front queue, N replica queues.** Callers submit to the router's
  bounded :class:`~.queue.RequestQueue` (ids are fleet-unique — replica
  queues never mint ids); each tick the router places waiting requests
  onto HEALTHY replicas, least-loaded or session-affine. Deadlines,
  priorities and cancellation ride the *same* :class:`~.queue.Request`
  object end-to-end: ``submitted_at``/``deadline`` are set once at
  submit and survive every re-queue, so a failed-over request never
  regains deadline credit, and ``cancel`` is one flag flip wherever the
  request currently sits (front, parked for retry, replica queue, or a
  live slot).

* **A health state machine per replica**, driven entirely by signals
  the engines already export — the :class:`~..resilience.TickWatchdog`
  read-only surface (``slow_streak``, ``miss_ewma``) plus
  ``ServeEngine.consecutive_decode_errors`` and retryable-failure
  responses. No extra device syncs: health is decided from host
  bookkeeping, keeping the per-replica hot path as host-free as the SET
  stream-event-triggered direction demands. States::

      HEALTHY --(slow streak / decode error / retryable failure)--> SUSPECT
      SUSPECT --(recover_healthy_ticks clean ticks)--> HEALTHY
      HEALTHY|SUSPECT --(wedge thresholds)--> WEDGED
      WEDGED --(queued work evicted, drain() issued)--> DRAINING
      DRAINING --(engine.drained)--> RETIRED

  SUSPECT only stops *placement* (hysteresis: transient stalls must not
  flap work across the fleet); WEDGED is one-way — the replica's queued
  requests are reclaimed intact (``evict_queued``) and its live slots
  run out under ``drain()``.

* **Retry budgets, not retry storms.** A request bounced by a wedged or
  erroring replica (``finish_reason`` ``backend_error``/``stuck``) is
  parked with exponential backoff (``backoff_base_s * 2^(attempts-1)``,
  capped) and re-placed on a healthy replica while
  ``attempts < retry_budget`` (attempts counts placements). Budget
  exhausted → one terminal ``status="error"`` /
  ``finish_reason="retries_exhausted"`` response. Every submitted id
  yields **exactly one** terminal :class:`~.queue.Response` through the
  router — a duplicate delivery raises, and ``tests/test_router.py``
  pins the exactly-once ledger under ``kill_replica`` chaos.

* **Lifecycle**: ``spawn_fn`` adds a replica after the front queue sits
  at ``spawn_depth`` for ``spawn_sustain_ticks`` consecutive ticks;
  ``retire_idle_ticks`` drains replicas the traffic no longer needs
  (never below ``min_replicas``). Both are host decisions between
  ticks; compiled programs are untouched.

The router is strictly additive: not constructing one changes nothing
anywhere (``apps/serve.py`` keeps the direct single-engine path, and
the engines' decode HLO is byte-identical — same opt-out-is-absent
discipline as the resilience layer). Single-threaded like the engine
tick loop; replica chaos (``wedge_replica``/``kill_replica``/
``slow_replica``) wraps the replica backends only when a
:class:`~..resilience.ChaosPlan` is passed.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs.events import NULL_EVENT_LOG, REQUEST
from ..obs.telemetry import get_registry, labelled
from .engine import EngineDraining, ServeEngine
from .queue import QueueFull, Request, RequestQueue, Response

__all__ = ["Router", "RouterPolicy", "Replica",
           "HEALTHY", "SUSPECT", "WEDGED", "DRAINING", "RETIRED"]

HEALTHY = "healthy"
SUSPECT = "suspect"
WEDGED = "wedged"
DRAINING = "draining"
RETIRED = "retired"
STATES = (HEALTHY, SUSPECT, WEDGED, DRAINING, RETIRED)
_STATE_CODE = {s: i for i, s in enumerate(STATES)}

# Engine finish_reasons the router may retry on another replica; every
# other terminal outcome is delivered as-is.
RETRYABLE_REASONS = ("backend_error", "stuck")


@dataclasses.dataclass
class RouterPolicy:
    """Fleet policy knobs. Defaults are deliberately conservative —
    quick to stop placing on a sick replica (SUSPECT is cheap: work
    just goes elsewhere), slow to wedge (WEDGED is one-way).

    ``placement`` — ``least_loaded`` picks the replica with the fewest
    queued+live requests (ties: lowest index); ``session`` pins each
    ``session`` key to its first replica while that replica is HEALTHY
    (KV-cache/prefix locality for multi-turn traffic) and falls back to
    least-loaded — remapping the session — when it isn't.

    ``retry_budget`` — max *placements* per request (``Request.attempts``
    is the ledger); a retryable failure at ``attempts >= retry_budget``
    is terminal. ``backoff_base_s``/``backoff_max_s`` shape the parked
    delay ``min(base * 2^(attempts-1), max)``; base 0 retries on the
    next tick (what deterministic fake-clock tests want — a parked
    request is only eligible once the queue clock passes its delay).

    SUSPECT triggers: ``suspect_slow_streak`` consecutive over-budget
    ticks (watchdog), any decode error, any retryable failure this
    tick, or ``suspect_miss_ewma`` (None disables the EWMA trigger).
    ``recover_healthy_ticks`` clean ticks clear SUSPECT. WEDGE
    triggers: ``wedge_slow_streak`` consecutive slow ticks,
    ``wedge_decode_errors`` consecutive decode errors (keep it below
    the engine's ``decode_error_limit``, which resets the streak), or
    ``wedge_error_ticks`` *cumulative* ticks that produced retryable
    failures (catches prefill-side death, where no decode streak ever
    forms).

    Lifecycle: ``spawn_depth``/``spawn_sustain_ticks``/``max_replicas``
    gate the spawn hook; ``retire_idle_ticks``/``min_replicas`` gate
    idle retirement (None disables).
    """

    placement: str = "least_loaded"
    retry_budget: int = 3
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    suspect_slow_streak: int = 2
    suspect_miss_ewma: Optional[float] = None
    recover_healthy_ticks: int = 3
    wedge_slow_streak: int = 6
    wedge_decode_errors: int = 2
    wedge_error_ticks: int = 3
    spawn_depth: Optional[int] = None
    spawn_sustain_ticks: int = 10
    max_replicas: int = 8
    retire_idle_ticks: Optional[int] = None
    min_replicas: int = 1

    def __post_init__(self):
        if self.placement not in ("least_loaded", "session"):
            raise ValueError(
                f"placement must be least_loaded|session, got "
                f"{self.placement!r}")
        if self.retry_budget < 1:
            raise ValueError(
                f"retry_budget must be >= 1, got {self.retry_budget}")
        if self.backoff_base_s < 0 or self.backoff_max_s < 0:
            raise ValueError("backoff seconds must be >= 0")
        for fld in ("suspect_slow_streak", "recover_healthy_ticks",
                    "wedge_slow_streak", "wedge_decode_errors",
                    "wedge_error_ticks", "spawn_sustain_ticks",
                    "max_replicas", "min_replicas"):
            if getattr(self, fld) < 1:
                raise ValueError(f"{fld} must be >= 1")


class Replica:
    """Router-side record of one engine replica: health state plus the
    hysteresis counters the state machine runs on."""

    __slots__ = ("index", "engine", "state", "healthy_streak",
                 "idle_ticks", "error_ticks", "had_error_this_tick")

    def __init__(self, index: int, engine: ServeEngine):
        self.index = index
        self.engine = engine
        self.state = HEALTHY
        self.healthy_streak = 0
        self.idle_ticks = 0
        self.error_ticks = 0          # cumulative ticks with retryable fails
        self.had_error_this_tick = False

    @property
    def load(self) -> int:
        return self.engine.queue.depth + self.engine.live_slots

    def __repr__(self) -> str:
        return (f"Replica({self.index}, state={self.state}, "
                f"load={self.load})")


class Router:
    """Shard one front :class:`~.queue.RequestQueue` across N
    :class:`~.engine.ServeEngine` replicas with health-gated failover.

    ``engines`` must be homogeneous (same model/buckets/caps — admission
    validation uses replica 0's backend) and each must own its own
    queue on the *same clock* as the front queue. ``spawn_fn`` (if
    given) builds one more engine on demand for the spawn hook.
    ``chaos`` arms replica-level fault injection
    (:data:`~..resilience.chaos.REPLICA_KINDS`, addressed by
    ``Fault.stage`` = replica index); None leaves the backends
    untouched.

    The surface mirrors :class:`~.engine.ServeEngine` — ``submit`` /
    ``tick`` / ``cancel`` / ``response`` / ``drain`` / ``idle`` /
    ``run_until_idle`` — so drivers (``apps/serve.py``) swap one for
    the other without restructuring their loop.
    """

    def __init__(self, engines: Sequence[ServeEngine],
                 queue: Optional[RequestQueue] = None, *,
                 policy: RouterPolicy = RouterPolicy(),
                 spawn_fn: Optional[Callable[[], ServeEngine]] = None,
                 chaos=None, event_log=None,
                 clock: Optional[Callable[[], float]] = None):
        engines = list(engines)
        if not engines:
            raise ValueError("Router needs at least one engine replica")
        if queue is None:
            queue = RequestQueue(clock=clock or time.monotonic)
        elif clock is not None and clock is not queue.clock:
            raise ValueError(
                "pass the clock on the queue (router adopts queue.clock)")
        seen = set()
        for eng in engines:
            if eng.queue is queue:
                raise ValueError(
                    "a replica engine may not share the router's front "
                    "queue (the router owns placement)")
            if id(eng.queue) in seen:
                raise ValueError(
                    "replica engines may not share a queue with each "
                    "other (each replica owns its backlog)")
            seen.add(id(eng.queue))
            if eng.clock is not queue.clock:
                raise ValueError(
                    "every replica engine must run on the front queue's "
                    "clock (deadlines are absolute in one clock domain)")
        self.queue = queue
        self.clock = queue.clock
        self.policy = policy
        self.spawn_fn = spawn_fn
        self.chaos = chaos
        self.events = event_log if event_log is not None else NULL_EVENT_LOG
        self.replicas: List[Replica] = []
        for eng in engines:
            self._add_replica(eng)
        self._responses: Dict[int, Response] = {}
        self._tracked: Dict[int, Request] = {}
        self._parked: List[Tuple[float, Request]] = []
        self._session_of: Dict[int, str] = {}
        self._session_map: Dict[str, int] = {}
        self._placed_on: Dict[int, int] = {}
        self._tick_index = 0
        self._depth_streak = 0
        self._draining = False

    # -- construction helpers ----------------------------------------------

    def _add_replica(self, engine: ServeEngine) -> Replica:
        rep = Replica(len(self.replicas), engine)
        if self.chaos is not None:
            self._install_chaos(rep)
        self.replicas.append(rep)
        return rep

    def _install_chaos(self, rep: Replica) -> None:
        """Wrap this replica's backend so planned replica faults fire at
        the router tick they cover. Kill/wedge raise from BOTH prefill
        and decode (a dead box fails everything); slow sleeps inside
        decode so the replica's own watchdog sees the overrun — chaos
        manifests only through the signals real faults would produce."""
        from ..resilience.chaos import ChaosError
        plan, router, idx = self.chaos, self, rep.index
        backend = rep.engine.backend
        orig_decode, orig_prefill = backend.decode, backend.prefill

        def _dead() -> Optional[str]:
            t = router._tick_index
            if plan.replica_fault("kill_replica", t, idx) is not None:
                return "kill_replica"
            if plan.replica_fault("wedge_replica", t, idx) is not None:
                return "wedge_replica"
            return None

        def chaotic_decode(live):
            kind = _dead()
            if kind is not None:
                raise ChaosError(
                    f"injected {kind} on replica {idx} at router tick "
                    f"{router._tick_index}")
            f = plan.replica_fault("slow_replica", router._tick_index, idx)
            if f is not None:
                time.sleep(f.magnitude)
            return orig_decode(live)

        # wraps() keeps the wrapped prefill's signature visible so the
        # engine's demand-kwarg probe (_prefill_kwargs) sees the real
        # backend: paged pools still get max_new_tokens, 3-arg
        # stub/legacy backends still get the legacy call.
        @functools.wraps(orig_prefill)
        def chaotic_prefill(slot, prompt, seed, **kw):
            kind = _dead()
            if kind is not None:
                raise ChaosError(
                    f"injected {kind} on replica {idx} at router tick "
                    f"{router._tick_index}")
            return orig_prefill(slot, prompt, seed, **kw)

        backend.decode = chaotic_decode
        backend.prefill = chaotic_prefill

    # -- front door --------------------------------------------------------

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None, seed: int = 0,
               priority: int = 0, timeout_s: Optional[float] = None,
               session: Optional[str] = None) -> Request:
        """Validate + enqueue at the fleet front door. Raises
        ``ValueError`` on an unservable request,
        :class:`~.engine.EngineDraining` after :meth:`drain`, and
        :class:`~.queue.QueueFull` when the front queue is at capacity —
        which is exactly what happens when every replica is SUSPECT or
        worse: placement stops, the front fills, callers feel
        backpressure instead of silent loss."""
        reg = get_registry()
        if self._draining:
            raise EngineDraining(
                "fleet is draining: live requests are finishing and no "
                "new work is admitted")
        backend = self.replicas[0].engine.backend
        if max_new_tokens is None:
            max_new_tokens = backend.gen.max_new_tokens
        backend.validate(len(prompt), max_new_tokens)
        try:
            req = self.queue.submit(prompt, max_new_tokens=max_new_tokens,
                                    seed=seed, priority=priority,
                                    timeout_s=timeout_s)
        except QueueFull:
            reg.counter("serve.fleet.rejected").inc()
            raise
        self._tracked[req.id] = req
        if session is not None:
            self._session_of[req.id] = str(session)
        reg.counter("serve.fleet.submitted").inc()
        reg.gauge("serve.fleet.front_depth").set(self.queue.depth)
        return req

    def cancel(self, request_id: int) -> bool:
        """Mark a live request cancelled wherever it currently sits —
        front queue, parked for retry, a replica's queue, or a running
        slot. One flag flip on the shared :class:`~.queue.Request`;
        whichever sweep sees it first emits the single terminal
        ``cancelled`` response. False for unknown/terminal ids."""
        req = self._tracked.get(request_id)
        if req is None:
            return False
        req.cancelled = True
        return True

    def response(self, request_id: int) -> Optional[Response]:
        return self._responses.get(request_id)

    # -- drain / status ----------------------------------------------------

    def drain(self) -> None:
        """Fleet-wide graceful shutdown: ``submit`` starts raising, the
        next tick sheds front-queued and parked work
        (``finish_reason="drain"``) and every replica drains its live
        slots. Idempotent."""
        if not self._draining:
            self._draining = True
            self.events.event("resilience", action="fleet_drain",
                              front=self.queue.depth,
                              parked=len(self._parked))
            for rep in self.replicas:
                if rep.state != RETIRED:
                    rep.engine.drain()

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def drained(self) -> bool:
        return self._draining and self.idle

    @property
    def idle(self) -> bool:
        return (self.queue.depth == 0 and not self._parked
                and all(r.engine.idle for r in self.replicas))

    def counts(self) -> Dict[str, int]:
        """Replica count per health state (``{state: n}``)."""
        out = {s: 0 for s in STATES}
        for rep in self.replicas:
            out[rep.state] += 1
        return out

    # -- delivery (the exactly-once ledger) --------------------------------

    def _deliver(self, resp: Response) -> Response:
        if resp.request_id in self._responses:
            raise RuntimeError(
                f"duplicate terminal response for request "
                f"{resp.request_id} (exactly-once delivery violated)")
        self._responses[resp.request_id] = resp
        req = self._tracked.pop(resp.request_id, None)
        self._session_of.pop(resp.request_id, None)
        self._placed_on.pop(resp.request_id, None)
        self.queue.forget(resp.request_id)
        reg = get_registry()
        reg.counter("serve.fleet.delivered").inc()
        if resp.status == "ok":
            reg.counter("serve.fleet.ok").inc()
        if req is not None and req.attempts > 1:
            reg.counter("serve.fleet.failed_over").inc()
        return resp

    def _finish_unplaced(self, req: Request, status: str, reason: str,
                         now: float) -> Response:
        """Terminal record for a request that never (re)reached a
        replica: front-reaped, parked-reaped, shed on fleet drain, or
        retries exhausted."""
        resp = Response(request_id=req.id, tokens=[], status=status,
                        finish_reason=reason, prompt_len=len(req.prompt),
                        ttft=None, latency=now - req.submitted_at)
        self.events.event(REQUEST, request=req.id, status=status,
                          finish_reason=reason, replica=None,
                          attempts=req.attempts)
        return self._deliver(resp)

    # -- retry parking -----------------------------------------------------

    def reclaim(self, requests: List[Request], now: float) -> List[Response]:
        """Re-absorb requests knocked off a replica — the ONE
        park-or-finish decision both recovery paths share (a wedged
        replica's evicted backlog and per-request retryable failures
        from a live tick), so the exactly-once ledger has a single
        writer. Per request: cancelled or past its deadline → parked
        for the next sweep's terminal cancelled/timeout record; retry
        budget remaining → parked with exponential backoff; else ONE
        terminal ``retries_exhausted`` error. Returns the terminal
        responses (already recorded in the ledger); parked requests
        surface through later ticks."""
        reg = get_registry()
        finished: List[Response] = []
        for req in requests:
            if req.cancelled or (req.deadline is not None
                                 and now >= req.deadline):
                # next tick's parked sweep emits the terminal
                # cancelled/timeout record
                self._parked.append((now, req))
            elif req.attempts < self.policy.retry_budget:
                self._park(req, now)
            else:
                reg.counter("serve.fleet.retries_exhausted").inc()
                finished.append(self._finish_unplaced(
                    req, "error", "retries_exhausted", now))
        return finished

    def _park(self, req: Request, now: float) -> None:
        p = self.policy
        delay = min(p.backoff_base_s * (2.0 ** max(req.attempts - 1, 0)),
                    p.backoff_max_s)
        self._parked.append((now + delay, req))
        get_registry().counter("serve.fleet.retried").inc()
        self.events.event("resilience", action="retry_parked",
                          request=req.id, attempts=req.attempts,
                          delay_s=delay)

    # -- placement ---------------------------------------------------------

    def _placeable(self) -> List[Replica]:
        return [r for r in self.replicas
                if r.state == HEALTHY
                and r.engine.queue.depth < r.engine.queue.capacity]

    def _choose(self, req: Request, candidates: List[Replica]) -> Replica:
        if self.policy.placement == "session":
            sess = self._session_of.get(req.id)
            if sess is not None:
                home = self._session_map.get(sess)
                for rep in candidates:
                    if rep.index == home:
                        return rep
        return min(candidates, key=lambda r: (r.load, r.index))

    def _kv_handoff(self, req: Request, sess: str, old_idx: int,
                    new_rep: Replica) -> None:
        """Session-remap KV bookkeeping (paged pools only — ``pool`` is
        absent on slab backends and the whole hook is a no-op). The
        prefix blocks the session populated on its old home are
        invalidated there: the conversation's KV continues on the new
        home, so a later remap BACK must re-prefill rather than extend a
        stale prefix. The new home is probed for warm prefix blocks so
        the handoff cost (cold re-prefill vs shared-prefix hit) is
        observable per remap."""
        reg = get_registry()
        reg.counter("serve.fleet.kv_handoff_total").inc()
        old_pool = getattr(
            self.replicas[old_idx].engine.backend, "pool", None)
        invalidated = 0
        if old_pool is not None:
            invalidated = old_pool.invalidate(
                old_pool.prefix_hashes(req.prompt))
            if invalidated:
                reg.counter(
                    "serve.fleet.kv_handoff_invalidated").inc(invalidated)
        new_pool = getattr(new_rep.engine.backend, "pool", None)
        warm = (new_pool.cached_prefix_blocks(req.prompt)
                if new_pool is not None else 0)
        reg.counter("serve.fleet.kv_handoff_warm" if warm
                    else "serve.fleet.kv_handoff_cold").inc()
        self.events.event("resilience", action="kv_handoff",
                          request=req.id, session=sess,
                          from_replica=old_idx, to_replica=new_rep.index,
                          invalidated=invalidated, warm_blocks=warm)

    def _try_place(self, req: Request, now: float) -> bool:
        candidates = self._placeable()
        if not candidates:
            return False
        rep = self._choose(req, candidates)
        sess = self._session_of.get(req.id)
        if sess is not None:
            home = self._session_map.get(sess)
            if home is not None and home != rep.index:
                self._kv_handoff(req, sess, home, rep)
        rep.engine.place(req)               # increments req.attempts
        self._placed_on[req.id] = rep.index
        if sess is not None and rep.state == HEALTHY:
            self._session_map[sess] = rep.index
        return True

    # -- health state machine ----------------------------------------------

    def _wedge(self, rep: Replica, reason: str, now: float) -> None:
        """WEDGED: reclaim the backlog intact, re-place or park it under
        the retry budget, and start draining the live slots. One-way."""
        rep.state = WEDGED
        get_registry().counter("serve.fleet.wedged").inc()
        evicted = rep.engine.evict_queued()
        self.events.event("resilience", action="replica_wedged",
                          replica=rep.index, reason=reason,
                          evicted=len(evicted))
        # terminal responses land in the ledger; tick's delivered list
        # picks them up via response() like any mid-health-pass finish
        self.reclaim(evicted, now)
        rep.engine.drain()
        rep.state = DRAINING

    def _update_health(self, rep: Replica, now: float) -> None:
        p = self.policy
        if rep.state == RETIRED:
            return
        if rep.state == DRAINING:
            if rep.engine.drained:
                rep.state = RETIRED
                get_registry().counter("serve.fleet.retired").inc()
                self.events.event("resilience", action="replica_retired",
                                  replica=rep.index)
            return

        wd = rep.engine.watchdog
        slow = wd.slow_streak if wd is not None else 0
        ewma = wd.miss_ewma if wd is not None else 0.0
        derr = rep.engine.consecutive_decode_errors
        if rep.had_error_this_tick:
            rep.error_ticks += 1

        if (slow >= p.wedge_slow_streak or derr >= p.wedge_decode_errors
                or rep.error_ticks >= p.wedge_error_ticks):
            self._wedge(rep, f"slow_streak={slow} decode_errors={derr} "
                             f"error_ticks={rep.error_ticks}", now)
            return

        bad = (slow >= p.suspect_slow_streak or derr > 0
               or rep.had_error_this_tick
               or (p.suspect_miss_ewma is not None
                   and ewma > p.suspect_miss_ewma))
        if rep.state == HEALTHY and bad:
            rep.state = SUSPECT
            rep.healthy_streak = 0
            get_registry().counter("serve.fleet.suspected").inc()
            self.events.event("resilience", action="replica_suspect",
                              replica=rep.index, slow_streak=slow,
                              decode_errors=derr, miss_ewma=ewma)
        elif rep.state == SUSPECT:
            if bad:
                rep.healthy_streak = 0
            else:
                rep.healthy_streak += 1
                if rep.healthy_streak >= p.recover_healthy_ticks:
                    rep.state = HEALTHY
                    rep.healthy_streak = 0
                    get_registry().counter("serve.fleet.recovered").inc()
                    self.events.event("resilience",
                                      action="replica_recovered",
                                      replica=rep.index)

    def _lifecycle(self, now: float) -> None:
        """Spawn on sustained front-queue depth; retire sustained-idle
        replicas (never below ``min_replicas`` placeable ones)."""
        p = self.policy
        active = [r for r in self.replicas if r.state in (HEALTHY, SUSPECT)]
        if p.spawn_depth is not None and self.spawn_fn is not None:
            if self.queue.depth >= p.spawn_depth:
                self._depth_streak += 1
            else:
                self._depth_streak = 0
            if self._depth_streak >= p.spawn_sustain_ticks \
                    and len(active) < p.max_replicas:
                rep = self._add_replica(self.spawn_fn())
                self._depth_streak = 0
                get_registry().counter("serve.fleet.spawned").inc()
                self.events.event("resilience", action="replica_spawned",
                                  replica=rep.index,
                                  front_depth=self.queue.depth)
        if p.retire_idle_ticks is None:
            return
        for rep in self.replicas:
            if rep.state != HEALTHY:
                continue
            if rep.engine.idle and self.queue.depth == 0 \
                    and not self._parked:
                rep.idle_ticks += 1
            else:
                rep.idle_ticks = 0
            active = [r for r in self.replicas
                      if r.state in (HEALTHY, SUSPECT)]
            if rep.idle_ticks >= p.retire_idle_ticks \
                    and len(active) > p.min_replicas:
                rep.engine.drain()
                rep.state = DRAINING
                rep.idle_ticks = 0
                get_registry().counter("serve.fleet.idle_retired").inc()
                self.events.event("resilience",
                                  action="replica_idle_retired",
                                  replica=rep.index)

    # -- the fleet tick ----------------------------------------------------

    def tick(self) -> List[Response]:
        """One fleet scheduling round: sweep the front/parked sets,
        advance every replica's health machine, place onto HEALTHY
        replicas, tick the replicas, then deliver-or-retry their
        terminal responses. Returns the responses DELIVERED this tick
        (retried failures are not delivered — they park)."""
        reg = get_registry()
        now = self.clock()
        tick_idx = self._tick_index
        delivered: List[Response] = []

        # 0) fleet drain — push back everything not yet on a replica
        if self._draining:
            for req in self.queue.evict_all():
                delivered.append(
                    self._finish_unplaced(req, "shed", "drain", now))
            for _, req in self._parked:
                delivered.append(
                    self._finish_unplaced(req, "shed", "drain", now))
            self._parked = []

        # 1) front + parked sweeps — deaths that never cost a replica
        for req, reason in self.queue.reap(now):
            status = "cancelled" if reason == "cancelled" else "timeout"
            delivered.append(
                self._finish_unplaced(req, status, reason, now))
        still = []
        for eligible_at, req in self._parked:
            if req.cancelled:
                delivered.append(
                    self._finish_unplaced(req, "cancelled", "cancelled",
                                          now))
            elif req.deadline is not None and now >= req.deadline:
                delivered.append(
                    self._finish_unplaced(req, "timeout", "deadline", now))
            else:
                still.append((eligible_at, req))
        self._parked = still

        # 2) health transitions + lifecycle (uses last tick's signals)
        for rep in self.replicas:
            self._update_health(rep, now)
            rep.had_error_this_tick = False
        if not self._draining:
            self._lifecycle(now)

        # 2b) dead fleet — no replica can ever serve again (none healthy
        # or recoverable, no spawn hook armed): fail the stranded work
        # now instead of parking it forever
        recoverable = any(r.state in (HEALTHY, SUSPECT)
                          for r in self.replicas)
        can_spawn = (self.spawn_fn is not None
                     and self.policy.spawn_depth is not None)
        if not recoverable and not can_spawn and not self._draining:
            stranded = self.queue.evict_all() + [r for _, r in self._parked]
            self._parked = []
            for req in stranded:
                reg.counter("serve.fleet.retries_exhausted").inc()
                delivered.append(self._finish_unplaced(
                    req, "error", "no_replicas", now))

        # 3) placement — parked retries first (oldest work), then front
        if not self._draining:
            still = []
            for eligible_at, req in self._parked:
                if eligible_at > now or not self._try_place(req, now):
                    still.append((eligible_at, req))
            self._parked = still
            while self.queue.depth and self._placeable():
                req = self.queue.pop()
                self._try_place(req, now)

        # 4) tick the replicas, deliver-or-retry what they finish
        for rep in self.replicas:
            if rep.state == RETIRED:
                continue
            for resp in rep.engine.tick():
                req = self._tracked.get(resp.request_id)
                if (resp.status == "error"
                        and resp.finish_reason in RETRYABLE_REASONS
                        and req is not None):
                    rep.had_error_this_tick = True
                    delivered.extend(self.reclaim([req], now))
                    continue
                delivered.append(self._deliver(resp))

        # 5) fleet gauges
        counts = self.counts()
        for state, n in counts.items():
            reg.gauge(f"serve.fleet.replicas_{state}").set(n)
        reg.gauge("serve.fleet.front_depth").set(self.queue.depth)
        reg.gauge("serve.fleet.parked").set(len(self._parked))
        for rep in self.replicas:
            reg.gauge(labelled("serve.fleet.replica.state",
                               replica=rep.index)).set(
                _STATE_CODE[rep.state])
            reg.gauge(labelled("serve.fleet.replica.queue_depth",
                               replica=rep.index)).set(
                rep.engine.queue.depth)
            reg.gauge(labelled("serve.fleet.replica.live_slots",
                               replica=rep.index)).set(
                rep.engine.live_slots)
        self._tick_index = tick_idx + 1
        return delivered

    # -- convenience loops -------------------------------------------------

    def run_until_idle(self, max_ticks: int = 1_000_000) -> List[Response]:
        """Tick until every tracked request delivered. With every
        replica dead this still terminates: retries exhaust their
        budgets and the dead-fleet sweep fails anything stranded."""
        delivered: List[Response] = []
        for _ in range(max_ticks):
            if self.idle:
                return delivered
            delivered.extend(self.tick())
        raise RuntimeError(
            f"fleet not idle after {max_ticks} ticks (front="
            f"{self.queue.depth}, parked={len(self._parked)}, "
            f"replicas={self.counts()})")
