"""Paged KV memory: fixed-size blocks + per-slot block tables.

The monolithic slab backends reserve ``max_len`` cache rows per slot
regardless of what the request actually needs, so memory — not compute —
caps live slots (SERVE_r08's lesson). This module makes KV memory a
first-class resource:

* :class:`KvPool` — a HOST-side allocator over ``num_blocks`` physical
  blocks of ``block_size`` rows each. Pure numpy/dict bookkeeping (free
  list, refcounts, prefix cache, LRU), unit-testable without jax. A slot
  reserves exactly ``ceil((prompt_len + max_new - 1) / block_size)``
  blocks at admission — proportional to the request, not to ``max_len``.
* **Shared-prefix cache with copy-on-write.** Full prompt blocks are
  content-addressed by a rolling hash of the token prefix; N requests
  sharing a system prompt pin ONE physical copy (refcounted). A write
  into a shared block (the prefill recompute tail) forks it first: the
  pool hands the backend ``(src, dst)`` copy pairs, the slot's table
  points at the private copy, and the cached original is untouched.
* **Device helpers** (:func:`storage_for`, :func:`gather_block_cache`,
  :func:`scatter_block_rows`, :func:`flat_row_index`, :func:`copy_block`)
  — the gather/scatter indexing the backends fuse into their compiled
  decode/prefill-chunk programs. The layer math (``m.block.decode``)
  runs unchanged on a gathered contiguous view, so paged decode stays
  bitwise-equal to the slab path.

The sacrificial block
---------------------
Physical block 0 is never allocated. Table rows are ``table_width``
int32 entries whose unreserved tail stays 0, and the flat row index
clamps the block index at ``table_width - 1`` — so every overshoot
write (decode past retirement inside a chunk, prefill padding past the
prompt, a released slot still riding the fixed-shape decode program,
the ring's inactive-stage cycles) lands harmlessly in block 0. This is
the slab backends' sacrificial-region trick, relocated into the
indexing: :meth:`KvPool.release` additionally zeroes the slot's table
row on the host, so a dead slot can NEVER corrupt a block that has been
reallocated to someone else.

int8 KV blocks compose with ``inference/quant.py``: storage carries
int8 codes plus one f32 scale per row per head, quantized on scatter
and dequantized inside the gather (fused into the attention read).
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.telemetry import get_registry

__all__ = ["KvPool", "PoolExhausted", "Admission", "block_demand",
           "storage_for", "gather_block_cache", "scatter_block_rows",
           "flat_row_index", "copy_block"]

SACRIFICIAL = 0


class PoolExhausted(RuntimeError):
    """Raised by :meth:`KvPool.admit` when the pool cannot cover a
    request's block demand — the paged analog of
    :class:`~.queue.QueueFull`, carrying the same style of detail so
    admission control can park instead of thrash."""

    def __init__(self, message: str, *, demand: int = 0, free: int = 0,
                 evictable: int = 0, total: int = 0):
        super().__init__(message)
        self.demand = demand
        self.free = free
        self.evictable = evictable
        self.total = total


def block_demand(prompt_len: int, max_new_tokens: int,
                 block_size: int) -> int:
    """Blocks a request must reserve. The last sampled token's KV row is
    never written (retirement happens first), hence ``- 1``; decode
    overshoot past that lands in the sacrificial block."""
    rows = prompt_len + max_new_tokens - 1
    return -(-rows // block_size)


@dataclasses.dataclass
class Admission:
    """What :meth:`KvPool.admit` hands the backend: the slot's table
    row, where prefill may resume (``resume_from`` — everything before
    it is covered by shared cached blocks), and the COW copies to run
    before any chunk writes."""

    slot: int
    table: np.ndarray                    # [table_width] int32
    resume_from: int
    shared_len: int
    prefix_hits: int
    cow_forks: List[Tuple[int, int]]     # (src, dst) physical ids
    blocks: List[int]
    rows_needed: int


class _Cached:
    __slots__ = ("block", "refs")

    def __init__(self, block: int):
        self.block = block
        self.refs = 0


class _SlotMeta:
    __slots__ = ("blocks", "rows_needed", "registered")

    def __init__(self, blocks, rows_needed, registered):
        self.blocks = blocks          # [(block_id, hash-or-None)]
        self.rows_needed = rows_needed
        self.registered = registered  # hashes first published by this slot


class KvPool:
    """Host-side paged-KV allocator. Single-threaded (the engine tick
    discipline); never touches jax.

    ``num_blocks`` counts physical blocks INCLUDING the sacrificial
    block 0, so ``num_blocks - 1`` are allocatable. ``gather_slack_rows``
    widens the table (with sacrificial entries) past ``max_len`` so a
    fixed-shape prefill chunk starting at ``prompt_len - 1`` can always
    slice ``chunk`` rows out of the gathered view without clamping.
    """

    def __init__(self, *, num_blocks: int, block_size: int, num_slots: int,
                 max_len: int, prefix_cache: bool = True,
                 gather_slack_rows: int = 0):
        if block_size < 1 or (block_size & (block_size - 1)) != 0:
            raise ValueError(
                f"block_size must be a positive power of two, got "
                f"{block_size}")
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is sacrificial), got "
                f"{num_blocks}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.num_slots = num_slots
        self.max_len = max_len
        self.prefix_cache = prefix_cache
        self.max_blocks = -(-max_len // block_size)
        ext = -(-(max_len + gather_slack_rows) // block_size)
        self.table_width = ext + 1
        self.table = np.zeros((num_slots, self.table_width), np.int32)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._cached: Dict[str, _Cached] = {}
        self._lru: "OrderedDict[str, int]" = OrderedDict()
        self._slot_meta: List[Optional[_SlotMeta]] = [None] * num_slots

    # -- capacity ----------------------------------------------------------

    @property
    def allocatable(self) -> int:
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def evictable_blocks(self) -> int:
        return len(self._lru)

    def demand_for(self, prompt_len: int, max_new_tokens: int) -> int:
        return block_demand(prompt_len, max_new_tokens, self.block_size)

    # -- prefix hashing ----------------------------------------------------

    def prefix_hashes(self, prompt: Sequence[int]) -> List[str]:
        """Rolling content hash per FULL prompt block (the partial tail
        block is always private, never cached)."""
        bs = self.block_size
        out: List[str] = []
        h = hashlib.sha256()
        for i in range(len(prompt) // bs):
            h.update(np.asarray(prompt[i * bs:(i + 1) * bs],
                                np.int64).tobytes())
            out.append(h.hexdigest())
        return out

    def _lookup(self, hashes: List[str]) -> int:
        hit = 0
        while hit < len(hashes) and hashes[hit] in self._cached:
            hit += 1
        return hit

    def cached_prefix_blocks(self, prompt: Sequence[int]) -> int:
        """Leading full blocks of ``prompt`` already in the cache — the
        router's warm-handoff probe."""
        if not self.prefix_cache:
            return 0
        return self._lookup(self.prefix_hashes(prompt))

    def cached_prefix_entries(
            self, prompt: Sequence[int]) -> List[Tuple[str, int]]:
        """The leading cached full blocks of ``prompt`` as
        ``(hash, physical_block_id)`` pairs — what a KV handoff exports
        from a session's old home replica."""
        if not self.prefix_cache:
            return []
        hashes = self.prefix_hashes(prompt)
        return [(h, self._cached[h].block)
                for h in hashes[:self._lookup(hashes)]]

    def take_blocks(self, n: int) -> List[int]:
        """Pop up to ``n`` physical blocks (free first, then LRU
        eviction) for an external write — the import side of a KV
        handoff. Returns fewer than ``n`` when the pool can't cover it;
        the caller seats what fit."""
        out: List[int] = []
        for _ in range(n):
            try:
                out.append(self._alloc())
            except PoolExhausted:
                break
        return out

    def seat_prefix(self, entries: Sequence[Tuple[str, int]]) -> int:
        """Register externally-written blocks as cached prefix entries
        (refs=0 → LRU-evictable, exactly the state :meth:`release`
        leaves a retired slot's published blocks in). The block content
        must already be on device. Skips hashes already cached —
        returning the colliding block to the free list — so a handoff
        racing a local prefill never double-registers."""
        n = 0
        for h, bid in entries:
            if not self.prefix_cache or h in self._cached:
                self._free.append(bid)
                continue
            self._cached[h] = _Cached(bid)
            self._lru[h] = bid
            self._lru.move_to_end(h)
            n += 1
        return n

    def invalidate(self, hashes: Sequence[str]) -> int:
        """Drop cached entries (router KV handoff: a session remapped
        off a sick home replica must not find a stale prefix here).
        Ref-held blocks merely become unshareable — they free to the
        free list when their last holder releases."""
        n = 0
        for h in hashes:
            ent = self._cached.pop(h, None)
            if ent is None:
                continue
            n += 1
            if ent.refs <= 0:
                self._lru.pop(h, None)
                self._free.append(ent.block)
        return n

    # -- allocation --------------------------------------------------------

    def _alloc(self) -> int:
        if self._free:
            return self._free.pop()
        if self._lru:
            h, bid = self._lru.popitem(last=False)   # oldest first
            del self._cached[h]
            get_registry().counter("serve.kv.evictions").inc()
            return bid
        raise PoolExhausted(
            "kv pool exhausted mid-admission (allocator bug: demand was "
            "pre-checked)", demand=1, free=0, evictable=0,
            total=self.allocatable)

    def _plan(self, prompt_len: int, max_new_tokens: int,
              hashes: Optional[List[str]], chunk: int):
        """(demand, hit, reuse, t0): how many blocks, how many cache
        hits, how many hits survive as read-only shares (vs forked), and
        where prefill resumes. ``t0`` must still compute position
        ``prompt_len - 1`` (the first sampled token needs ``h`` there),
        so a fully-cached prompt resumes at the last chunk boundary and
        forks the shared blocks its recompute tail rewrites."""
        bs = self.block_size
        demand = block_demand(prompt_len, max_new_tokens, bs)
        hit = self._lookup(hashes) if hashes is not None else 0
        shared_len = hit * bs
        t0 = min(shared_len, ((prompt_len - 1) // chunk) * chunk)
        reuse = min(hit, t0 // bs)
        return demand, hit, reuse, t0

    def can_admit(self, prompt_len: int, max_new_tokens: int,
                  prompt: Optional[Sequence[int]] = None, *,
                  chunk: int = 1) -> bool:
        """Admission probe: can the pool cover this request right now
        (free + evictable, minus shared-prefix hits)? Read-only."""
        hashes = (self.prefix_hashes(prompt)
                  if prompt is not None and self.prefix_cache else None)
        demand, hit, reuse, _ = self._plan(
            prompt_len, max_new_tokens, hashes, chunk)
        if demand > self.max_blocks:
            return False
        need = (hit - reuse) + (demand - hit)
        return need <= len(self._free) + len(self._lru)

    def admit(self, slot: int, prompt: Sequence[int],
              max_new_tokens: int, *, chunk: int = 1) -> Admission:
        """Reserve the slot's FULL block demand (no mid-decode OOM),
        reusing cached prefix blocks read-only and forking the ones the
        prefill recompute tail will write. Raises :class:`PoolExhausted`
        without mutating anything when the pool can't cover it."""
        if self._slot_meta[slot] is not None:
            raise RuntimeError(
                f"slot {slot} admitted twice without release (engine "
                f"bookkeeping bug)")
        plen = len(prompt)
        bs = self.block_size
        hashes = self.prefix_hashes(prompt) if self.prefix_cache else None
        demand, hit, reuse, t0 = self._plan(
            plen, max_new_tokens, hashes, chunk)
        rows = plen + max_new_tokens - 1
        need = (hit - reuse) + (demand - hit)
        avail = len(self._free) + len(self._lru)
        if demand > self.max_blocks or need > avail:
            raise PoolExhausted(
                f"request needs {need} blocks ({demand} total, "
                f"{hit} prefix hits, {reuse} reusable) but the pool has "
                f"{len(self._free)} free + {len(self._lru)} evictable of "
                f"{self.allocatable}",
                demand=need, free=len(self._free),
                evictable=len(self._lru), total=self.allocatable)
        reg = get_registry()
        full = plen // bs
        blocks: List[int] = []
        meta_blocks: List[Tuple[int, Optional[str]]] = []
        forks: List[Tuple[int, int]] = []
        registered = set()
        for i in range(reuse):                       # read-only shares
            h = hashes[i]
            ent = self._cached[h]
            if ent.refs == 0:
                self._lru.pop(h, None)
            ent.refs += 1
            blocks.append(ent.block)
            meta_blocks.append((ent.block, h))
        for i in range(reuse, hit):                  # copy-on-write forks
            src = self._cached[hashes[i]].block
            dst = self._alloc()
            forks.append((src, dst))
            blocks.append(dst)
            meta_blocks.append((dst, None))
        for i in range(hit, demand):                 # fresh blocks
            bid = self._alloc()
            h = None
            if hashes is not None and i < full:
                # a full prompt block this prefill writes end-to-end:
                # publish it (the write completes before any other
                # admission can hit the entry — single-threaded tick)
                h = hashes[i]
                ent = _Cached(bid)
                ent.refs = 1
                self._cached[h] = ent
                registered.add(h)
            blocks.append(bid)
            meta_blocks.append((bid, h))
        row = np.zeros(self.table_width, np.int32)
        row[:demand] = blocks
        self.table[slot, :] = row
        self._slot_meta[slot] = _SlotMeta(meta_blocks, rows, registered)
        if hit:
            reg.counter("serve.kv.prefix_hits").inc(hit)
        if hashes is not None and full > hit:
            reg.counter("serve.kv.prefix_misses").inc(full - hit)
        if forks:
            reg.counter("serve.kv.cow_forks").inc(len(forks))
        return Admission(slot=slot, table=row, resume_from=t0,
                         shared_len=hit * bs, prefix_hits=hit,
                         cow_forks=forks, blocks=blocks, rows_needed=rows)

    def release(self, slot: int, *, failed: bool = False) -> None:
        """Retire a slot: zero its table row (the dead slot decodes into
        the sacrificial block from now on), free private blocks, decref
        shared ones — refcount-0 cached blocks become LRU-evictable, not
        free (a future prompt may hit them). ``failed=True`` (prefill
        raised mid-write) unpublishes the hashes this admission
        registered: their content is garbage."""
        meta = self._slot_meta[slot]
        self.table[slot, :] = SACRIFICIAL
        if meta is None:
            return
        self._slot_meta[slot] = None
        for bid, h in meta.blocks:
            ent = self._cached.get(h) if h is not None else None
            if ent is not None and ent.block == bid:
                ent.refs -= 1
                if ent.refs <= 0:
                    if failed and h in meta.registered:
                        del self._cached[h]
                        self._free.append(bid)
                    else:
                        self._lru[h] = bid
                        self._lru.move_to_end(h)
            else:
                self._free.append(bid)

    # -- metrics -----------------------------------------------------------

    def stats(self) -> dict:
        total = self.allocatable
        live = [m for m in self._slot_meta if m is not None]
        reserved = sum(len(m.blocks) for m in live)
        needed = sum(m.rows_needed for m in live)
        in_use = total - len(self._free) - len(self._lru)
        return {
            "blocks_total": total,
            "blocks_free": len(self._free),
            "blocks_evictable": len(self._lru),
            "blocks_in_use": in_use,
            "occupancy": in_use / total if total else 0.0,
            # internal fragmentation: reserved rows the live requests can
            # never write (tail of each slot's last block)
            "fragmentation": (1.0 - needed / (reserved * self.block_size)
                              if reserved else 0.0),
            "cached_blocks": len(self._cached),
            "shared_blocks": sum(
                1 for e in self._cached.values() if e.refs > 1),
        }

    def observe(self) -> None:
        reg = get_registry()
        for k, v in self.stats().items():
            reg.gauge(f"serve.kv.{k}").set(float(v))


# -- device-side indexing (compiled into the backends' programs) -----------

def storage_for(proto, n_layers: int, num_blocks: int, block_size: int, *,
                kv_dtype: Optional[str] = None):
    """Pool device arrays ``[n_layers, num_blocks, block_size, ...]``
    from one layer's attention-cache prototype (``make_cache(1, L)``).
    ``kv_dtype="int8"`` stores int8 codes + one f32 scale per row per
    head (``inference/quant.py`` discipline, applied to KV rows)."""
    if not (isinstance(proto, dict) and set(proto) == {"k", "v"}):
        raise TypeError(
            "paged KV needs a {'k','v'} attention cache prototype, got "
            f"{type(proto).__name__} with "
            f"{sorted(proto) if isinstance(proto, dict) else '?'}")
    out = {}
    for name, a in proto.items():
        shape = (n_layers, num_blocks, block_size) + tuple(a.shape[2:])
        if kv_dtype is None:
            out[name] = jnp.zeros(shape, a.dtype)
        elif kv_dtype == "int8":
            out[name] = jnp.zeros(shape, jnp.int8)
            out[name + "_scale"] = jnp.zeros(shape[:-1] + (1,), jnp.float32)
        else:
            raise ValueError(
                f"kv_dtype must be None or 'int8', got {kv_dtype!r}")
    return out


def flat_row_index(table_row, positions, block_size: int):
    """Physical flat row index for each position: block-table gather
    with the block index CLAMPED to the trailing sacrificial entry, so
    any position past the reserved region maps into block 0."""
    mb = table_row.shape[-1] - 1
    bi = jnp.minimum(positions // block_size, mb)
    return jnp.take(table_row, bi) * block_size + positions % block_size


def gather_block_cache(pool_layer, table_row, *, block_size: int,
                       compute_dtype):
    """One slot's rows as a contiguous ``{'k','v'} [1, R, ...]`` view
    (R = ``(table_width - 1) * block_size``). The layer's ``decode``
    runs on this view unchanged — garbage rows from sacrificial/unwritten
    blocks sit at positions the causal mask kills exactly (``-1e30``
    underflows to 0.0 in the softmax), the same bitwise argument the
    slab backends already rely on. int8 pools dequantize here, fused
    into the attention read."""
    mb = table_row.shape[-1] - 1

    def g(name):
        rows = jnp.take(pool_layer[name], table_row[:mb], axis=0)
        return rows.reshape((mb * block_size,) + rows.shape[2:])

    if "k_scale" in pool_layer:
        return {name: (g(name).astype(jnp.float32) *
                       g(name + "_scale")).astype(compute_dtype)[None]
                for name in ("k", "v")}
    return {name: g(name)[None] for name in ("k", "v")}


def scatter_block_rows(pool_layer, flat_idx, rows):
    """Write new KV rows ``{'k': [M, ...], 'v': [M, ...]}`` at physical
    flat indices ``[M]`` (duplicate sacrificial indices may collide —
    block 0 content is never read un-masked, so any winner is fine).
    int8 pools quantize per row per head on the way in."""
    from ..inference.quant import quantize_kv_rows
    out = dict(pool_layer)
    int8 = "k_scale" in pool_layer

    def flat(a):
        return a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:])

    for name in ("k", "v"):
        a = pool_layer[name]
        if int8:
            q, s = quantize_kv_rows(rows[name])
            out[name] = flat(a).at[flat_idx].set(q).reshape(a.shape)
            sa = pool_layer[name + "_scale"]
            out[name + "_scale"] = flat(sa).at[flat_idx].set(s).reshape(
                sa.shape)
        else:
            out[name] = flat(a).at[flat_idx].set(
                rows[name].astype(a.dtype)).reshape(a.shape)
    return out


def copy_block(pool, src, dst, *, block_axis: int = 1):
    """COW fork: copy physical block ``src`` → ``dst`` across every
    array of the pool (all layers at once — a block is ``block_size``
    rows of EVERY layer under one table entry)."""
    def cp(a):
        blk = jax.lax.dynamic_slice_in_dim(a, src, 1, axis=block_axis)
        return jax.lax.dynamic_update_slice_in_dim(a, blk, dst,
                                                   axis=block_axis)

    return jax.tree_util.tree_map(cp, pool)
