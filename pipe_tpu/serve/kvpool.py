"""Paged KV memory, generation 2: radix-tree prefix sharing + offload.

The monolithic slab backends reserve ``max_len`` cache rows per slot
regardless of what the request actually needs, so memory — not compute —
caps live slots (SERVE_r08's lesson). This module makes KV memory a
first-class resource:

* :class:`KvPool` — a HOST-side allocator over ``num_blocks`` physical
  blocks of ``block_size`` rows each. Pure numpy/dict bookkeeping (free
  list, refcounts, radix tree, eviction clock), unit-testable without
  jax. A slot reserves exactly ``ceil((prompt_len + max_new - 1) /
  block_size)`` blocks at admission — proportional to the request, not
  to ``max_len``.
* **Radix tree over prefix blocks.** Full prompt blocks are
  content-addressed by a rolling chain hash of the token prefix, so a
  digest IS a path in a trie: two prompts sharing 10 of 12 leading
  blocks share the first 10 digests and diverge after. Gen 2 makes that
  tree explicit — path-compressed :class:`RadixNode` runs, split on
  divergence — so eviction can walk leaf-first, the fleet can advertise
  resident subtrees, and hot nodes (refcount above a threshold) can be
  replicated to siblings.
* **Copy-on-write sharing.** N requests sharing a system prompt pin ONE
  physical copy per block (per-digest refcounts). A write into a shared
  block (the prefill recompute tail) forks it first: the pool hands the
  backend ``(src, dst)`` copy pairs, the slot's table points at the
  private copy, and the cached original is untouched.
* **Block-level eviction and host offload.** Under pool pressure the
  allocator reclaims cold refcount-0 blocks one at a time, deepest
  (leaf) digest first so a node is never freed while live descendants
  would be orphaned, oldest last-touch first among leaves. With a
  :class:`HostKvStore` attached (:meth:`KvPool.attach_offload`), an
  evicted block's rows are spilled to host memory instead of dropped —
  the digest stays in the tree with ``block=None`` — and restored on
  demand at the next admission that reuses it (``Admission.restores``),
  riding the backend's existing regather carry flag. Admission prices
  demand against free + evictable (offloadable) blocks.
* **Device helpers** (:func:`storage_for`, :func:`gather_block_cache`,
  :func:`scatter_block_rows`, :func:`flat_row_index`, :func:`copy_block`)
  — the gather/scatter indexing the backends fuse into their compiled
  decode/prefill-chunk programs. The layer math (``m.block.decode``)
  runs unchanged on a gathered contiguous view, so paged decode stays
  bitwise-equal to the slab path.

The sacrificial block
---------------------
Physical block 0 is never allocated. Table rows are ``table_width``
int32 entries whose unreserved tail stays 0, and the flat row index
clamps the block index at ``table_width - 1`` — so every overshoot
write (decode past retirement inside a chunk, prefill padding past the
prompt, a released slot still riding the fixed-shape decode program,
the ring's inactive-stage cycles) lands harmlessly in block 0. This is
the slab backends' sacrificial-region trick, relocated into the
indexing: :meth:`KvPool.release` additionally zeroes the slot's table
row on the host, so a dead slot can NEVER corrupt a block that has been
reallocated to someone else.

Refcount monotonicity
---------------------
A slot that covers digest ``i`` read-only also covers every shallower
digest ``j < i`` (admission reuses a LEADING chain), so along any chain
refcounts are non-increasing with depth. Two consequences the allocator
leans on: (1) every refcount-0 resident digest is reachable leaf-first
— evicting the deepest refcount-0 digest never strands a held
descendant; (2) an offloaded digest can only be re-referenced through
an admission that first restores it, because any deeper hit restores
the whole leading chain.

int8 KV blocks compose with ``inference/quant.py``: storage carries
int8 codes plus one f32 scale per row per head, quantized on scatter
and dequantized inside the gather (fused into the attention read).
Offload payloads are raw host copies of the stored dtype (int8 codes +
scales for int8 pools, native fp rows otherwise), so an
offload→restore round trip is bitwise for both.
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.telemetry import get_registry

__all__ = ["KvPool", "PoolExhausted", "Admission", "HostKvStore",
           "RadixNode", "block_demand", "prefix_hashes",
           "prefix_match_depth", "storage_for", "gather_block_cache",
           "scatter_block_rows", "flat_row_index", "copy_block"]

SACRIFICIAL = 0


class PoolExhausted(RuntimeError):
    """Raised by :meth:`KvPool.admit` when the pool cannot cover a
    request's block demand — the paged analog of
    :class:`~.queue.QueueFull`, carrying the same style of detail so
    admission control can park instead of thrash."""

    def __init__(self, message: str, *, demand: int = 0, free: int = 0,
                 evictable: int = 0, total: int = 0):
        super().__init__(message)
        self.demand = demand
        self.free = free
        self.evictable = evictable
        self.total = total


def block_demand(prompt_len: int, max_new_tokens: int,
                 block_size: int) -> int:
    """Blocks a request must reserve. The last sampled token's KV row is
    never written (retirement happens first), hence ``- 1``; decode
    overshoot past that lands in the sacrificial block."""
    rows = prompt_len + max_new_tokens - 1
    return -(-rows // block_size)


def prefix_hashes(prompt: Sequence[int], block_size: int) -> List[str]:
    """Rolling content hash per FULL prompt block (the partial tail
    block is always private, never cached). Digest ``i`` covers blocks
    ``0..i``, so a digest uniquely names a PATH in the radix tree — two
    prompts share digest ``i`` iff their first ``(i+1)*block_size``
    tokens are identical."""
    out: List[str] = []
    h = hashlib.sha256()
    for i in range(len(prompt) // block_size):
        h.update(np.asarray(prompt[i * block_size:(i + 1) * block_size],
                            np.int64).tobytes())
        out.append(h.hexdigest())
    return out


def prefix_match_depth(hashes: Sequence[str], resident) -> int:
    """Leading blocks of a hash chain present in ``resident`` (a set of
    digests) — the fleet placement scorer's matcher."""
    depth = 0
    while depth < len(hashes) and hashes[depth] in resident:
        depth += 1
    return depth


@dataclasses.dataclass
class Admission:
    """What :meth:`KvPool.admit` hands the backend: the slot's table
    row, where prefill may resume (``resume_from`` — everything before
    it is covered by shared cached blocks), the COW copies to run
    before any chunk writes, and the host→device ``restores`` of
    offloaded blocks this admission reuses."""

    slot: int
    table: np.ndarray                    # [table_width] int32
    resume_from: int
    shared_len: int
    prefix_hits: int
    cow_forks: List[Tuple[int, int]]     # (src, dst) physical ids
    blocks: List[int]
    rows_needed: int
    restores: List[Tuple[int, dict]] = dataclasses.field(
        default_factory=list)            # (dst block id, host payload)


class RadixNode:
    """Path-compressed radix node: ``run`` is a chain of digests with no
    divergence between them; children diverge after the run's tail."""

    __slots__ = ("run", "parent", "children")

    def __init__(self, run: List[str], parent: Optional["RadixNode"]):
        self.run = run
        self.parent = parent
        self.children: List["RadixNode"] = []


class HostKvStore:
    """Host-memory spill target for offloaded KV blocks: an
    insertion-ordered digest → payload map with optional block/byte
    caps. ``put`` returns the digests it had to drop (oldest first) to
    stay under capacity — possibly including the one just put, when a
    single payload exceeds the byte cap."""

    def __init__(self, *, max_blocks: Optional[int] = None,
                 max_bytes: Optional[int] = None):
        self.max_blocks = max_blocks
        self.max_bytes = max_bytes
        self._data: "OrderedDict[str, dict]" = OrderedDict()
        self._sizes: Dict[str, int] = {}
        self._nbytes = 0

    @staticmethod
    def payload_nbytes(payload: dict) -> int:
        return sum(int(np.asarray(a).nbytes) for a in payload.values())

    @property
    def nbytes(self) -> int:
        return self._nbytes

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, digest: str) -> bool:
        return digest in self._data

    def put(self, digest: str, payload: dict) -> List[str]:
        if digest in self._data:
            self.pop(digest)
        nb = self.payload_nbytes(payload)
        self._data[digest] = payload
        self._sizes[digest] = nb
        self._nbytes += nb
        dropped: List[str] = []
        while ((self.max_blocks is not None
                and len(self._data) > self.max_blocks)
               or (self.max_bytes is not None
                   and self._nbytes > self.max_bytes)):
            d, _ = self._data.popitem(last=False)
            self._nbytes -= self._sizes.pop(d)
            dropped.append(d)
            if d == digest:
                break
        return dropped

    def get(self, digest: str) -> Optional[dict]:
        return self._data.get(digest)

    def pop(self, digest: str) -> Optional[dict]:
        payload = self._data.pop(digest, None)
        if payload is not None:
            self._nbytes -= self._sizes.pop(digest)
        return payload

    def stats(self) -> dict:
        return {"blocks": len(self._data), "nbytes": self._nbytes}


class _Cached:
    __slots__ = ("block", "refs", "tokens", "touch")

    def __init__(self, block: Optional[int],
                 tokens: Optional[np.ndarray] = None):
        self.block = block       # physical id; None while offloaded
        self.refs = 0
        self.tokens = tokens     # this block's token ids (replication)
        self.touch = 0


class _SlotMeta:
    __slots__ = ("blocks", "rows_needed", "registered")

    def __init__(self, blocks, rows_needed, registered):
        self.blocks = blocks          # [(block_id, hash-or-None)]
        self.rows_needed = rows_needed
        self.registered = registered  # hashes first published by this slot


class KvPool:
    """Host-side paged-KV allocator. Single-threaded (the engine tick
    discipline); never touches jax.

    ``num_blocks`` counts physical blocks INCLUDING the sacrificial
    block 0, so ``num_blocks - 1`` are allocatable. ``gather_slack_rows``
    widens the table (with sacrificial entries) past ``max_len`` so a
    fixed-shape prefill chunk starting at ``prompt_len - 1`` can always
    slice ``chunk`` rows out of the gathered view without clamping.
    """

    def __init__(self, *, num_blocks: int, block_size: int, num_slots: int,
                 max_len: int, prefix_cache: bool = True,
                 gather_slack_rows: int = 0):
        if block_size < 1 or (block_size & (block_size - 1)) != 0:
            raise ValueError(
                f"block_size must be a positive power of two, got "
                f"{block_size}")
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is sacrificial), got "
                f"{num_blocks}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.num_slots = num_slots
        self.max_len = max_len
        self.prefix_cache = prefix_cache
        self.max_blocks = -(-max_len // block_size)
        ext = -(-(max_len + gather_slack_rows) // block_size)
        self.table_width = ext + 1
        self.table = np.zeros((num_slots, self.table_width), np.int32)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._cached: Dict[str, _Cached] = {}
        # refcount-0 RESIDENT digests, oldest last-touch first — the
        # eviction scan order (leaf-first within that order)
        self._lru: "OrderedDict[str, int]" = OrderedDict()
        self._slot_meta: List[Optional[_SlotMeta]] = [None] * num_slots
        self._root = RadixNode([], None)
        self._node_of: Dict[str, Tuple[RadixNode, int]] = {}
        self._clock = 0
        self._store: Optional[HostKvStore] = None
        self._read_block: Optional[Callable[[int], dict]] = None

    # -- capacity ----------------------------------------------------------

    @property
    def allocatable(self) -> int:
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def evictable_blocks(self) -> int:
        return len(self._lru)

    @property
    def offloaded_blocks(self) -> int:
        return sum(1 for e in self._cached.values() if e.block is None)

    def demand_for(self, prompt_len: int, max_new_tokens: int) -> int:
        return block_demand(prompt_len, max_new_tokens, self.block_size)

    def attach_offload(self, store: HostKvStore,
                       read_block: Callable[[int], dict]) -> None:
        """Arm host offload: under pressure, evicted blocks spill into
        ``store`` (payload = ``read_block(physical_id)``, a dict of host
        arrays in storage dtype) instead of being dropped, and
        :meth:`admit` schedules their restore when a prompt rehits
        them."""
        self._store = store
        self._read_block = read_block

    @property
    def offload_enabled(self) -> bool:
        return self._store is not None and self._read_block is not None

    # -- prefix hashing ----------------------------------------------------

    def prefix_hashes(self, prompt: Sequence[int]) -> List[str]:
        return prefix_hashes(prompt, self.block_size)

    def _lookup(self, hashes: List[str]) -> int:
        # offloaded digests stay in ``_cached`` (block=None) and still
        # count as hits: restoring from host beats recomputing prefill
        hit = 0
        while hit < len(hashes) and hashes[hit] in self._cached:
            hit += 1
        return hit

    def cached_prefix_blocks(self, prompt: Sequence[int]) -> int:
        """Leading full blocks of ``prompt`` already in the cache
        (resident or offloaded) — the router's warm-handoff probe."""
        if not self.prefix_cache:
            return 0
        return self._lookup(self.prefix_hashes(prompt))

    def cached_prefix_entries(
            self, prompt: Sequence[int]) -> List[Tuple[str, int]]:
        """The leading RESIDENT cached full blocks of ``prompt`` as
        ``(hash, physical_block_id)`` pairs — what a KV handoff exports
        from a session's old home replica. Stops at the first offloaded
        digest (export reads device blocks)."""
        if not self.prefix_cache:
            return []
        out: List[Tuple[str, int]] = []
        for h in self.prefix_hashes(prompt):
            ent = self._cached.get(h)
            if ent is None or ent.block is None:
                break
            out.append((h, ent.block))
        return out

    # -- radix tree --------------------------------------------------------

    def _link(self, digest: str, parent: Optional[str]) -> None:
        """Insert ``digest`` as the child of ``parent`` (None = root).
        Extends the parent node's run when the parent is a childless run
        tail; otherwise splits the run after the parent (split on
        divergence) and attaches a fresh leaf."""
        if digest in self._node_of:
            return
        if parent is None or parent not in self._node_of:
            node, pos = self._root, -1
        else:
            node, pos = self._node_of[parent]
        if pos == len(node.run) - 1 and not node.children:
            node.run.append(digest)
            self._node_of[digest] = (node, len(node.run) - 1)
            return
        if pos < len(node.run) - 1:
            self._split(node, pos + 1)
        child = RadixNode([digest], node)
        node.children.append(child)
        self._node_of[digest] = (child, 0)

    def _split(self, node: RadixNode, cut: int) -> None:
        suffix = RadixNode(node.run[cut:], node)
        suffix.children = node.children
        for c in suffix.children:
            c.parent = suffix
        node.run = node.run[:cut]
        node.children = [suffix]
        for j, d in enumerate(suffix.run):
            self._node_of[d] = (suffix, j)

    def _successors(self, digest: str) -> List[str]:
        node, pos = self._node_of[digest]
        if pos + 1 < len(node.run):
            return [node.run[pos + 1]]
        return [c.run[0] for c in node.children]

    def _is_frontier(self, digest: str) -> bool:
        """No RESIDENT descendant: evicting/offloading this digest
        cannot strand a deeper block that still points through it."""
        for s in self._successors(digest):
            ent = self._cached.get(s)
            if ent is not None and ent.block is not None:
                return False
        return True

    def _drop_from(self, digest: str) -> List[str]:
        """Remove ``digest`` AND every deeper digest from the tree,
        returning all removed digests. Entry/block cleanup is the
        caller's job."""
        node, pos = self._node_of[digest]
        removed = list(node.run[pos:])
        del node.run[pos:]
        stack = node.children
        node.children = []
        while stack:
            n = stack.pop()
            removed.extend(n.run)
            stack.extend(n.children)
        for d in removed:
            self._node_of.pop(d, None)
        if node is not self._root and not node.run and not node.children:
            node.parent.children.remove(node)
        return removed

    def _path_digests(self, digest: str) -> List[str]:
        node, pos = self._node_of[digest]
        parts = [node.run[:pos + 1]]
        node = node.parent
        while node is not None:
            parts.append(node.run)
            node = node.parent
        return [d for run in reversed(parts) for d in run]

    def _radix_node_count(self) -> int:
        n = 0
        stack = [self._root]
        while stack:
            node = stack.pop()
            if node is not self._root or node.run:
                n += 1
            stack.extend(node.children)
        return n

    def _touch(self, digest: str) -> None:
        self._clock += 1
        ent = self._cached.get(digest)
        if ent is not None:
            ent.touch = self._clock
        if digest in self._lru:
            self._lru.move_to_end(digest)

    # -- handoff import ----------------------------------------------------

    def take_blocks(self, n: int) -> List[int]:
        """Pop up to ``n`` physical blocks (free first, then block-level
        eviction/offload) for an external write — the import side of a
        KV handoff. Returns fewer than ``n`` when the pool can't cover
        it; the caller seats what fit."""
        out: List[int] = []
        for _ in range(n):
            try:
                out.append(self._alloc())
            except PoolExhausted:
                break
        return out

    def seat_prefix(self, entries: Sequence[Tuple[str, int]], *,
                    chain: Optional[Sequence[str]] = None) -> int:
        """Register externally-written blocks as cached prefix entries
        (refs=0 → evictable, exactly the state :meth:`release` leaves a
        retired slot's published blocks in). The block content must
        already be on device. ``entries`` is a leading hash chain;
        ``chain`` optionally supplies the FULL chain (when the caller
        filtered already-cached digests out of ``entries``) so tree
        parentage stays exact. Skips hashes already resident — returning
        the colliding block to the free list — and revives offloaded
        duplicates in place (the import block becomes the resident
        copy), so a handoff racing a local prefill never
        double-registers."""
        parent_of: Dict[str, Optional[str]] = {}
        seq = list(chain) if chain is not None else [h for h, _ in entries]
        prev: Optional[str] = None
        for h in seq:
            parent_of[h] = prev
            prev = h
        n = 0
        for h, bid in entries:
            if not self.prefix_cache:
                self._free.append(bid)
                continue
            ent = self._cached.get(h)
            if ent is not None:
                if ent.block is None:
                    # offloaded duplicate: the imported device copy
                    # revives it; the host payload is now redundant
                    ent.block = bid
                    if self._store is not None:
                        self._store.pop(h)
                    if ent.refs <= 0:
                        self._lru[h] = bid
                        self._lru.move_to_end(h)
                    self._touch(h)
                    n += 1
                else:
                    self._free.append(bid)
                continue
            self._cached[h] = _Cached(bid)
            self._link(h, parent_of.get(h))
            self._lru[h] = bid
            self._lru.move_to_end(h)
            self._touch(h)
            n += 1
        return n

    def invalidate(self, hashes: Sequence[str]) -> int:
        """Drop cached entries (router KV handoff: a session remapped
        off a sick home replica must not find a stale prefix here).
        Dropping a digest drops its whole subtree — a descendant whose
        ancestor is gone can never be matched again. Ref-held blocks
        merely become unshareable — they free to the free list when
        their last holder releases."""
        n = 0
        for h in hashes:
            if h not in self._cached:
                continue
            for d in self._drop_from(h):
                ent = self._cached.pop(d, None)
                if ent is None:
                    continue
                n += 1
                if ent.block is None:
                    if self._store is not None:
                        self._store.pop(d)
                elif ent.refs <= 0:
                    self._lru.pop(d, None)
                    self._free.append(ent.block)
        return n

    # -- allocation --------------------------------------------------------

    def _alloc(self) -> int:
        if self._free:
            return self._free.pop()
        # leaf-first, oldest-touch-first: scan the eviction clock for
        # the oldest refcount-0 digest with no resident descendant
        for h in self._lru:
            if self._is_frontier(h):
                return self._evict_one(h)
        raise PoolExhausted(
            "kv pool exhausted mid-admission (allocator bug: demand was "
            "pre-checked)", demand=1, free=0, evictable=0,
            total=self.allocatable)

    def _evict_one(self, h: str) -> int:
        reg = get_registry()
        ent = self._cached[h]
        bid = ent.block
        self._lru.pop(h, None)
        reg.counter("serve.kv.evictions").inc()
        if self.offload_enabled:
            payload = self._read_block(bid)
            nbytes = HostKvStore.payload_nbytes(payload)
            dropped = self._store.put(h, payload)
            if h in dropped:
                # a payload the store can't hold at all: hard eviction
                dropped.remove(h)
                reg.counter("serve.kv.offload_dropped").inc()
                self._hard_drop(h)
            else:
                ent.block = None
                reg.counter("serve.kv.offload_out").inc()
                reg.counter("serve.kv.offload_bytes").inc(nbytes)
            for d in dropped:
                self._drop_offloaded(d)
        else:
            self._hard_drop(h)
        return bid

    def _hard_drop(self, h: str) -> None:
        """Remove ``h`` (whose block the caller now owns) and its
        subtree from tree + cache, freeing what the drop strands."""
        for d in self._drop_from(h):
            ent = self._cached.pop(d, None)
            if ent is None or d == h:
                continue
            if ent.block is None:
                if self._store is not None:
                    self._store.pop(d)
                get_registry().counter("serve.kv.offload_dropped").inc()
            elif ent.refs <= 0:
                self._lru.pop(d, None)
                self._free.append(ent.block)

    def _drop_offloaded(self, h: str) -> None:
        """The host store aged digest ``h`` out: drop it and its whole
        subtree (deeper offloaded payloads die with it; stranded
        refcount-0 resident imports free)."""
        reg = get_registry()
        if h not in self._node_of:
            self._cached.pop(h, None)
            reg.counter("serve.kv.offload_dropped").inc()
            return
        for d in self._drop_from(h):
            ent = self._cached.pop(d, None)
            if ent is None:
                continue
            if ent.block is None:
                if self._store is not None:
                    self._store.pop(d)
                reg.counter("serve.kv.offload_dropped").inc()
            elif ent.refs <= 0:
                self._lru.pop(d, None)
                self._free.append(ent.block)

    def _plan(self, prompt_len: int, max_new_tokens: int,
              hashes: Optional[List[str]], chunk: int):
        """(demand, hit, reuse, t0, restores): how many blocks, how many
        cache hits, how many hits survive as read-only shares (vs
        forked), where prefill resumes, and how many reused digests must
        first restore from the host store. ``t0`` must still compute
        position ``prompt_len - 1`` (the first sampled token needs ``h``
        there), so a fully-cached prompt resumes at the last chunk
        boundary and forks the shared blocks its recompute tail
        rewrites."""
        bs = self.block_size
        demand = block_demand(prompt_len, max_new_tokens, bs)
        hit = self._lookup(hashes) if hashes is not None else 0
        shared_len = hit * bs
        t0 = min(shared_len, ((prompt_len - 1) // chunk) * chunk)
        reuse = min(hit, t0 // bs)
        restores = 0
        if hashes is not None:
            restores = sum(1 for i in range(reuse)
                           if self._cached[hashes[i]].block is None)
        return demand, hit, reuse, t0, restores

    def can_admit(self, prompt_len: int, max_new_tokens: int,
                  prompt: Optional[Sequence[int]] = None, *,
                  chunk: int = 1) -> bool:
        """Admission probe: can the pool cover this request right now
        (free + evictable/offloadable, minus shared-prefix hits, plus a
        fresh block per offloaded digest it must restore)? Read-only."""
        hashes = (self.prefix_hashes(prompt)
                  if prompt is not None and self.prefix_cache else None)
        demand, hit, reuse, _, restores = self._plan(
            prompt_len, max_new_tokens, hashes, chunk)
        if demand > self.max_blocks:
            return False
        need = restores + (hit - reuse) + (demand - hit)
        return need <= len(self._free) + len(self._lru)

    def admit(self, slot: int, prompt: Sequence[int],
              max_new_tokens: int, *, chunk: int = 1) -> Admission:
        """Reserve the slot's FULL block demand (no mid-decode OOM),
        reusing cached prefix blocks read-only — restoring offloaded
        ones from the host store first — and forking the ones the
        prefill recompute tail will write. Raises :class:`PoolExhausted`
        without mutating anything when the pool can't cover it."""
        if self._slot_meta[slot] is not None:
            raise RuntimeError(
                f"slot {slot} admitted twice without release (engine "
                f"bookkeeping bug)")
        plen = len(prompt)
        bs = self.block_size
        hashes = self.prefix_hashes(prompt) if self.prefix_cache else None
        demand, hit, reuse, t0, n_restores = self._plan(
            plen, max_new_tokens, hashes, chunk)
        rows = plen + max_new_tokens - 1
        need = n_restores + (hit - reuse) + (demand - hit)
        avail = len(self._free) + len(self._lru)
        if demand > self.max_blocks or need > avail:
            raise PoolExhausted(
                f"request needs {need} blocks ({demand} total, "
                f"{hit} prefix hits, {reuse} reusable) but the pool has "
                f"{len(self._free)} free + {len(self._lru)} evictable of "
                f"{self.allocatable}",
                demand=need, free=len(self._free),
                evictable=len(self._lru), total=self.allocatable)
        reg = get_registry()
        full = plen // bs
        # pin the whole hit chain so mid-admission eviction can never
        # reclaim a block this admission is about to reuse or fork from
        pinned: List[str] = []
        for i in range(hit):
            h = hashes[i]
            if self._cached[h].refs == 0 and h in self._lru:
                self._lru.pop(h)
                pinned.append(h)
        blocks: List[int] = []
        meta_blocks: List[Tuple[int, Optional[str]]] = []
        forks: List[Tuple[int, int]] = []
        restores: List[Tuple[int, dict]] = []
        registered = set()
        for i in range(reuse):                       # read-only shares
            h = hashes[i]
            ent = self._cached[h]
            if ent.block is None:                    # restore from host
                dst = self._alloc()
                payload = (self._store.pop(h)
                           if self._store is not None else None)
                if payload is None:
                    raise RuntimeError(
                        f"offloaded kv block {h[:12]} has no host "
                        f"payload (allocator bug)")
                ent.block = dst
                restores.append((dst, payload))
            ent.refs += 1
            self._touch(h)
            blocks.append(ent.block)
            meta_blocks.append((ent.block, h))
        for i in range(reuse, hit):                  # copy-on-write forks
            h = hashes[i]
            ent = self._cached[h]
            dst = self._alloc()
            if ent.block is None:
                # fork of an offloaded block: fill the private copy
                # straight from the host payload (the cached original
                # stays offloaded, payload retained)
                payload = (self._store.get(h)
                           if self._store is not None else None)
                if payload is None:
                    raise RuntimeError(
                        f"offloaded kv block {h[:12]} has no host "
                        f"payload (allocator bug)")
                restores.append((dst, payload))
            else:
                forks.append((ent.block, dst))
            self._touch(h)
            blocks.append(dst)
            meta_blocks.append((dst, None))
        for i in range(hit, demand):                 # fresh blocks
            bid = self._alloc()
            h = None
            if hashes is not None and i < full:
                # a full prompt block this prefill writes end-to-end:
                # publish it (the write completes before any other
                # admission can hit the entry — single-threaded tick)
                h = hashes[i]
                ent = _Cached(bid, tokens=np.asarray(
                    prompt[i * bs:(i + 1) * bs], np.int64))
                ent.refs = 1
                self._cached[h] = ent
                self._link(h, hashes[i - 1] if i > 0 else None)
                self._touch(h)
                registered.add(h)
            blocks.append(bid)
            meta_blocks.append((bid, h))
        for h in pinned:                             # unpin fork sources
            ent = self._cached.get(h)
            if ent is not None and ent.refs == 0 and ent.block is not None:
                self._lru[h] = ent.block
                self._lru.move_to_end(h)
        row = np.zeros(self.table_width, np.int32)
        row[:demand] = blocks
        self.table[slot, :] = row
        self._slot_meta[slot] = _SlotMeta(meta_blocks, rows, registered)
        if hit:
            reg.counter("serve.kv.prefix_hits").inc(hit)
        if hashes is not None and full > hit:
            reg.counter("serve.kv.prefix_misses").inc(full - hit)
        if hit and full and hit == full:
            # counterfactual gen-1 baseline: a whole-prefix cache (exact
            # full-block prefix match only) would have hit these blocks
            # too; partial hits below are radix-only wins
            reg.counter("serve.kv.prefix_whole_hits").inc(hit)
        if forks:
            reg.counter("serve.kv.cow_forks").inc(len(forks))
        if restores:
            reg.counter("serve.kv.offload_restores").inc(len(restores))
        return Admission(slot=slot, table=row, resume_from=t0,
                         shared_len=hit * bs, prefix_hits=hit,
                         cow_forks=forks, blocks=blocks, rows_needed=rows,
                         restores=restores)

    def release(self, slot: int, *, failed: bool = False) -> None:
        """Retire a slot: zero its table row (the dead slot decodes into
        the sacrificial block from now on), free private blocks, decref
        shared ones — refcount-0 cached blocks become evictable, not
        free (a future prompt may hit them). ``failed=True`` (prefill
        raised mid-write) unpublishes the hashes this admission
        registered: their content is garbage."""
        meta = self._slot_meta[slot]
        self.table[slot, :] = SACRIFICIAL
        if meta is None:
            return
        self._slot_meta[slot] = None
        for bid, h in meta.blocks:
            ent = self._cached.get(h) if h is not None else None
            if ent is not None and ent.block == bid:
                ent.refs -= 1
                if ent.refs <= 0:
                    if failed and h in meta.registered:
                        self._unpublish(h)
                        self._free.append(bid)
                    else:
                        self._lru[h] = bid
                        self._touch(h)
            else:
                self._free.append(bid)

    def _unpublish(self, h: str) -> None:
        """A failed prefill's half-written publish: drop the digest and
        its subtree. The caller frees ``h``'s own block; deeper entries
        are either held by this same slot (freed as their meta entries
        decref to None-cached) or refcount-0 leftovers."""
        if h not in self._node_of:
            self._cached.pop(h, None)
            return
        for d in self._drop_from(h):
            ent = self._cached.pop(d, None)
            if ent is None or d == h:
                continue
            if ent.block is None:
                if self._store is not None:
                    self._store.pop(d)
            elif ent.refs <= 0:
                self._lru.pop(d, None)
                self._free.append(ent.block)

    # -- fleet directory ---------------------------------------------------

    def prefix_digest_summary(self, *, limit: int = 512) -> dict:
        """What a replica advertises over obs frames: resident (and
        offloaded) prefix digests plus occupancy — the fleet placement
        scorer matches an incoming prompt's hash chain against
        ``digests`` and weighs depth by headroom."""
        s = self.stats()
        return {
            "block_size": self.block_size,
            "digests": list(self._cached.keys())[:limit],
            "occupancy": s["occupancy"],
            "blocks_free": s["blocks_free"],
            "blocks_total": s["blocks_total"],
        }

    def hot_prefixes(self, min_refs: int, *, limit: int = 4) -> List[dict]:
        """Digests shared by at least ``min_refs`` live slots, deepest
        first, with the full token chain from the root (reconstructable
        only for locally-published blocks — imports carry no tokens).
        The fleet controller replicates these to siblings proactively."""
        cands = sorted(
            (d for d, e in self._cached.items()
             if e.refs >= min_refs and e.block is not None
             and d in self._node_of),
            key=lambda d: (-self._cached[d].refs,
                           -len(self._path_digests(d))))
        out: List[dict] = []
        covered: set = set()
        for d in cands:
            if len(out) >= limit:
                break
            if d in covered:
                continue
            path = self._path_digests(d)
            toks: List[int] = []
            ok = True
            for p in path:
                ent = self._cached.get(p)
                if ent is None or ent.tokens is None:
                    ok = False
                    break
                toks.extend(int(t) for t in ent.tokens)
            if not ok:
                continue
            covered.update(path)
            out.append({"digest": d, "refs": self._cached[d].refs,
                        "depth": len(path), "tokens": toks})
        return out

    # -- metrics -----------------------------------------------------------

    def stats(self) -> dict:
        total = self.allocatable
        live = [m for m in self._slot_meta if m is not None]
        reserved = sum(len(m.blocks) for m in live)
        needed = sum(m.rows_needed for m in live)
        in_use = total - len(self._free) - len(self._lru)
        resident = sum(1 for e in self._cached.values()
                       if e.block is not None)
        return {
            "blocks_total": total,
            "blocks_free": len(self._free),
            "blocks_evictable": len(self._lru),
            "blocks_in_use": in_use,
            "blocks_offloaded": len(self._cached) - resident,
            "occupancy": in_use / total if total else 0.0,
            # internal fragmentation: reserved rows the live requests can
            # never write (tail of each slot's last block)
            "fragmentation": (1.0 - needed / (reserved * self.block_size)
                              if reserved else 0.0),
            "cached_blocks": resident,
            "shared_blocks": sum(
                1 for e in self._cached.values()
                if e.refs > 1 and e.block is not None),
            "radix_nodes": self._radix_node_count(),
            "host_kv_bytes": (self._store.nbytes
                              if self._store is not None else 0),
        }

    def observe(self) -> None:
        reg = get_registry()
        for k, v in self.stats().items():
            reg.gauge(f"serve.kv.{k}").set(float(v))


# -- device-side indexing (compiled into the backends' programs) -----------

def storage_for(proto, n_layers: int, num_blocks: int, block_size: int, *,
                kv_dtype: Optional[str] = None):
    """Pool device arrays ``[n_layers, num_blocks, block_size, ...]``
    from one layer's attention-cache prototype (``make_cache(1, L)``).
    ``kv_dtype="int8"`` stores int8 codes + one f32 scale per row per
    head (``inference/quant.py`` discipline, applied to KV rows)."""
    if not (isinstance(proto, dict) and set(proto) == {"k", "v"}):
        raise TypeError(
            "paged KV needs a {'k','v'} attention cache prototype, got "
            f"{type(proto).__name__} with "
            f"{sorted(proto) if isinstance(proto, dict) else '?'}")
    out = {}
    for name, a in proto.items():
        shape = (n_layers, num_blocks, block_size) + tuple(a.shape[2:])
        if kv_dtype is None:
            out[name] = jnp.zeros(shape, a.dtype)
        elif kv_dtype == "int8":
            out[name] = jnp.zeros(shape, jnp.int8)
            out[name + "_scale"] = jnp.zeros(shape[:-1] + (1,), jnp.float32)
        else:
            raise ValueError(
                f"kv_dtype must be None or 'int8', got {kv_dtype!r}")
    return out


def flat_row_index(table_row, positions, block_size: int):
    """Physical flat row index for each position: block-table gather
    with the block index CLAMPED to the trailing sacrificial entry, so
    any position past the reserved region maps into block 0."""
    mb = table_row.shape[-1] - 1
    bi = jnp.minimum(positions // block_size, mb)
    return jnp.take(table_row, bi) * block_size + positions % block_size


def gather_block_cache(pool_layer, table_row, *, block_size: int,
                       compute_dtype):
    """One slot's rows as a contiguous ``{'k','v'} [1, R, ...]`` view
    (R = ``(table_width - 1) * block_size``). The layer's ``decode``
    runs on this view unchanged — garbage rows from sacrificial/unwritten
    blocks sit at positions the causal mask kills exactly (``-1e30``
    underflows to 0.0 in the softmax), the same bitwise argument the
    slab backends already rely on. int8 pools dequantize here, fused
    into the attention read."""
    mb = table_row.shape[-1] - 1

    def g(name):
        rows = jnp.take(pool_layer[name], table_row[:mb], axis=0)
        return rows.reshape((mb * block_size,) + rows.shape[2:])

    if "k_scale" in pool_layer:
        return {name: (g(name).astype(jnp.float32) *
                       g(name + "_scale")).astype(compute_dtype)[None]
                for name in ("k", "v")}
    return {name: g(name)[None] for name in ("k", "v")}


def scatter_block_rows(pool_layer, flat_idx, rows):
    """Write new KV rows ``{'k': [M, ...], 'v': [M, ...]}`` at physical
    flat indices ``[M]`` (duplicate sacrificial indices may collide —
    block 0 content is never read un-masked, so any winner is fine).
    int8 pools quantize per row per head on the way in."""
    from ..inference.quant import quantize_kv_rows
    out = dict(pool_layer)
    int8 = "k_scale" in pool_layer

    def flat(a):
        return a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:])

    for name in ("k", "v"):
        a = pool_layer[name]
        if int8:
            q, s = quantize_kv_rows(rows[name])
            out[name] = flat(a).at[flat_idx].set(q).reshape(a.shape)
            sa = pool_layer[name + "_scale"]
            out[name + "_scale"] = flat(sa).at[flat_idx].set(s).reshape(
                sa.shape)
        else:
            out[name] = flat(a).at[flat_idx].set(
                rows[name].astype(a.dtype)).reshape(a.shape)
    return out


def copy_block(pool, src, dst, *, block_axis: int = 1):
    """COW fork: copy physical block ``src`` → ``dst`` across every
    array of the pool (all layers at once — a block is ``block_size``
    rows of EVERY layer under one table entry)."""
    def cp(a):
        blk = jax.lax.dynamic_slice_in_dim(a, src, 1, axis=block_axis)
        return jax.lax.dynamic_update_slice_in_dim(a, blk, dst,
                                                   axis=block_axis)

    return jax.tree_util.tree_map(cp, pool)
