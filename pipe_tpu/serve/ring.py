"""Continuous batching over the stage ring: slots = ring groups.

:class:`~..inference.pipelined.PipelinedGenerator` keeps every stage
busy by chasing ``n_stages`` request groups around the ring — but it
decodes one fixed batch to completion: the ring drains as groups finish
and refills only on the next ``generate`` call. This backend makes the
ring **continuously** full: each of the ``n_stages`` slots is a ring
group that can be retired and re-admitted independently, mid-flight,
without touching the other groups' in-flight state.

The trick is that the decode program carries the ring across host
ticks. One tick = ``revolutions * n_stages`` cycles of the same
wavefront recurrence as ``PipelinedGenerator`` (stage ``s``, cycle
``c`` works group ``(c - s) mod n``), but the carry — per-stage
activation ``h``, the wrap-edge token, per-stage per-group write
positions — is device-resident state returned to the host and fed back
next tick, with a monotonically increasing global cycle counter ``c0``.
Admission is a host table write: prefill walks the new prompt through
the stages (one serial ring pass, writing cache rows ``[0, p)``),
samples the first token, and the host arms ``admit_cycle[g] = c0 + g``
— the exact cycle stage 0 next meets group ``g``. Stage ``s`` treats
group ``g`` as valid from ``admit_cycle[g] + s`` on, so the new
request's wavefront threads between the live groups' wavefronts without
any of them noticing; invalid (stage, cycle, group) combinations write
to the sacrificial cache region past ``max_len``, the same masked-slot
discipline as the generators.

Like the single-device backend, the decode program is traced once
(``serve.ring.decode_traces`` pins it) and prefill compiles per prompt
bucket. Parity: requests through this backend — greedy AND sampled —
match the one-shot single-device ``Generator`` token-for-token. The
sampler threads the Generator split chain through the revolutions:
each stage carries its own device-resident per-group key table
(``key_local``, the ``pos_local`` discipline applied to PRNG state),
advancing its row by one split per valid cycle, so the key stage
``n-1`` samples with at cycle ``t`` is bitwise the ``t``-th split of
the request's seed key. That shared chain is what lets the
speculative lane extend here: a spec revolution injects a K-token
draft/verify wavefront per group (stage 0 drafts and owns
tok/pos/history, stage ``n-1`` verifies, advances the key chain by
the accepted count in-program, and rides its verdict back to stage 0
on the ring's wrap edge), emitting 1..K Generator-exact tokens per
group per revolution.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..inference.draft import DraftSource, resolve_draft
from ..inference.generate import (GenerationConfig, head_logits,
                                  sample_logits)
from ..inference.quant import QuantLeaf, dequant_tree
from ..obs.telemetry import get_registry
from ..parallel.mesh import STAGE_AXIS
from ..utils.compat import shard_map
from .buckets import BucketSpec
from .kvpool import (KvPool, copy_block, flat_row_index,
                     gather_block_cache, scatter_block_rows)

__all__ = ["RingSlotBackend"]

_REBASE = 1 << 20   # keep the int32 cycle counter far from overflow


class RingSlotBackend:
    """``n_stages`` decode slots riding the pipeline ring, one request
    per group (rpg=1). Params are the ``PipelinedGenerator`` layout:
    ``stage_params`` stacked ``[n_stages, ...]`` and sharded over the
    ``stage`` mesh axis."""

    def __init__(self, mesh: Mesh, model, stage_params, pre_params,
                 post_params, *, max_len: int,
                 gen: GenerationConfig = GenerationConfig(),
                 buckets: Optional[BucketSpec] = None,
                 revolutions: int = 1, shape_cache_warn: int = 8,
                 kv_block_size: Optional[int] = None,
                 kv_pool_blocks: Optional[int] = None,
                 prefill_chunk: int = 16,
                 kv_dtype: Optional[str] = None,
                 kv_offload: bool = False,
                 kv_offload_blocks: Optional[int] = None,
                 resident="auto", resident_revolutions: int = 8,
                 spec_tokens: Optional[int] = None,
                 draft="ngram", draft_stages: int = 1,
                 spec_branches: Optional[int] = None,
                 spec_adaptive: bool = False):
        if STAGE_AXIS not in mesh.axis_names:
            raise ValueError(f"mesh must have a {STAGE_AXIS!r} axis")
        if not hasattr(model, "embed_at"):
            raise TypeError(
                f"{type(model).__name__} has no embed_at; KV-cache "
                "generation needs position-offset embedding")
        if gen.num_beams != 1:
            raise ValueError(
                "the serve engine decodes greedy/sampled slots; beam "
                "search has no incremental slot form (num_beams must be 1)")
        if revolutions < 1:
            raise ValueError(
                f"revolutions must be >= 1, got {revolutions}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2, got {max_len}")
        self.mesh = mesh
        self.model = model
        self.gen = gen
        self.buckets = buckets
        self.max_len = max_len
        self.n = mesh.shape[STAGE_AXIS]
        self.num_slots = self.n
        self.decode_chunk = revolutions   # tokens per slot per tick
        self.shape_cache_warn = shape_cache_warn
        # resident tri-state, exactly the single-device semantics:
        # "auto" keeps the cpu default on the byte-for-byte
        # single-launch path
        if resident not in ("auto", True, False):
            raise ValueError(
                f"resident must be 'auto', True or False, got {resident!r}")
        if resident == "auto":
            resident = jax.devices()[0].platform != "cpu"
        self.resident = bool(resident)
        if resident_revolutions < 1:
            raise ValueError(
                f"resident_revolutions must be >= 1, got "
                f"{resident_revolutions}")
        self.resident_revolutions = resident_revolutions
        # the engine's deadline horizon speaks in "resident chunks";
        # for the ring one chunk is one revolution
        self.resident_chunks = resident_revolutions
        spec = spec_tokens if spec_tokens is not None \
            else gen.spec_tokens
        if spec is not None and spec < 2:
            raise ValueError(f"spec_tokens must be >= 2, got {spec}")
        if spec is not None and not self.resident:
            raise ValueError(
                "spec_tokens needs the resident loop (the draft/verify "
                "wavefront IS the resident revolution); pass "
                "resident=True")
        self.spec_tokens = spec
        # resident readout stride: 1 token per revolution, or a K-token
        # row per spec round
        self.decode_width = spec if spec is not None else 1
        if spec is not None:
            self._drafter = draft if isinstance(draft, DraftSource) \
                else resolve_draft(
                    draft, n_stages=mesh.shape[STAGE_AXIS],
                    layers_per_stage=len(stage_params),
                    draft_stages=draft_stages,
                    spec_branches=spec_branches)
            if self._drafter.branches > 1:
                raise ValueError(
                    "tree draft is single-device only: the ring verify "
                    "chunk is the linear K-row wavefront message (pick "
                    "draft='ngram' or 'truncated')")
            if self._drafter.name == "truncated" and draft_stages != 1:
                raise ValueError(
                    f"ring truncated draft needs draft_stages=1 (only "
                    f"stage 0's layers are resident where the draft "
                    f"runs), got {draft_stages}")
            if spec_adaptive:
                raise ValueError(
                    "spec_adaptive is single-device only: the ring's "
                    "in-flight wavefront carry is K-shaped, so a rung "
                    "switch would orphan every in-flight round")
            self._spec_overshoot = spec - 1
            self._spec_acc_total = 0
            self._spec_draft_total = 0
        else:
            if not (draft == "ngram" and draft_stages == 1
                    and spec_branches is None and not spec_adaptive):
                raise ValueError(
                    "draft/draft_stages/spec_branches/spec_adaptive "
                    "configure the speculative lane; set "
                    "gen.spec_tokens")
            self._drafter = None
            self._spec_overshoot = 0
        self._stage_params = stage_params
        self._pre = pre_params
        self._post = post_params
        self._lps = len(stage_params)

        n = self.n
        cd = model.cfg.compute_dtype
        nh, hd = model.block.attn.nhead, model.block.attn.head_dim
        stage_sh = NamedSharding(mesh, P(STAGE_AXIS))
        self._stage_sh = stage_sh

        kbs = kv_block_size if kv_block_size is not None \
            else gen.kv_block_size
        self.paged = kbs is not None
        if self.paged:
            # paged KV over the ring: every stage holds the pool rows for
            # ITS layers ([lps, num_blocks, bs, ...] per shard). The block
            # table is layer- and stage-agnostic — one table entry
            # addresses the same physical block id in each shard — so the
            # host-side KvPool needs no ring awareness at all.
            if kv_dtype is not None:
                raise NotImplementedError(
                    "int8 KV blocks are single-device only for now; the "
                    "ring pool stores the compute dtype")
            if kv_offload:
                raise NotImplementedError(
                    "kv_offload is single-device only for now: spilling "
                    "a block means a host read of every stage's shard "
                    "of it, which the ring's sharded pool layout does "
                    "not expose yet")
            if buckets is not None:
                gen.check_kv_headroom(buckets.max_len, kbs,
                                      self._spec_overshoot)
            if prefill_chunk < 1:
                raise ValueError(
                    f"prefill_chunk must be >= 1, got {prefill_chunk}")
            self.prefill_chunk = prefill_chunk
            mb = -(-max_len // kbs)
            nb = kv_pool_blocks if kv_pool_blocks is not None \
                else n * mb + 1
            self.pool = KvPool(
                num_blocks=nb, block_size=kbs, num_slots=n,
                max_len=max_len, prefix_cache=gen.prefix_cache,
                gather_slack_rows=prefill_chunk)
            self._caches = {
                name: jax.device_put(jnp.zeros(
                    (n * self._lps, nb, kbs, nh, hd), cd), stage_sh)
                for name in ("k", "v")}
            # positions >= the reserved region clamp into table entry 0 —
            # the paged replacement for the slab's sacrificial region
            self._sacpos = (self.pool.table_width - 1) * kbs
            self._fork_jit = jax.jit(self._fork_fn, donate_argnums=(0,))
        else:
            if kv_dtype is not None:
                raise ValueError(
                    "kv_dtype needs the paged pool (set kv_block_size); "
                    "the slab path stores KV in the compute dtype")
            if kv_offload:
                raise ValueError(
                    "kv_offload needs the paged pool (set kv_block_size); "
                    "the slab path has no block-level eviction to spill")
            self.pool = None
            # sacrificial region: big enough to absorb a q=max_bucket
            # prefill write from an inactive stage, any post-retirement
            # decode overshoot within a tick, AND a q=K spec verify
            # chunk from an invalid (stage, cycle, group) combination
            max_bucket = buckets.max_len if buckets is not None \
                else max_len
            self._cache_len = max_len + max(
                max_bucket, spec if spec is not None else 1)
            self._sac = max_len
            self._caches = {
                "k": jax.device_put(jnp.zeros(
                    (n * self._lps, n, 1, self._cache_len, nh, hd), cd),
                    stage_sh),
                "v": jax.device_put(jnp.zeros(
                    (n * self._lps, n, 1, self._cache_len, nh, hd), cd),
                    stage_sh)}
        self._h = jax.device_put(
            jnp.zeros((n, 1, model.cfg.d_model), cd), stage_sh)
        self._tok_ring = jax.device_put(jnp.zeros((n,), jnp.int32),
                                        stage_sh)
        self._pos_local = jax.device_put(jnp.zeros((n, n), jnp.int32),
                                         stage_sh)
        # per-stage per-group PRNG state: stage s's row of group g's key
        # table, advanced by one split per valid cycle — every stage
        # replays the same Generator chain so stage n-1's sample at
        # generation step t uses bitwise the t-th split of the seed key
        kd0 = np.asarray(jax.random.key_data(jax.random.key(0)))
        self._kd_shape = kd0.shape
        self._key_local = jax.device_put(
            jnp.asarray(np.broadcast_to(
                kd0, (n, n) + kd0.shape).copy()), stage_sh)
        if spec is not None:
            # stage-0-authoritative spec state (other stages' rows are
            # shape-consistent garbage, never read across the psum):
            # current token, draft history, and the in-flight wavefront
            # message ring (h chunk, chunk tokens, base position,
            # validity, and the completion fields riding the wrap edge)
            self._tok_local = jax.device_put(
                jnp.zeros((n, n), jnp.int32), stage_sh)
            self._hist_local = jax.device_put(
                jnp.full((n, n, max_len + spec), gen.pad_token_id,
                         jnp.int32), stage_sh)
            self._spec_msg = {
                "h": jax.device_put(
                    jnp.zeros((n, spec, model.cfg.d_model), cd),
                    stage_sh),
                "x": jax.device_put(
                    jnp.zeros((n, spec), jnp.int32), stage_sh),
                "pos0": jax.device_put(
                    jnp.zeros((n,), jnp.int32), stage_sh),
                "vmsg": jax.device_put(
                    jnp.zeros((n,), jnp.int32), stage_sh),
                "t_seq": jax.device_put(
                    jnp.zeros((n, spec), jnp.int32), stage_sh),
                "n_emit": jax.device_put(
                    jnp.zeros((n,), jnp.int32), stage_sh),
                "cvalid": jax.device_put(
                    jnp.zeros((n,), jnp.int32), stage_sh),
            }

        # host tables (replicated program inputs)
        self._c0 = 0
        self._admit = np.zeros(n, np.int32)
        self._live_default = np.zeros(n, np.int32)
        self._tok_inject = np.zeros(n, np.int32)
        self._programs = {}

    # -- validation --------------------------------------------------------

    def validate(self, prompt_len: int, max_new_tokens: int) -> None:
        bucket = (self.buckets.bucket_for(prompt_len)
                  if self.buckets is not None and not self.paged
                  else prompt_len)
        if self.paged and self.pool.demand_for(
                prompt_len, max_new_tokens) > self.pool.allocatable:
            raise ValueError(
                f"request needs "
                f"{self.pool.demand_for(prompt_len, max_new_tokens)} KV "
                f"blocks but the whole pool holds "
                f"{self.pool.allocatable}; raise kv_pool_blocks or "
                f"shorten the request")
        if prompt_len + max_new_tokens + self._spec_overshoot \
                > self.max_len:
            extra = (f" + speculative headroom {self._spec_overshoot}"
                     if self._spec_overshoot else "")
            raise ValueError(
                f"prompt_len {prompt_len} + max_new_tokens "
                f"{max_new_tokens}{extra} exceeds the slot cache "
                f"({self.max_len} rows); raise max_len or shorten the "
                f"request")
        if max_new_tokens > self.gen.max_new_tokens:
            raise ValueError(
                f"max_new_tokens {max_new_tokens} exceeds the engine cap "
                f"({self.gen.max_new_tokens})")
        mp = getattr(self.model, "max_position", None)
        limit = mp() if callable(mp) else None
        need = max(bucket, prompt_len + max_new_tokens
                   + max(self.decode_chunk - 1, self._spec_overshoot))
        if limit is not None and need > limit:
            raise ValueError(
                f"request needs position {need} but the positional "
                f"table has {limit}")

    # -- shared device pieces ---------------------------------------------

    def _ring(self, x):
        n = self.n
        return jax.lax.ppermute(x, STAGE_AXIS,
                                [(i, (i + 1) % n) for i in range(n)])

    def _local_blocks(self, stage_params):
        cd = self.model.cfg.compute_dtype

        def local_slice(a):
            if isinstance(a, QuantLeaf):
                return QuantLeaf(q=a.q[0], scale=a.scale[0])
            return a[0].astype(cd)

        blocks = [jax.tree_util.tree_map(
                      local_slice, bp,
                      is_leaf=lambda x: isinstance(x, QuantLeaf))
                  for bp in stage_params]
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)

    def _run_blocks(self, block_stack, h, caches, grp, pos):
        """This stage's layers on ``h`` against group ``grp``'s slab —
        the ``PipelinedGenerator._run_blocks`` recurrence."""
        m = self.model
        cd = m.cfg.compute_dtype
        lps = self._lps

        def slab_slice(a):
            s = jax.lax.dynamic_slice(
                a, (0, grp) + (0,) * (a.ndim - 2),
                (lps, 1) + a.shape[2:])
            return jnp.squeeze(s, axis=1)

        def slab_write(a, new):
            return jax.lax.dynamic_update_slice(
                a, new[:, None], (0, grp) + (0,) * (a.ndim - 2))

        slab = jax.tree_util.tree_map(slab_slice, caches)

        def layer_step(h_c, inp):
            bp, cache = inp
            h_new, cache = m.block.decode(dequant_tree(bp, cd), h_c,
                                          cache, pos)
            return h_new, cache

        h, new_slab = jax.lax.scan(layer_step, h, (block_stack, slab))
        caches = jax.tree_util.tree_map(slab_write, caches, new_slab)
        return h, caches

    def _run_blocks_paged(self, block_stack, h, caches, trow, pos):
        """The paged analog of :meth:`_run_blocks`: this stage's layers
        on ``h`` against the gathered block view of the slot whose table
        row is ``trow``. The ``q = h.shape[1]`` new rows at ``pos`` are
        scattered back through the table; positions past the reserved
        region (inactive stages, dead groups) clamp into the sacrificial
        block. The layer decode itself is unchanged — the slab/paged
        bitwise-parity argument from ``serve/kvpool.py`` applies per
        stage."""
        m = self.model
        cd = m.cfg.compute_dtype
        bs = self.pool.block_size
        q = h.shape[1]
        ridx = flat_row_index(
            trow, pos + jnp.arange(q, dtype=jnp.int32), bs)

        def layer_step(h_c, inp):
            bp, pool_l = inp
            cache = gather_block_cache(pool_l, trow, block_size=bs,
                                       compute_dtype=cd)
            h_new, c2 = m.block.decode(dequant_tree(bp, cd), h_c, cache,
                                       pos)
            rows = {name: jax.lax.dynamic_slice(
                        c2[name], (0, pos) + (0,) * (c2[name].ndim - 2),
                        (1, q) + c2[name].shape[2:])[0]
                    for name in ("k", "v")}
            return h_new, scatter_block_rows(pool_l, ridx, rows)

        h, new_caches = jax.lax.scan(layer_step, h, (block_stack, caches))
        return h, new_caches

    # -- device programs ---------------------------------------------------

    def _prefill_fn(self, stage_params, pre, post, caches, pos_local,
                    prompt, true_len, slot, key):
        """One serial ring pass of the padded prompt: cycle ``i`` stage
        ``i`` runs its layers (q = bucket len) on the h arriving from
        stage ``i-1``, writing cache rows [0, B) of group ``slot``'s
        slab; stage n-1 samples the first token on the last cycle. The
        in-flight decode carry (h ring, wrap token) is untouched — live
        groups never notice an admission."""
        m, gen, n = self.model, self.gen, self.n
        cd = m.cfg.compute_dtype
        s = jax.lax.axis_index(STAGE_AXIS)
        get_registry().counter("serve.ring.prefill_traces").inc()
        block_stack = self._local_blocks(stage_params)
        pos_row = pos_local[0]                          # [n_groups]

        def cycle(carry, i):
            h_carry, caches, tok0 = carry
            active = (s == i)
            pos_w = jnp.where(active, 0, self._sac)
            h_embed = m.embed_at(pre, prompt, 0)        # [1, B, d]
            h_in = jnp.where(s == 0, h_embed, h_carry)
            h_out, caches = self._run_blocks(block_stack, h_in, caches,
                                             slot, pos_w)
            h_last = jax.lax.dynamic_slice(
                h_out, (0, true_len - 1, 0), (1, 1, h_out.shape[-1]))
            logits = head_logits(m, post, h_last)[:, 0, :]
            # `key` arrives pre-split: the host consumed k0 = key(seed)
            # as k1, sub = split(k0), passes sub here and arms the
            # stage key tables with k1 — the exact Generator chain
            tok = sample_logits(logits, key, gen)[0]
            emit = active & (s == n - 1)
            tok0 = jnp.where(emit, tok, tok0)
            return (self._ring(h_out), caches, tok0), None

        h0 = jnp.zeros((1, prompt.shape[1], m.cfg.d_model), cd)
        (_, caches, tok0), _ = jax.lax.scan(
            cycle, (h0, caches, jnp.int32(0)), jnp.arange(n))
        tok0 = jax.lax.psum(jnp.where(s == n - 1, tok0, 0), STAGE_AXIS)
        pos_row = jax.lax.dynamic_update_slice(
            pos_row, true_len[None], (slot,))
        return caches, pos_row[None], tok0

    def _step_key(self, key_row, grp, valid):
        """One Generator split on this stage's key row for ``grp``
        (frozen when the cycle is invalid): returns the sample key and
        the advanced table."""
        kd_g = jax.lax.dynamic_index_in_dim(key_row, grp, 0,
                                            keepdims=False)
        k2, sub = jax.random.split(jax.random.wrap_key_data(kd_g))
        new_kd = jnp.where(valid, jax.random.key_data(k2), kd_g)
        key_row = jax.lax.dynamic_update_slice(
            key_row, new_kd[None], (grp,) + (0,) * (key_row.ndim - 1))
        return sub, key_row

    def _decode_fn(self, stage_params, pre, post, caches, h_carry,
                   tok_ring, pos_local, key_local, c0, admit, live,
                   tok_inject):
        """``revolutions`` ring revolutions with a persistent carry. Per
        cycle ``c = c0 + i``: stage ``s`` works group ``grp = (c - s)
        mod n``; the group is valid here iff it is live and its
        admission wavefront has reached this stage (``c >= admit[grp] +
        s``); stage 0 swaps in the prefill-sampled token exactly at
        ``c == admit[grp]``. Invalid work lands in the sacrificial cache
        region. Sampling advances each stage's local key table by one
        split per valid cycle — the Generator chain. Traced once — the
        counter pins it."""
        m, gen, n = self.model, self.gen, self.n
        cd = m.cfg.compute_dtype
        R = self.decode_chunk
        s = jax.lax.axis_index(STAGE_AXIS)
        get_registry().counter("serve.ring.decode_traces").inc()
        block_stack = self._local_blocks(stage_params)
        eos = gen.eos_token_id

        def cycle(carry, i):
            h_carry, tok_ring, caches, pos_row, key_row, emitted = carry
            c = c0 + i
            grp = jnp.mod(c - s, n)
            adm = jnp.take(admit, grp)
            valid = (jnp.take(live, grp) != 0) & (c >= adm + s)
            pos = jnp.take(pos_row, grp)
            pos_use = jnp.where(valid, pos, self._sac)
            inject = c == adm
            tok_use = jnp.where(inject, jnp.take(tok_inject, grp),
                                tok_ring[0])
            h_embed = m.embed_at(pre, tok_use[None, None], pos_use)
            h_in = jnp.where(s == 0, h_embed, h_carry)
            h_out, caches = self._run_blocks(block_stack, h_in, caches,
                                             grp, pos_use)
            logits = head_logits(m, post, h_out)[:, 0, :]   # [1, V]
            sub, key_row = self._step_key(key_row, grp, valid)
            tok_out = sample_logits(logits, sub, gen)
            emit = (s == n - 1) & valid
            r = i // n
            old = jax.lax.dynamic_slice(emitted, (grp, r), (1, 1))[0, 0]
            emitted = jax.lax.dynamic_update_slice(
                emitted, jnp.where(emit, tok_out[0], old)[None, None],
                (grp, r))
            pos_row = jax.lax.dynamic_update_slice(
                pos_row, jnp.where(valid, pos + 1, pos)[None], (grp,))
            return (self._ring(h_out), self._ring(tok_out), caches,
                    pos_row, key_row, emitted), None

        emitted0 = jnp.zeros((n, R), jnp.int32)
        (h_carry, tok_ring, caches, pos_row, key_row, emitted), _ = \
            jax.lax.scan(
                cycle, (h_carry, tok_ring, caches, pos_local[0],
                        key_local[0], emitted0),
                jnp.arange(n * R))
        emitted = jax.lax.psum(
            jnp.where(s == n - 1, emitted, 0), STAGE_AXIS)
        return (caches, h_carry, tok_ring, pos_row[None],
                key_row[None], emitted)

    # -- paged device programs ---------------------------------------------

    def _prefill_chunk_fn(self, stage_params, pre, post, caches, tokens,
                          t0, true_len, trow, key):
        """THE ring prefill program: one fixed-shape ``[1, C]`` chunk at
        a traced offset, walked around the ring once (cycle ``i`` stage
        ``i`` active, exactly :meth:`_prefill_fn`'s serial pass), looped
        on the host until the prompt is covered — ANY prompt length, one
        compile, where the slab path keys a program per bucket. Inactive
        stages write their C rows at the sacrificial position; stage
        ``n - 1`` samples the chunk's candidate first token (the host
        keeps the last chunk's — only there does ``true_len - 1`` fall
        inside the chunk). The in-flight decode carry is untouched."""
        m, gen, n = self.model, self.gen, self.n
        cd = m.cfg.compute_dtype
        s = jax.lax.axis_index(STAGE_AXIS)
        get_registry().counter("serve.ring.prefill_chunk_traces").inc()
        block_stack = self._local_blocks(stage_params)

        def cycle(carry, i):
            h_carry, caches, tok0 = carry
            active = (s == i)
            pos_w = jnp.where(active, t0, self._sacpos)
            h_embed = m.embed_at(pre, tokens, t0)        # [1, C, d]
            h_in = jnp.where(s == 0, h_embed, h_carry)
            h_out, caches = self._run_blocks_paged(
                block_stack, h_in, caches, trow, pos_w)
            idx = jnp.clip(true_len - 1 - t0, 0, tokens.shape[1] - 1)
            h_last = jax.lax.dynamic_slice(
                h_out, (0, idx, 0), (1, 1, h_out.shape[-1]))
            logits = head_logits(m, post, h_last)[:, 0, :]
            tok = sample_logits(logits, key, gen)[0]   # key = pre-split sub
            emit = active & (s == n - 1)
            tok0 = jnp.where(emit, tok, tok0)
            return (self._ring(h_out), caches, tok0), None

        h0 = jnp.zeros((1, tokens.shape[1], m.cfg.d_model), cd)
        (_, caches, tok0), _ = jax.lax.scan(
            cycle, (h0, caches, jnp.int32(0)), jnp.arange(n))
        tok0 = jax.lax.psum(jnp.where(s == n - 1, tok0, 0), STAGE_AXIS)
        return caches, tok0

    def _fork_fn(self, caches, src, dst):
        """Copy-on-write block copy across every stage's layer shard
        (src/dst traced — one program for every fork; the copy is
        block-axis local, so it never crosses the stage sharding)."""
        get_registry().counter("serve.kv.fork_traces").inc()
        return copy_block(caches, src, dst, block_axis=1)

    def _decode_paged_fn(self, stage_params, pre, post, caches, h_carry,
                         tok_ring, pos_local, key_local, c0, admit,
                         live, tok_inject, tables):
        """:meth:`_decode_fn` with the slab slice/write swapped for the
        pool gather/scatter: stage ``s`` looks up group ``grp``'s table
        row and runs the SAME wavefront recurrence. Invalid (stage,
        cycle, group) work decodes at the sacrificial position, and
        released groups additionally carry all-zero table rows — a dead
        group can never touch a reallocated block. Traced once (the
        counter pins it)."""
        m, gen, n = self.model, self.gen, self.n
        R = self.decode_chunk
        s = jax.lax.axis_index(STAGE_AXIS)
        get_registry().counter("serve.ring.decode_traces").inc()
        block_stack = self._local_blocks(stage_params)

        def cycle(carry, i):
            h_carry, tok_ring, caches, pos_row, key_row, emitted = carry
            c = c0 + i
            grp = jnp.mod(c - s, n)
            adm = jnp.take(admit, grp)
            valid = (jnp.take(live, grp) != 0) & (c >= adm + s)
            pos = jnp.take(pos_row, grp)
            pos_use = jnp.where(valid, pos, self._sacpos)
            inject = c == adm
            tok_use = jnp.where(inject, jnp.take(tok_inject, grp),
                                tok_ring[0])
            h_embed = m.embed_at(pre, tok_use[None, None], pos_use)
            h_in = jnp.where(s == 0, h_embed, h_carry)
            trow = jax.lax.dynamic_index_in_dim(tables, grp, 0,
                                                keepdims=False)
            h_out, caches = self._run_blocks_paged(
                block_stack, h_in, caches, trow, pos_use)
            logits = head_logits(m, post, h_out)[:, 0, :]   # [1, V]
            sub, key_row = self._step_key(key_row, grp, valid)
            tok_out = sample_logits(logits, sub, gen)
            emit = (s == n - 1) & valid
            r = i // n
            old = jax.lax.dynamic_slice(emitted, (grp, r), (1, 1))[0, 0]
            emitted = jax.lax.dynamic_update_slice(
                emitted, jnp.where(emit, tok_out[0], old)[None, None],
                (grp, r))
            pos_row = jax.lax.dynamic_update_slice(
                pos_row, jnp.where(valid, pos + 1, pos)[None], (grp,))
            return (self._ring(h_out), self._ring(tok_out), caches,
                    pos_row, key_row, emitted), None

        emitted0 = jnp.zeros((n, R), jnp.int32)
        (h_carry, tok_ring, caches, pos_row, key_row, emitted), _ = \
            jax.lax.scan(
                cycle, (h_carry, tok_ring, caches, pos_local[0],
                        key_local[0], emitted0),
                jnp.arange(n * R))
        emitted = jax.lax.psum(
            jnp.where(s == n - 1, emitted, 0), STAGE_AXIS)
        return (caches, h_carry, tok_ring, pos_row[None],
                key_row[None], emitted)

    # -- resident device program -------------------------------------------

    def _resident_impl(self, paged, stage_params, pre, post, caches,
                       h_carry, tok_ring, pos_local, key_local, c0,
                       admit, live, tok_inject, budget, r_max,
                       tables=None):
        """The resident ring loop: a ``lax.while_loop`` whose body is
        ONE revolution of the exact wavefront recurrence above — the
        body stays switch-free (masked arithmetic + ppermute/psum, the
        ``compile_phases`` discipline; the 0-dispatch pin is
        ``tools/hlo_audit.py --resident``). Each revolution's emissions
        are psum'd so every stage can advance the replicated per-group
        ``done``/``budget`` carry; ``done`` joins the validity mask, so
        finished groups freeze (their writes route to the sacrificial
        region) instead of overshooting. Exits early when any live
        group goes done — a slot freed, host admission can matter — or
        after ``r_max`` revolutions (the deadline horizon). One host
        sync per launch: the revolution count."""
        m, gen, n = self.model, self.gen, self.n
        R = self.resident_revolutions
        s = jax.lax.axis_index(STAGE_AXIS)
        get_registry().counter("serve.ring.resident_traces").inc()
        block_stack = self._local_blocks(stage_params)
        eos = gen.eos_token_id
        sac = self._sacpos if paged else self._sac

        def body(state):
            h_carry, tok_ring, caches, pos_row, key_row, emitted, \
                done, budget, r = state

            def cycle(carry, j):
                h_carry, tok_ring, caches, pos_row, key_row, rev_tok, \
                    rev_emit = carry
                c = c0 + r * n + j
                grp = jnp.mod(c - s, n)
                adm = jnp.take(admit, grp)
                valid = (jnp.take(live, grp) != 0) \
                    & ~jnp.take(done, grp) & (c >= adm + s)
                pos = jnp.take(pos_row, grp)
                pos_use = jnp.where(valid, pos, sac)
                inject = c == adm
                tok_use = jnp.where(inject, jnp.take(tok_inject, grp),
                                    tok_ring[0])
                h_embed = m.embed_at(pre, tok_use[None, None], pos_use)
                h_in = jnp.where(s == 0, h_embed, h_carry)
                if paged:
                    trow = jax.lax.dynamic_index_in_dim(
                        tables, grp, 0, keepdims=False)
                    h_out, caches = self._run_blocks_paged(
                        block_stack, h_in, caches, trow, pos_use)
                else:
                    h_out, caches = self._run_blocks(
                        block_stack, h_in, caches, grp, pos_use)
                logits = head_logits(m, post, h_out)[:, 0, :]
                sub, key_row = self._step_key(key_row, grp, valid)
                tok_out = sample_logits(logits, sub, gen)
                emit = (s == n - 1) & valid
                old_t = jax.lax.dynamic_slice(rev_tok, (grp,), (1,))[0]
                rev_tok = jax.lax.dynamic_update_slice(
                    rev_tok, jnp.where(emit, tok_out[0], old_t)[None],
                    (grp,))
                old_e = jax.lax.dynamic_slice(rev_emit, (grp,), (1,))[0]
                rev_emit = jax.lax.dynamic_update_slice(
                    rev_emit, jnp.where(emit, jnp.int32(1), old_e)[None],
                    (grp,))
                pos_row = jax.lax.dynamic_update_slice(
                    pos_row, jnp.where(valid, pos + 1, pos)[None], (grp,))
                return (self._ring(h_out), self._ring(tok_out), caches,
                        pos_row, key_row, rev_tok, rev_emit), None

            z = jnp.zeros((n,), jnp.int32)
            (h_carry, tok_ring, caches, pos_row, key_row, rev_tok,
             rev_emit), _ = jax.lax.scan(
                cycle, (h_carry, tok_ring, caches, pos_row, key_row,
                        z, z),
                jnp.arange(n))
            rev_tok = jax.lax.psum(
                jnp.where(s == n - 1, rev_tok, 0), STAGE_AXIS)
            rev_emit = jax.lax.psum(
                jnp.where(s == n - 1, rev_emit, 0), STAGE_AXIS)
            emitted = jax.lax.dynamic_update_slice(
                emitted, rev_tok[:, None], (0, r))
            budget = budget - rev_emit
            done = done | (budget <= 0)
            if eos is not None:
                done = done | ((rev_tok == jnp.int32(eos))
                               & (rev_emit > 0))
            return (h_carry, tok_ring, caches, pos_row, key_row,
                    emitted, done, budget, r + 1)

        def cond(state):
            return (state[8] < r_max) & \
                ~jnp.any((live != 0) & state[6])

        emitted0 = jnp.zeros((n, R), jnp.int32)
        done0 = (live == 0) | (budget <= 0)
        state = (h_carry, tok_ring, caches, pos_local[0], key_local[0],
                 emitted0, done0, budget, jnp.int32(0))
        (h_carry, tok_ring, caches, pos_row, key_row, emitted, done,
         budget, r) = jax.lax.while_loop(cond, body, state)
        return (caches, h_carry, tok_ring, pos_row[None],
                key_row[None], emitted, r)

    def _resident_decode_fn(self, stage_params, pre, post, caches,
                            h_carry, tok_ring, pos_local, key_local,
                            c0, admit, live, tok_inject, budget,
                            r_max):
        return self._resident_impl(
            False, stage_params, pre, post, caches, h_carry, tok_ring,
            pos_local, key_local, c0, admit, live, tok_inject,
            budget, r_max)

    def _resident_decode_paged_fn(self, stage_params, pre, post, caches,
                                  h_carry, tok_ring, pos_local,
                                  key_local, c0, admit, live,
                                  tok_inject, tables, budget, r_max):
        return self._resident_impl(
            True, stage_params, pre, post, caches, h_carry, tok_ring,
            pos_local, key_local, c0, admit, live, tok_inject,
            budget, r_max, tables=tables)

    # -- speculative resident device program -------------------------------
    #
    # A spec revolution pipelines one draft/verify ROUND per group as a
    # K-row wavefront. Stage 0 owns the authoritative per-group state
    # (token, position, draft history): each cycle it applies the
    # completion the ring's wrap edge just delivered (stage n-1's
    # verdict for the round it injected n cycles earlier — the wrap
    # edge is group-aligned, so the verdict lands exactly one cycle
    # before the next injection), drafts K-1 continuations, and
    # launches the next chunk. Stages 1..n-2 run their layers on the
    # arriving K-row chunk. Stage n-1 owns the key table: it samples
    # the K-deep Generator split chain over the chunk logits, accepts
    # the matching draft prefix plus one correction token, advances
    # the group's key by the accepted count in-program, and rides the
    # verdict back to stage 0. Rejected rows sit at positions >= the
    # advanced pos, causally masked and re-written by the next round's
    # K-row chunk before any unmasked read — the same
    # rollback-overwrite law as the single-device lane, so accepted
    # tokens are bitwise the sequential Generator chain.

    def _spec_draft(self, paged, block_stack, caches, pre, hist_row,
                    tok_g, pos_g, pos_d, grp, trow):
        """Stage-local draft proposal for one group: K-1 candidate
        continuations of ``tok_g``. The n-gram drafter reads the
        stage-0 history table; the truncated drafter rolls this
        stage's own layers (draft_stages=1: stage 0's layers ARE the
        model's strict prefix) greedily with a tied-embedding head,
        writing draft KV rows at ``pos_d..pos_d+K-2`` — sacrificial
        everywhere but a validly-injecting stage 0, and re-written by
        the verify chunk there (the rollback-overwrite law)."""
        m, K = self.model, self.spec_tokens
        if self._drafter.name == "ngram":
            hrow = jax.lax.dynamic_index_in_dim(hist_row, grp, 0,
                                                keepdims=False)
            idx = jnp.arange(hrow.shape[0], dtype=jnp.int32)
            mask = (hrow == tok_g) & (idx < pos_g)
            j = jnp.max(jnp.where(mask, idx, jnp.int32(-1)))
            start = jnp.maximum(j + 1, 0)
            drafts = jax.lax.dynamic_slice(hrow, (start,), (K - 1,))
            return drafts, caches
        table = pre["embed"]["table"].astype(jnp.float32)
        cur = tok_g
        outs = []
        for i in range(K - 1):
            h = m.embed_at(pre, cur[None, None], pos_d + i)
            if paged:
                h, caches = self._run_blocks_paged(
                    block_stack, h, caches, trow, pos_d + i)
            else:
                h, caches = self._run_blocks(
                    block_stack, h, caches, grp, pos_d + i)
            logits = h[0, 0].astype(jnp.float32) @ table.T
            cur = jnp.argmax(logits).astype(jnp.int32)
            outs.append(cur)
        return jnp.stack(outs), caches

    def _resident_spec_impl(self, paged, stage_params, pre, post,
                            caches, msg, tok_local, pos_local,
                            key_local, hist_local, c0, admit, live,
                            budget, r_max, tables=None):
        """The resident spec ring loop: one revolution = one
        draft/verify round per group, pipelined as the K-row wavefront
        described above. Completions are recorded at stage 0 and
        psum'd at each revolution end so every stage advances the
        replicated done/budget identically; the one-revolution lag of
        that replicated view never causes an overshoot round — stage 0
        applies each completion BEFORE the same-cycle injection
        decision, through the revolution-local ``done_now`` mask."""
        m, gen, n = self.model, self.gen, self.n
        K = self.spec_tokens
        R = self.resident_revolutions
        s = jax.lax.axis_index(STAGE_AXIS)
        get_registry().counter("serve.ring.resident_traces").inc()
        block_stack = self._local_blocks(stage_params)
        eos = gen.eos_token_id
        sac = self._sacpos if paged else self._sac
        ar = jnp.arange(K, dtype=jnp.int32)

        def body(state):
            (msg, caches, tok_row, pos_row, key_row, hist_row,
             emitted, counts, done, budget, r) = state

            def cycle(carry, j):
                (msg, caches, tok_row, pos_row, key_row, hist_row,
                 done_now, rev_tok, rev_emit) = carry
                c = c0 + r * n + j
                grp = jnp.mod(c - s, n)
                adm = jnp.take(admit, grp)
                lv = jnp.take(live, grp) != 0
                x_arr = msg["x"][0]
                p0_arr = msg["pos0"][0]
                vm_arr = msg["vmsg"][0] != 0
                tseq = msg["t_seq"][0]
                ne_arr = msg["n_emit"][0]
                cv_arr = msg["cvalid"][0] != 0

                # -- completion application: gate out stale verdicts
                # (a retired-and-readmitted group re-arms ``admit``
                # past every in-flight injection cycle)
                app = cv_arr & lv & (c - n >= adm) \
                    & ~jnp.take(done, grp)
                napp = jnp.where(app, ne_arr, jnp.int32(0))
                pg = jnp.take(pos_row, grp)
                last = tseq[jnp.maximum(napp - 1, 0)]
                tok_row = jax.lax.dynamic_update_slice(
                    tok_row,
                    jnp.where(app, last, jnp.take(tok_row, grp))[None],
                    (grp,))
                hrow_g = jax.lax.dynamic_index_in_dim(
                    hist_row, grp, 0, keepdims=False)
                cur_h = jax.lax.dynamic_slice(hrow_g, (pg + 1,), (K,))
                hrow_g = jax.lax.dynamic_update_slice(
                    hrow_g, jnp.where(ar < napp, tseq, cur_h),
                    (pg + 1,))
                hist_row = jax.lax.dynamic_update_slice(
                    hist_row, hrow_g[None], (grp, 0))
                pos_row = jax.lax.dynamic_update_slice(
                    pos_row, (pg + napp)[None], (grp,))
                old_t = jax.lax.dynamic_slice(
                    rev_tok, (grp, 0), (1, K))[0]
                rev_tok = jax.lax.dynamic_update_slice(
                    rev_tok, jnp.where(app, tseq, old_t)[None],
                    (grp, 0))
                old_e = jax.lax.dynamic_slice(rev_emit, (grp,), (1,))[0]
                rev_emit = jax.lax.dynamic_update_slice(
                    rev_emit, jnp.where(app, napp, old_e)[None], (grp,))
                g_done = jnp.take(budget, grp) - napp <= 0
                if eos is not None:
                    g_done = g_done | jnp.any(
                        (tseq == jnp.int32(eos)) & (ar < napp))
                done_now = jax.lax.dynamic_update_slice(
                    done_now,
                    (jnp.take(done_now, grp) | (app & g_done))[None],
                    (grp,))

                # -- injection (stage 0): draft against the
                # just-advanced group state, launch the next chunk
                inj = lv & ~jnp.take(done_now, grp) & (c >= adm)
                use_inj = s == 0
                tok_g = jnp.take(tok_row, grp)
                pos_g = jnp.take(pos_row, grp)
                trow = (jax.lax.dynamic_index_in_dim(
                            tables, grp, 0, keepdims=False)
                        if paged else None)
                pos_d = jnp.where(use_inj & inj, pos_g, sac)
                drafts, caches = self._spec_draft(
                    paged, block_stack, caches, pre, hist_row, tok_g,
                    pos_g, pos_d, grp, trow)
                x_new = jnp.concatenate([tok_g[None], drafts])

                v_here = jnp.where(use_inj, inj,
                                   vm_arr & lv & (c >= adm + s))
                pos_chunk = jnp.where(
                    v_here, jnp.where(use_inj, pos_g, p0_arr), sac)
                x_here = jnp.where(use_inj, x_new, x_arr)
                h_embed = m.embed_at(pre, x_new[None, :], pos_chunk)
                h_in = jnp.where(use_inj, h_embed, msg["h"])
                if paged:
                    h_out, caches = self._run_blocks_paged(
                        block_stack, h_in, caches, trow, pos_chunk)
                else:
                    h_out, caches = self._run_blocks(
                        block_stack, h_in, caches, grp, pos_chunk)

                # -- verification (stage n-1): K-deep Generator split
                # chain, accept matching prefix + 1 correction, key
                # advanced by the accepted count
                logits = head_logits(m, post, h_out)[0]    # [K, V]
                kd_g = jax.lax.dynamic_index_in_dim(
                    key_row, grp, 0, keepdims=False)

                def sp(cdat, _):
                    k2, sub = jax.random.split(
                        jax.random.wrap_key_data(cdat))
                    c2 = jax.random.key_data(k2)
                    return c2, (c2, jax.random.key_data(sub))

                _, (carries, subs) = jax.lax.scan(
                    sp, kd_g, None, length=K)
                t = jax.vmap(lambda lg, sd: sample_logits(
                    lg[None], jax.random.wrap_key_data(sd), gen)[0])(
                        logits, subs)                      # [K]
                lead = jnp.cumprod(
                    (x_here[1:] == t[:K - 1]).astype(jnp.int32))
                ne_new = jnp.where(
                    v_here & (s == n - 1),
                    jnp.int32(1) + jnp.sum(lead), jnp.int32(0))
                sel = jnp.concatenate(
                    [kd_g[None], carries], axis=0)[ne_new]
                key_row = jax.lax.dynamic_update_slice(
                    key_row, sel[None],
                    (grp,) + (0,) * (key_row.ndim - 1))

                msg_out = {
                    "h": h_out,
                    "x": x_here[None],
                    "pos0": jnp.where(use_inj, pos_g, p0_arr)[None],
                    "vmsg": jnp.where(
                        use_inj, inj, vm_arr).astype(jnp.int32)[None],
                    "t_seq": jnp.where(s == n - 1, t, tseq)[None],
                    "n_emit": jnp.where(
                        s == n - 1, ne_new, ne_arr)[None],
                    "cvalid": jnp.where(
                        s == n - 1, v_here,
                        jnp.where(use_inj, False, cv_arr))
                        .astype(jnp.int32)[None],
                }
                msg = jax.tree_util.tree_map(self._ring, msg_out)
                return (msg, caches, tok_row, pos_row, key_row,
                        hist_row, done_now, rev_tok, rev_emit), None

            rt0 = jnp.zeros((n, K), jnp.int32)
            re0 = jnp.zeros((n,), jnp.int32)
            (msg, caches, tok_row, pos_row, key_row, hist_row,
             done_now, rev_tok, rev_emit), _ = jax.lax.scan(
                cycle, (msg, caches, tok_row, pos_row, key_row,
                        hist_row, done, rt0, re0),
                jnp.arange(n))
            rev_tok = jax.lax.psum(
                jnp.where(s == 0, rev_tok, 0), STAGE_AXIS)
            rev_emit = jax.lax.psum(
                jnp.where(s == 0, rev_emit, 0), STAGE_AXIS)
            emitted = jax.lax.dynamic_update_slice(
                emitted, rev_tok, (0, r * K))
            counts = jax.lax.dynamic_update_slice(
                counts, rev_emit[:, None], (0, r))
            budget = budget - rev_emit
            done = done | (budget <= 0)
            if eos is not None:
                done = done | jnp.any(
                    (rev_tok == jnp.int32(eos))
                    & (ar[None, :] < rev_emit[:, None]), axis=1)
            return (msg, caches, tok_row, pos_row, key_row, hist_row,
                    emitted, counts, done, budget, r + 1)

        def cond(state):
            return (state[10] < r_max) & \
                ~jnp.any((live != 0) & state[8])

        emitted0 = jnp.full((n, R * K), jnp.int32(gen.pad_token_id),
                            jnp.int32)
        counts0 = jnp.zeros((n, R), jnp.int32)
        done0 = (live == 0) | (budget <= 0)
        if eos is not None:
            e0 = jax.lax.psum(
                jnp.where((s == 0) & (tok_local[0] == jnp.int32(eos)),
                          1, 0), STAGE_AXIS)
            done0 = done0 | (e0 > 0)
        state = (msg, caches, tok_local[0], pos_local[0], key_local[0],
                 hist_local[0], emitted0, counts0, done0, budget,
                 jnp.int32(0))
        (msg, caches, tok_row, pos_row, key_row, hist_row, emitted,
         counts, done, budget, r) = jax.lax.while_loop(
            cond, body, state)
        return (caches, msg, tok_row[None], pos_row[None],
                key_row[None], hist_row[None], emitted, counts, r)

    def _resident_spec_fn(self, stage_params, pre, post, caches, msg,
                          tok_local, pos_local, key_local, hist_local,
                          c0, admit, live, budget, r_max):
        return self._resident_spec_impl(
            False, stage_params, pre, post, caches, msg, tok_local,
            pos_local, key_local, hist_local, c0, admit, live,
            budget, r_max)

    def _resident_spec_paged_fn(self, stage_params, pre, post, caches,
                                msg, tok_local, pos_local, key_local,
                                hist_local, c0, admit, live, tables,
                                budget, r_max):
        return self._resident_spec_impl(
            True, stage_params, pre, post, caches, msg, tok_local,
            pos_local, key_local, hist_local, c0, admit, live,
            budget, r_max, tables=tables)

    # -- backend API -------------------------------------------------------

    def _build(self, kind, B=None):
        pspec = jax.tree_util.tree_map(lambda _: P(STAGE_AXIS),
                                       self._stage_params)
        pre_spec = jax.tree_util.tree_map(lambda _: P(), self._pre)
        post_spec = jax.tree_util.tree_map(lambda _: P(), self._post)
        cache_spec = jax.tree_util.tree_map(lambda _: P(STAGE_AXIS),
                                            self._caches)
        S = P(STAGE_AXIS)
        if kind == "prefill":
            in_specs = (pspec, pre_spec, post_spec, cache_spec,
                        S, P(), P(), P(), P())
            out_specs = (cache_spec, S, P())
            fn = self._prefill_fn
        elif kind == "chunk":
            in_specs = (pspec, pre_spec, post_spec, cache_spec,
                        P(), P(), P(), P(), P())
            out_specs = (cache_spec, P())
            fn = self._prefill_chunk_fn
        elif kind == "decode_paged":
            in_specs = (pspec, pre_spec, post_spec, cache_spec,
                        S, S, S, S, P(), P(), P(), P(), P())
            out_specs = (cache_spec, S, S, S, S, P())
            fn = self._decode_paged_fn
        elif kind == "resident":
            in_specs = (pspec, pre_spec, post_spec, cache_spec,
                        S, S, S, S, P(), P(), P(), P(), P(), P())
            out_specs = (cache_spec, S, S, S, S, P(), P())
            fn = self._resident_decode_fn
        elif kind == "resident_paged":
            in_specs = (pspec, pre_spec, post_spec, cache_spec,
                        S, S, S, S, P(), P(), P(), P(), P(), P(), P())
            out_specs = (cache_spec, S, S, S, S, P(), P())
            fn = self._resident_decode_paged_fn
        elif kind == "resident_spec":
            msg_spec = jax.tree_util.tree_map(lambda _: S,
                                              self._spec_msg)
            in_specs = (pspec, pre_spec, post_spec, cache_spec,
                        msg_spec, S, S, S, S, P(), P(), P(), P(), P())
            out_specs = (cache_spec, msg_spec, S, S, S, S,
                         P(), P(), P())
            fn = self._resident_spec_fn
        elif kind == "resident_spec_paged":
            msg_spec = jax.tree_util.tree_map(lambda _: S,
                                              self._spec_msg)
            in_specs = (pspec, pre_spec, post_spec, cache_spec,
                        msg_spec, S, S, S, S, P(), P(), P(), P(), P(),
                        P())
            out_specs = (cache_spec, msg_spec, S, S, S, S,
                         P(), P(), P())
            fn = self._resident_spec_paged_fn
        else:
            in_specs = (pspec, pre_spec, post_spec, cache_spec,
                        S, S, S, S, P(), P(), P(), P())
            out_specs = (cache_spec, S, S, S, S, P())
            fn = self._decode_fn
        return jax.jit(shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))

    def prefill(self, slot: int, prompt: Sequence[int], seed: int,
                max_new_tokens: Optional[int] = None) -> int:
        reg = get_registry()
        if self.paged:
            return self._prefill_paged(
                slot, prompt, seed,
                max_new_tokens if max_new_tokens is not None
                else self.gen.max_new_tokens)
        if self.buckets is not None:
            padded, p = self.buckets.pad(prompt, self.gen.pad_token_id)
        else:
            padded, p = list(prompt), len(prompt)
        B = len(padded)
        run = self._programs.get(("prefill", B))
        if run is None:
            reg.counter("serve.engine.prefill_program_misses").inc()
            run = self._build("prefill", B)
            self._programs[("prefill", B)] = run
            n_pre = sum(1 for k in self._programs if k[0] == "prefill")
            reg.gauge("serve.engine.prefill_programs").set(n_pre)
            if self.buckets is None and n_pre == self.shape_cache_warn + 1:
                import warnings
                warnings.warn(
                    f"ring serve backend compiled {n_pre} distinct "
                    f"prefill programs with bucketing DISABLED — every "
                    f"new prompt length recompiles. Pass a BucketSpec "
                    f"to cap the program cache.",
                    RuntimeWarning, stacklevel=3)
        else:
            reg.counter("serve.engine.prefill_program_hits").inc()
        arr = jnp.asarray(padded, jnp.int32)[None, :]
        k1, sub = jax.random.split(jax.random.key(seed))
        caches, pos_local, tok0 = run(
            self._stage_params, self._pre, self._post, self._caches,
            self._pos_local, arr, jnp.int32(p), jnp.int32(slot), sub)
        self._caches = caches
        self._pos_local = pos_local
        tok0 = int(tok0)
        self._arm_slot(slot, len(prompt), tok0, k1, prompt)
        return tok0

    def _arm_slot(self, slot, plen, tok0, k_next, prompt):
        """Host admission-table writes shared by both prefill paths:
        the admit cycle, the inject token, every stage's key-table row
        (the chain tail after the prefill's split — an np round-trip,
        the ``pos_local`` arming discipline), and in spec mode the
        stage-0-authoritative token/history rows."""
        self._admit[slot] = self._c0 + slot
        self._tok_inject[slot] = tok0
        kl = np.array(self._key_local)
        kl[:, slot] = np.asarray(jax.random.key_data(k_next))
        self._key_local = jax.device_put(jnp.asarray(kl),
                                         self._stage_sh)
        if self.spec_tokens is not None:
            tl = np.array(self._tok_local)
            tl[:, slot] = tok0
            self._tok_local = jax.device_put(jnp.asarray(tl),
                                             self._stage_sh)
            row = np.full(self._hist_local.shape[-1],
                          self.gen.pad_token_id, np.int32)
            row[:plen] = np.asarray(prompt, np.int32)
            row[plen] = tok0
            hl = np.array(self._hist_local)
            hl[:, slot, :] = row
            self._hist_local = jax.device_put(jnp.asarray(hl),
                                              self._stage_sh)

    def _prefill_paged(self, slot: int, prompt: Sequence[int], seed: int,
                       max_new_tokens: int) -> int:
        """Admit into the pool (reserving full demand), run the COW
        forks, stream the prompt's recompute tail through the one chunk
        program (one serial ring pass per chunk), then arm the host
        admission tables exactly as the slab prefill does. A failure
        mid-stream releases the reservation and unpublishes half-written
        cache entries."""
        plen = len(prompt)
        adm = self.pool.admit(slot, prompt, max_new_tokens,
                              chunk=self.prefill_chunk)
        try:
            for src, dst in adm.cow_forks:
                self._caches = self._fork_jit(
                    self._caches, jnp.int32(src), jnp.int32(dst))
            run = self._programs.get("chunk")
            if run is None:
                run = self._build("chunk")
                self._programs["chunk"] = run
            trow = jnp.asarray(adm.table)
            C = self.prefill_chunk
            pad = self.gen.pad_token_id
            k1, sub = jax.random.split(jax.random.key(seed))
            t = adm.resume_from
            tok0 = 0
            while t < plen:
                toks = list(prompt[t:t + C])
                toks += [pad] * (C - len(toks))
                arr = jnp.asarray(toks, jnp.int32)[None, :]
                self._caches, tok0 = run(
                    self._stage_params, self._pre, self._post,
                    self._caches, arr, jnp.int32(t), jnp.int32(plen),
                    trow, sub)
                t += C
            tok0 = int(tok0)
        except Exception:
            self.pool.release(slot, failed=True)
            raise
        self._arm_slot(slot, plen, tok0, k1, prompt)
        pl = np.array(self._pos_local)
        pl[:, slot] = plen
        self._pos_local = jax.device_put(jnp.asarray(pl), self._stage_sh)
        return tok0

    def decode(self, live: np.ndarray,
               budgets: Optional[np.ndarray] = None,
               r_max: Optional[int] = None):
        """One tick = ``revolutions`` tokens per live slot. Returns
        ``(tokens [S, R], valid [S, R])``; validity accounts for
        admission wavefronts still filling the ring.

        With ``budgets`` on a resident backend the call runs the
        RESIDENT loop: up to ``r_max`` revolutions in one device
        program with on-device done-masking and early exit. Without
        ``budgets`` the single-launch path runs even when
        ``resident=True`` — the parity reference. Speculative slots
        are resident-only (the wavefront needs the on-device done
        mask), so spec mode requires ``budgets``."""
        if self.spec_tokens is not None and budgets is None:
            raise ValueError(
                "ring speculative decode is resident-only: pass "
                "budgets so the K-token wavefront can done-mask on "
                "device")
        if self.resident and budgets is not None:
            return self._decode_resident(live, budgets, r_max)
        n, R = self.n, self.decode_chunk
        live = np.asarray(live).astype(np.int32)
        kind = "decode_paged" if self.paged else "decode"
        run = self._programs.get(kind)
        if run is None:
            run = self._build(kind)
            self._programs[kind] = run
        args = (
            self._stage_params, self._pre, self._post, self._caches,
            self._h, self._tok_ring, self._pos_local, self._key_local,
            jnp.int32(self._c0), jnp.asarray(self._admit),
            jnp.asarray(live), jnp.asarray(self._tok_inject))
        if self.paged:
            args = args + (jnp.asarray(self.pool.table),)
        caches, h, tok_ring, pos_local, key_local, emitted = run(*args)
        self._caches, self._h = caches, h
        self._tok_ring, self._pos_local = tok_ring, pos_local
        self._key_local = key_local
        toks = np.asarray(emitted)                       # [n, R]
        g = np.arange(n)[:, None]
        r = np.arange(R)[None, :]
        emit_cycle = self._c0 + r * n + (g + n - 1) % n
        valid = (live[:, None] != 0) & \
            (emit_cycle >= self._admit[:, None] + n - 1)
        self._c0 += n * R
        if self._c0 > _REBASE:
            shift = self._c0
            self._c0 = 0
            self._admit = np.maximum(
                self._admit - shift, -np.int32(_REBASE)).astype(np.int32)
        return toks, valid

    def _decode_resident(self, live: np.ndarray, budgets: np.ndarray,
                         r_max: Optional[int]):
        """One resident launch: up to ``r_max`` revolutions on device,
        ONE host sync (the revolution count) to size the readout."""
        if self.spec_tokens is not None:
            return self._decode_resident_spec(live, budgets, r_max)
        reg = get_registry()
        n, R = self.n, self.resident_revolutions
        rm = R if r_max is None else max(1, min(int(r_max), R))
        live = np.asarray(live).astype(np.int32)
        kind = "resident_paged" if self.paged else "resident"
        run = self._programs.get(kind)
        if run is None:
            run = self._build(kind)
            self._programs[kind] = run
        args = (
            self._stage_params, self._pre, self._post, self._caches,
            self._h, self._tok_ring, self._pos_local, self._key_local,
            jnp.int32(self._c0), jnp.asarray(self._admit),
            jnp.asarray(live), jnp.asarray(self._tok_inject))
        if self.paged:
            args = args + (jnp.asarray(self.pool.table),)
        args = args + (jnp.asarray(np.asarray(budgets, np.int32)),
                       jnp.int32(rm))
        (caches, h, tok_ring, pos_local, key_local, emitted,
         r_ran) = run(*args)
        self._caches, self._h = caches, h
        self._tok_ring, self._pos_local = tok_ring, pos_local
        self._key_local = key_local
        r_ran = int(r_ran)                   # THE host sync
        if r_ran < rm:
            reg.counter("serve.engine.device_exits").inc()
        toks = np.asarray(emitted)[:, :r_ran]
        g = np.arange(n)[:, None]
        r = np.arange(r_ran)[None, :]
        emit_cycle = self._c0 + r * n + (g + n - 1) % n
        valid = (live[:, None] != 0) & \
            (emit_cycle >= self._admit[:, None] + n - 1)
        self._c0 += n * r_ran
        if self._c0 > _REBASE:
            shift = self._c0
            self._c0 = 0
            self._admit = np.maximum(
                self._admit - shift, -np.int32(_REBASE)).astype(np.int32)
        return toks, valid

    def _decode_resident_spec(self, live: np.ndarray,
                              budgets: np.ndarray,
                              r_max: Optional[int]):
        """Spec resident launch: the readout is a ``[S, r*K]`` token
        grid with per-round accepted counts. Validity comes from the
        counts alone — stage 0 only records completions for admitted
        groups, so there is no admission arithmetic to redo here."""
        reg = get_registry()
        n, R, K = self.n, self.resident_revolutions, self.spec_tokens
        rm = R if r_max is None else max(1, min(int(r_max), R))
        live = np.asarray(live).astype(np.int32)
        kind = "resident_spec_paged" if self.paged else "resident_spec"
        run = self._programs.get(kind)
        if run is None:
            run = self._build(kind)
            self._programs[kind] = run
        args = (
            self._stage_params, self._pre, self._post, self._caches,
            self._spec_msg, self._tok_local, self._pos_local,
            self._key_local, self._hist_local,
            jnp.int32(self._c0), jnp.asarray(self._admit),
            jnp.asarray(live))
        if self.paged:
            args = args + (jnp.asarray(self.pool.table),)
        args = args + (jnp.asarray(np.asarray(budgets, np.int32)),
                       jnp.int32(rm))
        (caches, msg, tok_local, pos_local, key_local, hist_local,
         emitted, counts, r_ran) = run(*args)
        self._caches, self._spec_msg = caches, msg
        self._tok_local, self._pos_local = tok_local, pos_local
        self._key_local, self._hist_local = key_local, hist_local
        r_ran = int(r_ran)                   # THE host sync
        if r_ran < rm:
            reg.counter("serve.engine.device_exits").inc()
        counts = np.asarray(counts)[:, :r_ran]           # [n, r]
        toks = np.asarray(emitted)[:, :r_ran * K]        # [n, r*K]
        valid = (np.arange(K)[None, None, :]
                 < counts[:, :, None]).reshape(n, r_ran * K)
        valid &= live[:, None] != 0
        self._c0 += n * r_ran
        if self._c0 > _REBASE:
            shift = self._c0
            self._c0 = 0
            self._admit = np.maximum(
                self._admit - shift, -np.int32(_REBASE)).astype(np.int32)
        # spec telemetry, the single-device lane's exact surface (no
        # EWMA row — the ring has no adaptive ladder)
        lmask = live != 0
        lc = counts[lmask]
        rounds = int((lc > 0).sum())
        emitted_n = int(lc.sum())
        reg.counter("serve.engine.spec_rounds").inc(rounds)
        reg.counter("serve.engine.spec_emitted").inc(emitted_n)
        self._spec_acc_total += max(emitted_n - rounds, 0)
        self._spec_draft_total += rounds * (K - 1)
        if self._spec_draft_total:
            reg.gauge("serve.spec.acceptance_rate").set(
                self._spec_acc_total / self._spec_draft_total)
        reg.gauge("serve.spec.draft_cost_frac").set(
            self._drafter.draft_cost_frac(K, self.n * self._lps))
        hist_m = reg.histogram("serve.spec.accept_len")
        for v in lc[lc > 0]:
            hist_m.observe(float(v))
        return toks, valid

    def can_admit(self, prompt_len: int, max_new_tokens: int,
                  prompt: Optional[Sequence[int]] = None) -> bool:
        """Block-availability admission gate (always True for the slab —
        its reservation is the slot itself)."""
        if not self.paged:
            return True
        return self.pool.can_admit(prompt_len, max_new_tokens, prompt,
                                   chunk=self.prefill_chunk)

    def release(self, slot: int) -> None:
        """Engine retirement hook: return the group's blocks to the pool
        (no-op for the slab — the next prefill rewrites the rows)."""
        if self.paged:
            self.pool.release(slot)

    def program_stats(self) -> dict:
        if self.paged:
            return {"prefill_programs": 1,
                    "decode_chunk": self.decode_chunk, "kv": "paged"}
        return {"prefill_programs": sum(
                    1 for k in self._programs
                    if isinstance(k, tuple) and k[0] == "prefill"),
                "decode_chunk": self.decode_chunk, "kv": "slab"}
