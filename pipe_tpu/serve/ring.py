"""Continuous batching over the stage ring: slots = ring groups.

:class:`~..inference.pipelined.PipelinedGenerator` keeps every stage
busy by chasing ``n_stages`` request groups around the ring — but it
decodes one fixed batch to completion: the ring drains as groups finish
and refills only on the next ``generate`` call. This backend makes the
ring **continuously** full: each of the ``n_stages`` slots is a ring
group that can be retired and re-admitted independently, mid-flight,
without touching the other groups' in-flight state.

The trick is that the decode program carries the ring across host
ticks. One tick = ``revolutions * n_stages`` cycles of the same
wavefront recurrence as ``PipelinedGenerator`` (stage ``s``, cycle
``c`` works group ``(c - s) mod n``), but the carry — per-stage
activation ``h``, the wrap-edge token, per-stage per-group write
positions — is device-resident state returned to the host and fed back
next tick, with a monotonically increasing global cycle counter ``c0``.
Admission is a host table write: prefill walks the new prompt through
the stages (one serial ring pass, writing cache rows ``[0, p)``),
samples the first token, and the host arms ``admit_cycle[g] = c0 + g``
— the exact cycle stage 0 next meets group ``g``. Stage ``s`` treats
group ``g`` as valid from ``admit_cycle[g] + s`` on, so the new
request's wavefront threads between the live groups' wavefronts without
any of them noticing; invalid (stage, cycle, group) combinations write
to the sacrificial cache region past ``max_len``, the same masked-slot
discipline as the generators.

Like the single-device backend, the decode program is traced once
(``serve.ring.decode_traces`` pins it) and prefill compiles per prompt
bucket. Parity: greedy requests through this backend match the one-shot
single-device ``Generator`` token-for-token (``tests/test_serve.py``);
sampled requests use a per-request ``fold_in(key, t)`` chain (the
``PipelinedGenerator`` convention), reproducible but intentionally not
the single-device split chain.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..inference.generate import (GenerationConfig, head_logits,
                                  sample_logits)
from ..inference.quant import QuantLeaf, dequant_tree
from ..obs.telemetry import get_registry
from ..parallel.mesh import STAGE_AXIS
from ..utils.compat import shard_map
from .buckets import BucketSpec
from .kvpool import (KvPool, copy_block, flat_row_index,
                     gather_block_cache, scatter_block_rows)

__all__ = ["RingSlotBackend"]

_REBASE = 1 << 20   # keep the int32 cycle counter far from overflow


class RingSlotBackend:
    """``n_stages`` decode slots riding the pipeline ring, one request
    per group (rpg=1). Params are the ``PipelinedGenerator`` layout:
    ``stage_params`` stacked ``[n_stages, ...]`` and sharded over the
    ``stage`` mesh axis."""

    def __init__(self, mesh: Mesh, model, stage_params, pre_params,
                 post_params, *, max_len: int,
                 gen: GenerationConfig = GenerationConfig(),
                 buckets: Optional[BucketSpec] = None,
                 revolutions: int = 1, shape_cache_warn: int = 8,
                 kv_block_size: Optional[int] = None,
                 kv_pool_blocks: Optional[int] = None,
                 prefill_chunk: int = 16,
                 kv_dtype: Optional[str] = None,
                 kv_offload: bool = False,
                 kv_offload_blocks: Optional[int] = None,
                 resident="auto", resident_revolutions: int = 8):
        if STAGE_AXIS not in mesh.axis_names:
            raise ValueError(f"mesh must have a {STAGE_AXIS!r} axis")
        if not hasattr(model, "embed_at"):
            raise TypeError(
                f"{type(model).__name__} has no embed_at; KV-cache "
                "generation needs position-offset embedding")
        if gen.num_beams != 1:
            raise ValueError(
                "the serve engine decodes greedy/sampled slots; beam "
                "search has no incremental slot form (num_beams must be 1)")
        if revolutions < 1:
            raise ValueError(
                f"revolutions must be >= 1, got {revolutions}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2, got {max_len}")
        self.mesh = mesh
        self.model = model
        self.gen = gen
        self.buckets = buckets
        self.max_len = max_len
        self.n = mesh.shape[STAGE_AXIS]
        self.num_slots = self.n
        self.decode_chunk = revolutions   # tokens per slot per tick
        self.decode_width = 1             # resident readout stride
        self.shape_cache_warn = shape_cache_warn
        # resident tri-state, exactly the single-device semantics:
        # "auto" keeps the cpu default on the byte-for-byte
        # single-launch path
        if resident not in ("auto", True, False):
            raise ValueError(
                f"resident must be 'auto', True or False, got {resident!r}")
        if resident == "auto":
            resident = jax.devices()[0].platform != "cpu"
        self.resident = bool(resident)
        if resident_revolutions < 1:
            raise ValueError(
                f"resident_revolutions must be >= 1, got "
                f"{resident_revolutions}")
        self.resident_revolutions = resident_revolutions
        # the engine's deadline horizon speaks in "resident chunks";
        # for the ring one chunk is one revolution
        self.resident_chunks = resident_revolutions
        if gen.spec_tokens is not None:
            raise NotImplementedError(
                "speculative decode is single-device only for now: the "
                "ring's sampled chain is fold_in(key, t), not the "
                "Generator split chain the spec lane replays")
        self._stage_params = stage_params
        self._pre = pre_params
        self._post = post_params
        self._lps = len(stage_params)

        n = self.n
        cd = model.cfg.compute_dtype
        nh, hd = model.block.attn.nhead, model.block.attn.head_dim
        stage_sh = NamedSharding(mesh, P(STAGE_AXIS))
        self._stage_sh = stage_sh

        kbs = kv_block_size if kv_block_size is not None \
            else gen.kv_block_size
        self.paged = kbs is not None
        if self.paged:
            # paged KV over the ring: every stage holds the pool rows for
            # ITS layers ([lps, num_blocks, bs, ...] per shard). The block
            # table is layer- and stage-agnostic — one table entry
            # addresses the same physical block id in each shard — so the
            # host-side KvPool needs no ring awareness at all.
            if kv_dtype is not None:
                raise NotImplementedError(
                    "int8 KV blocks are single-device only for now; the "
                    "ring pool stores the compute dtype")
            if kv_offload:
                raise NotImplementedError(
                    "kv_offload is single-device only for now: spilling "
                    "a block means a host read of every stage's shard "
                    "of it, which the ring's sharded pool layout does "
                    "not expose yet")
            if buckets is not None:
                gen.check_kv_headroom(buckets.max_len, kbs)
            if prefill_chunk < 1:
                raise ValueError(
                    f"prefill_chunk must be >= 1, got {prefill_chunk}")
            self.prefill_chunk = prefill_chunk
            mb = -(-max_len // kbs)
            nb = kv_pool_blocks if kv_pool_blocks is not None \
                else n * mb + 1
            self.pool = KvPool(
                num_blocks=nb, block_size=kbs, num_slots=n,
                max_len=max_len, prefix_cache=gen.prefix_cache,
                gather_slack_rows=prefill_chunk)
            self._caches = {
                name: jax.device_put(jnp.zeros(
                    (n * self._lps, nb, kbs, nh, hd), cd), stage_sh)
                for name in ("k", "v")}
            # positions >= the reserved region clamp into table entry 0 —
            # the paged replacement for the slab's sacrificial region
            self._sacpos = (self.pool.table_width - 1) * kbs
            self._fork_jit = jax.jit(self._fork_fn, donate_argnums=(0,))
        else:
            if kv_dtype is not None:
                raise ValueError(
                    "kv_dtype needs the paged pool (set kv_block_size); "
                    "the slab path stores KV in the compute dtype")
            if kv_offload:
                raise ValueError(
                    "kv_offload needs the paged pool (set kv_block_size); "
                    "the slab path has no block-level eviction to spill")
            self.pool = None
            # sacrificial region: big enough to absorb a q=max_bucket
            # prefill write from an inactive stage AND any
            # post-retirement decode overshoot within a tick
            max_bucket = buckets.max_len if buckets is not None \
                else max_len
            self._cache_len = max_len + max_bucket
            self._sac = max_len
            self._caches = {
                "k": jax.device_put(jnp.zeros(
                    (n * self._lps, n, 1, self._cache_len, nh, hd), cd),
                    stage_sh),
                "v": jax.device_put(jnp.zeros(
                    (n * self._lps, n, 1, self._cache_len, nh, hd), cd),
                    stage_sh)}
        self._h = jax.device_put(
            jnp.zeros((n, 1, model.cfg.d_model), cd), stage_sh)
        self._tok_ring = jax.device_put(jnp.zeros((n,), jnp.int32),
                                        stage_sh)
        self._pos_local = jax.device_put(jnp.zeros((n, n), jnp.int32),
                                         stage_sh)

        # host tables (replicated program inputs)
        self._c0 = 0
        self._admit = np.zeros(n, np.int32)
        self._live_default = np.zeros(n, np.int32)
        self._tok_inject = np.zeros(n, np.int32)
        self._plen = np.zeros(n, np.int32)
        kd0 = np.asarray(jax.random.key_data(jax.random.key(0)))
        self._key_data = np.broadcast_to(
            kd0, (n,) + kd0.shape).copy()
        self._programs = {}

    # -- validation --------------------------------------------------------

    def validate(self, prompt_len: int, max_new_tokens: int) -> None:
        bucket = (self.buckets.bucket_for(prompt_len)
                  if self.buckets is not None and not self.paged
                  else prompt_len)
        if self.paged and self.pool.demand_for(
                prompt_len, max_new_tokens) > self.pool.allocatable:
            raise ValueError(
                f"request needs "
                f"{self.pool.demand_for(prompt_len, max_new_tokens)} KV "
                f"blocks but the whole pool holds "
                f"{self.pool.allocatable}; raise kv_pool_blocks or "
                f"shorten the request")
        if prompt_len + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt_len {prompt_len} + max_new_tokens "
                f"{max_new_tokens} exceeds the slot cache ({self.max_len} "
                f"rows); raise max_len or shorten the request")
        if max_new_tokens > self.gen.max_new_tokens:
            raise ValueError(
                f"max_new_tokens {max_new_tokens} exceeds the engine cap "
                f"({self.gen.max_new_tokens})")
        mp = getattr(self.model, "max_position", None)
        limit = mp() if callable(mp) else None
        need = max(bucket, prompt_len + max_new_tokens
                   + self.decode_chunk - 1)
        if limit is not None and need > limit:
            raise ValueError(
                f"request needs position {need} but the positional "
                f"table has {limit}")

    # -- shared device pieces ---------------------------------------------

    def _ring(self, x):
        n = self.n
        return jax.lax.ppermute(x, STAGE_AXIS,
                                [(i, (i + 1) % n) for i in range(n)])

    def _local_blocks(self, stage_params):
        cd = self.model.cfg.compute_dtype

        def local_slice(a):
            if isinstance(a, QuantLeaf):
                return QuantLeaf(q=a.q[0], scale=a.scale[0])
            return a[0].astype(cd)

        blocks = [jax.tree_util.tree_map(
                      local_slice, bp,
                      is_leaf=lambda x: isinstance(x, QuantLeaf))
                  for bp in stage_params]
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)

    def _run_blocks(self, block_stack, h, caches, grp, pos):
        """This stage's layers on ``h`` against group ``grp``'s slab —
        the ``PipelinedGenerator._run_blocks`` recurrence."""
        m = self.model
        cd = m.cfg.compute_dtype
        lps = self._lps

        def slab_slice(a):
            s = jax.lax.dynamic_slice(
                a, (0, grp) + (0,) * (a.ndim - 2),
                (lps, 1) + a.shape[2:])
            return jnp.squeeze(s, axis=1)

        def slab_write(a, new):
            return jax.lax.dynamic_update_slice(
                a, new[:, None], (0, grp) + (0,) * (a.ndim - 2))

        slab = jax.tree_util.tree_map(slab_slice, caches)

        def layer_step(h_c, inp):
            bp, cache = inp
            h_new, cache = m.block.decode(dequant_tree(bp, cd), h_c,
                                          cache, pos)
            return h_new, cache

        h, new_slab = jax.lax.scan(layer_step, h, (block_stack, slab))
        caches = jax.tree_util.tree_map(slab_write, caches, new_slab)
        return h, caches

    def _run_blocks_paged(self, block_stack, h, caches, trow, pos):
        """The paged analog of :meth:`_run_blocks`: this stage's layers
        on ``h`` against the gathered block view of the slot whose table
        row is ``trow``. The ``q = h.shape[1]`` new rows at ``pos`` are
        scattered back through the table; positions past the reserved
        region (inactive stages, dead groups) clamp into the sacrificial
        block. The layer decode itself is unchanged — the slab/paged
        bitwise-parity argument from ``serve/kvpool.py`` applies per
        stage."""
        m = self.model
        cd = m.cfg.compute_dtype
        bs = self.pool.block_size
        q = h.shape[1]
        ridx = flat_row_index(
            trow, pos + jnp.arange(q, dtype=jnp.int32), bs)

        def layer_step(h_c, inp):
            bp, pool_l = inp
            cache = gather_block_cache(pool_l, trow, block_size=bs,
                                       compute_dtype=cd)
            h_new, c2 = m.block.decode(dequant_tree(bp, cd), h_c, cache,
                                       pos)
            rows = {name: jax.lax.dynamic_slice(
                        c2[name], (0, pos) + (0,) * (c2[name].ndim - 2),
                        (1, q) + c2[name].shape[2:])[0]
                    for name in ("k", "v")}
            return h_new, scatter_block_rows(pool_l, ridx, rows)

        h, new_caches = jax.lax.scan(layer_step, h, (block_stack, caches))
        return h, new_caches

    # -- device programs ---------------------------------------------------

    def _prefill_fn(self, stage_params, pre, post, caches, pos_local,
                    prompt, true_len, slot, key):
        """One serial ring pass of the padded prompt: cycle ``i`` stage
        ``i`` runs its layers (q = bucket len) on the h arriving from
        stage ``i-1``, writing cache rows [0, B) of group ``slot``'s
        slab; stage n-1 samples the first token on the last cycle. The
        in-flight decode carry (h ring, wrap token) is untouched — live
        groups never notice an admission."""
        m, gen, n = self.model, self.gen, self.n
        cd = m.cfg.compute_dtype
        s = jax.lax.axis_index(STAGE_AXIS)
        get_registry().counter("serve.ring.prefill_traces").inc()
        block_stack = self._local_blocks(stage_params)
        pos_row = pos_local[0]                          # [n_groups]

        def cycle(carry, i):
            h_carry, caches, tok0 = carry
            active = (s == i)
            pos_w = jnp.where(active, 0, self._sac)
            h_embed = m.embed_at(pre, prompt, 0)        # [1, B, d]
            h_in = jnp.where(s == 0, h_embed, h_carry)
            h_out, caches = self._run_blocks(block_stack, h_in, caches,
                                             slot, pos_w)
            h_last = jax.lax.dynamic_slice(
                h_out, (0, true_len - 1, 0), (1, 1, h_out.shape[-1]))
            logits = head_logits(m, post, h_last)[:, 0, :]
            tok = sample_logits(logits, jax.random.fold_in(key, 0),
                                gen)[0]
            emit = active & (s == n - 1)
            tok0 = jnp.where(emit, tok, tok0)
            return (self._ring(h_out), caches, tok0), None

        h0 = jnp.zeros((1, prompt.shape[1], m.cfg.d_model), cd)
        (_, caches, tok0), _ = jax.lax.scan(
            cycle, (h0, caches, jnp.int32(0)), jnp.arange(n))
        tok0 = jax.lax.psum(jnp.where(s == n - 1, tok0, 0), STAGE_AXIS)
        pos_row = jax.lax.dynamic_update_slice(
            pos_row, true_len[None], (slot,))
        return caches, pos_row[None], tok0

    def _decode_fn(self, stage_params, pre, post, caches, h_carry,
                   tok_ring, pos_local, c0, admit, live, tok_inject,
                   plen, key_data):
        """``revolutions`` ring revolutions with a persistent carry. Per
        cycle ``c = c0 + i``: stage ``s`` works group ``grp = (c - s)
        mod n``; the group is valid here iff it is live and its
        admission wavefront has reached this stage (``c >= admit[grp] +
        s``); stage 0 swaps in the prefill-sampled token exactly at
        ``c == admit[grp]``. Invalid work lands in the sacrificial cache
        region. Traced once — the counter pins it."""
        m, gen, n = self.model, self.gen, self.n
        cd = m.cfg.compute_dtype
        R = self.decode_chunk
        s = jax.lax.axis_index(STAGE_AXIS)
        get_registry().counter("serve.ring.decode_traces").inc()
        block_stack = self._local_blocks(stage_params)
        eos = gen.eos_token_id

        def cycle(carry, i):
            h_carry, tok_ring, caches, pos_row, emitted = carry
            c = c0 + i
            grp = jnp.mod(c - s, n)
            adm = jnp.take(admit, grp)
            valid = (jnp.take(live, grp) != 0) & (c >= adm + s)
            pos = jnp.take(pos_row, grp)
            pos_use = jnp.where(valid, pos, self._sac)
            inject = c == adm
            tok_use = jnp.where(inject, jnp.take(tok_inject, grp),
                                tok_ring[0])
            h_embed = m.embed_at(pre, tok_use[None, None], pos_use)
            h_in = jnp.where(s == 0, h_embed, h_carry)
            h_out, caches = self._run_blocks(block_stack, h_in, caches,
                                             grp, pos_use)
            logits = head_logits(m, post, h_out)[:, 0, :]   # [1, V]
            kd_g = jax.lax.dynamic_index_in_dim(key_data, grp, 0,
                                                keepdims=False)
            key_g = jax.random.wrap_key_data(kd_g)
            t_gen = pos - jnp.take(plen, grp) + 1
            tok_out = sample_logits(
                logits, jax.random.fold_in(key_g, t_gen), gen)
            emit = (s == n - 1) & valid
            r = i // n
            old = jax.lax.dynamic_slice(emitted, (grp, r), (1, 1))[0, 0]
            emitted = jax.lax.dynamic_update_slice(
                emitted, jnp.where(emit, tok_out[0], old)[None, None],
                (grp, r))
            pos_row = jax.lax.dynamic_update_slice(
                pos_row, jnp.where(valid, pos + 1, pos)[None], (grp,))
            return (self._ring(h_out), self._ring(tok_out), caches,
                    pos_row, emitted), None

        emitted0 = jnp.zeros((n, R), jnp.int32)
        (h_carry, tok_ring, caches, pos_row, emitted), _ = jax.lax.scan(
            cycle, (h_carry, tok_ring, caches, pos_local[0], emitted0),
            jnp.arange(n * R))
        emitted = jax.lax.psum(
            jnp.where(s == n - 1, emitted, 0), STAGE_AXIS)
        return caches, h_carry, tok_ring, pos_row[None], emitted

    # -- paged device programs ---------------------------------------------

    def _prefill_chunk_fn(self, stage_params, pre, post, caches, tokens,
                          t0, true_len, trow, key):
        """THE ring prefill program: one fixed-shape ``[1, C]`` chunk at
        a traced offset, walked around the ring once (cycle ``i`` stage
        ``i`` active, exactly :meth:`_prefill_fn`'s serial pass), looped
        on the host until the prompt is covered — ANY prompt length, one
        compile, where the slab path keys a program per bucket. Inactive
        stages write their C rows at the sacrificial position; stage
        ``n - 1`` samples the chunk's candidate first token (the host
        keeps the last chunk's — only there does ``true_len - 1`` fall
        inside the chunk). The in-flight decode carry is untouched."""
        m, gen, n = self.model, self.gen, self.n
        cd = m.cfg.compute_dtype
        s = jax.lax.axis_index(STAGE_AXIS)
        get_registry().counter("serve.ring.prefill_chunk_traces").inc()
        block_stack = self._local_blocks(stage_params)

        def cycle(carry, i):
            h_carry, caches, tok0 = carry
            active = (s == i)
            pos_w = jnp.where(active, t0, self._sacpos)
            h_embed = m.embed_at(pre, tokens, t0)        # [1, C, d]
            h_in = jnp.where(s == 0, h_embed, h_carry)
            h_out, caches = self._run_blocks_paged(
                block_stack, h_in, caches, trow, pos_w)
            idx = jnp.clip(true_len - 1 - t0, 0, tokens.shape[1] - 1)
            h_last = jax.lax.dynamic_slice(
                h_out, (0, idx, 0), (1, 1, h_out.shape[-1]))
            logits = head_logits(m, post, h_last)[:, 0, :]
            tok = sample_logits(logits, jax.random.fold_in(key, 0),
                                gen)[0]
            emit = active & (s == n - 1)
            tok0 = jnp.where(emit, tok, tok0)
            return (self._ring(h_out), caches, tok0), None

        h0 = jnp.zeros((1, tokens.shape[1], m.cfg.d_model), cd)
        (_, caches, tok0), _ = jax.lax.scan(
            cycle, (h0, caches, jnp.int32(0)), jnp.arange(n))
        tok0 = jax.lax.psum(jnp.where(s == n - 1, tok0, 0), STAGE_AXIS)
        return caches, tok0

    def _fork_fn(self, caches, src, dst):
        """Copy-on-write block copy across every stage's layer shard
        (src/dst traced — one program for every fork; the copy is
        block-axis local, so it never crosses the stage sharding)."""
        get_registry().counter("serve.kv.fork_traces").inc()
        return copy_block(caches, src, dst, block_axis=1)

    def _decode_paged_fn(self, stage_params, pre, post, caches, h_carry,
                         tok_ring, pos_local, c0, admit, live,
                         tok_inject, plen, key_data, tables):
        """:meth:`_decode_fn` with the slab slice/write swapped for the
        pool gather/scatter: stage ``s`` looks up group ``grp``'s table
        row and runs the SAME wavefront recurrence. Invalid (stage,
        cycle, group) work decodes at the sacrificial position, and
        released groups additionally carry all-zero table rows — a dead
        group can never touch a reallocated block. Traced once (the
        counter pins it)."""
        m, gen, n = self.model, self.gen, self.n
        R = self.decode_chunk
        s = jax.lax.axis_index(STAGE_AXIS)
        get_registry().counter("serve.ring.decode_traces").inc()
        block_stack = self._local_blocks(stage_params)

        def cycle(carry, i):
            h_carry, tok_ring, caches, pos_row, emitted = carry
            c = c0 + i
            grp = jnp.mod(c - s, n)
            adm = jnp.take(admit, grp)
            valid = (jnp.take(live, grp) != 0) & (c >= adm + s)
            pos = jnp.take(pos_row, grp)
            pos_use = jnp.where(valid, pos, self._sacpos)
            inject = c == adm
            tok_use = jnp.where(inject, jnp.take(tok_inject, grp),
                                tok_ring[0])
            h_embed = m.embed_at(pre, tok_use[None, None], pos_use)
            h_in = jnp.where(s == 0, h_embed, h_carry)
            trow = jax.lax.dynamic_index_in_dim(tables, grp, 0,
                                                keepdims=False)
            h_out, caches = self._run_blocks_paged(
                block_stack, h_in, caches, trow, pos_use)
            logits = head_logits(m, post, h_out)[:, 0, :]   # [1, V]
            kd_g = jax.lax.dynamic_index_in_dim(key_data, grp, 0,
                                                keepdims=False)
            key_g = jax.random.wrap_key_data(kd_g)
            t_gen = pos - jnp.take(plen, grp) + 1
            tok_out = sample_logits(
                logits, jax.random.fold_in(key_g, t_gen), gen)
            emit = (s == n - 1) & valid
            r = i // n
            old = jax.lax.dynamic_slice(emitted, (grp, r), (1, 1))[0, 0]
            emitted = jax.lax.dynamic_update_slice(
                emitted, jnp.where(emit, tok_out[0], old)[None, None],
                (grp, r))
            pos_row = jax.lax.dynamic_update_slice(
                pos_row, jnp.where(valid, pos + 1, pos)[None], (grp,))
            return (self._ring(h_out), self._ring(tok_out), caches,
                    pos_row, emitted), None

        emitted0 = jnp.zeros((n, R), jnp.int32)
        (h_carry, tok_ring, caches, pos_row, emitted), _ = jax.lax.scan(
            cycle, (h_carry, tok_ring, caches, pos_local[0], emitted0),
            jnp.arange(n * R))
        emitted = jax.lax.psum(
            jnp.where(s == n - 1, emitted, 0), STAGE_AXIS)
        return caches, h_carry, tok_ring, pos_row[None], emitted

    # -- resident device program -------------------------------------------

    def _resident_impl(self, paged, stage_params, pre, post, caches,
                       h_carry, tok_ring, pos_local, c0, admit, live,
                       tok_inject, plen, key_data, budget, r_max,
                       tables=None):
        """The resident ring loop: a ``lax.while_loop`` whose body is
        ONE revolution of the exact wavefront recurrence above — the
        body stays switch-free (masked arithmetic + ppermute/psum, the
        ``compile_phases`` discipline; the 0-dispatch pin is
        ``tools/hlo_audit.py --resident``). Each revolution's emissions
        are psum'd so every stage can advance the replicated per-group
        ``done``/``budget`` carry; ``done`` joins the validity mask, so
        finished groups freeze (their writes route to the sacrificial
        region) instead of overshooting. Exits early when any live
        group goes done — a slot freed, host admission can matter — or
        after ``r_max`` revolutions (the deadline horizon). One host
        sync per launch: the revolution count."""
        m, gen, n = self.model, self.gen, self.n
        R = self.resident_revolutions
        s = jax.lax.axis_index(STAGE_AXIS)
        get_registry().counter("serve.ring.resident_traces").inc()
        block_stack = self._local_blocks(stage_params)
        eos = gen.eos_token_id
        sac = self._sacpos if paged else self._sac

        def body(state):
            h_carry, tok_ring, caches, pos_row, emitted, done, budget, \
                r = state

            def cycle(carry, j):
                h_carry, tok_ring, caches, pos_row, rev_tok, \
                    rev_emit = carry
                c = c0 + r * n + j
                grp = jnp.mod(c - s, n)
                adm = jnp.take(admit, grp)
                valid = (jnp.take(live, grp) != 0) \
                    & ~jnp.take(done, grp) & (c >= adm + s)
                pos = jnp.take(pos_row, grp)
                pos_use = jnp.where(valid, pos, sac)
                inject = c == adm
                tok_use = jnp.where(inject, jnp.take(tok_inject, grp),
                                    tok_ring[0])
                h_embed = m.embed_at(pre, tok_use[None, None], pos_use)
                h_in = jnp.where(s == 0, h_embed, h_carry)
                if paged:
                    trow = jax.lax.dynamic_index_in_dim(
                        tables, grp, 0, keepdims=False)
                    h_out, caches = self._run_blocks_paged(
                        block_stack, h_in, caches, trow, pos_use)
                else:
                    h_out, caches = self._run_blocks(
                        block_stack, h_in, caches, grp, pos_use)
                logits = head_logits(m, post, h_out)[:, 0, :]
                kd_g = jax.lax.dynamic_index_in_dim(key_data, grp, 0,
                                                    keepdims=False)
                key_g = jax.random.wrap_key_data(kd_g)
                t_gen = pos - jnp.take(plen, grp) + 1
                tok_out = sample_logits(
                    logits, jax.random.fold_in(key_g, t_gen), gen)
                emit = (s == n - 1) & valid
                old_t = jax.lax.dynamic_slice(rev_tok, (grp,), (1,))[0]
                rev_tok = jax.lax.dynamic_update_slice(
                    rev_tok, jnp.where(emit, tok_out[0], old_t)[None],
                    (grp,))
                old_e = jax.lax.dynamic_slice(rev_emit, (grp,), (1,))[0]
                rev_emit = jax.lax.dynamic_update_slice(
                    rev_emit, jnp.where(emit, jnp.int32(1), old_e)[None],
                    (grp,))
                pos_row = jax.lax.dynamic_update_slice(
                    pos_row, jnp.where(valid, pos + 1, pos)[None], (grp,))
                return (self._ring(h_out), self._ring(tok_out), caches,
                        pos_row, rev_tok, rev_emit), None

            z = jnp.zeros((n,), jnp.int32)
            (h_carry, tok_ring, caches, pos_row, rev_tok, rev_emit), _ = \
                jax.lax.scan(
                    cycle, (h_carry, tok_ring, caches, pos_row, z, z),
                    jnp.arange(n))
            rev_tok = jax.lax.psum(
                jnp.where(s == n - 1, rev_tok, 0), STAGE_AXIS)
            rev_emit = jax.lax.psum(
                jnp.where(s == n - 1, rev_emit, 0), STAGE_AXIS)
            emitted = jax.lax.dynamic_update_slice(
                emitted, rev_tok[:, None], (0, r))
            budget = budget - rev_emit
            done = done | (budget <= 0)
            if eos is not None:
                done = done | ((rev_tok == jnp.int32(eos))
                               & (rev_emit > 0))
            return (h_carry, tok_ring, caches, pos_row, emitted, done,
                    budget, r + 1)

        def cond(state):
            return (state[7] < r_max) & \
                ~jnp.any((live != 0) & state[5])

        emitted0 = jnp.zeros((n, R), jnp.int32)
        done0 = (live == 0) | (budget <= 0)
        state = (h_carry, tok_ring, caches, pos_local[0], emitted0,
                 done0, budget, jnp.int32(0))
        h_carry, tok_ring, caches, pos_row, emitted, done, budget, r = \
            jax.lax.while_loop(cond, body, state)
        return caches, h_carry, tok_ring, pos_row[None], emitted, r

    def _resident_decode_fn(self, stage_params, pre, post, caches,
                            h_carry, tok_ring, pos_local, c0, admit,
                            live, tok_inject, plen, key_data, budget,
                            r_max):
        return self._resident_impl(
            False, stage_params, pre, post, caches, h_carry, tok_ring,
            pos_local, c0, admit, live, tok_inject, plen, key_data,
            budget, r_max)

    def _resident_decode_paged_fn(self, stage_params, pre, post, caches,
                                  h_carry, tok_ring, pos_local, c0,
                                  admit, live, tok_inject, plen,
                                  key_data, tables, budget, r_max):
        return self._resident_impl(
            True, stage_params, pre, post, caches, h_carry, tok_ring,
            pos_local, c0, admit, live, tok_inject, plen, key_data,
            budget, r_max, tables=tables)

    # -- backend API -------------------------------------------------------

    def _build(self, kind, B=None):
        pspec = jax.tree_util.tree_map(lambda _: P(STAGE_AXIS),
                                       self._stage_params)
        pre_spec = jax.tree_util.tree_map(lambda _: P(), self._pre)
        post_spec = jax.tree_util.tree_map(lambda _: P(), self._post)
        cache_spec = jax.tree_util.tree_map(lambda _: P(STAGE_AXIS),
                                            self._caches)
        if kind == "prefill":
            in_specs = (pspec, pre_spec, post_spec, cache_spec,
                        P(STAGE_AXIS), P(), P(), P(), P())
            out_specs = (cache_spec, P(STAGE_AXIS), P())
            fn = self._prefill_fn
        elif kind == "chunk":
            in_specs = (pspec, pre_spec, post_spec, cache_spec,
                        P(), P(), P(), P(), P())
            out_specs = (cache_spec, P())
            fn = self._prefill_chunk_fn
        elif kind == "decode_paged":
            in_specs = (pspec, pre_spec, post_spec, cache_spec,
                        P(STAGE_AXIS), P(STAGE_AXIS), P(STAGE_AXIS),
                        P(), P(), P(), P(), P(), P(), P())
            out_specs = (cache_spec, P(STAGE_AXIS), P(STAGE_AXIS),
                         P(STAGE_AXIS), P())
            fn = self._decode_paged_fn
        elif kind == "resident":
            in_specs = (pspec, pre_spec, post_spec, cache_spec,
                        P(STAGE_AXIS), P(STAGE_AXIS), P(STAGE_AXIS),
                        P(), P(), P(), P(), P(), P(), P(), P())
            out_specs = (cache_spec, P(STAGE_AXIS), P(STAGE_AXIS),
                         P(STAGE_AXIS), P(), P())
            fn = self._resident_decode_fn
        elif kind == "resident_paged":
            in_specs = (pspec, pre_spec, post_spec, cache_spec,
                        P(STAGE_AXIS), P(STAGE_AXIS), P(STAGE_AXIS),
                        P(), P(), P(), P(), P(), P(), P(), P(), P())
            out_specs = (cache_spec, P(STAGE_AXIS), P(STAGE_AXIS),
                         P(STAGE_AXIS), P(), P())
            fn = self._resident_decode_paged_fn
        else:
            in_specs = (pspec, pre_spec, post_spec, cache_spec,
                        P(STAGE_AXIS), P(STAGE_AXIS), P(STAGE_AXIS),
                        P(), P(), P(), P(), P(), P())
            out_specs = (cache_spec, P(STAGE_AXIS), P(STAGE_AXIS),
                         P(STAGE_AXIS), P())
            fn = self._decode_fn
        return jax.jit(shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))

    def prefill(self, slot: int, prompt: Sequence[int], seed: int,
                max_new_tokens: Optional[int] = None) -> int:
        reg = get_registry()
        if self.paged:
            return self._prefill_paged(
                slot, prompt, seed,
                max_new_tokens if max_new_tokens is not None
                else self.gen.max_new_tokens)
        if self.buckets is not None:
            padded, p = self.buckets.pad(prompt, self.gen.pad_token_id)
        else:
            padded, p = list(prompt), len(prompt)
        B = len(padded)
        run = self._programs.get(("prefill", B))
        if run is None:
            reg.counter("serve.engine.prefill_program_misses").inc()
            run = self._build("prefill", B)
            self._programs[("prefill", B)] = run
            n_pre = sum(1 for k in self._programs if k[0] == "prefill")
            reg.gauge("serve.engine.prefill_programs").set(n_pre)
            if self.buckets is None and n_pre == self.shape_cache_warn + 1:
                import warnings
                warnings.warn(
                    f"ring serve backend compiled {n_pre} distinct "
                    f"prefill programs with bucketing DISABLED — every "
                    f"new prompt length recompiles. Pass a BucketSpec "
                    f"to cap the program cache.",
                    RuntimeWarning, stacklevel=3)
        else:
            reg.counter("serve.engine.prefill_program_hits").inc()
        arr = jnp.asarray(padded, jnp.int32)[None, :]
        key = jax.random.key(seed)
        caches, pos_local, tok0 = run(
            self._stage_params, self._pre, self._post, self._caches,
            self._pos_local, arr, jnp.int32(p), jnp.int32(slot), key)
        self._caches = caches
        self._pos_local = pos_local
        tok0 = int(tok0)
        self._admit[slot] = self._c0 + slot
        self._tok_inject[slot] = tok0
        self._plen[slot] = p
        self._key_data[slot] = np.asarray(
            jax.random.key_data(jax.random.key(seed)))
        return tok0

    def _prefill_paged(self, slot: int, prompt: Sequence[int], seed: int,
                       max_new_tokens: int) -> int:
        """Admit into the pool (reserving full demand), run the COW
        forks, stream the prompt's recompute tail through the one chunk
        program (one serial ring pass per chunk), then arm the host
        admission tables exactly as the slab prefill does. A failure
        mid-stream releases the reservation and unpublishes half-written
        cache entries."""
        plen = len(prompt)
        adm = self.pool.admit(slot, prompt, max_new_tokens,
                              chunk=self.prefill_chunk)
        try:
            for src, dst in adm.cow_forks:
                self._caches = self._fork_jit(
                    self._caches, jnp.int32(src), jnp.int32(dst))
            run = self._programs.get("chunk")
            if run is None:
                run = self._build("chunk")
                self._programs["chunk"] = run
            trow = jnp.asarray(adm.table)
            C = self.prefill_chunk
            pad = self.gen.pad_token_id
            key = jax.random.key(seed)
            t = adm.resume_from
            tok0 = 0
            while t < plen:
                toks = list(prompt[t:t + C])
                toks += [pad] * (C - len(toks))
                arr = jnp.asarray(toks, jnp.int32)[None, :]
                self._caches, tok0 = run(
                    self._stage_params, self._pre, self._post,
                    self._caches, arr, jnp.int32(t), jnp.int32(plen),
                    trow, key)
                t += C
            tok0 = int(tok0)
        except Exception:
            self.pool.release(slot, failed=True)
            raise
        self._admit[slot] = self._c0 + slot
        self._tok_inject[slot] = tok0
        self._plen[slot] = plen
        self._key_data[slot] = np.asarray(
            jax.random.key_data(jax.random.key(seed)))
        pl = np.array(self._pos_local)
        pl[:, slot] = plen
        self._pos_local = jax.device_put(jnp.asarray(pl), self._stage_sh)
        return tok0

    def decode(self, live: np.ndarray,
               budgets: Optional[np.ndarray] = None,
               r_max: Optional[int] = None):
        """One tick = ``revolutions`` tokens per live slot. Returns
        ``(tokens [S, R], valid [S, R])``; validity accounts for
        admission wavefronts still filling the ring.

        With ``budgets`` on a resident backend the call runs the
        RESIDENT loop: up to ``r_max`` revolutions in one device
        program with on-device done-masking and early exit. Without
        ``budgets`` the single-launch path runs even when
        ``resident=True`` — the parity reference."""
        if self.resident and budgets is not None:
            return self._decode_resident(live, budgets, r_max)
        n, R = self.n, self.decode_chunk
        live = np.asarray(live).astype(np.int32)
        kind = "decode_paged" if self.paged else "decode"
        run = self._programs.get(kind)
        if run is None:
            run = self._build(kind)
            self._programs[kind] = run
        args = (
            self._stage_params, self._pre, self._post, self._caches,
            self._h, self._tok_ring, self._pos_local,
            jnp.int32(self._c0), jnp.asarray(self._admit),
            jnp.asarray(live), jnp.asarray(self._tok_inject),
            jnp.asarray(self._plen), jnp.asarray(self._key_data))
        if self.paged:
            args = args + (jnp.asarray(self.pool.table),)
        caches, h, tok_ring, pos_local, emitted = run(*args)
        self._caches, self._h = caches, h
        self._tok_ring, self._pos_local = tok_ring, pos_local
        toks = np.asarray(emitted)                       # [n, R]
        g = np.arange(n)[:, None]
        r = np.arange(R)[None, :]
        emit_cycle = self._c0 + r * n + (g + n - 1) % n
        valid = (live[:, None] != 0) & \
            (emit_cycle >= self._admit[:, None] + n - 1)
        self._c0 += n * R
        if self._c0 > _REBASE:
            shift = self._c0
            self._c0 = 0
            self._admit = np.maximum(
                self._admit - shift, -np.int32(_REBASE)).astype(np.int32)
        return toks, valid

    def _decode_resident(self, live: np.ndarray, budgets: np.ndarray,
                         r_max: Optional[int]):
        """One resident launch: up to ``r_max`` revolutions on device,
        ONE host sync (the revolution count) to size the readout."""
        reg = get_registry()
        n, R = self.n, self.resident_revolutions
        rm = R if r_max is None else max(1, min(int(r_max), R))
        live = np.asarray(live).astype(np.int32)
        kind = "resident_paged" if self.paged else "resident"
        run = self._programs.get(kind)
        if run is None:
            run = self._build(kind)
            self._programs[kind] = run
        args = (
            self._stage_params, self._pre, self._post, self._caches,
            self._h, self._tok_ring, self._pos_local,
            jnp.int32(self._c0), jnp.asarray(self._admit),
            jnp.asarray(live), jnp.asarray(self._tok_inject),
            jnp.asarray(self._plen), jnp.asarray(self._key_data))
        if self.paged:
            args = args + (jnp.asarray(self.pool.table),)
        args = args + (jnp.asarray(np.asarray(budgets, np.int32)),
                       jnp.int32(rm))
        caches, h, tok_ring, pos_local, emitted, r_ran = run(*args)
        self._caches, self._h = caches, h
        self._tok_ring, self._pos_local = tok_ring, pos_local
        r_ran = int(r_ran)                   # THE host sync
        if r_ran < rm:
            reg.counter("serve.engine.device_exits").inc()
        toks = np.asarray(emitted)[:, :r_ran]
        g = np.arange(n)[:, None]
        r = np.arange(r_ran)[None, :]
        emit_cycle = self._c0 + r * n + (g + n - 1) % n
        valid = (live[:, None] != 0) & \
            (emit_cycle >= self._admit[:, None] + n - 1)
        self._c0 += n * r_ran
        if self._c0 > _REBASE:
            shift = self._c0
            self._c0 = 0
            self._admit = np.maximum(
                self._admit - shift, -np.int32(_REBASE)).astype(np.int32)
        return toks, valid

    def can_admit(self, prompt_len: int, max_new_tokens: int,
                  prompt: Optional[Sequence[int]] = None) -> bool:
        """Block-availability admission gate (always True for the slab —
        its reservation is the slot itself)."""
        if not self.paged:
            return True
        return self.pool.can_admit(prompt_len, max_new_tokens, prompt,
                                   chunk=self.prefill_chunk)

    def release(self, slot: int) -> None:
        """Engine retirement hook: return the group's blocks to the pool
        (no-op for the slab — the next prefill rewrites the rows)."""
        if self.paged:
            self.pool.release(slot)

    def program_stats(self) -> dict:
        if self.paged:
            return {"prefill_programs": 1,
                    "decode_chunk": self.decode_chunk, "kv": "paged"}
        return {"prefill_programs": sum(
                    1 for k in self._programs
                    if isinstance(k, tuple) and k[0] == "prefill"),
                "decode_chunk": self.decode_chunk, "kv": "slab"}
