"""Bounded admission queue: the serve engine's request front door.

Design choices, in order of importance:

* **Backpressure over buffering.** ``submit`` raises :class:`QueueFull`
  at capacity instead of growing without bound — under overload the
  caller (a load balancer, a client with retry budget) learns *now*,
  while the requests already admitted keep their latency. The bench
  artifact quantifies this: goodput under 2x overload with the bound on
  vs off (``SERVE_r08.json``).
* **Deadlines are absolute and enforced at both ends.** A request can
  expire while queued (reaped before ever touching the model) or while
  running (the engine retires its slot mid-generation and returns the
  partial tokens with ``status="timeout"``).
* **Cancellation is a flag, not a removal.** ``cancel`` marks the entry;
  the queue/engine collapse it at the next tick. O(1), race-free with
  the engine's single-threaded tick loop.
* **FIFO or priority.** ``policy="priority"`` pops the highest
  ``priority`` first (ties FIFO by arrival sequence). FIFO is the
  default — predictable TTFT under load.

The queue is host-side bookkeeping only; nothing here touches jax. The
clock is injectable (``clock=``) so deadline/cancellation tests run
deterministically without sleeping.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
import uuid
from typing import Callable, List, Optional, Sequence, Tuple

__all__ = ["QueueFull", "Request", "Response", "RequestQueue"]


class QueueFull(RuntimeError):
    """Raised by ``submit`` when the admission queue is at capacity —
    the backpressure signal. Retry later or shed the request. Carries
    ``depth``/``capacity``/``oldest_age_s`` so callers can tune their
    backoff (a deep queue whose head is old means the service is
    wedged, not merely busy)."""

    def __init__(self, message: str, *, depth: int = 0, capacity: int = 0,
                 oldest_age_s: Optional[float] = None):
        super().__init__(message)
        self.depth = depth
        self.capacity = capacity
        self.oldest_age_s = oldest_age_s


@dataclasses.dataclass
class Request:
    """One generation request. ``prompt`` is a list of int token ids;
    ``max_new_tokens`` caps this request below the engine-wide limit;
    ``seed`` drives the per-request sampling key chain; ``deadline`` is
    absolute in the queue's clock domain (set from ``timeout_s`` at
    submit). ``attempts`` counts placements onto an engine replica —
    the router's retry budget; a request served directly by one engine
    keeps it at 0. ``submitted_at`` and ``deadline`` are set exactly
    once, at the original submit: a failed-over request keeps them
    through every re-queue, so it never regains deadline credit.
    ``trace_id`` is the distributed-tracing correlation key, minted
    exactly once at the original :meth:`RequestQueue.submit` and carried
    verbatim through placement, retry park, KV handoff and failover —
    including across the process-replica wire — so every span a request
    touches, in any process, lands in one stitched timeline."""

    id: int
    prompt: List[int]
    max_new_tokens: int
    seed: int = 0
    priority: int = 0
    deadline: Optional[float] = None
    submitted_at: float = 0.0
    cancelled: bool = False
    attempts: int = 0
    trace_id: Optional[str] = None
    # Disaggregated serving (fleet/disagg.py): which phase this request
    # currently wants — "prefill" (clamped to one token, routed to the
    # prefill pool), "decode" (full generation resuming from shipped KV,
    # routed to the decode pool), or None (whole request on a mixed
    # replica — every pre-disaggregation deployment).
    phase: Optional[str] = None


@dataclasses.dataclass
class Response:
    """Terminal record for one request. ``status``: ``ok`` | ``timeout``
    | ``cancelled`` | ``error`` (backend failure or stuck slot) |
    ``shed`` (pushed back unserved — degraded mode or drain).
    ``finish_reason``: ``eos`` | ``length`` | ``deadline`` |
    ``cancelled`` | ``backend_error`` | ``stuck`` | ``shed`` | ``drain``
    | ``retries_exhausted`` (router: retry budget spent on retryable
    backend failures) | ``no_replicas`` (router: no replica can ever
    serve again). ``tokens`` holds whatever was generated
    before the request finished (possibly empty when it never reached a
    slot). ``ttft`` is first-token latency (None when no token was
    produced); ``latency`` is submit-to-retire."""

    request_id: int
    tokens: List[int]
    status: str
    finish_reason: str
    prompt_len: int
    ttft: Optional[float]
    latency: float


class RequestQueue:
    """Bounded FIFO/priority queue with deadlines and cancellation."""

    def __init__(self, capacity: int = 64, *, policy: str = "fifo",
                 clock: Callable[[], float] = time.monotonic):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if policy not in ("fifo", "priority"):
            raise ValueError(f"policy must be fifo|priority, got {policy!r}")
        self.capacity = capacity
        self.policy = policy
        self.clock = clock
        self._seq = itertools.count()
        self._waiting: List[Request] = []
        self._by_id = {}

    def __len__(self) -> int:
        return len(self._waiting)

    @property
    def depth(self) -> int:
        return len(self._waiting)

    def submit(self, prompt: Sequence[int], *, max_new_tokens: int,
               seed: int = 0, priority: int = 0,
               timeout_s: Optional[float] = None) -> Request:
        """Enqueue or raise :class:`QueueFull`. Returns the live
        :class:`Request` (its ``id`` is the handle for ``cancel``)."""
        if len(self._waiting) >= self.capacity:
            age = self.oldest_age()
            raise QueueFull(
                f"admission queue at capacity (depth "
                f"{len(self._waiting)}/{self.capacity}; oldest queued "
                f"request has waited "
                f"{'n/a' if age is None else f'{age:.3f}s'}); retry "
                f"with backoff or raise capacity",
                depth=len(self._waiting), capacity=self.capacity,
                oldest_age_s=age)
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        now = self.clock()
        req = Request(id=next(self._seq), prompt=prompt,
                      max_new_tokens=int(max_new_tokens), seed=int(seed),
                      priority=int(priority),
                      deadline=None if timeout_s is None else now + timeout_s,
                      submitted_at=now, trace_id=uuid.uuid4().hex[:16])
        self._waiting.append(req)
        self._by_id[req.id] = req
        return req

    def requeue(self, req: Request) -> Request:
        """Re-enqueue an EXISTING request (router placement/failover),
        preserving its identity: id, ``submitted_at`` and ``deadline``
        are untouched, so a failed-over request keeps its original
        arrival and never regains deadline credit. Raises
        :class:`QueueFull` at capacity, exactly like ``submit``."""
        if len(self._waiting) >= self.capacity:
            age = self.oldest_age()
            raise QueueFull(
                f"admission queue at capacity (depth "
                f"{len(self._waiting)}/{self.capacity}) re-queueing "
                f"request {req.id}",
                depth=len(self._waiting), capacity=self.capacity,
                oldest_age_s=age)
        self._waiting.append(req)
        self._by_id[req.id] = req
        return req

    def evict_all(self) -> List[Request]:
        """Remove and return every queued request INTACT — no terminal
        record, no status change. The router uses this to reclaim a
        wedged replica's backlog for re-placement; contrast
        ``shed_lowest``/``reap``, which end the requests they remove."""
        evicted, self._waiting = self._waiting, []
        for req in evicted:
            self._by_id.pop(req.id, None)
        return evicted

    def cancel(self, request_id: int) -> bool:
        """Mark a queued or running request cancelled. Returns False for
        unknown/already-retired ids."""
        req = self._by_id.get(request_id)
        if req is None:
            return False
        req.cancelled = True
        return True

    def forget(self, request_id: int) -> None:
        """Engine hook: the request reached a terminal state."""
        self._by_id.pop(request_id, None)

    def reap(self, now: Optional[float] = None) -> List[Tuple[Request, str]]:
        """Remove and return queued entries that died while waiting:
        ``(request, "deadline"|"cancelled")`` pairs."""
        if now is None:
            now = self.clock()
        dead, alive = [], []
        for req in self._waiting:
            if req.cancelled:
                dead.append((req, "cancelled"))
            elif req.deadline is not None and now >= req.deadline:
                dead.append((req, "deadline"))
            else:
                alive.append(req)
        self._waiting = alive
        return dead

    def oldest_age(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds the longest-waiting queued request has waited (None
        when empty)."""
        if not self._waiting:
            return None
        if now is None:
            now = self.clock()
        return now - min(r.submitted_at for r in self._waiting)

    def earliest_deadline(self) -> Optional[float]:
        """Soonest deadline among queued (uncancelled) requests, or None
        when nothing queued carries one. The resident serve loop clamps
        its on-device horizon to this: the device may run chunks
        back-to-back only up to the moment host attention (a reap, an
        admission) could actually change the slot set."""
        dls = [r.deadline for r in self._waiting
               if r.deadline is not None and not r.cancelled]
        return min(dls) if dls else None

    def shed_lowest(self, n: int) -> List[Request]:
        """Degraded-mode load shedding: remove and return up to ``n``
        queued requests, lowest ``priority`` first (ties: youngest
        arrival first — the oldest of a priority level has waited
        longest and keeps its place; exact-arrival ties fall to the
        highest ``id``). The key is ``(priority, arrival, id)`` — pure
        request identity, never list position — so the shed set is
        deterministic even after router re-queues reorder the backing
        list. Used by the engine when the deadline-miss EWMA crosses
        its threshold and during drain."""
        if n < 1 or not self._waiting:
            return []
        order = sorted(range(len(self._waiting)),
                       key=lambda i: (self._waiting[i].priority,
                                      -self._waiting[i].submitted_at,
                                      -self._waiting[i].id))
        drop = set(order[:n])
        shed = [self._waiting[i] for i in sorted(drop)]
        self._waiting = [r for i, r in enumerate(self._waiting)
                         if i not in drop]
        return shed

    def peek(self) -> Optional[Request]:
        """The request ``pop`` would return, without removing it — the
        engine's block-availability admission gate looks before it
        leaps (head-of-line parking keeps FIFO/priority order honest;
        popping then re-queueing would rotate the request to the
        tail)."""
        if not self._waiting:
            return None
        if self.policy == "fifo":
            return self._waiting[0]
        best = max(range(len(self._waiting)),
                   key=lambda i: (self._waiting[i].priority, -i))
        return self._waiting[best]

    def pop(self) -> Optional[Request]:
        """Next request to admit (None when empty). Priority policy pops
        the highest ``priority``, FIFO within a priority level. Call
        ``reap`` first; ``pop`` assumes the head entries are live."""
        if not self._waiting:
            return None
        if self.policy == "fifo":
            return self._waiting.pop(0)
        best = max(range(len(self._waiting)),
                   key=lambda i: (self._waiting[i].priority, -i))
        return self._waiting.pop(best)

    def admission_order(self) -> List[Request]:
        """Every queued request in the exact order repeated ``pop``
        calls would return them, WITHOUT removing anything — the
        engine's head-of-line-skip admission scan: when the head can't
        seat (block demand too big for the pool right now), the next
        admissible request in this order may go first."""
        if self.policy == "fifo":
            return list(self._waiting)
        order = sorted(range(len(self._waiting)),
                       key=lambda i: (-self._waiting[i].priority, i))
        return [self._waiting[i] for i in order]

    def take(self, request_id: int) -> Optional[Request]:
        """Remove and return a SPECIFIC queued request by id (None when
        it isn't queued) — the companion to :meth:`admission_order`:
        after the scan picks a non-head request, ``take`` pulls exactly
        that one, leaving the blocked head parked in place."""
        for i, req in enumerate(self._waiting):
            if req.id == request_id:
                return self._waiting.pop(i)
        return None
