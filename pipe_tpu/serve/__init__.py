"""Continuous-batching serving over the pipe_tpu generators.

The subsystem in one paragraph: :class:`~.queue.RequestQueue` is the
bounded front door (backpressure, deadlines, cancellation, FIFO or
priority); :class:`~.engine.ServeEngine` schedules requests into fixed
decode **slots** and runs one compiled, fixed-shape decode step per host
tick — zero steady-state recompiles, pinned by a trace counter;
:class:`~.buckets.BucketSpec` caps prefill to a closed set of
prompt-length shapes. Two slot backends:
:class:`~.engine.SingleDeviceSlotBackend` (replicated weights, S
arbitrary) and :class:`~.ring.RingSlotBackend` (stage-sharded weights —
slots are the pipeline ring's request groups, kept continuously full
across admissions/retirements). Both back their KV memory with either a
per-slot monolithic slab or a :class:`~.kvpool.KvPool` of fixed-size
blocks (``kv_block_size=``) — paged mode adds shared-prefix reuse with
copy-on-write and ONE chunked prefill program for every prompt length.
At fleet scale, :class:`~.router.Router`
shards one front queue across N engine replicas with health-gated
failover, retry budgets, and exactly-once response delivery. See
``docs/serving.md`` ("Online serving" / "Fleet serving") and
``apps/serve.py`` for the driver.
"""

from .buckets import BucketSpec
from .engine import EngineDraining, ServeEngine, SingleDeviceSlotBackend
from .kvpool import Admission, KvPool, PoolExhausted, block_demand
from .queue import QueueFull, Request, RequestQueue, Response
from .ring import RingSlotBackend
from .router import (DRAINING, HEALTHY, RETIRED, SUSPECT, WEDGED, Replica,
                     Router, RouterPolicy)

__all__ = ["BucketSpec", "ServeEngine", "SingleDeviceSlotBackend",
           "RingSlotBackend", "QueueFull", "Request", "RequestQueue",
           "Response", "EngineDraining", "Router", "RouterPolicy",
           "Replica", "HEALTHY", "SUSPECT", "WEDGED", "DRAINING",
           "RETIRED", "KvPool", "PoolExhausted", "Admission",
           "block_demand"]
