"""Slot-based continuous batching: a dynamic request stream through ONE
compiled decode step.

The paper's schedule-as-static-table discipline, applied to serving: the
device program is fixed-shape and compiled once; everything dynamic —
arrivals, retirements, deadlines — is host-side table maintenance, like
the executors' masked-slot op tables. The engine owns ``S`` decode
slots, each a row block of every layer's KV cache plus a (token,
position, PRNG key) triple. A host **tick** is:

1. reap requests that died waiting (deadline/cancel) and retire running
   slots whose deadline passed or that were cancelled;
2. admit waiting requests into free slots — one bucketed prefill program
   per prompt-length bucket (:class:`~.buckets.BucketSpec`) writes the
   slot's cache rows and samples the first token (TTFT is measured
   here);
3. run the **one** decode step for all S slots — finished/empty slots
   decode garbage into rows the next prefill overwrites, the same
   sacrificial-write trick as the pipelined generators — and retire
   slots on EOS / per-request ``max_new_tokens``.

Zero steady-state recompiles is a pinned invariant, not an aspiration:
the decode program body increments ``serve.engine.decode_traces`` at
trace time (traces happen once per compile), and ``tests/test_serve.py``
asserts the counter stays at 1 across staggered mixed-length traffic.

Token parity is the other pin: because each slot carries the exact
(prefill -> split -> sample -> split -> sample...) key chain of a
batch-1 :class:`~..inference.generate.Generator` call, and right-padded
bucket rows are causally masked until decode overwrites them, a request
served through the engine produces bitwise the tokens of a one-shot
``Generator.generate`` on its prompt — regardless of what the other
slots are doing.

``decode_chunk > 1`` runs K decode steps per tick inside a ``lax.scan``
(one host round-trip per K tokens — the host-sync amortization knob);
the carry chain is identical however it is chopped, so parity holds.
The cost is retirement lag: a slot finishing mid-chunk wastes at most
K-1 slot-steps before the host sees it.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..inference.draft import DraftSource, resolve_draft, tree_layout
from ..inference.generate import (GenerationConfig, head_logits,
                                  sample_logits)
from ..inference.quant import QuantLeaf, dequant_tree
from ..obs.events import NULL_EVENT_LOG, REQUEST
from ..obs.telemetry import get_registry, host_overhead_per_token
from .buckets import BucketSpec
from .kvpool import (HostKvStore, KvPool, PoolExhausted, block_demand,
                     copy_block, flat_row_index, gather_block_cache,
                     scatter_block_rows, storage_for)
from .queue import QueueFull, Request, RequestQueue, Response

__all__ = ["SingleDeviceSlotBackend", "ServeEngine", "EngineDraining"]


class EngineDraining(RuntimeError):
    """Raised by ``submit`` after :meth:`ServeEngine.drain`: the engine
    is finishing its live slots and admits nothing new (the graceful-
    shutdown signal — see ``apps/serve.py``'s SIGTERM handler)."""


class _Slot:
    """Host-side state of one running request."""

    __slots__ = ("req", "tokens", "ttft", "admitted_tick")

    def __init__(self, req: Request, first_token: int, ttft: float,
                 admitted_tick: int = 0):
        self.req = req
        self.tokens: List[int] = [first_token]
        self.ttft = ttft
        self.admitted_tick = admitted_tick


class SingleDeviceSlotBackend:
    """S decode slots over one device's worth of (replicated) params.

    ``params`` is the training-layout ``(stage_params, pre_params,
    post_params)`` triple (``model.init``); blocks are flattened/stacked
    once at construction, quantized leaves (``inference/quant.py``) pass
    through and dequantize in-step — same weight handling as
    :class:`~..inference.generate.Generator`.
    """

    def __init__(self, model, params, *, num_slots: int, max_len: int,
                 gen: GenerationConfig = GenerationConfig(),
                 buckets: Optional[BucketSpec] = None,
                 decode_chunk: int = 1, shape_cache_warn: int = 8,
                 kv_block_size: Optional[int] = None,
                 kv_pool_blocks: Optional[int] = None,
                 prefill_chunk: int = 16,
                 kv_dtype: Optional[str] = None,
                 kv_offload: bool = False,
                 kv_offload_blocks: Optional[int] = None,
                 resident="auto", resident_chunks: int = 8,
                 spec_tokens: Optional[int] = None,
                 draft="ngram", draft_stages: int = 1,
                 spec_branches: Optional[int] = None,
                 spec_adaptive: bool = False):
        if not hasattr(model, "embed_at"):
            raise TypeError(
                f"{type(model).__name__} has no embed_at; KV-cache "
                "generation needs position-offset embedding")
        if gen.num_beams != 1:
            raise ValueError(
                "the serve engine decodes greedy/sampled slots; beam "
                "search has no incremental slot form (num_beams must be 1)")
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if decode_chunk < 1:
            raise ValueError(
                f"decode_chunk must be >= 1, got {decode_chunk}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2, got {max_len}")
        self.model = model
        self.gen = gen
        self.num_slots = num_slots
        self.max_len = max_len
        self.buckets = buckets
        self.decode_chunk = decode_chunk
        self.shape_cache_warn = shape_cache_warn
        # resident tri-state: the fused multi-chunk loop pays off where
        # launch/sync overhead does (accelerators); "auto" keeps the cpu
        # default on the byte-for-byte single-chunk path.
        if resident not in ("auto", True, False):
            raise ValueError(
                f"resident must be 'auto', True or False, got {resident!r}")
        if resident == "auto":
            resident = jax.devices()[0].platform != "cpu"
        self.resident = bool(resident)
        if resident_chunks < 1:
            raise ValueError(
                f"resident_chunks must be >= 1, got {resident_chunks}")
        self.resident_chunks = resident_chunks
        spec = spec_tokens if spec_tokens is not None else gen.spec_tokens
        if spec is not None and spec < 2:
            raise ValueError(
                f"spec_tokens must be >= 2, got {spec}")
        if spec is not None and not self.resident:
            raise ValueError(
                "spec_tokens needs the resident loop (the draft/verify "
                "round IS the resident chunk body); pass resident=True")
        self.spec_tokens = spec
        # tokens per resident iteration: the readout stride of the token
        # buffer the resident program returns. Spec mode re-sets this
        # per launch to the adaptive ladder rung that ran.
        self.decode_width = spec if spec is not None else decode_chunk

        stage_params, pre_params, post_params = params
        cd = model.cfg.compute_dtype
        flat = [bp for stage in stage_params for bp in stage]
        blocks = [jax.tree_util.tree_map(
                      lambda p: p if isinstance(p, QuantLeaf)
                      else p.astype(cd),
                      bp, is_leaf=lambda x: isinstance(x, QuantLeaf))
                  for bp in flat]
        self._n_layers = len(blocks)
        self._n_stages = len(stage_params)
        self._layers_per_stage = len(stage_params[0])
        self._block_stack = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *blocks)
        self._pre = pre_params
        self._post = post_params

        if spec is not None:
            self._drafter = draft if isinstance(draft, DraftSource) \
                else resolve_draft(
                    draft, n_stages=self._n_stages,
                    layers_per_stage=self._layers_per_stage,
                    draft_stages=draft_stages,
                    spec_branches=spec_branches)
            if self._drafter.branches > 1 and \
                    not hasattr(model, "embed_tree"):
                raise TypeError(
                    f"{type(model).__name__} has no embed_tree; tree "
                    "verification needs per-node position embedding")
            # spec verify writes Q = 1 + branches*(K-1) rows per round
            # starting at most at pos = plen + max_new - 2; headroom
            # keeps the Q-row dynamic_update_slice inside the slab/view
            # so its start is never clamped (a clamped start misaligns
            # EVERY row written)
            self._spec_overshoot = self._drafter.branches * (spec - 1)
            # adaptive-K: a small pre-traced ladder of round depths; the
            # host picks a rung per launch from the per-slot accepted-
            # length EWMA. Non-adaptive = one rung = PR 11 behavior.
            self._spec_ladder = (
                sorted({2, (spec + 2) // 2, spec}) if spec_adaptive
                else [spec])
            self._spec_ewma = np.full((num_slots,), float(spec))
            self._spec_acc_total = 0
            self._spec_draft_total = 0
        else:
            if not (draft == "ngram" and draft_stages == 1
                    and spec_branches is None and not spec_adaptive):
                raise ValueError(
                    "draft/draft_stages/spec_branches/spec_adaptive "
                    "configure the speculative lane; set spec_tokens")
            self._drafter = None
            self._spec_overshoot = 0
            self._spec_ladder = []

        kbs = kv_block_size if kv_block_size is not None \
            else gen.kv_block_size
        self.paged = kbs is not None
        self.kv_dtype = kv_dtype
        proto = model.block.attn.make_cache(1, max_len, dtype=cd)
        if self.paged:
            # paged KV: a block pool + per-slot tables replace the slab.
            # Default pool = the slab's row budget (same memory, ~2x the
            # servable live slots on mixed-length traffic) + block 0.
            if prefill_chunk < 1:
                raise ValueError(
                    f"prefill_chunk must be >= 1, got {prefill_chunk}")
            self.prefill_chunk = prefill_chunk
            mb = -(-max_len // kbs)
            nb = kv_pool_blocks if kv_pool_blocks is not None \
                else num_slots * mb + 1
            if buckets is not None:
                gen.check_kv_headroom(buckets.max_len, kbs,
                                      self._spec_overshoot)
            self.pool = KvPool(
                num_blocks=nb, block_size=kbs, num_slots=num_slots,
                max_len=max_len, prefix_cache=gen.prefix_cache,
                gather_slack_rows=prefill_chunk)
            self._pool_kv = storage_for(
                proto, self._n_layers, nb, kbs, kv_dtype=kv_dtype)
            self.kv_offload = bool(kv_offload)
            if self.kv_offload:
                # host spill target for cold refcount-0 blocks: payloads
                # are raw device bytes (int8 codes + scales for int8
                # pools), so offload -> restore is a bitwise round trip
                self._kv_store = HostKvStore(
                    max_blocks=(kv_offload_blocks
                                if kv_offload_blocks is not None
                                else nb))
                self.pool.attach_offload(self._kv_store,
                                         self._offload_read_block)
                self._restore_jit = jax.jit(self._restore_fn,
                                            donate_argnums=(0,))
            else:
                self._kv_store = None
            self._chunk_jit = jax.jit(self._chunk_fn, donate_argnums=(2,))
            self._sample_jit = jax.jit(self._sample_fn)
            self._fork_jit = jax.jit(self._fork_fn, donate_argnums=(0,))
            self._decode_jit = jax.jit(self._decode_paged_fn,
                                       donate_argnums=(3, 8))
            # per-slot gathered views carried across decode chunks —
            # valid until a prefill moves a table (_views_dirty), when
            # the decode program re-gathers from the (always-current)
            # pool. Compute dtype even for int8 pools: the view is the
            # dequantized working set.
            R = self.pool.max_blocks * kbs
            self._views = {
                name: jnp.zeros(
                    (self._n_layers, num_slots, R) + proto[name].shape[2:],
                    cd)
                for name in ("k", "v")}
            self._views_dirty = True
        else:
            if kv_dtype is not None:
                raise ValueError(
                    "kv_dtype needs the paged pool (set kv_block_size); "
                    "the slab path stores KV in the compute dtype")
            if kv_offload:
                raise ValueError(
                    "kv_offload needs the paged pool (set kv_block_size); "
                    "the slab path has no block-level eviction to spill")
            self.kv_offload = False
            self._kv_store = None
            self.pool = None
            self._caches = jax.tree_util.tree_map(
                lambda a: jnp.zeros(
                    (self._n_layers, num_slots) + a.shape[1:], a.dtype),
                proto)
            self._decode_jit = jax.jit(self._decode_fn, donate_argnums=(3,))
        self._tok = jnp.zeros((num_slots,), jnp.int32)
        self._pos = jnp.zeros((num_slots,), jnp.int32)
        kd0 = jax.random.key_data(jax.random.key(0))
        self._key_data = jnp.broadcast_to(kd0, (num_slots,) + kd0.shape)

        if self.resident:
            if self.paged:
                # the regather flag lives ON DEVICE in resident mode —
                # prefill arms it (the one host decision, counted), the
                # resident program consumes and clears it in its carry
                self._regather = jnp.asarray(True)
                if self.spec_tokens is None:
                    self._resident_jit = jax.jit(
                        self._resident_paged_fn, donate_argnums=(3, 8))
                else:
                    # one jit per ladder rung: K is closure-bound so the
                    # donated positions line up with the un-curried
                    # signature; every rung traces once, then the steady
                    # state is rung selection over compiled programs
                    self._resident_spec_jits = {
                        k: jax.jit(
                            (lambda *a, _k=k:
                             self._resident_spec_paged_fn(_k, *a)),
                            donate_argnums=(3, 8, 10))
                        for k in self._spec_ladder}
            else:
                if self.spec_tokens is None:
                    self._resident_jit = jax.jit(
                        self._resident_fn, donate_argnums=(3,))
                else:
                    self._resident_spec_jits = {
                        k: jax.jit(
                            (lambda *a, _k=k:
                             self._resident_spec_fn(_k, *a)),
                            donate_argnums=(3, 7))
                        for k in self._spec_ladder}
            if self.spec_tokens is not None:
                # device-side token history, the n-gram draft source:
                # hist[s, p] = the token EMBEDDED at position p of slot
                # s (prompt rows written at prefill, accepted tokens at
                # their positions in-program). spec_tokens rows of slack
                # absorb the masked write past the last position.
                self._hist = jnp.full(
                    (num_slots, max_len + self.spec_tokens),
                    gen.pad_token_id, jnp.int32)

        self._prefill_programs = {}

    # -- validation --------------------------------------------------------

    def validate(self, prompt_len: int, max_new_tokens: int) -> None:
        """Admission-control shape checks — reject at submit, not at
        prefill, so a bad request never costs a slot. Paged mode adds
        the can-it-EVER-fit check: demand beyond the whole pool is
        unservable, not merely parked."""
        bucket = (self.buckets.bucket_for(prompt_len)
                  if self.buckets is not None and not self.paged
                  else prompt_len)
        if self.paged and self.pool.demand_for(
                prompt_len, max_new_tokens) > self.pool.allocatable:
            raise ValueError(
                f"request needs "
                f"{self.pool.demand_for(prompt_len, max_new_tokens)} KV "
                f"blocks but the whole pool holds "
                f"{self.pool.allocatable}; raise kv_pool_blocks or "
                f"shorten the request")
        if prompt_len + max_new_tokens + self._spec_overshoot > self.max_len:
            extra = (f" + speculative headroom {self._spec_overshoot}"
                     if self._spec_overshoot else "")
            raise ValueError(
                f"prompt_len {prompt_len} + max_new_tokens "
                f"{max_new_tokens}{extra} exceeds the slot cache "
                f"({self.max_len} rows); raise max_len or shorten the "
                f"request")
        if max_new_tokens > self.gen.max_new_tokens:
            raise ValueError(
                f"max_new_tokens {max_new_tokens} exceeds the engine cap "
                f"({self.gen.max_new_tokens})")
        mp = getattr(self.model, "max_position", None)
        limit = mp() if callable(mp) else None
        if limit is not None and max(bucket,
                                     prompt_len + max_new_tokens) > limit:
            raise ValueError(
                f"request needs position {max(bucket, prompt_len + max_new_tokens)} "
                f"but the positional table has {limit}")

    # -- device programs ---------------------------------------------------

    def _prefill_fn(self, block_stack, pre, post, caches, prompt,
                    true_len, slot, key):
        """One bucket-length-B prefill: runs the padded prompt through
        every layer against a fresh full-length temp cache, then writes
        the ENTIRE slot slab (previous occupant's rows are gone, not
        merely masked) and samples the first token with the exact
        batch-1 Generator key chain."""
        m, gen = self.model, self.gen
        cd = m.cfg.compute_dtype
        get_registry().counter("serve.engine.prefill_traces").inc()
        proto = m.block.attn.make_cache(1, self.max_len, dtype=cd)
        temp0 = jax.tree_util.tree_map(
            lambda a: jnp.zeros((self._n_layers,) + a.shape, a.dtype),
            proto)
        h = m.embed_at(pre, prompt, 0)                    # [1, B, d]

        def layer(h, inp):
            bp, cache = inp
            h, cache = m.block.decode(dequant_tree(bp, cd), h, cache, 0)
            return h, cache

        h, temp = jax.lax.scan(layer, h, (block_stack, temp0))
        caches = jax.tree_util.tree_map(
            lambda big, rows: jax.lax.dynamic_update_slice(
                big, rows, (0, slot) + (0,) * (rows.ndim - 2)),
            caches, temp)
        h_last = jax.lax.dynamic_slice(
            h, (0, true_len - 1, 0), (1, 1, h.shape[-1]))
        key, sub = jax.random.split(key)
        tok0 = sample_logits(head_logits(m, post, h_last)[:, 0, :],
                             sub, gen)[0]
        return caches, tok0, key

    def _decode_fn(self, block_stack, pre, post, caches, tok, pos,
                   key_data):
        """THE decode step: ``decode_chunk`` tokens for all S slots in
        one fixed-shape program. Per-slot positions ride a ``vmap`` over
        the layer decode (the scalar-pos cache write becomes a batched
        scatter). Traced exactly once — the counter below increments at
        trace time only, pinning the zero-recompile claim."""
        m, gen = self.model, self.gen
        cd = m.cfg.compute_dtype
        get_registry().counter("serve.engine.decode_traces").inc()
        eos = gen.eos_token_id

        def embed_one(t, p):
            return m.embed_at(pre, t[None, None], p)[0]    # [1, d]

        def step(carry, _):
            if eos is None:
                caches, tok, pos, key_data = carry
            else:
                caches, tok, pos, key_data, done = carry
            h = jax.vmap(embed_one)(tok, pos)              # [S, 1, d]

            def layer(h, inp):
                bp, cache = inp
                bpd = dequant_tree(bp, cd)

                def one(hh, cc, pp):
                    out, cc2 = m.block.decode(
                        bpd, hh[None],
                        jax.tree_util.tree_map(lambda a: a[None], cc), pp)
                    return out[0], jax.tree_util.tree_map(
                        lambda a: a[0], cc2)

                return jax.vmap(one)(h, cache, pos)

            h, caches = jax.lax.scan(layer, h, (block_stack, caches))
            logits = head_logits(m, post, h)[:, 0, :]      # [S, V]
            keys = jax.random.wrap_key_data(key_data)
            ks = jax.vmap(jax.random.split)(keys)          # [S, 2] keys
            key_data = jax.random.key_data(ks[:, 0])
            nxt = jax.vmap(
                lambda lg, k: sample_logits(lg[None], k, gen)[0])(
                    logits, ks[:, 1])
            if eos is None:
                return (caches, nxt, pos + 1, key_data), nxt
            nxt = jnp.where(done, jnp.int32(gen.pad_token_id), nxt)
            done = done | (nxt == jnp.int32(eos))
            return (caches, nxt, pos + 1, key_data, done), nxt

        init = (caches, tok, pos, key_data)
        if eos is not None:
            init = init + (tok == jnp.int32(eos),)
        carry, toks = jax.lax.scan(step, init, None,
                                   length=self.decode_chunk)
        caches, tok, pos, key_data = carry[:4]
        return caches, tok, pos, key_data, jnp.moveaxis(toks, 0, 1)

    # -- paged device programs ---------------------------------------------

    def _chunk_fn(self, block_stack, pre, pool_kv, table_row, tokens,
                  t0, true_len):
        """THE prefill program: one fixed-shape ``[1, C]`` chunk at a
        traced offset, looped on the host until the prompt is covered —
        ANY prompt length, one compile (the per-bucket programs the slab
        path keys on prompt shape are gone). Each layer attends against
        the slot's gathered block view (earlier chunks' rows included)
        and scatters its C new rows back through the table; pad
        positions past ``true_len`` land in the slot's own future decode
        blocks or the sacrificial block, both rewritten/ignored before
        any unmasked read. Returns ``h`` at ``true_len - 1`` clamped
        into this chunk — the host keeps the last chunk's."""
        m = self.model
        cd = m.cfg.compute_dtype
        get_registry().counter("serve.engine.prefill_chunk_traces").inc()
        bs = self.pool.block_size
        C = tokens.shape[1]
        h = m.embed_at(pre, tokens, t0)                  # [1, C, d]
        positions = t0 + jnp.arange(C, dtype=jnp.int32)
        ridx = flat_row_index(table_row, positions, bs)

        def layer(h, inp):
            bp, pool_l = inp
            cache = gather_block_cache(pool_l, table_row, block_size=bs,
                                       compute_dtype=cd)
            h, c2 = m.block.decode(dequant_tree(bp, cd), h, cache, t0)
            rows = {name: jax.lax.dynamic_slice(
                        c2[name], (0, t0) + (0,) * (c2[name].ndim - 2),
                        (1, C) + c2[name].shape[2:])[0]
                    for name in ("k", "v")}
            return h, scatter_block_rows(pool_l, ridx, rows)

        h, pool_kv = jax.lax.scan(layer, h, (block_stack, pool_kv))
        idx = jnp.clip(true_len - 1 - t0, 0, C - 1)
        h_last = jax.lax.dynamic_slice(h, (0, idx, 0), (1, 1, h.shape[-1]))
        return pool_kv, h_last

    def _sample_fn(self, post, h_last, key):
        """First-token epilogue: the exact batch-1 Generator key chain
        (split then sample) the slab prefill runs in-program — kept as
        its own fixed-shape program so the chunk loop stays
        length-agnostic."""
        key, sub = jax.random.split(key)
        tok0 = sample_logits(
            head_logits(self.model, post, h_last)[:, 0, :], sub,
            self.gen)[0]
        return tok0, key

    def _fork_fn(self, pool_kv, src, dst):
        """Copy-on-write block copy (src/dst traced — one program for
        every fork)."""
        get_registry().counter("serve.kv.fork_traces").inc()
        return copy_block(pool_kv, src, dst, block_axis=1)

    def _offload_read_block(self, bid: int) -> dict:
        """Host copy of one physical block across every pool array —
        the payload :class:`~.kvpool.HostKvStore` holds while the block
        is offloaded. Raw storage bytes (int8 codes + scales for int8
        pools), so the later restore is bitwise."""
        return {name: np.asarray(a[:, bid])
                for name, a in self._pool_kv.items()}

    def _restore_fn(self, pool_kv, dst, payload):
        """Write an offloaded block's host payload back at physical
        block ``dst`` (traced — ONE program for every restore, the
        mirror of :meth:`_fork_fn`; the view refresh rides the regather
        flag the admitting prefill arms anyway)."""
        get_registry().counter("serve.kv.restore_traces").inc()
        out = dict(pool_kv)
        for name, rows in payload.items():
            out[name] = jax.lax.dynamic_update_slice_in_dim(
                pool_kv[name], rows[:, None], dst, axis=1)
        return out

    def _decode_paged_fn(self, block_stack, pre, post, pool_kv, tables,
                         tok, pos, key_data, views, regather):
        """The paged decode step: each slot's block view — its first
        ``max_blocks`` table entries, covering every row it can read or
        write (``rows_needed <= max_len``), exactly the slab's attention
        footprint — is gathered ONLY when ``regather`` says a prefill
        moved a table since the last chunk; otherwise the views carried
        from the previous chunk are the same rows bitwise, because the
        end-of-chunk scatter keeps the pool current every tick. The
        chunk then runs ``decode_chunk`` slab-style steps against the
        view (bitwise-identical attention math, in-chunk rows read back
        from the view exactly as the slab reads its own updates), and
        the S*C new rows scatter back once through the FULL-width
        tables, whose sacrificial clamp routes overshoot/dead-slot
        writes into block 0 — a dead slot can never corrupt a
        reallocated block. Traced once; the same counter as the slab
        path pins zero steady-state recompiles."""
        m, gen = self.model, self.gen
        cd = m.cfg.compute_dtype
        get_registry().counter("serve.engine.decode_traces").inc()
        eos = gen.eos_token_id
        bs = self.pool.block_size
        C = self.decode_chunk
        S = tok.shape[0]
        pos0 = pos

        def embed_one(t, p):
            return m.embed_at(pre, t[None, None], p)[0]    # [1, d]

        view_t = tables[:, :self.pool.max_blocks + 1]

        def gather_layer(pool_l):
            out = jax.vmap(lambda tr: gather_block_cache(
                pool_l, tr, block_size=bs, compute_dtype=cd))(view_t)
            return {name: a[:, 0] for name, a in out.items()}  # [S, R, .]

        views = jax.lax.cond(
            regather, lambda v: jax.vmap(gather_layer)(pool_kv),
            lambda v: v, views)                        # [L, S, R, ...]

        def step(carry, _):
            if eos is None:
                views, tok, pos, key_data = carry
            else:
                views, tok, pos, key_data, done = carry
            h = jax.vmap(embed_one)(tok, pos)              # [S, 1, d]

            def layer(h, inp):
                bp, view_l = inp
                bpd = dequant_tree(bp, cd)

                def one(hh, cache_l, pp):
                    cache = {name: cache_l[name][None]
                             for name in ("k", "v")}
                    out, c2 = m.block.decode(bpd, hh[None], cache, pp)
                    return out[0], {name: c2[name][0]
                                    for name in ("k", "v")}

                h, view_l = jax.vmap(one)(h, view_l, pos)
                return h, view_l

            h, views = jax.lax.scan(layer, h, (block_stack, views))
            logits = head_logits(m, post, h)[:, 0, :]      # [S, V]
            keys = jax.random.wrap_key_data(key_data)
            ks = jax.vmap(jax.random.split)(keys)          # [S, 2] keys
            key_data = jax.random.key_data(ks[:, 0])
            nxt = jax.vmap(
                lambda lg, k: sample_logits(lg[None], k, gen)[0])(
                    logits, ks[:, 1])
            if eos is None:
                return (views, nxt, pos + 1, key_data), nxt
            nxt = jnp.where(done, jnp.int32(gen.pad_token_id), nxt)
            done = done | (nxt == jnp.int32(eos))
            return (views, nxt, pos + 1, key_data, done), nxt

        init = (views, tok, pos, key_data)
        if eos is not None:
            init = init + (tok == jnp.int32(eos),)
        carry, toks = jax.lax.scan(step, init, None, length=C)
        views, tok, pos, key_data = carry[:4]

        # rows written this chunk, back through the full-width tables
        ridx = jax.vmap(lambda tr, p0: flat_row_index(
            tr, p0 + jnp.arange(C, dtype=jnp.int32), bs))(tables, pos0)

        def scat_layer(_, inp):
            pool_l, view_l = inp
            rows = {name: jax.vmap(
                lambda v, p0: jax.lax.dynamic_slice(
                    v, (p0,) + (0,) * (v.ndim - 1),
                    (C,) + v.shape[1:]))(view_l[name], pos0).reshape(
                        (S * C,) + view_l[name].shape[2:])
                for name in ("k", "v")}
            return 0, scatter_block_rows(pool_l, ridx.reshape(-1), rows)

        _, pool_kv = jax.lax.scan(scat_layer, 0, (pool_kv, views))
        return pool_kv, tok, pos, key_data, views, jnp.moveaxis(toks, 0, 1)

    # -- resident device programs ------------------------------------------
    #
    # The resident loop is a `lax.while_loop` over the SAME per-chunk
    # math as the single-chunk programs above (the step bodies are
    # duplicated, not refactored, so the non-resident paths stay
    # byte-for-byte untouched). The carry adds three things the host
    # used to own: a per-slot `done` mask (eos/length), a per-slot
    # token `budget` (remaining max_new_tokens), and — paged — the
    # `regather` flag, consumed and cleared on device. The loop exits
    # early when any LIVE slot goes done (a slot freed: host admission
    # can change the slot set) or after `r_max` chunks (the deadline
    # horizon). One host sync per launch: the chunk count `k`, which
    # sizes the token readout. Per-step token/key/pos evolution is
    # bitwise the single-chunk chain; tokens past a slot's eos/budget
    # are pad and the host's readout break reaches them never.

    def _resident_step(self, block_stack, pre, post, carry, paged):
        """One decode step shared by the two non-spec resident bodies:
        the exact `_decode_fn`/`_decode_paged_fn` step with the done
        mask extended by the token budget."""
        m, gen = self.model, self.gen
        cd = m.cfg.compute_dtype
        eos = gen.eos_token_id
        caches, tok, pos, key_data, done, budget = carry

        def embed_one(t, p):
            return m.embed_at(pre, t[None, None], p)[0]

        h = jax.vmap(embed_one)(tok, pos)                  # [S, 1, d]

        def layer(h, inp):
            bp, cache = inp
            bpd = dequant_tree(bp, cd)

            if paged:
                def one(hh, cache_l, pp):
                    cache = {name: cache_l[name][None]
                             for name in ("k", "v")}
                    out, c2 = m.block.decode(bpd, hh[None], cache, pp)
                    return out[0], {name: c2[name][0]
                                    for name in ("k", "v")}
            else:
                def one(hh, cc, pp):
                    out, cc2 = m.block.decode(
                        bpd, hh[None],
                        jax.tree_util.tree_map(lambda a: a[None], cc), pp)
                    return out[0], jax.tree_util.tree_map(
                        lambda a: a[0], cc2)

            return jax.vmap(one)(h, cache, pos)

        h, caches = jax.lax.scan(layer, h, (block_stack, caches))
        logits = head_logits(m, post, h)[:, 0, :]          # [S, V]
        keys = jax.random.wrap_key_data(key_data)
        ks = jax.vmap(jax.random.split)(keys)              # [S, 2] keys
        key_data = jax.random.key_data(ks[:, 0])
        nxt = jax.vmap(
            lambda lg, k: sample_logits(lg[None], k, gen)[0])(
                logits, ks[:, 1])
        nxt = jnp.where(done, jnp.int32(gen.pad_token_id), nxt)
        budget = budget - jnp.where(done, 0, 1)
        done = done | (budget <= 0)
        if eos is not None:
            done = done | (nxt == jnp.int32(eos))
        return (caches, nxt, pos + 1, key_data, done, budget), nxt

    def _resident_done0(self, tok, live, budget):
        """Initial done mask: dead slots, spent budgets, and slots whose
        first token already hit eos (the engine retires those before
        decode — this covers direct backend callers)."""
        done = ~live | (budget <= 0)
        if self.gen.eos_token_id is not None:
            done = done | (tok == jnp.int32(self.gen.eos_token_id))
        return done

    def _resident_fn(self, block_stack, pre, post, caches, tok, pos,
                     key_data, live, budget, r_max):
        """Slab resident loop: up to ``r_max`` (traced, <= the static
        ``resident_chunks``) decode chunks back-to-back in one program.
        Returns the token buffer ``[S, R*C]``, per-chunk valid counts
        ``[S, R]`` and the chunk count actually run."""
        get_registry().counter("serve.engine.resident_traces").inc()
        C = self.decode_chunk
        R = self.resident_chunks
        S = tok.shape[0]

        def body(state):
            caches, tok, pos, key_data, done, budget, buf, k = state
            carry, toks = jax.lax.scan(
                lambda c, _: self._resident_step(
                    block_stack, pre, post, c, False),
                (caches, tok, pos, key_data, done, budget), None, length=C)
            caches, tok, pos, key_data, done, budget = carry
            buf = jax.lax.dynamic_update_slice(
                buf, jnp.moveaxis(toks, 0, 1), (0, k * C))
            return caches, tok, pos, key_data, done, budget, buf, k + 1

        def cond(state):
            return (state[7] < r_max) & ~jnp.any(live & state[4])

        buf0 = jnp.full((S, R * C), jnp.int32(self.gen.pad_token_id),
                        jnp.int32)
        state = (caches, tok, pos, key_data,
                 self._resident_done0(tok, live, budget), budget, buf0,
                 jnp.int32(0))
        caches, tok, pos, key_data, done, budget, buf, k = \
            jax.lax.while_loop(cond, body, state)
        counts = jnp.where(
            (jnp.arange(R, dtype=jnp.int32)[None, :] < k) & live[:, None],
            jnp.int32(C), jnp.int32(0))
        return caches, tok, pos, key_data, buf, counts, k

    def _resident_paged_fn(self, block_stack, pre, post, pool_kv, tables,
                           tok, pos, key_data, views, regather, live,
                           budget, r_max):
        """Paged resident loop. The regather decision rides the carry:
        the (traced) flag gathers fresh views once at entry iff a
        prefill moved a table since the last launch, and the program
        returns it CLEARED — a no-prefill tick launches with the cold
        flag and performs zero host-driven gather decisions. The
        2-branch cond is a role conditional (both branches produce the
        same view shape), not a dispatch."""
        m = self.model
        cd = m.cfg.compute_dtype
        get_registry().counter("serve.engine.resident_traces").inc()
        bs = self.pool.block_size
        C = self.decode_chunk
        R = self.resident_chunks
        S = tok.shape[0]
        view_t = tables[:, :self.pool.max_blocks + 1]

        def gather_layer(pool_l):
            out = jax.vmap(lambda tr: gather_block_cache(
                pool_l, tr, block_size=bs, compute_dtype=cd))(view_t)
            return {name: a[:, 0] for name, a in out.items()}

        views = jax.lax.cond(
            regather, lambda v: jax.vmap(gather_layer)(pool_kv),
            lambda v: v, views)                            # [L, S, R, ...]

        def body(state):
            pool_kv, views, tok, pos, key_data, done, budget, buf, k = state
            pos0 = pos
            carry, toks = jax.lax.scan(
                lambda c, _: self._resident_step(
                    block_stack, pre, post, c, True),
                (views, tok, pos, key_data, done, budget), None, length=C)
            views, tok, pos, key_data, done, budget = carry
            ridx = jax.vmap(lambda tr, p0: flat_row_index(
                tr, p0 + jnp.arange(C, dtype=jnp.int32), bs))(tables, pos0)

            def scat_layer(_, inp):
                pool_l, view_l = inp
                rows = {name: jax.vmap(
                    lambda v, p0: jax.lax.dynamic_slice(
                        v, (p0,) + (0,) * (v.ndim - 1),
                        (C,) + v.shape[1:]))(view_l[name], pos0).reshape(
                            (S * C,) + view_l[name].shape[2:])
                    for name in ("k", "v")}
                return 0, scatter_block_rows(pool_l, ridx.reshape(-1), rows)

            _, pool_kv = jax.lax.scan(scat_layer, 0, (pool_kv, views))
            buf = jax.lax.dynamic_update_slice(
                buf, jnp.moveaxis(toks, 0, 1), (0, k * C))
            return (pool_kv, views, tok, pos, key_data, done, budget,
                    buf, k + 1)

        def cond(state):
            return (state[8] < r_max) & ~jnp.any(live & state[5])

        buf0 = jnp.full((S, R * C), jnp.int32(self.gen.pad_token_id),
                        jnp.int32)
        state = (pool_kv, views, tok, pos, key_data,
                 self._resident_done0(tok, live, budget), budget, buf0,
                 jnp.int32(0))
        pool_kv, views, tok, pos, key_data, done, budget, buf, k = \
            jax.lax.while_loop(cond, body, state)
        counts = jnp.where(
            (jnp.arange(R, dtype=jnp.int32)[None, :] < k) & live[:, None],
            jnp.int32(C), jnp.int32(0))
        return (pool_kv, tok, pos, key_data, views,
                jnp.zeros((), jnp.bool_), buf, counts, k)

    # -- speculative resident programs -------------------------------------
    #
    # One resident iteration becomes a draft/verify ROUND: propose
    # K-1 tokens by prompt-lookup (the most recent earlier occurrence
    # of the current token in the slot's device-side history buffer),
    # verify [tok, drafts] teacher-forced in ONE fixed-shape q=K decode
    # at the slot's offset (the chunked-prefill mechanism, whose
    # width-invariance the prefill parity pins already establish), and
    # accept the leading prefix that matches plus the one correction
    # token. Rollback is free: rejected rows sit at positions >= the
    # advanced pos, causally masked, and the next round's q=K write
    # covers them before any unmasked read. The per-slot key chain
    # consumes exactly n_emit splits, so accepted tokens are bitwise
    # the sequential Generator chain.

    def _spec_round(self, K, block_stack, pre, post, carry, paged):
        """One draft/verify round (shared by the slab/paged spec
        bodies) at ladder depth ``K``. Carry: (caches-or-views, tok,
        pos, key_data, hist, done, budget); returns the updated carry
        plus the round's ``[S, K]`` token row and ``[S]`` accepted
        counts.

        With a multi-branch drafter the verify chunk is the flattened
        draft tree — ``Q = 1 + B*(K-1)`` rows under the causal tree
        mask (:func:`~..inference.draft.tree_layout`), same-depth nodes
        sharing one sample key so whichever branch lies on the true
        sequential path replays the exact Generator chain. The longest
        matching root-to-leaf path wins; its KV rows are relocated to
        the canonical positions before the round returns, so the next
        round's chunk reads them like any linear prefix."""
        m, gen = self.model, self.gen
        cd = m.cfg.compute_dtype
        eos = gen.eos_token_id
        caches, tok, pos, key_data, hist, done, budget = carry
        S = tok.shape[0]
        B = self._drafter.branches
        Q = 1 + B * (K - 1) if B > 1 else K
        ar = jnp.arange(K, dtype=jnp.int32)

        # 1) draft: [S, B, K-1] candidate continuations of tok
        drafts, caches = self._drafter.propose(
            m, gen, pre, block_stack, caches, tok, pos, hist, K, paged)

        # 2) verify: ONE fixed-shape q=Q teacher-forced decode. Linear
        # (B=1) keeps the PR 11 chunk byte-for-byte; tree embeds each
        # node at pos+depth and masks to ancestors-or-self.
        x = jnp.concatenate(
            [tok[:, None], drafts.reshape(S, B * (K - 1))], axis=1)
        if B == 1:
            anc = None
            h = jax.vmap(
                lambda xs, p: m.embed_at(pre, xs[None], p)[0])(x, pos)
        else:
            depths_np, anc_np = tree_layout(K, B)
            depths = jnp.asarray(depths_np)
            anc = jnp.asarray(anc_np)
            h = jax.vmap(
                lambda xs, p: m.embed_tree(pre, xs[None], p, depths)[0])(
                    x, pos)

        def layer(h, inp):
            bp, cache = inp
            bpd = dequant_tree(bp, cd)

            if paged:
                def one(hh, cache_l, pp):
                    cache = {name: cache_l[name][None]
                             for name in ("k", "v")}
                    out, c2 = m.block.decode(bpd, hh[None], cache, pp,
                                             tree=anc)
                    return out[0], {name: c2[name][0]
                                    for name in ("k", "v")}
            else:
                def one(hh, cc, pp):
                    out, cc2 = m.block.decode(
                        bpd, hh[None],
                        jax.tree_util.tree_map(lambda a: a[None], cc),
                        pp, tree=anc)
                    return out[0], jax.tree_util.tree_map(
                        lambda a: a[0], cc2)

            return jax.vmap(one)(h, cache, pos)

        h, caches = jax.lax.scan(layer, h, (block_stack, caches))
        logits = head_logits(m, post, h)                   # [S, Q, V]

        # 3) the sequential key chain, unrolled K deep: carries[i] is
        # the slot key AFTER i+1 splits, subs[i] the i-th sample key.
        # Tree nodes index subs by DEPTH: the sample at depth d is the
        # d-th sequential draw whichever branch it sits on.
        def chain(kd0):
            def sp(c, _):
                k2, sub = jax.random.split(jax.random.wrap_key_data(c))
                c2 = jax.random.key_data(k2)
                return c2, (c2, jax.random.key_data(sub))
            _, (carries, subs) = jax.lax.scan(sp, kd0, None, length=K)
            return carries, subs

        carries, subs = jax.vmap(chain)(key_data)
        node_subs = subs if B == 1 else subs[:, depths_np]
        t = jax.vmap(jax.vmap(
            lambda lg, sd: sample_logits(
                lg[None], jax.random.wrap_key_data(sd), gen)[0]))(
                    logits, node_subs)                     # [S, Q]

        # 4) accept the longest matching root-to-leaf path + 1
        # correction token. Any branch whose first L levels match
        # carries exactly the sequential chain's tokens, so ties agree
        # on every emitted token and argmax's first-max pick is safe.
        if B == 1:
            t_lin = t
            match = (drafts[:, 0, :] == t[:, :K - 1])
            lead = jnp.cumprod(match.astype(jnp.int32), axis=1)
            n_emit = jnp.int32(1) + jnp.sum(lead, axis=1)
        else:
            tb = t[:, 1:].reshape(S, B, K - 1)
            prev = jnp.concatenate(
                [jnp.broadcast_to(t[:, :1, None], (S, B, 1)),
                 tb[:, :, :-1]], axis=2)
            lead_b = jnp.cumprod(
                (drafts == prev).astype(jnp.int32), axis=2)
            len_b = jnp.sum(lead_b, axis=2)                # [S, B]
            bsel = jnp.argmax(len_b, axis=1).astype(jnp.int32)
            n_emit = jnp.int32(1) + jnp.take_along_axis(
                len_b, bsel[:, None], axis=1)[:, 0]
            t_lin = jnp.concatenate(
                [t[:, :1],
                 jnp.take_along_axis(
                     tb, bsel[:, None, None], axis=1)[:, 0]], axis=1)
        n_emit = jnp.where(done, jnp.int32(0), n_emit)
        emit_mask = ar[None, :] < n_emit[:, None]
        toks_out = jnp.where(emit_mask, t_lin,
                             jnp.int32(gen.pad_token_id))

        if B > 1:
            # relocate the winning branch's K-1 chunk rows to the
            # canonical rows [pos+1, pos+K): rows at or beyond the
            # advanced pos' are junk-allowed (causally masked, and the
            # next round's Q-row write covers them), so the whole
            # branch copies unconditionally.
            arr = jnp.arange(K - 1, dtype=jnp.int32)

            def rl(a):          # [L, S, rows, ...] (slab slab-rows or
                def ps(al, p, sb):              # paged view-rows alike)
                    src = p + 1 + sb * (K - 1) + arr
                    rows = jnp.take(al, src, axis=1)
                    return jax.lax.dynamic_update_slice(
                        al, rows, (0, p + 1) + (0,) * (al.ndim - 2))
                return jax.vmap(ps, in_axes=(1, 0, 0),
                                out_axes=1)(a, pos, bsel)

            caches = jax.tree_util.tree_map(rl, caches)

        # 5) advance — done slots frozen (pos/key/hist/budget untouched)
        last = jnp.take_along_axis(
            t_lin, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0]
        tok = jnp.where(done, tok, last)

        def hupd(hrow, p, trow, n):
            cur = jax.lax.dynamic_slice(hrow, (p + 1,), (K,))
            upd = jnp.where(ar < n, trow, cur)
            return jax.lax.dynamic_update_slice(hrow, upd, (p + 1,))

        hist = jax.vmap(hupd)(hist, pos, t_lin, n_emit)
        sel = jnp.concatenate([key_data[:, None], carries], axis=1)
        key_data = jax.vmap(lambda s, n: s[n])(sel, n_emit)
        pos = pos + n_emit
        budget = budget - n_emit
        done = done | (budget <= 0)
        if eos is not None:
            done = done | jnp.any(
                (t_lin == jnp.int32(eos)) & emit_mask, axis=1)
        return (caches, tok, pos, key_data, hist, done, budget,
                toks_out, n_emit)

    def _resident_spec_fn(self, K, block_stack, pre, post, caches, tok,
                          pos, key_data, hist, live, budget, r_max):
        """Slab resident loop with the speculative lane: each iteration
        is one draft/verify round emitting 1..K tokens per live slot."""
        get_registry().counter("serve.engine.resident_traces").inc()
        R = self.resident_chunks
        S = tok.shape[0]

        def body(state):
            caches, tok, pos, key_data, hist, done, budget, \
                buf, nacc, k = state
            (caches, tok, pos, key_data, hist, done, budget, toks,
             n_emit) = self._spec_round(
                K, block_stack, pre, post,
                (caches, tok, pos, key_data, hist, done, budget), False)
            buf = jax.lax.dynamic_update_slice(buf, toks, (0, k * K))
            nacc = jax.lax.dynamic_update_slice(
                nacc, n_emit[:, None], (0, k))
            return (caches, tok, pos, key_data, hist, done, budget,
                    buf, nacc, k + 1)

        def cond(state):
            return (state[9] < r_max) & ~jnp.any(live & state[5])

        buf0 = jnp.full((S, R * K), jnp.int32(self.gen.pad_token_id),
                        jnp.int32)
        nacc0 = jnp.zeros((S, R), jnp.int32)
        state = (caches, tok, pos, key_data, hist,
                 self._resident_done0(tok, live, budget), budget,
                 buf0, nacc0, jnp.int32(0))
        caches, tok, pos, key_data, hist, done, budget, buf, nacc, k = \
            jax.lax.while_loop(cond, body, state)
        return caches, tok, pos, key_data, hist, buf, nacc, k

    def _resident_spec_paged_fn(self, K, block_stack, pre, post,
                                pool_kv, tables, tok, pos, key_data,
                                views, regather, hist, live, budget,
                                r_max):
        """Paged resident loop with the speculative lane: the verify
        runs against the carried views, each round's Q chunk rows
        scatter back through the full-width tables (rejected/dead rows
        route to the sacrificial block exactly like dead-slot
        decode)."""
        m = self.model
        cd = m.cfg.compute_dtype
        get_registry().counter("serve.engine.resident_traces").inc()
        bs = self.pool.block_size
        B = self._drafter.branches
        Q = 1 + B * (K - 1) if B > 1 else K
        R = self.resident_chunks
        S = tok.shape[0]
        view_t = tables[:, :self.pool.max_blocks + 1]

        def gather_layer(pool_l):
            out = jax.vmap(lambda tr: gather_block_cache(
                pool_l, tr, block_size=bs, compute_dtype=cd))(view_t)
            return {name: a[:, 0] for name, a in out.items()}

        views = jax.lax.cond(
            regather, lambda v: jax.vmap(gather_layer)(pool_kv),
            lambda v: v, views)

        def body(state):
            pool_kv, views, tok, pos, key_data, hist, done, budget, \
                buf, nacc, k = state
            pos0 = pos
            (views, tok, pos, key_data, hist, done, budget, toks,
             n_emit) = self._spec_round(
                K, block_stack, pre, post,
                (views, tok, pos, key_data, hist, done, budget), True)
            ridx = jax.vmap(lambda tr, p0: flat_row_index(
                tr, p0 + jnp.arange(Q, dtype=jnp.int32), bs))(tables, pos0)

            def scat_layer(_, inp):
                pool_l, view_l = inp
                rows = {name: jax.vmap(
                    lambda v, p0: jax.lax.dynamic_slice(
                        v, (p0,) + (0,) * (v.ndim - 1),
                        (Q,) + v.shape[1:]))(view_l[name], pos0).reshape(
                            (S * Q,) + view_l[name].shape[2:])
                    for name in ("k", "v")}
                return 0, scatter_block_rows(pool_l, ridx.reshape(-1), rows)

            _, pool_kv = jax.lax.scan(scat_layer, 0, (pool_kv, views))
            buf = jax.lax.dynamic_update_slice(buf, toks, (0, k * K))
            nacc = jax.lax.dynamic_update_slice(
                nacc, n_emit[:, None], (0, k))
            return (pool_kv, views, tok, pos, key_data, hist, done,
                    budget, buf, nacc, k + 1)

        def cond(state):
            return (state[10] < r_max) & ~jnp.any(live & state[6])

        buf0 = jnp.full((S, R * K), jnp.int32(self.gen.pad_token_id),
                        jnp.int32)
        nacc0 = jnp.zeros((S, R), jnp.int32)
        state = (pool_kv, views, tok, pos, key_data, hist,
                 self._resident_done0(tok, live, budget), budget,
                 buf0, nacc0, jnp.int32(0))
        (pool_kv, views, tok, pos, key_data, hist, done, budget, buf,
         nacc, k) = jax.lax.while_loop(cond, body, state)
        return (pool_kv, tok, pos, key_data, views,
                jnp.zeros((), jnp.bool_), hist, buf, nacc, k)

    # -- backend API -------------------------------------------------------

    def prefill(self, slot: int, prompt: Sequence[int], seed: int,
                max_new_tokens: Optional[int] = None) -> int:
        """Fill slot ``slot``'s cache rows from ``prompt`` and return the
        first sampled token. Blocking — the returned int IS the TTFT
        moment. Slab mode: one program per prompt-length bucket. Paged
        mode: ONE chunked program regardless of length;
        ``max_new_tokens`` sizes the block reservation (defaults to the
        engine cap — full-demand reservation means no mid-decode OOM)."""
        reg = get_registry()
        if self.paged:
            return self._prefill_paged(
                slot, prompt, seed,
                max_new_tokens if max_new_tokens is not None
                else self.gen.max_new_tokens)
        if self.buckets is not None:
            padded, p = self.buckets.pad(prompt, self.gen.pad_token_id)
        else:
            padded, p = list(prompt), len(prompt)
        B = len(padded)
        run = self._prefill_programs.get(B)
        if run is None:
            reg.counter("serve.engine.prefill_program_misses").inc()
            run = jax.jit(self._prefill_fn, donate_argnums=(3,))
            self._prefill_programs[B] = run
            reg.gauge("serve.engine.prefill_programs").set(
                len(self._prefill_programs))
            if self.buckets is None and \
                    len(self._prefill_programs) == self.shape_cache_warn + 1:
                import warnings
                warnings.warn(
                    f"serve engine compiled "
                    f"{len(self._prefill_programs)} distinct prefill "
                    f"programs with bucketing DISABLED — every new "
                    f"prompt length recompiles. Pass a BucketSpec to cap "
                    f"the program cache.", RuntimeWarning, stacklevel=3)
        else:
            reg.counter("serve.engine.prefill_program_hits").inc()
        arr = jnp.asarray(padded, jnp.int32)[None, :]
        key = jax.random.key(seed)
        caches, tok0, key = run(self._block_stack, self._pre, self._post,
                                self._caches, arr, jnp.int32(p),
                                jnp.int32(slot), key)
        self._caches = caches
        tok0 = int(tok0)
        self._tok = self._tok.at[slot].set(tok0)
        self._pos = self._pos.at[slot].set(p)
        self._key_data = self._key_data.at[slot].set(
            jax.random.key_data(key))
        self._hist_write(slot, prompt, tok0)
        return tok0

    def _hist_write(self, slot: int, prompt: Sequence[int],
                    tok0: int) -> None:
        """Seed the speculative draft history for a freshly prefilled
        slot: hist[s, p] = the token embedded at position p (prompt
        rows + the first sampled token); pad beyond."""
        if self.spec_tokens is None:
            return
        row = np.full((self._hist.shape[1],), self.gen.pad_token_id,
                      np.int32)
        row[:len(prompt)] = np.asarray(list(prompt), np.int32)
        row[len(prompt)] = tok0
        self._hist = self._hist.at[slot].set(jnp.asarray(row))
        # adaptive-K starts each request optimistic: full draft depth
        # until its own acceptance says otherwise
        self._spec_ewma[slot] = float(self.spec_tokens)

    def _prefill_paged(self, slot: int, prompt: Sequence[int], seed: int,
                       max_new_tokens: int) -> int:
        """Admit into the pool (reserving full demand), run the COW
        forks, stream the prompt's recompute tail through the one chunk
        program, sample the first token with the Generator key chain. A
        failure mid-stream releases the reservation and unpublishes any
        half-written cache entries."""
        plen = len(prompt)
        adm = self.pool.admit(slot, prompt, max_new_tokens,
                              chunk=self.prefill_chunk)
        try:
            for dst, payload in adm.restores:
                # offloaded prefix blocks this admission reuses come
                # back from the host store BEFORE any fork/chunk writes;
                # the regather armed below refreshes the decode views —
                # no extra host decision per tick
                self._pool_kv = self._restore_jit(
                    self._pool_kv, jnp.int32(dst),
                    {k: jnp.asarray(v) for k, v in payload.items()})
            for src, dst in adm.cow_forks:
                self._pool_kv = self._fork_jit(
                    self._pool_kv, jnp.int32(src), jnp.int32(dst))
            trow = jnp.asarray(adm.table)
            C = self.prefill_chunk
            pad = self.gen.pad_token_id
            t = adm.resume_from
            h_last = None
            while t < plen:
                toks = list(prompt[t:t + C])
                toks += [pad] * (C - len(toks))
                arr = jnp.asarray(toks, jnp.int32)[None, :]
                self._pool_kv, h_last = self._chunk_jit(
                    self._block_stack, self._pre, self._pool_kv, trow,
                    arr, jnp.int32(t), jnp.int32(plen))
                t += C
            tok0, key = self._sample_jit(
                self._post, h_last, jax.random.key(seed))
        except Exception:
            self.pool.release(slot, failed=True)
            raise
        tok0 = int(tok0)
        self._tok = self._tok.at[slot].set(tok0)
        self._pos = self._pos.at[slot].set(plen)
        self._key_data = self._key_data.at[slot].set(
            jax.random.key_data(key))
        self._views_dirty = True       # this slot's table moved
        if self.resident:
            # arm the device-side regather flag — the ONE host gather
            # decision per admission (counted here; steady-state
            # resident ticks make zero)
            self._regather = jnp.asarray(True)
            get_registry().counter(
                "serve.kv.regather_host_decisions").inc()
        self._hist_write(slot, prompt, tok0)
        return tok0

    def decode(self, live: np.ndarray,
               budgets: Optional[np.ndarray] = None,
               r_max: Optional[int] = None):
        """One decode chunk for all slots. Returns ``(tokens [S, K],
        valid [S, K])`` — dead slots compute garbage (their rows are
        rewritten at the next prefill — or, paged, land in the
        sacrificial block); ``valid`` masks them out.

        With ``budgets`` (per-slot remaining max_new_tokens) on a
        resident backend, the call runs the RESIDENT loop instead: up
        to ``r_max`` chunks (default ``resident_chunks``) in one
        device program, returning ``[S, k*width]`` tokens with the
        per-chunk validity the device's done-masking produced. Without
        ``budgets`` the single-chunk path runs even when
        ``resident=True`` — that is the parity reference."""
        if self.resident and budgets is not None:
            return self._decode_resident(live, budgets, r_max)
        if self.paged:
            get_registry().counter(
                "serve.kv.regather_host_decisions").inc()
            pool_kv, tok, pos, kd, views, toks = self._decode_jit(
                self._block_stack, self._pre, self._post, self._pool_kv,
                jnp.asarray(self.pool.table), self._tok, self._pos,
                self._key_data, self._views,
                jnp.asarray(self._views_dirty))
            self._pool_kv = pool_kv
            self._views = views
            self._views_dirty = False
            if self.resident:
                self._regather = jnp.asarray(False)  # views now current
        else:
            caches, tok, pos, kd, toks = self._decode_jit(
                self._block_stack, self._pre, self._post, self._caches,
                self._tok, self._pos, self._key_data)
            self._caches = caches
        self._tok, self._pos, self._key_data = tok, pos, kd
        toks = np.asarray(toks)
        valid = np.broadcast_to(
            np.asarray(live, bool)[:, None], toks.shape)
        return toks, valid

    def _decode_resident(self, live: np.ndarray, budgets: np.ndarray,
                         r_max: Optional[int]):
        """One resident launch: up to ``r_max`` chunks/rounds on
        device, ONE host sync (the chunk count) to size the readout."""
        reg = get_registry()
        R = self.resident_chunks
        rm = R if r_max is None else max(1, min(int(r_max), R))
        live_d = jnp.asarray(np.asarray(live, bool))
        budget = jnp.asarray(np.asarray(budgets, np.int32))
        if self.spec_tokens is not None:
            self.decode_width = self._pick_spec_k(live)
        if self.paged:
            tables = jnp.asarray(self.pool.table)
            if self.spec_tokens is not None:
                (pool_kv, tok, pos, kd, views, regather, hist, buf,
                 counts, k) = self._resident_spec_jits[self.decode_width](
                    self._block_stack, self._pre, self._post,
                    self._pool_kv, tables, self._tok, self._pos,
                    self._key_data, self._views, self._regather,
                    self._hist, live_d, budget, jnp.int32(rm))
                self._hist = hist
            else:
                (pool_kv, tok, pos, kd, views, regather, buf, counts,
                 k) = self._resident_jit(
                    self._block_stack, self._pre, self._post,
                    self._pool_kv, tables, self._tok, self._pos,
                    self._key_data, self._views, self._regather,
                    live_d, budget, jnp.int32(rm))
            self._pool_kv = pool_kv
            self._views = views
            self._views_dirty = False
            self._regather = regather          # cleared, never synced
        else:
            if self.spec_tokens is not None:
                caches, tok, pos, kd, hist, buf, counts, k = \
                    self._resident_spec_jits[self.decode_width](
                        self._block_stack, self._pre, self._post,
                        self._caches, self._tok, self._pos,
                        self._key_data, self._hist, live_d, budget,
                        jnp.int32(rm))
                self._hist = hist
            else:
                caches, tok, pos, kd, buf, counts, k = \
                    self._resident_jit(
                        self._block_stack, self._pre, self._post,
                        self._caches, self._tok, self._pos,
                        self._key_data, live_d, budget, jnp.int32(rm))
            self._caches = caches
        self._tok, self._pos, self._key_data = tok, pos, kd
        k = int(k)                             # THE host sync
        if k < rm:
            reg.counter("serve.engine.device_exits").inc()
        W = self.decode_width
        toks = np.asarray(buf)[:, :k * W]
        counts = np.asarray(counts)[:, :k]
        valid = (np.arange(W)[None, None, :]
                 < counts[:, :, None]).reshape(self.num_slots, k * W)
        if self.spec_tokens is not None:
            lmask = np.asarray(live, bool)
            lc = counts[lmask]
            rounds = int((lc > 0).sum())
            emitted = int(lc.sum())
            reg.counter("serve.engine.spec_rounds").inc(rounds)
            reg.counter("serve.engine.spec_emitted").inc(emitted)
            # spec telemetry: acceptance = accepted draft tokens over
            # drafted positions (K-1 per round), cumulative; per-round
            # accepted-length histogram (log2 buckets downstream);
            # draft cost as the drafter's work-unit prediction at the
            # rung that ran
            self._spec_acc_total += max(emitted - rounds, 0)
            self._spec_draft_total += rounds * (W - 1)
            if self._spec_draft_total:
                reg.gauge("serve.spec.acceptance_rate").set(
                    self._spec_acc_total / self._spec_draft_total)
            reg.gauge("serve.spec.draft_cost_frac").set(
                self._drafter.draft_cost_frac(W, self._n_layers))
            hist_m = reg.histogram("serve.spec.accept_len")
            for v in lc[lc > 0]:
                hist_m.observe(float(v))
            # adaptive-K: per-slot EWMA of accepted length feeds the
            # next launch's rung pick (shrink when drafts miss, grow
            # back when they land)
            if len(self._spec_ladder) > 1:
                rc = np.maximum((counts > 0).sum(axis=1), 1)
                mean_acc = counts.sum(axis=1) / rc
                upd = lmask & (counts.sum(axis=1) > 0)
                self._spec_ewma[upd] = (0.7 * self._spec_ewma[upd]
                                        + 0.3 * mean_acc[upd])
        return toks, valid

    def _pick_spec_k(self, live: np.ndarray) -> int:
        """Smallest pre-traced ladder rung covering the live slots'
        accepted-length EWMA (plus one probe token so acceptance can
        grow back). Single-rung ladders — the non-adaptive default —
        short-circuit to ``spec_tokens``."""
        ladder = self._spec_ladder
        if len(ladder) == 1:
            return ladder[0]
        lmask = np.asarray(live, bool)
        if not lmask.any():
            return ladder[0]
        need = int(np.ceil(self._spec_ewma[lmask].max())) + 1
        need = max(2, min(need, self.spec_tokens))
        for k in ladder:
            if k >= need:
                return k
        return ladder[-1]

    def can_admit(self, prompt_len: int, max_new_tokens: int,
                  prompt: Optional[Sequence[int]] = None) -> bool:
        """Block-availability admission gate (always True for the slab —
        its reservation is the slot itself)."""
        if not self.paged:
            return True
        return self.pool.can_admit(prompt_len, max_new_tokens, prompt,
                                   chunk=self.prefill_chunk)

    def release(self, slot: int) -> None:
        """Engine retirement hook: return the slot's blocks to the pool
        (no-op for the slab — the next prefill rewrites the rows)."""
        if self.paged:
            self.pool.release(slot)

    def program_stats(self) -> dict:
        if self.paged:
            return {"prefill_programs": 1, "decode_chunk": self.decode_chunk,
                    "kv": "paged"}
        return {"prefill_programs": len(self._prefill_programs),
                "decode_chunk": self.decode_chunk, "kv": "slab"}

    # -- KV handoff (fleet session remap) ----------------------------------

    def export_prefix_payload(self, prompt: Sequence[int],
                              codec: str = "int8") -> Optional[dict]:
        """Serialize this backend's cached shared-prefix blocks covering
        ``prompt`` for a fleet KV handoff. ``codec="raw"`` ships the
        pool's stored bytes exactly (in-process handoff — bitwise, so
        prefix hits on the destination preserve token parity);
        ``codec="int8"`` quantizes float rows through
        :func:`~..inference.quant.quantize_kv_rows` for the wire (int8
        pools are already their own int8 path and ship raw either way).
        Returns None when there is no pool or no cached prefix."""
        if not self.paged:
            return None
        if codec not in ("raw", "int8"):
            raise ValueError(f"codec must be raw|int8, got {codec!r}")
        entries = self.pool.cached_prefix_entries(prompt)
        if not entries:
            return None
        bids = jnp.asarray([b for _, b in entries], jnp.int32)
        int8_storage = "k_scale" in self._pool_kv
        arrays = {}
        if int8_storage or codec == "raw":
            names = (("k", "v", "k_scale", "v_scale") if int8_storage
                     else ("k", "v"))
            for name in names:
                arrays[name] = np.asarray(
                    jnp.take(self._pool_kv[name], bids, axis=1))
            wire_codec = "raw"
        else:
            from ..inference.quant import quantize_kv_rows
            for name in ("k", "v"):
                q, s = quantize_kv_rows(
                    jnp.take(self._pool_kv[name], bids, axis=1))
                arrays[name] = np.asarray(q)
                arrays[name + "_scale"] = np.asarray(s)
            wire_codec = "int8"
        nbytes = sum(a.nbytes for a in arrays.values())
        get_registry().counter("serve.kv.prefix_exported").inc(len(entries))
        return {"hashes": [h for h, _ in entries],
                "block_size": self.pool.block_size,
                "n_layers": self._n_layers,
                "codec": wire_codec,
                "int8_storage": int8_storage,
                "arrays": arrays,
                "nbytes": nbytes}

    def import_prefix_payload(self, payload: dict) -> int:
        """Seat an exported prefix payload into this backend's pool:
        allocate destination blocks, write the rows onto the device
        arrays, and register the hashes as refs-0 cached entries (the
        next admission takes the refs). Hashes already cached locally
        are skipped; returns the number of blocks actually seated (0
        for slab backends or a geometry mismatch — a handoff between
        heterogeneous pools is a silent no-op, not an error: the
        destination simply re-prefills cold)."""
        if not self.paged:
            return 0
        if (payload.get("block_size") != self.pool.block_size
                or payload.get("n_layers") != self._n_layers):
            return 0
        int8_storage = "k_scale" in self._pool_kv
        fresh = [(i, h) for i, h in enumerate(payload["hashes"])
                 if h not in self.pool._cached]
        if not fresh:
            return 0
        dst = self.pool.take_blocks(len(fresh))
        fresh = fresh[:len(dst)]
        if not fresh:
            return 0
        src_idx = jnp.asarray([i for i, _ in fresh], jnp.int32)
        dst_idx = jnp.asarray(dst, jnp.int32)
        arrays = payload["arrays"]
        codec = payload.get("codec", "raw")
        if codec == "raw" and payload.get("int8_storage") == int8_storage:
            names = (("k", "v", "k_scale", "v_scale") if int8_storage
                     else ("k", "v"))
            for name in names:
                rows = jnp.take(jnp.asarray(arrays[name]), src_idx, axis=1)
                self._pool_kv[name] = self._pool_kv[name].at[
                    :, dst_idx].set(rows.astype(self._pool_kv[name].dtype))
        else:
            # cross-codec: materialize float rows, then store in this
            # pool's own layout (re-quantizing for int8 storage)
            from ..inference.quant import quantize_kv_rows
            for name in ("k", "v"):
                rows = jnp.take(jnp.asarray(arrays[name]), src_idx, axis=1)
                if codec == "int8" or payload.get("int8_storage"):
                    scale = jnp.take(
                        jnp.asarray(arrays[name + "_scale"]), src_idx,
                        axis=1)
                    rows = rows.astype(jnp.float32) * scale
                if int8_storage:
                    q, s = quantize_kv_rows(rows)
                    self._pool_kv[name] = \
                        self._pool_kv[name].at[:, dst_idx].set(q)
                    sa = self._pool_kv[name + "_scale"]
                    self._pool_kv[name + "_scale"] = \
                        sa.at[:, dst_idx].set(s)
                else:
                    self._pool_kv[name] = self._pool_kv[name].at[
                        :, dst_idx].set(
                            rows.astype(self._pool_kv[name].dtype))
        seated = self.pool.seat_prefix(
            [(h, int(b)) for (_, h), b in zip(fresh, dst)],
            chain=payload["hashes"])
        get_registry().counter("serve.kv.prefix_imported").inc(seated)
        return seated


class ServeEngine:
    """The continuous-batching scheduler over a slot backend.

    ``backend`` is a :class:`SingleDeviceSlotBackend` or
    :class:`~.ring.RingSlotBackend`; the engine itself is pure host-side
    bookkeeping (single-threaded tick loop — call ``tick`` from one
    thread). ``queue`` defaults to a fresh bounded
    :class:`~.queue.RequestQueue`; pass your own to share a front door
    or to inject a test clock.

    ``watchdog`` (a :class:`~..resilience.TickWatchdog`) arms the
    host-side health policies — slow-tick accounting, stuck-slot
    retirement, degraded-mode shedding; None (default) changes nothing.
    ``chaos`` (a :class:`~..resilience.ChaosPlan`) injects serve-side
    faults by tick index for the chaos bench/tests. A backend exception
    is contained, never fatal: a failed prefill retires only the
    offending request (``status="error"``, the slot goes back to the
    free list, ``resilience.slot_errors`` counts it); a failed decode
    skips the tick with all slot state intact, and only after
    ``decode_error_limit`` consecutive failures are the live slots
    retired as errors (batched decode cannot attribute the fault to one
    slot).
    """

    def __init__(self, backend, queue: Optional[RequestQueue] = None,
                 *, event_log=None,
                 clock: Optional[Callable[[], float]] = None,
                 watchdog=None, chaos=None, decode_error_limit: int = 3,
                 phase: str = "mixed"):
        if phase not in ("mixed", "prefill", "decode"):
            raise ValueError(
                f"phase must be 'mixed', 'prefill' or 'decode', got "
                f"{phase!r}")
        self.phase = phase
        self.backend = backend
        if queue is None:
            queue = RequestQueue(clock=clock or time.monotonic)
        elif clock is not None and clock is not queue.clock:
            raise ValueError(
                "pass the clock on the queue (engine adopts queue.clock)")
        if decode_error_limit < 1:
            raise ValueError(
                f"decode_error_limit must be >= 1, got {decode_error_limit}")
        self.queue = queue
        self.clock = queue.clock
        self.events = event_log if event_log is not None else NULL_EVENT_LOG
        self.watchdog = watchdog
        self.chaos = chaos
        self.decode_error_limit = decode_error_limit
        self._slots: List[Optional[_Slot]] = [None] * backend.num_slots
        self._free = list(range(backend.num_slots - 1, -1, -1))
        self._responses = {}
        self._tick_index = 0
        self._decode_errors = 0
        self._miss_ewma = 0.0
        self._draining = False
        # observed per-chunk decode latency (EWMA) — sizes the resident
        # deadline horizon in chunks; None until the first decode
        self._chunk_ewma: Optional[float] = None

    # -- front door --------------------------------------------------------

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None, seed: int = 0,
               priority: int = 0,
               timeout_s: Optional[float] = None) -> Request:
        """Validate + enqueue. Raises ``ValueError`` on an unservable
        request (too long for the buckets/cache/positions) and
        :class:`~.queue.QueueFull` under backpressure."""
        reg = get_registry()
        if self._draining:
            raise EngineDraining(
                "engine is draining: live requests are finishing and no "
                "new work is admitted")
        if max_new_tokens is None:
            max_new_tokens = self.backend.gen.max_new_tokens
        self._check_phase(prompt, max_new_tokens)
        self.backend.validate(len(prompt), max_new_tokens)
        try:
            req = self.queue.submit(prompt, max_new_tokens=max_new_tokens,
                                    seed=seed, priority=priority,
                                    timeout_s=timeout_s)
        except QueueFull:
            reg.counter("serve.engine.rejected").inc()
            raise
        reg.counter("serve.engine.submitted").inc()
        reg.gauge("serve.engine.queue_depth").set(self.queue.depth)
        return req

    def place(self, req: Request) -> Request:
        """Router placement: admit an EXISTING :class:`~.queue.Request`
        into this engine's queue, preserving its id, arrival and
        deadline (no new deadline credit) and counting the placement in
        ``req.attempts`` — the router's retry-budget ledger. Raises
        like ``submit`` (:class:`EngineDraining`, ``ValueError``,
        :class:`~.queue.QueueFull`)."""
        reg = get_registry()
        if self._draining:
            raise EngineDraining(
                "engine is draining: live requests are finishing and no "
                "new work is admitted")
        self._check_phase(req.prompt, req.max_new_tokens)
        self.backend.validate(len(req.prompt), req.max_new_tokens)
        self.queue.requeue(req)
        req.attempts += 1
        reg.counter("serve.engine.placed").inc()
        reg.gauge("serve.engine.queue_depth").set(self.queue.depth)
        return req

    def _check_phase(self, prompt: Sequence[int],
                     max_new_tokens: int) -> None:
        """Disaggregated operating modes (fleet/disagg.py). A prefill
        replica serves ONLY the chunked-prefill program: requests must
        arrive clamped to ``max_new_tokens=1`` (the first token retires
        the slot straight off the prefill, leaving the prompt's prefix
        blocks cached for export). A decode replica never prefills from
        scratch: a prompt spanning at least one full KV block must have
        its prefix already seated (``import_prefix_payload``) so the
        admission prefill merely resumes from the cached frontier, and
        the imported-prefix length must fit the decode slot span
        (:meth:`~...inference.generate.GenerationConfig.check_decode_headroom`).
        Mixed mode (default) changes nothing."""
        if self.phase == "prefill" and max_new_tokens != 1:
            raise ValueError(
                f"prefill-only replica: requests must arrive clamped to "
                f"max_new_tokens=1, got {max_new_tokens} — route the "
                f"decode phase to a decode or mixed replica "
                f"(fleet/disagg.py owns the split)")
        if self.phase == "decode":
            pool = getattr(self.backend, "pool", None)
            if pool is not None:
                buckets = getattr(self.backend, "buckets", None)
                if buckets is not None:
                    self.backend.gen.check_decode_headroom(
                        len(prompt), max_new_tokens, buckets.max_len,
                        getattr(self.backend, "_spec_overshoot", 0))
                if (len(prompt) >= pool.block_size
                        and pool.cached_prefix_blocks(prompt) == 0):
                    raise ValueError(
                        f"decode-only replica: no cached KV prefix for "
                        f"this {len(prompt)}-token prompt — import the "
                        f"prefill replica's blocks first "
                        f"(import_prefix_payload) or route to a mixed "
                        f"replica; decode replicas never re-prefill")

    def cancel(self, request_id: int) -> bool:
        return self.queue.cancel(request_id)

    def response(self, request_id: int) -> Optional[Response]:
        return self._responses.get(request_id)

    @property
    def live_slots(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    @property
    def idle(self) -> bool:
        return self.live_slots == 0 and self.queue.depth == 0

    @property
    def consecutive_decode_errors(self) -> int:
        """Consecutive failed decode ticks (reset by any success) — a
        fleet-health signal the router reads alongside the watchdog
        properties; ``decode_error_limit`` of these retires the live
        set."""
        return self._decode_errors

    # -- graceful drain ------------------------------------------------------

    def drain(self) -> None:
        """Enter graceful shutdown: ``submit`` starts raising
        :class:`EngineDraining`, the next tick sheds everything still
        queued (``status="shed"``, ``finish_reason="drain"``), and live
        slots run to completion. Idempotent."""
        if not self._draining:
            self._draining = True
            self.events.event("resilience", action="drain",
                              live=self.live_slots, queued=self.queue.depth)

    def evict_queued(self) -> List[Request]:
        """Remove and return this engine's queued requests INTACT — no
        terminal record, no status change — so a router can re-place
        them on a healthy replica. Live slots are untouched. Contrast
        :meth:`drain`, which sheds queued work terminally
        (``finish_reason="drain"``)."""
        evicted = self.queue.evict_all()
        if evicted:
            reg = get_registry()
            reg.counter("serve.engine.evicted").inc(len(evicted))
            reg.gauge("serve.engine.queue_depth").set(self.queue.depth)
            self.events.event("resilience", action="evict_queued",
                              count=len(evicted))
        return evicted

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def drained(self) -> bool:
        """True once a drain finished: nothing queued, nothing live."""
        return self._draining and self.idle

    # -- retirement --------------------------------------------------------

    def _record(self, resp: Response, bucket: Optional[int],
                req: Optional[Request] = None) -> None:
        self._responses[resp.request_id] = resp
        self.queue.forget(resp.request_id)
        reg = get_registry()
        reg.counter("serve.engine.retired").inc()
        reg.histogram("serve.engine.e2e_sec").observe(resp.latency)
        if resp.status == "timeout":
            reg.counter("serve.engine.timed_out").inc()
        elif resp.status == "cancelled":
            reg.counter("serve.engine.cancelled").inc()
        elif resp.status == "error":
            reg.counter("serve.engine.errors").inc()
        elif resp.status == "shed":
            reg.counter("serve.engine.shed").inc()
        wd = self.watchdog
        if wd is not None and resp.status in ("ok", "timeout"):
            # only served outcomes move the deadline-miss EWMA: shedding
            # is the *response* to misses and must not latch degraded mode
            self._miss_ewma = wd.record_outcome(resp.status == "timeout")
            reg.gauge("resilience.deadline_miss_ewma").set(self._miss_ewma)
        self.events.event(
            REQUEST, request=resp.request_id, status=resp.status,
            finish_reason=resp.finish_reason, prompt_len=resp.prompt_len,
            bucket=bucket, tokens=len(resp.tokens), ttft=resp.ttft,
            latency=resp.latency, stage="terminal",
            trace=getattr(req, "trace_id", None),
            attempts=getattr(req, "attempts", 0))

    def _finish_queued(self, req: Request, reason: str,
                       now: float) -> Response:
        status = "cancelled" if reason == "cancelled" else "timeout"
        resp = Response(request_id=req.id, tokens=[], status=status,
                        finish_reason=reason, prompt_len=len(req.prompt),
                        ttft=None, latency=now - req.submitted_at)
        self._record(resp, None, req)
        return resp

    def _shed_queued(self, req: Request, reason: str,
                     now: float) -> Response:
        """Queued request pushed back out unserved (degraded-mode
        shedding or drain): ``status="shed"``."""
        resp = Response(request_id=req.id, tokens=[], status="shed",
                        finish_reason=reason, prompt_len=len(req.prompt),
                        ttft=None, latency=now - req.submitted_at)
        self._record(resp, None, req)
        return resp

    def _fail_queued(self, req: Request, exc: Exception,
                     now: float) -> Response:
        """Admission failed in the backend (prefill raised): the request
        dies ``status="error"`` — the slot was returned to the free list
        and every other request keeps serving."""
        get_registry().counter("resilience.slot_errors").inc()
        self.events.event("resilience", action="slot_error",
                          request=req.id, where="prefill",
                          error=type(exc).__name__)
        resp = Response(request_id=req.id, tokens=[], status="error",
                        finish_reason="backend_error",
                        prompt_len=len(req.prompt),
                        ttft=None, latency=now - req.submitted_at)
        self._record(resp, None, req)
        return resp

    def _retire(self, slot: int, status: str, reason: str,
                now: float) -> Response:
        st = self._slots[slot]
        self._slots[slot] = None
        self._free.append(slot)
        rel = getattr(self.backend, "release", None)
        if rel is not None:
            rel(slot)
        req = st.req
        bucket = (self.backend.buckets.bucket_for(len(req.prompt))
                  if self.backend.buckets is not None else len(req.prompt))
        resp = Response(request_id=req.id, tokens=list(st.tokens),
                        status=status, finish_reason=reason,
                        prompt_len=len(req.prompt), ttft=st.ttft,
                        latency=now - req.submitted_at)
        self._record(resp, bucket, req)
        return resp

    # -- the tick ----------------------------------------------------------

    def tick(self) -> List[Response]:
        """One scheduler step: sweep deadlines/cancellations, apply the
        watchdog policies, admit into free slots, run one decode chunk,
        retire. Returns the requests that reached a terminal state
        during this tick."""
        reg = get_registry()
        tick_idx = self._tick_index
        self._tick_index += 1
        if self.chaos is not None:
            self._apply_chaos(reg, tick_idx)
        t_start = self.clock()
        now = t_start
        finished: List[Response] = []
        eos = self.backend.gen.eos_token_id
        wd = self.watchdog

        # 0) drain — everything still queued goes back to its caller
        if self._draining and self.queue.depth:
            for req in self.queue.shed_lowest(self.queue.depth):
                finished.append(self._shed_queued(req, "drain", now))

        # 1) deaths — queued first (never cost a slot), then running
        for req, reason in self.queue.reap(now):
            finished.append(self._finish_queued(req, reason, now))
        for slot in range(self.backend.num_slots):
            st = self._slots[slot]
            if st is None:
                continue
            if st.req.cancelled:
                finished.append(
                    self._retire(slot, "cancelled", "cancelled", now))
            elif st.req.deadline is not None and now >= st.req.deadline:
                finished.append(
                    self._retire(slot, "timeout", "deadline", now))

        # 1b) stuck slots — alive far past the ticks their token budget
        # can possibly need; retire as errors instead of squatting
        if wd is not None and wd.stuck_slack_ticks is not None:
            chunk = getattr(self.backend, "decode_chunk", 1)
            for slot in range(self.backend.num_slots):
                st = self._slots[slot]
                if st is None:
                    continue
                limit = wd.stuck_after(st.req.max_new_tokens, chunk)
                if tick_idx - st.admitted_tick >= limit:
                    reg.counter("resilience.stuck_slots").inc()
                    wd.record_stuck()
                    self.events.event("resilience", action="stuck_slot",
                                      request=st.req.id, slot=slot,
                                      age_ticks=tick_idx - st.admitted_tick)
                    finished.append(self._retire(slot, "error", "stuck", now))

        # 1c) degraded mode — shed lowest-priority queued work while the
        # deadline-miss EWMA sits above the threshold
        if wd is not None and wd.shed_ewma_threshold is not None \
                and not self._draining \
                and self._miss_ewma > wd.shed_ewma_threshold \
                and self.queue.depth:
            n = max(1, self.queue.depth // 2)
            reg.counter("resilience.shed").inc(n)
            self.events.event("resilience", action="shed", count=n,
                              miss_ewma=self._miss_ewma,
                              queued=self.queue.depth)
            for req in self.queue.shed_lowest(n):
                finished.append(self._shed_queued(req, "shed", now))

        # 2) admissions — prefill straight into the freed slots; a
        # backend failure here is attributable to ONE request: fail it,
        # free the slot, keep admitting. Paged backends gate on BLOCK
        # availability too: when the pool can't cover the head request's
        # demand, the head PARKS (it keeps its place; FIFO/priority
        # order is never rotated) but the scan tries the next request in
        # pop order — a small request behind a parked giant no longer
        # starves (serve.engine.admission_skipped counts the bypasses).
        device_sec = 0.0                    # prefill + decode launches
        head_blocked_counted = False
        while self._free and not self._draining:
            can = getattr(self.backend, "can_admit", None)
            candidates = self.queue.admission_order()
            if not candidates:
                break
            req = None
            for cand in candidates:
                if can is None or can(len(cand.prompt),
                                      cand.max_new_tokens, cand.prompt):
                    req = cand
                    break
                if cand is candidates[0] and not head_blocked_counted:
                    head_blocked_counted = True
                    pool = getattr(self.backend, "pool", None)
                    detail = ({"blocks_free": pool.free_blocks,
                               "blocks_evictable": pool.evictable_blocks}
                              if pool is not None else {})
                    reg.counter("serve.kv.admission_blocked").inc()
                    self.events.event("serve", action="admission_blocked",
                                      request=cand.id,
                                      depth=self.queue.depth, **detail)
            if req is None:
                break                       # nothing admissible: park all
            if req is not candidates[0]:
                reg.counter("serve.engine.admission_skipped").inc()
                self.events.event("serve", action="admission_skipped",
                                  request=req.id,
                                  parked=candidates[0].id,
                                  depth=self.queue.depth)
            self.queue.take(req.id)
            slot = self._free.pop()
            t_pre = self.clock()
            try:
                if self.chaos is not None and self.chaos.serve_fault(
                        "backend_raise", tick_idx) is not None:
                    from ..resilience.chaos import ChaosError
                    raise ChaosError(
                        f"injected backend fault at tick {tick_idx}")
                tok0 = self.backend.prefill(
                    slot, req.prompt, req.seed,
                    **self._prefill_kwargs(req))
            except Exception as e:           # noqa: BLE001 — containment
                self._free.append(slot)
                finished.append(self._fail_queued(req, e, self.clock()))
                continue
            device_sec += self.clock() - t_pre
            t_first = self.clock()
            st = _Slot(req, tok0, ttft=t_first - req.submitted_at,
                       admitted_tick=tick_idx)
            self._slots[slot] = st
            reg.counter("serve.engine.admitted").inc()
            reg.histogram("serve.engine.ttft_sec").observe(st.ttft)
            self.events.event(REQUEST, request=req.id, stage="prefill",
                              trace=req.trace_id, slot=slot, ttft=st.ttft,
                              attempts=req.attempts,
                              prompt_len=len(req.prompt))
            if eos is not None and tok0 == eos:
                finished.append(self._retire(slot, "ok", "eos", t_first))
            elif req.max_new_tokens == 1:
                finished.append(self._retire(slot, "ok", "length", t_first))

        # 3) decode — one fixed-shape chunk for every slot. A failure is
        # NOT attributable (all slots share the program): skip the tick
        # with slot state intact, and only a run of consecutive failures
        # retires the live set.
        live = np.array([s is not None for s in self._slots])
        decode_sec = 0.0
        if live.any():
            t0 = self.clock()
            try:
                reg.counter("serve.engine.host_syncs").inc()
                if getattr(self.backend, "resident", False):
                    budgets = np.array(
                        [0 if s is None else
                         max(s.req.max_new_tokens - len(s.tokens), 0)
                         for s in self._slots], np.int32)
                    toks, valid = self.backend.decode(
                        live, budgets=budgets,
                        r_max=self._resident_horizon(now))
                else:
                    toks, valid = self.backend.decode(live)
            except Exception as e:           # noqa: BLE001 — containment
                self._on_decode_error(reg, e, tick_idx, finished)
            else:
                self._decode_errors = 0
                t1 = self.clock()
                decode_sec = t1 - t0
                device_sec += decode_sec
                width = getattr(
                    self.backend, "decode_width",
                    getattr(self.backend, "decode_chunk", 1))
                chunks = max(1, toks.shape[1] // max(1, width))
                per = decode_sec / chunks
                self._chunk_ewma = per if self._chunk_ewma is None \
                    else 0.8 * self._chunk_ewma + 0.2 * per
                emitted = 0
                for slot in range(self.backend.num_slots):
                    st = self._slots[slot]
                    if st is None:
                        continue
                    for k in range(toks.shape[1]):
                        if not valid[slot, k]:
                            continue
                        t = int(toks[slot, k])
                        st.tokens.append(t)
                        emitted += 1
                        if eos is not None and t == eos:
                            finished.append(
                                self._retire(slot, "ok", "eos", t1))
                            break
                        if len(st.tokens) >= st.req.max_new_tokens:
                            finished.append(
                                self._retire(slot, "ok", "length", t1))
                            break
                if emitted:
                    reg.counter("serve.engine.tokens").inc(emitted)
                    reg.histogram("serve.engine.token_sec").observe(
                        (t1 - t0) / emitted)

        reg.gauge("serve.engine.queue_depth").set(self.queue.depth)
        reg.gauge("serve.engine.slot_occupancy").set(
            self.live_slots / self.backend.num_slots)
        pool = getattr(self.backend, "pool", None)
        if pool is not None:
            pool.observe()
        dur = self.clock() - t_start
        # everything in the tick that was NOT a device launch (prefill
        # or decode) is host overhead the resident loop amortizes away;
        # the cumulative ratio is the SERVE_r14 before/after headline
        reg.timer("serve.engine.host_sec").observe(
            max(dur - device_sec, 0.0))
        reg.gauge("serve.engine.host_overhead_per_token").set(
            host_overhead_per_token(reg))
        reg.gauge("resilience.tick_sec").set(dur)
        if wd is not None and wd.record_tick(dur):
            reg.counter("resilience.watchdog_slow_ticks").inc()
            self.events.event("resilience", action="slow_tick",
                              tick=tick_idx, duration_s=dur,
                              budget_s=wd.tick_budget_s)
        return finished

    def _resident_horizon(self, now: float) -> int:
        """How many chunks the device may run before host attention
        could matter: the soonest deadline — live slots or queued
        requests — divided by the observed per-chunk latency, clamped
        to [1, resident_chunks]. No deadlines in sight: the full
        resident depth (slot-free early exit still fires on device)."""
        R = getattr(self.backend, "resident_chunks", 1)
        dls = [s.req.deadline for s in self._slots
               if s is not None and s.req.deadline is not None]
        qd = self.queue.earliest_deadline()
        if qd is not None:
            dls.append(qd)
        if not dls:
            return R
        ew = self._chunk_ewma
        left = min(dls) - now
        if ew is None or ew <= 0.0 or left <= 0.0:
            return 1
        return int(max(1, min(R, left / ew)))

    def _prefill_kwargs(self, req: Request) -> dict:
        """Pass the request's token budget to backends whose prefill
        reserves by demand (paged pools). Legacy/stub/wrapped backends
        with a 3-arg prefill get the legacy call."""
        import inspect
        try:
            params = inspect.signature(self.backend.prefill).parameters
        except (TypeError, ValueError):
            return {}
        if "max_new_tokens" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()):
            return {"max_new_tokens": req.max_new_tokens}
        return {}

    def _apply_chaos(self, reg, tick_idx: int) -> None:
        """Serve-side fault injection (chaos plan only; no-op in real
        deployments). ``backend_raise`` is handled at the prefill site."""
        f = self.chaos.serve_fault("stall_tick", tick_idx)
        if f is not None:
            reg.counter("resilience.chaos_stalls").inc()
            time.sleep(f.magnitude)
        if self.chaos.serve_fault("queue_flood", tick_idx) is not None:
            i = 0
            while self.queue.depth < self.queue.capacity:
                self.queue.submit(self.chaos.flood_prompt(i),
                                  max_new_tokens=1, priority=-(10 ** 6))
                i += 1
            reg.counter("resilience.chaos_floods").inc()

    def _on_decode_error(self, reg, exc: Exception, tick_idx: int,
                         finished: List[Response]) -> None:
        self._decode_errors += 1
        reg.counter("resilience.decode_errors").inc()
        self.events.event("resilience", action="decode_error",
                          tick=tick_idx, consecutive=self._decode_errors,
                          error=type(exc).__name__)
        if self._decode_errors < self.decode_error_limit:
            return                           # skip the tick; state intact
        now = self.clock()
        for slot in range(self.backend.num_slots):
            if self._slots[slot] is not None:
                reg.counter("resilience.slot_errors").inc()
                finished.append(
                    self._retire(slot, "error", "backend_error", now))
        self._decode_errors = 0

    # -- convenience loops -------------------------------------------------

    def run_until_idle(self, max_ticks: int = 1_000_000) -> List[Response]:
        """Tick until every queued/running request retired."""
        finished: List[Response] = []
        for _ in range(max_ticks):
            if self.idle:
                return finished
            finished.extend(self.tick())
        raise RuntimeError(
            f"engine not idle after {max_ticks} ticks "
            f"(live={self.live_slots}, queued={self.queue.depth})")

    def serve(self, prompts: Sequence[Sequence[int]], *,
              max_new_tokens: Optional[int] = None,
              seeds: Optional[Sequence[int]] = None) -> List[Response]:
        """Batch convenience: submit all, drain, return responses in
        submit order. Oversubscription beyond queue capacity is drained
        incrementally (submit blocks on ticks, not on QueueFull)."""
        ids = {}
        i = 0
        while i < len(prompts) or not self.idle:
            while i < len(prompts):
                try:
                    req = self.submit(
                        prompts[i], max_new_tokens=max_new_tokens,
                        seed=seeds[i] if seeds is not None else 0)
                except QueueFull:
                    break
                ids[i] = req.id
                i += 1
            self.tick()
        return [self._responses[ids[j]] for j in range(len(prompts))]
